#!/usr/bin/env python
"""Broadcast demo (parity with /root/reference/guide/broadcast.py):
rank 0 broadcasts an arbitrary picklable object to everyone.

Run under the local tracker:
    python -m rabit_tpu.tracker.launcher -n 4 -- python guide/broadcast.py rabit_engine=robust
"""
import os
import sys

# for a normal run without the tracker script, make the repo importable
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import rabit_tpu as rabit  # noqa: E402

rabit.init()
rank = rabit.get_rank()
s = None
if rank == 0:
    s = {"hello world": 100, 2: 3}
print(f'@node[{rank}] before-broadcast: s="{s}"')
s = rabit.broadcast(s, 0)
print(f'@node[{rank}] after-broadcast: s="{s}"')
rabit.finalize()
