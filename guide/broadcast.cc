// Broadcast demo on the typed C++ API (parity with
// /root/reference/guide/broadcast.cc): rank 0 broadcasts a string.
#include <tpurabit/tpurabit.h>

#include <cstdio>
#include <string>

int main(int argc, char* argv[]) {
  tpurabit::Init(argc, argv);
  const int rank = tpurabit::GetRank();
  std::string s;
  if (rank == 0) s = "hello world";
  printf("@node[%d] before-broadcast: s=\"%s\"\n", rank, s.c_str());
  tpurabit::Broadcast(&s, 0);
  printf("@node[%d] after-broadcast: s=\"%s\"\n", rank, s.c_str());
  tpurabit::Finalize();
  return 0;
}
