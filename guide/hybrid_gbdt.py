#!/usr/bin/env python
"""Hybrid-deployment demo: XLA data plane + fault-tolerant engine.

The flagship deployment this framework adds beyond the reference's API
trio: one boosting round is ONE jitted XLA program
(``gbdt.train_round_hybrid``) — per-level histograms ride an in-graph
``psum`` over this process's local device mesh, and the cross-worker
combine crosses the fault-tolerant engine through a host callback inside
the program.  Checkpoints capture device state (the forest globally, this
rank's margin locally), so a killed worker is restarted by the tracker,
reloads both from peers, rebuilds its device arrays, and the final forest
is byte-identical to a run with no failures.

Solo (no tracker; the engine hop is an identity):
    python guide/hybrid_gbdt.py

Distributed, 2 workers, with worker 1 killed mid-training and recovered:
    python -m rabit_tpu.tracker.launcher -n 2 --max-restarts 3 -- \
        python guide/hybrid_gbdt.py rabit_engine=mock mock=1,1,1,0
"""
import functools
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# The demo pins a 2-virtual-device CPU mesh so it runs identically on any
# machine; a real TPU deployment uses the host's chips as the local mesh.
from rabit_tpu._platform import force_cpu_platform  # noqa: E402

force_cpu_platform(2)

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P  # noqa: E402

import rabit_tpu as rabit  # noqa: E402
from rabit_tpu.models import gbdt  # noqa: E402

N_TREES = 3

rabit.init()
rank, world = rabit.get_rank(), rabit.get_world_size()

# Every rank derives the same dataset and bin edges, then keeps one shard.
rng = np.random.RandomState(11)
X = rng.randn(512, 6).astype(np.float32)
y = (X[:, 0] + 0.7 * X[:, 1] > 0).astype(np.float32)
cfg = gbdt.GBDTConfig(n_features=6, n_trees=N_TREES, depth=3, n_bins=16)
edges = gbdt.compute_bin_edges(X, cfg.n_bins)
Xs, ys = X[rank::world], y[rank::world]
Xs, ys = Xs[: len(ys) - len(ys) % 2], ys[: len(ys) - len(ys) % 2]

mesh = Mesh(np.array(jax.devices()[:2]), ("dp",))
rows = NamedSharding(mesh, P("dp"))
xb = jax.device_put(
    np.asarray(gbdt.quantize(jnp.asarray(Xs), jnp.asarray(edges))),
    NamedSharding(mesh, P("dp", None)),
)
yj = jax.device_put(ys, rows)

# The cross-worker hop: a host callback inside the jitted program.  A
# worker killed here exits immediately so peers detect the death at once
# (blocking XLA's rendezvous for its termination timeout helps nobody) —
# but print the cause first so a real error is distinguishable from an
# injected kill.
def engine_hook(a: np.ndarray) -> np.ndarray:
    try:
        return rabit.allreduce(np.asarray(a, np.float32), rabit.SUM)
    except BaseException:
        import traceback

        traceback.print_exc()
        os._exit(13)

step = jax.jit(functools.partial(
    gbdt.train_round_hybrid, cfg=cfg, mesh=mesh,
    engine_allreduce=engine_hook if world > 1 else None,
))

# First life: fresh state.  Restarted life: forest + margin from peers.
version, forest_np, margin_np = rabit.load_checkpoint(with_local=True)
if version == 0:
    state = gbdt.init_state(cfg, len(ys))
    state = state._replace(margin=jax.device_put(state.margin, rows))
else:
    print(f"@node[{rank}] recovered at version {version}")
    state = gbdt.TrainState(
        forest=gbdt.Forest(*(jnp.asarray(a) for a in forest_np)),
        margin=jax.device_put(margin_np, rows),
        round=jnp.asarray(version, jnp.int32),
    )

for t in range(version, N_TREES):
    state = step(state, xb, yj)
    rabit.checkpoint(tuple(np.asarray(a) for a in state.forest),
                     np.asarray(state.margin))

pred = np.asarray(gbdt.predict_margin(state.forest, xb, cfg=cfg)) > 0
counts = rabit.allreduce(
    np.array([(pred == ys).sum(), len(ys)], np.float64), rabit.SUM)
msg = f"@node[{rank}] hybrid gbdt: {N_TREES} trees, " \
      f"train-acc {counts[0] / counts[1]:.3f}"
print(msg)
if world > 1:
    rabit.tracker_print(msg)  # visible in cluster.messages for the tests
rabit.finalize()
