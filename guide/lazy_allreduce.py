#!/usr/bin/env python
"""Lazy-preparation demo (parity with /root/reference/guide/lazy_allreduce.py):
the prepare function fills the buffer right before the reduction — and is
skipped entirely when the result is recovered from a peer's replay buffer,
which is why it exists.  Run on the mock engine so failures can be
injected (``rabit_engine=mock`` and the ``mock=rank,version,seqno,trial``
kill switch ride in as argv ``k=v`` params, like the reference's
``rabit.init(lib='mock')`` + mock args):

    python -m rabit_tpu.tracker.launcher -n 4 --max-restarts 3 -- \
        python guide/lazy_allreduce.py rabit_engine=mock mock=0,0,0,0
"""
import numpy as np

import os
import sys

# for a normal run without the tracker script, make the repo importable
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import rabit_tpu as rabit  # noqa: E402

rabit.init()
n = 3
rank = rabit.get_rank()
a = np.zeros(n)


def prepare(arr):
    print(f"@node[{rank}] run prepare function")
    for i in range(n):
        arr[i] = rank + i


print(f"@node[{rank}] before-allreduce: a={a}")
a = rabit.allreduce(a, rabit.MAX, prepare_fun=prepare)
print(f"@node[{rank}] after-allreduce-max: a={a}")
a = rabit.allreduce(a, rabit.SUM)
print(f"@node[{rank}] after-allreduce-sum: a={a}")
rabit.finalize()
