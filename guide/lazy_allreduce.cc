// Lazy-preparation demo on the typed C++ API (parity with
// /root/reference/guide/lazy_allreduce.cc): the lambda fills the buffer
// right before the reduction and is skipped when the result is served from
// a peer's replay buffer during recovery.  Run on the mock engine
// (rabit_engine=mock mock=r,v,s,t) to watch that happen.
#include <tpurabit/tpurabit.h>

#include <cstdio>
#include <vector>

int main(int argc, char* argv[]) {
  tpurabit::Init(argc, argv);
  const int rank = tpurabit::GetRank();
  const int n = 3;
  std::vector<int> a(n);

  tpurabit::Allreduce<tpurabit::op::Max>(a.data(), n, [&]() {
    printf("@node[%d] run prepare function\n", rank);
    for (int i = 0; i < n; ++i) a[i] = rank + i;
  });
  printf("@node[%d] after-allreduce-max: a={%d, %d, %d}\n", rank, a[0], a[1], a[2]);

  tpurabit::Allreduce<tpurabit::op::Sum>(a.data(), n);
  printf("@node[%d] after-allreduce-sum: a={%d, %d, %d}\n", rank, a[0], a[1], a[2]);
  tpurabit::Finalize();
  return 0;
}
