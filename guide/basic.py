#!/usr/bin/env python
"""Basic allreduce demo (parity with /root/reference/guide/basic.py):
every rank fills a vector with rank+i, then MAX- and SUM-allreduces it.

Run standalone (solo mode) or under the local tracker:
    python -m rabit_tpu.tracker.launcher -n 4 -- python guide/basic.py rabit_engine=robust
"""
import numpy as np

import os
import sys

# for a normal run without the tracker script, make the repo importable
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import rabit_tpu as rabit  # noqa: E402

rabit.init()
n = 3
rank = rabit.get_rank()
a = np.zeros(n)
for i in range(n):
    a[i] = rank + i

print(f"@node[{rank}] before-allreduce: a={a}")
a = rabit.allreduce(a, rabit.MAX)
print(f"@node[{rank}] after-allreduce-max: a={a}")
a = rabit.allreduce(a, rabit.SUM)
print(f"@node[{rank}] after-allreduce-sum: a={a}")
rabit.finalize()
