// Basic allreduce demo on the typed C++ API (parity with
// /root/reference/guide/basic.cc): every rank fills a vector with rank+i,
// then MAX- and SUM-allreduces it.
//
// Build: make -C guide    Run: see guide/README.md
#include <tpurabit/tpurabit.h>

#include <cstdio>
#include <cstdlib>
#include <vector>

int main(int argc, char* argv[]) {
  int n = 3;
  if (argc > 1 && atoi(argv[1]) > 0) n = atoi(argv[1]);
  tpurabit::Init(argc, argv);
  const int rank = tpurabit::GetRank();
  std::vector<int> a(n);
  for (int i = 0; i < n; ++i) a[i] = rank + i;

  printf("@node[%d] before-allreduce: a={%d, %d, %d}\n", rank, a[0], a[1], a[2]);
  tpurabit::Allreduce<tpurabit::op::Max>(a.data(), a.size());
  printf("@node[%d] after-allreduce-max: a={%d, %d, %d}\n", rank, a[0], a[1], a[2]);
  tpurabit::Allreduce<tpurabit::op::Sum>(a.data(), a.size());
  printf("@node[%d] after-allreduce-sum: a={%d, %d, %d}\n", rank, a[0], a[1], a[2]);
  tpurabit::Finalize();
  return 0;
}
