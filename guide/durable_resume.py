#!/usr/bin/env python
"""Durable-spill demo: surviving WHOLE-JOB preemption (beyond-reference).

Peer recovery (guide/lazy_allreduce.py, tests/test_recover.py) covers
individual worker deaths — peers hold the state in memory.  A TPU-slice
preemption kills every worker at once; with
``rabit_checkpoint_dir=<path>`` each committed checkpoint also lands on
disk (CRC-checked, atomic, last two versions), and a FRESH cluster
resumes from the newest version every rank can serve instead of
retraining from zero.

Run twice with the same directory and watch the second run skip the
already-trained rounds:

    python -m rabit_tpu.tracker.launcher -n 2 -- \
        python guide/durable_resume.py rabit_engine=robust \
        rabit_checkpoint_dir=/tmp/durable_demo
"""
import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import rabit_tpu as rabit  # noqa: E402

NITER = 4

rabit.init()
rank = rabit.get_rank()

version, model = rabit.load_checkpoint()
if version == 0:
    model = {"weights": np.zeros(4), "rounds_done": 0}
    print(f"@node[{rank}] fresh start")
else:
    # On a re-run this prints immediately at version NITER: the state came
    # off the durable spill, not from surviving peers.
    print(f"@node[{rank}] resumed from disk at version {version}: {model}")

for it in range(version, NITER):
    grad = np.full(4, float(rank + it))
    grad = rabit.allreduce(grad, rabit.SUM)
    model = {
        "weights": model["weights"] + grad,
        "rounds_done": model["rounds_done"] + 1,
    }
    rabit.checkpoint(model)
    print(f"@node[{rank}] round {it} done, weights={model['weights']}")

assert model["rounds_done"] == NITER, model
rabit.tracker_print(f"[{rank}] final weights {model['weights']}\n")
rabit.finalize()
