"""Pooled workers — warm processes leased to successive jobs.

A :class:`PooledWorker` is the client half of the service's pool
(doc/service.md): it parks ONCE per lease cycle with the reserved
``pool/<name>`` task id (``CMD_SPARE`` — the PR 6 park machinery, so
the warm socket and the cached-blob path are reused verbatim), waits to
be leased into whichever job's wave the service fills next, runs that
job to completion with the ordinary
:class:`~rabit_tpu.elastic.client.ElasticWorker` loop, and re-parks.
The process — its Python runtime, its listen socket's port range, its
heartbeat machinery — stays warm across fits, which is what makes
thousands of short GBDT fits per minute a service-shaped workload
instead of thousands of cold worker boots.

The worker never learns job keys: its task id keeps the ``pool/``
prefix through the whole lease, and the SERVICE routes its RPCs
(heartbeats, epoch polls, quorum reports, shutdown) to the right
partition via its lease registry.  Release is an EOF on the park socket
(the service died or ``stop()`` was called) or the ``max_leases``
budget running out.
"""

from __future__ import annotations

import threading
from typing import Callable

import numpy as np

from rabit_tpu.elastic.client import ElasticResult, ElasticWorker
from rabit_tpu.tracker import protocol as P


class PooledWorker:
    """One pool member (module docstring).

    ``contribution(version, world, rank)`` is the per-round work, shared
    by every job this worker is leased to (jobs parameterize by world
    size and rank — the service-bench shape; a real deployment would
    dispatch on the model the leased job's blob carries).
    ``max_leases=0`` keeps cycling until the pool is released.
    """

    def __init__(self, tracker, name: str,
                 contribution: Callable[[int, int, int], np.ndarray],
                 niter: int, *,
                 max_leases: int = 0,
                 heartbeat_sec: float = 0.0,
                 deadline_sec: float = 60.0,
                 rpc_timeout: float = 2.0,
                 wave_timeout: float = 20.0,
                 quorum: str = "",
                 codec: str = ""):
        self.tracker = tracker
        self.task_id = P.join_job(P.POOL_PREFIX, name)
        self.contribution = contribution
        self.niter = int(niter)
        self.max_leases = int(max_leases)
        self.heartbeat_sec = float(heartbeat_sec)
        self.deadline_sec = float(deadline_sec)
        self.rpc_timeout = float(rpc_timeout)
        self.wave_timeout = float(wave_timeout)
        self.quorum = quorum
        self.codec = codec
        self.results: list[ElasticResult] = []
        self._stop = threading.Event()
        self._current: ElasticWorker | None = None

    def stop(self) -> None:
        self._stop.set()
        cur = self._current
        if cur is not None:
            cur.stop()

    def run(self) -> list[ElasticResult]:
        """Park -> lease -> fit -> re-park until released (EOF/stop) or
        the lease budget is spent.  Returns one ElasticResult per lease
        cycle (a final parked-only result marks the release)."""
        while not self._stop.is_set():
            worker = ElasticWorker(
                self.tracker, self.task_id, self.contribution, self.niter,
                spare=True,
                heartbeat_sec=self.heartbeat_sec,
                deadline_sec=self.deadline_sec,
                rpc_timeout=self.rpc_timeout,
                wave_timeout=self.wave_timeout,
                quorum=self.quorum, codec=self.codec)
            self._current = worker
            try:
                res = worker.run()
            finally:
                self._current = None
            self.results.append(res)
            if res.parked_only or not res.promoted or res.error:
                break  # released (job over / service gone) or broken
            if self.max_leases and sum(
                    1 for r in self.results if r.promoted) \
                    >= self.max_leases:
                break
        return self.results

    def start_thread(self) -> threading.Thread:
        """Run the lease loop on a daemon thread (the in-process bench/
        test harness shape)."""
        t = threading.Thread(target=self.run, daemon=True,
                             name=f"pooled-{self.task_id}")
        t.start()
        return t
