"""The multi-tenant collective service — one control plane, many jobs.

A :class:`CollectiveService` turns the tracker stack into a LONG-LIVED
service (doc/service.md): where a plain
:class:`~rabit_tpu.tracker.tracker.Tracker` bootstraps one job and dies
with it, the service keeps serving — each job is a **headless tracker
partition** (its own ``MembershipManager``/``QuorumTable``/lease table/
spare pool/telemetry, constructed with ``Tracker(headless=True)``)
multiplexed on the service's ONE selectors reactor.  The wire does not
change: a worker of job ``j`` prefixes its task id (``"j/0"``,
``protocol.join_job``), the service's ``_route_hello`` override splits
the prefix off and dispatches to the partition, and an empty key routes
to the legacy ``""`` partition byte-for-byte (the single-job path is
the unrouted base-class code).

What the service adds on top of the partitions:

* **admission control** (:class:`~rabit_tpu.service.registry.JobRegistry`)
  — ``admit(key, world)`` checks the job key, the service-wide and
  per-tenant concurrency quotas, and the rank/fd budget; a refusal is a
  structured ``admission_refused`` event and — on the wire — a closed
  connection (the worker's bounded RPC retries fail fast, exactly the
  dead-tracker shape).  Unknown job keys arriving on the wire are
  auto-admitted at ``rabit_service_auto_world`` ranks, or refused when
  that is 0 (the default: programmatic admission only);
* **one journal, namespaced** — every partition's mutation records ride
  the service's single :class:`~rabit_tpu.ha.journal.Journal` tagged
  with their job key (:class:`_JobJournal`), the mirror is a
  :class:`~rabit_tpu.service.state.ServiceState`, and replay (or a warm
  standby's takeover, ``Standby(service=True)``) restores EVERY live
  job from the one file/stream;
* **a shared relay tier** — relays need no per-job configuration: the
  job key rides inside the batch route key, and the batch ACK carries a
  per-job ``jobs`` map so one relay answers every job's CMD_EPOCH polls
  from its cache (rabit_tpu.relay);
* **pooled workers** — a worker parked with the reserved ``pool/``
  prefix (``CMD_SPARE`` — the PR 6 park + cached-blob machinery,
  unchanged) joins the SERVICE's pool and is leased into successive
  jobs' waves (``worker_leased``): admit a job with ``pooled=True`` and
  the service fills its bootstrap wave (and any later recovery wave)
  from the pool — the "thousands of short GBDT fits per minute" shape
  where fits reuse warm processes instead of cold-starting workers;
* **per-job telemetry** — each partition writes
  ``telemetry-<job>.json`` into the shared obs dir; the service's own
  serving/admission evidence lands in ``telemetry-service.json``.

Isolation: partitions share nothing but the reactor and the journal's
writer thread — a straggler storm, worker kill, or quorum stall inside
one job moves that partition's waves and leases only.  One monitor
thread pair drives every partition's ``_lease_tick``/``_wave_tick``, so
N concurrent jobs cost the service two threads, not 2N.
"""

from __future__ import annotations

import time
import threading

from rabit_tpu.config import Config
from rabit_tpu.obs import stream as obs_stream
from rabit_tpu.service.registry import JobRegistry, tenant_of
from rabit_tpu.service.state import ServiceState
from rabit_tpu.tracker import protocol as P
from rabit_tpu.tracker.tracker import Tracker, _aggregate_incidents

#: Route-key prefix of one pooled worker: "pool/<name>".
_POOL_ROUTE = P.POOL_PREFIX + P.JOB_SEP


class AdmissionRefused(RuntimeError):
    """``admit`` hit a quota or an invalid job key (the reason is the
    message; an ``admission_refused`` event carries it too)."""


class _JobJournal:
    """One partition's view of the service's shared journal: every
    record the partition appends is tagged with its job key, so one
    totally-ordered file interleaves every job's history and
    :class:`~rabit_tpu.service.state.ServiceState` replays each into
    its own partition (doc/service.md)."""

    def __init__(self, journal, job: str):
        self._journal = journal
        self.job = job
        #: assigned by Tracker.__init__; the service already folds the
        #: real journal's writer events into its own timeline, so the
        #: per-partition hook stays unused.
        self.on_event = None

    def append(self, kind: str, **fields) -> None:
        self._journal.append(kind, job=self.job, **fields)

    def close(self) -> None:
        pass  # the service owns the real journal's lifecycle


class CollectiveService(Tracker):
    """One long-lived multi-job tracker (module docstring).

    Constructor shape: the serving/schedule/quorum keywords mirror
    :class:`Tracker` and become every partition's defaults;
    ``world_size`` is the legacy ``""`` job's (and ``admit``'s default)
    world.  Quotas default to the ``rabit_service_*`` config keys
    (doc/parameters.md).  ``journal`` accepts a path (opened with a
    multi-job :class:`ServiceState` mirror — an existing file restores
    every live job) or a ready :class:`~rabit_tpu.ha.journal.Journal`
    whose state must be a ServiceState; ``resume_from`` is the replayed
    ServiceState a promoted standby seeds partitions from.
    """

    def __init__(self, world_size: int = 1, host: str = "127.0.0.1",
                 port: int = 0,
                 quiet: bool = False,
                 obs_dir: str | None = None,
                 conn_timeout_sec: float = 60.0,
                 on_suspect=None,
                 shrink_after_sec: float = 0.0,
                 min_world: int = 1,
                 promote_after_sec: float = 0.25,
                 schedule: str = "auto",
                 sched_mesh: str = "",
                 sched_repair: bool = True,
                 sched_wait_share: float = 0.25,
                 quorum: str = "",
                 quorum_flag_after: int = 3,
                 reactor: bool = True,
                 backlog: int | None = None,
                 max_messages: int = 4096,
                 max_jobs: int | None = None,
                 max_jobs_per_tenant: int | None = None,
                 max_ranks: int | None = None,
                 auto_world: int | None = None,
                 journal=None,
                 resume_from: ServiceState | None = None,
                 listen_sock=None,
                 ha_tick_sec: float | None = None):
        cfg = Config()
        if max_jobs is None:
            max_jobs = cfg.get_int("rabit_service_max_jobs", 0)
        if max_jobs_per_tenant is None:
            max_jobs_per_tenant = cfg.get_int(
                "rabit_service_max_jobs_per_tenant", 0)
        if max_ranks is None:
            max_ranks = cfg.get_int("rabit_service_max_ranks", 0)
        if auto_world is None:
            auto_world = cfg.get_int("rabit_service_auto_world", 0)
        self.registry = JobRegistry(max_jobs=max_jobs,
                                    max_jobs_per_tenant=max_jobs_per_tenant,
                                    max_ranks=max_ranks)
        self.auto_world = int(auto_world)
        self._default_world = max(int(world_size), 1)
        # The partition table and pooled-worker lease registry.  A
        # dedicated lock (never held across a partition call) keeps the
        # routing hot path free of the base tracker's state lock.
        self._svc_lock = threading.Lock()
        self._parts: dict[str, Tracker] = {}
        self._pooled: set[str] = set()
        self._admitted_at: dict[str, float] = {}
        #: full pooled-worker task id -> the job key it is leased to
        self._pool_leases: dict[str, str] = {}
        self._part_kwargs = dict(
            conn_timeout_sec=conn_timeout_sec,
            shrink_after_sec=shrink_after_sec, min_world=min_world,
            promote_after_sec=promote_after_sec, schedule=schedule,
            sched_mesh=sched_mesh, sched_repair=sched_repair,
            sched_wait_share=sched_wait_share, quorum=quorum,
            quorum_flag_after=quorum_flag_after,
            max_messages=max_messages)
        # The service itself serves (reactor, relay channels, journal
        # channels) under job="service": its telemetry file is
        # telemetry-service.json, its journal records are tagged
        # "service" (dropped by ServiceState — serving evidence, not job
        # state), and its OWN wave machinery is never fed a worker (the
        # routing override owns every hello).
        super().__init__(self._default_world, host=host, port=port,
                         quiet=quiet, obs_dir=obs_dir,
                         conn_timeout_sec=conn_timeout_sec,
                         on_suspect=on_suspect,
                         schedule=schedule, sched_mesh=sched_mesh,
                         sched_repair=sched_repair,
                         sched_wait_share=sched_wait_share,
                         reactor=reactor, backlog=backlog,
                         max_messages=max_messages,
                         journal=None, listen_sock=listen_sock,
                         ha_tick_sec=ha_tick_sec, job="service")
        if isinstance(journal, str):
            from rabit_tpu.ha.journal import Journal

            journal = Journal(
                journal,
                state=(resume_from if resume_from is not None
                       else ServiceState()),
                seeded=resume_from is not None,
                snapshot_every=cfg.get_int("rabit_ha_snapshot_every", 256))
        self.journal = journal
        if self.journal is not None:
            self.journal.on_event = self._journal_event
            if resume_from is None:
                # an existing file journal replayed at open: restore
                # every live job it recorded (doc/service.md)
                resume_from = self.journal.state_snapshot()
                resume_from = (ServiceState.from_snapshot(resume_from)
                               if resume_from.get("jobs") or
                               resume_from.get("service") else None)
        self._journal("init", base_world=self._default_world)
        if resume_from is not None:
            self._restore_jobs(resume_from)

    # -- journal namespacing ------------------------------------------------

    def _journal(self, kind: str, **fields) -> None:
        """Service-level records are tagged ``job="service"`` (serving
        evidence — ServiceState drops them); records about a specific
        job pass their own ``job=`` and keep it."""
        if self.journal is not None:
            fields.setdefault("job", "service")
            self.journal.append(kind, **fields)

    # -- admission ----------------------------------------------------------

    def admit(self, key: str, world: int | None = None, *,
              pooled: bool = False) -> Tracker:
        """Admit one job: quota-check, create its partition, journal the
        admission.  Returns the partition (its ``wait()``/telemetry are
        the job's); raises :class:`AdmissionRefused` (after emitting the
        ``admission_refused`` event) when a quota or key check fails.

        ``pooled=True`` marks the job's waves as POOL-FILLED: the
        service leases parked ``pool/``-workers into every forming wave
        instead of waiting for the job to bring its own workers."""
        world = int(world if world is not None else self._default_world)
        reason = self.registry.admit(key, world)
        if reason is not None:
            self._refuse(key, reason)
            raise AdmissionRefused(reason)
        part = self._make_partition(key, world, pooled=pooled)
        self._journal("job_admit", job=key, world=world,
                      pooled=bool(pooled), tenant=tenant_of(key))
        with self._lock:
            self.events.append({
                "ts": round(time.time(), 6), "kind": "job_admitted",
                "job": key, "world": world, "pooled": bool(pooled),
                "tenant": tenant_of(key),
            })
        if not self.quiet:
            print(f"[service] job {key!r} admitted (world {world}"
                  f"{', pooled' if pooled else ''})", flush=True)
        return part

    def _refuse(self, key: str, reason: str) -> None:
        with self._lock:
            self.events.append({
                "ts": round(time.time(), 6), "kind": "admission_refused",
                "job": key, "tenant": tenant_of(key), "reason": reason,
            })
        if not self.quiet:
            print(f"[service] job {key!r} REFUSED: {reason}", flush=True)

    def _wire_admit(self, key: str) -> Tracker | None:
        """A hello for an unknown job key: auto-admit at
        ``rabit_service_auto_world`` ranks, else refuse (the connection
        closes with no reply)."""
        if self.auto_world <= 0:
            self._refuse(key, "unknown job (wire auto-admission is off; "
                              "set rabit_service_auto_world or admit() "
                              "the job first)")
            return None
        try:
            return self.admit(key, self.auto_world)
        except AdmissionRefused:
            return None

    def _make_partition(self, key: str, world: int, pooled: bool = False,
                        resume=None) -> Tracker:
        part = Tracker(
            world, host=self.host, port=self.port, quiet=self.quiet,
            obs_dir=self.obs_dir,
            on_suspect=self._suspect_cb(key),
            reactor=self._reactor,
            journal=(_JobJournal(self.journal, key)
                     if self.journal is not None else None),
            resume_from=resume,
            job=key, headless=True,
            **self._part_kwargs)
        # ONE content-addressed snapshot store across every partition
        # (doc/delivery.md): N tenants publishing identical bytes hold
        # one copy, and the publish reply's "have" dedup bit is true no
        # matter which job uploaded the digest first.
        part._snaps = self._snaps
        with self._svc_lock:
            self._parts[key] = part
            if pooled:
                self._pooled.add(key)
            self._admitted_at[key] = time.monotonic()
        return part

    def _suspect_cb(self, key: str):
        """Partition lease expiries surface on the service's on_suspect
        with the FULL wire task id, so one launcher-side callback serves
        every job."""
        def cb(task_id: str) -> None:
            if self.on_suspect is not None:
                full = (task_id if task_id.startswith(_POOL_ROUTE)
                        else P.join_job(key, task_id))
                self.on_suspect(full)
        return cb

    def _restore_jobs(self, state: ServiceState) -> None:
        """Re-admit every live job of a replayed ServiceState (a
        standby's takeover, or an existing journal file reopened): the
        partitions resume their rank lines, epochs, quorum records and
        journaled leases exactly as a single-job Tracker resumes from a
        ControlState (doc/ha.md)."""
        for key in sorted(state.jobs):
            cs = state.jobs[key]
            meta = state.meta.get(key, {})
            world = int(meta.get("world") or cs.base_world or cs.world or 1)
            self.registry.admit(key, world, force=True)
            self._make_partition(key, world,
                                 pooled=bool(meta.get("pooled")),
                                 resume=cs)
            with self._lock:
                self.events.append({
                    "ts": round(time.time(), 6), "kind": "job_admitted",
                    "job": key, "world": world, "tenant": tenant_of(key),
                    "pooled": bool(meta.get("pooled")), "restored": True,
                })
            if not self.quiet:
                print(f"[service] job {key!r} RESTORED from the journal "
                      f"(world {world}, epoch {cs.epoch})", flush=True)

    # -- routing (the Tracker seam) -----------------------------------------

    def partition(self, key: str) -> Tracker | None:
        """The live partition for ``key`` (None once retired)."""
        with self._svc_lock:
            return self._parts.get(key)

    def live_jobs(self) -> list[str]:
        with self._svc_lock:
            return sorted(self._parts)

    def _route_hello(self, task_id: str, cmd: int):
        route_id = task_id
        if route_id.startswith(("q#", "s#")):
            # relay-batched quorum reports (q#) and delivery RPCs (s#)
            # prefix the child's key (doc/scaling.md, doc/delivery.md);
            # route on the real id, reply under the prefixed one (the
            # caller keeps the full route key).
            route_id = route_id[2:]
        job, rest = P.split_job(route_id)
        if cmd == P.CMD_OBS:
            # Live-telemetry routing (doc/observability.md "Live
            # telemetry plane"): a job-prefixed id reaches that job's
            # partition (its scrape, or a relay-coalesced "<job>/#delta"
            # frame); everything else gets the SERVICE-level view —
            # never admission (a scrape must not mint a job).
            if job:
                part = self.partition(job)
                return (part if part is not None else self), \
                    (rest if part is not None else task_id)
            part = self.partition("") if rest == "#delta" else None
            return (part if part is not None else self), task_id
        if cmd in (P.CMD_SUB, P.CMD_SNAP):
            # Delivery-plane routing (doc/delivery.md): a subscriber's
            # poll or fetch reaches the job's partition when it is live
            # and the service-level view otherwise — NEVER admission (a
            # poll must not mint a job).  CMD_SNAP works either way: the
            # digest store is service-shared (cross-job dedup), so a
            # fetch for a retired job's digest still answers.
            if job:
                part = self.partition(job)
                return (part if part is not None else self), \
                    (rest if part is not None else task_id)
            part = self.partition("")
            return (part if part is not None else self), task_id
        if job == P.POOL_PREFIX:
            # A pooled worker: CMD_SPARE (re-)parks it in the SERVICE
            # pool (releasing any stale lease); every other command
            # follows its current lease to the job it is working for.
            if cmd == P.CMD_SPARE:
                with self._svc_lock:
                    self._pool_leases.pop(route_id, None)
                return self, task_id
            with self._svc_lock:
                leased = self._pool_leases.get(route_id)
                part = self._parts.get(leased) if leased is not None \
                    else None
            return (part if part is not None else self), task_id
        if not job:
            part = self.partition("")
            if part is not None:
                return part, task_id
            # Lazy legacy admission: the first bare-id hello admits the
            # "" job at the constructor world — the single-job path
            # through a service, byte-identical to a plain Tracker.
            try:
                return self.admit("", self._default_world), task_id
            except AdmissionRefused:
                return None, "legacy job refused"
        part = self.partition(job)
        if part is None:
            part = self._wire_admit(job)
            if part is None:
                return None, "admission refused"
        return part, rest

    # -- monitors (one thread pair ticks every partition) -------------------

    def _parts_items(self) -> list[tuple[str, Tracker]]:
        with self._svc_lock:
            return sorted(self._parts.items())

    def _lease_tick(self, now: float) -> None:
        super()._lease_tick(now)
        for _key, part in self._parts_items():
            part._lease_tick(now)

    def _wave_tick(self) -> None:
        with self._lock:
            # dead pooled workers must leave the pool before a lease
            # hands a job a dead socket (the spare-reap contract)
            self._reap_spares_locked()
        for key, part in self._parts_items():
            if part._done.is_set():
                self._retire(key, part)
                continue
            with self._svc_lock:
                pooled = key in self._pooled
            if pooled:
                self._fill_from_pool(key, part)
            part._wave_tick()

    def _fill_from_pool(self, key: str, part: Tracker) -> None:
        """Lease parked ``pool/`` workers into a pooled job's forming
        wave: the bootstrap wave of a fresh job (no epoch yet) and any
        later recovery wave (survivors pending) fill to the job's world
        from the service pool; each lease is a ``worker_leased`` event
        and a lease-registry entry that routes the worker's RPCs to this
        partition until it re-parks or the job completes."""
        with part._lock:
            if part._done.is_set():
                return
            need = part.world_size - len(part._pending)
            fresh = part.elastic.epoch < 0
            forming = bool(part._pending)
        if need <= 0 or not (fresh or forming):
            return
        take = []
        with self._lock:
            avail = [s for s in self._spares
                     if s.task_id.startswith(_POOL_ROUTE)]
            take = avail[:need]
            if not take:
                return
            taken = set(map(id, take))
            self._spares = [s for s in self._spares
                            if id(s) not in taken]
        with self._svc_lock:
            for s in take:
                self._pool_leases[s.task_id] = key
        ts = round(time.time(), 6)
        with self._lock:
            pool_left = sum(1 for s in self._spares
                            if s.task_id.startswith(_POOL_ROUTE))
            for s in take:
                self.events.append({
                    "ts": ts, "kind": "worker_leased",
                    "task_id": s.task_id, "job": key, "pool": pool_left,
                })
        if not self.quiet:
            print(f"[service] leased {[s.task_id for s in take]} -> "
                  f"job {key!r} (pool {pool_left})", flush=True)
        with part._lock:
            for s in take:
                s.cmd = P.CMD_START
                s.origin = "spare"
                part._pending.append(s)
                part._pending_ids.add(s.task_id)
            if part._wave_started is None:
                part._wave_started = time.monotonic()
            plan = part._close_wave_locked(timer=False)
        if plan is not None:
            part._send_wave(plan)

    def _retire(self, key: str, part: Tracker) -> None:
        """A completed job leaves the service: its quota slot and rank
        budget free up, its pooled workers' leases clear (they re-park
        on their own), and a ``job_retired`` record removes it from the
        journal's live set — replay after this point restores every
        OTHER job."""
        with self._svc_lock:
            if self._parts.get(key) is not part:
                return  # already retired by a concurrent tick
            self._parts.pop(key)
            self._pooled.discard(key)
            for tid in [t for t, j in self._pool_leases.items()
                        if j == key]:
                self._pool_leases.pop(tid)
            admitted_at = self._admitted_at.pop(key, None)
        part.stop()  # idempotent telemetry flush + spare release
        self.registry.release(key)
        self._journal("job_retired", job=key)
        with self._lock:
            self.events.append({
                "ts": round(time.time(), 6), "kind": "job_completed",
                "job": key, "world": part.world_size,
                "seconds": (round(time.monotonic() - admitted_at, 6)
                            if admitted_at is not None else -1.0),
            })
        if not self.quiet:
            print(f"[service] job {key!r} completed "
                  f"({self.registry.stats()['live_jobs']} live)",
                  flush=True)

    # -- relay fan-out -------------------------------------------------------

    def _batch_ack_info(self) -> dict:
        """The shared relay tier's cache refresh: the base fields plus a
        per-job ``jobs`` map, so one relay answers CMD_EPOCH locally for
        every job behind it (doc/service.md)."""
        info = super()._batch_ack_info()
        jobs = {}
        for key, part in self._parts_items():
            jinfo = part._epoch_info()
            with part._lock:
                if part._delivery is not None:
                    # the job's published version line rides the ACK so
                    # the relay answers CMD_SUB polls locally
                    # (doc/delivery.md)
                    jinfo["delivery"] = dict(part._delivery)
            jobs[key] = jinfo
        info["jobs"] = jobs
        return info

    # -- live telemetry plane (doc/observability.md) -------------------------

    def build_scrape(self, opts: dict | None = None) -> dict:
        """The multi-tenant CMD_OBS exposition: the service's own live
        section plus a ``tenants`` map shaped tenant -> job -> rank ->
        link — the accounting schema the QoS scheduler and pool
        autoscaler consume.  Each tenant section precomputes its
        ``wire_bytes`` split by (job, codec, fused) from the jobs'
        streamed rollups, so a policy loop needs no client-side math."""
        doc = super().build_scrape(opts)
        with self._lock:
            pool = sum(1 for s in self._spares
                       if s.task_id.startswith(_POOL_ROUTE))
        doc["service"] = {
            **self.registry.stats(),
            "live": self.live_jobs(),
            "pool_parked": pool,
            "auto_world": self.auto_world,
        }
        tenants: dict[str, dict] = {}
        for key, part in self._parts_items():
            jdoc = part._scrape_job_state()
            tenant = tenants.setdefault(
                tenant_of(key),
                {"jobs": {}, "wire_bytes": {}, "wire_bytes_total": 0})
            tenant["jobs"][key] = jdoc
            by_codec = obs_stream.wire_bytes_by_codec(
                jdoc["stream"]["total"])
            for codec, n in by_codec.items():
                tenant["wire_bytes"][codec] = (
                    tenant["wire_bytes"].get(codec, 0) + n)
                tenant["wire_bytes_total"] += n
        doc["tenants"] = tenants
        # Re-aggregate the top-level incidents digest over EVERY job doc
        # (the super() pass only saw the service's own legacy section).
        all_jobs = dict(doc["jobs"])
        for tenant in tenants.values():
            all_jobs.update(tenant["jobs"])
        doc["incidents"] = _aggregate_incidents(all_jobs)
        return doc

    # -- lifecycle -----------------------------------------------------------

    def stop(self) -> None:
        for _key, part in self._parts_items():
            part.stop()
        super().stop()

    def kill(self) -> None:
        for _key, part in self._parts_items():
            part.kill()
        super().kill()

    def build_telemetry(self) -> dict:
        doc = super().build_telemetry()
        with self._lock:
            pool = sum(1 for s in self._spares
                       if s.task_id.startswith(_POOL_ROUTE))
        doc["service"] = {
            **self.registry.stats(),
            "live": self.live_jobs(),
            "pool_parked": pool,
            "auto_world": self.auto_world,
            "n_leased": sum(1 for e in doc["events"]
                            if e["kind"] == "worker_leased"),
        }
        return doc
