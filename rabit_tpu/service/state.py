"""The multi-job control plane as one replayable state machine.

A :class:`ServiceState` is the service-level analog of
:class:`rabit_tpu.ha.state.ControlState`: where the single-job state is
mutated by journal records, the service state is mutated by the SAME
records carrying one extra ``job`` field — the key of the partition the
record belongs to.  One ``rabit_ha_journal`` file (or CMD_JOURNAL
stream) therefore holds every live job's history interleaved in commit
order, and replaying it restores every partition (doc/service.md,
doc/ha.md).

Routing rules, chosen so a journal remains evidence under every mix of
writers:

* a record's ``job`` field (default ``""``) selects the partition; the
  per-partition fold is EXACTLY ``ControlState.apply`` — the replay
  determinism the single-job gate proves carries over per job;
* a partition comes into existence only through its ``init`` record or
  a service-level ``job_admit`` record — stray records of never-admitted
  jobs (and the journal's untagged ``tick`` keepalives) are dropped, so
  liveness noise can never materialize a phantom job;
* ``job_retired`` removes a completed job's partition — "replay restores
  every live job" means exactly the jobs that were admitted and have not
  completed;
* a ``snapshot`` record holding a service-format state (the ``service``
  marker key) replaces everything — the compaction head; a single-job
  snapshot record routes into its partition like any other record, so a
  pre-service journal replays into the legacy ``""`` partition.

``snapshot_bytes`` stays canonical (sorted keys, no whitespace), so
"standby replay == primary mirror" remains one byte comparison with any
number of jobs interleaved — the property gate tests/test_ha.py extends
to two interleaved jobs.
"""

from __future__ import annotations

import json

from rabit_tpu.ha.state import ControlState

#: Record kinds that may CREATE a partition (see module docstring).
_CREATE_KINDS = ("init", "job_admit")


class ServiceState:
    """Every live job's :class:`ControlState`, plus the service-level
    admission metadata a promoted tracker re-admits partitions from."""

    def __init__(self) -> None:
        self.jobs: dict[str, ControlState] = {}
        #: per-job admission metadata (``job_admit`` records):
        #: {"world": W, "pooled": bool, "tenant": str}
        self.meta: dict[str, dict] = {}
        self.applied = 0  # records folded in (diagnostics only)

    def job(self, key: str) -> ControlState:
        """The partition for ``key``, created empty when absent."""
        return self.jobs.setdefault(str(key), ControlState())

    # -- record application -------------------------------------------------

    def apply(self, kind: str, fields: dict) -> None:
        """Fold one journal record in (module docstring routing rules).
        Deterministic and tolerant: malformed fields drop the record,
        never poison the replay."""
        fields = dict(fields or {})
        try:
            key = str(fields.pop("job", ""))
        except (TypeError, ValueError):
            return
        if key == "service":
            # the service's own serving evidence (init, ticks, pool
            # parks) — never job state; reserved by the registry so no
            # real job can collide with it
            return
        if kind == "snapshot":
            state = fields.get("state")
            if isinstance(state, dict) and state.get("service"):
                self.load_snapshot(state)
            else:
                # a single-job snapshot record: one partition's history
                # (a pre-service journal) replays into its partition
                self.job(key).apply(kind, fields)
            self.applied += 1
            return
        if kind == "job_admit":
            try:
                world = int(fields.get("world", 0))
            except (TypeError, ValueError):
                return
            self.meta[key] = {"world": world,
                              "pooled": bool(fields.get("pooled")),
                              "tenant": str(fields.get("tenant", ""))}
            self.job(key)
            self.applied += 1
            return
        if kind == "job_retired":
            self.jobs.pop(key, None)
            self.meta.pop(key, None)
            self.applied += 1
            return
        if key not in self.jobs and kind not in _CREATE_KINDS:
            return  # tick keepalives / records of never-admitted jobs
        self.job(key).apply(kind, fields)
        self.applied += 1

    # -- snapshots ----------------------------------------------------------

    def snapshot(self) -> dict:
        return {
            "service": 1,
            "jobs": {k: cs.snapshot() for k, cs in sorted(self.jobs.items())},
            "meta": {k: dict(m) for k, m in sorted(self.meta.items())},
        }

    def snapshot_bytes(self) -> bytes:
        """CANONICAL byte encoding (sorted keys, no whitespace) — the
        multi-job replay-determinism byte compare."""
        return json.dumps(self.snapshot(), sort_keys=True,
                          separators=(",", ":")).encode()

    def load_snapshot(self, snap: dict) -> None:
        self.jobs = {str(k): ControlState.from_snapshot(s)
                     for k, s in (snap.get("jobs") or {}).items()}
        self.meta = {str(k): dict(m)
                     for k, m in (snap.get("meta") or {}).items()}

    @classmethod
    def from_snapshot(cls, snap: dict) -> "ServiceState":
        state = cls()
        state.load_snapshot(snap)
        return state

    # -- aggregate views (standby logging, telemetry) -----------------------

    @property
    def n_jobs(self) -> int:
        return len(self.jobs)

    @property
    def epoch(self) -> int:
        """The legacy partition's epoch (-1 when no ``""`` job lives) —
        keeps the standby's sync/failover log lines meaningful."""
        cs = self.jobs.get("")
        return cs.epoch if cs is not None else -1

    @property
    def world(self) -> int:
        cs = self.jobs.get("")
        return cs.world if cs is not None else 0
