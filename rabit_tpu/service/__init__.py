"""Multi-tenant collective service (doc/service.md).

One long-lived control plane, many concurrent jobs: per-job tracker
partitions multiplexed on one reactor (:class:`CollectiveService`),
admission control and per-tenant quotas (:class:`JobRegistry`), every
job's journal records namespaced into one HA journal
(:class:`ServiceState`), and warm pooled workers leased to successive
jobs (:class:`PooledWorker`).
"""

from rabit_tpu.service.pool import PooledWorker
from rabit_tpu.service.registry import JobRegistry, tenant_of
from rabit_tpu.service.service import (
    AdmissionRefused,
    CollectiveService,
)
from rabit_tpu.service.state import ServiceState

__all__ = [
    "AdmissionRefused",
    "CollectiveService",
    "JobRegistry",
    "PooledWorker",
    "ServiceState",
    "tenant_of",
]
