"""Admission control — which jobs a long-lived service lets in.

A :class:`JobRegistry` is the pure bookkeeping side of multi-tenancy
(doc/service.md): it validates job keys, derives each job's TENANT (the
key up to the first ``.`` — ``"teamA.fit17"`` belongs to tenant
``teamA``), and enforces the quotas that keep one tenant's burst from
starving its neighbors:

* ``max_jobs`` — concurrent jobs service-wide (0 = unlimited);
* ``max_jobs_per_tenant`` — concurrent jobs per tenant;
* ``max_ranks`` — the fd budget: the sum of admitted jobs' world sizes
  bounds the wave-held sockets + worker links the service can be asked
  to carry at once (each admitted rank is at least one held connection
  during its bootstrap wave).

Refusals return a REASON string (never raise): the serving path turns a
refusal into a structured ``admission_refused`` event and a closed
connection, and callers that want an exception get it from
``CollectiveService.admit``.  The registry is deliberately free of
sockets and clocks so every decision is unit-testable.
"""

from __future__ import annotations

import re
import threading

from rabit_tpu.tracker import protocol as P

#: Valid job keys: path-safe (the key lands in telemetry filenames),
#: wire-safe (never contains the JOB_SEP), bounded.
_KEY_RE = re.compile(r"^[A-Za-z0-9_.\-]{1,64}$")

#: Keys the service itself uses: ``pool`` prefixes service-level pooled
#: workers, ``service`` names the service's own telemetry file.
RESERVED_KEYS = frozenset({P.POOL_PREFIX, "service"})


def tenant_of(key: str) -> str:
    """The tenant a job key belongs to (the key up to the first ``.``;
    the whole key when undotted; ``""`` for the legacy empty key)."""
    return key.split(".", 1)[0]


class JobRegistry:
    """Thread-safe admission bookkeeping (module docstring)."""

    def __init__(self, max_jobs: int = 0, max_jobs_per_tenant: int = 0,
                 max_ranks: int = 0):
        self.max_jobs = int(max_jobs)
        self.max_jobs_per_tenant = int(max_jobs_per_tenant)
        self.max_ranks = int(max_ranks)
        self._lock = threading.Lock()
        self.jobs: dict[str, int] = {}  # key -> admitted world size
        self.n_admitted = 0
        self.n_refused = 0
        self.n_completed = 0

    @property
    def ranks_in_use(self) -> int:
        with self._lock:
            return sum(self.jobs.values())

    def check(self, key: str, world: int) -> str | None:
        """Would ``admit`` succeed?  Returns the refusal reason, or None
        when the job fits.  Does not mutate."""
        if key != "" and not _KEY_RE.match(key):
            return f"invalid job key {key!r} (want [A-Za-z0-9_.-], <=64)"
        if key in RESERVED_KEYS or tenant_of(key) in RESERVED_KEYS:
            return f"job key {key!r} is reserved"
        if world < 1:
            return f"invalid world size {world}"
        with self._lock:
            if key in self.jobs:
                return f"job {key!r} already live"
            if self.max_jobs > 0 and len(self.jobs) >= self.max_jobs:
                return (f"service full: {len(self.jobs)}/"
                        f"{self.max_jobs} jobs live")
            if self.max_jobs_per_tenant > 0:
                tenant = tenant_of(key)
                mine = sum(1 for k in self.jobs if tenant_of(k) == tenant)
                if mine >= self.max_jobs_per_tenant:
                    return (f"tenant {tenant!r} full: {mine}/"
                            f"{self.max_jobs_per_tenant} jobs live")
            if self.max_ranks > 0 and \
                    sum(self.jobs.values()) + world > self.max_ranks:
                return (f"rank budget exceeded: "
                        f"{sum(self.jobs.values())}+{world} > "
                        f"{self.max_ranks}")
        return None

    def admit(self, key: str, world: int,
              force: bool = False) -> str | None:
        """Admit a job (atomically re-checking the quotas).  Returns
        None on success, the refusal reason otherwise.  ``force=True``
        skips the quota checks (a failover restore must re-admit every
        journaled live job — they were inside quota when admitted)."""
        if not force:
            reason = self.check(key, world)
            if reason is not None:
                with self._lock:
                    self.n_refused += 1
                return reason
        with self._lock:
            if key in self.jobs:
                return f"job {key!r} already live"
            self.jobs[key] = max(int(world), 1)
            self.n_admitted += 1
        return None

    def release(self, key: str) -> None:
        """Free a completed/failed job's slot and rank budget."""
        with self._lock:
            if self.jobs.pop(key, None) is not None:
                self.n_completed += 1

    def live(self) -> list[str]:
        with self._lock:
            return sorted(self.jobs)

    def stats(self) -> dict:
        with self._lock:
            return {
                "live_jobs": len(self.jobs),
                "ranks_in_use": sum(self.jobs.values()),
                "n_admitted": self.n_admitted,
                "n_refused": self.n_refused,
                "n_completed": self.n_completed,
                "max_jobs": self.max_jobs,
                "max_jobs_per_tenant": self.max_jobs_per_tenant,
                "max_ranks": self.max_ranks,
            }
