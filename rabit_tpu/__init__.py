"""rabit_tpu — a TPU-native fault-tolerant collective framework.

A ground-up re-design of the capabilities of rabit (Reliable Allreduce and
Broadcast Interface, the fault-tolerant collective library behind distributed
XGBoost) for TPU hardware:

* the data plane is XLA: collectives lower to ``jax.lax`` ops (``psum``,
  ``all_gather``, ``ppermute``) over a ``jax.sharding.Mesh`` and ride ICI;
* the control plane is native C++: a TCP engine (tree + ring collectives,
  tracker bootstrap) carries recovery traffic, cross-host DCN traffic and
  serves as the CPU reference implementation;
* the fault-tolerance protocol (iteration-versioned in-memory checkpoints,
  consensus-driven replay, live re-admission of restarted workers) layers on
  top of either engine.

Public API parity with the reference Python binding
(``/root/reference/python/rabit.py``): ``init``, ``finalize``, ``get_rank``,
``get_world_size``, ``tracker_print``, ``get_processor_name``, ``broadcast``,
``allreduce``, ``allgather``, ``load_checkpoint``, ``checkpoint``,
``lazy_checkpoint``, ``version_number`` and the op enums ``MAX``, ``MIN``,
``SUM``, ``BITOR``.
"""

from rabit_tpu.api import (
    MAX,
    MIN,
    SUM,
    BITOR,
    init,
    finalize,
    get_rank,
    get_world_size,
    is_distributed,
    tracker_print,
    get_processor_name,
    broadcast,
    allreduce,
    allgather,
    load_checkpoint,
    checkpoint,
    lazy_checkpoint,
    version_number,
    collective_stats,
    reset_collective_stats,
)

__version__ = "0.5.0"

__all__ = [
    "MAX",
    "MIN",
    "SUM",
    "BITOR",
    "init",
    "finalize",
    "get_rank",
    "get_world_size",
    "is_distributed",
    "tracker_print",
    "get_processor_name",
    "broadcast",
    "allreduce",
    "allgather",
    "load_checkpoint",
    "checkpoint",
    "lazy_checkpoint",
    "version_number",
    "collective_stats",
    "reset_collective_stats",
]
