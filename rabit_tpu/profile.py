"""Tracing / profiling (SURVEY §5 aux subsystems) — legacy facade.

The reference's observability is (a) ``rabit_debug=1`` per-op latency log
lines (allreduce_robust.cc:214-217,289-294) and (b) the mock engine's
per-checkpoint-interval timing totals (allreduce_mock.h:56-77).  The TPU
build's observability now lives in :mod:`rabit_tpu.obs` — a thread-safe
metrics registry (counters/gauges/latency histograms) plus a flight
recorder of structured events.  This module keeps the historical surface:

* ``CollectiveStats`` / ``OpStats`` / ``GLOBAL_STATS`` — now thin views
  over the process-wide :data:`rabit_tpu.obs.GLOBAL_REGISTRY`, so existing
  callers (``rt.collective_stats().report()``) keep working and gain
  thread safety + histogram percentiles for free.

The deprecated stdout-line parsers (``parse_stats_line`` /
``is_recovery_stats_line``) reached their removal horizon and are gone:
the tracker converts the robust engine's ``recover_stats`` /
``failure_detected`` prints — and the recovery workloads'
``recovered_at=`` / ``resumed from disk`` stamps — into structured events
(``LocalCluster.events``, ``telemetry.json``), which every in-repo
consumer reads; the undecorated line parser for that ingest lives in
``rabit_tpu.obs.events``.

Usage:

    import rabit_tpu as rt
    ... rt.allreduce(...) ...
    print(rt.collective_stats().report())   # counts/bytes/latency per op

    from rabit_tpu.profile import xla_trace
    with xla_trace("/tmp/tb"):              # open in TensorBoard / xprof
        run_tpu_step()
"""

from __future__ import annotations

import contextlib

from rabit_tpu.obs.metrics import GLOBAL_REGISTRY, MetricsRegistry, OpStats


class CollectiveStats:
    """Per-operation accumulated timing — the historical facade, now backed
    by a thread-safe :class:`rabit_tpu.obs.MetricsRegistry`.  A bare
    ``CollectiveStats()`` gets its own private registry; ``GLOBAL_STATS``
    shares the process-wide one that ``rabit_tpu.api`` times into."""

    def __init__(self, registry: MetricsRegistry | None = None):
        self._registry = registry if registry is not None else MetricsRegistry()

    @property
    def registry(self) -> MetricsRegistry:
        return self._registry

    @property
    def ops(self) -> dict[str, OpStats]:
        return self._registry.ops

    def timed(self, op: str, nbytes: int):
        """Context manager timing one collective (delegates to the
        registry; also feeds the per-op latency histogram)."""
        return self._registry.timed(op, nbytes)

    def reset(self) -> None:
        self._registry.reset()

    def report(self) -> str:
        """One line per op: count, volume, mean/max latency, bandwidth,
        and latency percentiles."""
        return self._registry.report()


#: process-wide collector used by rabit_tpu.api
GLOBAL_STATS = CollectiveStats(registry=GLOBAL_REGISTRY)


@contextlib.contextmanager
def xla_trace(logdir: str):
    """Capture an XLA device trace for TensorBoard/xprof — the TPU-native
    replacement for hand-rolled per-link counters."""
    import jax

    jax.profiler.start_trace(logdir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()
