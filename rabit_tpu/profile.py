"""Tracing / profiling (SURVEY §5 aux subsystems).

The reference's observability is (a) ``rabit_debug=1`` per-op latency log
lines (allreduce_robust.cc:214-217,289-294) and (b) the mock engine's
per-checkpoint-interval timing totals (allreduce_mock.h:56-77).  The TPU
build keeps both ideas at the API layer — every collective is timed into a
process-wide ``CollectiveStats`` — and adds the TPU-native piece: a thin
wrapper over the XLA profiler for device traces.

Usage:

    import rabit_tpu as rt
    ... rt.allreduce(...) ...
    print(rt.collective_stats().report())   # counts/bytes/latency per op

    from rabit_tpu.profile import xla_trace
    with xla_trace("/tmp/tb"):              # open in TensorBoard / xprof
        run_tpu_step()
"""

from __future__ import annotations

import contextlib
import time
from dataclasses import dataclass, field


@dataclass
class OpStats:
    calls: int = 0
    nbytes: int = 0
    seconds: float = 0.0
    max_seconds: float = 0.0

    def add(self, nbytes: int, seconds: float) -> None:
        self.calls += 1
        self.nbytes += nbytes
        self.seconds += seconds
        self.max_seconds = max(self.max_seconds, seconds)


@dataclass
class CollectiveStats:
    """Per-operation accumulated timing, the Python-layer analogue of the
    mock engine's tsum_allreduce/tsum_allgather counters."""

    ops: dict[str, OpStats] = field(default_factory=dict)

    @contextlib.contextmanager
    def timed(self, op: str, nbytes: int):
        t0 = time.perf_counter()
        try:
            yield
        finally:
            self.ops.setdefault(op, OpStats()).add(
                nbytes, time.perf_counter() - t0
            )

    def reset(self) -> None:
        self.ops.clear()

    def report(self) -> str:
        """One line per op: count, volume, mean/max latency, bandwidth."""
        lines = []
        for op in sorted(self.ops):
            s = self.ops[op]
            mean_ms = 1e3 * s.seconds / max(s.calls, 1)
            bw = s.nbytes / s.seconds / 2**20 if s.seconds > 0 else 0.0
            lines.append(
                f"{op}: {s.calls} calls, {s.nbytes / 2**20:.2f} MiB, "
                f"mean {mean_ms:.3f} ms, max {1e3 * s.max_seconds:.3f} ms, "
                f"{bw:.1f} MiB/s"
            )
        return "\n".join(lines) if lines else "(no collectives recorded)"


#: process-wide collector used by rabit_tpu.api
GLOBAL_STATS = CollectiveStats()


def parse_stats_line(line: str) -> dict[str, str]:
    """Parse a ``key=value``-style tracker line (the robust engine's
    ``recover_stats`` / ``recover_stats_final`` observability prints) into a
    dict.  One parser for every consumer (recovery/consensus benches, tests)
    so a stats-line format change has a single point of truth."""
    return dict(p.split("=", 1) for p in line.split() if "=" in p)


def is_recovery_stats_line(line: str) -> bool:
    """True for a recovered life's per-recovery ``recover_stats`` line from
    LoadCheckPoint — the line whose counters the recovery bench and tests
    consume.  Excludes the shutdown-time ``recover_stats_final`` lines
    (shared prefix, no per-recovery fields) and first lives (version=0).
    The companion predicate to :func:`parse_stats_line`, kept here for the
    same reason: one point of truth for the line format."""
    return ("recover_stats " in line and "recover_stats_final" not in line
            and "version=0 " not in line)


@contextlib.contextmanager
def xla_trace(logdir: str):
    """Capture an XLA device trace for TensorBoard/xprof — the TPU-native
    replacement for hand-rolled per-link counters."""
    import jax

    jax.profiler.start_trace(logdir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()
