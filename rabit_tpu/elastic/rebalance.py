"""Shard rebalancing around membership holes.

When the world shrinks (or grows back), each survivor's data shard must
be re-cut so the job still covers the WHOLE dataset: rabit's GBDT
histogram workload sums per-shard histograms, so a dead rank's rows
silently vanishing from the fold is wrong-answers, not just lost
capacity.  The dense contiguous partition here is the one partition
every rank can recompute locally from ``(n_rows, world_size, rank)``
alone — no coordination beyond the epoch's world size, which every rank
already agrees on.

Pure functions; wired through ``rabit_tpu.api.register_rebalance`` and
``rabit_tpu.models.gbdt.elastic_shard`` (the GBDT histogram path), and
used directly by the elastic worker harness and tests.
"""

from __future__ import annotations

import numpy as np


def shard_bounds(n_rows: int, world: int) -> list[tuple[int, int]]:
    """Dense contiguous ``[lo, hi)`` row ranges per rank.  The remainder
    rows go to the lowest ranks, so any two ranks' shard sizes differ by
    at most one and every row belongs to exactly one rank at every world
    size."""
    if world < 1:
        raise ValueError(f"world must be >= 1, got {world}")
    if n_rows < 0:
        raise ValueError(f"n_rows must be >= 0, got {n_rows}")
    base, rem = divmod(n_rows, world)
    bounds = []
    lo = 0
    for r in range(world):
        hi = lo + base + (1 if r < rem else 0)
        bounds.append((lo, hi))
        lo = hi
    return bounds


def shard_slice(n_rows: int, world: int, rank: int) -> slice:
    """This rank's rows under the dense partition (a ``slice`` so callers
    can index numpy arrays / memmaps without copying)."""
    if not 0 <= rank < world:
        raise ValueError(f"rank {rank} outside 0..{world - 1}")
    lo, hi = shard_bounds(n_rows, world)[rank]
    return slice(lo, hi)


def rebalance_plan(n_rows: int, old_world: int, new_world: int) -> dict:
    """Row movement when the partition re-cuts from ``old_world`` to
    ``new_world`` ranks: per new rank, which old ranks' ranges overlap
    its new range (``sources``), and the total rows that change owners
    (``moved_rows``) — the cost a shard-rebalance callback pays, surfaced
    in benches and the ``shard_rebalanced`` event."""
    old = shard_bounds(n_rows, old_world)
    new = shard_bounds(n_rows, new_world)
    sources: dict[int, list[tuple[int, int, int]]] = {}
    moved = 0
    for nr, (nlo, nhi) in enumerate(new):
        parts = []
        for orank, (olo, ohi) in enumerate(old):
            lo, hi = max(nlo, olo), min(nhi, ohi)
            if lo < hi:
                parts.append((orank, lo, hi))
                if orank != nr:
                    moved += hi - lo
        sources[nr] = parts
    return {"moved_rows": moved, "sources": sources,
            "old_world": old_world, "new_world": new_world}


def refold(parts: list[np.ndarray]) -> np.ndarray:
    """Rank-order fold of per-rank contributions — the deterministic fold
    every elastic collective uses (rank 0 first, then 1, ...), so the
    result is bitwise identical on every rank and reproducible at any
    world size for exact dtypes (integer histograms)."""
    if not parts:
        raise ValueError("refold needs at least one contribution")
    acc = np.array(parts[0], copy=True)
    for p in parts[1:]:
        acc = acc + p
    return acc
