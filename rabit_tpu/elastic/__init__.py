"""rabit_tpu.elastic — elastic worlds: membership epochs, hot spares,
shrink/grow recovery waves (ISSUE 6 tentpole; doc/elasticity.md).

Three pieces:

* **membership** — the pure world-epoch state machine the tracker
  delegates to: a monotonically increasing ``(epoch, world_size,
  rank_map)`` line, wave decisions (promote a parked spare / shrink to
  the survivors / grow back toward the launch size), and rank-map
  deltas;
* **rebalance** — the dense shard re-partition every rank recomputes
  locally from ``(n_rows, world, rank)`` when the world resizes, plus
  the rank-order fold that keeps collectives bitwise reproducible
  across resizes;
* **client** — the elastic worker harness: spare parking on a warm
  socket, epoch-stamped ring links, deterministic allreduce, post-wave
  state consensus, version-boundary epoch polling.
"""

from rabit_tpu.elastic.membership import (  # noqa: F401 (re-exports)
    MembershipManager,
    WaveDecision,
    WorldEpoch,
    rank_map_delta,
)
from rabit_tpu.elastic.rebalance import (  # noqa: F401 (re-exports)
    rebalance_plan,
    refold,
    shard_bounds,
    shard_slice,
)
#: client re-exports resolve lazily (PEP 562): the client rides the
#: tracker protocol and obs shipping, both of which import THIS package
#: through the tracker's membership delegation — an eager import here
#: would be a cycle.
_CLIENT_EXPORTS = ("ElasticWorker", "ElasticResult", "EpochBroken")


def __getattr__(name: str):
    if name in _CLIENT_EXPORTS:
        from rabit_tpu.elastic import client

        return getattr(client, name)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")


def settings(cfg) -> dict:
    """Resolve the elastic config keys (doc/parameters.md, "Elastic
    worlds") into the tracker/launcher-facing knobs: whether this worker
    is a hot spare, the shrink deadline, the world floor, and the
    spare-promotion grace."""
    return {
        "spare": cfg.get_bool("rabit_spare"),
        "shrink_after_sec": float(
            cfg.get("rabit_shrink_after_sec", "0") or "0"),
        "min_world": cfg.get_int("rabit_min_world", 1),
        "promote_after_sec": float(
            cfg.get("rabit_spare_promote_sec", "0.25") or "0.25"),
    }
