"""Elastic worker harness — the Python client of the elastic tracker.

One :class:`ElasticWorker` is a full protocol citizen of an elastic job
(doc/elasticity.md): it binds a listen socket, checks in (``CMD_START``,
or ``CMD_SPARE`` to park in the hot-spare pool), builds epoch-stamped
ring links to its peers, and runs a deterministic iterate-allreduce-
checkpoint loop whose collectives are **bitwise identical on every rank
at every world size**: each round ring-allgathers the per-rank
contributions and folds them in rank order (rank 0 first), so exact
dtypes (integer histograms — the GBDT workload's shape) reproduce the
same bits no matter how the world resized along the way.

The worker executes whatever ring the tracker PLANNED
(doc/scheduling.md): the Assignment's trailing schedule section carries
a ring ORDER (``rabit_tpu.sched`` — identity for tree/ring, a
mesh-serpentine Swing layout, or a repaired ring routed around a
degraded link), links go to the planned neighbors, and allgather blocks
are attributed by ring position — the FOLD stays rank-order, so every
schedule reproduces the same bits.  The executor also measures how long
it waits on its incoming link and, past ``slow_report_share`` of the
epoch's wall time, reports the link as degraded (a ``slow_link`` print
the tracker converts to a ``link_degraded`` event) — the live telemetry
the next wave's repair plan consumes.

Quorum mode (``quorum=`` spec; rabit_tpu.quorum,
doc/partial_allreduce.md) replaces the lockstep allgather with a
straggler-tolerant round: tagged blocks flood the planned ring augmented
by SKIP links (a successor past ``quorum_wait`` dials around its silent
predecessor; the upstream rank tees the flow past the straggler), the
round folds once the tracker's frozen K-of-N exclusion record says so,
and a straggler's late blocks land as exact correction terms at the next
record after delivery — with the final round always exact, and every
fold bitwise identical on every rank, under replay, and after recovery.
``codec=`` composes the PR 5 wire codecs into both the legacy and quorum
paths (deterministic rank-symmetric encode, rank-order decode-fold).

Failure shape: any link error mid-collective abandons the epoch — links
close, the worker re-checks-in with ``CMD_RECOVER``, and the next wave
(same size after a spare promotion, smaller after a shrink, larger after
a grow-back) re-partitions the work via ``rabit_tpu.elastic.rebalance``
and resumes from the last committed version.  State agreement after
every wave is a version consensus plus a holder broadcast along the
ring, mirroring the durable store's ``_disk_resume`` contract; a freshly
promoted spare starts from the tracker's cached compressed bootstrap
blob and is topped up the same way.

The harness runs as threads (tests, chaos fuzzing, benches) or inside a
process; everything socket is bounded, so "stuck" is an error, never a
hang.  The native C++ engine keeps its fixed-world contract — elastic
resizing at this layer is what the tracker's membership epochs enable
for Python-side workloads, and the seam the engines hook via
``rabit_tpu.api.rebootstrap``.
"""

from __future__ import annotations

import json
import pickle
import select
import socket
import threading
import time
from dataclasses import dataclass, field
from typing import Callable

import numpy as np

from rabit_tpu.elastic.rebalance import refold
from rabit_tpu.obs.metrics import MetricsRegistry
from rabit_tpu.obs.ship import (Heartbeat, build_snapshot, renew_lease,
                                ship_snapshot)
from rabit_tpu.obs.stream import DeltaSource, stream_observe
from rabit_tpu.tracker import protocol as P


class EpochBroken(Exception):
    """The current epoch's links are unusable (peer died, stale epoch,
    timeout): abandon the epoch and re-enter a wave."""


class Rewave(Exception):
    """The tracker asked for a re-wave at this version boundary (grow)."""


@dataclass
class ElasticResult:
    task_id: str
    completed: bool = False
    died: bool = False
    promoted: bool = False
    parked_only: bool = False
    final_version: int = 0
    state: np.ndarray | None = None
    epochs: list[int] = field(default_factory=list)
    worlds: list[int] = field(default_factory=list)
    error: str = ""
    #: cumulative seconds spent waiting on the incoming ring link across
    #: all epochs — the degraded-link signature the benches compare
    wait_prev_s: float = 0.0
    #: slow_link reports this worker sent (at most one per epoch)
    slow_reports: int = 0
    # -- quorum mode (rabit_tpu.quorum, doc/partial_allreduce.md) --
    #: rounds folded under a tracker-agreed exclusion record
    quorum_rounds: int = 0
    #: rounds whose record excluded at least one rank
    excluded_rounds: int = 0
    #: correction terms (late blocks) this worker folded
    corrections_folded: int = 0
    #: rounds this worker skipped contributing to while catching up
    #: (the bounded-staleness catch-up: the group's record had already
    #: excluded it, so no correction debt is created)
    skipped_contributions: int = 0
    #: monotonic commit time per version (quorum benches derive the
    #: live-rank round cadence from these)
    commit_times: dict = field(default_factory=dict)


class ElasticWorker:
    """One elastic job participant (see module docstring).

    ``contribution(version, world, rank) -> np.ndarray`` is the per-round
    work: it must cover this rank's shard of the SAME logical dataset at
    any world size (``rebalance.shard_slice`` is the canonical cut), with
    a world-independent shape, so the rank-order fold reproduces the same
    totals across resizes.  ``fail`` injects deterministic deaths for
    chaos schedules: ``("die", v)`` exits silently before contributing to
    version ``v``; ``("die_parked",)`` a spare that dies in the pool;
    ``("die_promoted",)`` a spare that dies the instant it is promoted —
    mid-promotion, before any link comes up.
    """

    def __init__(
        self,
        tracker,
        task_id: str,
        contribution: Callable[[int, int, int], np.ndarray],
        niter: int,
        *,
        spare: bool = False,
        heartbeat_sec: float = 0.0,
        rpc_timeout: float = 2.0,
        wave_timeout: float = 20.0,
        link_timeout: float = 10.0,
        deadline_sec: float = 60.0,
        fail: tuple | None = None,
        advertise_port: int | None = None,
        slow_report_share: float = 0.0,
        quorum: str = "",
        quorum_wait: float = 0.35,
        codec: str = "",
        job: str = "",
    ):
        # ``tracker`` is one (host, port) or a failover LIST of them
        # (rabit_tracker_addrs, doc/ha.md: the primary first, then its
        # warm standby); every tracker RPC and raw check-in connection
        # rotates through the list, so a primary tracker death is a
        # retry, not a job loss.
        if tracker and isinstance(tracker[0], (tuple, list)):
            self.addrs = [(t[0], int(t[1])) for t in tracker]
        else:
            self.addrs = [(tracker[0], int(tracker[1]))]
        self.tracker = self.addrs[0]
        self._active = 0  # index of the address that last answered
        # The optional job key prefixes the wire task id ("job/task",
        # protocol.join_job) so a multi-job CollectiveService routes
        # this worker to its job's partition; empty = the legacy
        # single-job namespace, byte-identical (doc/service.md).
        self.task_id = P.join_job(job, task_id)
        self.contribution = contribution
        self.niter = int(niter)
        self.spare = bool(spare)
        self.heartbeat_sec = float(heartbeat_sec)
        self.rpc_timeout = float(rpc_timeout)
        self.wave_timeout = float(wave_timeout)
        self.link_timeout = float(link_timeout)
        self.deadline = time.monotonic() + float(deadline_sec)
        self.fail = fail
        self._stop = threading.Event()
        self._listen = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._listen.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._listen.bind(("127.0.0.1", 0))
        self._listen.listen(16)
        self.listen_port = self._listen.getsockname()[1]
        self._links: dict[int, socket.socket] = {}
        self._hb: Heartbeat | None = None
        self._rank = -1
        # The port peers are told to dial — normally the listen port, but
        # a chaos harness interposing a per-link proxy advertises the
        # proxy's port instead (rabit_tpu.chaos slow_link).
        self.advertise_port = advertise_port
        # Degraded-link self-reporting (doc/scheduling.md): past this
        # share of the epoch's wall time spent waiting on the incoming
        # ring link, report it once per epoch.  0 disables.
        self.slow_report_share = float(slow_report_share)
        # planned-ring execution state, reset per assignment
        self._order: list[int] = []
        self._pos = 0
        self._ring_prev = -1
        self._ring_next = -1
        self._wait_total_s = 0.0   # across all epochs (ElasticResult)
        self._epoch_wait_s = 0.0
        # Per-worker streamed-metrics registry (doc/observability.md):
        # chaos schedules and tests run many workers per process, so the
        # ring-wait series must not alias in the process-global registry;
        # the heartbeat tick piggybacks each window's delta (CMD_METRICS)
        # so the tracker's live rollup — and the diagnosis plane reading
        # it — sees this worker's link waits while the job runs.
        self._metrics_reg = MetricsRegistry()
        self._delta_src = DeltaSource(self._metrics_reg)
        self._epoch_started = 0.0
        self._epoch_reported = False
        self._n_slow_reports = 0
        # Quorum mode (rabit_tpu.quorum, doc/partial_allreduce.md): the
        # K-of-N spec ("" = legacy exact collectives), the per-round
        # deadline before reporting a partial quorum / skipping a silent
        # upstream rank, and an optional wire codec (rabit_tpu.compress;
        # deterministic rank-symmetric encode, rank-order decode-fold —
        # i8 + quorum is the median-tracking fast path).
        self.quorum_spec = str(quorum or "")
        if self.quorum_spec:
            from rabit_tpu.quorum import parse_spec

            parse_spec(self.quorum_spec)  # typo'd quorum fails at build
        self.quorum_wait = float(quorum_wait)
        self.codec_name = str(codec or "")
        self._codec = None
        if self.codec_name:
            from rabit_tpu.compress import get_codec

            self._codec = get_codec(self.codec_name)
        # per-epoch quorum round state (cleared in _close_links)
        self._qframes: dict[tuple[int, int], bytes] = {}  # (v, origin)
        self._qseen: set[tuple[int, int]] = set()
        self._qagreed_prev: set[tuple[int, int]] = set()
        self._known_late: set[int] = set()
        self._skip_in: list[socket.socket] = []   # we dialed around someone
        self._tee_out: list[socket.socket] = []   # someone dialed around us
        self._skip_from = -1
        # job-lifetime quorum accounting (ElasticResult)
        self._qlike: np.ndarray | None = None     # decode template
        self._q_rounds = 0
        self._q_excluded_rounds = 0
        self._q_corrections = 0
        self._q_skipped = 0
        self._commit_times: dict[int, float] = {}

    # -- lifecycle -----------------------------------------------------------

    def stop(self) -> None:
        self._stop.set()

    def _check_deadline(self) -> None:
        if self._stop.is_set():
            raise EpochBroken("stopped")
        if time.monotonic() > self.deadline:
            raise TimeoutError(
                f"elastic worker {self.task_id}: deadline exceeded")

    # -- tracker RPCs --------------------------------------------------------

    def _connect(self, timeout: float) -> socket.socket:
        """Dial the tracker, rotating through the failover address list
        starting from the last one that answered (doc/ha.md).  Raises
        the last OSError when no address answers."""
        last: Exception | None = None
        for i in range(len(self.addrs)):
            idx = (self._active + i) % len(self.addrs)
            try:
                sock = socket.create_connection(self.addrs[idx],
                                                timeout=timeout)
            except OSError as exc:
                last = exc
                continue
            self._active = idx
            return sock
        raise last if last is not None else OSError("no tracker address")

    def _checkin(self, cmd: int, prev_rank: int) -> P.Assignment:
        """START/RECOVER check-in on a raw socket: the reply is either an
        Assignment (the wave closed with us in it) or a park frame (the
        wave had no slot — we joined the spare pool; the SAME socket then
        waits for promotion).  Transport failures and timed-out waves
        retry — the tracker replaces a task id's stale pending entry on
        re-check-in — until the worker deadline converts "stuck" into a
        hard error."""
        while True:
            self._check_deadline()
            sock = None
            try:
                sock = self._connect(self.rpc_timeout)
                P.send_hello(sock, cmd, self.task_id, prev_rank=prev_rank,
                             listen_port=self.advertise_port
                             or self.listen_port)
                asg = self._await_assignment(sock)
                if asg is None:  # parked: wait for promotion, same socket
                    asg = self._await_assignment(sock, parked=True)
                if asg is not None:
                    return asg
            except (OSError, ValueError, ConnectionError, EpochBroken):
                pass
            finally:
                # Safe on success too: the assignment was fully parsed and
                # the tracker closes its end after sending.
                if sock is not None:
                    try:
                        sock.close()
                    except OSError:
                        pass
            time.sleep(0.05)

    def _await_assignment(self, sock: socket.socket,
                          parked: bool = False) -> P.Assignment | None:
        """Wait (bounded, stop-aware) for the wave reply on ``sock``.
        Returns the Assignment, or None when a park frame arrived
        (``parked=False``) to signal "now in the pool"."""
        end = min(time.monotonic() + self.wave_timeout, self.deadline)
        while True:
            self._check_deadline()
            sock.settimeout(0.2)
            try:
                magic = P.get_u32(sock)
            except socket.timeout:
                if time.monotonic() > end and not parked:
                    raise EpochBroken("wave reply timed out")
                continue
            sock.settimeout(self.link_timeout)
            if magic == P.MAGIC_ASSIGN:
                return self._finish_assignment(sock)
            if magic == P.MAGIC_BLOB and not parked:
                version = P.get_u32(sock)
                n = P.get_u32(sock)
                blob = P.recv_exact(sock, n) if n else b""
                self._note_blob(version, blob)
                return None
            raise ValueError(f"unexpected wave reply magic {magic:#x}")

    @staticmethod
    def _finish_assignment(sock: socket.socket) -> P.Assignment:
        """Parse the Assignment body after its magic was consumed."""
        return P.Assignment.recv_body(sock)

    def _park(self) -> P.Assignment | None:
        """CMD_SPARE park: receive the cached bootstrap blob, then hold
        the warm socket until promoted (Assignment), released (EOF at
        job end), or told to die by the fail schedule."""
        sock = self._connect(self.rpc_timeout)
        try:
            P.send_hello(sock, P.CMD_SPARE, self.task_id,
                         listen_port=self.advertise_port
                         or self.listen_port)
            sock.settimeout(self.wave_timeout)
            version, blob = P.recv_blob_frame(sock)
            self._note_blob(version, blob)
            if self.fail is not None and self.fail[0] == "die_parked":
                raise EpochBroken("spare died while parked")
            while True:
                if self._stop.is_set() or time.monotonic() > self.deadline:
                    return None
                sock.settimeout(0.2)
                try:
                    magic = P.get_u32(sock)
                except socket.timeout:
                    continue
                except (ConnectionError, OSError):
                    return None  # tracker gone / job over: unused spare
                sock.settimeout(self.link_timeout)
                if magic != P.MAGIC_ASSIGN:
                    return None
                return self._finish_assignment(sock)
        finally:
            try:
                sock.close()
            except OSError:
                pass

    def _maybe_report_slow(self, asg: P.Assignment) -> None:
        """Degraded-link self-report (doc/scheduling.md, "Repair
        policy"): when waiting on the incoming ring link has consumed
        more than ``slow_report_share`` of this epoch's wall time, print
        a ``slow_link`` line the tracker ingests as a ``link_degraded``
        event.  At most one report per epoch; a delayed frame cascades
        downstream, but the slow link's DST accumulates by far the most
        wait, so self-attribution of the incoming link is correct."""
        if (self.slow_report_share <= 0 or self._epoch_reported
                or asg.world_size <= 1):
            return
        elapsed = time.monotonic() - self._epoch_started
        if elapsed < 0.2:  # too little evidence to indict a link
            return
        share = self._epoch_wait_s / elapsed
        if share < self.slow_report_share:
            return
        self._epoch_reported = True
        self._n_slow_reports += 1
        line = (f"[{asg.rank}] slow_link src={self._ring_prev} "
                f"dst={asg.rank} wait={self._epoch_wait_s:.3f} "
                f"share={share:.3f}")
        try:
            P.tracker_rpc(self.tracker[0], self.tracker[1], P.CMD_PRINT,
                          self.task_id, prev_rank=asg.rank, message=line,
                          timeout=self.rpc_timeout, retries=1,
                          addrs=self.addrs)
        except (P.TrackerUnreachable, ValueError):
            pass  # reporting must never fail the job

    def _query_epoch(self) -> dict | None:
        try:
            info = P.tracker_rpc(
                self.tracker[0], self.tracker[1], P.CMD_EPOCH, self.task_id,
                prev_rank=self._rank, message=str(self._version),
                timeout=self.rpc_timeout, retries=1, addrs=self.addrs)
            return info if isinstance(info, dict) else None
        except (P.TrackerUnreachable, ValueError):
            return None

    def _ship_blob(self) -> None:
        """Rank 0 refreshes the tracker's spare bootstrap blob after each
        commit: the (version, state) pickle, zlib-framed exactly like the
        durable store's recovery blobs (rabit_tpu.compress)."""
        from rabit_tpu.compress import get_codec

        blob = get_codec("zlib").encode_bytes(
            pickle.dumps((self._version, self._state),
                         protocol=pickle.HIGHEST_PROTOCOL))
        try:
            with self._connect(self.rpc_timeout) as sock:
                P.send_hello(sock, P.CMD_BLOB, self.task_id,
                             blob=blob, blob_version=self._version)
                P.get_u32(sock)  # ACK — best-effort, errors tolerated
        except (OSError, ConnectionError, ValueError):
            pass  # blob shipping must never fail the job

    def _note_blob(self, version: int, blob: bytes) -> None:
        if version <= 0 or not blob:
            return
        from rabit_tpu.compress import get_codec

        try:
            ver, state = pickle.loads(get_codec("zlib").decode_bytes(blob))
        except Exception:  # noqa: BLE001 — a torn blob is only a cold start
            return
        if ver > self._version:
            self._version, self._state = int(ver), state

    # -- peer links ----------------------------------------------------------

    def _adopt_schedule(self, asg: P.Assignment) -> None:
        """Adopt the assignment's planned ring (doc/scheduling.md): a
        valid trailing ring_order permutation wins, anything else (older
        tracker, empty frame) falls back to the legacy identity ring.
        Resets the epoch's wait accounting."""
        world = asg.world_size
        if (len(asg.ring_order) == world
                and sorted(asg.ring_order) == list(range(world))):
            self._order = list(asg.ring_order)
        else:
            self._order = list(range(world))
        self._pos = self._order.index(asg.rank)
        self._ring_prev = self._order[(self._pos - 1) % world]
        self._ring_next = self._order[(self._pos + 1) % world]
        self._epoch_wait_s = 0.0
        self._epoch_started = time.monotonic()
        self._epoch_reported = False

    def _build_links(self, asg: P.Assignment) -> None:
        """Establish the epoch's ring links: lower rank dials, higher rank
        accepts; the MAGIC_LINK handshake carries (rank, epoch) so stale
        dialers from a previous epoch are dropped (the native engine's
        exact contract, comm.cc BuildLinks).  Neighbors come from the
        PLANNED ring order, not the assignment's legacy prefix."""
        self._close_links()
        self._adopt_schedule(asg)
        world = asg.world_size
        if world <= 1:
            return
        neighbors = {self._ring_prev, self._ring_next} - {asg.rank}
        expect_accept = {p for p in neighbors if p < asg.rank}
        deadline = min(time.monotonic() + self.link_timeout, self.deadline)
        for peer in sorted(p for p in neighbors if p > asg.rank):
            host, port = asg.peers[peer]
            try:
                s = socket.create_connection((host, port),
                                             timeout=self.link_timeout)
                s.settimeout(self.link_timeout)
                s.sendall(P.put_u32(P.MAGIC_LINK) + P.put_i32(asg.rank)
                          + P.put_u32(asg.epoch))
            except OSError as exc:
                raise EpochBroken(f"dial to rank {peer} failed: {exc!r}")
            self._links[peer] = s
        while expect_accept:
            if self._stop.is_set() or time.monotonic() > deadline:
                raise EpochBroken(
                    f"links from {sorted(expect_accept)} never arrived")
            self._listen.settimeout(0.2)
            try:
                s, _ = self._listen.accept()
            except socket.timeout:
                continue
            except OSError as exc:
                raise EpochBroken(f"accept failed: {exc!r}")
            try:
                s.settimeout(self.link_timeout)
                magic = P.get_u32(s)
                peer = P.get_i32(s)
                epoch = P.get_u32(s)
            except (ConnectionError, OSError, socket.timeout):
                s.close()
                continue
            if (magic != P.MAGIC_LINK or epoch != asg.epoch
                    or peer not in expect_accept):
                s.close()  # stale dialer from a previous epoch; drop
                continue
            self._links[peer] = s
            expect_accept.discard(peer)

    def _close_links(self) -> None:
        for s in self._links.values():
            try:
                s.close()
            except OSError:
                pass
        self._links.clear()
        # Quorum round state is epoch-scoped: skip/tee sockets die with
        # the ring links, and retained frames/records cannot survive a
        # membership wave (ranks renumber — doc/partial_allreduce.md,
        # "Epoch boundaries").
        for s in self._skip_in + self._tee_out:
            try:
                s.close()
            except OSError:
                pass
        self._skip_in = []
        self._tee_out = []
        self._qframes.clear()
        self._qseen.clear()
        self._qagreed_prev.clear()
        self._known_late.clear()
        self._skip_from = -1

    @staticmethod
    def _send_frame(sock: socket.socket, payload: bytes) -> None:
        try:
            sock.sendall(P.put_u32(len(payload)) + payload)
        except OSError as exc:
            raise EpochBroken(f"link send failed: {exc!r}")

    @staticmethod
    def _recv_frame(sock: socket.socket) -> bytes:
        try:
            n = P.get_u32(sock)
            return P.recv_exact(sock, n) if n else b""
        except (ConnectionError, OSError, socket.timeout) as exc:
            raise EpochBroken(f"link recv failed: {exc!r}")

    # -- collectives ---------------------------------------------------------

    def _ring_allgather(self, asg: P.Assignment,
                        payload: bytes) -> list[bytes]:
        """Every rank's payload, in RANK ORDER — world-1 ring hops (send
        to ring_next, receive from ring_prev), then the caller folds
        deterministically.  Payloads are small control-plane frames; both
        ring directions of a 2-world share one socket, which is safe
        because each hop sends before it receives and the frames fit the
        kernel socket buffers."""
        world = asg.world_size
        blocks: dict[int, bytes] = {asg.rank: payload}
        if world == 1:
            return [payload]
        nxt = self._links[self._ring_next]
        prv = self._links[self._ring_prev]
        outgoing = payload
        for step in range(world - 1):
            self._send_frame(nxt, outgoing)
            t0 = time.monotonic()
            incoming = self._recv_frame(prv)
            wait = time.monotonic() - t0
            self._epoch_wait_s += wait
            self._wait_total_s += wait
            # Per-planned-link wait histogram for the live telemetry
            # plane (doc/observability.md): the route-around loop reads
            # these (src -> dst) health series from the tracker scrape.
            stream_observe("link_wait_seconds", wait,
                           registry=self._metrics_reg,
                           src=self._ring_prev, dst=asg.rank)
            # the block s steps behind THIS POSITION in the planned ring
            blocks[self._order[(self._pos - 1 - step) % world]] = incoming
            outgoing = incoming
        return [blocks[r] for r in range(world)]

    def _ring_broadcast(self, asg: P.Assignment, root: int,
                        payload: bytes | None) -> bytes:
        """Forward ``payload`` from ``root`` around the ring (world-1
        hops); every rank receives the identical bytes."""
        world = asg.world_size
        if world == 1:
            assert payload is not None
            return payload
        dist = (self._pos - self._order.index(root)) % world
        if dist == 0:
            assert payload is not None
            self._send_frame(self._links[self._ring_next], payload)
            return payload
        payload = self._recv_frame(self._links[self._ring_prev])
        if dist < world - 1:
            self._send_frame(self._links[self._ring_next], payload)
        return payload

    def _encode_block(self, contrib: np.ndarray) -> bytes:
        """One rank's wire block: raw bytes, or the configured codec's
        deterministic rank-symmetric encoding (rabit_tpu.compress)."""
        if self._codec is None:
            return contrib.tobytes()
        if contrib.dtype != np.float32:
            raise ValueError(
                f"codec={self.codec_name!r} needs float32 contributions, "
                f"got {contrib.dtype}")
        return self._codec.encode(contrib)

    def _decode_block(self, blob: bytes, like: np.ndarray) -> np.ndarray:
        """Decode one wire block back into ``like``'s shape/dtype.  Every
        rank decodes the identical bytes with the identical codec, so the
        rank-order fold stays bitwise identical."""
        if self._codec is None:
            return np.frombuffer(blob, dtype=like.dtype).reshape(like.shape)
        return self._codec.decode(blob, int(like.size)).reshape(like.shape)

    def _allreduce_sum(self, asg: P.Assignment,
                       contrib: np.ndarray) -> np.ndarray:
        """Rank-order fold of the allgathered contributions: bitwise
        identical on every rank, and — for exact dtypes — identical
        across world sizes that partition the same dataset."""
        contrib = np.ascontiguousarray(contrib)
        parts = self._ring_allgather(asg, self._encode_block(contrib))
        return refold([self._decode_block(b, contrib) for b in parts])

    # -- quorum rounds (rabit_tpu.quorum, doc/partial_allreduce.md) ----------
    #
    # A quorum round replaces the lockstep ring allgather with a flood of
    # TAGGED blocks ``(version, origin, payload)`` over the planned ring
    # augmented by skip links: every first-seen block is stored and fanned
    # out (ring next + tees), so duplicates are idempotent and the flow
    # stays connected even when a straggler's position is routed around.
    # The round then fetches the tracker's frozen exclusion record (one
    # CMD_QUORUM RPC), drains until it holds every agreed block and every
    # decided correction, and folds in rank order — bitwise identical on
    # every rank, under replay, and after recovery.

    def _quorum_on(self) -> bool:
        return bool(self.quorum_spec)

    def _q_have(self, v: int) -> set[int]:
        """Ranks whose version-``v`` block this worker currently holds."""
        return {r for (vv, r) in self._qframes if vv == v}

    def _qpost(self, asg: P.Assignment, v: int, origin: int,
               payload: bytes) -> bool:
        """Store a tagged block on first sight and fan it out to the ring
        successor plus every tee.  Returns True when the block was new."""
        key = (v, origin)
        if key in self._qseen:
            return False
        self._qseen.add(key)
        self._qframes[key] = payload
        frame = P.put_block_frame(v, origin, payload)
        if asg.world_size > 1 and self._ring_next in self._links:
            self._send_frame(self._links[self._ring_next], frame)
        for s in list(self._tee_out):
            try:
                s.sendall(P.put_u32(len(frame)) + frame)
            except OSError:
                self._drop_tee(s)
        return True

    def _drop_tee(self, s: socket.socket) -> None:
        try:
            s.close()
        except OSError:
            pass
        if s in self._tee_out:
            self._tee_out.remove(s)

    def _drop_skip(self, s: socket.socket) -> None:
        try:
            s.close()
        except OSError:
            pass
        if s in self._skip_in:
            self._skip_in.remove(s)

    def _q_accept(self, asg: P.Assignment) -> None:
        """Accept one mid-round dial: a MAGIC_SKIP hello registers a tee
        (the dialer is routing around our silent downstream neighbor) and
        is replayed every retained frame so it can fold the rounds it is
        missing; anything else (a stale MAGIC_LINK dialer from a dead
        epoch) is dropped — exactly _build_links' stale-dialer rule."""
        self._listen.settimeout(0.2)
        try:
            s, _ = self._listen.accept()
        except (socket.timeout, OSError):
            return
        try:
            s.settimeout(self.link_timeout)
            magic = P.get_u32(s)
            if magic != P.MAGIC_SKIP:
                s.close()
                return
            _peer, epoch, _since = P.read_skip_frame(s)
        except (ConnectionError, OSError, ValueError):
            try:
                s.close()
            except OSError:
                pass
            return
        if epoch != asg.epoch:
            try:
                s.close()
            except OSError:
                pass
            return
        try:
            for (v, origin) in sorted(self._qframes):
                frame = P.put_block_frame(v, origin,
                                          self._qframes[(v, origin)])
                s.sendall(P.put_u32(len(frame)) + frame)
        except OSError:
            try:
                s.close()
            except OSError:
                pass
            return
        self._tee_out.append(s)

    def _q_skip_dial(self, asg: P.Assignment, v: int) -> None:
        """Route around a silent upstream (the ISSUE's 'a rank past the
        quorum deadline is skipped by its ring successor'): dial the
        ring-order predecessor of the current frame source and receive
        the flow from there.  Repeated stalls walk further back — two
        adjacent stragglers are skipped one dial at a time."""
        world = asg.world_size
        if world <= 2:
            return  # no third rank to route through
        cur = self._skip_from if self._skip_from >= 0 else self._ring_prev
        pos = self._order.index(cur)
        target = self._order[(pos - 1) % world]
        if target == asg.rank or target == cur:
            return
        self._skip_from = target  # walk further back next stall regardless
        try:
            host, port = asg.peers[target]
            s = socket.create_connection((host, port),
                                         timeout=self.link_timeout)
            s.settimeout(self.link_timeout)
            s.sendall(P.put_skip_frame(asg.rank, asg.epoch, v))
        except (OSError, KeyError):
            return
        self._skip_in.append(s)

    def _qpump(self, asg: P.Assignment, tick: float = 0.05) -> bool:
        """One bounded pass over every inbound source — the ring prev
        link, any skip links, and the listen socket (peers dialing around
        OUR silent neighbor).  Returns True when a new frame landed."""
        ins: list[socket.socket] = []
        if asg.world_size > 1 and self._ring_prev in self._links:
            ins.append(self._links[self._ring_prev])
        ins += self._skip_in
        ins.append(self._listen)
        try:
            readable, _, _ = select.select(ins, [], [], tick)
        except (OSError, ValueError):
            raise EpochBroken("select failed on ring sockets")
        progress = False
        for s in readable:
            if s is self._listen:
                self._q_accept(asg)
                continue
            try:
                data = self._recv_frame(s)
            except EpochBroken:
                if s in self._skip_in:
                    self._drop_skip(s)  # redundant path died; ring remains
                    continue
                raise
            try:
                v, origin, payload = P.read_block_frame(data)
            except ValueError:
                continue  # torn/foreign frame from a stale-epoch writer
            if not (0 <= origin < asg.world_size):
                continue
            if self._qpost(asg, v, origin, payload):
                progress = True
        return progress

    def _q_rpc(self, asg: P.Assignment, v: int, have: list[int],
               held: list[tuple[int, int]]) -> dict | None:
        """One CMD_QUORUM report; returns the parsed reply or None on a
        transport miss (the caller's bounded loop retries)."""
        # canonical JSON (sorted keys, fixed separators): the report is
        # wire bytes on a contract path — tools/tpulint determinism
        msg = json.dumps({"epoch": asg.epoch, "v": v, "have": have,
                          "held": [list(t) for t in held]},
                         sort_keys=True, separators=(",", ":"))
        try:
            reply = P.tracker_rpc(self.tracker[0], self.tracker[1],
                                  P.CMD_QUORUM, self.task_id,
                                  prev_rank=asg.rank, message=msg,
                                  timeout=self.rpc_timeout, retries=1,
                                  addrs=self.addrs)
            return reply if isinstance(reply, dict) else None
        except (P.TrackerUnreachable, ValueError):
            return None

    def _quorum_allreduce(self, asg: P.Assignment, v: int,
                          contrib: np.ndarray | None) -> np.ndarray:
        """One K-of-N round: collect -> agree -> drain -> fold.

        ``contrib=None`` is the catch-up shape: the group's record for
        this round was already decided without us (frames for a LATER
        round prove it), so we fold the frozen record and move on instead
        of dragging an ever-growing correction chain — this is what
        bounds the staleness.  The FINAL round is always exact (every
        contribution must land before the job's last commit)."""
        from rabit_tpu.quorum import quorum_count

        world = asg.world_size
        k = quorum_count(world, self.quorum_spec)
        all_ranks = set(range(world))
        exact = (k >= world) or (v >= self.niter)
        if contrib is not None:
            self._qpost(asg, v, asg.rank, self._encode_block(contrib))
        if self._qlike is None:
            if contrib is None:
                raise EpochBroken("quorum catch-up before any contribution")
            self._qlike = np.zeros_like(contrib)
        deadline = min(time.monotonic() + self.wave_timeout, self.deadline)
        # -- collect: pump until the expected blocks landed (known-late
        # ranks are not waited for — after the first excluded round the
        # straggler costs nothing per round) or the quorum deadline.
        expected = set(all_ranks) if exact else (all_ranks
                                                 - self._known_late)
        if contrib is not None:
            expected.add(asg.rank)
        else:
            expected.discard(asg.rank)
        qdl = time.monotonic() + self.quorum_wait
        last_progress = time.monotonic()
        while not expected <= self._q_have(v):
            self._check_deadline()
            if time.monotonic() > deadline:
                raise EpochBroken(f"quorum round v{v}: collect timed out")
            if self._qpump(asg):
                last_progress = time.monotonic()
            elif time.monotonic() - last_progress > self.quorum_wait:
                self._q_skip_dial(asg, v)
                last_progress = time.monotonic()
            if not exact and time.monotonic() > qdl:
                break
        # -- agree: fetch the round's frozen exclusion record.  EVERY
        # rank consults the tracker every round — a rank that collected
        # all N must still learn whether a slower reporter froze a
        # smaller fold, or the bits diverge.
        rec: dict | None = None
        while rec is None:
            self._check_deadline()
            if time.monotonic() > deadline:
                raise EpochBroken(f"quorum round v{v}: no record within "
                                  f"bound")
            have = sorted(self._q_have(v))
            held = sorted((sv, r) for (sv, r) in self._qframes if sv < v)
            reply = self._q_rpc(asg, v, have, held)
            if reply is not None:
                if reply.get("disabled"):
                    raise EpochBroken(
                        "worker runs quorum mode but the tracker has no "
                        "quorum table (set Tracker(quorum=...))")
                if reply.get("stale_epoch"):
                    raise EpochBroken("quorum report hit a newer epoch")
                if reply.get("decided"):
                    rec = reply
                    break
            if self._qpump(asg):
                last_progress = time.monotonic()
            elif time.monotonic() - last_progress > self.quorum_wait:
                self._q_skip_dial(asg, v)
                last_progress = time.monotonic()
        excluded = {int(r) for r in rec.get("excluded", ())}
        corrections = sorted((int(sv), int(r))
                             for sv, r in rec.get("corrections", ()))
        # -- drain: the record is law — hold every agreed block and every
        # decided correction before folding (they flow from whichever
        # live rank the deciding reporter was).
        need = ({(v, r) for r in all_ranks - excluded}
                | set(corrections))
        while not need <= set(self._qframes):
            self._check_deadline()
            if time.monotonic() > deadline:
                missing = sorted(need - set(self._qframes))
                raise EpochBroken(f"quorum round v{v}: agreed blocks "
                                  f"never arrived: {missing}")
            if self._qpump(asg):
                last_progress = time.monotonic()
            elif time.monotonic() - last_progress > self.quorum_wait:
                self._q_skip_dial(asg, v)
                last_progress = time.monotonic()
        # -- fold, in rank order, corrections after the round's blocks in
        # (src_version, rank) order: same blocks, same order, same bits
        # on every rank.
        like = self._qlike
        agreed = sorted(all_ranks - excluded)
        parts = [self._decode_block(self._qframes[(v, r)], like)
                 for r in agreed]
        parts += [self._decode_block(self._qframes[key], like)
                  for key in corrections]
        total = refold(parts)
        # bookkeeping: remember who is late (next round's collect skips
        # waiting on them), retire folded corrections, and retain only a
        # one-round window of payloads for skip-dial catch-up.
        self._q_rounds += 1
        if excluded:
            self._q_excluded_rounds += 1
        self._q_corrections += len(corrections)
        self._known_late = set(excluded)
        for key in corrections:
            self._qframes.pop(key, None)
        for key in self._qagreed_prev:
            self._qframes.pop(key, None)
        self._qagreed_prev = {(v, r) for r in agreed}
        return total

    # -- state agreement -----------------------------------------------------

    def _sync_state(self, asg: P.Assignment) -> None:
        """Post-wave consensus: agree on the newest committed version and
        top up every rank below it from the holder — the in-memory analog
        of the durable store's ``_disk_resume`` (lowest-ranked holder
        serves, the blob crosses the ring once)."""
        vers = self._ring_allgather(
            asg, np.array([self._version], np.int64).tobytes())
        versions = [int(np.frombuffer(b, np.int64)[0]) for b in vers]
        vmax = max(versions)
        if vmax <= 0 or all(v == vmax for v in versions):
            return
        root = versions.index(vmax)
        blob = (pickle.dumps((self._version, self._state),
                             protocol=pickle.HIGHEST_PROTOCOL)
                if asg.rank == root else None)
        got = self._ring_broadcast(asg, root, blob)
        if self._version < vmax:
            self._version, self._state = pickle.loads(got)

    # -- heartbeats ----------------------------------------------------------

    def _start_heartbeat(self) -> None:
        if self.heartbeat_sec <= 0 or self._hb is not None:
            return
        host, port = self.tracker

        def tick() -> bool:
            if self._stop.is_set():
                return False
            ok = renew_lease(host, port, self.task_id, self.heartbeat_sec,
                             rank=self._rank, addrs=self.addrs)
            # Piggyback the window's streamed-metrics delta on the lease
            # cadence (best-effort, like every obs ship).  Deferred until
            # a rank is assigned: the tracker rejects out-of-range ranks
            # at ingest, and an untaken delta simply ships after
            # promotion — no window is consumed-and-dropped while parked.
            rank = self._rank
            if rank >= 0:
                delta = self._delta_src.take()
                if delta:
                    snap = build_snapshot(self._metrics_reg, rank,
                                          self.task_id,
                                          extra={"delta": delta})
                    ship_snapshot(snap, host, port, self.task_id,
                                  timeout=max(self.heartbeat_sec, 0.2),
                                  addrs=self.addrs)
            return ok

        self._hb = Heartbeat(self.heartbeat_sec, tick, immediate=True).start()

    def _stop_heartbeat(self) -> None:
        hb, self._hb = self._hb, None
        if hb is not None:
            hb.stop()

    # -- the job loop --------------------------------------------------------

    def run(self) -> ElasticResult:
        res = ElasticResult(task_id=self.task_id)
        self._version = 0
        self._state: np.ndarray | None = None
        try:
            return self._run(res)
        except P.TrackerUnreachable as exc:
            res.error = repr(exc)
            return res
        except EpochBroken as exc:
            res.error = repr(exc)
            res.died = True
            return res
        except (ConnectionError, OSError) as exc:
            # TimeoutError (the worker deadline AND socket timeouts) is an
            # OSError subclass; a tracker already gone (job over before a
            # late spare arrived) is a ConnectionError.  Either way: report,
            # never propagate into the harness thread.
            res.error = repr(exc)
            return res
        finally:
            res.wait_prev_s = round(self._wait_total_s, 6)
            res.slow_reports = self._n_slow_reports
            res.quorum_rounds = self._q_rounds
            res.excluded_rounds = self._q_excluded_rounds
            res.corrections_folded = self._q_corrections
            res.skipped_contributions = self._q_skipped
            res.commit_times = dict(self._commit_times)
            self._stop_heartbeat()
            self._close_links()
            try:
                self._listen.close()
            except OSError:
                pass

    def _run(self, res: ElasticResult) -> ElasticResult:
        if self.spare:
            asg = self._park()
            if asg is None:
                res.parked_only = True
                res.died = (self.fail is not None
                            and self.fail[0] == "die_parked")
                return res
            res.promoted = True
            if self.fail is not None and self.fail[0] == "die_promoted":
                # Mid-promotion death: the assignment landed but no link
                # ever comes up — peers' link build fails and the next
                # wave re-plans around this spare.
                res.died = True
                return res
        else:
            asg = self._checkin(P.CMD_START, -1)
        while True:
            self._rank = asg.rank
            res.epochs.append(asg.epoch)
            res.worlds.append(asg.world_size)
            try:
                self._build_links(asg)
                self._sync_state(asg)
                self._start_heartbeat()
                while self._version < self.niter:
                    v = self._version + 1
                    if (self.fail is not None and self.fail[0] == "die"
                            and v >= self.fail[1]):
                        # Silent death: heartbeats stop, every socket
                        # closes — peers hit EpochBroken, the lease
                        # expires, and the membership layer takes over.
                        self._stop_heartbeat()
                        self._close_links()
                        res.died = True
                        res.final_version = self._version
                        res.state = self._state
                        return res
                    self._check_deadline()
                    if self._quorum_on():
                        # Bounded-staleness catch-up: a frame for a LATER
                        # round proves round v's record is already frozen
                        # — without our block, so it excluded us.  Fold
                        # the frozen record and rejoin the group's round
                        # instead of contributing rounds the job has
                        # moved past (doc/partial_allreduce.md).  Drain
                        # the queued backlog first: a rank that just
                        # finished a slow contribution hasn't looked at
                        # its sockets since the round began.
                        while self._qpump(asg, tick=0.0):
                            pass
                        ahead = max((vv for (vv, _r) in self._qseen),
                                    default=0)
                        contrib = None
                        if ahead <= v:
                            contrib = np.ascontiguousarray(
                                self.contribution(v, asg.world_size,
                                                  asg.rank))
                        else:
                            self._q_skipped += 1
                        total = self._quorum_allreduce(asg, v, contrib)
                    else:
                        contrib = np.ascontiguousarray(
                            self.contribution(v, asg.world_size, asg.rank))
                        total = self._allreduce_sum(asg, contrib)
                    self._state = (total if self._state is None
                                   else self._state + total)
                    self._version = v
                    self._commit_times[v] = time.monotonic()
                    if asg.rank == 0:
                        self._ship_blob()
                    if self._version < self.niter:
                        self._maybe_report_slow(asg)
                        info = self._query_epoch()
                        if info is not None and info.get("rewave"):
                            raise Rewave()
                break  # all versions committed
            except Rewave:
                self._close_links()
                asg = self._checkin(P.CMD_RECOVER, asg.rank)
            except EpochBroken:
                self._check_deadline()
                self._close_links()
                asg = self._checkin(P.CMD_RECOVER, asg.rank)
        # Clean shutdown handshake (tracker job accounting).
        self._stop_heartbeat()
        try:
            # With a failover list the budget spans a takeover window:
            # the rotation needs enough attempts to outlive the
            # standby's takeover lease, or completion accounting loses
            # this rank's clean exit (doc/ha.md).
            P.tracker_rpc(self.tracker[0], self.tracker[1], P.CMD_SHUTDOWN,
                          self.task_id, prev_rank=asg.rank,
                          timeout=self.rpc_timeout,
                          retries=7 if len(self.addrs) > 1 else 1,
                          backoff_cap=0.5, addrs=self.addrs)
        except (P.TrackerUnreachable, ValueError):
            pass
        res.completed = True
        res.final_version = self._version
        res.state = self._state
        return res
