"""Membership epochs — the elastic world's single source of truth.

rabit's recovery contract restores the *same* world size: a wave blocks
until ``world_size`` ranks re-check-in, so a preempted worker with no
replacement capacity stalls the job forever.  The production answer
(PAPERS.md: *Highly Available Data Parallel ML training on Mesh
Networks*) is an elastic membership layer: the job's composition is a
monotonically increasing **world epoch** ``(epoch, world_size,
rank_map)``, and a recovery wave may close at a *different* world size
than it opened —

* **promote** — a parked hot spare fills the dead rank's slot and the
  wave closes at the same size, within one wave;
* **shrink** — no spare arrives within ``shrink_after_sec``: the wave
  closes with the survivors only, ranks reassigned densely;
* **grow** — the world is below its launch size and spares are parked:
  the next wave (entered by workers at a version boundary, so
  checkpoint semantics stay intact) re-admits them up to ``base_world``.

This module is the pure decision core: no sockets, no threads, no
tracker state — the tracker (rabit_tpu/tracker/tracker.py) feeds it
check-in counts and wave ages and commits the waves it closes, and
tests drive it directly.  See doc/elasticity.md for the state machine.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Mapping

#: decide() actions.
WAIT = "wait"
CLOSE = "close"


@dataclass(frozen=True)
class WorldEpoch:
    """One committed membership generation.  ``rank_map`` is the full
    task-id -> rank assignment of the wave that opened this epoch (the
    authoritative map a late joiner needs; deltas derive from comparing
    consecutive epochs, see :func:`rank_map_delta`)."""

    epoch: int
    world_size: int
    rank_map: Mapping[str, int] = field(default_factory=dict)


@dataclass(frozen=True)
class WaveDecision:
    """What to do with a pending wave right now.

    ``action`` is ``WAIT`` (keep collecting check-ins) or ``CLOSE``.
    On CLOSE, ``world`` is the world size to close at and
    ``take_spares`` how many parked spares to promote into the wave
    first.  ``resized`` is ``world - previous world`` (negative =
    shrink, positive = grow, 0 = steady)."""

    action: str
    world: int = 0
    take_spares: int = 0
    resized: int = 0


def rank_map_delta(prev: Mapping[str, int],
                   new: Mapping[str, int]) -> dict:
    """The membership delta between two epochs' rank maps:
    ``{"joined": {task: rank}, "left": {task: old_rank},
    "moved": {task: [old_rank, new_rank]}}`` — what an epoch-stamped
    assignment reply summarizes for consumers that tracked the previous
    epoch."""
    joined = {t: r for t, r in new.items() if t not in prev}
    left = {t: r for t, r in prev.items() if t not in new}
    moved = {t: [prev[t], r] for t, r in new.items()
             if t in prev and prev[t] != r}
    return {"joined": joined, "left": left, "moved": moved}


class MembershipManager:
    """Owns the world-epoch line for one job.

    Not thread-safe by itself — the tracker calls it under its own lock
    (every method is pure computation over small dicts).  ``base_world``
    is the launch size and the grow-back target; ``current`` is the
    latest committed :class:`WorldEpoch` (epoch -1, the launch size, and
    an empty rank map before the first wave commits).
    """

    def __init__(self, base_world: int, *, min_world: int = 1,
                 shrink_after_sec: float = 0.0,
                 promote_after_sec: float = 0.25):
        if base_world < 1:
            raise ValueError(f"base_world must be >= 1, got {base_world}")
        self.base_world = int(base_world)
        self.min_world = max(int(min_world), 1)
        self.shrink_after_sec = float(shrink_after_sec)
        self.promote_after_sec = float(promote_after_sec)
        self.current = WorldEpoch(-1, self.base_world, {})
        #: committed epochs, oldest first (telemetry's resize timeline).
        self.history: list[WorldEpoch] = []

    # -- accessors ----------------------------------------------------------

    @property
    def epoch(self) -> int:
        return self.current.epoch

    @property
    def world(self) -> int:
        return self.current.world_size

    def grow_wanted(self, n_spares: int) -> bool:
        """True when the world is below its launch size and parked spares
        could fill it — the flag the tracker's epoch-query reply carries
        so workers re-enter a wave at their next version boundary."""
        return n_spares > 0 and self.world < self.base_world

    def restore(self, epoch: int, world_size: int,
                rank_map: Mapping[str, int],
                history: list[tuple[int, int]] | None = None) -> None:
        """Adopt a replayed membership line (HA failover, doc/ha.md):
        the promoted tracker must continue the SAME monotonic epoch
        numbering — a reused epoch would let stale-epoch peer links and
        quorum records collide with fresh ones.  ``history`` rebuilds
        the telemetry timeline as ``(epoch, world)`` pairs (rank maps of
        past epochs are not retained by the journal's compacted
        state)."""
        self.current = WorldEpoch(int(epoch), int(world_size),
                                  dict(rank_map))
        self.history = [WorldEpoch(int(e), int(w), {})
                        for e, w in (history or [])]
        if history and self.history:
            # the newest history entry is the current epoch: keep its map
            last = self.history[-1]
            if last.epoch == self.current.epoch:
                self.history[-1] = self.current

    # -- the wave decision ---------------------------------------------------

    def decide(self, n_pending: int, n_spares: int,
               wave_age: float) -> WaveDecision:
        """Close, promote-and-close, shrink-and-close, or wait.

        ``n_pending`` live check-ins are waiting on the current wave,
        ``n_spares`` live spares are parked, and the wave has been
        forming for ``wave_age`` seconds.  Precedence:

        1. grow back toward ``base_world`` when check-ins + spares
           exceed the current (shrunk) world;
        2. close steady when the wave is full;
        3. promote parked spares into missing slots once the wave has
           been short for ``promote_after_sec`` (a grace so a slow but
           live worker's own check-in wins the slot);
        4. shrink to the survivors once ``shrink_after_sec`` passes with
           no spare to promote (0 disables shrinking — the legacy
           block-forever contract);
        5. otherwise wait.
        """
        if n_pending <= 0:
            return WaveDecision(WAIT)
        target = self.world
        # 1. grow: a wave below base_world absorbs surplus check-ins and
        # parked spares up to the launch size.
        if self.world < self.base_world:
            reachable = min(self.base_world, n_pending + n_spares)
            if reachable > target:
                target = reachable
        if n_pending >= target:
            return WaveDecision(CLOSE, world=target,
                                take_spares=0,
                                resized=target - self.world)
        missing = target - n_pending
        # 3. promote: fill the hole from the spare pool within one wave.
        if n_spares > 0 and wave_age >= self.promote_after_sec:
            take = min(missing, n_spares)
            if n_pending + take >= target:
                return WaveDecision(CLOSE, world=target, take_spares=take,
                                    resized=target - self.world)
            # partial fill: promote what exists, then fall through to the
            # shrink clock for the remainder.
            if (self.shrink_after_sec > 0
                    and wave_age >= self.shrink_after_sec
                    and n_pending + take >= self.min_world):
                return WaveDecision(CLOSE, world=n_pending + take,
                                    take_spares=take,
                                    resized=n_pending + take - self.world)
            return WaveDecision(WAIT)
        # 4. shrink: the pool is empty past the deadline — close with the
        # survivors and keep making progress.
        if (self.shrink_after_sec > 0 and wave_age >= self.shrink_after_sec
                and n_pending >= self.min_world):
            return WaveDecision(CLOSE, world=n_pending,
                                resized=n_pending - self.world)
        return WaveDecision(WAIT)

    # -- committing ----------------------------------------------------------

    def commit(self, rank_map: Mapping[str, int],
               world_size: int) -> tuple[WorldEpoch, dict]:
        """Commit a closed wave as the next epoch.  Returns the new
        :class:`WorldEpoch` and the :func:`rank_map_delta` against the
        previous one.  The epoch number is monotonically increasing and
        never reused — it stamps assignments, peer-link handshakes, and
        RTC3 checkpoint frames."""
        if sorted(rank_map.values()) != list(range(world_size)):
            raise ValueError(
                f"rank_map {dict(rank_map)!r} is not a dense assignment "
                f"of world {world_size}")
        prev = self.current
        new = WorldEpoch(prev.epoch + 1, int(world_size), dict(rank_map))
        self.current = new
        self.history.append(new)
        return new, rank_map_delta(prev.rank_map, new.rank_map)
