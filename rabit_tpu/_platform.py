"""JAX platform pinning for tests, dryruns, and benchmark fallbacks.

The container's sitecustomize force-registers the experimental 'axon' TPU
backend through jax config — ``JAX_PLATFORMS=cpu`` in the environment does
NOT stick — so pinning to CPU requires overriding the config knob itself,
and the virtual device count must land in ``XLA_FLAGS`` before the first
backend/device query.  This is the single shared copy of that trick
(tests/conftest.py, __graft_entry__.py and bench.py all use it; they had
drifted as three hand-rolled variants in round 1).
"""

import os
import re

_COUNT_FLAG = "--xla_force_host_platform_device_count"


def force_cpu_platform(n_devices: int = 1) -> None:
    """Pin JAX to a virtual ``n_devices``-device CPU platform.

    Must be called before anything initializes a JAX backend (first
    ``jax.devices()``/``jit`` call); a pre-existing device-count flag is
    replaced, not silently kept.  Calling too late raises RuntimeError
    (unless the live backend already satisfies the request) instead of
    silently no-opping into an axon-backend hang.
    """
    import jax

    if _backends_initialized():
        devs = jax.devices()
        if devs[0].platform == "cpu" and len(devs) >= n_devices:
            return  # idempotent: already pinned satisfactorily
        raise RuntimeError(
            "force_cpu_platform called after a JAX backend initialized "
            f"({devs[0].platform} x{len(devs)}); pin before first device use "
            "or run in a fresh process"
        )

    flags = os.environ.get("XLA_FLAGS", "")
    if _COUNT_FLAG in flags:
        flags = re.sub(rf"{_COUNT_FLAG}=\d+", f"{_COUNT_FLAG}={n_devices}", flags)
    else:
        flags = (flags + f" {_COUNT_FLAG}={n_devices}").strip()
    os.environ["XLA_FLAGS"] = flags

    jax.config.update("jax_platforms", "cpu")


def enable_persistent_cache() -> None:
    """Point JAX's persistent compilation cache at the repo-local
    ``.jax_cache/`` (gitignored).

    The bench/ablation tools race several fused-round configs whose
    Mosaic compiles cost ~70-100 s EACH per process on the axon backend;
    with a warm cache the whole driver bench fits a ~30 s healed-tunnel
    window instead of ~300 s (measured 220-488 s cold vs 25.3 s warm —
    the round-3/4 wedged-tunnel failure mode).  Keyed on HLO content, so
    code changes recompile; timing loops only ever measure runs.  This is
    the single shared copy of the two config knobs (bench.py and
    tools/hist_ablation.py use it), mirroring force_cpu_platform's
    no-drift rationale above.
    """
    import jax

    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    jax.config.update("jax_compilation_cache_dir",
                      os.path.join(repo_root, ".jax_cache"))
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)


def _backends_initialized() -> bool:
    try:
        from jax._src import xla_bridge

        return xla_bridge.backends_are_initialized()
    except Exception:
        try:
            from jax._src import xla_bridge

            return bool(xla_bridge._backends)
        except Exception:
            return False
