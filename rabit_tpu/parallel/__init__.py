"""TPU parallelism: meshes, in-graph collectives, sequence-parallel rings."""

from rabit_tpu.parallel.mesh import (
    create_mesh,
    resize_ring,
    ring_perm,
    replicated,
    sharded_along,
    snake_order,
)
from rabit_tpu.parallel.collectives import (
    allreduce,
    broadcast,
    allgather,
    reduce_scatter,
    ring_shift,
    ring_reduce_scatter,
    ring_allgather,
    ring_allreduce,
    ring_allreduce_quantized,
    fused_allreduce,
)
from rabit_tpu.parallel.ring import (
    reference_attention,
    ring_attention,
    ulysses_attention,
)

__all__ = [
    "create_mesh",
    "resize_ring",
    "ring_perm",
    "replicated",
    "sharded_along",
    "snake_order",
    "allreduce",
    "broadcast",
    "allgather",
    "reduce_scatter",
    "ring_shift",
    "ring_reduce_scatter",
    "ring_allgather",
    "ring_allreduce",
    "ring_allreduce_quantized",
    "fused_allreduce",
    "ring_attention",
    "ulysses_attention",
    "reference_attention",
]
