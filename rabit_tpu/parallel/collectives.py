"""In-graph collectives — the TPU data plane.

These are the XLA-native re-expression of the reference's hand-rolled
poll-loop collectives: where rabit selects tree vs ring by payload size and
pipelines 1MB chunks over TCP (TryAllreduce dispatch,
/root/reference/src/allreduce_base.cc:454-464), here the *compiler* owns
scheduling — ``psum``/``all_gather``/``psum_scatter`` lower to fused ICI
collectives.  The explicit ring algorithms (``ring_reduce_scatter``,
``ring_allgather``, ``ring_allreduce``) express the same
bandwidth-optimal chunked rings as the reference
(TryReduceScatterRing :857-946, TryAllgatherRing :779-843) as ``ppermute``
chains — each hop a single ICI neighbor transfer — and double as the
communication skeleton for sequence parallelism (see parallel.ring).

All functions take an ``axis_name`` and must run inside ``shard_map`` /
``pjit`` over a Mesh.
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp
from jax import lax

from rabit_tpu.engine.base import BITOR, MAX, MIN, SUM
from rabit_tpu.parallel.mesh import ring_perm

Array = jax.Array


def allreduce(x: Array, axis_name: str, op: int = SUM) -> Array:
    """Allreduce with a rabit op enum (MAX/MIN/SUM/BITOR)."""
    if op == SUM:
        return lax.psum(x, axis_name)
    if op == MAX:
        return lax.pmax(x, axis_name)
    if op == MIN:
        return lax.pmin(x, axis_name)
    if op == BITOR:
        # No bitwise-or collective primitive: decompose into bit planes and
        # OR them with ONE fused pmax (a | b == max(a,b) per bit).  BITOR
        # buffers are tiny (consensus flag words, reference ActionSummary
        # allreduce_robust.h:298-315) so the nbits× inflation is free.
        nbits = x.dtype.itemsize * 8
        utype = jnp.dtype(f"uint{nbits}")
        ux = x.astype(utype)
        shifts = jnp.arange(nbits, dtype=utype).reshape((nbits,) + (1,) * x.ndim)
        planes = (ux[None] >> shifts) & utype.type(1)
        ored = lax.pmax(planes, axis_name)
        return (ored << shifts).sum(axis=0, dtype=utype).astype(x.dtype)
    raise ValueError(f"unknown reduction op {op}")


def broadcast(x: Array, axis_name: str, root: int = 0) -> Array:
    """Broadcast ``x`` from mesh position ``root`` (reference: TryBroadcast,
    allreduce_base.cc:677-765 — here a masked psum XLA turns into an
    all-reduce-from-one)."""
    idx = lax.axis_index(axis_name)
    contrib = jnp.where(idx == root, x, jnp.zeros_like(x))
    if x.dtype == jnp.bool_:
        return lax.psum(contrib.astype(jnp.int32), axis_name).astype(x.dtype)
    return lax.psum(contrib, axis_name)


def allgather(x: Array, axis_name: str, axis: int = 0, tiled: bool = False) -> Array:
    return lax.all_gather(x, axis_name, axis=axis, tiled=tiled)


def reduce_scatter(x: Array, axis_name: str, axis: int = 0) -> Array:
    """Sum-reduce then scatter slices along ``axis`` (tiled)."""
    return lax.psum_scatter(x, axis_name, scatter_dimension=axis, tiled=True)


def ring_shift(x: Any, axis_name: str, shift: int = 1) -> Any:
    """Send this shard to the ring successor ``shift`` positions away.
    Works on pytrees.  The generic streaming primitive (reference:
    RingPassing, allreduce_robust.cc:1529-1587)."""
    n = lax.axis_size(axis_name)
    return lax.ppermute(x, axis_name, ring_perm(n, shift))


def ring_reduce_scatter(x: Array, axis_name: str) -> Array:
    """Explicit n-1-step ring reduce-scatter.

    ``x``'s leading dim must be divisible by the axis size; rank i ends up
    holding chunk i of the fully reduced sum.  Mirrors the reference's
    pipelined ring (TryReduceScatterRing): at step s each rank forwards the
    partial sum of chunk (i-1-s) mod n to its successor and folds its own
    copy into the chunk arriving from its predecessor.
    """
    n = lax.axis_size(axis_name)
    idx = lax.axis_index(axis_name)
    perm = ring_perm(n)
    chunks = x.reshape((n, x.shape[0] // n) + x.shape[1:])

    def body(s, send):
        recv = lax.ppermute(send, axis_name, perm)
        mine = lax.dynamic_index_in_dim(chunks, (idx - 2 - s) % n, keepdims=False)
        return recv + mine

    init = lax.dynamic_index_in_dim(chunks, (idx - 1) % n, keepdims=False)
    return lax.fori_loop(0, n - 1, body, init)


def ring_allgather(x: Array, axis_name: str) -> Array:
    """Explicit n-1-step ring allgather: input is this rank's slice, output
    is ``(n,) + x.shape`` with slice j from rank j (reference:
    TryAllgatherRing — slice-addressed so sequence-sharded workloads
    compose, engine.h:56-79)."""
    n = lax.axis_size(axis_name)
    idx = lax.axis_index(axis_name)
    perm = ring_perm(n)
    out = jnp.zeros((n,) + x.shape, x.dtype)
    out = lax.dynamic_update_index_in_dim(out, x, idx, 0)

    def body(s, carry):
        out, cur = carry
        cur = lax.ppermute(cur, axis_name, perm)
        # After s+1 hops the block in hand originated s+1 ring positions back.
        out = lax.dynamic_update_index_in_dim(out, cur, (idx - s - 1) % n, 0)
        return out, cur

    out, _ = lax.fori_loop(0, n - 1, body, (out, x))
    return out


def ring_allreduce(x: Array, axis_name: str) -> Array:
    """Bandwidth-optimal ring allreduce = ring reduce-scatter + ring
    allgather (reference: TryAllreduceRing, allreduce_base.cc:958-977).
    Leading dim must be divisible by the axis size."""
    n = lax.axis_size(axis_name)
    owned = ring_reduce_scatter(x, axis_name)
    gathered = ring_allgather(owned, axis_name)
    return gathered.reshape(x.shape)


def _quantize_i8(v: Array, block: int, planes: int):
    """Per-block symmetric int8 quantization: returns (q[planes, m, block]
    int8, scales[m, 1] f32).  planes=1 is plain int8 (~2^-8 of block max);
    planes=2 adds a residual plane (~2^-16 of block max — the same hi/lo
    trick as ops.boost's MXU encoder).  v must be 1-D, length a multiple
    of ``block``."""
    vb = v.reshape(-1, block)
    amax = jnp.max(jnp.abs(vb), axis=1, keepdims=True)
    scale = jnp.maximum(amax, jnp.float32(1e-30)) * (1.0 / 127.0)
    a = jnp.clip(jnp.round(vb / scale), -127, 127)
    if planes == 1:
        return a.astype(jnp.int8)[None], scale
    # |resid| <= s/2 => |b| <= 127 analytically, but the bound has only
    # ~1e-5 of f32 headroom and int8 astype WRAPS on overflow (and on
    # non-finite input), so clip like the primary plane — free vs the op.
    b = jnp.clip(jnp.round((vb - a * scale) * (254.0 / scale)), -127, 127)
    return jnp.stack([a, b]).astype(jnp.int8), scale


def _dequantize_i8(q: Array, scale: Array) -> Array:
    v = q[0].astype(jnp.float32) * scale
    if q.shape[0] == 2:
        v = v + q[1].astype(jnp.float32) * (scale * (1.0 / 254.0))
    return v.reshape(-1)


def ring_allreduce_quantized(x: Array, axis_name: str, *,
                             block: int = 256, planes: int = 2) -> Array:
    """Bandwidth-compressed ring allreduce (SUM): every ICI/DCN hop ships
    int8 payloads with per-``block`` f32 scales, and all arithmetic stays
    f32 on device (EQuARX-class technique; PAPERS.md).  ``planes=2`` (the
    default) sends a hi/lo int8 pair — ~2x fewer wire bytes than f32 at
    ~2^-16-of-block-max accuracy per hop; ``planes=1`` sends one plane —
    ~3.9x compression at ~2^-8 per hop.  Reduce-scatter hops re-quantize
    the running partial sum (errors accumulate over the n-1 hops); the
    allgather phase quantizes each owner's final chunk ONCE and forwards
    the identical payload, adding a single quantization.

    LOSSY but rank-consistent: the value of chunk j on every rank is the
    decode of owner j's single int8+scale payload, and every decode —
    including the owner's own — runs at the SAME program point (one
    write-then-hop loop body, identical on all ranks under SPMD), so the
    allreduce output is bitwise identical across ranks.  Downstream
    argmax-style decisions (e.g. GBDT split selection) therefore cannot
    diverge between ranks even on exact ties.  Still opt-in: paths with
    bit-exactness guarantees vs a SERIAL replay (the robust replay
    contract, hybrid byte-identical recovery) must keep the exact
    collectives — lossy means the value differs from an unquantized psum.
    f32 input, leading dim divisible by the axis size, chunk elements
    divisible by ``block``."""
    if planes not in (1, 2):
        raise ValueError(f"ring_allreduce_quantized: planes must be 1 or 2, "
                         f"got {planes}")
    if x.dtype != jnp.float32:
        raise ValueError(
            f"ring_allreduce_quantized: f32 input required (got {x.dtype}); "
            "cast first — accumulation runs in f32 regardless"
        )
    n = lax.axis_size(axis_name)
    idx = lax.axis_index(axis_name)
    perm = ring_perm(n)
    chunks = x.reshape((n, x.shape[0] // n) + x.shape[1:])
    csize = chunks[0].size
    if csize % block:
        raise ValueError(
            f"ring_allreduce_quantized: chunk size {csize} not divisible by "
            f"block {block} (pad the payload or pick a divisor block)"
        )

    def rs_body(s, carry):
        """Quantize the running partial sum, hop it, fold in my chunk."""
        held = carry
        recv_q, recv_s = lax.ppermute(
            _quantize_i8(held.reshape(-1), block, planes), axis_name, perm)
        mine = lax.dynamic_index_in_dim(chunks, (idx - 2 - s) % n,
                                        keepdims=False)
        return _dequantize_i8(recv_q, recv_s).reshape(mine.shape) + mine

    init = lax.dynamic_index_in_dim(chunks, (idx - 1) % n, keepdims=False)
    owned = lax.fori_loop(0, n - 1, rs_body, init)

    # Allgather: ONE quantization per owner; the int8 payload is forwarded
    # verbatim so hops add no further error.  Write-then-hop for n steps —
    # the owner's own chunk goes through the same in-loop decode as every
    # received chunk, which is what makes the output bitwise identical
    # across ranks (an out-of-loop owner decode is a differently-fused
    # code path that may round differently; ADVICE r4).  Costs one
    # payload-rotating hop beyond the minimal n-1.
    q0, s0 = _quantize_i8(owned.reshape(-1), block, planes)
    # The zeros carry must enter the loop already marked varying over the
    # mesh axis (each rank fills it with different chunks) or the loop
    # body's first update changes its vma type and tracing rejects it.
    out = lax.pcast(jnp.zeros((n, csize), jnp.float32),
                    (axis_name,), to="varying")

    def ag_body(s, carry):
        out, q, sc = carry
        out = lax.dynamic_update_index_in_dim(
            out, _dequantize_i8(q, sc), (idx - s) % n, 0)
        q, sc = lax.ppermute((q, sc), axis_name, perm)
        return out, q, sc

    out, _, _ = lax.fori_loop(0, n, ag_body, (out, q0, s0))
    return out.reshape(x.shape)


def fused_allreduce(tree: Any, axis_name: str, op: int = SUM) -> Any:
    """Allreduce a whole pytree as ONE collective per dtype group.

    The in-graph LazyAllreduce: leaves are raveled, concatenated by dtype,
    reduced with a single psum/pmax/pmin, and split back — guaranteeing one
    fused XLA collective where the reference fuses small reductions lazily
    (lazy_allreduce.cc / north-star LazyAllreduce).
    """
    leaves, treedef = jax.tree_util.tree_flatten(tree)
    groups: dict[Any, list[int]] = {}
    for i, leaf in enumerate(leaves):
        groups.setdefault(jnp.asarray(leaf).dtype, []).append(i)
    out_leaves: list[Any] = [None] * len(leaves)
    for dtype, idxs in groups.items():
        flats = [jnp.ravel(leaves[i]) for i in idxs]
        sizes = [f.size for f in flats]
        fused = allreduce(jnp.concatenate(flats), axis_name, op)
        offset = 0
        for i, size in zip(idxs, sizes):
            out_leaves[i] = lax.dynamic_slice_in_dim(fused, offset, size).reshape(
                jnp.shape(leaves[i])
            )
            offset += size
    return jax.tree_util.tree_unflatten(treedef, out_leaves)
