"""Device-mesh construction with TPU topology awareness.

The reference's tracker assigns each worker a position in a reduction tree
and a ring laid over TCP links (ReConnectLinks,
/root/reference/src/allreduce_base.cc:263-438).  On TPU the equivalent is
laying the mesh ring along ICI neighbors: we read each device's torus
coordinates and snake through the torus so that consecutive mesh positions
are physical neighbors, which turns every ``ppermute`` ring shift into a
single-hop ICI transfer.
"""

from __future__ import annotations

from typing import Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec


def snake_order(devices: Sequence) -> list:
    """Order devices so consecutive entries are torus neighbors.

    Devices with ``coords`` (TPU) are sorted boustrophedon: even rows
    left-to-right, odd rows right-to-left, recursively over the outer
    dimensions — a Hamiltonian path on a grid, so each hop is one ICI link.
    Devices without coords (CPU/virtual) keep id order.
    """
    devs = list(devices)
    if not devs or getattr(devs[0], "coords", None) is None:
        return sorted(devs, key=lambda d: d.id)

    def key(d):
        # coords are (x, y, z); snake along x within y rows, along y within
        # z planes.
        x, y, z = (list(d.coords) + [0, 0, 0])[:3]
        sx = x if (y + z) % 2 == 0 else -x
        sy = y if z % 2 == 0 else -y
        return (z, sy, sx)

    return sorted(devs, key=key)


def create_mesh(
    axis_names: Sequence[str] = ("dp",),
    shape: Sequence[int] | None = None,
    devices: Sequence | None = None,
) -> Mesh:
    """Build a Mesh over ``devices`` (default: all), snake-ordered for ICI.

    ``shape`` defaults to all devices on the first axis and 1 on the rest.
    """
    devs = snake_order(devices if devices is not None else jax.devices())
    if shape is None:
        shape = [len(devs)] + [1] * (len(axis_names) - 1)
    shape = tuple(shape)
    n = int(np.prod(shape))
    if n > len(devs):
        raise ValueError(f"mesh shape {shape} needs {n} devices, have {len(devs)}")
    grid = np.array(devs[:n], dtype=object).reshape(shape)
    return Mesh(grid, tuple(axis_names))


def ring_perm(n: int, shift: int = 1) -> list[tuple[int, int]]:
    """ppermute permutation sending mesh position i to i+shift (mod n)."""
    return [(i, (i + shift) % n) for i in range(n)]


def resize_ring(n_old: int, n_new: int, shift: int = 1) -> dict:
    """Ring-topology rebuild for an elastic resize (doc/elasticity.md):
    the new ppermute permutation for ``n_new`` mesh positions plus the
    link delta against the ``n_old`` ring — the links a shrink/grow
    actually has to (re-)establish; every other hop persists.  The delta
    is what ``XlaEngine.rebuild_mesh`` consumers and the elastic benches
    report as resize cost."""
    if n_old < 1 or n_new < 1:
        raise ValueError(f"ring sizes must be >= 1, got {n_old}->{n_new}")
    old = set(ring_perm(n_old, shift))
    new = ring_perm(n_new, shift)
    return {"perm": new,
            "added": sorted(set(new) - old),
            "removed": sorted(old - set(new))}


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, PartitionSpec())


def sharded_along(mesh: Mesh, axis_name: str, ndim: int = 1, dim: int = 0) -> NamedSharding:
    """NamedSharding partitioning array dimension ``dim`` over ``axis_name``."""
    spec = [None] * ndim
    spec[dim] = axis_name
    return NamedSharding(mesh, PartitionSpec(*spec))
