"""Ring attention — sequence/context parallelism over the ICI ring.

The reference predates LLMs but its ring machinery (slice-addressed ring
allgather + generic ring streaming, /root/reference/src/allreduce_base.cc:779-843
and allreduce_robust.cc:1529-1587) is exactly the communication skeleton of
ring attention.  Here that skeleton is first-class: the sequence is sharded
over a mesh axis, each device owns one block of Q/K/V, and K/V blocks rotate
around the ring with ``ppermute`` while a numerically stable online softmax
accumulates — so arbitrarily long contexts run with per-device memory
O(seq/n) and every hop is one ICI neighbor transfer overlapping compute.

Shapes are per-device blocks: q, k, v are ``[block, heads, dim]``; the
global sequence is ``n_devices * block`` laid out so mesh position i holds
block i.  Run inside ``shard_map``.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from rabit_tpu.parallel.mesh import ring_perm

_NEG_INF = -1e30


def _block_attend(q, k, v, scale, q_pos, k_pos, causal):
    """Scores of q block against one k/v block with optional causal mask.
    Returns (unnormalized out, row max, row sumexp)."""
    s = jnp.einsum("qhd,khd->hqk", q, k) * scale
    if causal:
        mask = k_pos[None, None, :] <= q_pos[None, :, None]
        s = jnp.where(mask, s, _NEG_INF)
    m = jnp.max(s, axis=-1)                      # [h, q]
    p = jnp.exp(s - m[..., None])                # [h, q, k]
    p = jnp.where(m[..., None] <= _NEG_INF / 2, 0.0, p)
    o = jnp.einsum("hqk,khd->qhd", p, v)         # [q, h, d]
    l = jnp.sum(p, axis=-1)                      # [h, q]
    return o, m, l


def ring_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    axis_name: str,
    causal: bool = False,
) -> jax.Array:
    """Blockwise ring attention over sequence shards.

    Each of the n mesh positions holds contiguous sequence block i; K/V
    rotate n times around the ring; the online-softmax accumulator merges
    each visiting block.  Output is this device's attention block
    ``[block, heads, dim]``.
    """
    n = lax.axis_size(axis_name)
    idx = lax.axis_index(axis_name)
    perm = ring_perm(n)
    block, heads, dim = q.shape
    scale = 1.0 / (dim ** 0.5)
    q_pos = idx * block + jnp.arange(block)

    def merge(carry, kb, vb, s):
        o, m, l = carry
        # The k/v block in hand after s hops originated s positions back.
        src = (idx - s) % n
        k_pos = src * block + jnp.arange(block)
        bo, bm, bl = _block_attend(q, kb, vb, scale, q_pos, k_pos, causal)
        m_new = jnp.maximum(m, bm)
        alpha = jnp.exp(m - m_new)               # rescale old accumulator
        beta = jnp.exp(bm - m_new)               # rescale new block
        alpha = jnp.where(m <= _NEG_INF / 2, 0.0, alpha)
        beta = jnp.where(bm <= _NEG_INF / 2, 0.0, beta)
        o = o * alpha.T[..., None] + bo * beta.T[..., None]
        l = l * alpha + bl * beta
        return o, m_new, l

    def step(carry, s):
        o, m, l, kb, vb = carry
        # Rotate K/V to the ring successor — one ICI hop, overlapped by XLA
        # with this block's compute — then fold the arriving block in.
        kb, vb = lax.ppermute((kb, vb), axis_name, perm)
        o, m, l = merge((o, m, l), kb, vb, s)
        return (o, m, l, kb, vb), None

    o0 = jnp.zeros_like(q, dtype=jnp.float32)  # inherits q's vma
    _vary = lambda x: lax.pcast(x, axis_name, to="varying")
    m0 = _vary(jnp.full((heads, block), _NEG_INF, dtype=jnp.float32))
    l0 = _vary(jnp.zeros((heads, block), dtype=jnp.float32))
    # Fold the local block first, then n-1 rotate-and-fold steps.
    kf, vf = k.astype(jnp.float32), v.astype(jnp.float32)
    o, m, l = merge((o0, m0, l0), kf, vf, 0)
    (o, m, l, _, _), _ = lax.scan(step, (o, m, l, kf, vf), jnp.arange(1, n))
    l = jnp.where(l == 0.0, 1.0, l)
    return (o / l.T[..., None]).astype(q.dtype)


def ulysses_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    axis_name: str,
    causal: bool = False,
) -> jax.Array:
    """All-to-all sequence parallelism (DeepSpeed-Ulysses style) — the
    other first-class long-context mechanism beside ring_attention.

    One ``all_to_all`` reshards the seq-sharded q/k/v to HEAD-sharded
    (each device holds the full sequence for heads/n heads), full
    attention runs locally per head slice, and a second ``all_to_all``
    reshards back.  Two all-to-alls total vs the ring's n ppermute hops:
    cheaper when heads >= devices and the full-sequence score block fits
    memory; ring_attention wins for extreme contexts (O(seq/n) memory).

    Per-device shapes ``[block, heads, dim]`` with ``heads % n == 0``;
    run inside ``shard_map`` over ``axis_name``.
    """
    n = lax.axis_size(axis_name)
    block, heads, dim = q.shape
    if heads % n != 0:
        raise ValueError(
            f"ulysses_attention needs heads ({heads}) divisible by the "
            f"'{axis_name}' axis size ({n}); use ring_attention otherwise"
        )
    to_heads = lambda x: lax.all_to_all(
        x, axis_name, split_axis=1, concat_axis=0, tiled=True
    )  # [block, h, d] -> [n*block, h/n, d]
    # f32 scores/softmax like ring_attention's accumulators: both
    # long-context mechanisms must give the same-quality answer for
    # low-precision inputs.
    o = reference_attention(
        to_heads(q).astype(jnp.float32),
        to_heads(k).astype(jnp.float32),
        to_heads(v).astype(jnp.float32),
        causal=causal,
    ).astype(q.dtype)
    return lax.all_to_all(o, axis_name, split_axis=0, concat_axis=1, tiled=True)


def reference_attention(q, k, v, causal: bool = False) -> jax.Array:
    """Unsharded full attention, ``[seq, heads, dim]``: the test oracle for
    ring_attention AND the local per-head-slice compute core of
    ulysses_attention (which feeds it f32 inputs) — behavior changes here
    change production output."""
    seq, heads, dim = q.shape
    s = jnp.einsum("qhd,khd->hqk", q, k) / (dim ** 0.5)
    if causal:
        mask = jnp.arange(seq)[None, :] <= jnp.arange(seq)[:, None]
        s = jnp.where(mask[None], s, _NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("hqk,khd->qhd", p, v).astype(q.dtype)
