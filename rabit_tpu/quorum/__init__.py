"""rabit_tpu.quorum — straggler-tolerant K-of-N partial allreduce
(ISSUE 8 tentpole; doc/partial_allreduce.md).

rabit's lockstep collectives make every round as slow as the slowest
worker; PR 3's straggler analytics measured exactly that.  Quorum mode
spends the measurement: a collective round completes once **K of N**
contributions have folded, the stragglers' late blocks land as
**correction terms** at the next round boundary after delivery, and a
per-round **exclusion record** agreed through the tracker keeps every
rank's fold — and any replay after recovery — bitwise identical.

Three pieces:

* **policy** — the ``rabit_quorum`` spec math (fraction or count -> K
  per world size), pure and elastic-aware;
* **table** — the tracker-side ledger: decide-once exclusion records,
  the outstanding-correction ledger (dropped with evidence at epoch
  boundaries), late-delivery events, and exclusion streaks feeding the
  PR 7 degraded-link avoid-set machinery;
* the **executor** lives in :mod:`rabit_tpu.elastic.client`: tagged
  block frames flooding a skip-augmented ring (a successor past the
  quorum deadline dials around its silent predecessor — MAGIC_SKIP —
  and the upstream rank tees the flow past the straggler), one
  ``CMD_QUORUM`` agreement RPC per round, rank-order folds.

The engines (native/xla) keep their exact collectives: quorum is a
control-plane contract between the tracker and schedule-aware
executors, exactly like the PR 7 planned rings.  ``rabit_quorum=""``
(default) or ``"1.0"`` never excludes — results are bitwise identical
to the legacy exact path.
"""

from rabit_tpu.quorum.policy import (  # noqa: F401 (re-exports)
    parse_spec,
    quorum_count,
)
from rabit_tpu.quorum.table import QuorumTable  # noqa: F401


def resolve(cfg) -> dict:
    """Resolve the quorum config keys (doc/parameters.md, "Partial
    (quorum) allreduce") into the tracker/worker-facing knobs.  Raises
    ValueError on a malformed ``rabit_quorum`` — a typo'd quorum must
    not silently run exact."""
    spec = (cfg.get("rabit_quorum", "") or "").strip()
    if spec:
        parse_spec(spec)
    return {
        "quorum": spec,
        "wait_sec": float(
            cfg.get("rabit_quorum_wait_sec", "0.35") or "0.35"),
        "flag_after": cfg.get_int("rabit_quorum_flag_after", 3),
    }
