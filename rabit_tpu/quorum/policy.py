"""Quorum policy — how many contributions make a round (pure math).

The ``rabit_quorum`` spec is either a fraction in ``(0, 1]`` (``"0.75"``
means three quarters of the current world, ``"1.0"`` means everyone —
the quorum machinery runs but never excludes) or an integer count
(``"6"`` means six ranks, clamped into ``[1, world]``).  An integer
literal is always a COUNT: ``"1"`` is a one-rank quorum, ``"1.0"`` is
all of them.  The empty spec disables quorum mode entirely — the legacy
exact collective, byte for byte.

Fractions resolve against the CURRENT world size, so an elastic shrink
or grow re-derives K at every wave without re-configuration.
"""

from __future__ import annotations

import math


def parse_spec(spec: str) -> tuple[str, float]:
    """Validate a ``rabit_quorum`` spec; returns ("frac", f) or
    ("count", n).  Raises ValueError on anything else — a typo'd quorum
    must fail loudly at init, not silently run exact (or worse, K=1)."""
    spec = (spec or "").strip()
    if not spec:
        raise ValueError("empty quorum spec (use '' to disable quorum mode)")
    try:
        n = int(spec)
    except ValueError:
        pass
    else:
        if n < 1:
            raise ValueError(f"rabit_quorum count must be >= 1, got {n}")
        return ("count", float(n))
    try:
        f = float(spec)
    except ValueError:
        raise ValueError(f"rabit_quorum={spec!r} is neither a count nor a "
                         f"fraction")
    if not 0.0 < f <= 1.0:
        raise ValueError(f"rabit_quorum fraction must be in (0, 1], "
                         f"got {f}")
    return ("frac", f)


def quorum_count(world: int, spec: str) -> int:
    """K for one world size: the number of contributions that completes a
    round.  Empty spec (quorum off) and ``"1.0"`` both resolve to the
    full world; counts clamp into ``[1, world]``."""
    world = int(world)
    if world < 1:
        raise ValueError(f"world must be >= 1, got {world}")
    spec = (spec or "").strip()
    if not spec:
        return world
    kind, value = parse_spec(spec)
    if kind == "count":
        return max(1, min(world, int(value)))
    return max(1, min(world, math.ceil(value * world)))
