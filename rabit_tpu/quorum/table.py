"""Tracker-side quorum agreement — the per-round exclusion-record ledger.

Every quorum round needs ONE answer to "which K contributions does this
round fold?", identical on every rank, or the folds diverge bitwise.
The tracker is the natural single decision point (it already owns rank
assignment and the membership epoch line): workers report what they hold
(``CMD_QUORUM``) and the FIRST report meeting the K-of-N quorum freezes
the round's record ``(epoch, version) -> (excluded_ranks,
corrections)``.  Every later report — including the excluded straggler's
own, arriving rounds late — is answered with the same frozen record, so
replay after recovery re-reads the same exclusions.

The table is pure bookkeeping (no sockets, no clock): the tracker calls
it under its own lock and emits the returned event dicts into the
telemetry timeline.  Three ledgers ride along:

* **outstanding** — ``(src_version, rank) -> world`` contributions a
  record excluded that have not yet folded as corrections; a later
  record's deciding report that holds them folds them
  (``correction_folded``), an epoch change drops them
  (``correction_dropped`` — corrections do not survive a membership
  wave: a shrunk rank is excluded permanently, not buffered);
* **late evidence** — the first report that *holds* an outstanding late
  block emits ``contribution_late`` (the straggler delivered);
* **streaks** — consecutive exclusions per rank; a rank late
  ``flag_after`` rounds in a row is handed back to the tracker so its
  incoming planned-ring link feeds the SAME avoid-set machinery as a
  slow link (doc/scheduling.md repair) and the next wave's plan moves
  the straggler off the ring hot path.
"""

from __future__ import annotations

from rabit_tpu.quorum.policy import parse_spec, quorum_count


class QuorumTable:
    """One job's quorum ledger (see module docstring).  NOT thread-safe:
    the tracker serializes access under its own lock."""

    def __init__(self, spec: str, flag_after: int = 3):
        parse_spec(spec)  # fail loudly at construction on a typo'd spec
        self.spec = str(spec)
        self.flag_after = max(int(flag_after), 0)
        #: (epoch, version) -> frozen record dict (the CMD_QUORUM reply)
        self._records: dict[tuple[int, int], dict] = {}
        #: (src_version, rank) -> world size the exclusion happened at
        self._outstanding: dict[tuple[int, int], int] = {}
        self._late_seen: set[tuple[int, int]] = set()
        self._streak: dict[int, int] = {}

    # -- reporting ---------------------------------------------------------

    def report(self, epoch: int, version: int, world: int,
               have: list[int], held: list) -> tuple[dict, list[dict],
                                                     list[int]]:
        """Fold one worker report in; returns ``(reply, events,
        flag_ranks)``.  ``reply`` is the frozen record (or an undecided
        placeholder), ``events`` are telemetry event dicts (sans ``ts``),
        ``flag_ranks`` are ranks whose exclusion streak just hit
        ``flag_after`` (feed them to the schedule repair avoid set)."""
        events: list[dict] = []
        flags: list[int] = []
        held_t = sorted({(int(sv), int(r)) for sv, r in held})
        for t in held_t:
            if t in self._outstanding and t not in self._late_seen:
                self._late_seen.add(t)
                events.append({"kind": "contribution_late", "epoch": epoch,
                               "version": version, "src_version": t[0],
                               "rank": t[1]})
        key = (int(epoch), int(version))
        rec = self._records.get(key)
        if rec is None:
            have_set = {int(r) for r in have if 0 <= int(r) < world}
            k = quorum_count(world, self.spec)
            if len(have_set) < k:
                return ({"decided": False, "k": k, "version": version},
                        events, flags)
            held_ok = [t for t in held_t if t in self._outstanding]
            excluded = sorted(set(range(world)) - have_set)
            rec = {"decided": True, "epoch": int(epoch),
                   "version": int(version), "k": k,
                   "excluded": excluded,
                   "corrections": [list(t) for t in held_ok]}
            self._records[key] = rec
            for t in held_ok:
                del self._outstanding[t]
            for r in excluded:
                self._outstanding[(int(version), r)] = int(world)
            if excluded:
                events.append({"kind": "quorum_met", "epoch": epoch,
                               "version": version, "k": k, "world": world,
                               "n_have": len(have_set),
                               "excluded": excluded})
            for sv, r in held_ok:
                events.append({"kind": "correction_folded", "epoch": epoch,
                               "version": version, "src_version": sv,
                               "rank": r})
            for r in range(world):
                if r in rec["excluded"]:
                    streak = self._streak.get(r, 0) + 1
                    self._streak[r] = streak
                    if self.flag_after and streak == self.flag_after:
                        flags.append(r)
                else:
                    self._streak[r] = 0
        return rec, events, flags

    def has_record(self, epoch: int, version: int) -> bool:
        """True when the round's exclusion record is already frozen —
        the tracker journals a freeze exactly once (doc/ha.md)."""
        return (int(epoch), int(version)) in self._records

    def seed(self, seed: dict) -> None:
        """Restore the ledgers from a replayed control-plane state
        (``rabit_tpu.ha.ControlState.quorum_seed``): a promoted tracker
        must answer every already-decided round with the SAME frozen
        record, or folds diverge bitwise across the failover."""
        self._records = {(int(e), int(v)): dict(r)
                         for (e, v), r in seed.get("records", {}).items()}
        self._outstanding = {(int(sv), int(r)): int(w) for (sv, r), w in
                             seed.get("outstanding", {}).items()}
        self._late_seen = {(int(sv), int(r))
                           for sv, r in seed.get("late_seen", ())}
        self._streak = {int(r): int(n)
                        for r, n in seed.get("streak", {}).items()}

    # -- membership boundaries ---------------------------------------------

    def epoch_changed(self, epoch: int) -> list[tuple[int, int, int]]:
        """A membership wave committed ``epoch``: corrections do not
        survive the boundary (ranks renumber, shards re-cut, a shrunk
        rank is gone for good), so the outstanding ledger settles by
        dropping.  Returns ``[(src_version, rank, world), ...]`` for the
        tracker's ``correction_dropped`` evidence; records of older
        epochs are pruned so a redone round gets a fresh decision."""
        dropped = sorted((sv, r, w)
                         for (sv, r), w in self._outstanding.items())
        self._outstanding.clear()
        self._late_seen.clear()
        self._streak.clear()
        self._records = {k: r for k, r in self._records.items()
                         if k[0] >= int(epoch)}
        return dropped

    # -- introspection -----------------------------------------------------

    def outstanding(self) -> list[tuple[int, int, int]]:
        """Undelivered exclusions as ``(src_version, rank, world)`` —
        telemetry surfaces these so accounting (chaos closed-form
        adjustment, operators) can quantify the missing mass exactly."""
        return sorted((sv, r, w) for (sv, r), w in self._outstanding.items())
