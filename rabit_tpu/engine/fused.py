"""Fused in-XLA quantized collectives — one jitted encode→ppermute→decode
graph over the process mesh (ISSUE 11 tentpole; doc/compression.md, "Fused
in-XLA path").

PR 5's codecs cut allreduce wire bytes up to 4.79x, but every path except
``XlaEngine.allreduce_compressed`` still round-trips the quantize →
collective → dequantize pipeline through the host, and even that override
leans on XLA's opaque AllReduce — the PR 7 planned ring order never reaches
the device.  This module lowers the whole pipeline into ONE jitted graph
(EQuARX-style fusion, PAPERS.md) expressed as a chunked ``lax.ppermute``
ring whose source/dest table IS the planned schedule's ring order — Swing
serpentine rings and degraded-link repaired rings included.

Graph shape (one ``shard_map`` body, identical on every rank):

1. **encode** — the local f32 shard quantizes on-device with the codec's
   in-graph path (``codec.jax_encode``; bit-identical planes to the numpy
   reference, asserted by tests/test_compress.py), after zero-padding to
   ``world * slice_blocks`` scale blocks so every ring position owns an
   equal block range;
2. **reduce-scatter phase** — ``W-1`` ppermute hops along the planned ring.
   Each hop moves QUANTIZED plane chunks with their per-block f32 scales
   riding alongside (a chunk is the block-range slice of every wire
   segment), pipelined so hop ``s`` carries the ``W-s`` chunks still in
   transit: position ``p`` receives its own slice's chunk from the origin
   ``s`` positions back and forwards the rest.  Per-rank wire cost is
   ``(W-1)/2`` encoded planes — the hops carry int8/bf16, never f32;
3. **decode-fold** — the slice owner dequantizes all ``W`` buffered chunks
   in-register and folds them **in rank order** (never arrival/ring order),
   so the fold is the exact closed form of
   :func:`rabit_tpu.compress.transport.reference_allreduce` and the result
   is bitwise identical for every schedule, replay, and world layout — the
   host transport stays the reference oracle and the fallback for non-XLA
   engines;
4. **allgather phase** — ``W-1`` ppermute hops circulate the folded f32
   slices; every rank assembles the identical full result.

Determinism note: the decoded planes cross an identity ``ppermute`` before
the fold.  XLA's CPU emitter otherwise contracts the dequant multiply into
the fold add (an FMA skips the intermediate rounding the host's numpy fold
performs), and a collective result is the one producer boundary the fuser
never rematerializes across — measured: without the fence ~27% of summed
elements drift in the last bit; with it every codec/op/schedule/world combo
is bit-equal to the host fold.  ``lax.optimization_barrier`` does NOT stop
the contraction on this backend.

Chunking: ``rabit_fused_chunk_kib`` splits each hop's payload into at most
that many KiB per ``ppermute`` issue, so XLA can overlap a chunk's transfer
with the next chunk's packing (the "Efficient AllReduce with Stragglers"
chunked-ring shape).  Parity is chunk-size independent (bytes are split,
never re-encoded).
"""

from __future__ import annotations

from typing import Callable

import numpy as np

from rabit_tpu.compress.codecs import BLOCK, Codec, _BlockI8, get_codec
from rabit_tpu.engine.base import MAX, MIN, SUM

#: Default hop sub-chunk size (KiB) of the ppermute pipeline
#: (``rabit_fused_chunk_kib``; 0 disables splitting).
DEFAULT_CHUNK_KIB = 256

#: Ops the fused fold covers (BITOR payloads are never codec-compressed).
FUSED_OPS = (SUM, MAX, MIN)


def chunk_bytes_from_config(config) -> int:
    """Resolve ``rabit_fused_chunk_kib`` into bytes (doc/parameters.md)."""
    return max(config.get_int("rabit_fused_chunk_kib", DEFAULT_CHUNK_KIB),
               0) * 1024


def fused_mode(config) -> bool:
    """Resolve ``rabit_fused_allreduce`` for an XLA engine: ``auto``
    (default) means ON — the key exists so deployments can force the host
    transport (``0``) for debugging or pin the fused path explicitly
    (``1``).  Non-XLA engines never consult it (off elsewhere: the host
    transport is their only compressed path)."""
    mode = (config.get("rabit_fused_allreduce", "auto") or "auto")
    mode = mode.strip().lower()
    if mode == "auto":
        return True
    return mode not in ("0", "false", "no", "off", "")


def segment_widths(codec: Codec) -> tuple[int, ...]:
    """Per-BLOCK byte width of each contiguous segment of the codec's wire
    layout (plane-major, scales last — doc/compression.md).  Chunking by
    scale-block ranges keeps every chunk a self-contained mini-wire: the
    per-block scales ride alongside their payload blocks."""
    if isinstance(codec, _BlockI8):
        return tuple([BLOCK] * codec.planes + [4])
    widths = {"identity": (4 * BLOCK,), "bf16": (2 * BLOCK,),
              "bf16x2": (2 * BLOCK, 2 * BLOCK)}.get(codec.name)
    if widths is None:
        raise ValueError(
            f"codec {codec.name!r} has no fused wire layout (host-only?)")
    return widths


def plan_ring_order(world: int, config) -> tuple[int, ...]:
    """The ppermute source/dest table: the PR 7 planner's ring ORDER for
    this world under the job's ``rabit_schedule``/``rabit_sched_mesh``
    config.  The planner is a pure function of its inputs, so every
    process derives the identical table with no tracker round-trip."""
    from rabit_tpu import sched

    knobs = sched.resolve(config)
    mesh = sched.mesh_for_world(world, knobs["mesh"])
    return sched.plan(world, knobs["schedule"], mesh).ring_order


def _fold_fn(op: int):
    import jax.numpy as jnp

    if op == SUM:
        return jnp.add
    if op == MAX:
        return jnp.maximum
    if op == MIN:
        return jnp.minimum
    raise ValueError(f"unsupported fused op {op} (want one of {FUSED_OPS})")


def build_fused_allreduce(mesh, ring_order, op: int, codec: Codec, n: int,
                          chunk_bytes: int = DEFAULT_CHUNK_KIB * 1024
                          ) -> Callable:
    """Compile the fused graph for one (mesh, ring, op, codec, n) shape.

    ``mesh`` is a 1-D jax Mesh with one device per rank, device ``i`` being
    rank ``i``; ``ring_order[i]`` is the rank at ring position ``i`` (a
    :class:`rabit_tpu.sched.Plan` ``ring_order``, or any permutation).
    Returns a jitted callable taking a ``[world, n]`` f32 global array
    sharded one row per device and returning the same shape with EVERY row
    the identical rank-order fold — bit-equal to
    ``reference_allreduce(rows, op, codec)``.
    """
    import jax
    import jax.numpy as jnp
    from jax import lax
    from jax.experimental.shard_map import shard_map
    from jax.sharding import PartitionSpec as P

    axis = mesh.axis_names[0]
    world = len(ring_order)
    order = tuple(int(r) for r in ring_order)
    if sorted(order) != list(range(world)):
        raise ValueError(f"ring_order {order!r} is not a permutation of "
                         f"0..{world - 1}")
    if mesh.devices.size != world:
        raise ValueError(f"mesh has {mesh.devices.size} devices for world "
                         f"{world}")
    if n < 1:
        raise ValueError(f"fused allreduce needs n >= 1, got {n}")
    fold = _fold_fn(op)

    # Equal-slice geometry: pad to world * slice_blocks scale blocks so the
    # ring moves identically-shaped chunks.  Zero padding is block-local in
    # every codec, so the first n decoded elements are unaffected.
    nb = -(-n // BLOCK)
    slice_blocks = -(-nb // world)
    nb_pad = slice_blocks * world
    n_pad = nb_pad * BLOCK
    widths = segment_widths(codec)
    seg_offs = np.cumsum([0] + [w * nb_pad for w in widths])[:-1]
    chunk_elems = slice_blocks * BLOCK
    cb = sum(widths) * slice_blocks  # chunk wire bytes (planes + scales)

    pos_of = np.zeros(world, np.int32)
    for i, r in enumerate(order):
        pos_of[r] = i
    rank_at = np.array(order, np.int32)
    perm = [(order[i], order[(i + 1) % world]) for i in range(world)]
    ident_perm = [(i, i) for i in range(world)]

    def pp(x):
        """One planned-ring hop, split into <= chunk_bytes ppermutes so
        transfer and packing pipeline (the rabit_fused_chunk_kib knob)."""
        total = x.size * x.dtype.itemsize
        if chunk_bytes <= 0 or total <= chunk_bytes:
            return lax.ppermute(x, axis, perm)
        nsplit = min(-(-total // chunk_bytes), x.shape[-1])
        parts = jnp.array_split(x, nsplit, axis=x.ndim - 1)
        return jnp.concatenate([lax.ppermute(part, axis, perm)
                                for part in parts], axis=x.ndim - 1)

    def extract(wire, p: int):
        """Chunk for ring position ``p``: the block-range slice of every
        wire segment, concatenated — a self-contained mini-wire for
        ``chunk_elems`` elements (scales ride with their blocks)."""
        parts = [lax.slice_in_dim(wire, int(o) + p * slice_blocks * w,
                                  int(o) + (p + 1) * slice_blocks * w)
                 for o, w in zip(seg_offs, widths)]
        return jnp.concatenate(parts) if len(parts) > 1 else parts[0]

    def body(xrow):
        x = xrow.reshape(-1)
        if n_pad != n:
            x = jnp.pad(x, (0, n_pad - n))
        wire = codec.jax_encode(x)  # quantized planes, on device
        me = lax.axis_index(axis)
        my_pos = jnp.asarray(pos_of)[me]
        chunks = jnp.stack([extract(wire, p) for p in range(world)])
        # Reduce-scatter phase: buffer every origin's chunk for MY slice,
        # indexed by origin RANK so the fold below runs in rank order.
        buf = jnp.zeros((world, cb), jnp.uint8)
        buf = lax.dynamic_update_index_in_dim(
            buf, jnp.take(chunks, my_pos, axis=0), me, 0)
        if world > 1:
            # Hop pipeline: I inject all my foreign chunks ordered by ring
            # distance; each received list's head is addressed to me (from
            # the origin s positions back) and the tail forwards onward.
            send = jnp.stack([jnp.take(chunks, (my_pos + d) % world, axis=0)
                              for d in range(1, world)])
            for s in range(1, world):
                recv = pp(send)
                origin = jnp.asarray(rank_at)[(my_pos - s) % world]
                buf = lax.dynamic_update_index_in_dim(buf, recv[0], origin, 0)
                send = recv[1:]
        dec = jax.vmap(lambda row: codec.jax_decode(row, chunk_elems))(buf)
        if world > 1:
            # Rounding fence (module docstring): without it XLA contracts
            # the dequant multiply into the fold add and the low bits drift
            # off the host oracle.
            dec = lax.ppermute(dec, axis, ident_perm)
            acc = lax.fori_loop(
                1, world,
                lambda r, a: fold(a, lax.dynamic_index_in_dim(
                    dec, r, 0, keepdims=False)),
                dec[0])
        else:
            acc = dec[0]
        # Allgather phase: circulate the folded f32 slices; slice of ring
        # position p lands at block range [p*slice_blocks, (p+1)*...).
        out = jnp.zeros((world, chunk_elems), jnp.float32)
        out = lax.dynamic_update_index_in_dim(out, acc, my_pos, 0)
        cur = acc
        for s in range(1, world):
            cur = pp(cur)
            out = lax.dynamic_update_index_in_dim(
                out, cur, (my_pos - s) % world, 0)
        return out.reshape(-1)[:n][None]

    mapped = shard_map(body, mesh=mesh, in_specs=P(axis, None),
                       out_specs=P(axis, None), check_rep=False)
    return jax.jit(mapped)


# -- single-process harness (tests, benches) ---------------------------------

def local_mesh(world: int):
    """A 1-D mesh over the first ``world`` local devices — the CPU-mesh
    stand-in for the engine's one-device-per-process mesh (tests pin an
    8-device virtual CPU platform; tests/conftest.py)."""
    import jax
    from jax.sharding import Mesh

    devs = jax.devices()
    if len(devs) < world:
        raise RuntimeError(
            f"local fused mesh needs {world} devices, have {len(devs)}")
    return Mesh(np.array(devs[:world]), ("r",))


def place_contributions(mesh, contribs):
    """Stack per-rank f32 contributions into the fused graph's input: a
    ``[world, n]`` global array, one row per device."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    stacked = np.stack([np.ascontiguousarray(c, np.float32).reshape(-1)
                        for c in contribs])
    return jax.device_put(
        stacked, NamedSharding(mesh, P(mesh.axis_names[0], None)))


def run_local(contribs, op: int, codec, ring_order=None,
              chunk_bytes: int = DEFAULT_CHUNK_KIB * 1024) -> np.ndarray:
    """Build and run the fused graph over local devices, one rank per
    device; asserts the output is replicated bit-identically across ranks
    and returns it.  The parity gate's driver
    (tests/test_fused.py: fused ≡ ``reference_allreduce``)."""
    c = codec if isinstance(codec, Codec) else get_codec(codec)
    world = len(contribs)
    mesh = local_mesh(world)
    order = tuple(ring_order) if ring_order is not None else tuple(
        range(world))
    n = np.ascontiguousarray(contribs[0]).size
    fn = build_fused_allreduce(mesh, order, op, c, n, chunk_bytes)
    out = np.asarray(fn(place_contributions(mesh, contribs)))
    for r in range(1, world):
        if not np.array_equal(out[0], out[r]):
            raise AssertionError(
                f"fused allreduce diverged: rank {r} != rank 0")
    return out[0]
