"""XLA engine — cross-host collectives through JAX.

The engine-level (host numpy) API for multi-host TPU jobs launched with
``jax.distributed``: rank = process index, world = process count, and the
collectives ride XLA's DCN/ICI transport instead of the reference's
hand-rolled TCP loops.  This is the third backend the reference's engine
seam anticipated (engine_mpi.cc:20-101 as the proof the seam is swappable;
BASELINE.json north star).

The reduction itself runs ON DEVICE: each process contributes its array as
one shard of a global array laid out over a one-device-per-process mesh, and
a jitted reduction over the sharded axis with a replicated out-sharding
makes XLA emit the cross-host AllReduce (O(log W) / ring, XLA's choice) —
no allgather-then-host-fold.  Jit caching specializes per (shape, dtype)
automatically; one compiled executable per (op, shape, dtype) is reused for
the life of the process.

In-graph device collectives for SPMD programs live in ``rabit_tpu.parallel``;
this engine is the host-side control surface with the same semantics as the
other backends.
"""

from __future__ import annotations

import os
from typing import Callable

import numpy as np

from rabit_tpu.engine.base import BITOR, MAX, MIN, SUM, Engine, numpy_reduce


class XlaEngine(Engine):
    def __init__(self, config):
        super().__init__(config)
        self._version = 0
        self._global_blob: bytes | None = None
        self._local_blob: bytes | None = None
        self._lazy_thunk: Callable[[], bytes] | None = None
        self._mesh = None
        self._jits: dict[int, Callable] = {}
        # compiled (encode, decode+fold) pairs of the compressed path,
        # per (op, codec, element count)
        self._cjits: dict[tuple, tuple[Callable, Callable]] = {}
        # compiled fused encode->ppermute->decode-fold graphs
        # (engine/fused.py), per (op, codec, element count)
        self._fjits: dict[tuple, Callable] = {}
        self._fused_order: tuple[int, ...] | None = None
        # rabit_fused_allreduce, resolved lazily at the first compressed
        # collective (None = not resolved yet)
        self._fused_on: bool | None = None

    def init(self) -> None:
        import jax

        # Multi-process bootstrap: honour the standard JAX cluster env vars
        # (as exported by tests/test_xla_engine.py or a real multi-host
        # launcher).  Config keys override env so a launcher can pass them
        # as argv k=v pairs.  Must run before any other jax call touches
        # the backend.
        # `or` fallback (not a .get default): the keys are declared in
        # config.DEFAULTS with empty sentinels, so a plain default arg
        # would never fire and the env vars would be shadowed.
        coord = (self.config.get("rabit_xla_coordinator", "")
                 or os.environ.get("JAX_COORDINATOR_ADDRESS", ""))
        nproc = int(
            self.config.get("rabit_xla_num_processes", "")
            or os.environ.get("JAX_NUM_PROCESSES", "0") or "0"
        )
        pid = (self.config.get("rabit_xla_process_id", "")
               or os.environ.get("JAX_PROCESS_ID", ""))
        any_set = bool(coord) or nproc > 0 or pid != ""
        all_set = bool(coord) and nproc > 0 and pid != ""
        if any_set and not all_set:
            # Half-set cluster config must fail loudly: silently skipping
            # initialize would leave this worker at world 1 computing local
            # results while its peers block waiting for it.
            raise RuntimeError(
                "incomplete jax.distributed settings: coordinator="
                f"{coord!r} num_processes={nproc} process_id={pid!r} — set "
                "all of JAX_COORDINATOR_ADDRESS / JAX_NUM_PROCESSES / "
                "JAX_PROCESS_ID (or the rabit_xla_* config keys), or none"
            )
        if all_set and nproc > 1:
            try:
                jax.distributed.initialize(coord, nproc, int(pid))
            except RuntimeError as exc:
                # Only double-initialization (the application bootstrapped
                # jax.distributed itself) is benign — jax 0.9 phrases it
                # "distributed.initialize should only be called once."; a
                # dead coordinator or world mismatch must fail loudly, not
                # degrade to world 1.
                msg = str(exc).lower()
                if "only be called once" not in msg and "already initialized" not in msg:
                    raise
        self._rank = jax.process_index()
        self._world = jax.process_count()

    def shutdown(self) -> None:
        self._mesh = None
        self._jits.clear()
        self._cjits.clear()
        self._fjits.clear()
        self._fused_order = None

    def rebuild_mesh(self) -> None:
        """Adopt a resized world (rabit_tpu.elastic): drop every compiled
        artifact pinned to the old process mesh — the one-device-per-
        process Mesh, the jitted reduce fns, the compressed-path pairs —
        and re-read the process topology, so the next collective lowers
        against the current world.  Invoked through
        ``rabit_tpu.api.rebootstrap``."""
        import jax

        from rabit_tpu.parallel.mesh import resize_ring

        old_world = max(getattr(self, "_world", 1), 1)
        self._mesh = None
        self._jits.clear()
        self._cjits.clear()
        # the fused graphs bake the OLD world's ring order and device set
        # into their ppermute tables — stale after a resize
        self._fjits.clear()
        self._fused_order = None
        self._rank = jax.process_index()
        self._world = jax.process_count()
        delta = resize_ring(old_world, max(self._world, 1))
        self.obs_event("epoch_changed", world=self._world,
                       links_added=len(delta["added"]),
                       links_removed=len(delta["removed"]))

    def get_rank(self) -> int:
        return getattr(self, "_rank", 0)

    def get_world_size(self) -> int:
        return getattr(self, "_world", 1)

    # -- device-side reduction --------------------------------------------

    def _proc_mesh(self):
        """A 1-D mesh with exactly one device per process, ordered by
        process index — the engine's 'one shard per worker' data layout."""
        if self._mesh is None:
            import jax
            from jax.sharding import Mesh

            per_proc: dict[int, object] = {}
            for d in jax.devices():
                if d.process_index not in per_proc or d.id < per_proc[d.process_index].id:
                    per_proc[d.process_index] = d
            devs = [per_proc[p] for p in sorted(per_proc)]
            if len(devs) != self._world:
                raise RuntimeError(
                    f"expected one device per process, got {len(devs)} for "
                    f"world {self._world}"
                )
            self._mesh = Mesh(np.array(devs), ("p",))
        return self._mesh

    def _reduce_fn(self, op: int):
        """Jitted reduce-over-shard-axis with replicated output: XLA lowers
        this to one cross-process AllReduce on the device interconnect."""
        if op not in self._jits:
            import jax
            import jax.numpy as jnp
            from jax.sharding import NamedSharding, PartitionSpec as P

            mesh = self._proc_mesh()

            if op == SUM:
                red = lambda x: jnp.sum(x, axis=0)
            elif op == MAX:
                red = lambda x: jnp.max(x, axis=0)
            elif op == MIN:
                red = lambda x: jnp.min(x, axis=0)
            elif op == BITOR:
                # Cross-process reduce computations are restricted to
                # sum/min/max on some backends (CPU Gloo rejects reduce-or),
                # so OR is lowered to per-bit-plane MAX: expand to bits,
                # max across processes, recombine (disjoint planes sum back
                # exactly) — same trick as parallel/collectives.py's BITOR.
                def red(x):
                    dt = x.dtype
                    nbits = dt.itemsize * 8
                    wide = jnp.uint64 if nbits > 32 else jnp.uint32
                    xu = x.astype(wide)
                    if nbits < 64:
                        xu = xu & np.array((1 << nbits) - 1, wide)
                    shifts = jnp.arange(nbits, dtype=wide)
                    bits = (xu[..., None] >> shifts) & np.array(1, wide)
                    planes = jnp.max(bits, axis=0)
                    return jnp.sum(planes << shifts, axis=-1, dtype=wide).astype(dt)
            else:
                raise ValueError(f"unknown reduction op {op}")
            self._jits[op] = jax.jit(
                red, out_shardings=NamedSharding(mesh, P())
            )
        return self._jits[op]

    def allreduce(self, data, op, prepare_fun=None, cache_key=None):
        if prepare_fun is not None:
            prepare_fun(data)
        if self.get_world_size() == 1:
            return data
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P

        arr = np.ascontiguousarray(data)
        if arr.dtype.itemsize == 8:
            # Under JAX's default 32-bit mode device_put canonicalizes
            # int64/float64 down to 32 bits — silent truncation.  64-bit
            # payloads take a bit-exact host path instead: ship the raw
            # bytes (uint8 survives canonicalization) and fold on host.
            gathered = self.allgather(arr.view(np.uint8).reshape(-1))
            parts = gathered.reshape(self._world, -1).view(arr.dtype)
            acc = np.array(parts[0], copy=True)
            for i in range(1, self._world):
                acc = numpy_reduce(op, acc, parts[i])
            return acc.reshape(arr.shape)
        mesh = self._proc_mesh()
        sharding = NamedSharding(mesh, P("p", *([None] * arr.ndim)))
        local = jax.device_put(arr[None], mesh.devices[self._rank])
        garr = jax.make_array_from_single_device_arrays(
            (self._world,) + arr.shape, sharding, [local]
        )
        out = self._reduce_fn(op)(garr)
        return np.asarray(out.addressable_data(0)).astype(arr.dtype)

    # -- compressed allreduce (in-graph) -----------------------------------

    def _compressed_fns(self, op: int, codec, n: int):
        """Jitted on-device (encode, decode+fold) pair.  The fold takes the
        process-sharded uint8 plane array and reduces the decoded shards
        with a replicated out-sharding, so XLA ships the ENCODED planes
        across DCN/ICI — one fused device collective per call — and every
        rank computes the identical replicated result."""
        key = (op, codec.name, n)
        if key not in self._cjits:
            import jax
            import jax.numpy as jnp
            from jax.sharding import NamedSharding, PartitionSpec as P

            mesh = self._proc_mesh()
            if op == SUM:
                red = lambda p: jnp.sum(p, axis=0)
            elif op == MAX:
                red = lambda p: jnp.max(p, axis=0)
            elif op == MIN:
                red = lambda p: jnp.min(p, axis=0)
            else:  # pragma: no cover — resolve() never routes BITOR here
                raise ValueError(f"unsupported compressed op {op}")

            def fold(g):
                return red(jax.vmap(lambda row: codec.jax_decode(row, n))(g))

            self._cjits[key] = (
                jax.jit(codec.jax_encode),
                jax.jit(fold, out_shardings=NamedSharding(mesh, P())),
            )
        return self._cjits[key]

    def fused_active(self, codec, op) -> bool:
        """True when :meth:`allreduce_compressed` will take the fused
        in-graph ppermute path for this (codec, op) — the obs layer stamps
        ``fused=1`` into the collective identity from this answer."""
        if self._fused_on is None:
            from rabit_tpu.engine.fused import fused_mode

            self._fused_on = fused_mode(self.config)
        return (self._fused_on and self.get_world_size() > 1
                and codec.has_jax and op in (SUM, MAX, MIN))

    def _fused_fn(self, op: int, codec, n: int):
        """Jitted fused encode→ppermute→decode-fold graph over the process
        mesh (engine/fused.py), the ppermute table taken from the PR 7
        planned ring order for this world."""
        key = (op, codec.name, n)
        if key not in self._fjits:
            from rabit_tpu.engine import fused as _fused

            mesh = self._proc_mesh()
            if self._fused_order is None:
                self._fused_order = _fused.plan_ring_order(
                    self._world, self.config)
            self._fjits[key] = _fused.build_fused_allreduce(
                mesh, self._fused_order, op, codec, n,
                chunk_bytes=_fused.chunk_bytes_from_config(self.config))
        return self._fjits[key]

    def allreduce_compressed(self, data, op, codec, prepare_fun=None,
                             cache_key=None):
        """On-device quantized allreduce.  Default (rabit_fused_allreduce
        auto/on): the fully fused path — ONE jitted graph runs encode, a
        chunked ppermute ring in the planned schedule order (reduce-scatter
        + allgather phases, hops carry quantized planes), and the
        rank-order decode-fold, bitwise identical to the host reference
        fold.  rabit_fused_allreduce=0 keeps the pre-fusion shape: jitted
        on-device encode + one XLA-chosen collective over packed planes +
        jitted decode-fold.  Falls back to the numpy host transport for
        solo worlds (no mesh/jit is ever built for a no-op collective),
        host-only codecs, and ops the device fold does not cover."""
        if prepare_fun is not None:
            prepare_fun(data)
        arr = np.ascontiguousarray(data)
        if (self.get_world_size() == 1 or not codec.has_jax
                or arr.dtype != np.float32 or op not in (SUM, MAX, MIN)):
            return super().allreduce_compressed(arr, op, codec,
                                                cache_key=cache_key)
        import jax
        import time as _time

        from rabit_tpu import compress as _compress
        from jax.sharding import NamedSharding, PartitionSpec as P

        n = arr.size
        mesh = self._proc_mesh()
        if self.fused_active(codec, op):
            fn = self._fused_fn(op, codec, n)
            t0 = _time.perf_counter()
            sharding = NamedSharding(mesh, P("p", None))
            local = jax.device_put(arr.reshape(1, -1),
                                   mesh.devices[self._rank])
            garr = jax.make_array_from_single_device_arrays(
                (self._world, n), sharding, [local]
            )
            out = fn(garr)
            result = np.asarray(out.addressable_data(0)).reshape(arr.shape)
            # wire accounting: the ring moves (W-1)/W encoded chunk sets
            # per phase; meter the canonical per-rank encoded size so the
            # codec ratios stay comparable with the host path's meter
            _compress.observe(codec.name, raw=arr.nbytes,
                              wire=codec.wire_len(n),
                              encode_s=_time.perf_counter() - t0,
                              fused=True)
            return result
        encode, fold = self._compressed_fns(op, codec, n)
        t0 = _time.perf_counter()
        packed = encode(arr.reshape(-1))  # on the local device
        wire_len = codec.wire_len(n)
        sharding = NamedSharding(mesh, P("p", None))
        local = jax.device_put(packed[None], mesh.devices[self._rank])
        garr = jax.make_array_from_single_device_arrays(
            (self._world, wire_len), sharding, [local]
        )
        out = fold(garr)
        result = np.asarray(out.addressable_data(0)).reshape(arr.shape)
        _compress.observe(codec.name, raw=arr.nbytes, wire=wire_len,
                          encode_s=_time.perf_counter() - t0)
        return result

    def broadcast(self, data, root, cache_key=None):
        if self.get_world_size() == 1:
            if root != 0:
                raise ValueError(f"broadcast root {root} out of range")
            if data is None:
                raise ValueError("root must pass data to broadcast")
            return data
        from jax.experimental import multihost_utils as mhu

        is_root = self.get_rank() == root
        # Two-phase length-then-payload, like the reference binding
        # (python/rabit.py:171-206): all processes must present equal shapes.
        # Length rides as (hi, lo) int32 halves — JAX downcasts int64 arrays
        # under its default 32-bit config, which would wrap >=2GiB payloads.
        nbytes = len(data) if is_root and data is not None else 0
        length = np.array([nbytes >> 31, nbytes & 0x7FFFFFFF], np.int32)
        length = np.asarray(mhu.broadcast_one_to_all(length, is_source=is_root))
        buf = np.zeros((int(length[0]) << 31) | int(length[1]), np.uint8)
        if is_root:
            buf[:] = np.frombuffer(data, np.uint8)
        buf = np.asarray(mhu.broadcast_one_to_all(buf, is_source=is_root))
        return buf.tobytes()

    def allgather(self, data, cache_key=None):
        if self.get_world_size() == 1:
            return data
        from jax.experimental import multihost_utils as mhu

        return np.asarray(mhu.process_allgather(np.asarray(data))).reshape(-1)

    def load_checkpoint(self):
        if self._global_blob is None and self._lazy_thunk is not None:
            self._global_blob = bytes(self._lazy_thunk())
        return self._version, self._global_blob, self._local_blob

    def checkpoint(self, global_blob, local_blob=None):
        # Host-memory checkpoint per process; multi-host recovery of a
        # preempted VM is the native robust engine's job (hybrid deployment:
        # XLA data plane + robust TCP control plane).
        self._global_blob = bytes(global_blob)
        self._local_blob = None if local_blob is None else bytes(local_blob)
        self._lazy_thunk = None
        self._version += 1

    def lazy_checkpoint(self, get_global_blob):
        self._lazy_thunk = get_global_blob
        self._global_blob = None
        self._local_blob = None
        self._version += 1

    def version_number(self):
        return self._version
