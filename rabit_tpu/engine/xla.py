"""XLA engine — cross-host collectives through JAX.

The engine-level (host numpy) API for multi-host TPU jobs launched with
``jax.distributed``: rank = process index, world = process count, and the
collectives ride XLA's DCN/ICI transport via ``jax.experimental.
multihost_utils`` instead of the reference's hand-rolled TCP loops.  This is
the third backend the reference's engine seam anticipated (engine_mpi.cc as
the proof the seam is swappable; BASELINE.json north star).

In-graph device collectives live in ``rabit_tpu.parallel``; this engine is
the host-side control surface with the same semantics as the others.
"""

from __future__ import annotations

from typing import Callable

import numpy as np

from rabit_tpu.engine.base import Engine, numpy_reduce


class XlaEngine(Engine):
    def __init__(self, config):
        super().__init__(config)
        self._version = 0
        self._global_blob: bytes | None = None
        self._local_blob: bytes | None = None
        self._lazy_thunk: Callable[[], bytes] | None = None

    def init(self) -> None:
        import jax

        self._rank = jax.process_index()
        self._world = jax.process_count()

    def get_rank(self) -> int:
        return getattr(self, "_rank", 0)

    def get_world_size(self) -> int:
        return getattr(self, "_world", 1)

    def allreduce(self, data, op, prepare_fun=None, cache_key=None):
        if prepare_fun is not None:
            prepare_fun(data)
        if self.get_world_size() == 1:
            return data
        from jax.experimental import multihost_utils as mhu

        gathered = np.asarray(mhu.process_allgather(np.asarray(data)))
        acc = np.array(gathered[0], copy=True)
        for i in range(1, gathered.shape[0]):
            acc = numpy_reduce(op, acc, gathered[i])
        return acc.astype(data.dtype)

    def broadcast(self, data, root, cache_key=None):
        if self.get_world_size() == 1:
            if root != 0:
                raise ValueError(f"broadcast root {root} out of range")
            if data is None:
                raise ValueError("root must pass data to broadcast")
            return data
        from jax.experimental import multihost_utils as mhu

        is_root = self.get_rank() == root
        # Two-phase length-then-payload, like the reference binding
        # (python/rabit.py:171-206): all processes must present equal shapes.
        # Length rides as (hi, lo) int32 halves — JAX downcasts int64 arrays
        # under its default 32-bit config, which would wrap >=2GiB payloads.
        nbytes = len(data) if is_root and data is not None else 0
        length = np.array([nbytes >> 31, nbytes & 0x7FFFFFFF], np.int32)
        length = np.asarray(mhu.broadcast_one_to_all(length, is_source=is_root))
        buf = np.zeros((int(length[0]) << 31) | int(length[1]), np.uint8)
        if is_root:
            buf[:] = np.frombuffer(data, np.uint8)
        buf = np.asarray(mhu.broadcast_one_to_all(buf, is_source=is_root))
        return buf.tobytes()

    def allgather(self, data, cache_key=None):
        if self.get_world_size() == 1:
            return data
        from jax.experimental import multihost_utils as mhu

        return np.asarray(mhu.process_allgather(np.asarray(data))).reshape(-1)

    def load_checkpoint(self):
        if self._global_blob is None and self._lazy_thunk is not None:
            self._global_blob = bytes(self._lazy_thunk())
        return self._version, self._global_blob, self._local_blob

    def checkpoint(self, global_blob, local_blob=None):
        # Host-memory checkpoint per process; multi-host recovery of a
        # preempted VM is the native robust engine's job (hybrid deployment:
        # XLA data plane + robust TCP control plane).
        self._global_blob = bytes(global_blob)
        self._local_blob = None if local_blob is None else bytes(local_blob)
        self._lazy_thunk = None
        self._version += 1

    def lazy_checkpoint(self, get_global_blob):
        self._lazy_thunk = get_global_blob
        self._global_blob = None
        self._local_blob = None
        self._version += 1

    def version_number(self):
        return self._version
