"""Engine interface — the backend seam.

Capability parity with the reference's ``IEngine`` pure-virtual interface
(``/root/reference/include/rabit/internal/engine.h:32-209``): every backend
(solo, XLA/ICI, native TCP, native robust, mock) implements this surface and
the public API dispatches to a process-wide singleton.  Unlike the reference,
backend selection happens at *run time* from config (``rabit_engine=...``),
not at link time.
"""

from __future__ import annotations

import socket as _socket
from abc import ABC, abstractmethod
from typing import Any, Callable

import numpy as np

from rabit_tpu.config import Config

# Reduction op enum — wire/ABI compatible with the reference
# (python/rabit.py:83-86, engine.h mpi::OpType).
MAX = 0
MIN = 1
SUM = 2
BITOR = 3

_NUMPY_OPS: dict[int, Callable[[np.ndarray, np.ndarray], np.ndarray]] = {
    MAX: np.maximum,
    MIN: np.minimum,
    SUM: np.add,
    BITOR: np.bitwise_or,
}

# dtype enum — ABI compatible with the reference C API
# (python/rabit.py:209-218, c_api.cc:36-120).
DTYPE_ENUM = {
    np.dtype("int8"): 0,
    np.dtype("uint8"): 1,
    np.dtype("int32"): 2,
    np.dtype("uint32"): 3,
    np.dtype("int64"): 4,
    np.dtype("uint64"): 5,
    np.dtype("float32"): 6,
    np.dtype("float64"): 7,
}


def numpy_reduce(op: int, dst: np.ndarray, src: np.ndarray) -> np.ndarray:
    """Apply a builtin reduction op elementwise (reference: op::Reducer,
    rabit-inl.h:95-102)."""
    if op not in _NUMPY_OPS:
        raise ValueError(f"unknown reduction op {op}")
    return _NUMPY_OPS[op](dst, src)


class Engine(ABC):
    """Backend interface.  All buffers at this layer are numpy arrays or raw
    bytes; the XLA engine additionally accepts jax arrays."""

    def __init__(self, config: Config):
        self.config = config

    def obs_event(self, kind: str, /, **fields):
        """Record a structured engine-layer event into the process flight
        recorder (rabit_tpu.obs), tagged with the backend class.  Lazy
        import: base must stay importable before the obs package."""
        from rabit_tpu import obs

        return obs.record_event(kind, engine=type(self).__name__, **fields)

    # -- lifecycle ---------------------------------------------------------

    def init(self) -> None:
        """Connect/bootstrap.  Called once by ``rabit_tpu.init``."""

    def shutdown(self) -> None:
        """Graceful teardown.  Called by ``rabit_tpu.finalize``."""

    def init_after_exception(self) -> None:
        """Recover engine state after the caller caught an exception
        (reference: IEngine::InitAfterException)."""
        raise RuntimeError(f"{type(self).__name__} cannot recover from exceptions")

    # -- topology ----------------------------------------------------------

    @abstractmethod
    def get_rank(self) -> int: ...

    @abstractmethod
    def get_world_size(self) -> int: ...

    def is_distributed(self) -> bool:
        return self.get_world_size() > 1

    def get_host(self) -> str:
        return _socket.gethostname()

    def get_ring_prev_rank(self) -> int:
        """Rank of the ring predecessor (reference: GetRingPrevRank)."""
        world = self.get_world_size()
        return (self.get_rank() + world - 1) % world

    # -- collectives -------------------------------------------------------

    @abstractmethod
    def allreduce(
        self,
        data: np.ndarray,
        op: int,
        prepare_fun: Callable[[np.ndarray], None] | None = None,
        cache_key: str | None = None,
    ) -> np.ndarray:
        """In-place-semantics allreduce: returns the reduced array (same
        shape/dtype as ``data``).  ``prepare_fun`` is the lazy initializer:
        it must be invoked on ``data`` right before the reduction unless the
        result is served from recovery/replay (reference semantics,
        rabit.h:182-206)."""

    @abstractmethod
    def broadcast(self, data: bytes | None, root: int, cache_key: str | None = None) -> bytes:
        """Broadcast a byte string from ``root`` to everyone."""

    @abstractmethod
    def allgather(
        self,
        data: np.ndarray,
        cache_key: str | None = None,
    ) -> np.ndarray:
        """Gather equal-sized per-rank slices into one array: input is this
        rank's slice, output is the concatenation over ranks (built on the
        reference's slice-addressed ring allgather, engine.h:56-79)."""

    def allreduce_compressed(
        self,
        data: np.ndarray,
        op: int,
        codec,
        prepare_fun: Callable[[np.ndarray], None] | None = None,
        cache_key: str | None = None,
    ) -> np.ndarray:
        """Allreduce with a wire codec (rabit_tpu.compress): each rank's
        contribution crosses the engine encoded; every rank decodes and
        folds the gathered planes identically, so the result is bitwise
        identical on all ranks and bitwise reproducible under replay.

        Default implementation: the numpy host transport over this
        engine's own primitives (encode -> one framed allgather, plus a
        tiny size-agreement allreduce when the deflate stage makes wire
        sizes data-dependent).  Backends with an in-graph path override
        this (engine/xla.py runs encode/decode on-device so the flush
        stays one fused device collective).

        Unlike the exact path, ``prepare_fun`` runs eagerly — its output
        feeds the encoder — which is always semantically safe (skipping it
        on replay is an optimization, not a contract)."""
        from rabit_tpu import compress as _compress

        if prepare_fun is not None:
            prepare_fun(data)
        return _compress.host_allreduce(
            self, np.ascontiguousarray(data), op, codec,
            cache_key=cache_key,
            deflate=_compress.policy().wire_deflate,
        )

    def fused_active(self, codec, op) -> bool:
        """True when ``allreduce_compressed(codec, op)`` will run as one
        fused in-graph device collective (engine/fused.py) rather than the
        host transport.  The obs layer stamps ``fused=1`` into the
        collective identity from this answer, so Perfetto traces and the
        straggler analytics can tell the two data planes apart.  Only the
        XLA engine overrides this; everywhere else the host path is the
        only compressed path (``rabit_fused_allreduce`` is off elsewhere
        by construction)."""
        return False

    # -- custom reduction --------------------------------------------------

    def allreduce_fn(
        self,
        data: np.ndarray,
        reduce_fn: Callable[[np.ndarray, np.ndarray], np.ndarray],
        prepare_fun: Callable[[np.ndarray], None] | None = None,
        cache_key: str | None = None,
    ) -> np.ndarray:
        """Allreduce with a user reduction function (reference: Reducer /
        SerializeReducer, rabit.h:352-456).  Default implementation: gather
        all slices and fold locally — backends may override with a tree
        reduction of serialized states."""
        if prepare_fun is not None:
            prepare_fun(data)
        flat = np.ascontiguousarray(data).reshape(-1)
        gathered = self.allgather(flat, cache_key=cache_key)
        world = self.get_world_size()
        parts = gathered.reshape(world, *data.shape)
        acc = np.array(parts[0], copy=True)
        for i in range(1, world):
            acc = reduce_fn(acc, parts[i])
        return acc.astype(data.dtype).reshape(data.shape)

    # -- checkpoint / recovery --------------------------------------------

    @abstractmethod
    def load_checkpoint(self) -> tuple[int, bytes | None, bytes | None]:
        """Return (version, global_blob, local_blob); version 0 means no
        checkpoint exists yet."""

    @abstractmethod
    def checkpoint(self, global_blob: bytes, local_blob: bytes | None = None) -> None:
        """Commit an iteration: store blobs, bump version."""

    def lazy_checkpoint(self, get_global_blob: Callable[[], bytes]) -> None:
        """Defer serialization until a failure actually needs the blob
        (reference: LazyCheckPoint, rabit.h:311-332).  Default: eager."""
        self.checkpoint(get_global_blob())

    @abstractmethod
    def version_number(self) -> int: ...

    # -- observability -----------------------------------------------------

    def tracker_print(self, msg: str) -> None:
        print(msg, end="" if msg.endswith("\n") else "\n", flush=True)


class ShutdownSignal(Exception):
    """Raised internally when the tracker orders shutdown."""
