"""ctypes bridge to the native C++ engine (libtpurabit.so).

Capability parity with the reference's Python binding loader
(/root/reference/python/rabit.py:47-74) — but instead of three separately
linked libraries (librabit / librabit_mock / librabit_mpi) one library hosts
all backends and ``rabit_engine=empty|base|robust|mock`` picks at init time.
The library is auto-built from native/ on first use.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
import time
from pathlib import Path
from typing import Callable

import numpy as np

from rabit_tpu.engine.base import DTYPE_ENUM, Engine

_NATIVE_DIR = Path(__file__).resolve().parents[2] / "native"
_LIB_PATH = _NATIVE_DIR / "libtpurabit.so"
_lib = None
_lib_lock = threading.Lock()

_PREPARE_CB = ctypes.CFUNCTYPE(None, ctypes.c_void_p)
_REDUCE_CB = ctypes.CFUNCTYPE(
    None, ctypes.c_void_p, ctypes.c_void_p, ctypes.c_uint64, ctypes.c_void_p
)
_SERIALIZE_CB = ctypes.CFUNCTYPE(
    ctypes.c_int, ctypes.c_void_p,
    ctypes.POINTER(ctypes.c_char_p), ctypes.POINTER(ctypes.c_uint64),
)


def _build_lib() -> None:
    proc = subprocess.run(
        ["make", "-C", str(_NATIVE_DIR), "-j4"],
        capture_output=True,
        text=True,
    )
    if proc.returncode != 0:
        raise RuntimeError(
            f"native library build failed:\n{proc.stdout}\n{proc.stderr}"
        )


def load_lib() -> ctypes.CDLL:
    global _lib
    with _lib_lock:
        if _lib is not None:
            return _lib
        if not _LIB_PATH.exists():
            _build_lib()
        lib = ctypes.CDLL(str(_LIB_PATH))
        lib.TrtGetLastError.restype = ctypes.c_char_p
        lib.RabitInit.argtypes = [ctypes.c_int, ctypes.POINTER(ctypes.c_char_p)]
        lib.RabitAllreduce.argtypes = [
            ctypes.c_void_p, ctypes.c_uint64, ctypes.c_int, ctypes.c_int,
            _PREPARE_CB, ctypes.c_void_p,
        ]
        lib.RabitAllreduceKeyed.argtypes = lib.RabitAllreduce.argtypes + [
            ctypes.c_char_p
        ]
        lib.RabitBroadcast.argtypes = [ctypes.c_void_p, ctypes.c_uint64, ctypes.c_int]
        lib.RabitBroadcastKeyed.argtypes = lib.RabitBroadcast.argtypes + [
            ctypes.c_char_p
        ]
        lib.RabitAllgather.argtypes = [ctypes.c_void_p] + [ctypes.c_uint64] * 4
        lib.RabitAllgatherKeyed.argtypes = [ctypes.c_void_p] + [
            ctypes.c_uint64
        ] * 3 + [ctypes.c_char_p]
        lib.RabitCheckPoint.argtypes = [
            ctypes.c_char_p, ctypes.c_uint64, ctypes.c_char_p, ctypes.c_uint64
        ]
        lib.RabitLazyCheckPoint.argtypes = [ctypes.c_char_p, ctypes.c_uint64]
        lib.TrtLazyCheckPointFn.argtypes = [_SERIALIZE_CB, ctypes.c_void_p]
        lib.RabitLoadCheckPoint.argtypes = [
            ctypes.POINTER(ctypes.POINTER(ctypes.c_char)),
            ctypes.POINTER(ctypes.c_uint64),
            ctypes.POINTER(ctypes.POINTER(ctypes.c_char)),
            ctypes.POINTER(ctypes.c_uint64),
        ]
        lib.TrtAllreduceCustom.argtypes = [
            ctypes.c_void_p, ctypes.c_uint64, ctypes.c_uint64,
            _REDUCE_CB, ctypes.c_void_p, _PREPARE_CB, ctypes.c_void_p,
            ctypes.c_char_p,
        ]
        lib.RabitTrackerPrint.argtypes = [ctypes.c_char_p]
        lib.RabitGetProcessorName.argtypes = [
            ctypes.c_char_p, ctypes.POINTER(ctypes.c_uint64), ctypes.c_uint64
        ]
        _lib = lib
        return lib


class NativeError(RuntimeError):
    pass


class NativeEngine(Engine):
    """Engine backed by the native library (TCP tree/ring collectives,
    robust recovery, mock fault injection)."""

    def __init__(self, config, kind: str = "native"):
        super().__init__(config)
        self._kind = kind
        self._lib = load_lib()

    def _check(self, rc: int, what: str) -> None:
        if rc != 0:
            msg = self._lib.TrtGetLastError().decode()
            # Bridge-side evidence: the error (mock kill, socket failure,
            # recovery abort) lands in the flight recorder before the
            # exception unwinds Python — a subsequent hang/SIGTERM dump
            # then carries it.
            self.obs_event("engine_error", what=what, error=msg)
            raise NativeError(f"{what} failed: {msg}")

    # -- lifecycle ---------------------------------------------------------

    def init(self) -> None:
        cfg = dict(self.config.as_dict())
        if self._kind != "native":
            cfg["rabit_engine"] = self._kind
        args = [f"{k}={v}".encode() for k, v in cfg.items()]
        arr = (ctypes.c_char_p * len(args))(*args)
        self.obs_event("engine_init", backend=self._kind)
        t0 = time.time()
        try:
            self._check(self._lib.RabitInit(len(args), arr), "init")
        except NativeError as exc:
            # Fail-fast diagnosis: a dead tracker surfaces from the native
            # bootstrap as a connect failure after its bounded
            # rabit_connect_retry backoff loop (socket.cc Connect).  Name
            # the address and the budget so the operator sees "tracker
            # gone", not a bare errno.
            if "connect to" in str(exc):
                uri = self.config.get("rabit_tracker_uri", "NULL")
                port = self.config.get("rabit_tracker_port", "9091")
                retry = self.config.get_int("rabit_connect_retry", 5)
                raise NativeError(
                    f"{exc} — tracker at {uri}:{port} unreachable after "
                    f"{retry + 1} backed-off connect attempts "
                    f"(rabit_connect_retry={retry}); is the tracker "
                    f"running?"
                ) from exc
            raise
        # (Re)bootstrap complete: the assignment is live.  Restarted lives
        # see DMLC_NUM_ATTEMPT > 0 — the recorder then shows the reconnect
        # wave this rank came back through.  The seconds field closes the
        # engine_init -> bootstrap_done span the trace exporter draws.
        self.obs_event(
            "bootstrap_done",
            rank=self.get_rank(),
            world=self.get_world_size(),
            attempt=int(os.environ.get("DMLC_NUM_ATTEMPT", "0") or "0"),
            seconds=round(time.time() - t0, 6),
        )

    def shutdown(self) -> None:
        self.obs_event("engine_shutdown", backend=self._kind)
        self._check(self._lib.RabitFinalize(), "finalize")

    def init_after_exception(self) -> None:
        self.obs_event("init_after_exception", backend=self._kind)
        self._check(self._lib.RabitInitAfterException(), "init_after_exception")

    def rebootstrap(self) -> None:
        """Re-bootstrap after a world-epoch change (rabit_tpu.elastic):
        finalize the engine and check in again, adopting whatever
        assignment — rank, world size, topology — the tracker's current
        epoch hands out.  The native collective core keeps its fixed-world
        contract WITHIN a bootstrap; resizing happens by re-entering one.
        In-memory checkpoint replay state does not survive the finalize —
        callers re-feed state from the durable store (rabit_checkpoint_dir)
        or an application-level blob, exactly like a whole-job resume.
        Invoked through ``rabit_tpu.api.rebootstrap``."""
        self.obs_event("epoch_changed", backend=self._kind,
                       world=self.get_world_size())
        self._check(self._lib.RabitFinalize(), "finalize")
        self.init()

    # -- topology ----------------------------------------------------------

    def get_rank(self) -> int:
        return self._lib.RabitGetRank()

    def get_world_size(self) -> int:
        return self._lib.RabitGetWorldSize()

    def is_distributed(self) -> bool:
        return bool(self._lib.RabitIsDistributed())

    def get_ring_prev_rank(self) -> int:
        return self._lib.RabitGetRingPrevRank()

    def get_host(self) -> str:
        buf = ctypes.create_string_buffer(256)
        length = ctypes.c_uint64()
        self._check(
            self._lib.RabitGetProcessorName(buf, ctypes.byref(length), 256),
            "get_processor_name",
        )
        return buf.value.decode()

    def tracker_print(self, msg: str) -> None:
        self._check(self._lib.RabitTrackerPrint(msg.encode()), "tracker_print")

    # -- collectives -------------------------------------------------------

    def allreduce(self, data, op, prepare_fun=None, cache_key=None):
        buf = np.ascontiguousarray(data)
        cb = _PREPARE_CB()
        if prepare_fun is not None:
            cb = _PREPARE_CB(lambda _arg: prepare_fun(buf))
        rc = self._lib.RabitAllreduceKeyed(
            buf.ctypes.data_as(ctypes.c_void_p), buf.size,
            DTYPE_ENUM[buf.dtype], op, cb, None,
            (cache_key or "").encode(),
        )
        self._check(rc, "allreduce")
        return buf

    def allreduce_fn(self, data, reduce_fn, prepare_fun=None, cache_key=None):
        buf = np.ascontiguousarray(data)
        count = buf.size
        itemsize = buf.dtype.itemsize

        def c_reduce(dst, src, n, _ctx):
            d = np.ctypeslib.as_array(
                ctypes.cast(dst, ctypes.POINTER(ctypes.c_uint8)), shape=(n * itemsize,)
            ).view(buf.dtype)
            s = np.ctypeslib.as_array(
                ctypes.cast(src, ctypes.POINTER(ctypes.c_uint8)), shape=(n * itemsize,)
            ).view(buf.dtype)
            d[...] = reduce_fn(d.copy(), s)

        rcb = _REDUCE_CB(c_reduce)
        pcb = _PREPARE_CB()
        if prepare_fun is not None:
            pcb = _PREPARE_CB(lambda _arg: prepare_fun(buf))
        rc = self._lib.TrtAllreduceCustom(
            buf.ctypes.data_as(ctypes.c_void_p), itemsize, count,
            rcb, None, pcb, None, (cache_key or "").encode(),
        )
        self._check(rc, "allreduce_custom")
        return buf

    def broadcast(self, data, root, cache_key=None):
        rank = self.get_rank()
        key = (cache_key or "").encode()
        # two-phase: length then payload (reference python/rabit.py:171-206)
        length = np.array([len(data) if rank == root and data is not None else 0],
                          np.uint64)
        self._check(
            self._lib.RabitBroadcastKeyed(
                length.ctypes.data_as(ctypes.c_void_p), 8, root, key
            ),
            "broadcast",
        )
        n = int(length[0])
        buf = np.zeros(n, np.uint8)
        if rank == root:
            buf[:] = np.frombuffer(data, np.uint8)
        if n > 0:
            self._check(
                self._lib.RabitBroadcastKeyed(
                    buf.ctypes.data_as(ctypes.c_void_p), n, root, key
                ),
                "broadcast",
            )
        return buf.tobytes()

    def allgather(self, data, cache_key=None):
        flat = np.ascontiguousarray(data).reshape(-1)
        world = self.get_world_size()
        rank = self.get_rank()
        nbytes = flat.nbytes
        out = np.zeros(world * flat.size, flat.dtype)
        out[rank * flat.size:(rank + 1) * flat.size] = flat
        self._check(
            self._lib.RabitAllgatherKeyed(
                out.ctypes.data_as(ctypes.c_void_p), out.nbytes,
                rank * nbytes, (rank + 1) * nbytes,
                (cache_key or "").encode(),
            ),
            "allgather",
        )
        return out

    # -- checkpointing -----------------------------------------------------

    def load_checkpoint(self):
        gptr = ctypes.POINTER(ctypes.c_char)()
        lptr = ctypes.POINTER(ctypes.c_char)()
        glen = ctypes.c_uint64()
        llen = ctypes.c_uint64()
        version = self._lib.RabitLoadCheckPoint(
            ctypes.byref(gptr), ctypes.byref(glen),
            ctypes.byref(lptr), ctypes.byref(llen),
        )
        if version < 0:
            raise NativeError(
                f"load_checkpoint failed: {self._lib.TrtGetLastError().decode()}"
            )
        if version == 0:
            return 0, None, None
        gblob = ctypes.string_at(gptr, glen.value) if glen.value else None
        lblob = ctypes.string_at(lptr, llen.value) if llen.value else None
        # Recovery phase evidence at the bridge: a version > 0 load means
        # this life's state was served by peers (the robust engine's
        # recover_stats print carries the protocol counters; the tracker
        # converts that line into a structured event — see
        # rabit_tpu.obs.events.event_from_stats_line).
        self.obs_event(
            "checkpoint_loaded", version=version,
            global_bytes=glen.value, local_bytes=llen.value,
        )
        return version, gblob, lblob

    def checkpoint(self, global_blob, local_blob=None):
        self._check(
            self._lib.RabitCheckPoint(
                global_blob, len(global_blob),
                local_blob, 0 if local_blob is None else len(local_blob),
            ),
            "checkpoint",
        )
        self.obs_event("version_bump", version=self.version_number())

    def lazy_checkpoint(self, get_global_blob: Callable[[], bytes]) -> None:
        # True lazy across the ABI (reference global_lazycheck,
        # allreduce_robust.cc:527-535): register a serialize-on-demand
        # callback, so pickling only happens if a failure actually needs the
        # blob.  Caller contract (same as the reference's, rabit.h:311-332):
        # the model behind get_global_blob must stay unchanged until the
        # next checkpoint — the callback can fire any time in that window,
        # including while the NEXT checkpoint's pre-commit consensus still
        # serves this version to a recovering peer.
        def _serialize(ctx, out_data, out_len):
            try:
                self._lazy_blob = get_global_blob()
                out_data[0] = self._lazy_blob
                out_len[0] = len(self._lazy_blob)
                return 0
            except Exception:
                return -1

        cb = _SERIALIZE_CB(_serialize)
        # Every callback the engine might still reference must stay alive:
        # the previous one until this registration has definitely replaced
        # it inside the engine — and both if the call fails partway.
        self._lazy_keepalive = getattr(self, "_lazy_keepalive", []) + [cb]
        self._check(self._lib.TrtLazyCheckPointFn(cb, None), "lazy_checkpoint")
        self._lazy_keepalive = [cb]

    def version_number(self):
        return self._lib.RabitVersionNumber()
