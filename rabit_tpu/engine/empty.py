"""Solo (single-process) engine.

Capability parity with the reference's EmptyEngine
(``/root/reference/src/engine_empty.cc:17-91``): rank 0, world size 1, all
collectives are identities — so single-process programs run with zero
configuration.  Unlike the reference's EmptyEngine (which aborts on
checkpoint calls in base-only builds), the solo engine keeps an in-memory
versioned checkpoint so the full API is exercisable without a cluster,
matching the robust engine's world==1 fast path
(allreduce_robust.cc:253-256, :488-490).
"""

from __future__ import annotations

from typing import Callable

import numpy as np

from rabit_tpu.engine.base import Engine


class SoloEngine(Engine):
    def __init__(self, config):
        super().__init__(config)
        self._version = 0
        self._global_blob: bytes | None = None
        self._local_blob: bytes | None = None

    def get_rank(self) -> int:
        return 0

    def get_world_size(self) -> int:
        return 1

    def is_distributed(self) -> bool:
        return False

    def allreduce(self, data, op, prepare_fun=None, cache_key=None):
        if prepare_fun is not None:
            prepare_fun(data)
        return data

    def allreduce_fn(self, data, reduce_fn, prepare_fun=None, cache_key=None):
        if prepare_fun is not None:
            prepare_fun(data)
        return data

    def broadcast(self, data, root, cache_key=None):
        if root != 0:
            raise ValueError(f"broadcast root {root} out of range for world size 1")
        if data is None:
            raise ValueError("root must pass data to broadcast")
        return data

    def allgather(self, data: np.ndarray, cache_key=None) -> np.ndarray:
        return data

    def load_checkpoint(self):
        if self._global_blob is None and getattr(self, "_lazy_thunk", None) is not None:
            self._global_blob = bytes(self._lazy_thunk())
        return self._version, self._global_blob, self._local_blob

    def checkpoint(self, global_blob: bytes, local_blob: bytes | None = None) -> None:
        self._global_blob = bytes(global_blob)
        self._local_blob = None if local_blob is None else bytes(local_blob)
        self._version += 1

    def lazy_checkpoint(self, get_global_blob: Callable[[], bytes]) -> None:
        # Solo mode has no peers to recover from; keep the thunk, bump the
        # version, and only serialize if someone later loads.  Lazy
        # checkpoints carry no local model (reference contract: LazyCheckPoint
        # takes only the global model, rabit.h:311-332).
        self._lazy_thunk = get_global_blob
        self._global_blob = None
        self._local_blob = None
        self._version += 1

    def version_number(self) -> int:
        return self._version
