"""Engine registry and runtime backend selection.

The reference picks its backend at link time via which library you link
(``librabit`` vs ``librabit_mock`` vs ``librabit_mpi``, src/engine.cc:19-27);
here the backend is a config key (``rabit_engine=auto|empty|xla|native|mock``)
resolved when ``rabit_tpu.init`` runs.
"""

from __future__ import annotations

from rabit_tpu.config import Config
from rabit_tpu.engine.base import Engine


def create_engine(config: Config) -> Engine:
    kind = config.get("rabit_engine", "auto")
    if kind == "auto":
        # A tracker URI means we are one worker of a launched cluster -> the
        # native fault-tolerant TCP engine.  Otherwise run solo; the XLA mesh
        # data plane is reached through rabit_tpu.parallel / models, which are
        # SPMD and do not need a per-process engine.
        if config.get("rabit_tracker_uri", "NULL") != "NULL":
            kind = "native"
        else:
            kind = "empty"
    if kind == "empty":
        from rabit_tpu.engine.empty import SoloEngine

        return SoloEngine(config)
    if kind == "xla":
        from rabit_tpu.engine.xla import XlaEngine

        return XlaEngine(config)
    if kind in ("native", "mock", "robust", "base"):
        try:
            from rabit_tpu.engine.native import NativeEngine
        except ModuleNotFoundError as exc:
            raise RuntimeError(
                "the native TCP engine is not available in this build"
            ) from exc

        return NativeEngine(config, kind)
    raise ValueError(f"unknown rabit_engine {kind!r}")
