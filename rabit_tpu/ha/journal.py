"""The durable control-plane journal — append, compact, stream.

One :class:`Journal` owns a single writer thread fed by a queue: every
tracker mutation point enqueues a ``(kind, fields)`` record
(non-blocking, safe under the tracker lock), and the writer frames it
(``protocol.put_journal_frame`` — crc'd, codec-tagged, the durable
store's RTC2 layout), appends it to the ``rabit_ha_journal`` file (when
one is configured), folds it into the in-memory
:class:`~rabit_tpu.ha.state.ControlState` mirror, and fans the frame
out to every subscriber (the CMD_JOURNAL channels streaming to warm
standbys).  A single writer means file bytes, mirror state, and every
subscriber see the records in ONE total order — which is what makes
"standby replay == primary snapshot" a byte comparison instead of a
race.

Compaction: after ``snapshot_every`` records the writer rewrites the
file as ONE ``snapshot`` record (atomic tmp + rename, the store.py
protocol) and pushes the same snapshot frame to subscribers — replay
stays O(live state), not O(history), and every streaming standby gets a
fresh byte-assert point (a divergent standby notes a ``journal_gap``
and self-heals by adopting the snapshot).

Opening an existing journal replays it first (torn tail records are
truncated — the crc reads them as absent) and immediately compacts, so
a tracker promoted over an inherited journal starts from a clean
snapshot head.  ``path=None`` keeps the journal memory-only: the mirror
and the subscriber stream still work, which is all a streamed
(CMD_JOURNAL) standby needs.

Durability scope: writes are flushed per record but NOT fsync'd by
default (``fsync=True`` opts in) — the journal's first job is failover
(the standby holds the state in memory), the file is the restart /
audit trail.  A lost tail record costs one re-formed wave, never a
wrong bit: workers re-enter recovery waves and every decision is
re-derived deterministically from the replayed prefix.
"""

from __future__ import annotations

import os
import queue
import threading
from typing import Callable

from rabit_tpu.ha.state import ControlState
from rabit_tpu.tracker import protocol as P


def read_journal(path: str) -> tuple[list[tuple[str, dict]], bool]:
    """Read every intact record of a journal file.  Returns
    ``(records, torn)`` — ``torn`` flags a trailing partial/corrupt
    frame (truncated by the reader, kept on disk: the next writer
    compacts over it)."""
    try:
        with open(path, "rb") as f:
            data = f.read()
    except FileNotFoundError:
        return [], False
    records, consumed, err = P.journal_frames_from_buffer(data)
    return records, (err is not None or consumed < len(data))


def replay(records: list[tuple[str, dict]],
           state: ControlState | None = None) -> ControlState:
    """Fold records into ``state`` (a fresh one by default)."""
    state = state if state is not None else ControlState()
    for kind, fields in records:
        state.apply(kind, fields)
    return state


class Journal:
    """One tracker's control-plane journal (module docstring).

    ``state`` seeds the mirror (a promoted tracker passes the state it
    replayed as a standby); ``on_event`` receives ``{"kind":
    "journal_snapshot"|"journal_gap", ...}`` dicts from the writer
    thread (the tracker appends them to its telemetry timeline).
    """

    def __init__(self, path: str | None = None, codec: str = "zlib",
                 snapshot_every: int = 256,
                 state: ControlState | None = None,
                 on_event: Callable[[dict], None] | None = None,
                 fsync: bool = False,
                 seeded: bool | None = None):
        self.path = path
        self.codec = codec
        self.snapshot_every = max(int(snapshot_every), 1)
        self.fsync = bool(fsync)
        self.on_event = on_event
        self._state = state if state is not None else ControlState()
        self._lock = threading.Lock()  # mirror reads vs writer applies
        self._subs: list[queue.Queue] = []
        self._q: queue.Queue = queue.Queue()
        self._file = None
        self._since_snapshot = 0
        self.n_appended = 0
        self.n_snapshots = 0
        self._closed = threading.Event()
        # A caller-supplied state is AUTHORITATIVE by default (a promoted
        # standby already replayed this very file / its stream): the
        # existing file is compacted over, never re-applied — replaying
        # it into the supplied state would double-count every record.
        # ``seeded=False`` overrides that for a caller that supplies a
        # FRESH custom mirror (a CollectiveService's multi-job
        # ServiceState, doc/service.md) and wants the file replayed into
        # it.
        self._seeded = (state is not None) if seeded is None else bool(seeded)
        if path:
            self._bootstrap_file(path)
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="rabit-ha-journal")
        self._thread.start()

    # -- public API (any thread; everything enqueues) -----------------------

    def append(self, kind: str, **fields) -> None:
        """Record one control-plane mutation.  Non-blocking: safe to
        call under the tracker lock (the frame/write/fan-out happens on
        the writer thread, in enqueue order)."""
        if not self._closed.is_set():
            self._q.put(("rec", kind, fields))

    def subscribe(self) -> queue.Queue:
        """A live frame stream seeded with a snapshot of the current
        mirror: the writer enqueues the snapshot frame and then every
        later record, so a subscriber replays to exactly the primary's
        state with no gap and no duplicate."""
        sub: queue.Queue = queue.Queue()
        self._q.put(("sub", sub))
        return sub

    def unsubscribe(self, sub: queue.Queue) -> None:
        self._q.put(("unsub", sub))

    def flush(self, timeout: float = 5.0) -> bool:
        """Block until every record enqueued so far is written and
        fanned out (tests; pre-handoff barriers)."""
        done = threading.Event()
        self._q.put(("flush", done))
        return done.wait(timeout)

    def close(self) -> None:
        self._closed.set()
        self._q.put(None)
        self._thread.join(timeout=5.0)

    def state_bytes(self) -> bytes:
        """The mirror's canonical snapshot bytes (the primary side of
        the replay-determinism byte assert)."""
        with self._lock:
            return self._state.snapshot_bytes()

    def state_snapshot(self) -> dict:
        with self._lock:
            return self._state.snapshot()

    # -- writer thread ------------------------------------------------------

    def _bootstrap_file(self, path: str) -> None:
        """Open (and, when it already exists, replay + compact) the
        journal file.  Runs on the constructing thread so the mirror is
        ready before the tracker starts mutating.  With a seeded state
        the file is NOT re-applied (the seed already is its replay) —
        it is simply compacted under a snapshot of the seed."""
        records, torn = read_journal(path)
        if records and not self._seeded:
            with self._lock:
                replay(records, self._state)
        if torn:
            self._emit({"kind": "journal_gap", "path": path,
                        "records": len(records)})
        os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
        if records or torn:
            self._compact()  # a clean snapshot head over the old history
        else:
            self._file = open(path, "ab")

    def _emit(self, event: dict) -> None:
        if self.on_event is not None:
            try:
                self.on_event(event)
            except Exception:  # noqa: BLE001 — telemetry must not kill IO
                pass

    def _snapshot_frame(self) -> bytes:
        with self._lock:
            snap = self._state.snapshot()
        return P.put_journal_frame("snapshot", {"state": snap}, self.codec)

    def _compact(self) -> None:
        """Rewrite the file as one snapshot record (atomic replace, the
        store.py tmp+rename protocol) and push the same snapshot frame
        to subscribers as their byte-assert point."""
        frame = self._snapshot_frame()
        if self.path:
            if self._file is not None:
                try:
                    self._file.close()
                except OSError:
                    pass
            tmp = f"{self.path}.tmp.{os.getpid()}"
            with open(tmp, "wb") as f:
                f.write(frame)
                f.flush()
                if self.fsync:
                    os.fsync(f.fileno())
            os.replace(tmp, self.path)
            self._file = open(self.path, "ab")
        for sub in self._subs:
            sub.put(frame)
        self._since_snapshot = 0
        self.n_snapshots += 1
        self._emit({"kind": "journal_snapshot", "n": self.n_snapshots,
                    "nbytes": len(frame)})

    def _run(self) -> None:
        while True:
            item = self._q.get()
            if item is None:
                break
            op = item[0]
            if op == "rec":
                _, kind, fields = item
                frame = P.put_journal_frame(kind, fields, self.codec)
                with self._lock:
                    self._state.apply(kind, fields)
                if self._file is not None:
                    try:
                        self._file.write(frame)
                        self._file.flush()
                        if self.fsync:
                            os.fsync(self._file.fileno())
                    except OSError:
                        pass  # a full disk must not take the tracker down
                for sub in self._subs:
                    sub.put(frame)
                self.n_appended += 1
                self._since_snapshot += 1
                if self._since_snapshot >= self.snapshot_every:
                    self._compact()
            elif op == "sub":
                sub = item[1]
                sub.put(self._snapshot_frame())
                self._subs.append(sub)
            elif op == "unsub":
                sub = item[1]
                if sub in self._subs:
                    self._subs.remove(sub)
            elif op == "flush":
                item[1].set()
        if self._file is not None:
            try:
                self._file.close()
            except OSError:
                pass
