"""The warm-standby tracker — tail, replay, take over.

A :class:`Standby` is the second half of the HA control plane
(doc/ha.md): it binds its advertised address IMMEDIATELY (bound but not
listening, so clients probing it pre-takeover get connection-refused
and rotate back to the primary — ``tracker_rpc``'s address-list
failover), tails the primary's journal, and replays every record into
an identical :class:`~rabit_tpu.ha.state.ControlState`.  Two sync
transports, same frames:

* **streamed** — one persistent ``CMD_JOURNAL`` channel to the primary:
  a snapshot record first, then every mutation as it commits, plus
  ``tick`` keepalives.  Every snapshot frame after the first is a
  byte-assert point: the standby compares its replayed state against
  the primary's snapshot and notes a ``journal_gap`` (then self-heals
  by adopting the snapshot) on divergence — the replay-determinism gate
  running live.
* **file** — tail a shared ``rabit_ha_journal`` file (compactions
  replace the inode; the tailer detects the swap and re-reads).

Takeover is lease-shaped (``rabit_ha_takeover_sec``): the primary is
suspected when the channel stays down — or silent past the tick
cadence — for a full takeover lease.  The standby then listens on its
pre-bound socket and constructs a real
:class:`~rabit_tpu.tracker.tracker.Tracker` seeded with the replayed
state (``resume_from=``): ranks, epochs, quorum records, link flags and
the spare pool survive; journaled leases are re-armed with fresh
deadlines so a worker that died during the cut is still suspected.
Workers and relays fail over client-side (``rabit_tracker_addrs``) and
the interrupted wave re-forms on the standby — deterministically, so
the re-completed collectives are bitwise identical to an undisturbed
run (asserted by the chaos failover campaigns).
"""

from __future__ import annotations

import os
import socket
import threading
import time

from rabit_tpu.ha.journal import Journal
from rabit_tpu.ha.state import ControlState
from rabit_tpu.tracker import protocol as P


class Standby:
    """One warm-standby tracker (module docstring).

    ``primary=(host, port)`` selects the streamed CMD_JOURNAL transport;
    ``journal_path=`` the file-tail transport (give both: the stream
    syncs, the file is the liveness fallback — but one is enough).
    ``tracker_kwargs`` are passed through to the promoted
    :class:`Tracker` (schedule, quorum, on_suspect, ...).
    """

    def __init__(self, primary: tuple[str, int] | None = None,
                 journal_path: str | None = None,
                 host: str = "127.0.0.1", port: int = 0,
                 standby_id: str = "standby0",
                 takeover_sec: float = 1.0,
                 poll_sec: float = 0.1,
                 journal: str | None = None,
                 tracker_kwargs: dict | None = None,
                 quiet: bool = True,
                 service: bool = False):
        if primary is None and journal_path is None:
            raise ValueError("standby needs a primary address and/or a "
                             "journal path to tail")
        # Multi-job mode (doc/service.md): the tailed journal belongs to
        # a CollectiveService — replay into a ServiceState (every job's
        # partition restored from the ONE interleaved record stream) and
        # promote a CollectiveService instead of a single-job Tracker.
        self.service = bool(service)
        if service:
            from rabit_tpu.service.state import ServiceState

            self._state_cls = ServiceState
        else:
            self._state_cls = ControlState
        self.primary = ((primary[0], int(primary[1]))
                        if primary is not None else None)
        self.journal_path = journal_path
        self.standby_id = standby_id
        self.takeover_sec = float(takeover_sec)
        self.poll_sec = float(poll_sec)
        #: journal path the PROMOTED tracker writes (defaults to the
        #: tailed file, so the journal line continues across a failover)
        self.promoted_journal = journal if journal is not None \
            else journal_path
        self.tracker_kwargs = dict(tracker_kwargs or {})
        self.quiet = quiet
        self.state = self._state_cls()
        self.events: list[dict] = []  # seeded into the promoted tracker
        self.synced = threading.Event()     # first snapshot applied
        self.promoted = threading.Event()
        self.tracker = None  # the promoted Tracker, once promoted
        self._stop = threading.Event()
        self._lock = threading.Lock()
        # Bind the advertised address NOW, listen only at takeover: a
        # bound-unlistening socket refuses connections, which is exactly
        # the "not serving yet" signal the client-side rotation expects.
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind((host, port))
        self.host, self.port = self._sock.getsockname()
        self._thread: threading.Thread | None = None

    # -- lifecycle ----------------------------------------------------------

    def start(self) -> "Standby":
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name=f"rabit-ha-{self.standby_id}")
        self._thread.start()
        return self

    def stop(self) -> None:
        """Clean teardown: stops the sync loop and the promoted tracker
        (when one exists)."""
        self._stop.set()
        tracker = self.tracker
        if tracker is not None:
            tracker.stop()
        else:
            try:
                self._sock.close()
            except OSError:
                pass
        if self._thread is not None:
            self._thread.join(timeout=5.0)

    def kill(self) -> None:
        """Abrupt death (chaos ``standby_death``): the standby — or the
        tracker it promoted to — disappears without cleanup."""
        self._stop.set()
        tracker = self.tracker
        if tracker is not None:
            tracker.kill()
        else:
            try:
                self._sock.close()
            except OSError:
                pass

    def wait_synced(self, timeout: float | None = None) -> bool:
        return self.synced.wait(timeout)

    def wait_promoted(self, timeout: float | None = None) -> bool:
        return self.promoted.wait(timeout)

    # -- sync loop ----------------------------------------------------------

    def _note(self, ev: dict) -> None:
        """Record one standby event (the dict carries a literal "kind"
        so the event-kind registry check sees the emission)."""
        ev = {"ts": round(time.time(), 6), **ev}
        with self._lock:
            self.events.append(ev)
        if not self.quiet:
            print(f"[standby {self.standby_id}] {ev}", flush=True)

    def _apply_records(self, records: list[tuple[str, dict]]) -> None:
        """Fold tailed records in; snapshot records after the first sync
        byte-assert the replay against the primary's state."""
        for kind, fields in records:
            if kind == "snapshot" and self.synced.is_set():
                mine = self.state.snapshot_bytes()
                theirs = self._state_cls.from_snapshot(
                    fields["state"]).snapshot_bytes()
                if mine != theirs:
                    # Divergence means records were lost or applied
                    # differently: evidence first, then self-heal by
                    # adopting the primary's snapshot.
                    self._note({"kind": "journal_gap",
                                "applied": self.state.applied,
                                "mine": len(mine), "theirs": len(theirs)})
                    self.state.apply(kind, fields)
                continue
            self.state.apply(kind, fields)
            if kind == "snapshot" and not self.synced.is_set():
                self._note({"kind": "standby_synced",
                            "epoch": self.state.epoch,
                            "world": self.state.world})
                self.synced.set()

    def _run(self) -> None:
        """Tail until the primary's takeover lease lapses, then promote.
        ``alive_at`` is refreshed by every byte that arrives (stream) or
        every successful read/probe (file)."""
        alive_at = time.monotonic()
        chan: socket.socket | None = None
        buf = bytearray()
        file_pos = 0
        file_id: tuple[int, int] | None = None  # (st_ino, st_size basis)
        while not self._stop.is_set():
            now = time.monotonic()
            if now - alive_at > self.takeover_sec:
                if chan is not None:
                    try:
                        chan.close()
                    except OSError:
                        pass
                self._take_over()
                return
            if self.primary is not None:
                if chan is None:
                    chan = self._dial_primary()
                    if chan is not None:
                        buf = bytearray()
                if chan is not None:
                    got = self._pump_channel(chan, buf)
                    if got is None:  # channel died
                        try:
                            chan.close()
                        except OSError:
                            pass
                        chan = None
                    elif got:
                        alive_at = time.monotonic()
                    continue  # the pump's recv timeout already paced us
            if self.journal_path is not None:
                file_pos, file_id, fresh = self._tail_file(file_pos, file_id)
                if fresh:
                    alive_at = time.monotonic()
            self._stop.wait(self.poll_sec)

    def _dial_primary(self) -> socket.socket | None:
        try:
            chan = socket.create_connection(self.primary, timeout=1.0)
            chan.settimeout(1.0)
            P.send_hello(chan, P.CMD_JOURNAL, self.standby_id)
            if P.get_u32(chan) != P.ACK:
                chan.close()
                return None
            chan.settimeout(self.poll_sec)
            return chan
        except (ConnectionError, OSError, ValueError):
            return None

    def _pump_channel(self, chan: socket.socket,
                      buf: bytearray) -> bool | None:
        """One bounded read + frame parse.  Returns True when bytes
        arrived, False on a quiet tick, None when the channel died."""
        try:
            data = chan.recv(65536)
        except socket.timeout:
            return False
        except OSError:
            return None
        if not data:
            return None
        buf += data
        records, consumed, err = P.journal_frames_from_buffer(bytes(buf))
        del buf[:consumed]
        self._apply_records(records)
        if err is not None:
            self._note({"kind": "journal_gap", "transport": "stream",
                        "error": err})
            return None  # resync from a fresh snapshot on reconnect
        return True

    def _tail_file(self, pos: int, fid: tuple[int, int] | None
                   ) -> tuple[int, tuple[int, int] | None, bool]:
        """Read any new complete frames past ``pos``; a compaction
        (inode swap / shrink) restarts the replay from the new snapshot
        head."""
        path = self.journal_path
        try:
            st = os.stat(path)
        except OSError:
            return pos, fid, False
        if fid is not None and (st.st_ino != fid[0] or st.st_size < pos):
            pos = 0  # compacted: the file now starts with a snapshot
        fid = (st.st_ino, st.st_size)
        if st.st_size <= pos:
            return pos, fid, False
        try:
            with open(path, "rb") as f:
                f.seek(pos)
                data = f.read()
        except OSError:
            return pos, fid, False
        records, consumed, err = P.journal_frames_from_buffer(data)
        self._apply_records(records)
        if records and not self.synced.is_set():
            # a file tailed from byte 0 is consistent from the first
            # record (the stream transport waits for its snapshot head)
            self._note({"kind": "standby_synced",
                        "epoch": self.state.epoch,
                        "world": self.state.world})
            self.synced.set()
        if err is not None:
            # mid-file corruption: stop before it; the primary's next
            # compaction rewrites the file and the tailer resyncs
            self._note({"kind": "journal_gap", "transport": "file",
                        "error": err})
        return pos + consumed, fid, bool(records)

    # -- takeover -----------------------------------------------------------

    def _take_over(self) -> None:
        from rabit_tpu.tracker.tracker import Tracker

        if self._stop.is_set():
            return
        ev = {"kind": "tracker_failover",
              "standby": self.standby_id,
              "epoch": self.state.epoch, "world": self.state.world,
              "synced": self.synced.is_set()}
        if self.service:
            ev["jobs"] = self.state.n_jobs
        self._note(ev)
        kwargs = dict(self.tracker_kwargs)
        kwargs.setdefault("quiet", self.quiet)
        journal = None
        if self.promoted_journal:
            journal = Journal(self.promoted_journal, state=self.state)
        # listen() happens inside Tracker (listen_sock=): the pre-bound
        # socket starts refusing dials only now, which is exactly when
        # the client-side rotation should start landing here.
        if self.service:
            # Promote a full multi-job service: every live job's
            # partition is re-admitted from the replayed ServiceState
            # (doc/service.md) — one journal, BOTH (all) jobs restored.
            from rabit_tpu.service.service import CollectiveService

            tracker = CollectiveService(
                self.state.world or 1,
                listen_sock=self._sock,
                resume_from=self.state,
                journal=journal,
                **kwargs)
        else:
            tracker = Tracker(
                self.state.base_world or self.state.world or 1,
                listen_sock=self._sock,
                resume_from=self.state,
                journal=journal,
                **kwargs)
        with self._lock:
            tracker.events[:0] = self.events
        self.tracker = tracker
        tracker.start()
        self.promoted.set()
        if not self.quiet:
            print(f"[standby {self.standby_id}] promoted to primary at "
                  f"{self.host}:{self.port} (epoch {self.state.epoch}, "
                  f"world {self.state.world})", flush=True)
