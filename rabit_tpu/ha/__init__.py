"""HA control plane — journaled tracker state and warm-standby failover.

rabit's contract is that any *worker* can die and the job keeps going;
until this package, the job still died with its tracker — the one
process owning rank assignment, the lease table, membership epochs,
wave state, the QuorumTable, and the schedule plans (ROADMAP.md's last
single point of failure; PAPERS.md "Highly Available Data Parallel ML
training on Mesh Networks" makes the same point for TPU pods: the
control plane, not the data plane, turns a preemption into a job
loss).  Three pieces close it (doc/ha.md):

* :class:`~rabit_tpu.ha.state.ControlState` — the control plane as a
  pure replayable state machine with a CANONICAL byte snapshot;
* :class:`~rabit_tpu.ha.journal.Journal` — every mutation appended as a
  framed, crc'd, codec-tagged record (``protocol.put_journal_frame``,
  the durable store's RTC2 layout), compacted to O(live state), written
  to ``rabit_ha_journal`` and/or streamed over ``CMD_JOURNAL``;
* :class:`~rabit_tpu.ha.standby.Standby` — tails the journal, replays
  it (byte-asserted against the primary's snapshots), and takes over on
  the primary's takeover lease — workers and relays fail over
  client-side via ``rabit_tracker_addrs``, the interrupted wave
  re-forms, and the job's collectives stay bitwise identical.

"Kill the tracker mid-wave" is now just another chaos schedule
(``rabit_tpu.chaos.run_elastic_schedule(failover=...)``).
"""

from rabit_tpu.ha.journal import Journal, read_journal, replay
from rabit_tpu.ha.standby import Standby
from rabit_tpu.ha.state import ControlState

__all__ = ["ControlState", "Journal", "Standby", "read_journal", "replay"]
