"""Standalone warm-standby CLI (doc/ha.md).

    python -m rabit_tpu.ha --primary HOST:PORT [--host H] [--port P] \\
        [--journal PATH] [--takeover-sec S] [--id standby0]

Runs a :class:`~rabit_tpu.ha.standby.Standby` until it is promoted (or
killed).  Defaults come from the config layer: ``rabit_ha_journal``,
``rabit_ha_takeover_sec``, ``rabit_ha_tick_sec`` (doc/parameters.md).
Deployments that launch through ``rabit_tpu.tracker.launcher`` get the
same thing in-process via ``--standby``.
"""

from __future__ import annotations

import argparse
import sys

from rabit_tpu.config import Config
from rabit_tpu.ha.standby import Standby


def main(argv: list[str] | None = None) -> int:
    cfg = Config()
    ap = argparse.ArgumentParser(prog="rabit_tpu.ha", description=__doc__)
    ap.add_argument("--primary", required=True, metavar="HOST:PORT",
                    help="the primary tracker to tail over CMD_JOURNAL")
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--port", type=int, default=0,
                    help="the standby's advertised port (the second "
                         "rabit_tracker_addrs entry); 0 picks one")
    ap.add_argument("--journal",
                    default=cfg.get("rabit_ha_journal", "") or None,
                    help="journal file the promoted tracker writes "
                         "(default: rabit_ha_journal)")
    ap.add_argument("--takeover-sec", type=float,
                    default=float(cfg.get("rabit_ha_takeover_sec",
                                          "1.0") or "1.0"))
    ap.add_argument("--id", default="standby0")
    args = ap.parse_args(argv)
    host, _, port_s = args.primary.rpartition(":")
    standby = Standby(primary=(host, int(port_s)), host=args.host,
                      port=args.port, standby_id=args.id,
                      takeover_sec=args.takeover_sec,
                      journal=args.journal, quiet=False).start()
    print(f"[standby {args.id}] advertising {standby.host}:{standby.port} "
          f"(add it to rabit_tracker_addrs)", flush=True)
    try:
        standby.wait_promoted()
        if standby.tracker is not None:
            standby.tracker.wait()
    except KeyboardInterrupt:
        pass
    finally:
        standby.stop()
    return 0


if __name__ == "__main__":
    sys.exit(main())
