"""The control plane as a replayable state machine.

Everything the tracker must not lose with its process — rank
assignments, the membership epoch line, lease grants, the spare pool,
frozen quorum records, degraded-link flags, the planned ring — lives
here as one :class:`ControlState`, mutated ONLY by :meth:`apply`\\ ing
journal records (rabit_tpu/ha/journal.py).  The primary tracker appends
a record at every mutation point; a warm standby replays the same
records; both sides must land on the same bytes, so the representation
is deliberately boring:

* every field is plain JSON-serializable data (dicts keyed by strings,
  sorted at snapshot time) — no sockets, no clocks, no object identity;
* :meth:`snapshot_bytes` is CANONICAL (sorted keys, no whitespace), so
  "standby state == primary state" is one byte comparison — the replay
  determinism gate tests/test_ha.py enforces for arbitrary recorded
  mutation sequences;
* unknown record kinds are ignored (the ``tick`` keepalive today,
  forward compatibility tomorrow) and malformed fields are dropped
  rather than raised — a journal is evidence, and replay must recover
  whatever prefix of it is intact.

What is deliberately NOT here: lease *deadlines* (wall-clock; a
promoted tracker re-arms every journaled lease with a fresh deadline so
a worker that died during the failover window is still suspected), the
cached bootstrap-blob *bytes* (only its version — rank 0 re-ships the
blob after its next commit), and telemetry (events/metrics die with the
process; the journal records decisions, not observations).
"""

from __future__ import annotations

import json


def _qkey(a: int, b: int) -> str:
    """JSON-safe key for an (int, int) pair (epoch:version, sv:rank)."""
    return f"{int(a)}:{int(b)}"


def _unqkey(key: str) -> tuple[int, int]:
    a, _, b = key.partition(":")
    return int(a), int(b)


class ControlState:
    """One tracker's replayable control-plane state (module docstring)."""

    def __init__(self) -> None:
        self.base_world = 0
        self.world = 0
        self.epoch = -1
        self.rank_map: dict[str, int] = {}    # current epoch's assignment
        self.ranks: dict[str, int] = {}       # all-time stable ranks
        self.n_starts: dict[str, int] = {}    # CMD_START admissions per task
        self.epochs: list[list[int]] = []     # [[epoch, world], ...]
        self.leases: dict[str, list] = {}     # task -> [interval, rank]
        self.spares: list[str] = []           # parked spares, pool order
        self.blob_version = 0                 # newest cached bootstrap blob
        self.shutdown: list[str] = []         # tasks that shut down cleanly
        self.link_flags: list[list[str]] = []  # [[src_task, dst_task], ...]
        self.sched_algo = ""
        self.last_ring: list[int] = []
        # model-delivery version line (doc/delivery.md): the newest
        # published {version, epoch, digest, size}, or {} before any
        # publish.  The snapshot BYTES are deliberately not journaled —
        # the publisher re-pushes after its next commit.
        self.delivery: dict = {}
        # quorum ledgers, mirroring rabit_tpu.quorum.QuorumTable
        self.q_records: dict[str, dict] = {}       # "epoch:v" -> record
        self.q_outstanding: dict[str, int] = {}    # "sv:rank" -> world
        self.q_late_seen: list[str] = []           # "sv:rank"
        self.q_streak: dict[str, int] = {}         # str(rank) -> streak
        self.applied = 0  # records folded in (snapshot resets it too)

    # -- record application -------------------------------------------------

    def apply(self, kind: str, fields: dict) -> None:
        """Fold one journal record in.  Must stay deterministic: the
        primary's mirror and every standby replay the identical
        sequence and are byte-compared (doc/ha.md)."""
        try:
            getattr(self, f"_apply_{kind}", self._apply_ignore)(fields)
        except (KeyError, TypeError, ValueError):
            return  # a malformed record must not poison the replay
        self.applied += 1

    def _apply_ignore(self, fields: dict) -> None:
        pass  # tick keepalives, future record kinds

    def _apply_init(self, f: dict) -> None:
        self.base_world = int(f["base_world"])
        if self.world == 0:
            self.world = self.base_world

    def _apply_wave(self, f: dict) -> None:
        self.epoch = int(f["epoch"])
        self.world = int(f["world"])
        self.rank_map = {str(t): int(r) for t, r in f["rank_map"].items()}
        self.ranks.update(self.rank_map)
        for t in f.get("started", ()):
            self.n_starts[str(t)] = self.n_starts.get(str(t), 0) + 1
        gone = set(self.rank_map) | set(map(str, f.get("promoted", ())))
        self.spares = [s for s in self.spares if s not in gone]
        self.epochs.append([self.epoch, self.world])
        # the epoch boundary settles the quorum ledger by dropping, and
        # records of older epochs are pruned (QuorumTable.epoch_changed)
        self.q_outstanding = {}
        self.q_late_seen = []
        self.q_streak = {}
        self.q_records = {k: r for k, r in self.q_records.items()
                          if _unqkey(k)[0] >= self.epoch}

    def _apply_spare_park(self, f: dict) -> None:
        t = str(f["task_id"])
        self.spares = [s for s in self.spares if s != t] + [t]
        self.blob_version = max(self.blob_version,
                                int(f.get("blob_version", 0)))

    def _apply_spare_drop(self, f: dict) -> None:
        gone = set(map(str, f["task_ids"]))
        self.spares = [s for s in self.spares if s not in gone]

    def _apply_lease(self, f: dict) -> None:
        self.leases[str(f["task_id"])] = [float(f["interval"]),
                                          int(f["rank"])]

    def _apply_lease_drop(self, f: dict) -> None:
        self.leases.pop(str(f["task_id"]), None)

    def _apply_shutdown(self, f: dict) -> None:
        t = str(f["task_id"])
        if t not in self.shutdown:
            self.shutdown.append(t)
            self.shutdown.sort()
        self.leases.pop(t, None)

    def _apply_link_flag(self, f: dict) -> None:
        pair = [str(f["src"]), str(f["dst"])]
        if pair not in self.link_flags:
            self.link_flags.append(pair)
            self.link_flags.sort()

    def _apply_sched(self, f: dict) -> None:
        self.sched_algo = str(f.get("algo", ""))
        self.last_ring = [int(r) for r in f.get("ring", ())]

    def _apply_blob(self, f: dict) -> None:
        self.blob_version = max(self.blob_version, int(f["version"]))

    def _apply_snapshot_published(self, f: dict) -> None:
        line = {"version": int(f["version"]), "epoch": int(f["epoch"]),
                "digest": str(f["digest"]), "size": int(f["size"])}
        if line["version"] >= int(self.delivery.get("version", 0)):
            self.delivery = line

    def _apply_quorum_freeze(self, f: dict) -> None:
        """A round's exclusion record froze: mirror QuorumTable.report's
        decided branch (corrections retired, exclusions outstanding,
        streaks advanced)."""
        epoch, version = int(f["epoch"]), int(f["version"])
        world = int(f["world"])
        rec = dict(f["record"])
        self.q_records[_qkey(epoch, version)] = rec
        for sv, r in rec.get("corrections", ()):
            self.q_outstanding.pop(_qkey(sv, r), None)
        excluded = {int(r) for r in rec.get("excluded", ())}
        for r in sorted(excluded):
            self.q_outstanding[_qkey(version, r)] = world
        for r in range(world):
            key = str(r)
            if r in excluded:
                self.q_streak[key] = self.q_streak.get(key, 0) + 1
            else:
                self.q_streak[key] = 0

    def _apply_quorum_late(self, f: dict) -> None:
        key = _qkey(int(f["src_version"]), int(f["rank"]))
        if key not in self.q_late_seen:
            self.q_late_seen.append(key)
            self.q_late_seen.sort()

    def _apply_snapshot(self, f: dict) -> None:
        self.load_snapshot(f["state"])

    # -- snapshots ----------------------------------------------------------

    def snapshot(self) -> dict:
        """The full state as one plain JSON document (the compaction
        head record's payload, and the unit the determinism gate
        compares)."""
        return {
            "base_world": self.base_world,
            "world": self.world,
            "epoch": self.epoch,
            "rank_map": dict(self.rank_map),
            "ranks": dict(self.ranks),
            "n_starts": dict(self.n_starts),
            "epochs": [list(e) for e in self.epochs],
            "leases": {t: list(v) for t, v in self.leases.items()},
            "spares": list(self.spares),
            "blob_version": self.blob_version,
            "shutdown": sorted(self.shutdown),
            "link_flags": sorted(list(p) for p in self.link_flags),
            "sched_algo": self.sched_algo,
            "last_ring": list(self.last_ring),
            "delivery": dict(self.delivery),
            "q_records": {k: dict(r) for k, r in self.q_records.items()},
            "q_outstanding": dict(self.q_outstanding),
            "q_late_seen": sorted(self.q_late_seen),
            "q_streak": dict(self.q_streak),
        }

    def snapshot_bytes(self) -> bytes:
        """CANONICAL byte encoding of :meth:`snapshot` — sorted keys, no
        whitespace — so replay determinism is one byte comparison."""
        return json.dumps(self.snapshot(), sort_keys=True,
                          separators=(",", ":")).encode()

    def load_snapshot(self, snap: dict) -> None:
        fresh = ControlState()
        fresh.base_world = int(snap.get("base_world", 0))
        fresh.world = int(snap.get("world", 0))
        fresh.epoch = int(snap.get("epoch", -1))
        fresh.rank_map = {str(t): int(r)
                          for t, r in snap.get("rank_map", {}).items()}
        fresh.ranks = {str(t): int(r)
                       for t, r in snap.get("ranks", {}).items()}
        fresh.n_starts = {str(t): int(n)
                          for t, n in snap.get("n_starts", {}).items()}
        fresh.epochs = [[int(e), int(w)] for e, w in snap.get("epochs", ())]
        fresh.leases = {str(t): [float(v[0]), int(v[1])]
                        for t, v in snap.get("leases", {}).items()}
        fresh.spares = [str(s) for s in snap.get("spares", ())]
        fresh.blob_version = int(snap.get("blob_version", 0))
        fresh.shutdown = sorted(str(t) for t in snap.get("shutdown", ()))
        fresh.link_flags = sorted([str(a), str(b)]
                                  for a, b in snap.get("link_flags", ()))
        fresh.sched_algo = str(snap.get("sched_algo", ""))
        fresh.last_ring = [int(r) for r in snap.get("last_ring", ())]
        fresh.delivery = dict(snap.get("delivery", {}))
        fresh.q_records = {str(k): dict(r)
                           for k, r in snap.get("q_records", {}).items()}
        fresh.q_outstanding = {str(k): int(w) for k, w in
                               snap.get("q_outstanding", {}).items()}
        fresh.q_late_seen = sorted(str(k)
                                   for k in snap.get("q_late_seen", ()))
        fresh.q_streak = {str(r): int(n)
                          for r, n in snap.get("q_streak", {}).items()}
        self.__dict__.update(fresh.__dict__)

    @classmethod
    def from_snapshot(cls, snap: dict) -> "ControlState":
        state = cls()
        state.load_snapshot(snap)
        return state

    # -- derived views (what a promoted tracker seeds itself from) ----------

    def quorum_seed(self) -> dict:
        """The QuorumTable restore payload (rabit_tpu.quorum
        ``QuorumTable.seed``): frozen records plus the three ledgers, in
        the table's native key shapes."""
        return {
            "records": {_unqkey(k): dict(r)
                        for k, r in self.q_records.items()},
            "outstanding": {_unqkey(k): w
                            for k, w in self.q_outstanding.items()},
            "late_seen": {_unqkey(k) for k in self.q_late_seen},
            "streak": {int(r): n for r, n in self.q_streak.items()},
        }
