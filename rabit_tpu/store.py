"""Durable checkpoint spill — surviving WHOLE-JOB preemption.

The reference keeps checkpoints in memory only (doc/guide.md:185: a
rejoiner pulls state from surviving peers), which covers single-worker
deaths but loses everything when ALL workers die at once — exactly what a
TPU-slice preemption does.  With ``rabit_checkpoint_dir`` set, every
committed checkpoint is also written to disk (atomic rename + directory
fsync, last two versions retained), and a FRESH cluster (engine consensus
version 0) agrees on the newest version every rank can serve and resumes
from it — including serving the global blob over a broadcast to ranks
whose disk copy is missing or stale.

This sits entirely ABOVE the engine seam (rabit_tpu.api), so it works
with every backend unchanged.  The resume base version travels INSIDE the
wrapped global blob, so a worker restarted later in the resumed job
recovers the base from the peer-served blob, not from process memory.
"""

from __future__ import annotations

import os
import re
from pathlib import Path

_GLOBAL_RE = re.compile(r"^global_r(\d+)_v(\d+)\.bin$")
_KEEP = 2  # two-phase commit skews live ranks by at most one version


class CheckpointStore:
    def __init__(self, directory: str, rank: int):
        self.dir = Path(directory)
        self.rank = rank
        self.dir.mkdir(parents=True, exist_ok=True)
        # One directory scan at startup seeds the version list (and sweeps
        # tmp leftovers of crashed saves); after that, save() maintains it
        # in memory so the per-checkpoint hot path never lists the shared
        # directory — O(world^2) dirent reads per round on network
        # filesystems otherwise.
        self._versions: list[int] = []
        for p in self.dir.iterdir():
            if p.suffix == ".tmp" and f"_r{rank}_" in p.name:
                p.unlink(missing_ok=True)
            m = _GLOBAL_RE.match(p.name)
            if m and int(m.group(1)) == rank:
                self._versions.append(int(m.group(2)))
        self._versions.sort()

    # -- paths --------------------------------------------------------------

    def _gpath(self, version: int) -> Path:
        return self.dir / f"global_r{self.rank}_v{version}.bin"

    def _lpath(self, version: int) -> Path:
        return self.dir / f"local_r{self.rank}_v{version}.bin"

    # -- writes -------------------------------------------------------------

    def save(self, version: int, gblob: bytes, lblob: bytes | None) -> None:
        """Persist one committed checkpoint atomically; prune old versions."""
        self._write(self._gpath(version), gblob)
        if lblob is not None:
            self._write(self._lpath(version), lblob)
        if version not in self._versions:
            self._versions.append(version)
            self._versions.sort()
        while len(self._versions) > _KEEP:
            v = self._versions.pop(0)
            self._gpath(v).unlink(missing_ok=True)
            self._lpath(v).unlink(missing_ok=True)

    def _write(self, path: Path, blob: bytes) -> None:
        tmp = path.with_suffix(".tmp")
        with open(tmp, "wb") as f:
            f.write(blob)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)  # atomic: readers see old or new, never torn
        # The rename itself must survive a host crash too — fsync the
        # directory entry, or the "durable" newest version can vanish on
        # power loss while the prune of the older one persisted.
        dfd = os.open(self.dir, os.O_RDONLY)
        try:
            os.fsync(dfd)
        finally:
            os.close(dfd)

    # -- reads --------------------------------------------------------------

    def versions(self) -> list[int]:
        """This rank's persisted versions, ascending."""
        return list(self._versions)

    def latest(self) -> int:
        return self._versions[-1] if self._versions else 0

    def has(self, version: int) -> bool:
        return version > 0 and self._gpath(version).exists()

    def load_global(self, version: int) -> bytes:
        return self._gpath(version).read_bytes()

    def load_local(self, version: int) -> bytes | None:
        p = self._lpath(version)
        return p.read_bytes() if p.exists() else None
