"""Durable checkpoint spill — surviving WHOLE-JOB preemption.

The reference keeps checkpoints in memory only (doc/guide.md:185: a
rejoiner pulls state from surviving peers), which covers single-worker
deaths but loses everything when ALL workers die at once — exactly what a
TPU-slice preemption does.  With ``rabit_checkpoint_dir`` set, every
committed checkpoint is also written to disk (atomic rename + directory
fsync, last two versions retained), and a FRESH cluster (engine consensus
version 0) agrees on the newest version every rank can serve and resumes
from it — including serving the global blob over a broadcast to ranks
whose disk copy is missing or stale.

This sits entirely ABOVE the engine seam (rabit_tpu.api), so it works
with every backend unchanged.  The resume base version travels INSIDE the
wrapped global blob, so a worker restarted later in the resumed job
recovers the base from the peer-served blob, not from process memory.
"""

from __future__ import annotations

import os
import re
import struct
import zlib
from pathlib import Path

_GLOBAL_RE = re.compile(r"^global_r(\d+)_v(\d+)\.bin$")
_KEEP = 2  # two-phase commit skews live ranks by at most one version
# (the default; rabit_checkpoint_keep raises it — a deeper window for
# slow consumers of the delivery plane, doc/delivery.md)
# File layout: magic + crc32 + payload length, then the payload.  A file
# that fails the check (torn by a crash the rename protocol could not
# cover, or bit-rotted) reads as ABSENT, so resume degrades to an older
# version or the holder-broadcast path instead of crashing on garbage.
#
# Three frame generations: RTC1 (uncompressed payload), RTC2, which adds
# a codec byte (rabit_tpu.compress ids) so spilled blobs land compressed
# (rabit_checkpoint_compress, default zlib), and RTC3, which additionally
# records the WORLD EPOCH (rabit_tpu.elastic) the committing membership
# generation held — so a resume can tell which world size produced each
# version and replay stays deterministic across an elastic resize.  The
# crc covers the ENCODED payload — integrity is checked before any decode
# touches the bytes — and older frames stay readable forever.  RTC3 is
# written only when a nonzero epoch is recorded; epoch-0 jobs keep
# emitting the bytes-identical RTC1/RTC2 frames older readers know.
_MAGIC = b"RTC1"
_HDR = struct.Struct("<4sII")
_MAGIC2 = b"RTC2"
_HDR2 = struct.Struct("<4sBxxxII")  # magic, codec id, pad, crc, enc len
_MAGIC3 = b"RTC3"
_HDR3 = struct.Struct("<4sBxxxIII")  # ..., crc, enc len, world epoch


class CheckpointStore:
    def __init__(self, directory: str, rank: int, codec: str = "zlib",
                 keep: int | None = None):
        from rabit_tpu.compress import get_codec
        from rabit_tpu.config import Config

        self.dir = Path(directory)
        self.rank = rank
        self._codec = None if codec in ("", "identity") else get_codec(codec)
        # Retention window (rabit_checkpoint_keep): versions beyond the
        # newest ``keep`` prune after each successful commit — without
        # it the store directory grows one file pair per commit forever.
        if keep is None:
            keep = Config().get_int("rabit_checkpoint_keep", _KEEP)
        self._keep = max(int(keep), 1)
        # Pinned versions survive pruning regardless of age: the
        # delivery plane pins the latest PUBLISHED version so a
        # subscriber's fetch-in-flight never loses its bytes to a
        # concurrent commit (doc/delivery.md).
        self._pinned: set[int] = set()
        self.dir.mkdir(parents=True, exist_ok=True)
        # One directory scan at startup seeds the version list (and sweeps
        # tmp leftovers of crashed saves); after that, save() maintains it
        # in memory so the per-checkpoint hot path never lists the shared
        # directory — O(world^2) dirent reads per round on network
        # filesystems otherwise.
        self._versions: list[int] = []
        self._cache: dict[Path, bytes] = {}  # verified payloads by path
        for p in self.dir.iterdir():
            if p.suffix == ".tmp" and f"_r{rank}_" in p.name:
                p.unlink(missing_ok=True)
            m = _GLOBAL_RE.match(p.name)
            if m and int(m.group(1)) == rank:
                self._versions.append(int(m.group(2)))
        self._versions.sort()

    # -- paths --------------------------------------------------------------

    def _gpath(self, version: int) -> Path:
        return self.dir / f"global_r{self.rank}_v{version}.bin"

    def _lpath(self, version: int) -> Path:
        return self.dir / f"local_r{self.rank}_v{version}.bin"

    # -- writes -------------------------------------------------------------

    def save(self, version: int, gblob: bytes, lblob: bytes | None,
             epoch: int = 0) -> None:
        """Persist one committed checkpoint atomically; prune old versions.
        A nonzero ``epoch`` (elastic worlds) is recorded in the frame
        header (RTC3) and read back by :meth:`epoch_of`."""
        self._write(self._gpath(version), gblob, epoch=epoch)
        if lblob is not None:
            self._write(self._lpath(version), lblob, epoch=epoch)
        if version not in self._versions:
            self._versions.append(version)
            self._versions.sort()
        self._prune()

    def pin(self, version: int) -> None:
        """Exempt ``version`` from pruning (and release every older
        pin): the delivery plane pins the latest published version so a
        fetch-in-flight never loses its bytes (doc/delivery.md)."""
        self._pinned = {v for v in self._pinned if v > version}
        self._pinned.add(version)
        self._prune()

    def _prune(self) -> None:
        unpinned = [v for v in self._versions if v not in self._pinned]
        while len(unpinned) > self._keep:
            v = unpinned.pop(0)
            self._versions.remove(v)
            for p in (self._gpath(v), self._lpath(v)):
                p.unlink(missing_ok=True)
                self._cache.pop(p, None)

    def _write(self, path: Path, blob: bytes, epoch: int = 0) -> None:
        if epoch > 0:
            # Elastic job: the frame carries the committing world epoch.
            # Codec id 0 (identity) keeps the layout uniform when the
            # store is configured uncompressed.
            codec_id, payload = 0, blob
            if self._codec is not None:
                from rabit_tpu.compress import observe

                payload = self._codec.encode_bytes(blob)
                observe(self._codec.name, raw=len(blob), wire=len(payload))
                codec_id = self._codec.codec_id
            header = _HDR3.pack(_MAGIC3, codec_id, zlib.crc32(payload),
                                len(payload), epoch)
        elif self._codec is None:
            header, payload = _HDR.pack(_MAGIC, zlib.crc32(blob),
                                        len(blob)), blob
        else:
            from rabit_tpu.compress import observe

            payload = self._codec.encode_bytes(blob)
            observe(self._codec.name, raw=len(blob), wire=len(payload))
            header = _HDR2.pack(_MAGIC2, self._codec.codec_id,
                                zlib.crc32(payload), len(payload))
        tmp = path.with_suffix(".tmp")
        with open(tmp, "wb") as f:
            f.write(header)
            f.write(payload)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)  # atomic: readers see old or new, never torn
        self._cache[path] = blob
        # The rename itself must survive a host crash too — fsync the
        # directory entry, or the "durable" newest version can vanish on
        # power loss while the prune of the older one persisted.
        dfd = os.open(self.dir, os.O_RDONLY)
        try:
            os.fsync(dfd)
        finally:
            os.close(dfd)

    # -- reads --------------------------------------------------------------

    def versions(self) -> list[int]:
        """This rank's persisted versions, ascending."""
        return list(self._versions)

    def latest_valid(self) -> int:
        """Newest version whose global blob passes the integrity check —
        what this rank may truthfully advertise to the resume consensus
        (advertising a corrupt file could elect an unservable vmax)."""
        for v in reversed(self._versions):
            if self.has(v):
                return v
        return 0

    def _read_checked(self, path: Path) -> bytes | None:
        """The DECODED payload, or None when missing/torn/corrupt.
        Verified reads are memoized so the resume path (latest_valid ->
        has -> load) does not re-read multi-MB blobs; writes/prunes keep
        the memo fresh.  Both frame generations read back: RTC2 carries a
        codec byte (decode after the crc passes), RTC1 is the legacy
        uncompressed layout — a new job resumes an old job's spill
        unchanged."""
        if path in self._cache:
            return self._cache[path]
        try:
            raw = path.read_bytes()
        except FileNotFoundError:
            return None
        blob: bytes | None = None
        if len(raw) >= _HDR3.size and raw[:4] == _MAGIC3:
            _magic, codec_id, crc, n, _epoch = _HDR3.unpack_from(raw)
            enc = raw[_HDR3.size:]
            if len(enc) == n and zlib.crc32(enc) == crc:
                from rabit_tpu.compress import get_codec_by_id

                try:
                    blob = get_codec_by_id(codec_id).decode_bytes(enc)
                except (ValueError, zlib.error):
                    blob = None
        elif len(raw) >= _HDR2.size and raw[:4] == _MAGIC2:
            _magic, codec_id, crc, n = _HDR2.unpack_from(raw)
            enc = raw[_HDR2.size:]
            if len(enc) == n and zlib.crc32(enc) == crc:
                from rabit_tpu.compress import get_codec_by_id

                try:
                    blob = get_codec_by_id(codec_id).decode_bytes(enc)
                except (ValueError, zlib.error):
                    blob = None  # unknown codec / stream the crc cannot vouch for
        elif len(raw) >= _HDR.size and raw[:4] == _MAGIC:
            magic, crc, n = _HDR.unpack_from(raw)
            payload = raw[_HDR.size:]
            if len(payload) == n and zlib.crc32(payload) == crc:
                blob = payload
        if blob is None:
            print(f"[rabit_tpu] checkpoint store: ignoring unreadable blob "
                  f"{path} (missing/invalid RTC1/RTC2 header or crc "
                  f"mismatch)", flush=True)
            return None
        self._cache[path] = blob
        return blob

    def epoch_of(self, version: int) -> int:
        """World epoch recorded in the version's global frame (RTC3), 0
        for pre-elastic frames (RTC1/RTC2) or missing/torn files — the
        resume path uses it to tell which membership generation committed
        each version."""
        try:
            with open(self._gpath(version), "rb") as f:
                head = f.read(_HDR3.size)
        except OSError:
            return 0
        if len(head) >= _HDR3.size and head[:4] == _MAGIC3:
            return _HDR3.unpack_from(head)[4]
        return 0

    def has(self, version: int) -> bool:
        """True only for a version whose global blob passes the integrity
        check — the resume consensus must not promise bytes it cannot
        serve."""
        return version > 0 and self._read_checked(self._gpath(version)) is not None

    def load_global(self, version: int) -> bytes:
        blob = self._read_checked(self._gpath(version))
        if blob is None:
            raise RuntimeError(
                f"checkpoint store: global v{version} for rank {self.rank} "
                f"is missing or corrupt ({self._gpath(version)})"
            )
        return blob

    def load_local(self, version: int) -> bytes | None:
        return self._read_checked(self._lpath(version))
