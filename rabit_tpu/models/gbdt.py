"""Histogram gradient-boosted decision trees — the flagship workload.

Distributed XGBoost's histogram aggregation is the workload rabit exists for
(reference doc/guide.md:130-140: each worker builds per-feature gradient
histograms over its data shard and Allreduces them every tree level;
BASELINE.json: "XGBoost hist tree_method gradient-histogram allreduce").
This module is that workload rebuilt TPU-first:

* features are quantized to ``n_bins`` integer bins once, up front;
* every boosting round grows one depth-``D`` tree level-wise; per level the
  (node, feature, bin) gradient/hessian histograms are one ``segment_sum``
  — a static-shape scatter-add XLA maps onto the TPU — and ONE fused
  ``psum`` across the data-parallel mesh axis (the rabit Allreduce);
* histogram work is additionally shardable across a feature-parallel mesh
  axis: each position histograms its feature slice, then one
  ``all_gather`` reassembles — 2-D (dp, fp) parallelism;
* everything is jit-compiled with static shapes: the level loop is unrolled
  (depth is a compile-time constant), rows carry a node index updated by
  gathers, no data-dependent control flow.

The functional core (``train_round``, ``predict``) is pure and shardable;
``GBDT`` wraps it for host numpy users, including the rabit-classic
deployment where each process holds a shard and histograms are combined
with ``engine.allreduce`` over the native TCP engine.
"""

from __future__ import annotations

import functools
from typing import Any, Callable, NamedTuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax


class GBDTConfig(NamedTuple):
    """Static hyperparameters (hashable: usable as a jit static arg)."""

    n_features: int
    n_trees: int = 20
    depth: int = 6
    n_bins: int = 256
    learning_rate: float = 0.3
    reg_lambda: float = 1.0
    min_child_weight: float = 1.0
    objective: str = "logistic"  # "logistic" | "squared"
    # Run the histogram contraction at int8 MXU rate (2x bf16 on
    # v5e-class chips) via a two-plane fixed-point split of the gradient
    # matrix; ~2^-14-of-block-max round-off (ops/boost.py _encode_i8)
    # vs ~2^-16-relative for the
    # default hi/lo-bf16 split.  Honored by every TPU Pallas dispatch —
    # fused and hook-based rounds alike; non-TPU backends (exact-f32
    # scatter) ignore it.
    mxu_i8: bool = False
    # Final leaf pass of train_round_fused: True runs the fused Pallas
    # route+margin kernel (ops/boost.py route_margin_level); False runs
    # the routing-only kernel and leaves ``margin += leaf[node]`` to XLA
    # (a 1M-row gather from a 2**depth-entry table).  Both are exact.
    # The round-5 whole-round on-chip measurements decided the default:
    # XLA-final won in BOTH MXU modes and in three independent runs
    # (73.8 vs 78.1 ms bf16, 77.3 vs 78.7 ms i8 — RESULTS/final_pass.jsonl;
    # 70.0/70.1 vs 74.0/72.8 ms in the driver-bench races), so False is
    # the measured default and the fused kernel stays as the challenger
    # bench.py re-races each capture.
    fused_final: bool = False
    # Split each row block into this many independent sub-contractions in
    # the level kernels' histogram accumulation (ops/boost.py _accum):
    # sub-block i's MXU matmul has no dependency on sub-block i+1's VPU
    # indicator build, giving Mosaic explicit overlap room (the measured
    # VPU/MXU co-dominance headroom, RESULTS.md §1).  Must divide the row
    # block (1024); results are added in f32.  Default 1 = current
    # single-contraction form; >1 is the on-chip ablation's experiment.
    r_split: int = 1


class Forest(NamedTuple):
    """A stack of perfect binary trees in level order.

    ``feature``/``threshold``: [n_trees, depth, 2**(depth-1)] — level d of a
    tree uses the first 2**d entries; thresholds are bin ids (go right when
    ``bin > threshold``).  ``leaf``: [n_trees, 2**depth] leaf weights.
    Untrained trees are all-zero and contribute nothing to predictions.
    """

    feature: jax.Array
    threshold: jax.Array
    leaf: jax.Array


class TrainState(NamedTuple):
    forest: Forest
    margin: jax.Array  # [rows_this_shard] current boosting margin
    round: jax.Array   # scalar int32: trees built so far


def init_forest(cfg: GBDTConfig) -> Forest:
    max_nodes = 2 ** (cfg.depth - 1)
    return Forest(
        feature=jnp.zeros((cfg.n_trees, cfg.depth, max_nodes), jnp.int32),
        threshold=jnp.zeros((cfg.n_trees, cfg.depth, max_nodes), jnp.int32),
        leaf=jnp.zeros((cfg.n_trees, 2 ** cfg.depth), jnp.float32),
    )


def init_state(cfg: GBDTConfig, n_rows: int) -> TrainState:
    return TrainState(
        forest=init_forest(cfg),
        margin=jnp.zeros(n_rows, jnp.float32),
        round=jnp.zeros((), jnp.int32),
    )


# -- quantization ----------------------------------------------------------


def compute_bin_edges(X: np.ndarray, n_bins: int) -> np.ndarray:
    """Per-feature quantile cut points, [n_features, n_bins - 1] (host-side,
    once per dataset — the 'sketch' phase of hist tree_method)."""
    qs = np.linspace(0.0, 1.0, n_bins + 1)[1:-1]
    return np.quantile(np.asarray(X, np.float32), qs, axis=0).T.astype(np.float32)


def quantize(X: jax.Array, edges: jax.Array) -> jax.Array:
    """Map features to integer bins in [0, n_bins): bin = #edges <= x."""
    find = lambda col, e: jnp.searchsorted(e, col, side="right")
    return jax.vmap(find, in_axes=(1, 0), out_axes=1)(X, edges).astype(jnp.int32)


# -- gradients -------------------------------------------------------------


def gradients(cfg: GBDTConfig, margin: jax.Array, y: jax.Array):
    if cfg.objective == "logistic":
        p = jax.nn.sigmoid(margin)
        return p - y, p * (1.0 - p)
    if cfg.objective == "squared":
        return margin - y, jnp.ones_like(margin)
    raise ValueError(f"unknown objective {cfg.objective}")


# -- histograms (the hot op) ----------------------------------------------


def node_histograms(
    xb: jax.Array, g: jax.Array, h: jax.Array, node: jax.Array,
    n_nodes: int, n_bins: int, mxu_i8: bool = False
) -> jax.Array:
    """Per-(node, feature, bin) gradient/hessian sums: [n_nodes, F, B, 2].

    Dispatches to the backend-appropriate kernel in ``rabit_tpu.ops.hist``:
    a Pallas MXU one-hot-contraction kernel on TPU (~17x the scatter-add
    path; int8-rate variant under ``mxu_i8``), exact-f32 segment_sum
    elsewhere.  This is the TPU-native form of the reference workload's
    per-level histogram build (doc/guide.md:130-140).
    """
    from rabit_tpu.ops import hist as _hist

    return _hist.node_histograms(xb, g, h, node, n_nodes, n_bins,
                                 mxu_i8=mxu_i8)


def best_splits(hist: jax.Array, cfg: GBDTConfig):
    """Best (feature, bin, gain) per node from summed histograms.

    Standard XGBoost gain: GL^2/(HL+λ) + GR^2/(HR+λ) − G^2/(H+λ), split
    candidates are 'bin <= b goes left', invalid when either side's hessian
    mass is under min_child_weight.
    """
    g, h = hist[..., 0], hist[..., 1]            # [nodes, F, B]
    GL, HL = jnp.cumsum(g, -1), jnp.cumsum(h, -1)
    G, H = GL[..., -1:], HL[..., -1:]
    GR, HR = G - GL, H - HL
    score = lambda a, b: a * a / (b + cfg.reg_lambda)
    gain = score(GL, HL) + score(GR, HR) - score(G, H)
    valid = (HL >= cfg.min_child_weight) & (HR >= cfg.min_child_weight)
    gain = jnp.where(valid, gain, -jnp.inf)
    flat = gain.reshape(gain.shape[0], -1)
    best = jnp.argmax(flat, axis=-1)
    best_gain = jnp.take_along_axis(flat, best[:, None], -1)[:, 0]
    n_bins = hist.shape[2]
    return (
        (best // n_bins).astype(jnp.int32),
        (best % n_bins).astype(jnp.int32),
        best_gain,
    )


def split_child_masses(hist: jax.Array, feat: jax.Array, thr: jax.Array) -> jax.Array:
    """Leaf (g, h) masses read off the parent histogram at the chosen split
    — XGBoost's histogram identity (children sums = split cumsums), so the
    leaf fit needs no extra row pass over the data.  ``hist`` is the final
    level's COMBINED [n_nodes, F, B, 2] histogram; returns [2*n_nodes, 2]
    interleaved (left_0, right_0, left_1, right_1, ...) in leaf order
    (leaf = 2*node + went_right)."""
    g, h = hist[..., 0], hist[..., 1]                  # [nodes, F, B]
    GL, HL = jnp.cumsum(g, -1), jnp.cumsum(h, -1)
    G, H = GL[..., -1], HL[..., -1]                    # [nodes, F]
    n_nodes = hist.shape[0]
    rows = jnp.arange(n_nodes)
    gl = GL[rows, feat, thr]
    hl = HL[rows, feat, thr]
    gt = G[rows, feat]
    ht = H[rows, feat]
    left = jnp.stack([gl, hl], -1)                     # [nodes, 2]
    right = jnp.stack([gt - gl, ht - hl], -1)
    return jnp.stack([left, right], axis=1).reshape(2 * n_nodes, 2)


# -- training --------------------------------------------------------------


def _hist_local(xb, g, h, node, n_nodes, n_bins, mxu_i8=False):
    return node_histograms(xb, g, h, node, n_nodes, n_bins, mxu_i8=mxu_i8)


def train_round(
    state: TrainState,
    xb: jax.Array,
    y: jax.Array,
    cfg: GBDTConfig,
    hist_fn: Callable[..., jax.Array] | None = None,
    combine_leaf: Callable[[jax.Array], jax.Array] = lambda gh: gh,
) -> TrainState:
    """Grow one tree on (this shard of) the data and append it to the forest.

    ``hist_fn(xb, g, h, node, n_nodes, n_bins) -> [n_nodes, F, B, 2]`` is
    the histogram-build-and-allreduce hook: plain local histograms for
    single-shard training; histograms + ``lax.psum`` over the dp axis inside
    shard_map; a feature-sliced build + psum + all_gather for 2-D (dp, fp);
    or an engine.allreduce callback in the rabit-classic multi-process
    deployment.  These hooks are the ONLY communication points — exactly the
    reference workload's Allreduce placement (doc/guide.md:130-140).
    """
    if hist_fn is None:
        hist_fn = functools.partial(_hist_local, mxu_i8=cfg.mxu_i8)
    n, F = xb.shape
    max_nodes = 2 ** (cfg.depth - 1)
    g, h = gradients(cfg, state.margin, y)
    node = jnp.zeros(n, jnp.int32)
    feats, thrs = [], []
    for d in range(cfg.depth):
        n_nodes = 2 ** d
        hist = hist_fn(xb, g, h, node, n_nodes, cfg.n_bins)
        feat, thr, _gain = best_splits(hist, cfg)
        feats.append(jnp.zeros(max_nodes, jnp.int32).at[:n_nodes].set(feat))
        thrs.append(jnp.zeros(max_nodes, jnp.int32).at[:n_nodes].set(thr))
        # Route every row one level down: right iff bin > threshold.
        fsel = feat[node]                                        # [n]
        xv = jnp.take_along_axis(xb, fsel[:, None], 1)[:, 0]
        node = node * 2 + (xv > thr[node]).astype(jnp.int32)
    # Leaf weights from summed per-leaf gradient mass.
    from rabit_tpu.ops import hist as _hist

    n_leaves = 2 ** cfg.depth
    leaf_gh = _hist.segment_sum(jnp.stack([g, h], -1), node, n_leaves)
    leaf_gh = combine_leaf(leaf_gh)  # [n_leaves, 2] allreduce
    leaf = -cfg.learning_rate * leaf_gh[:, 0] / (leaf_gh[:, 1] + cfg.reg_lambda)
    margin = state.margin + leaf[node]
    t = state.round
    forest = Forest(
        feature=lax.dynamic_update_index_in_dim(
            state.forest.feature, jnp.stack(feats), t, 0
        ),
        threshold=lax.dynamic_update_index_in_dim(
            state.forest.threshold, jnp.stack(thrs), t, 0
        ),
        leaf=lax.dynamic_update_index_in_dim(state.forest.leaf, leaf, t, 0),
    )
    return TrainState(forest=forest, margin=margin, round=t + 1)


def train_round_dp(state, xb, y, cfg, dp_axis: str = "dp", fp_axis: str | None = None):
    """train_round wired for shard_map: rows sharded over ``dp_axis``; when
    ``fp_axis`` is given (rows replicated across it), each fp position
    histograms only its F/fp feature slice — the compute splits — then one
    psum over dp and one all_gather over fp reassemble the global
    histogram."""
    if fp_axis is None:
        hist_fn = lambda xb, g, h, node, n_nodes, n_bins: lax.psum(
            node_histograms(xb, g, h, node, n_nodes, n_bins,
                            mxu_i8=cfg.mxu_i8), dp_axis
        )
        combine_leaf = lambda gh: lax.psum(gh, dp_axis)
    else:
        fp_size = lax.axis_size(fp_axis)
        f_local = cfg.n_features // fp_size
        fp_idx = lax.axis_index(fp_axis)

        def hist_fn(xb, g, h, node, n_nodes, n_bins):
            x_slice = lax.dynamic_slice_in_dim(xb, fp_idx * f_local, f_local, 1)
            sl = node_histograms(x_slice, g, h, node, n_nodes, n_bins,
                                 mxu_i8=cfg.mxu_i8)
            sl = lax.psum(sl, dp_axis)
            return lax.all_gather(sl, fp_axis, axis=1, tiled=True)

        # every fp copy sees the same rows: reduce leaves over dp only.
        combine_leaf = lambda gh: lax.psum(gh, dp_axis)
    return train_round(state, xb, y, cfg, hist_fn, combine_leaf)


def train_round_fused(
    state: TrainState,
    xb3: jax.Array,
    y: jax.Array,
    cfg: GBDTConfig,
    combine: Callable[[jax.Array], jax.Array] = lambda x: x,
    interpret: bool = False,
) -> TrainState:
    """One boosting round via the fused Pallas kernels (ops.boost): routing,
    split lookup, and histogram accumulation run in one streaming pass per
    level, so rows cross HBM depth+1 times per round (depth histogram
    passes + one routing-only leaf pass) instead of ~3x depth.

    ``xb3`` is the pre-blocked quantized matrix from ``ops.boost.block_rows``
    (built once per fit).  ``combine`` is the histogram allreduce hook
    (one call per level; leaf masses derive from the last combined
    histogram via split_child_masses, so there is no leaf collective)
    (e.g. ``lambda a: lax.psum(a, 'dp')`` under shard_map) — the same single
    communication point per level as the reference workload.
    """
    from rabit_tpu.ops import boost

    n = y.shape[0]
    block = xb3.shape[1]  # row-block size is fixed by how xb3 was blocked
    max_nodes = 2 ** (cfg.depth - 1)
    g, h = gradients(cfg, state.margin, y)
    g3, _ = boost.block_rows(g, block)
    h3, _ = boost.block_rows(h, block)
    if g3.shape[0] != xb3.shape[0]:
        raise ValueError(
            f"train_round_fused: {n} rows block into {g3.shape[0]} blocks of "
            f"{block}, but xb3 has {xb3.shape[0]} blocks — a dp shard's row "
            "count must match its pre-blocked feature matrix or rows would be "
            "silently mispaired with gradients"
        )

    hist = combine(boost.hist_level0(xb3, g3, h3, n_bins=cfg.n_bins,
                                     interpret=interpret, mxu_i8=cfg.mxu_i8,
                                     r_split=cfg.r_split))
    feat, thr, _ = best_splits(hist, cfg)
    feats = [jnp.zeros(max_nodes, jnp.int32).at[:1].set(feat)]
    thrs = [jnp.zeros(max_nodes, jnp.int32).at[:1].set(thr)]
    node3 = jnp.zeros_like(g3, shape=g3.shape, dtype=jnp.int32)
    for d in range(1, cfg.depth):
        hist, node3 = boost.hist_level(xb3, node3, g3, h3, feat, thr,
                                       depth=d, n_bins=cfg.n_bins,
                                       interpret=interpret,
                                       mxu_i8=cfg.mxu_i8,
                                       r_split=cfg.r_split)
        hist = combine(hist)
        feat, thr, _ = best_splits(hist, cfg)
        feats.append(jnp.zeros(max_nodes, jnp.int32).at[: 2 ** d].set(feat))
        thrs.append(jnp.zeros(max_nodes, jnp.int32).at[: 2 ** d].set(thr))
    # Leaf (g, h) masses come straight off the final combined histogram
    # (split_child_masses) — already globally reduced, so no leaf collective
    # and no histogram work in the last row pass (depth collectives per
    # round, not depth+1).  The last pass routes rows to their leaves and
    # applies ``margin += leaf[node]`` either inside one fused kernel
    # (cfg.fused_final) or as a routing kernel plus an XLA gather from the
    # 2**depth-entry leaf table — the gather form measured faster
    # whole-round in both MXU modes and is the default; see the
    # GBDTConfig.fused_final docstring (RESULTS/final_pass.jsonl).
    leaf_gh = split_child_masses(hist, feat, thr)
    leaf = -cfg.learning_rate * leaf_gh[:, 0] / (leaf_gh[:, 1] + cfg.reg_lambda)
    if cfg.fused_final:
        margin3, _ = boost.block_rows(state.margin, block)
        margin3, _node3 = boost.route_margin_level(
            xb3, node3, margin3, feat, thr, leaf, depth=cfg.depth,
            interpret=interpret)
        margin = boost.unblock_rows(margin3, n)
    else:
        node3 = boost.route_level(xb3, node3, feat, thr, depth=cfg.depth,
                                  interpret=interpret)
        margin = state.margin + leaf[boost.unblock_rows(node3, n)]
    t = state.round
    forest = Forest(
        feature=lax.dynamic_update_index_in_dim(
            state.forest.feature, jnp.stack(feats), t, 0
        ),
        threshold=lax.dynamic_update_index_in_dim(
            state.forest.threshold, jnp.stack(thrs), t, 0
        ),
        leaf=lax.dynamic_update_index_in_dim(state.forest.leaf, leaf, t, 0),
    )
    return TrainState(forest=forest, margin=margin, round=t + 1)


def train_round_hybrid(
    state: TrainState,
    xb: jax.Array,
    y: jax.Array,
    cfg: GBDTConfig,
    mesh=None,
    dp_axis: str = "dp",
    engine_allreduce: Callable[[np.ndarray], np.ndarray] | None = None,
) -> TrainState:
    """One boosting round for the HYBRID deployment: XLA data plane married
    to the fault-tolerant native engine (the reference's recovery seam,
    allreduce_robust.cc:687-725, which round-2's review named the last
    first-order gap).

    The whole round is ONE jitted XLA program: per level, local histograms
    are built under ``shard_map`` with an in-graph ``psum`` over the
    intra-host device mesh, and the cross-worker hop crosses the robust
    TCP engine through a host callback.  The callbacks are ordered by data
    dependence — level d's combined histogram feeds level d+1's routing —
    so every worker issues the identical deterministic collective sequence,
    which is exactly what lets the robust engine's replay log serve
    byte-identical results to a worker recovering mid-round.

    ``engine_allreduce`` is a host fn ``np.ndarray -> np.ndarray`` (e.g.
    ``lambda a: rabit_tpu.allreduce(a, rt.SUM)``); None means solo (the
    callback is omitted entirely, keeping the program pure for dryruns).
    """

    def cross(a: jax.Array, tag: int) -> jax.Array:
        if engine_allreduce is None:
            return a
        # `tag` is a per-call-site constant operand: two levels of one
        # round can produce IDENTICAL histograms (degenerate shards), and
        # pure_callback's contract would let XLA CSE the two "pure" calls
        # into one host call — desynchronizing the engine's collective
        # sequence across workers.  Distinct constant operands make the
        # calls distinct HLO ops, so each level's engine hop always fires.
        # (io_callback(ordered=True) would be the canonical primitive, but
        # XLA's SPMD partitioner rejects side-effecting ops with the
        # replicated shardings this program needs.)
        return jax.pure_callback(
            lambda x, _t: np.asarray(engine_allreduce(np.asarray(x)), dtype=x.dtype),
            jax.ShapeDtypeStruct(a.shape, a.dtype),
            a,
            np.int32(tag),
        )

    if mesh is None:
        hist_fn = lambda xb_, g, h, node, nn, nb: cross(
            node_histograms(xb_, g, h, node, nn, nb, mxu_i8=cfg.mxu_i8), nn
        )
    else:
        from jax.sharding import PartitionSpec as P

        def hist_fn(xb_, g, h, node, nn, nb):
            local = jax.shard_map(
                lambda a, b, c, d: lax.psum(
                    node_histograms(a, b, c, d, nn, nb, mxu_i8=cfg.mxu_i8),
                    dp_axis
                ),
                mesh=mesh,
                in_specs=(P(dp_axis, None), P(dp_axis), P(dp_axis), P(dp_axis)),
                out_specs=P(),
                check_vma=False,
            )(xb_, g, h, node)
            return cross(local, nn)  # nn = 2**level: unique per level

    return train_round(state, xb, y, cfg, hist_fn,
                       functools.partial(cross, tag=-1))


def train_round_dp_fused(state, xb3, y, cfg, dp_axis: str = "dp",
                         interpret: bool = False, wire_i8: bool = False,
                         wire_block: int = 256):
    """train_round_fused wired for shard_map: row blocks sharded over
    ``dp_axis`` (shard xb3 on its leading block dim, margin/y on rows); one
    psum per tree level (leaf masses ride the last one) — communication
    placement to train_round_dp, with the fused kernels doing the local
    work.

    ``wire_i8=True`` ships each level's histogram allreduce over the
    quantized int8-wire ring (parallel.ring_allreduce_quantized, ~2x fewer
    ICI/DCN bytes at ~2^-16-of-block-max accuracy per hop) instead of
    ``lax.psum`` — the bandwidth-bound-regime option for large
    feature x bin spaces or DCN-crossing dp axes.  Lossy but structurally
    rank-consistent: every rank (owner included) decodes each chunk's
    identical wire bytes at the identical program point, so the reduced
    histograms — and hence best_splits argmax decisions, even on exact
    ties — are bitwise identical across ranks; the forests cannot
    silently diverge.  Keep exact psum where results must also be
    byte-identical to a serial replay (the robust replay contract).
    Requires the flattened per-level histogram (2^d * F * n_bins * 2
    floats) divisible by dp_size * wire_block."""
    if wire_i8:
        from rabit_tpu.parallel import ring_allreduce_quantized

        def combine(a):
            return ring_allreduce_quantized(
                a.reshape(-1), dp_axis, block=wire_block).reshape(a.shape)
    else:
        combine = lambda a: lax.psum(a, dp_axis)
    return train_round_fused(state, xb3, y, cfg, combine=combine,
                             interpret=interpret)


# -- prediction ------------------------------------------------------------


def predict_margin(forest: Forest, xb: jax.Array, cfg: GBDTConfig) -> jax.Array:
    """Sum of leaf values over all trees; [n].  Untrained (zero) trees
    contribute 0, so this is valid mid-training."""
    n = xb.shape[0]

    def one_tree(margin, tree):
        feature, threshold, leaf = tree
        pos = jnp.zeros(n, jnp.int32)
        for d in range(cfg.depth):
            f = feature[d][pos]
            thr = threshold[d][pos]
            xv = jnp.take_along_axis(xb, f[:, None], 1)[:, 0]
            pos = pos * 2 + (xv > thr).astype(jnp.int32)
        return margin + leaf[pos], None

    margin, _ = lax.scan(one_tree, jnp.zeros(n, jnp.float32), forest)
    return margin


def predict_proba(forest: Forest, xb: jax.Array, cfg: GBDTConfig) -> jax.Array:
    return jax.nn.sigmoid(predict_margin(forest, xb, cfg))


# -- elastic sharding -------------------------------------------------------


def elastic_shard(X: np.ndarray, y: np.ndarray, world: int,
                  rank: int) -> tuple[np.ndarray, np.ndarray]:
    """This rank's rows of the FULL dataset under the elastic dense
    partition (rabit_tpu.elastic.rebalance) — the shard-rebalance hook of
    the histogram deployment.  When the world resizes, every surviving
    rank re-cuts with the new ``(world, rank)`` and the per-shard
    histogram sums keep covering the whole dataset around the hole; wire
    it to ``rabit_tpu.api.register_rebalance`` so the re-cut runs at every
    adopted epoch (doc/elasticity.md)."""
    from rabit_tpu.elastic.rebalance import shard_slice

    sl = shard_slice(len(X), world, rank)
    return X[sl], y[sl]


# -- host-facing wrapper ---------------------------------------------------


class GBDT:
    """Numpy-in, numpy-out trainer.

    ``engine_allreduce``: optional host allreduce hook (e.g. the native TCP
    engine's) — the rabit-classic distributed deployment where each process
    trains on its own shard and only histograms cross the wire.
    """

    def __init__(self, engine_allreduce: Callable[[np.ndarray], np.ndarray] | None = None, **hyper):
        self._hyper = hyper
        self._engine_allreduce = engine_allreduce
        self.cfg: GBDTConfig | None = None
        self.forest: Forest | None = None
        self.edges: np.ndarray | None = None

    def fit(self, X: np.ndarray, y: np.ndarray, warm_state: TrainState | None = None):
        X = np.asarray(X, np.float32)
        y = np.asarray(y, np.float32)
        self.cfg = GBDTConfig(n_features=X.shape[1], **self._hyper)
        self.edges = compute_bin_edges(X, self.cfg.n_bins)
        xb = quantize(jnp.asarray(X), jnp.asarray(self.edges))
        state = warm_state or init_state(self.cfg, X.shape[0])

        if self._engine_allreduce is None:
            if jax.default_backend() == "tpu":
                from rabit_tpu.ops import boost

                xb3, _ = boost.block_rows(xb)
                step = jax.jit(functools.partial(train_round_fused, cfg=self.cfg))
                for _ in range(self.cfg.n_trees):
                    state = step(state, xb3, jnp.asarray(y))
            else:
                step = jax.jit(functools.partial(train_round, cfg=self.cfg))
                for _ in range(self.cfg.n_trees):
                    state = step(state, xb, jnp.asarray(y))
        else:
            # Histograms leave the device, cross the engine (TCP/XLA), and
            # come back — the exact reference call pattern.
            hook = lambda hist: jnp.asarray(self._engine_allreduce(np.asarray(hist)))
            hist_fn = lambda xb, g, h, node, n_nodes, n_bins: hook(
                node_histograms(xb, g, h, node, n_nodes, n_bins,
                                mxu_i8=self.cfg.mxu_i8)
            )
            for _ in range(self.cfg.n_trees):
                state = train_round(state, xb, jnp.asarray(y), self.cfg, hist_fn, hook)
        self.forest = jax.tree.map(np.asarray, state.forest)
        self._state = state
        return self

    def fit_shard(self, X: np.ndarray, y: np.ndarray, world: int,
                  rank: int, warm_state: TrainState | None = None):
        """Elastic-deployment fit: train on this rank's dense shard of the
        FULL dataset (``elastic_shard``).  After a world resize, call again
        with the new ``(world, rank)`` (and the recovered ``warm_state``)
        — the re-cut shard plus the engine-allreduce hook keep histogram
        sums covering every row at any world size."""
        Xs, ys = elastic_shard(X, y, world, rank)
        return self.fit(Xs, ys, warm_state=warm_state)

    def predict_margin(self, X: np.ndarray) -> np.ndarray:
        if self.forest is None:
            raise RuntimeError("GBDT.predict called before fit")
        xb = quantize(jnp.asarray(np.asarray(X, np.float32)), jnp.asarray(self.edges))
        fn = jax.jit(functools.partial(predict_margin, cfg=self.cfg))
        return np.asarray(fn(jax.tree.map(jnp.asarray, self.forest), xb))

    def predict_proba(self, X: np.ndarray) -> np.ndarray:
        return 1.0 / (1.0 + np.exp(-self.predict_margin(X)))

    def predict(self, X: np.ndarray) -> np.ndarray:
        if self.cfg.objective == "logistic":
            return (self.predict_margin(X) > 0).astype(np.int32)
        return self.predict_margin(X)
