"""Distributed linear models (logistic / squared loss) — the simplest
rabit-style workload: each worker holds a row shard, computes the local
gradient on device, and one Allreduce(SUM) per step combines them
(the pattern of reference doc/guide.md:130-140; rabit's README names
"linear model" as a target workload alongside trees).

TPU-first shape: the local gradient is one jitted ``X.T @ residual`` matmul
(MXU), and the combine hook is the only communication point —
``lax.psum`` under ``shard_map`` for in-graph dp, or the engine's host
allreduce for the rabit-classic multi-process deployment (with
checkpoint/recovery via the robust engine).
"""

from __future__ import annotations

import functools
from typing import Callable, NamedTuple

import jax
import jax.numpy as jnp
import numpy as np


class LinearConfig(NamedTuple):
    n_features: int
    objective: str = "logistic"  # "logistic" | "squared"
    learning_rate: float = 0.5
    reg_lambda: float = 1e-3
    n_steps: int = 50


class LinearState(NamedTuple):
    w: jax.Array  # [F + 1] weights, bias last
    step: jax.Array


def init_state(cfg: LinearConfig) -> LinearState:
    return LinearState(
        w=jnp.zeros(cfg.n_features + 1, jnp.float32),
        step=jnp.zeros((), jnp.int32),
    )


def _margin(w, X):
    return X @ w[:-1] + w[-1]


def local_grad(w: jax.Array, X: jax.Array, y: jax.Array, cfg: LinearConfig):
    """Per-shard [F + 2] vector: gradient (incl. bias) ++ shard row count.
    Summing it across workers gives the global gradient and count in ONE
    allreduce."""
    m = _margin(w, X)
    if cfg.objective == "logistic":
        r = jax.nn.sigmoid(m) - y
    elif cfg.objective == "squared":
        r = m - y
    else:
        raise ValueError(f"unknown objective {cfg.objective}")
    gw = X.T @ r  # MXU
    gb = jnp.sum(r)
    n = jnp.full((), X.shape[0], jnp.float32)
    return jnp.concatenate([gw, gb[None], n[None]])


def apply_grad(state: LinearState, gsum: jax.Array, cfg: LinearConfig) -> LinearState:
    n = gsum[-1]
    g = gsum[:-1] / n
    g = g.at[:-1].add(cfg.reg_lambda * state.w[:-1])  # no penalty on bias
    return LinearState(w=state.w - cfg.learning_rate * g, step=state.step + 1)


def train_step(state: LinearState, X: jax.Array, y: jax.Array, cfg: LinearConfig,
               combine: Callable[[jax.Array], jax.Array] = lambda x: x) -> LinearState:
    """One full-batch GD step; ``combine`` is the allreduce hook."""
    return apply_grad(state, combine(local_grad(state.w, X, y, cfg)), cfg)


def train_step_dp(state, X, y, cfg, axis: str = "dp"):
    """train_step wired for shard_map: rows sharded over ``axis``."""
    return train_step(state, X, y, cfg,
                      combine=lambda v: jax.lax.psum(v, axis))


def predict_margin(w: jax.Array, X: jax.Array) -> jax.Array:
    return _margin(w, X)


class LinearModel:
    """Numpy-in trainer.  ``engine_allreduce`` (host [k] f32 -> [k] f32 sum)
    switches on the rabit-classic deployment: each process trains on its
    shard and only the [F+2] gradient vector crosses the engine."""

    def __init__(self, engine_allreduce: Callable[[np.ndarray], np.ndarray] | None = None,
                 **hyper):
        self._hyper = hyper
        self._engine_allreduce = engine_allreduce
        self.cfg: LinearConfig | None = None
        self.w: np.ndarray | None = None

    def fit(self, X: np.ndarray, y: np.ndarray,
            start: LinearState | None = None, start_step: int = 0):
        X = jnp.asarray(np.asarray(X, np.float32))
        y = jnp.asarray(np.asarray(y, np.float32))
        self.cfg = LinearConfig(n_features=int(X.shape[1]), **self._hyper)
        state = start or init_state(self.cfg)
        if self._engine_allreduce is None:
            step = jax.jit(functools.partial(train_step, cfg=self.cfg))
            for _ in range(start_step, self.cfg.n_steps):
                state = step(state, X, y)
        else:
            grad = jax.jit(functools.partial(local_grad, cfg=self.cfg))
            upd = jax.jit(functools.partial(apply_grad, cfg=self.cfg))
            for _ in range(start_step, self.cfg.n_steps):
                gsum = self._engine_allreduce(np.asarray(grad(state.w, X, y)))
                state = upd(state, jnp.asarray(gsum))
        self.state = state
        self.w = np.asarray(state.w)
        return self

    def predict_margin(self, X: np.ndarray) -> np.ndarray:
        return np.asarray(predict_margin(jnp.asarray(self.w), jnp.asarray(np.asarray(X, np.float32))))

    def predict(self, X: np.ndarray) -> np.ndarray:
        m = self.predict_margin(X)
        if self.cfg.objective == "logistic":
            return (m > 0).astype(np.int32)
        return m
