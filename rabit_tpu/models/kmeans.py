"""Distributed k-means (Lloyd's algorithm) — the reference tutorial's
"exercise" workload (doc/guide.md asks the reader to build exactly this on
rabit): each worker assigns its row shard to the nearest centroid and one
Allreduce(SUM) of the [K, F+1] (cluster sums ++ counts) statistics matrix
per iteration re-estimates the centroids.

TPU-first shape: assignment is one ``X @ C.T`` matmul (MXU) plus a row
argmin; the per-cluster sums use the one-hot-matmul ``segment_sum`` from
``rabit_tpu.ops`` (scatter-free on TPU); the combine hook is the only
communication point (psum under shard_map, or the engine's host allreduce
in the rabit-classic deployment).
"""

from __future__ import annotations

from typing import Callable, NamedTuple

import jax
import jax.numpy as jnp
import numpy as np


class KMeansConfig(NamedTuple):
    n_clusters: int
    n_iters: int = 20


def assign(X: jax.Array, centers: jax.Array) -> jax.Array:
    """Nearest-centroid ids, [n].  argmin ||x - c||^2 = argmin c.c - 2 x.c
    (the x.x term is constant per row) — one MXU matmul, no pairwise
    distance tensor."""
    cc = jnp.sum(centers * centers, axis=1)  # [K]
    scores = cc[None, :] - 2.0 * (X @ centers.T)  # [n, K]
    return jnp.argmin(scores, axis=1).astype(jnp.int32)


def local_stats(X: jax.Array, centers: jax.Array) -> jax.Array:
    """Per-shard [K, F + 1] matrix: per-cluster feature sums ++ counts."""
    from rabit_tpu.ops import hist as _hist

    k = centers.shape[0]
    a = assign(X, centers)
    ones = jnp.ones((X.shape[0], 1), jnp.float32)
    vals = jnp.concatenate([X, ones], axis=1)  # [n, F+1]
    return _hist.segment_sum(vals, a, k)


def update(centers: jax.Array, stats: jax.Array) -> jax.Array:
    """New centroids from summed stats; empty clusters keep their centroid."""
    counts = stats[:, -1:]
    return jnp.where(counts > 0, stats[:, :-1] / jnp.maximum(counts, 1.0), centers)


def train_iter(centers: jax.Array, X: jax.Array,
               combine: Callable[[jax.Array], jax.Array] = lambda x: x) -> jax.Array:
    return update(centers, combine(local_stats(X, centers)))


def train_iter_dp(centers, X, axis: str = "dp"):
    return train_iter(centers, X, combine=lambda v: jax.lax.psum(v, axis))


def inertia(X: jax.Array, centers: jax.Array) -> jax.Array:
    a = assign(X, centers)
    d = X - centers[a]
    return jnp.sum(d * d)


class KMeans:
    """Numpy-in trainer; ``engine_allreduce`` switches on the rabit-classic
    multi-process deployment (only the [K, F+1] stats matrix crosses the
    engine each iteration)."""

    def __init__(self, n_clusters: int, n_iters: int = 20,
                 engine_allreduce: Callable[[np.ndarray], np.ndarray] | None = None,
                 seed: int = 0):
        self.cfg = KMeansConfig(n_clusters=n_clusters, n_iters=n_iters)
        self._engine_allreduce = engine_allreduce
        self._seed = seed
        self.centers: np.ndarray | None = None

    def fit(self, X: np.ndarray, init_centers: np.ndarray | None = None,
            start_iter: int = 0):
        X = jnp.asarray(np.asarray(X, np.float32))
        if init_centers is None:
            if self._engine_allreduce is not None:
                # Workers hold different shards: seeding from the local shard
                # would give every worker different centers and the summed
                # stats would be incoherent.  Agree on an init first
                # (e.g. rabit_tpu.api.broadcast rank 0's choice).
                raise ValueError(
                    "distributed KMeans needs an agreed init_centers "
                    "(broadcast one from rank 0)"
                )
            rng = np.random.RandomState(self._seed)
            idx = rng.choice(X.shape[0], self.cfg.n_clusters, replace=False)
            centers = jnp.asarray(np.asarray(X)[idx])
        else:
            centers = jnp.asarray(np.asarray(init_centers, np.float32))
        if self._engine_allreduce is None:
            it = jax.jit(train_iter)
            for _ in range(start_iter, self.cfg.n_iters):
                centers = it(centers, X)
        else:
            stats = jax.jit(local_stats)
            upd = jax.jit(update)
            for _ in range(start_iter, self.cfg.n_iters):
                s = self._engine_allreduce(np.asarray(stats(X, centers)))
                centers = upd(centers, jnp.asarray(s))
        self.centers = np.asarray(centers)
        return self

    def predict(self, X: np.ndarray) -> np.ndarray:
        return np.asarray(assign(jnp.asarray(np.asarray(X, np.float32)),
                                 jnp.asarray(self.centers)))

    def inertia(self, X: np.ndarray) -> float:
        return float(inertia(jnp.asarray(np.asarray(X, np.float32)),
                             jnp.asarray(self.centers)))
