"""Model families built on the framework's collectives."""

from rabit_tpu.models.kmeans import KMeans, KMeansConfig
from rabit_tpu.models.linear import LinearConfig, LinearModel, LinearState
from rabit_tpu.models.gbdt import (
    GBDT,
    GBDTConfig,
    Forest,
    TrainState,
    compute_bin_edges,
    quantize,
    init_state,
    train_round,
    train_round_dp,
    predict_margin,
    predict_proba,
)

__all__ = [
    "KMeans",
    "KMeansConfig",
    "LinearConfig",
    "LinearModel",
    "LinearState",
    "GBDT",
    "GBDTConfig",
    "Forest",
    "TrainState",
    "compute_bin_edges",
    "quantize",
    "init_state",
    "train_round",
    "train_round_dp",
    "predict_margin",
    "predict_proba",
]
