"""Model families built on the framework's collectives."""

from rabit_tpu.models.gbdt import (
    GBDT,
    GBDTConfig,
    Forest,
    TrainState,
    compute_bin_edges,
    quantize,
    init_state,
    train_round,
    train_round_dp,
    predict_margin,
    predict_proba,
)

__all__ = [
    "GBDT",
    "GBDTConfig",
    "Forest",
    "TrainState",
    "compute_bin_edges",
    "quantize",
    "init_state",
    "train_round",
    "train_round_dp",
    "predict_margin",
    "predict_proba",
]
