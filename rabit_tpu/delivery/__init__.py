"""Model-delivery plane — the checkpoint store as a content-addressed
snapshot CDN (doc/delivery.md).

Every byte the system shipped before this package moved on the WRITE
path: training jobs commit RTC3 checkpoints, relays cache bootstrap
blobs, nothing ever read a model back out at scale.  This package adds
the read side:

* :class:`Publisher` — the writer's seam, riding the checkpoint commit
  (``rabit_tpu.api.checkpoint`` with ``rabit_delivery_publish=1``).
  Each commit registers ``(version, epoch, digest, size)`` with the
  tracker (``CMD_SUB publish`` — journaled as ``snapshot_published``,
  so a standby restores the version line) and uploads the snapshot
  bytes only when the reply says the content digest is not already
  held: N tenants publishing identical bytes ship them ONCE.

* :class:`Subscriber` — the reader: poll the current version line
  (``CMD_SUB``, answered relay-locally from the batch-ACK-refreshed
  cache), fetch the snapshot in chunks (``CMD_SNAP``, served from the
  relay's digest-keyed cache after the first fetch), verify the
  content digest end to end, and rotate through
  ``rabit_tracker_addrs`` across a tracker failover.  A missed version
  is not an error — the subscriber converges on the NEWEST line
  (catch-up semantics), and an empty snap frame (bytes not yet landed,
  or not yet re-pushed after a failover) is a retryable race.

The wire is the ordinary tracker protocol, so subscribers point at a
relay exactly like workers do and the root's accept load stays
O(relays) while subscribers are O(10^5).
"""

from __future__ import annotations

import hashlib
import json
import socket
import time

from rabit_tpu.config import Config
from rabit_tpu.tracker import protocol as P

#: Default fetch window: large enough to amortize the RPC, small enough
#: that a slow subscriber never pins a relay reply buffer.
CHUNK_BYTES = 1 << 20

_EMPTY_LINE = {"version": 0, "epoch": 0, "digest": "", "size": 0}


def digest_of(blob: bytes) -> str:
    """The content address of one snapshot: sha256 hex of its bytes.
    The tracker recomputes it server-side on upload, so the store is
    self-certifying — a publisher cannot register bytes under a digest
    that does not match them."""
    return hashlib.sha256(blob).hexdigest()


class Publisher:
    """The write side of the delivery plane (module docstring).

    ``publish()`` is register-then-upload-if-needed: the version line
    lands (and journals) first, the bytes follow only on a digest miss.
    The tiny window where the line is ahead of the bytes is part of the
    contract — subscribers treat an empty fetch as retryable.
    """

    def __init__(self, host: str, port: int, job: str = "",
                 task_id: str = "pub0",
                 addrs: list[tuple[str, int]] | None = None,
                 timeout: float = 10.0, retries: int = 5):
        self.host, self.port = host, int(port)
        self.job = job
        self.task_id = task_id
        self.addrs = [(a[0], int(a[1])) for a in (addrs or [])]
        self.timeout = float(timeout)
        self.retries = max(int(retries), 0)
        #: the last line this publisher registered (evidence/tests)
        self.published: dict | None = None
        self.uploads = 0      # uploads actually shipped
        self.dedup_skips = 0  # uploads skipped (digest already held)

    def publish(self, version: int, blob: bytes, epoch: int = 0) -> dict:
        """Register one committed snapshot; returns the tracker's line
        reply (including the ``have`` dedup bit).  Raises
        :class:`~rabit_tpu.tracker.protocol.TrackerUnreachable` when no
        configured address answers."""
        line = {"version": int(version), "epoch": int(epoch),
                "digest": digest_of(blob), "size": len(blob)}
        reply = P.tracker_rpc(
            self.host, self.port, P.CMD_SUB, self.task_id,
            message=json.dumps({"publish": line}),
            timeout=self.timeout, retries=self.retries,
            addrs=self.addrs, job=self.job)
        if not isinstance(reply, dict):
            reply = dict(line, have=False)
        if reply.get("have"):
            self.dedup_skips += 1
        else:
            self._upload(line["version"], blob)
            self.uploads += 1
        self.published = line
        return reply

    def _upload(self, version: int, blob: bytes) -> None:
        """Ship the snapshot bytes (CMD_BLOB — the existing proxied,
        relay-cached upload path; the tracker stores them digest-keyed).
        Rotates through the failover list like every client RPC."""
        cands = [(self.host, self.port)]
        for a in self.addrs:
            if a not in cands:
                cands.append(a)
        last: Exception | None = None
        for attempt in range(self.retries + 1):
            host, port = cands[attempt % len(cands)]
            try:
                with socket.create_connection(
                        (host, port), timeout=self.timeout) as sock:
                    sock.settimeout(self.timeout)
                    P.send_hello(sock, P.CMD_BLOB,
                                 P.join_job(self.job, self.task_id),
                                 blob=blob, blob_version=int(version))
                    if P.get_u32(sock) == P.ACK:
                        return
            except (ConnectionError, OSError, ValueError) as exc:
                last = exc
            if attempt < self.retries:
                time.sleep(min(0.1 * (2 ** attempt), 1.0))
        raise P.TrackerUnreachable(
            f"snapshot upload v{version} failed after "
            f"{self.retries + 1} attempt(s); last error: {last!r}")


class Subscriber:
    """The read side of the delivery plane (module docstring)."""

    def __init__(self, host: str, port: int, job: str = "",
                 task_id: str = "sub0",
                 addrs: list[tuple[str, int]] | None = None,
                 timeout: float = 10.0, retries: int = 5,
                 chunk_bytes: int = CHUNK_BYTES,
                 poll_sec: float | None = None):
        self.host, self.port = host, int(port)
        self.job = job
        self.task_id = task_id
        self.addrs = [(a[0], int(a[1])) for a in (addrs or [])]
        self.timeout = float(timeout)
        self.retries = max(int(retries), 0)
        self.chunk_bytes = max(int(chunk_bytes), 1)
        if poll_sec is None:
            poll_sec = float(Config().get("rabit_delivery_poll_sec",
                                          "0.5") or "0.5")
        self.poll_sec = max(float(poll_sec), 0.01)
        #: newest version this subscriber has fully fetched
        self.seen_version = 0

    def poll(self) -> dict:
        """The current published version line (``version`` 0 = nothing
        published yet)."""
        reply = P.tracker_rpc(
            self.host, self.port, P.CMD_SUB, self.task_id, message="{}",
            timeout=self.timeout, retries=self.retries,
            addrs=self.addrs, job=self.job)
        return reply if isinstance(reply, dict) else dict(_EMPTY_LINE)

    def wait_for(self, min_version: int | None = None,
                 deadline_sec: float = 30.0) -> dict:
        """Block (poll-cadence) until the published line reaches
        ``min_version`` (default: anything newer than ``seen_version``).
        Catch-up semantics: a subscriber that slept through versions
        5..9 wakes to the line naming 10 — intermediate versions are
        not replayed, the stream converges on the newest snapshot."""
        target = (int(min_version) if min_version is not None
                  else self.seen_version + 1)
        deadline = time.monotonic() + float(deadline_sec)
        while True:
            line = self.poll()
            if int(line.get("version", 0)) >= target:
                return line
            if time.monotonic() >= deadline:
                raise TimeoutError(
                    f"delivery line never reached v{target} "
                    f"(currently v{line.get('version', 0)})")
            time.sleep(self.poll_sec)

    def fetch(self, line: dict | None = None,
              deadline_sec: float = 30.0) -> tuple[dict, bytes]:
        """Fetch the snapshot the line names (default: the current
        line), chunk by chunk, and verify its content digest.  Empty
        frames — the publish-before-upload race, or a fresh standby
        whose byte store has not been re-fed — retry until the
        deadline.  Returns ``(line, blob)``."""
        if line is None:
            line = self.poll()
        digest = str(line.get("digest", ""))
        if not digest:
            raise LookupError("nothing published yet (empty digest)")
        deadline = time.monotonic() + float(deadline_sec)
        while True:
            blob = self._fetch_once(digest)
            if blob is not None and digest_of(blob) == digest:
                self.seen_version = max(self.seen_version,
                                        int(line.get("version", 0)))
                return dict(line), blob
            if time.monotonic() >= deadline:
                raise TimeoutError(
                    f"snapshot {digest[:12]}… not served within "
                    f"{deadline_sec:.1f}s")
            time.sleep(self.poll_sec)

    def _fetch_once(self, digest: str) -> bytes | None:
        """One whole-blob fetch attempt; None on absence or a torn
        window sequence (the caller retries — absence is a race, not an
        error)."""
        buf = bytearray()
        off = 0
        total: int | None = None
        while True:
            try:
                got, tot, goff, chunk = P.tracker_rpc(
                    self.host, self.port, P.CMD_SNAP, self.task_id,
                    message=json.dumps({"digest": digest, "off": off,
                                        "len": self.chunk_bytes}),
                    timeout=self.timeout, retries=self.retries,
                    addrs=self.addrs, job=self.job)
            except P.TrackerUnreachable:
                return None
            if got != digest or goff != off:
                return None  # absent, or the holder changed mid-fetch
            if total is None:
                total = tot
            elif tot != total:
                return None
            buf += chunk
            off += len(chunk)
            if off >= total:
                return bytes(buf)
            if not chunk:
                return None  # short frame with bytes still owed
