"""Layered key=value configuration.

Capability parity with the reference's ``SetParam`` layering (built-in
defaults <- watched env vars <- argv ``k=v`` overrides, see
``/root/reference/src/allreduce_base.cc:49-64`` and ``doc/parameters.md``)
re-expressed as a plain dataclass-free dict with typed accessors instead of
strcmp chains.
"""

from __future__ import annotations

import os
from typing import Iterable, Mapping


# Environment variables consulted at init time (reference: the ``env_vars``
# watch list in allreduce_base.cc / allreduce_robust.cc).  Both the legacy
# DMLC_* spellings and RABIT_TPU_* spellings are honoured; the latter wins.
_ENV_KEYS = [
    "DMLC_TRACKER_URI",
    "DMLC_TRACKER_PORT",
    "DMLC_TASK_ID",
    "DMLC_ROLE",
    "DMLC_NUM_ATTEMPT",
    "DMLC_WORKER_CONNECT_RETRY",
    "RABIT_OBS_DIR",
    "rabit_global_replica",
    "rabit_local_replica",
]

# Mapping from env-var name to canonical config key.
_ENV_TO_KEY = {
    "DMLC_TRACKER_URI": "rabit_tracker_uri",
    "DMLC_TRACKER_PORT": "rabit_tracker_port",
    "DMLC_TASK_ID": "rabit_task_id",
    "DMLC_ROLE": "rabit_role",
    "DMLC_NUM_ATTEMPT": "rabit_num_trial",
    "DMLC_WORKER_CONNECT_RETRY": "rabit_connect_retry",
    "RABIT_OBS_DIR": "rabit_obs_dir",
}

_UNIT = {"B": 1, "K": 1 << 10, "M": 1 << 20, "G": 1 << 30}

#: Built-in defaults — the performance envelope knobs of the reference
#: (allreduce_base.cc:18-46, allreduce_robust.cc:26-40) with identical
#: semantics and defaults.
DEFAULTS: dict[str, str] = {
    "rabit_engine": "auto",           # auto | empty | xla | native | mock
    # XLA engine multi-process bootstrap (engine/xla.py): empty means
    # "fall back to the standard JAX cluster env vars" (the engine reads
    # these with an `or` chain, so the empty default never shadows
    # JAX_COORDINATOR_ADDRESS / JAX_NUM_PROCESSES / JAX_PROCESS_ID).
    "rabit_xla_coordinator": "",
    "rabit_xla_num_processes": "",
    "rabit_xla_process_id": "",
    "rabit_tracker_uri": "NULL",
    "rabit_tracker_port": "9091",
    "rabit_task_id": "NULL",
    "rabit_num_trial": "0",
    "rabit_connect_retry": "5",
    "rabit_reduce_ring_mincount": str(32 << 10),
    "rabit_tree_reduce_minsize": str(1 << 20),
    "rabit_reduce_buffer": "256M",
    "rabit_global_replica": "5",
    "rabit_local_replica": "2",
    "rabit_timeout": "1",
    "rabit_timeout_sec": "1800",
    # rabit_stall_timeout_sec is deliberately NOT defaulted here: its
    # default is engine-dependent (robust: 300s, base: off — see
    # Comm::SetDefaultStallSec), and a value here would be serialized into
    # RabitInit argv and override that.
    "rabit_bootstrap_cache": "0",
    # Durable checkpoint spill: when set, every committed checkpoint is
    # also written here and a FRESH cluster resumes from the newest disk
    # version (whole-job preemption durability; rabit_tpu/store.py).
    "rabit_checkpoint_dir": "",
    # Compressed collectives (rabit_tpu/compress, doc/compression.md).
    # rabit_compress_allreduce: default codec for api.allreduce payloads
    # (identity|bf16|bf16x2|i8|i8x2; empty = exact f32).  Applies only to
    # float32 non-BITOR payloads of at least rabit_compress_min_bytes
    # bytes; a per-call codec= argument always wins.
    # rabit_compress_wire_deflate: lossless deflate stage on the host
    # transport's wire bytes (the in-graph XLA path ships raw planes).
    # rabit_compress_broadcast: byte codec (zlib) for api.broadcast
    # payloads.  rabit_checkpoint_compress: codec byte of the durable
    # store's frames (old frames stay readable; empty = uncompressed).
    "rabit_compress_allreduce": "",
    "rabit_compress_min_bytes": "1024",
    "rabit_compress_wire_deflate": "1",
    "rabit_compress_broadcast": "",
    "rabit_checkpoint_compress": "zlib",
    # Fused in-XLA quantized collectives (rabit_tpu/engine/fused.py;
    # doc/compression.md "Fused in-XLA path").  rabit_fused_allreduce:
    # auto (default — ON for the XLA engine, meaningless elsewhere: the
    # host transport is the only compressed path off-XLA) | 1 | 0.  When
    # on, XlaEngine.allreduce_compressed lowers encode -> chunked
    # ppermute ring (the PR 7 planned schedule order) -> rank-order
    # decode-fold into ONE jitted graph, bitwise identical to the host
    # reference fold.  rabit_fused_chunk_kib tunes the per-ppermute hop
    # sub-chunk size (KiB; 0 = one ppermute per hop) for comm/compute
    # overlap.
    "rabit_fused_allreduce": "auto",
    "rabit_fused_chunk_kib": "256",
    "rabit_debug": "0",
    # Observability (rabit_tpu/obs, doc/observability.md): when
    # rabit_obs_dir (or the RABIT_OBS_DIR env var) is set, each rank dumps
    # its flight recorder there on SIGTERM or when a collective is stuck
    # longer than rabit_obs_hang_sec, and the tracker writes the job-level
    # telemetry.json there.  rabit_obs_heartbeat_sec > 0 additionally
    # ships periodic metric snapshots to the tracker (shutdown always
    # ships one).
    "rabit_obs_dir": "",
    "rabit_obs_capacity": "2048",
    "rabit_obs_hang_sec": "300",
    "rabit_obs_heartbeat_sec": "0",
    # Live telemetry plane (doc/observability.md "Live telemetry
    # plane").  rabit_obs_spill_sec > 0: each rank periodically spills
    # its flight ring into the obs dir so `trace_tool export --follow`
    # can emit a growing Perfetto file mid-run.  rabit_obs_max_files
    # caps the obs dir's flight-dump count (oldest-first eviction,
    # obs_evicted event; 0 disables).  rabit_obs_scrape names the
    # task id CMD_OBS scrape clients identify as (obs_top, benches).
    "rabit_obs_spill_sec": "0",
    "rabit_obs_max_files": "256",
    "rabit_obs_scrape": "obs",
    # Liveness layer (doc/fault_tolerance.md).  rabit_heartbeat_sec > 0:
    # renew a CMD_HEARTBEAT lease with the tracker every N seconds; the
    # tracker suspects this worker (lease_expired event + on_suspect
    # callback, which the launcher wires to SIGKILL-and-restart) after
    # 2 x N seconds of silence — the failure detector for SILENT deaths
    # (frozen process, preempted VM) that raise no exit code and no TCP
    # error.  rabit_hang_abort_sec > 0: a collective stuck in flight this
    # long makes the rank dump its flight recorder and abort itself
    # (exit 11, dump-then-die) so the launcher restarts it — the
    # worker-side belt to the tracker lease's suspenders.
    "rabit_heartbeat_sec": "0",
    "rabit_hang_abort_sec": "0",
    # Elastic worlds (rabit_tpu/elastic, doc/elasticity.md).
    # rabit_spare=1 marks a worker as a HOT SPARE: it checks in with
    # CMD_SPARE, receives the cached compressed bootstrap blob, and parks
    # on a warm socket until the tracker promotes it into a dead rank's
    # slot.  rabit_shrink_after_sec > 0 lets a recovery wave close SHRUNK
    # when no spare arrives within the deadline (0 keeps the legacy
    # block-until-full contract); rabit_min_world floors the shrink.
    # rabit_spare_promote_sec is the grace before a short wave steals a
    # parked spare — a slow-but-live worker's own check-in wins the slot
    # inside the grace.
    "rabit_spare": "0",
    "rabit_shrink_after_sec": "0",
    "rabit_min_world": "1",
    "rabit_spare_promote_sec": "0.25",
    # Collective schedules (rabit_tpu/sched, doc/scheduling.md).
    # rabit_schedule picks the per-epoch ring layout the tracker plans
    # (auto|tree|ring|swing); rabit_sched_mesh pins the mesh-model dims
    # ("RxC[:nowrap]", empty = near-square auto); rabit_sched_repair
    # lets degraded-link reports trigger a repair replan at the next
    # epoch boundary; rabit_sched_wait_share is the executor's
    # wait-share threshold for indicting its incoming link.
    "rabit_schedule": "auto",
    "rabit_sched_mesh": "",
    "rabit_sched_repair": "1",
    "rabit_sched_wait_share": "0.25",
    # Partial (quorum) allreduce (rabit_tpu/quorum,
    # doc/partial_allreduce.md).  rabit_quorum: a fraction in (0,1]
    # ("0.67" = two thirds of the current world) or an integer count —
    # a collective round completes once that many contributions have
    # folded; stragglers' late blocks land as exact correction terms at
    # the next round boundary after delivery.  Empty (default) keeps
    # the legacy exact lockstep collective; "1.0" runs the quorum wire
    # but never excludes (bitwise identical to legacy).
    # rabit_quorum_wait_sec is the executor's per-round deadline before
    # it reports a partial quorum (and before a silent upstream rank is
    # skipped around); rabit_quorum_flag_after feeds a rank excluded
    # that many consecutive rounds into the schedule-repair avoid set
    # (0 disables the feed).
    "rabit_quorum": "",
    "rabit_quorum_wait_sec": "0.35",
    "rabit_quorum_flag_after": "3",
    # Cross-rank tracing (rabit_tpu/obs/trace.py, tools/trace_tool.py).
    # rabit_trace_exit=1: dump the flight ring as flight-*-exit.jsonl at
    # finalize, so CLEAN runs leave the per-rank evidence the job-wide
    # trace merger joins.  rabit_trace_clock_pings: timestamped
    # round-trips at shutdown that (re)estimate this rank's clock offset
    # against the tracker before the final snapshot ships it.
    "rabit_trace_exit": "0",
    "rabit_trace_clock_pings": "2",
    # Serving at scale (doc/scaling.md).  rabit_tracker_backlog: the
    # tracker's listen(2) backlog — a bootstrap wave is world_size nearly
    # simultaneous connects, and a short backlog turns the overflow into
    # 1s+ SYN-retransmit latency; raise toward the world size for
    # O(10^3)+ direct worlds (relayed deployments keep the root's accept
    # count at O(relays) instead).
    "rabit_tracker_backlog": "1024",
    # HA control plane (rabit_tpu/ha, doc/ha.md).  rabit_tracker_addrs:
    # comma-separated "host:port" tracker addresses (the primary first,
    # then its warm standby) — every tracker_rpc rotates through them on
    # failure, so a primary tracker death fails over client-side.
    # rabit_ha_journal: path of the durable control-plane journal the
    # tracker appends every mutation to (empty = journaling off);
    # rabit_ha_snapshot_every: records between compacted snapshots (the
    # replay-cost bound); rabit_ha_takeover_sec: the standby's takeover
    # lease — how long the primary may be unreachable/silent before the
    # standby promotes itself; rabit_ha_tick_sec: the primary's journal
    # keepalive cadence (the liveness signal that lease watches).
    # Multi-tenant collective service (rabit_tpu/service, doc/service.md).
    # rabit_job_key: the job this worker belongs to — it prefixes the
    # wire task id ("<job>/<task>"; empty = the legacy single-job
    # namespace, byte-identical on the wire) so a CollectiveService
    # routes the worker to its job's control-plane partition.
    # rabit_service_max_jobs / rabit_service_max_jobs_per_tenant /
    # rabit_service_max_ranks: the service's admission quotas
    # (concurrent jobs service-wide, concurrent jobs per tenant — the
    # job key up to its first "." — and the fd budget as the sum of
    # admitted world sizes; 0 = unlimited).  rabit_service_auto_world:
    # world size for jobs admitted straight from the wire (an unknown
    # job key's first check-in); 0 refuses unknown keys — programmatic
    # admission only.
    "rabit_job_key": "",
    "rabit_service_max_jobs": "0",
    "rabit_service_max_jobs_per_tenant": "0",
    "rabit_service_max_ranks": "0",
    "rabit_service_auto_world": "0",
    "rabit_tracker_addrs": "",
    "rabit_ha_journal": "",
    "rabit_ha_snapshot_every": "256",
    "rabit_ha_takeover_sec": "1.0",
    "rabit_ha_tick_sec": "0.25",
    # Default ON, matching the native engine (see comm.cc Configure): with
    # Nagle on, every cold-direction header write stalls ~40ms behind the
    # peer's delayed ACK — measured 44ms/op on loopback object broadcasts.
    "rabit_enable_tcp_no_delay": "1",
    # Diagnosis plane (rabit_tpu/obs/diagnose.py, doc/observability.md).
    # rabit_diag_enable: run the HealthMonitor on the tracker (and every
    # service partition); rabit_diag_window_sec: detection-window cadence;
    # rabit_diag_open_windows / rabit_diag_resolve_windows: hysteresis —
    # consecutive firing windows before an incident opens / quiet windows
    # before it resolves; rabit_diag_min_wait_sec: ignore windows whose
    # total link wait is below this (clean-run noise floor);
    # rabit_diag_link_share: the degraded-link concentration threshold
    # (top link's share of the window's wait); rabit_diag_hole_ratio:
    # the compute-straggler hole threshold (the quiet link's wait vs the
    # per-link mean); rabit_diag_storm_leases: lease expiries across the
    # recent windows that count as a preemption storm, not one death.
    "rabit_diag_enable": "1",
    "rabit_diag_window_sec": "0.5",
    "rabit_diag_open_windows": "2",
    "rabit_diag_resolve_windows": "4",
    "rabit_diag_min_wait_sec": "0.05",
    "rabit_diag_link_share": "0.5",
    "rabit_diag_hole_ratio": "0.25",
    "rabit_diag_storm_leases": "3",
    # Model-delivery plane (rabit_tpu/delivery, doc/delivery.md).
    # rabit_delivery_publish=1: rank 0 publishes every checkpoint commit
    # as a content-addressed snapshot (version line + digest-deduped
    # bytes) through the tracker.  rabit_delivery_poll_sec: subscriber
    # poll/retry cadence.  rabit_relay_cache_bytes: each relay's
    # digest-keyed snapshot cache budget (LRU beyond it; live jobs'
    # newest digests are never evicted).  rabit_checkpoint_keep: the
    # durable store's retention window (versions beyond the newest N
    # prune after each commit; the published version stays pinned).
    "rabit_delivery_publish": "0",
    "rabit_delivery_poll_sec": "0.5",
    "rabit_relay_cache_bytes": "256M",
    "rabit_checkpoint_keep": "2",
}


def parse_unit(value: str) -> int:
    """Parse ``"256M"``-style sizes (reference: ParseUnit,
    allreduce_base.cc:150-170)."""
    value = value.strip()
    if value and value[-1].upper() in _UNIT:
        return int(float(value[:-1]) * _UNIT[value[-1].upper()])
    return int(value)


class Config:
    """Merged configuration with typed accessors."""

    def __init__(
        self,
        args: Iterable[str] | None = None,
        overrides: Mapping[str, str] | None = None,
    ):
        self._cfg = dict(DEFAULTS)
        # layer 2: environment
        for env_name in _ENV_KEYS:
            val = os.environ.get(env_name)
            if val is not None:
                self._cfg[_ENV_TO_KEY.get(env_name, env_name)] = val
        for env_name, val in os.environ.items():
            if env_name.startswith("RABIT_TPU_"):
                self._cfg[env_name[len("RABIT_TPU_"):].lower()] = val
        # layer 3: argv "k=v" pairs
        for arg in args or []:
            if "=" in arg:
                key, val = arg.split("=", 1)
                self._cfg[key] = val
        # layer 4: explicit kwargs
        for key, val in (overrides or {}).items():
            self._cfg[key] = str(val)

    def get(self, key: str, default: str | None = None) -> str | None:
        return self._cfg.get(key, default)

    def get_int(self, key: str, default: int = 0) -> int:
        val = self._cfg.get(key)
        return default if val is None else int(val)

    def get_size(self, key: str, default: int = 0) -> int:
        val = self._cfg.get(key)
        return default if val is None else parse_unit(val)

    def get_bool(self, key: str, default: bool = False) -> bool:
        val = self._cfg.get(key)
        if val is None:
            return default
        return val.strip().lower() not in ("0", "false", "no", "off", "")

    def __getitem__(self, key: str) -> str:
        return self._cfg[key]

    def __contains__(self, key: str) -> bool:
        return key in self._cfg

    def as_dict(self) -> dict[str, str]:
        return dict(self._cfg)

    @property
    def timeout_sec(self) -> int:
        """Watchdog bound; 0 when the watchdog is disabled."""
        if not self.get_bool("rabit_timeout"):
            return 0
        return self.get_int("rabit_timeout_sec", 1800)
