"""Standalone relay process: ``python -m rabit_tpu.relay --tracker H:P``.

One relay node of the hierarchical coordination tier (doc/scaling.md).
Point a shard of workers' ``DMLC_TRACKER_URI``/``DMLC_TRACKER_PORT`` at
the address this prints; the relay terminates their liveness/metrics
RPCs locally and batches upstream.  The in-process launcher
(``rabit_tpu.tracker.launcher --relays R``) hosts relays directly; this
entry point is for real multi-host deployments.
"""

from __future__ import annotations

import argparse
import sys
import time

from rabit_tpu.relay import Relay


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--tracker", required=True, metavar="HOST:PORT",
                    help="root tracker address")
    ap.add_argument("--id", default="r0", help="relay id (telemetry)")
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--port", type=int, default=0,
                    help="child-facing listen port (0 = ephemeral)")
    ap.add_argument("--flush-sec", type=float, default=0.25,
                    help="upstream batch cadence")
    ap.add_argument("--quiet", action="store_true")
    args = ap.parse_args(argv)
    host, _, port = args.tracker.rpartition(":")
    relay = Relay((host or "127.0.0.1", int(port)), relay_id=args.id,
                  host=args.host, port=args.port,
                  flush_sec=args.flush_sec, quiet=args.quiet).start()
    # The launcher-parsable address line (flushed before the serve loop).
    print(f"[relay {args.id}] listening on {relay.host}:{relay.port}",
          flush=True)
    try:
        while True:
            time.sleep(1.0)
    except KeyboardInterrupt:
        relay.stop()
        return 0


if __name__ == "__main__":
    sys.exit(main())
