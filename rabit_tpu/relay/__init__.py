"""Hierarchical relay tier — constant fan-out coordination at pod scale.

At O(10^3)-O(10^4) workers the root tracker's ceiling is not the data
plane but its own accept path: every heartbeat, metrics snapshot, and
epoch poll is a fresh TCP connection, and a bootstrap wave is an O(N)
accept storm (doc/scaling.md; PAPERS.md "Highly Available Data Parallel
ML training on Mesh Networks" makes the same argument — the coordination
tier must be hierarchical and constant-fan-out or it sets job startup
and recovery latency).

A :class:`Relay` is a STATELESS fan-in node speaking the ordinary
tracker wire to its children (workers point ``DMLC_TRACKER_URI`` at it —
zero worker changes) and ONE persistent ``CMD_BATCH`` channel to the
root tracker:

* **terminated locally** — heartbeats (a local lease table mirrors the
  tracker's semantics; live leases are re-advertised upstream once per
  flush with a padded interval, so the root's lease covers the batching
  cadence and a relay bounce), metrics snapshots (latest per task wins,
  exactly the tracker's fold), epoch polls (answered from a cache the
  batch ACKs refresh), prints and shutdowns (ACKed locally, forwarded in
  the next flush);
* **routed** — START/RECOVER/SPARE check-ins park the child connection
  at the relay, the hello rides the next (immediate) batch upstream, and
  the tracker's reply (Assignment, park frame) is routed back by task
  id over the channel — the root accepts O(relays) connections per wave
  instead of O(world);
* **batched agreement** — CMD_QUORUM reports park the child connection
  (like a check-in) and ride the next immediate batch; the tracker
  folds the report and routes the frozen exclusion record back under
  the child's ``q#``-prefixed key — a quorum-heavy world costs the
  root one envelope per flush instead of one connection per rank per
  round, and re-delivery after a channel cut is safe because the
  tracker's QuorumTable decides each round exactly once;
* **proxied** — CMD_BLOB (rank-0 blob upload: large and rare) passes
  through on its own short-lived upstream connection, behind a per-job
  (job, version) cache: re-uploads of a version the root already ACKed
  are answered locally (``blob_cache_hits``), a version bump
  invalidates and proxies — N children re-shipping one bootstrap blob
  cost the root one fetch;
* **job-multiplexed** — children of a multi-job CollectiveService
  (doc/service.md) need no relay configuration: the job key rides
  inside their task ids (so routed replies and held check-ins are
  per-job automatically), and the batch ACK's ``jobs`` map keeps a
  per-job CMD_EPOCH cache so one relay tier serves every job's
  version-boundary polls locally;
* **clock-projected** — the relay brackets every batch round-trip and
  keeps an NTP-style offset estimate against the tracker clock; child
  heartbeat/metrics ACKs carry the PROJECTED tracker time, so PR 3
  cross-rank clock sync still works per rank through a relay.

Statelessness is the failure model: a dead relay is just a reconnect,
not a membership event.  Children retry against the same address
(``tracker_rpc`` backoff), parked check-ins are re-sent when the channel
reconnects, and the tracker's purge/reap paths treat a dead channel's
virtual connections as hung up.  Child leases survive a relay bounce
because the upstream lease interval is padded
(:data:`RELAY_LEASE_PAD` x the flush cadence).

The ROOT dying is also just a reconnect (doc/ha.md): construct the
relay with a list of tracker addresses (``rabit_tracker_addrs`` — the
primary and its warm standby) and the channel rotates to the next
address when a dial fails.  On every reconnect the relay replays its
un-ACKed batch envelope (minus the heartbeats/metrics that re-coalesce
anyway), so no check-in, shutdown, print, or quorum report is lost
across the failover cut — the new primary dedupes by task id and
decide-once records, so the replay is idempotent.  Children behind a
relay therefore never re-dial at all when the root fails over: the
relay tier IS their stable coordination address.
"""

from __future__ import annotations

import hashlib
import json
import selectors
import socket
import threading
import time
from collections import OrderedDict

from rabit_tpu.config import Config
from rabit_tpu.obs import stream as obs_stream
from rabit_tpu.tracker import protocol as P

#: Upstream heartbeat padding: a child's lease is re-advertised to the
#: root with interval ``max(child_interval, flush_sec) * RELAY_LEASE_PAD``
#: so the root's LEASE_FACTOR x interval lease tolerates one whole missed
#: flush (a relay bounce + reconnect) without a spurious lease_expired.
#: The RELAY's local lease (the child's true interval) stays the fast
#: detector; the root's padded lease is the backstop.
RELAY_LEASE_PAD = 2.0

#: How long a held (wave-parked) child write may block the channel
#: reader before the child is declared gone.
_HELD_SEND_TIMEOUT = 30.0


class _Child:
    """Per-child-connection state on the relay's reactor loop."""

    __slots__ = ("sock", "addr", "parser", "out", "deadline", "task_id",
                 "held")

    def __init__(self, sock: socket.socket, addr, deadline: float):
        self.sock = sock
        self.addr = addr
        self.parser = P.StreamParser(P.hello_parser())
        self.out = bytearray()
        self.deadline = deadline
        self.task_id = ""
        self.held = False


class _LocalLease:
    __slots__ = ("interval", "expires", "prev_rank")

    def __init__(self, interval: float, expires: float, prev_rank: int):
        self.interval = interval
        self.expires = expires
        self.prev_rank = prev_rank


class Relay:
    """One relay process/node (see module docstring).

    Runs two loops: a selectors-based child reactor (accept, parse,
    terminate-or-park) and an upstream pump (flush one coalesced batch
    per ``flush_sec`` — immediately when a check-in or shutdown is
    queued — plus a channel reader routing tracker replies to parked
    children).  ``start()``/``stop()`` bound every thread; nothing here
    blocks unboundedly.
    """

    def __init__(self, tracker, relay_id: str = "r0",
                 host: str = "127.0.0.1", port: int = 0,
                 flush_sec: float = 0.25, backlog: int = 1024,
                 rpc_timeout: float = 5.0, quiet: bool = True):
        # ``tracker`` is one (host, port) or a failover LIST of them
        # (primary first — rabit_tracker_addrs, doc/ha.md); the channel
        # rotates through the list when a dial fails.
        if tracker and isinstance(tracker[0], (tuple, list)):
            self.trackers = [(t[0], int(t[1])) for t in tracker]
        else:
            self.trackers = [(tracker[0], int(tracker[1]))]
        self._tr = 0  # index of the address currently believed primary
        self.relay_id = relay_id
        self.flush_sec = float(flush_sec)
        self.rpc_timeout = float(rpc_timeout)
        self.quiet = quiet
        self._srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._srv.bind((host, port))
        self._srv.listen(backlog)
        self.host, self.port = self._srv.getsockname()
        self._stopped = threading.Event()
        self._lock = threading.Lock()
        # coalesced upstream state (drained per flush; all under _lock)
        self._leases: dict[str, _LocalLease] = {}
        self._metrics: dict[str, tuple[int, bytes, float]] = {}
        # Streamed-metric deltas coalesced per JOB per flush
        # (doc/observability.md "Live telemetry plane"): unlike the
        # latest-wins snapshot table above, delta windows ACCUMULATE
        # (counters sum, histogram buckets add) — replacement would lose
        # every window but the last.
        self._deltas: dict[str, dict] = {}
        self._queued: list[P.BatchMsg] = []
        self._held: dict[str, socket.socket] = {}   # parked check-ins
        self._held_msg: dict[str, P.BatchMsg] = {}  # their hellos (for
        #                                             re-send on reconnect)
        self._held_sent: set[str] = set()
        # Sockets other threads want closed: ONLY the child reactor may
        # close a registered socket (a cross-thread close frees the fd
        # while it is still registered, and the next accept's fd reuse
        # then fails to register).
        self._defer_close: set[socket.socket] = set()
        self._flush_now = threading.Event()
        # upstream channel + tracker-clock projection
        self._chan: socket.socket | None = None
        self._chan_lock = threading.Lock()
        self._ack = threading.Event()
        self._partitioned = False
        self.clock_offset = 0.0   # tracker_ts - relay_ts
        self.clock_err = float("inf")
        self._epoch_cache = {"epoch": 0, "world": 0, "rewave": False}
        # Multi-job service (doc/service.md): a CollectiveService's
        # batch ACK carries a per-job "jobs" map — children of job "j"
        # (task id "j/0") get their CMD_EPOCH polls answered from their
        # OWN job's cache, so one shared relay tier serves every job.
        self._job_epochs: dict[str, dict] = {}
        # Relay-side blob cache, DIGEST-KEYED (doc/delivery.md): the
        # bytes live once in ``_digest_blobs`` (LRU order, bounded by
        # the ``rabit_relay_cache_bytes`` byte budget) and every job's
        # ``_blob_cache`` entry maps job -> (version, digest) —
        # refcounted via ``_digest_refs`` so N jobs shipping identical
        # bytes hold ONE copy, and a retired job releases only its
        # reference.  A same-or-older-version upload is ACKed LOCALLY
        # (blob_cache_hits); a version bump releases the superseded
        # digest and proxies through.  CMD_SNAP fetches populate the
        # same store (unreferenced — pure LRU tenants).
        self._blob_cache: dict[str, tuple[int, str]] = {}
        self._digest_blobs: OrderedDict[str, bytes] = OrderedDict()
        self._digest_refs: dict[str, int] = {}
        self._cache_used = 0
        self._cache_budget = Config().get_size("rabit_relay_cache_bytes",
                                               256 << 20)
        # Per-job delivery version lines, refreshed from batch ACKs
        # (doc/delivery.md): a known line answers a child's CMD_SUB poll
        # locally — 10^5 subscribers polling never touch the root.
        self._sub_lines: dict[str, dict] = {}
        # Local evidence timeline (blob_cache_evicted), bounded.
        self.events: list[dict] = []
        # The last batch's replayable sub-messages, held until its ACK
        # lands: a channel cut between send and ACK (a root failover)
        # replays them on the next connect so no check-in, shutdown,
        # print, or quorum report is lost across the cut (doc/ha.md).
        # Heartbeats/metrics are excluded — they re-coalesce every
        # flush anyway.  Delta frames (CMD_OBS) are excluded too, the
        # other way around: a replay after the root DID fold them would
        # double-count the window, and approximate-but-never-inflated is
        # the accounting contract across a failover cut.
        self._unacked: list[P.BatchMsg] = []
        self._replay = False
        # evidence counters
        self.stats = {"children": 0, "rpcs_terminated": 0, "batches": 0,
                      "batch_msgs": 0, "routed": 0, "reconnects": 0,
                      "failovers": 0, "replayed_msgs": 0,
                      "blob_cache_hits": 0, "snap_cache_hits": 0,
                      "snap_proxies": 0, "evictions": 0}

    @property
    def tracker(self) -> tuple[str, int]:
        """The root address currently believed primary (rotated by the
        channel's reconnect loop on dial failure)."""
        return self.trackers[self._tr]

    # -- lifecycle ---------------------------------------------------------

    def start(self) -> "Relay":
        threading.Thread(target=self._serve_children, daemon=True,
                         name=f"relay-children-{self.relay_id}").start()
        threading.Thread(target=self._upstream_pump, daemon=True,
                         name=f"relay-upstream-{self.relay_id}").start()
        return self

    def stop(self) -> None:
        self._stopped.set()
        self._flush_now.set()
        try:
            self._srv.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        try:
            self._srv.close()
        except OSError:
            pass
        self._drop_channel()
        with self._lock:
            held, self._held = self._held, {}
            self._held_msg.clear()
            self._held_sent.clear()
        for conn in held.values():
            try:
                conn.close()
            except OSError:
                pass

    def set_partition(self, on: bool) -> None:
        """Chaos hook (doc/scaling.md): while partitioned the relay keeps
        serving its children locally but cannot reach the root — batches
        fail, the channel stays down, and held check-ins park until the
        heal.  The root's padded leases decide whether the window was
        survivable."""
        self._partitioned = bool(on)
        if on:
            self._drop_channel()
        else:
            self._flush_now.set()

    # -- tracker-clock projection ------------------------------------------

    def _stamp(self) -> bytes:
        """The PROJECTED tracker clock, in the exact format of the
        tracker's own metrics/heartbeat ACK stamp — children's ClockSync
        keeps estimating tracker_ts - worker_ts through a relay."""
        return P.put_str(f"{time.time() + self.clock_offset:.6f}")

    # -- child reactor ------------------------------------------------------

    def _serve_children(self) -> None:
        sel = selectors.DefaultSelector()
        self._srv.setblocking(False)
        try:
            sel.register(self._srv, selectors.EVENT_READ, None)
        except (OSError, ValueError):
            return
        children: set[_Child] = set()
        next_sweep = time.monotonic() + 0.5
        try:
            while not self._stopped.is_set():
                try:
                    events = sel.select(0.05)
                except OSError:
                    break
                for key, mask in events:
                    if key.data is None:
                        self._accept_children(sel, children)
                    elif mask & selectors.EVENT_READ:
                        self._child_read(sel, children, key.data)
                    elif mask & selectors.EVENT_WRITE:
                        self._child_flush(sel, children, key.data)
                if self._defer_close:
                    with self._lock:
                        dead, self._defer_close = self._defer_close, set()
                    for ch in [c for c in children if c.sock in dead]:
                        self._child_drop(sel, children, ch)
                        dead.discard(ch.sock)
                    for sock in dead:  # never registered / already dropped
                        try:
                            sock.close()
                        except OSError:
                            pass
                now = time.monotonic()
                if now >= next_sweep:
                    next_sweep = now + 0.5
                    self._expire_local_leases()
                    for ch in [c for c in children
                               if c.deadline and now > c.deadline]:
                        self._child_drop(sel, children, ch)
        finally:
            for ch in list(children):
                self._child_drop(sel, children, ch)
            sel.close()

    def _accept_children(self, sel, children: set[_Child]) -> None:
        while True:
            try:
                conn, addr = self._srv.accept()
            except (BlockingIOError, InterruptedError, OSError):
                return
            conn.setblocking(False)
            ch = _Child(conn, addr, time.monotonic() + 60.0)
            try:
                sel.register(conn, selectors.EVENT_READ, ch)
            except (OSError, ValueError):
                try:
                    conn.close()
                except OSError:
                    pass
                continue
            children.add(ch)
            self.stats["children"] += 1

    def _child_drop(self, sel, children: set[_Child], ch: _Child) -> None:
        children.discard(ch)
        try:
            sel.unregister(ch.sock)
        except (KeyError, OSError, ValueError):
            pass
        if ch.held:
            # A parked check-in hung up: tell the tracker so the wave
            # purge counts live survivors only.  Guard against a stale
            # entry for a task that re-checked-in on a fresh connection.
            self._unhold(ch.task_id, notify=True, expect=ch.sock)
        try:
            ch.sock.close()
        except OSError:
            pass

    def _child_detach(self, sel, children: set[_Child], ch: _Child) -> None:
        children.discard(ch)
        try:
            sel.unregister(ch.sock)
        except (KeyError, OSError, ValueError):
            pass
        ch.sock.setblocking(True)

    def _child_read(self, sel, children: set[_Child], ch: _Child) -> None:
        try:
            data = ch.sock.recv(65536)
        except (BlockingIOError, InterruptedError):
            return
        except OSError:
            self._child_drop(sel, children, ch)
            return
        if not data:
            self._child_drop(sel, children, ch)
            return
        if ch.held:
            return  # held children never speak past their hello
        try:
            if not ch.parser.feed(data):
                return
            h = ch.parser.result
        except ValueError:
            self._child_drop(sel, children, ch)
            return
        ch.task_id = h.task_id
        self._dispatch_child(sel, children, ch, h)

    def _dispatch_child(self, sel, children: set[_Child], ch: _Child,
                        h: P.Hello) -> None:
        now = time.monotonic()
        if h.cmd in (P.CMD_START, P.CMD_RECOVER, P.CMD_SPARE):
            # Park the connection; the hello rides the next (immediate)
            # batch and the tracker's reply is routed back by task id.
            # The conn STAYS on the selector (read-registered) so an EOF
            # while parked is noticed and reported upstream.
            ch.held = True
            ch.deadline = 0.0
            msg = P.BatchMsg(h.task_id, h.cmd, h.prev_rank,
                             ch.addr[0], h.listen_port, b"", time.time())
            with self._lock:
                old = self._held.pop(h.task_id, None)
                self._held[h.task_id] = ch.sock
                self._held_msg[h.task_id] = msg
                self._held_sent.discard(h.task_id)
                if h.cmd != P.CMD_SPARE:
                    self._leases.pop(h.task_id, None)
            if old is not None and old is not ch.sock:
                with self._lock:
                    self._defer_close.add(old)
            self._flush_now.set()
            return
        if h.cmd == P.CMD_QUORUM:
            # Batched agreement (doc/scaling.md, doc/ha.md): park the
            # child like a check-in and fold the report into the next
            # immediate envelope; the tracker routes the frozen record
            # back under the q#-prefixed key and ROUTE_CLOSE delivers
            # ACK + record JSON to this very socket.  One envelope per
            # flush replaces one root connection per rank per round.
            ch.held = True
            ch.deadline = 0.0
            key = "q#" + h.task_id
            ch.task_id = key
            msg = P.BatchMsg(key, P.CMD_QUORUM, h.prev_rank, ch.addr[0],
                             0, h.message.encode(), time.time())
            with self._lock:
                old = self._held.pop(key, None)
                self._held[key] = ch.sock
                self._held_msg[key] = msg
                self._held_sent.discard(key)
            if old is not None and old is not ch.sock:
                with self._lock:
                    self._defer_close.add(old)
            self._flush_now.set()
            return
        if h.cmd == P.CMD_BLOB:
            # Blob uploads: the relay caches the newest (job, version)
            # it has proxied — a re-upload of the same (or an older)
            # version is ACKed locally so N children re-shipping one
            # bootstrap blob cost the root ONE fetch; a version bump
            # invalidates the entry and proxies through (the last
            # per-call proxy, now amortized — doc/service.md).
            # DELIBERATELY synchronous upstream (not batch-channel):
            # the proxy runs on its own detached thread with bounded
            # timeouts, so the child reactor never blocks (tpulint's
            # reactor-blocking family verifies this — thread hand-offs
            # are not call edges); blobs are large and rare, and the
            # batch envelope is sized for control-plane records.
            # Folding blobs into CMD_BATCH stays the follow-on for the
            # depth-2+ relay tree (ROADMAP "N-way replicated tracker").
            job, _rest = P.split_job(h.task_id)
            with self._lock:
                cached = self._blob_cache.get(job)
            if cached is not None and h.blob_version <= cached[0]:
                self.stats["blob_cache_hits"] += 1
                self.stats["rpcs_terminated"] += 1
                ch.out += P.put_u32(P.ACK)
                self._child_flush(sel, children, ch)
                return
            self._child_detach(sel, children, ch)
            threading.Thread(target=self._proxy_blob, args=(ch.sock, h, job),
                             daemon=True,
                             name=f"relay-proxy-{self.relay_id}").start()
            return
        if h.cmd == P.CMD_SUB:
            # Delivery version-line poll (doc/delivery.md): a known line
            # (ack-refreshed, per job) answers LOCALLY — the subscriber
            # swarm's polls never touch the root.  Publishes, and polls
            # before any ACK named this job's line, park the child and
            # ride the next immediate batch; the tracker routes the
            # reply back under the s#-prefixed key (the quorum shape).
            job, _rest = P.split_job(h.task_id)
            with self._lock:
                line = self._sub_lines.get(job)
            if line is not None and "publish" not in h.message:
                self.stats["rpcs_terminated"] += 1
                ch.out += P.put_u32(P.ACK) + P.put_str(json.dumps(line))
                self._child_flush(sel, children, ch)
                return
            ch.held = True
            ch.deadline = 0.0
            key = "s#" + h.task_id
            ch.task_id = key
            msg = P.BatchMsg(key, P.CMD_SUB, h.prev_rank, ch.addr[0],
                             0, h.message.encode(), time.time())
            with self._lock:
                old = self._held.pop(key, None)
                self._held[key] = ch.sock
                self._held_msg[key] = msg
                self._held_sent.discard(key)
            if old is not None and old is not ch.sock:
                with self._lock:
                    self._defer_close.add(old)
            self._flush_now.set()
            return
        if h.cmd == P.CMD_SNAP:
            # Snapshot chunk fetch (doc/delivery.md): a cached digest is
            # sliced and answered locally (the CDN hit — repeat digests
            # cost the root nothing); a miss detaches to a proxy thread
            # that fetches the WHOLE blob once, caches it digest-keyed,
            # and answers the requested window.  Pure bytes math on the
            # hit path — the child reactor never blocks.
            try:
                req = json.loads(h.message) if h.message else {}
            except ValueError:
                req = {}
            if not isinstance(req, dict):
                req = {}
            digest = str(req.get("digest", ""))
            with self._lock:
                blob = self._digest_blobs.get(digest)
                if blob is not None:
                    self._digest_blobs.move_to_end(digest)
            if blob is not None:
                self.stats["snap_cache_hits"] += 1
                self.stats["rpcs_terminated"] += 1
                obs_stream.stream_count("delivery_cache_hits", 1,
                                        relay=self.relay_id)
                ch.out += self._snap_window(digest, blob, req)
                self._child_flush(sel, children, ch)
                return
            self.stats["snap_proxies"] += 1
            obs_stream.stream_count("delivery_cache_misses", 1,
                                    relay=self.relay_id)
            self._child_detach(sel, children, ch)
            threading.Thread(target=self._proxy_snap,
                             args=(ch.sock, h, req), daemon=True,
                             name=f"relay-snap-{self.relay_id}").start()
            return
        self.stats["rpcs_terminated"] += 1
        if h.cmd == P.CMD_HEARTBEAT:
            try:
                interval = float(h.message)
            except ValueError:
                interval = 0.0
            if 0 < interval < 86400:
                with self._lock:
                    self._leases[h.task_id] = _LocalLease(
                        interval,
                        now + P.LEASE_FACTOR * interval, h.prev_rank)
            ch.out += P.put_u32(P.ACK) + self._stamp()
        elif h.cmd == P.CMD_METRICS:
            # Strip any piggybacked streamed-metrics delta BEFORE the
            # latest-wins snapshot store: the delta folds into the
            # per-job sum accumulator (no window lost to coalescing, no
            # double-fold at the tracker), the cumulative snapshot
            # coalesces as before.  Pure dict math — the child reactor
            # must never block (doc/static_analysis.md).
            payload = h.message
            delta_doc = None
            try:
                snap = json.loads(payload)
                delta = (snap.pop("delta", None)
                         if isinstance(snap, dict) else None)
                if isinstance(delta, dict) and delta:
                    job, _rest = P.split_job(h.task_id)
                    rank = int(snap.get("rank", h.prev_rank))
                    delta_doc = obs_stream.delta_doc(job, rank, delta)
                    payload = json.dumps(snap)
            except (ValueError, TypeError):
                delta_doc = None
            with self._lock:
                self._metrics[h.task_id] = (h.prev_rank,
                                            payload.encode(), time.time())
                if delta_doc is not None:
                    job = delta_doc["job"]
                    self._deltas[job] = obs_stream.merge_delta_doc(
                        self._deltas.get(job), delta_doc)
            ch.out += P.put_u32(P.ACK) + self._stamp()
        elif h.cmd == P.CMD_EPOCH:
            # Per-job cache first (multi-job service, doc/service.md);
            # the legacy single-job cache serves bare task ids and any
            # job the ACK map has not named yet.
            job, _rest = P.split_job(h.task_id)
            cache = self._job_epochs.get(job) if job else None
            ch.out += (P.put_u32(P.ACK)
                       + P.put_str(json.dumps(cache if cache is not None
                                              else self._epoch_cache)))
        elif h.cmd == P.CMD_PRINT:
            with self._lock:
                self._queued.append(P.BatchMsg(
                    h.task_id, P.CMD_PRINT, h.prev_rank, ch.addr[0], 0,
                    h.message.encode(), time.time()))
            ch.out += P.put_u32(P.ACK)
        elif h.cmd == P.CMD_SHUTDOWN:
            with self._lock:
                self._leases.pop(h.task_id, None)
                self._queued.append(P.BatchMsg(
                    h.task_id, P.CMD_SHUTDOWN, h.prev_rank, ch.addr[0], 0,
                    b"", time.time()))
            self._flush_now.set()  # completion accounting must not wait
            ch.out += P.put_u32(P.ACK)
        else:
            self._child_drop(sel, children, ch)
            return
        self._child_flush(sel, children, ch)

    def _child_flush(self, sel, children: set[_Child], ch: _Child) -> None:
        while ch.out:
            try:
                n = ch.sock.send(ch.out)
            except (BlockingIOError, InterruptedError):
                try:
                    sel.modify(ch.sock, selectors.EVENT_WRITE, ch)
                except (KeyError, OSError, ValueError):
                    self._child_drop(sel, children, ch)
                return
            except OSError:
                self._child_drop(sel, children, ch)
                return
            del ch.out[:n]
        self._child_drop(sel, children, ch)

    def _proxy_rpc(self, conn: socket.socket, h: P.Hello) -> bool:
        """Pass one CMD_QUORUM/CMD_BLOB through to the root and relay the
        reply bytes back verbatim.  Returns True when the root ACKed."""
        ok = False
        try:
            try:
                with socket.create_connection(
                        self.tracker, timeout=self.rpc_timeout) as up:
                    up.settimeout(self.rpc_timeout)
                    P.send_hello(up, h.cmd, h.task_id,
                                 prev_rank=h.prev_rank, message=h.message,
                                 blob=h.blob, blob_version=h.blob_version)
                    ack = P.get_u32(up)
                    reply = P.put_u32(ack)
                    if h.cmd == P.CMD_QUORUM:
                        reply += P.put_str(P.get_str(up))
                ok = True
                conn.settimeout(self.rpc_timeout)
                conn.sendall(reply)
            except (ConnectionError, OSError, ValueError):
                pass  # child's bounded RPC retries; proxy must not wedge
        finally:
            try:
                conn.close()
            except OSError:
                pass
        return ok

    def _proxy_blob(self, conn: socket.socket, h: P.Hello,
                    job: str) -> None:
        """Proxy one blob upload and — only once the root ACKed — cache
        it digest-keyed for (job, version): a cache entry must never
        swallow re-uploads of a blob the root never received."""
        if self._proxy_rpc(conn, h) and h.blob_version > 0:
            digest = hashlib.sha256(h.blob).hexdigest()
            self._cache_put(digest, h.blob, job=job,
                            version=h.blob_version)

    def _proxy_snap(self, conn: socket.socket, h: P.Hello,
                    req: dict) -> None:
        """Fetch one digest's WHOLE snapshot from the root on a detached
        thread, cache it digest-keyed, and answer the child's requested
        window.  The whole-blob fetch is the dedup lever: every later
        subscriber asking for this digest is served locally.  A missing
        digest relays the root's empty frame — absence is retryable,
        never an error (doc/delivery.md)."""
        digest = str(req.get("digest", ""))
        blob = None
        try:
            try:
                with socket.create_connection(
                        self.tracker, timeout=self.rpc_timeout) as up:
                    up.settimeout(self.rpc_timeout)
                    P.send_hello(up, P.CMD_SNAP, h.task_id,
                                 message=json.dumps({"digest": digest}))
                    got, total, _off, payload = P.read_snap_frame(up)
                if got == digest and payload:
                    blob = payload
                    if len(payload) == total:
                        self._cache_put(digest, blob)
            except (ConnectionError, OSError, ValueError):
                pass
            conn.settimeout(self.rpc_timeout)
            if blob is None:
                conn.sendall(P.put_snap_frame("", 0, 0, b""))
            else:
                conn.sendall(self._snap_window(digest, blob, req))
        except OSError:
            pass
        finally:
            try:
                conn.close()
            except OSError:
                pass

    @staticmethod
    def _snap_window(digest: str, blob: bytes, req: dict) -> bytes:
        """One CMD_SNAP reply frame: the requested [off, off+len) window
        of a cached blob (len 0 / absent = the rest of the blob)."""
        try:
            off = max(int(req.get("off", 0)), 0)
            ln = int(req.get("len", 0) or 0)
        except (TypeError, ValueError):
            off, ln = 0, 0
        chunk = blob[off:off + ln] if ln > 0 else blob[off:]
        return P.put_snap_frame(digest, len(blob), off, chunk)

    # -- digest-keyed cache bookkeeping (doc/delivery.md) ------------------

    def _cache_put(self, digest: str, blob: bytes, job: str | None = None,
                   version: int = 0) -> None:
        """Insert one blob under its digest; optionally bind it as
        ``job``'s current (version, digest) entry, releasing the
        superseded digest.  Enforces the LRU byte budget by evicting
        UNREFERENCED digests oldest-first — bytes a live job still
        references are never dropped out from under a fetch."""
        with self._lock:
            if job is not None:
                old = self._blob_cache.get(job)
                self._blob_cache[job] = (version, digest)
                if old is None or old[1] != digest:
                    self._digest_refs[digest] = (
                        self._digest_refs.get(digest, 0) + 1)
                    if old is not None:
                        self._release_digest_locked(old[1], "superseded")
            if digest not in self._digest_blobs:
                self._digest_blobs[digest] = blob
                self._cache_used += len(blob)
            else:
                self._digest_blobs.move_to_end(digest)
            while self._cache_used > self._cache_budget:
                victim = next((d for d in self._digest_blobs
                               if self._digest_refs.get(d, 0) <= 0
                               and d != digest), None)
                if victim is None:
                    break
                vb = self._digest_blobs.pop(victim)
                self._cache_used -= len(vb)
                self._note_evicted_locked(victim, len(vb), "lru")

    def _release_digest_locked(self, digest: str, reason: str) -> None:
        """Drop one reference; evict the bytes once no job holds one."""
        n = self._digest_refs.get(digest, 1) - 1
        if n > 0:
            self._digest_refs[digest] = n
            return
        self._digest_refs.pop(digest, None)
        blob = self._digest_blobs.pop(digest, None)
        if blob is not None:
            self._cache_used -= len(blob)
            self._note_evicted_locked(digest, len(blob), reason)

    def _note_evicted_locked(self, digest: str, nbytes: int,
                             reason: str) -> None:
        self.stats["evictions"] += 1
        if len(self.events) < 4096:  # evidence, not a leak
            self.events.append({
                "ts": round(time.time(), 6), "kind": "blob_cache_evicted",
                "relay": self.relay_id, "digest": digest,
                "nbytes": nbytes, "reason": reason,
            })

    def _expire_local_leases(self) -> None:
        """Drop local leases past LEASE_FACTOR x interval: the child is
        gone, so its upstream renewals stop and the root's padded lease
        expires it — detection through a relay is local-lease + padded-
        lease, both bounded."""
        now = time.monotonic()
        with self._lock:
            for task_id in [t for t, l in self._leases.items()
                            if now >= l.expires]:
                del self._leases[task_id]

    def _unhold(self, task_id: str, notify: bool,
                expect: socket.socket | None = None) -> None:
        with self._lock:
            if expect is not None and self._held.get(task_id) is not expect:
                return  # superseded by a fresh check-in; leave it alone
            self._held.pop(task_id, None)
            self._held_msg.pop(task_id, None)
            was_sent = task_id in self._held_sent
            self._held_sent.discard(task_id)
            if notify and was_sent:
                self._queued.append(P.BatchMsg(
                    task_id, P.CMD_HANGUP, -1, "", 0, b"", time.time()))
                self._flush_now.set()

    # -- upstream pump ------------------------------------------------------

    def _drop_channel(self) -> None:
        with self._chan_lock:
            chan, self._chan = self._chan, None
        if chan is not None:
            try:
                chan.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                chan.close()
            except OSError:
                pass

    def _connect_channel(self) -> socket.socket | None:
        if self._partitioned:
            return None
        try:
            chan = socket.create_connection(self.tracker,
                                            timeout=self.rpc_timeout)
            chan.settimeout(self.rpc_timeout)
            P.send_hello(chan, P.CMD_BATCH, self.relay_id)
            if P.get_u32(chan) != P.ACK:
                chan.close()
                return None
            chan.settimeout(None)
        except (ConnectionError, OSError, ValueError):
            # Root failover rotation (doc/ha.md): the next connect
            # attempt tries the next configured tracker address — the
            # standby's pre-bound socket refuses until it takes over,
            # so the rotation settles on whichever address serves.
            if len(self.trackers) > 1:
                self._tr = (self._tr + 1) % len(self.trackers)
                self.stats["failovers"] += 1
            return None
        with self._chan_lock:
            self._chan = chan
        with self._lock:
            # Parked check-ins must be re-announced on a fresh channel:
            # the tracker replaces a task id's stale pending entry, so a
            # duplicate hello is safe and a lost one is not.  The last
            # un-ACKed envelope replays for the same reason (shutdowns,
            # prints, quorum reports — all idempotent at the tracker).
            self._held_sent.clear()
            self._replay = bool(self._unacked)
        self.stats["reconnects"] += 1
        threading.Thread(target=self._channel_reader, args=(chan,),
                         daemon=True,
                         name=f"relay-rx-{self.relay_id}").start()
        if not self.quiet:
            print(f"[relay {self.relay_id}] channel up to "
                  f"{self.tracker[0]}:{self.tracker[1]}", flush=True)
        return chan

    def _channel_reader(self, chan: socket.socket) -> None:
        """Route tracker frames to parked children until the channel
        dies.  Runs once per channel incarnation."""
        try:
            while not self._stopped.is_set():
                task_id, flags, payload = P.read_route_frame(chan)
                if task_id == "":
                    self._fold_ack(payload)
                    continue
                with self._lock:
                    conn = self._held.get(task_id)
                if conn is None:
                    continue  # child gave up and re-checked-in elsewhere
                self.stats["routed"] += 1
                try:
                    conn.settimeout(_HELD_SEND_TIMEOUT)
                    if payload:
                        conn.sendall(payload)
                except OSError:
                    self._unhold(task_id, notify=True, expect=conn)
                    with self._lock:
                        self._defer_close.add(conn)
                    continue
                if flags & P.ROUTE_CLOSE:
                    self._unhold(task_id, notify=False, expect=conn)
                    with self._lock:
                        self._defer_close.add(conn)
        except (ConnectionError, OSError, ValueError):
            pass
        finally:
            with self._chan_lock:
                if self._chan is chan:
                    self._chan = None
            try:
                chan.close()
            except OSError:
                pass

    def _fold_ack(self, payload: bytes) -> None:
        try:
            info = json.loads(payload.decode())
        except (ValueError, UnicodeDecodeError):
            return
        if "epoch" in info:
            self._epoch_cache = {"epoch": info.get("epoch", 0),
                                 "world": info.get("world", 0),
                                 "rewave": bool(info.get("rewave"))}
        line = info.get("delivery")
        if isinstance(line, dict):
            # single-job delivery line (bare task ids → job "")
            with self._lock:
                self._sub_lines[""] = dict(line)
        jobs = info.get("jobs")
        if isinstance(jobs, dict):
            # per-job epoch caches from a CollectiveService's ACK; one
            # whole-map swap keeps reads torn-free without a lock
            self._job_epochs = {
                str(k): {"epoch": v.get("epoch", 0),
                         "world": v.get("world", 0),
                         "rewave": bool(v.get("rewave"))}
                for k, v in jobs.items() if isinstance(v, dict)}
            sub_lines = {
                str(k): dict(v["delivery"]) for k, v in jobs.items()
                if isinstance(v, dict) and isinstance(v.get("delivery"),
                                                      dict)}
            with self._lock:
                bare = self._sub_lines.get("")
                self._sub_lines = sub_lines
                if bare is not None:
                    self._sub_lines[""] = bare
                # Retirement sweep (doc/delivery.md): a job the service
                # ACK no longer names is retired — release its cache
                # reference so the digest's bytes evict once no other
                # job shares them.
                for job in [j for j in self._blob_cache
                            if j and j not in jobs]:
                    old = self._blob_cache.pop(job)
                    self._release_digest_locked(old[1], "job_retired")
        t_recv = time.time()
        t_send = getattr(self, "_last_batch_send", None)
        server_ts = info.get("server_ts")
        if t_send is not None and server_ts is not None:
            err = max(t_recv - t_send, 0.0) / 2.0
            # best-by-error with decay, mirroring obs.trace.ClockSync's
            # preference for tight round trips
            if err <= self.clock_err * 2.0 or err < 0.05:
                self.clock_offset = float(server_ts) - (t_send + t_recv) / 2
                self.clock_err = err
        with self._lock:
            self._unacked = []  # the envelope landed; nothing to replay
        self._ack.set()

    def _build_batch(self) -> list[P.BatchMsg]:
        now = time.time()
        with self._lock:
            msgs = list(self._queued)
            self._queued = []
            for task_id, msg in self._held_msg.items():
                if task_id not in self._held_sent:
                    msgs.append(msg)
                    self._held_sent.add(task_id)
            # liveness, coalesced: every live local lease re-advertised
            # with the PADDED upstream interval (see RELAY_LEASE_PAD)
            pad = RELAY_LEASE_PAD
            for task_id, lease in self._leases.items():
                up_interval = max(lease.interval, self.flush_sec) * pad
                msgs.append(P.BatchMsg(
                    task_id, P.CMD_HEARTBEAT, lease.prev_rank, "", 0,
                    f"{up_interval:.6f}".encode(), now))
            # metrics, coalesced: latest snapshot per task since the
            # last flush
            for task_id, (rank, payload, ts) in self._metrics.items():
                msgs.append(P.BatchMsg(task_id, P.CMD_METRICS, rank, "", 0,
                                       payload, ts))
            self._metrics = {}
            # streamed-metric deltas: ONE coalesced frame per job per
            # flush, routed as "<job>/#delta" so a multi-job service
            # folds each frame into the owning partition.  An oversized
            # window (> protocol.DELTA_MAX_BYTES compressed) is dropped
            # whole — bounded frames are the contract.
            deltas, self._deltas = self._deltas, {}
            for job, doc in sorted(deltas.items()):
                try:
                    frame = P.put_delta_frame(doc)
                except ValueError:
                    continue
                msgs.append(P.BatchMsg(P.join_job(job, "#delta"),
                                       P.CMD_OBS, -1, "", 0, frame, now))
        return msgs

    def _upstream_pump(self) -> None:
        backoff = 0.05
        while not self._stopped.is_set():
            self._flush_now.wait(self.flush_sec)
            self._flush_now.clear()
            if self._stopped.is_set():
                return
            with self._chan_lock:
                chan = self._chan
            if chan is None:
                chan = self._connect_channel()
                if chan is None:
                    time.sleep(min(backoff, 1.0))
                    backoff = min(backoff * 2, 1.0)
                    continue
                backoff = 0.05
            # An empty batch still goes out: it is the keepalive that
            # refreshes the epoch cache (rewave reaches idle children)
            # and the clock-offset estimate.
            msgs = self._build_batch()
            with self._lock:
                if self._replay and self._unacked:
                    # Fresh channel, un-ACKed envelope outstanding:
                    # replay it ahead of the new batch — the old root
                    # may have died between our send and its ACK, and
                    # the new one dedupes (doc/ha.md).
                    msgs = self._unacked + msgs
                    self.stats["replayed_msgs"] += len(self._unacked)
                self._replay = False
            self._ack.clear()
            self._last_batch_send = time.time()
            try:
                chan.sendall(P.put_batch_frame(msgs))
            except OSError:
                # Channel died mid-flush: requeue nothing beyond the
                # replayable envelope below (heartbeats and metrics
                # re-coalesce next interval; held hellos re-send on
                # reconnect via _held_sent), drop, retry.
                with self._lock:
                    self._unacked = [
                        m for m in msgs
                        if m.cmd not in (P.CMD_HEARTBEAT, P.CMD_METRICS,
                                         P.CMD_OBS)]
                self._drop_channel()
                continue
            with self._lock:
                self._unacked = [
                    m for m in msgs
                    if m.cmd not in (P.CMD_HEARTBEAT, P.CMD_METRICS,
                                     P.CMD_OBS)]
            self.stats["batches"] += 1
            self.stats["batch_msgs"] += len(msgs)
            self._ack.wait(self.rpc_timeout)
