"""Diagnosis plane — live incident detection over the streamed telemetry.

The live telemetry plane (doc/observability.md) ships per-link
``link_wait_seconds{src,dst}`` histograms and control-plane events to the
tracker, but PR 16 left interpretation to humans staring at ``obs_top``.
This module is the detection layer: a :class:`HealthMonitor` hangs off
every tracker (and every partition of a ``CollectiveService``), evaluates
a fixed rule set over :class:`~rabit_tpu.obs.stream.StreamRollup` deltas
once per detection window, and opens/resolves structured
:class:`IncidentReport` s with the evidence chain that fired them.

The two wait-shape rules implement the separation the papers motivate
("Don't Let a Few Network Failures Slow the Entire AllReduce" — localize
the ONE degraded link; "Efficient AllReduce with Stragglers" — tell a
compute straggler apart from a link fault).  Both faults surface as ring
wait, but with opposite shapes:

* a **degraded link** (src, dst) delays every frame crossing it.  In the
  first rounds its DST accumulates by far the most wait, so window wait
  CONCENTRATES on one link — but in steady state the delay bubble
  CIRCULATES: the late dst asks late next round, absorbs the transit
  delay, and charges the wait to its own downstream link, so cumulative
  link waits equalize around the ring.  The worker's in-round self-report
  (``slow_link`` print -> ``link_degraded`` event, measured against its
  OWN round wall before the rotation smears anything) is therefore the
  attribution signal, and the sustained elevated window wait is the
  consecutive-window evidence the hysteresis gates on;
* a **compute straggler** r re-injects its delay at the SAME rank every
  round (no rotation — the sleep recurs at the source), so every OTHER
  rank waits roughly once per round on its own incoming link while r's
  incoming frames are long since queued: window wait SPREADS uniformly
  with a near-zero HOLE at r's incoming link — the hole names the rank.

Hysteresis: a rule must fire ``rabit_diag_open_windows`` consecutive
windows before an incident opens, and stay quiet
``rabit_diag_resolve_windows`` windows before it resolves — one noisy
window indicts nobody, and a flapping link is one incident, not fifty.
Confirmed ``degraded-link`` incidents feed the tracker's avoid-set
repair machinery (``Tracker.flag_link``), replacing the one-report-
per-epoch wait-share self-report as the attributed repair signal.

Everything here is pure dict math over already-assembled state — no IO,
no sockets — so it is safe anywhere the tracker calls it (the monitor
tick thread; never the reactor, see doc/static_analysis.md).
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field

from rabit_tpu.config import Config

#: Incident exposition schema (bump on incompatible change).
DIAG_SCHEMA = 1

#: Every incident class this engine can open, with the rule in one line.
INCIDENT_CLASSES: dict[str, str] = {
    "degraded-link": "one planned-ring link holds a dominant share of the "
                     "window's link wait (concentration shape), or a "
                     "worker self-report attributes the sustained wait "
                     "to its incoming link (steady-state rotation shape)",
    "compute-straggler": "window link wait is spread across the ring with "
                         "a near-zero hole at one rank's incoming link "
                         "(the hole names the late-entering rank)",
    "lost-relay": "a relay's persistent batch channel died and stayed "
                  "down (relay_lost without a matching relay_up)",
    "tracker-saturation": "the bounded worker-print log is actively "
                          "dropping messages (messages_dropped growing)",
    "preemption-storm": "several heartbeat leases expired within the "
                        "recent windows (mass preemption, not one death)",
}

#: The degraded-link rule's second gate: the top link must also dominate
#: the runner-up by this factor, so a 2-link world's naturally ~50/50
#: clean split can never cross the share threshold alone.
DOMINANCE = 2.0

#: Evidence entries kept per incident / resolved incidents kept.
EVIDENCE_CAP = 8
HISTORY_CAP = 16


@dataclass
class IncidentReport:
    """One open (or resolved) incident: class, the subject it names, and
    the capped evidence chain of window observations that fired it."""

    incident_id: str
    cls: str
    subject: dict
    opened_ts: float
    windows: int = 0                 # windows of supporting evidence seen
    resolved_ts: float | None = None
    evidence: list[dict] = field(default_factory=list)

    def add_evidence(self, obs: dict) -> None:
        self.windows += 1
        self.evidence.append(obs)
        if len(self.evidence) > EVIDENCE_CAP:
            del self.evidence[0]

    def to_doc(self) -> dict:
        doc = {
            "id": self.incident_id,
            "class": self.cls,
            "subject": dict(self.subject),
            "opened_ts": round(self.opened_ts, 6),
            "windows": self.windows,
            "evidence": [dict(e) for e in self.evidence],
        }
        if self.resolved_ts is not None:
            doc["resolved_ts"] = round(self.resolved_ts, 6)
        return doc


def _rank_of(label: str | int):
    """Rollup link labels are strings; incidents name integer ranks when
    they can (a non-numeric label passes through verbatim)."""
    try:
        return int(label)
    except (TypeError, ValueError):
        return label


class HealthMonitor:
    """The detection-rule engine.  One per tracker/partition; the owner
    calls :meth:`observe` once per detection window from its monitor
    thread and :meth:`render` from scrape/telemetry assembly.  All state
    lives behind one leaf lock (nothing is called while it is held)."""

    def __init__(self, cfg: Config | None = None):
        cfg = cfg or Config()
        self.enabled = cfg.get_bool("rabit_diag_enable", True)
        self.window_sec = float(cfg.get("rabit_diag_window_sec", "0.5")
                                or "0.5")
        self.open_windows = max(cfg.get_int("rabit_diag_open_windows", 2), 1)
        self.resolve_windows = max(
            cfg.get_int("rabit_diag_resolve_windows", 4), 1)
        self.min_wait_sec = float(cfg.get("rabit_diag_min_wait_sec", "0.05")
                                  or "0.05")
        self.link_share = float(cfg.get("rabit_diag_link_share", "0.5")
                                or "0.5")
        self.hole_ratio = float(cfg.get("rabit_diag_hole_ratio", "0.25")
                                or "0.25")
        self.storm_leases = max(cfg.get_int("rabit_diag_storm_leases", 3), 1)
        self._lock = threading.Lock()
        # previous window's cumulative link table / fold count / drops
        self._prev_links: dict[tuple, tuple[int, float]] = {}
        self._prev_folds = 0
        self._prev_dropped = 0
        # rolling per-window severities for the burst-shaped rules
        self._expiry_windows: list[int] = []
        self._drop_windows: list[int] = []
        self._relays_down: set[str] = set()
        # worker self-report attribution: (src, dst) -> the strongest
        # link_degraded report seen while the wait symptom persists
        self._attributed: dict[tuple[str, str], dict] = {}
        # hysteresis state, keyed by (class, subject-key)
        self._streak: dict[tuple, int] = {}
        self._quiet: dict[tuple, int] = {}
        self._open: dict[tuple, IncidentReport] = {}
        self._history: list[IncidentReport] = []
        self._seq = 0
        self.n_opened = 0
        self.n_resolved = 0

    # -- rule evaluation (pure dict math) ---------------------------------

    @staticmethod
    def _link_table(stream_doc: dict) -> dict[tuple, tuple[int, float]]:
        """Cumulative (count, wait-sum) per (src, dst) from a rendered
        rollup's ``links`` rows."""
        table: dict[tuple, tuple[int, float]] = {}
        for row in stream_doc.get("links", ()):
            key = (str(row.get("src")), str(row.get("dst")))
            table[key] = (int(row.get("count", 0)),
                          float(row.get("sum", 0.0)))
        return table

    def _wait_candidates(self, ts: float, links: dict) -> list[tuple]:
        """The two wait-shape rules over one window's link-wait deltas.
        Returns at most one ``(class, subject_key, subject, evidence)``
        candidate — concentration beats the hole check, so a fault that
        produces both shapes is one incident, not two."""
        window: dict[tuple, tuple[int, float]] = {}
        for key, (count, wsum) in links.items():
            pc, ps = self._prev_links.get(key, (0, 0.0))
            dc, dw = count - pc, wsum - ps
            if dc > 0:
                window[key] = (dc, max(dw, 0.0))
        total = sum(dw for _dc, dw in window.values())
        if not window or total < self.min_wait_sec:
            # The wait symptom is gone: any standing self-report
            # attribution is stale (the link healed or was repaired).
            self._attributed.clear()
            return []
        rows = sorted(window.items(), key=lambda kv: -kv[1][1])
        (top_key, (top_n, top_w)) = rows[0]
        second_w = rows[1][1][1] if len(rows) > 1 else 0.0
        share = top_w / total
        if share >= self.link_share and top_w >= DOMINANCE * second_w:
            src, dst = top_key
            ev = {"ts": round(ts, 6), "rule": "link-wait-concentration",
                  "window_wait_s": round(total, 6),
                  "link_wait_s": round(top_w, 6),
                  "share": round(share, 4), "n_links": len(window),
                  "n_waits": top_n}
            subject = {"src": _rank_of(src), "dst": _rank_of(dst)}
            return [("degraded-link", ("link", src, dst), subject, ev)]
        if len(window) >= 3:
            (low_key, (_low_n, low_w)) = rows[-1]
            mean = total / len(window)
            if low_w <= self.hole_ratio * mean:
                rank = low_key[1]  # dst of the hole link entered late
                ev = {"ts": round(ts, 6), "rule": "link-wait-hole",
                      "window_wait_s": round(total, 6),
                      "hole_link": [_rank_of(low_key[0]), _rank_of(rank)],
                      "hole_wait_s": round(low_w, 6),
                      "mean_link_wait_s": round(mean, 6),
                      "n_links": len(window)}
                subject = {"rank": _rank_of(rank)}
                return [("compute-straggler", ("rank", rank), subject, ev)]
        if self._attributed:
            # Steady-state degraded link: the delay bubble circulates and
            # the cumulative sums equalize (see module docstring), so the
            # worker's in-round self-report names the link and the
            # sustained window wait carries the streak.  The strongest
            # report wins, so one fault is one incident.
            (src, dst), rep = max(self._attributed.items(),
                                  key=lambda kv: kv[1]["share"])
            ev = {"ts": round(ts, 6), "rule": "link-wait-attributed",
                  "window_wait_s": round(total, 6),
                  "reported_share": round(rep["share"], 4),
                  "reported_wait_s": round(rep["wait"], 6),
                  "n_links": len(window)}
            subject = {"src": _rank_of(src), "dst": _rank_of(dst)}
            return [("degraded-link", ("link", src, dst), subject, ev)]
        return []

    def _state_candidates(self, ts: float, state: dict) -> list[tuple]:
        """Control-plane rules over the tracker-assembled window state:
        relay losses, print-log drops, lease-expiry bursts."""
        out: list[tuple] = []
        for ev in state.get("events_delta", ()):
            kind = ev.get("kind")
            if kind == "relay_lost" and "relay" in ev:
                self._relays_down.add(str(ev["relay"]))
            elif kind == "relay_up" and "relay" in ev:
                self._relays_down.discard(str(ev["relay"]))
        for relay in sorted(self._relays_down):
            out.append(("lost-relay", ("relay", relay), {"relay": relay},
                        {"ts": round(ts, 6), "rule": "relay-channel-down",
                         "relay": relay}))
        dropped = int(state.get("messages_dropped", 0))
        self._drop_windows.append(max(dropped - self._prev_dropped, 0))
        self._prev_dropped = dropped
        del self._drop_windows[:-max(self.open_windows, 2)]
        drops = sum(self._drop_windows)
        if drops > 0:
            out.append(("tracker-saturation", ("saturation",),
                        {"dropped": dropped},
                        {"ts": round(ts, 6), "rule": "print-log-dropping",
                         "recent_drops": drops, "total_dropped": dropped}))
        expired = [ev for ev in state.get("events_delta", ())
                   if ev.get("kind") == "lease_expired"]
        self._expiry_windows.append(len(expired))
        del self._expiry_windows[:-max(self.open_windows, 2)]
        burst = sum(self._expiry_windows)
        if burst >= self.storm_leases:
            out.append(("preemption-storm", ("storm",),
                        {"n_expired": burst},
                        {"ts": round(ts, 6), "rule": "lease-expiry-burst",
                         "n_expired": burst,
                         "tasks": sorted(str(ev.get("task_id", "?"))
                                         for ev in expired)}))
        return out

    # -- the window tick ---------------------------------------------------

    def observe(self, now: float, stream_doc: dict,
                state: dict) -> tuple[list[IncidentReport],
                                      list[IncidentReport]]:
        """Evaluate one detection window.  ``stream_doc`` is a rendered
        rollup (:meth:`StreamRollup.render`), ``state`` the owner's small
        window-state dict (``events_delta``, ``messages_dropped``, ...).
        Returns ``(opened, resolved)`` incident lists; the caller emits
        the events and feeds the repair hook."""
        if not self.enabled:
            return [], []
        ts = time.time()
        with self._lock:
            for ev in state.get("events_delta", ()):
                # Worker degraded-link self-reports attribute the wait
                # shape (quorum-sourced flags name a straggler RANK and
                # already carry their own round-count hysteresis, and
                # origin-stamped reports are operator decisions that
                # flag the link directly with synthetic evidence — they
                # are not link-fault attribution).
                if ev.get("kind") == "link_degraded" \
                        and ev.get("via") != "quorum" \
                        and not ev.get("origin") \
                        and "src" in ev and "dst" in ev:
                    key = (str(ev["src"]), str(ev["dst"]))
                    rep = {"share": float(ev.get("share", 0.0) or 0.0),
                           "wait": float(ev.get("wait", 0.0) or 0.0)}
                    old = self._attributed.get(key)
                    if old is None or rep["share"] >= old["share"]:
                        self._attributed[key] = rep
            folds = int(stream_doc.get("n_folds", 0))
            links = self._link_table(stream_doc)
            fresh_folds = folds != self._prev_folds
            candidates: list[tuple] = []
            if fresh_folds:
                # No new folds means no wait evidence either way: the
                # wait-shape streaks freeze instead of decaying, so a
                # heartbeat hiccup cannot flap an open incident.
                candidates += self._wait_candidates(ts, links)
                self._prev_links = links
                self._prev_folds = folds
            candidates += self._state_candidates(ts, state)
            fired = {key: (cls, subject, ev)
                     for cls, key, subject, ev in candidates}
            opened: list[IncidentReport] = []
            resolved: list[IncidentReport] = []
            for key, (cls, subject, ev) in fired.items():
                self._streak[key] = self._streak.get(key, 0) + 1
                self._quiet.pop(key, None)
                inc = self._open.get(key)
                if inc is not None:
                    inc.add_evidence(ev)
                elif self._streak[key] >= self.open_windows:
                    self._seq += 1
                    inc = IncidentReport(
                        incident_id=f"{cls}#{self._seq}", cls=cls,
                        subject=subject, opened_ts=ts)
                    inc.windows = self._streak[key] - 1
                    inc.add_evidence(ev)
                    self._open[key] = inc
                    self.n_opened += 1
                    opened.append(inc)
            wait_frozen = not fresh_folds
            for key in list(self._streak):
                if key in fired:
                    continue
                if wait_frozen and key[0] in ("link", "rank"):
                    continue  # no evidence either way this window
                if key in self._open:
                    self._quiet[key] = self._quiet.get(key, 0) + 1
                    if self._quiet[key] >= self.resolve_windows:
                        inc = self._open.pop(key)
                        inc.resolved_ts = ts
                        self._history.append(inc)
                        del self._history[:-HISTORY_CAP]
                        self._streak.pop(key, None)
                        self._quiet.pop(key, None)
                        self.n_resolved += 1
                        resolved.append(inc)
                else:
                    self._streak.pop(key, None)
            return opened, resolved

    # -- exposition --------------------------------------------------------

    def open_incidents(self) -> list[IncidentReport]:
        with self._lock:
            return sorted(self._open.values(), key=lambda i: i.opened_ts)

    def render(self) -> dict:
        """The ``incidents`` section a scrape/telemetry document embeds:
        open incidents (oldest first), a capped resolved history, and the
        lifetime counters."""
        with self._lock:
            return {
                "schema": DIAG_SCHEMA,
                "enabled": self.enabled,
                "window_sec": self.window_sec,
                "n_opened": self.n_opened,
                "n_resolved": self.n_resolved,
                "open": [i.to_doc() for i in sorted(
                    self._open.values(), key=lambda i: i.opened_ts)],
                "recent": [i.to_doc() for i in self._history],
            }
