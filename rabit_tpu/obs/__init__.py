"""rabit_tpu.obs — per-rank observability: flight recorder + metrics.

Three pieces (ISSUE 1 tentpole):

* a per-rank **flight recorder** (events.py) — bounded ring of structured
  events: op begin/end with cache_key/nbytes, bootstrap/recovery phases,
  checkpoint commits, engine lifecycle;
* a **metrics registry** (metrics.py) — thread-safe counters / gauges /
  latency histograms subsuming the old ``CollectiveStats``;
* **shipping** (ship.py) — workers send metric snapshots to the tracker
  (``CMD_METRICS``) on shutdown/heartbeat; the tracker writes a job-level
  ``telemetry.json``.

This module owns the process-wide singletons and the failure paths: when
``RABIT_OBS_DIR`` (or ``rabit_obs_dir=``) is configured, a SIGTERM or a
collective stuck past ``rabit_obs_hang_sec`` dumps the flight recorder to
``<dir>/flight-rank<R>-pid<P>-<reason>.jsonl`` (NCCL-flight-recorder
style), so hangs produce evidence instead of silence.
"""

from __future__ import annotations

import contextlib
import os
import signal
import threading
import time

from rabit_tpu.obs.events import (  # noqa: F401 (re-exports)
    DEFAULT_CAPACITY,
    Event,
    FlightRecorder,
    event_from_stats_line,
    events_from_lines,
    load_dump,
)
from rabit_tpu.obs.metrics import (  # noqa: F401 (re-exports)
    GLOBAL_REGISTRY,
    Counter,
    Gauge,
    Histogram,
    MetricsRegistry,
    OpStats,
    _Span,
)
from rabit_tpu.obs import ship as _ship

#: Process-wide flight recorder (engine + api layers record into it).
GLOBAL_RECORDER = FlightRecorder()


def get_recorder() -> FlightRecorder:
    return GLOBAL_RECORDER


def get_registry() -> MetricsRegistry:
    return GLOBAL_REGISTRY


def record_event(kind: str, /, **fields) -> Event:
    """Record one structured event into the process flight recorder."""
    return GLOBAL_RECORDER.record(kind, **fields)


# -- process obs state -------------------------------------------------------

class _ObsState:
    """Mutable per-process configuration filled in by ``configure``."""

    def __init__(self):
        self.lock = threading.Lock()
        self.obs_dir: str = ""
        self.hang_sec: float = 300.0
        self.rank: int = -1
        self.task_id: str = ""
        self.tracker: tuple[str, int] | None = None
        self.heartbeat: _ship.Heartbeat | None = None
        self.watchdog_started = False
        self.sigterm_installed = False
        self.prev_sigterm = None
        self.hang_dumped = False
        # thread-id -> (op, cache_key, t0_monotonic) of in-flight collectives
        self.inflight: dict[int, tuple[str, str | None, float]] = {}


_STATE = _ObsState()


def configure(config, rank: int = -1) -> None:
    """Wire observability from the engine config.  Called by
    ``rabit_tpu.init`` after the engine is up (and safe to call again on a
    later init: singletons persist, identity/settings are refreshed).

    Keys (doc/observability.md): ``rabit_obs_dir`` (also the plain
    ``RABIT_OBS_DIR`` env var), ``rabit_obs_capacity``,
    ``rabit_obs_hang_sec``, ``rabit_obs_heartbeat_sec``.
    """
    obs_dir = (config.get("rabit_obs_dir", "") or
               os.environ.get("RABIT_OBS_DIR", "") or "")
    if obs_dir == "NULL":
        obs_dir = ""
    capacity = config.get_int("rabit_obs_capacity", DEFAULT_CAPACITY)
    hang_sec = float(config.get("rabit_obs_hang_sec", "300") or "300")
    heartbeat_sec = float(config.get("rabit_obs_heartbeat_sec", "0") or "0")
    tracker_uri = config.get("rabit_tracker_uri", "NULL")
    task_id = config.get("rabit_task_id", "NULL") or "NULL"

    GLOBAL_RECORDER.set_capacity(capacity)
    with _STATE.lock:
        _STATE.obs_dir = obs_dir
        _STATE.hang_sec = hang_sec
        _STATE.rank = rank
        _STATE.task_id = task_id
        _STATE.tracker = None
        if tracker_uri and tracker_uri != "NULL":
            _STATE.tracker = (
                tracker_uri, config.get_int("rabit_tracker_port", 9091)
            )
    if obs_dir:
        os.makedirs(obs_dir, exist_ok=True)
        _install_sigterm_dump()
        if hang_sec > 0:
            _start_hang_watchdog()
    if heartbeat_sec > 0 and _STATE.tracker is not None:
        stop_heartbeat()
        hb = _ship.Heartbeat(
            heartbeat_sec, _make_snapshot,
            _STATE.tracker[0], _STATE.tracker[1], task_id,
        ).start()
        with _STATE.lock:
            _STATE.heartbeat = hb


# -- collective spans --------------------------------------------------------

@contextlib.contextmanager
def collective(op: str, nbytes: int, cache_key: str | None = None):
    """The one timing/eventing path for every public collective: records
    ``op_begin``/``op_end`` events, marks the thread in-flight for the hang
    watchdog, and times into the registry's per-op stats + latency
    histogram.  Yields a span whose ``nbytes`` may be updated inside the
    window (object broadcast learns its length from the wire)."""
    tid = threading.get_ident()
    record_event("op_begin", op=op, nbytes=nbytes, cache_key=cache_key)
    with _STATE.lock:
        _STATE.inflight[tid] = (op, cache_key, time.monotonic())
    t0 = time.perf_counter()
    span = _Span(op, nbytes, cache_key)
    try:
        yield span
    finally:
        dt = time.perf_counter() - t0
        with _STATE.lock:
            _STATE.inflight.pop(tid, None)
        GLOBAL_REGISTRY.observe_op(op, span.nbytes, dt)
        record_event("op_end", op=op, nbytes=span.nbytes,
                     cache_key=cache_key, seconds=round(dt, 6))


# -- failure-path dumps ------------------------------------------------------

def dump_now(reason: str) -> str | None:
    """Dump the flight recorder to the configured obs dir; returns the path
    (None when no dir is configured).  Never raises."""
    with _STATE.lock:
        obs_dir, rank = _STATE.obs_dir, _STATE.rank
        inflight = list(_STATE.inflight.values())
    if not obs_dir:
        return None
    try:
        for op, key, t0 in inflight:
            record_event("op_inflight", op=op, cache_key=key,
                         stuck_seconds=round(time.monotonic() - t0, 3))
        path = os.path.join(
            obs_dir, f"flight-rank{rank}-pid{os.getpid()}-{reason}.jsonl"
        )
        return GLOBAL_RECORDER.dump(
            path, header={"reason": reason, "rank": rank,
                          "task_id": _STATE.task_id}
        )
    except OSError:
        return None


def _on_sigterm(signum, frame):
    dump_now("sigterm")
    prev = _STATE.prev_sigterm
    if callable(prev):
        prev(signum, frame)
        return
    # restore the previous disposition and re-deliver so the process still
    # dies with the normal SIGTERM exit status
    signal.signal(signal.SIGTERM, prev if prev is not None else signal.SIG_DFL)
    os.kill(os.getpid(), signal.SIGTERM)


def _install_sigterm_dump() -> None:
    with _STATE.lock:
        if _STATE.sigterm_installed:
            return
        _STATE.sigterm_installed = True
    try:
        _STATE.prev_sigterm = signal.signal(signal.SIGTERM, _on_sigterm)
    except ValueError:
        # not the main thread — the watchdog still covers hangs
        with _STATE.lock:
            _STATE.sigterm_installed = False


def _watchdog_loop() -> None:
    while True:
        with _STATE.lock:
            hang_sec = _STATE.hang_sec
            obs_dir = _STATE.obs_dir
            dumped = _STATE.hang_dumped
            stuck = None
            if hang_sec > 0:
                now = time.monotonic()
                for op, key, t0 in _STATE.inflight.values():
                    if now - t0 > hang_sec:
                        stuck = (op, key, now - t0)
                        break
        if obs_dir and not dumped and stuck is not None:
            record_event("hang_detected", op=stuck[0], cache_key=stuck[1],
                         stuck_seconds=round(stuck[2], 3))
            dump_now("hang")
            with _STATE.lock:
                _STATE.hang_dumped = True
        time.sleep(min(1.0, hang_sec / 4.0) if hang_sec > 0 else 1.0)


def _start_hang_watchdog() -> None:
    with _STATE.lock:
        if _STATE.watchdog_started:
            return
        _STATE.watchdog_started = True
    threading.Thread(
        target=_watchdog_loop, name="rabit-obs-watchdog", daemon=True
    ).start()


# -- shutdown shipping -------------------------------------------------------

def _make_snapshot() -> dict:
    with _STATE.lock:
        rank, task_id = _STATE.rank, _STATE.task_id
    return _ship.build_snapshot(
        GLOBAL_REGISTRY, rank, task_id,
        extra={"flight_dropped": GLOBAL_RECORDER.dropped},
    )


def stop_heartbeat() -> None:
    with _STATE.lock:
        hb, _STATE.heartbeat = _STATE.heartbeat, None
    if hb is not None:
        hb.stop()


def ship_final_snapshot() -> bool:
    """Ship the shutdown-time snapshot to the tracker (best-effort; False
    when no tracker is configured or the send failed).  Called by
    ``rabit_tpu.finalize`` BEFORE the engine's own shutdown handshake so
    the tracker is still serving when the snapshot arrives."""
    stop_heartbeat()
    with _STATE.lock:
        tracker, task_id = _STATE.tracker, _STATE.task_id
    if tracker is None:
        return False
    return _ship.ship_snapshot(_make_snapshot(), tracker[0], tracker[1],
                               task_id)
