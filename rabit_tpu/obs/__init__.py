"""rabit_tpu.obs — per-rank observability: flight recorder + metrics.

Three pieces (ISSUE 1 tentpole):

* a per-rank **flight recorder** (events.py) — bounded ring of structured
  events: op begin/end with cache_key/nbytes, bootstrap/recovery phases,
  checkpoint commits, engine lifecycle;
* a **metrics registry** (metrics.py) — thread-safe counters / gauges /
  latency histograms subsuming the old ``CollectiveStats``;
* **shipping** (ship.py) — workers send metric snapshots to the tracker
  (``CMD_METRICS``) on shutdown/heartbeat; the tracker writes a job-level
  ``telemetry.json``.

This module owns the process-wide singletons and the failure paths: when
``RABIT_OBS_DIR`` (or ``rabit_obs_dir=``) is configured, a SIGTERM or a
collective stuck past ``rabit_obs_hang_sec`` dumps the flight recorder to
``<dir>/flight-rank<R>-pid<P>-<reason>.jsonl`` (NCCL-flight-recorder
style), so hangs produce evidence instead of silence.

Two liveness escalations ride the same watchdog (doc/fault_tolerance.md):

* ``rabit_hang_abort_sec`` > 0 — dump-then-die: after the evidence dump, a
  rank stuck past the bound aborts itself (exit ``HANG_ABORT_EXIT``) so
  the launcher restarts it and the job heals instead of idling;
* ``rabit_heartbeat_sec`` > 0 — a lease renewal ticker to the tracker
  (``CMD_HEARTBEAT``).  Renewal is withheld once the watchdog declares
  this process hung, so a stuck-but-scheduling worker is suspected by the
  tracker exactly like a frozen one.
"""

from __future__ import annotations

import contextlib
import os
import signal
import sys
import threading
import time

from rabit_tpu.obs.events import (  # noqa: F401 (re-exports)
    DEFAULT_CAPACITY,
    Event,
    FlightRecorder,
    event_from_stats_line,
    events_from_lines,
    load_dump,
)
from rabit_tpu.obs.metrics import (  # noqa: F401 (re-exports)
    GLOBAL_REGISTRY,
    Counter,
    Gauge,
    Histogram,
    MetricsRegistry,
    OpStats,
    _Span,
)
from rabit_tpu.obs import ship as _ship
from rabit_tpu.obs import stream as _stream
from rabit_tpu.obs.trace import GLOBAL_CLOCK  # noqa: F401 (re-export)

#: Exit code of the hang-abort escalation (dump-then-die).  Distinct from
#: the native recovery watchdog's exit 10 so launch logs tell the two
#: detectors apart.
HANG_ABORT_EXIT = 11

#: Process-wide flight recorder (engine + api layers record into it).
GLOBAL_RECORDER = FlightRecorder()


def get_recorder() -> FlightRecorder:
    return GLOBAL_RECORDER


def get_registry() -> MetricsRegistry:
    return GLOBAL_REGISTRY


def record_event(kind: str, /, **fields) -> Event:
    """Record one structured event into the process flight recorder."""
    return GLOBAL_RECORDER.record(kind, **fields)


# -- process obs state -------------------------------------------------------

class _ObsState:
    """Mutable per-process configuration filled in by ``configure``."""

    def __init__(self):
        self.lock = threading.Lock()
        self.obs_dir: str = ""
        self.hang_sec: float = 300.0
        self.hang_abort_sec: float = 0.0
        self.heartbeat_sec: float = 0.0
        self.rank: int = -1
        self.task_id: str = ""
        self.tracker: tuple[str, int] | None = None
        self.heartbeat: _ship.Heartbeat | None = None
        self.lease_hb: _ship.Heartbeat | None = None
        # Live telemetry plane (doc/observability.md): the delta source
        # diffing successive registry states into the bounded windows
        # every CMD_METRICS snapshot piggybacks; the periodic flight-ring
        # spill ticker (rabit_obs_spill_sec) feeding follow-mode trace
        # export; the flight-dump retention cap (rabit_obs_max_files).
        self.delta_source = _stream.DeltaSource()
        self.spill_hb: _ship.Heartbeat | None = None
        self.spill_sec: float = 0.0
        self.max_files: int = 256
        self.watchdog_started = False
        self.sigterm_installed = False
        self.prev_sigterm = None
        # set by the watchdog when it declares this process hung; gates the
        # one-shot dump AND withholds further lease renewals.  Cleared (and
        # a hang_recovered event recorded) when the declared op completes —
        # a slow-but-successful collective must not permanently withhold
        # renewals and get a healthy worker killed.
        self.hang_dumped = False
        # (thread-id, t0, op) of the in-flight entry the declaration was
        # made on, so recovery is detected even if another collective is
        # already in flight by the next watchdog scan
        self.hang_ref: tuple[int, float, str] | None = None
        # thread-id -> (op, cache_key, t0_monotonic, version, seqno) of
        # in-flight collectives
        self.inflight: dict[int, tuple[str, str | None, float, int, int]] = {}
        # dumps written by this process so far — the filename counter that
        # keeps a second same-reason dump (hang, recover, hang again) from
        # overwriting the first
        self.dump_seq = 0
        # cross-rank collective identity (trace.py): seqno resets on every
        # checkpoint-version change, so a restarted worker resumes the
        # numbering where the survivors' replay serves it
        self.op_version = 0
        self.op_seq = 0
        # rabit_trace_* knobs (doc/observability.md "Cross-rank tracing")
        self.trace_exit = False
        self.trace_clock_pings = 2
        # HA failover list (rabit_tracker_addrs, doc/ha.md): extra
        # tracker addresses every shipped RPC rotates through
        self.tracker_addrs: list = []


_STATE = _ObsState()


def _parse_tracker_addrs(spec: str) -> list:
    """Lazy-import shim for protocol.parse_addrs (this module loads
    before the tracker package in some entry paths)."""
    from rabit_tpu.tracker.protocol import parse_addrs

    return parse_addrs(spec)


def configure(config, rank: int = -1) -> None:
    """Wire observability from the engine config.  Called by
    ``rabit_tpu.init`` after the engine is up (and safe to call again on a
    later init: singletons persist, identity/settings are refreshed).

    Keys (doc/observability.md, doc/fault_tolerance.md): ``rabit_obs_dir``
    (also the plain ``RABIT_OBS_DIR`` env var), ``rabit_obs_capacity``,
    ``rabit_obs_hang_sec``, ``rabit_obs_heartbeat_sec``,
    ``rabit_obs_spill_sec``, ``rabit_obs_max_files``,
    ``rabit_hang_abort_sec``, ``rabit_heartbeat_sec``,
    ``rabit_trace_exit``, ``rabit_trace_clock_pings``.
    """
    obs_dir = (config.get("rabit_obs_dir", "") or
               os.environ.get("RABIT_OBS_DIR", "") or "")
    if obs_dir == "NULL":
        obs_dir = ""
    capacity = config.get_int("rabit_obs_capacity", DEFAULT_CAPACITY)
    hang_sec = float(config.get("rabit_obs_hang_sec", "300") or "300")
    hang_abort_sec = float(config.get("rabit_hang_abort_sec", "0") or "0")
    heartbeat_sec = float(config.get("rabit_obs_heartbeat_sec", "0") or "0")
    spill_sec = float(config.get("rabit_obs_spill_sec", "0") or "0")
    max_files = config.get_int("rabit_obs_max_files", 256)
    lease_sec = float(config.get("rabit_heartbeat_sec", "0") or "0")
    tracker_uri = config.get("rabit_tracker_uri", "NULL")
    task_id = config.get("rabit_task_id", "NULL") or "NULL"

    trace_exit = (config.get("rabit_trace_exit", "0") or "0") not in (
        "0", "", "false", "no")
    clock_pings = config.get_int("rabit_trace_clock_pings", 2)

    GLOBAL_RECORDER.set_capacity(capacity)
    with _STATE.lock:
        _STATE.obs_dir = obs_dir
        _STATE.hang_sec = hang_sec
        _STATE.hang_abort_sec = hang_abort_sec
        _STATE.heartbeat_sec = lease_sec
        _STATE.rank = rank
        _STATE.task_id = task_id
        _STATE.trace_exit = trace_exit
        _STATE.trace_clock_pings = clock_pings
        _STATE.spill_sec = spill_sec
        _STATE.max_files = max_files
        # Fresh delta baseline: the first window shipped to THIS job's
        # tracker is the full cumulative state, so the tracker-side fold
        # reconciles with the cumulative snapshot even when the process
        # (and its registry) outlives a previous init.
        _STATE.delta_source = _stream.DeltaSource()
        # fresh init: the cross-rank collective numbering restarts at
        # (version 0, seq 0), exactly like every other first-life rank's
        _STATE.op_version = 0
        _STATE.op_seq = 0
        _STATE.tracker = None
        if tracker_uri and tracker_uri != "NULL":
            _STATE.tracker = (
                tracker_uri, config.get_int("rabit_tracker_port", 9091)
            )
        # the HA address list (primary + warm standby, doc/ha.md); the
        # primary tuple above stays first in every rotation
        _STATE.tracker_addrs = _parse_tracker_addrs(
            config.get("rabit_tracker_addrs", "") or "")
    # A re-init may point at a different tracker; offset samples against
    # the old one are meaningless on the new timeline.
    GLOBAL_CLOCK.reset()
    if obs_dir:
        os.makedirs(obs_dir, exist_ok=True)
        _install_sigterm_dump()
    # The watchdog serves three consumers: evidence dumps (needs a dir),
    # the hang-abort escalation, and hang-gated lease renewal.  Start it
    # when any of them is live.
    lease_on = lease_sec > 0 and _STATE.tracker is not None
    if ((hang_sec > 0 and (obs_dir or lease_on)) or hang_abort_sec > 0):
        _start_hang_watchdog()
    stop_heartbeat()
    if heartbeat_sec > 0 and _STATE.tracker is not None:
        hb = _ship.Heartbeat(heartbeat_sec, _ship_metrics_snapshot).start()
        with _STATE.lock:
            _STATE.heartbeat = hb
    if lease_on:
        # immediate=True: the lease exists the moment the worker is up, so
        # a worker frozen right after init is still covered.
        lhb = _ship.Heartbeat(lease_sec, _renew_lease, immediate=True).start()
        with _STATE.lock:
            _STATE.lease_hb = lhb
    if spill_sec > 0 and obs_dir:
        # Periodic flight-ring spill (doc/observability.md "Live
        # telemetry plane"): follow-mode trace export tails these dumps
        # mid-run; retention above keeps the dir bounded.
        shb = _ship.Heartbeat(spill_sec, _spill_tick).start()
        with _STATE.lock:
            _STATE.spill_hb = shb


# -- collective spans --------------------------------------------------------

def collective_epoch(version: int) -> None:
    """Note a checkpoint-version change (commit or recovery load) in the
    cross-rank collective numbering: the per-version seqno resets, so the
    same logical collective carries the same ``(version, seqno)`` on every
    rank — including a restarted worker, whose load_checkpoint lands it on
    exactly the version the survivors' numbering restarted at (trace.py
    merges dumps on this identity)."""
    with _STATE.lock:
        if version != _STATE.op_version:
            _STATE.op_version = int(version)
            _STATE.op_seq = 0


def collective_seq() -> tuple[int, int]:
    """The (version, next-seqno) the next collective will be stamped with."""
    with _STATE.lock:
        return _STATE.op_version, _STATE.op_seq


@contextlib.contextmanager
def collective(op: str, nbytes: int, cache_key: str | None = None,
               codec: str | None = None, fused: bool = False):
    """The one timing/eventing path for every public collective: records
    ``op_begin``/``op_end`` events stamped with the cross-rank
    ``(version, seqno)`` identity, marks the thread in-flight for the hang
    watchdog, and times into the registry's per-op stats + latency
    histogram.  Yields a span whose ``nbytes`` may be updated inside the
    window (object broadcast learns its length from the wire).

    ``codec`` (a rabit_tpu.compress codec name) joins the collective
    identity in both events: ranks must agree on the codec of each logical
    collective exactly as they agree on its (version, seqno), so a config
    skew shows up as differing ``codec`` fields on the same identity in
    the merged cross-rank trace — a detectable error, not silent
    corruption (the wire transport additionally hard-fails on mismatched
    frame ids; doc/compression.md, "Replay safety").

    ``fused=True`` marks a collective the engine runs as one fused
    in-graph device op (engine/fused.py): ``fused=1`` joins both events so
    traces and straggler analytics distinguish fused from host-path ops."""
    tid = threading.get_ident()
    with _STATE.lock:
        version, seqno = _STATE.op_version, _STATE.op_seq
        _STATE.op_seq += 1
        _STATE.inflight[tid] = (op, cache_key, time.monotonic(), version,
                                seqno)
    extra = {} if codec is None else {"codec": codec}
    if fused:
        extra["fused"] = 1
    record_event("op_begin", op=op, nbytes=nbytes, cache_key=cache_key,
                 version=version, seqno=seqno, **extra)
    t0 = time.perf_counter()
    span = _Span(op, nbytes, cache_key)
    try:
        yield span
    finally:
        dt = time.perf_counter() - t0
        with _STATE.lock:
            _STATE.inflight.pop(tid, None)
        GLOBAL_REGISTRY.observe_op(op, span.nbytes, dt)
        record_event("op_end", op=op, nbytes=span.nbytes,
                     cache_key=cache_key, seconds=round(dt, 6),
                     version=version, seqno=seqno, **extra)


# -- failure-path dumps ------------------------------------------------------

def _evict_flight_dumps(obs_dir: str, max_files: int) -> int:
    """Oldest-first flight-dump eviction down to ``max_files``
    (rabit_obs_max_files): the periodic spill must not fill a disk over a
    long run.  Returns how many files were removed; never raises."""
    if max_files <= 0:
        return 0
    try:
        names = [n for n in os.listdir(obs_dir)
                 if n.startswith("flight-") and n.endswith(".jsonl")]
    except OSError:
        return 0
    excess = len(names) - max_files
    if excess <= 0:
        return 0
    stamped = []
    for n in names:
        path = os.path.join(obs_dir, n)
        try:
            stamped.append((os.path.getmtime(path), path))
        except OSError:
            continue
    stamped.sort()
    evicted = 0
    for _mtime, path in stamped[:excess]:
        try:
            os.remove(path)
            evicted += 1
        except OSError:
            pass
    if evicted:
        record_event("obs_evicted", n=evicted, max_files=max_files)
    return evicted


def _spill_tick() -> None:
    """One periodic flight-ring spill (rabit_obs_spill_sec): the live
    evidence follow-mode trace export tails mid-run."""
    dump_now("spill")


def dump_now(reason: str) -> str | None:
    """Dump the flight recorder to the configured obs dir; returns the path
    (None when no dir is configured).  Never raises.

    The filename carries a per-process dump counter (``-n<seq>-``) so the
    same reason firing twice in one life (hang, recover, hang again) writes
    two files instead of overwriting the first's evidence."""
    with _STATE.lock:
        obs_dir, rank = _STATE.obs_dir, _STATE.rank
        inflight = list(_STATE.inflight.values())
        max_files = _STATE.max_files
    if not obs_dir:
        return None
    try:
        for op, key, t0, version, seqno in inflight:
            record_event("op_inflight", op=op, cache_key=key,
                         stuck_seconds=round(time.monotonic() - t0, 3),
                         version=version, seqno=seqno)
        with _STATE.lock:
            _STATE.dump_seq += 1
            seq = _STATE.dump_seq
        path = os.path.join(
            obs_dir,
            f"flight-rank{rank}-pid{os.getpid()}-n{seq}-{reason}.jsonl",
        )
        out = GLOBAL_RECORDER.dump(
            path, header={"reason": reason, "rank": rank, "dump_seq": seq,
                          "task_id": _STATE.task_id}
        )
        _evict_flight_dumps(obs_dir, max_files)
        return out
    except OSError:
        return None


def _on_sigterm(signum, frame):
    dump_now("sigterm")
    prev = _STATE.prev_sigterm
    if callable(prev):
        prev(signum, frame)
        return
    # restore the previous disposition and re-deliver so the process still
    # dies with the normal SIGTERM exit status
    signal.signal(signal.SIGTERM, prev if prev is not None else signal.SIG_DFL)
    os.kill(os.getpid(), signal.SIGTERM)


def _install_sigterm_dump() -> None:
    with _STATE.lock:
        if _STATE.sigterm_installed:
            return
        _STATE.sigterm_installed = True
    try:
        _STATE.prev_sigterm = signal.signal(signal.SIGTERM, _on_sigterm)
    except ValueError:
        # not the main thread — the watchdog still covers hangs
        with _STATE.lock:
            _STATE.sigterm_installed = False


def _watchdog_loop() -> None:
    while True:
        recovered: tuple[str, float] | None = None
        with _STATE.lock:
            hang_sec = _STATE.hang_sec
            abort_sec = _STATE.hang_abort_sec
            declared = _STATE.hang_dumped
            now = time.monotonic()
            worst: tuple[str, str | None, float, int, float] | None = None
            for tid, (op, key, t0, _v, _s) in _STATE.inflight.items():
                if worst is None or now - t0 > worst[2]:
                    worst = (op, key, now - t0, tid, t0)
            if declared and _STATE.hang_ref is not None:
                # Latch release: the op the declaration was made on is no
                # longer in flight — the "hang" was slow-but-successful.
                # Clear the latch so lease renewals resume (a permanently
                # withheld lease would get this healthy worker killed) and
                # the one-shot dump re-arms for a future real hang.
                ref_tid, ref_t0, ref_op = _STATE.hang_ref
                cur = _STATE.inflight.get(ref_tid)
                if cur is None or cur[2] != ref_t0:
                    _STATE.hang_dumped = False
                    _STATE.hang_ref = None
                    declared = False
                    recovered = (ref_op, now - ref_t0)
        if recovered is not None:
            record_event("hang_recovered", op=recovered[0],
                         stuck_seconds=round(recovered[1], 3))
        # Detection threshold: rabit_obs_hang_sec when set, else the abort
        # bound alone drives it (abort without a separate dump threshold).
        detect_sec = hang_sec if hang_sec > 0 else abort_sec
        if (worst is not None and detect_sec > 0 and worst[2] > detect_sec
                and not declared):
            record_event("hang_detected", op=worst[0], cache_key=worst[1],
                         stuck_seconds=round(worst[2], 3))
            dump_now("hang")  # no-op without an obs dir
            with _STATE.lock:
                _STATE.hang_dumped = True
                _STATE.hang_ref = (worst[3], worst[4], worst[0])
            declared = True
        if worst is not None and abort_sec > 0 and worst[2] > abort_sec:
            # Dump-then-die: evidence is already on disk (the declaration
            # above); a second dump carries the abort decision itself, then
            # the process exits so the launcher can restart it — the
            # worker-side belt to the tracker lease's suspenders.
            record_event("hang_abort", op=worst[0], cache_key=worst[1],
                         stuck_seconds=round(worst[2], 3),
                         exit_code=HANG_ABORT_EXIT)
            dump_now("abort")
            print(f"[rabit_tpu.obs] collective {worst[0]!r} stuck for "
                  f"{worst[2]:.1f}s > rabit_hang_abort_sec={abort_sec}: "
                  f"aborting (exit {HANG_ABORT_EXIT}) so the launcher can "
                  f"restart this worker", flush=True, file=sys.stderr)
            os._exit(HANG_ABORT_EXIT)
        bounds = [b for b in (hang_sec, abort_sec) if b > 0]
        time.sleep(max(min([1.0] + [b / 4.0 for b in bounds]), 0.02))


def _start_hang_watchdog() -> None:
    with _STATE.lock:
        if _STATE.watchdog_started:
            return
        _STATE.watchdog_started = True
    threading.Thread(
        target=_watchdog_loop, name="rabit-obs-watchdog", daemon=True
    ).start()


# -- periodic / shutdown shipping --------------------------------------------

def _make_snapshot() -> dict:
    with _STATE.lock:
        rank, task_id = _STATE.rank, _STATE.task_id
        source = _STATE.delta_source
    extra: dict = {"flight_dropped": GLOBAL_RECORDER.dropped}
    clock = GLOBAL_CLOCK.snapshot()
    if clock is not None:
        # this rank's tracker-clock offset estimate (trace.py projection)
        extra["clock"] = clock
    # Piggyback the streamed-metrics delta window (doc/observability.md
    # "Live telemetry plane"): the tracker/relay strips it at ingest and
    # folds it into the live rollup; the snapshot itself stays cumulative.
    delta = source.take()
    if delta is not None:
        extra["delta"] = delta
    return _ship.build_snapshot(GLOBAL_REGISTRY, rank, task_id, extra=extra)


def _ship_metrics_snapshot() -> bool:
    """One metrics-heartbeat tick (runs on the heartbeat thread)."""
    with _STATE.lock:
        tracker, task_id = _STATE.tracker, _STATE.task_id
        addrs = list(_STATE.tracker_addrs)
    if tracker is None:
        return False
    return _ship.ship_snapshot(_make_snapshot(), tracker[0], tracker[1],
                               task_id, addrs=addrs)


def _renew_lease() -> bool:
    """One lease-renewal tick (runs on the lease heartbeat thread).

    Withheld once the watchdog has declared this process hung: a worker
    stuck in a collective but still scheduling threads must look exactly as
    dead to the tracker as a frozen one, so the lease detector covers both
    silent-failure shapes."""
    with _STATE.lock:
        tracker = _STATE.tracker
        rank, task_id = _STATE.rank, _STATE.task_id
        interval = _STATE.heartbeat_sec
        hung = _STATE.hang_dumped
        addrs = list(_STATE.tracker_addrs)
    if tracker is None or hung:
        return False
    return _ship.renew_lease(tracker[0], tracker[1], task_id, interval,
                             rank=rank, addrs=addrs)


def stop_heartbeat() -> None:
    """Stop every periodic sender (metric snapshots, lease renewals, and
    the flight-ring spill ticker)."""
    with _STATE.lock:
        hb, _STATE.heartbeat = _STATE.heartbeat, None
        lhb, _STATE.lease_hb = _STATE.lease_hb, None
        shb, _STATE.spill_hb = _STATE.spill_hb, None
    for t in (hb, lhb, shb):
        if t is not None:
            t.stop()


def ship_final_snapshot() -> bool:
    """Ship the shutdown-time snapshot to the tracker (best-effort; False
    when no tracker is configured or the send failed).  Called by
    ``rabit_tpu.finalize`` BEFORE the engine's own shutdown handshake so
    the tracker is still serving when the snapshot arrives."""
    stop_heartbeat()
    with _STATE.lock:
        tracker, task_id = _STATE.tracker, _STATE.task_id
        pings = _STATE.trace_clock_pings
        addrs = list(_STATE.tracker_addrs)
    if tracker is None:
        return False
    # Tighten (or bootstrap — a job that never enabled heartbeats has no
    # samples yet) the clock estimate before it is frozen into the final
    # snapshot: each ping is one timestamped round-trip, no lease effect.
    if pings > 0:
        _ship.clock_ping(tracker[0], tracker[1], task_id, samples=pings,
                         addrs=addrs)
    return _ship.ship_snapshot(_make_snapshot(), tracker[0], tracker[1],
                               task_id, addrs=addrs)


def dump_final() -> str | None:
    """With ``rabit_trace_exit=1``, write this life's flight ring as a
    ``-exit`` dump at finalize, so a CLEAN run leaves the per-rank evidence
    the cross-rank trace merger joins (hangs/SIGTERMs already dump; clean
    exits previously left nothing).  Called by ``rabit_tpu.finalize`` after
    the engine shutdown handshake."""
    with _STATE.lock:
        want = _STATE.trace_exit and bool(_STATE.obs_dir)
    return dump_now("exit") if want else None
