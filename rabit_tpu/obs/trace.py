"""Cross-rank collective tracing — one job-wide timeline from N flight dumps.

PR 1's flight recorder and PR 2's liveness layer each see one process at a
time: ``flight-rank*.jsonl`` and ``telemetry.json`` cannot answer "which
rank arrived last at allreduce #417" or "what was rank 3 doing while rank 0
hung".  Straggler-aware allreduce (arxiv 2505.23523) and failure
localization (arxiv 2606.01680) both start from the artifact this module
builds: a per-collective, per-rank arrival timeline.  Three pieces:

* **clock alignment** — :class:`ClockSync` accumulates NTP-style offset
  estimates from the timestamped ``CMD_HEARTBEAT``/``CMD_METRICS`` replies
  (tracker stamps its clock into the ACK; the worker brackets the RPC and
  takes the midpoint).  The best (lowest round-trip-error) estimate ships
  inside every metrics snapshot, so ``telemetry.json`` carries a per-rank
  ``clock`` record and per-rank ``time.time()`` stamps can be projected
  onto the tracker's timeline with a known error bound;
* **merge + export** — :func:`load_job` joins every ``flight-*.jsonl`` in
  an obs dir with ``telemetry.json``; :func:`build_chrome_trace` emits
  Chrome/Perfetto ``trace_event`` JSON (one track per rank, spans for
  collectives and bootstraps, a tracker track with recovery-wave spans and
  lease/hang/checkpoint instants) openable in ``ui.perfetto.dev``;
* **straggler analytics** — :func:`straggler_report` computes per-seqno
  arrival skew (first-enter vs last-enter), per-rank cumulative lateness
  and wait share, and a top-K straggler table.  Collectives whose window
  overlaps a recovery wave are analyzed separately, so restart latency
  does not masquerade as steady-state straggling.

Collectives are identified ACROSS ranks by ``(version, seqno)``:
``rabit_tpu.obs.collective`` stamps every ``op_begin``/``op_end`` with the
checkpoint version and a per-version sequence number that resets on every
version change (commit or recovery load) — so a restarted worker resumes
the numbering exactly where the survivors' replay serves it, and the same
logical collective carries the same id in every rank's dump.

CLI: ``tools/trace_tool.py export|report|validate`` (doc/observability.md).
"""

from __future__ import annotations

import json
import math
import os
import re
import threading
import time
from dataclasses import dataclass, field

from rabit_tpu.obs.events import Event, load_dump

#: pid used for the tracker's track in the exported trace (rank pids are
#: the small non-negative rank numbers; this one sorts last and cannot
#: collide with any real rank).
TRACKER_PID = 1_000_000

#: Widen recovery windows by this much when classifying collectives, so a
#: begin stamped just outside the window (clock error, scan cadence) is
#: still attributed to the recovery, not to a steady-state straggler.
RECOVERY_MARGIN_SEC = 0.25

_DUMP_RE = re.compile(
    r"flight-rank(?P<rank>-?\d+)-pid(?P<pid>\d+)(?:-n(?P<seq>\d+))?"
    r"-(?P<reason>[A-Za-z_]+)\.jsonl$"
)


class TraceError(RuntimeError):
    """A dump or telemetry file could not be merged (malformed JSON, no
    usable header, colliding ranks...).  CI treats this as a failure;
    an *empty* obs dir is not an error — it merges to an empty trace."""


# -- clock alignment ---------------------------------------------------------

class ClockSync:
    """NTP-style offset estimator for one worker against the tracker clock.

    Each timestamped tracker RPC yields ``offset = server_ts - midpoint``
    with error bound ``rtt / 2``; the estimator keeps the lowest-error
    sample (late samples win ties, so a long-running worker tracks drift
    at equal quality).  ``offset`` maps this process's ``time.time()``
    onto the tracker's: ``tracker_ts = worker_ts + offset``.
    """

    def __init__(self):
        self._lock = threading.Lock()
        self._offset = 0.0
        self._err = math.inf
        self._samples = 0

    def update(self, offset: float, err: float) -> None:
        with self._lock:
            self._samples += 1
            if err <= self._err:
                self._offset, self._err = float(offset), float(err)

    def reset(self) -> None:
        with self._lock:
            self._offset, self._err, self._samples = 0.0, math.inf, 0

    @property
    def samples(self) -> int:
        with self._lock:
            return self._samples

    def estimate(self) -> tuple[float, float] | None:
        """(offset_s, err_s), or None before the first sample."""
        with self._lock:
            if self._samples == 0:
                return None
            return self._offset, self._err

    def snapshot(self) -> dict | None:
        """JSON-able record shipped inside metric snapshots."""
        est = self.estimate()
        if est is None:
            return None
        return {"offset_s": round(est[0], 6), "err_s": round(est[1], 6),
                "samples": self.samples}


#: Process-wide clock estimate against this job's tracker (updated by
#: rabit_tpu.obs.ship on every timestamped RPC; shipped in snapshots).
GLOBAL_CLOCK = ClockSync()


# -- job loading -------------------------------------------------------------

@dataclass
class JobTrace:
    """Everything known about one job: per-rank merged event streams (each
    sorted by ts, exact duplicates across overlapping dumps removed),
    the tracker's telemetry document, and per-rank clock offsets."""

    ranks: dict[int, list[Event]] = field(default_factory=dict)
    telemetry: dict | None = None
    #: rank -> {"offset_s", "err_s", "samples"}
    clocks: dict[int, dict] = field(default_factory=dict)
    dump_paths: list[str] = field(default_factory=list)

    def offset(self, rank: int) -> float:
        return self.clocks.get(rank, {}).get("offset_s", 0.0)

    def max_clock_err(self) -> float:
        errs = [c.get("err_s", 0.0) for c in self.clocks.values()]
        return max(errs) if errs else 0.0

    def project(self, rank: int, ts: float) -> float:
        """Worker-clock ts -> tracker-clock ts."""
        return ts + self.offset(rank)


def parse_dump_name(path: str) -> dict | None:
    """rank/pid/dump-seq/reason from a flight dump filename (the header
    line is authoritative; this is the fallback for truncated dumps)."""
    m = _DUMP_RE.search(os.path.basename(path))
    if m is None:
        return None
    return {"rank": int(m.group("rank")), "pid": int(m.group("pid")),
            "dump_seq": int(m.group("seq") or 0),
            "reason": m.group("reason")}


def discover_dumps(obs_dir: str) -> list[str]:
    try:
        names = sorted(os.listdir(obs_dir))
    except OSError:
        return []
    return [os.path.join(obs_dir, n) for n in names
            if n.startswith("flight-") and n.endswith(".jsonl")]


def telemetry_name(job_key: str = "") -> str:
    """The telemetry filename for one job of a shared obs dir
    (doc/service.md): ``telemetry-<job>.json`` under a multi-job
    service, the bare legacy name for the single-job path."""
    return f"telemetry-{job_key}.json" if job_key else "telemetry.json"


_TELE_RE = re.compile(r"^telemetry-(?P<job>.+)\.json$")


def discover_telemetry_jobs(obs_dir: str) -> list[str]:
    """The job keys whose per-job telemetry files exist under a shared
    multi-tenant obs dir (``telemetry-<job>.json``), sorted.  The bare
    legacy ``telemetry.json`` is NOT a job — callers check it first."""
    try:
        names = sorted(os.listdir(obs_dir))
    except OSError:
        return []
    out = []
    for name in names:
        m = _TELE_RE.match(name)
        if m:
            out.append(m.group("job"))
    return out


def load_job(obs_dir: str, job_key: str = "",
             tolerant: bool = False) -> JobTrace:
    """Join every flight dump + telemetry.json under ``obs_dir``.
    ``job_key`` selects one job's telemetry file of a shared multi-job
    obs dir (:func:`telemetry_name`).

    Multiple dumps per rank (several lives, or hang-then-exit in one life)
    are merged: events are pooled, exact duplicates (same ts/kind/fields —
    the overlap between a hang dump and the later exit dump of the same
    ring) removed, and the stream re-sorted by ts.  Raises
    :class:`TraceError` on malformed inputs; an empty dir is fine.

    ``tolerant=True`` skips unreadable inputs instead of raising — the
    follow-mode contract, where a spill dump may be mid-write or freshly
    evicted (rabit_obs_max_files) when the exporter lists the dir."""
    job = JobTrace()
    pools: dict[int, dict[str, Event]] = {}
    for path in discover_dumps(obs_dir):
        try:
            events = load_dump(path)
        except (OSError, ValueError, KeyError) as exc:
            if tolerant:
                continue
            raise TraceError(f"unreadable flight dump {path}: {exc!r}") from exc
        rank = None
        if events and events[0].kind == "flight_dump":
            rank = events[0].fields.get("rank")
            events = events[1:]
        if rank is None:
            ident = parse_dump_name(path)
            if ident is None:
                if tolerant:
                    continue
                raise TraceError(f"flight dump {path} has neither a header "
                                 f"rank nor a parseable filename")
            rank = ident["rank"]
        rank = int(rank)
        pool = pools.setdefault(rank, {})
        for ev in events:
            key = f"{ev.ts:.6f}|{ev.kind}|" + json.dumps(ev.fields,
                                                         sort_keys=True)
            pool.setdefault(key, ev)
        job.dump_paths.append(path)
    for rank, pool in pools.items():
        job.ranks[rank] = sorted(pool.values(), key=lambda e: e.ts)

    tele_path = os.path.join(obs_dir, telemetry_name(job_key))
    if os.path.exists(tele_path):
        try:
            with open(tele_path) as f:
                job.telemetry = json.load(f)
        except (OSError, ValueError) as exc:
            if tolerant:
                return job
            raise TraceError(f"unreadable {os.path.basename(tele_path)}: "
                             f"{exc!r}") from exc
        clocks = dict(job.telemetry.get("clocks") or {})
        for r, snap in (job.telemetry.get("ranks") or {}).items():
            if isinstance(snap, dict) and snap.get("clock"):
                clocks.setdefault(r, snap["clock"])
        for r, clock in clocks.items():
            try:
                job.clocks[int(r)] = dict(clock)
            except (TypeError, ValueError):
                continue
    return job


# -- span pairing ------------------------------------------------------------

@dataclass
class OpSpan:
    op: str
    version: int | None
    seqno: int | None
    begin: float              # worker clock
    end: float | None = None  # None: still in flight at dump time
    nbytes: int = 0
    cache_key: str | None = None
    # engine/fused.py ran this op as one fused in-graph device collective
    # (the op_begin/op_end events carry fused=1); host-path ops stay False
    fused: bool = False

    @property
    def keyed(self) -> bool:
        return self.version is not None and self.seqno is not None

    @property
    def key(self) -> tuple:
        return (self.version, self.seqno, self.op)


def pair_ops(events: list[Event]) -> list[OpSpan]:
    """Match one rank's op_begin/op_end stream into spans.  Seqno-stamped
    events pair by (version, seqno, op); legacy events (pre-seqno dumps)
    fall back to per-op FIFO order.  A begin without an end (the op in
    flight when the dump was written) yields an open span."""
    spans: list[OpSpan] = []
    open_keyed: dict[tuple, OpSpan] = {}
    open_fifo: dict[str, list[OpSpan]] = {}
    for ev in events:
        if ev.kind == "op_begin":
            span = OpSpan(
                op=str(ev.fields.get("op", "?")),
                version=ev.fields.get("version"),
                seqno=ev.fields.get("seqno"),
                begin=ev.ts,
                nbytes=int(ev.fields.get("nbytes") or 0),
                cache_key=ev.fields.get("cache_key"),
                fused=bool(ev.fields.get("fused")),
            )
            spans.append(span)
            if span.keyed:
                open_keyed[span.key] = span
            else:
                open_fifo.setdefault(span.op, []).append(span)
        elif ev.kind == "op_end":
            op = str(ev.fields.get("op", "?"))
            version, seqno = ev.fields.get("version"), ev.fields.get("seqno")
            span = None
            if version is not None and seqno is not None:
                span = open_keyed.pop((version, seqno, op), None)
            elif open_fifo.get(op):
                span = open_fifo[op].pop(0)
            if span is not None:
                span.end = ev.ts
                span.nbytes = int(ev.fields.get("nbytes") or span.nbytes)
    return spans


# -- Chrome/Perfetto export --------------------------------------------------

def _us(ts: float, t_base: float) -> float:
    return round((ts - t_base) * 1e6, 3)


def _instant(name: str, ts_us: float, pid: int, scope: str = "t",
             args: dict | None = None) -> dict:
    ev = {"name": name, "cat": "rabit", "ph": "i", "ts": ts_us,
          "pid": pid, "tid": 0, "s": scope}
    if args:
        ev["args"] = args
    return ev


#: Worker-side event kinds rendered as instants on the rank's track (the
#: op_begin/op_end pairs become spans instead and are excluded here).
_RANK_INSTANTS = {
    "hang_detected", "hang_recovered", "hang_abort", "op_inflight",
    "engine_error", "checkpoint_commit", "load_checkpoint",
    "checkpoint_loaded", "version_bump", "init_after_exception",
    "engine_finalize", "engine_shutdown", "engine_ready",
    "epoch_changed", "shard_rebalanced", "obs_evicted",
}

#: Tracker-side event kinds rendered as instants on the tracker track —
#: including the world-epoch boundaries of an elastic job (spare
#: promotions, shrinks, grows), so a Perfetto timeline shows resizes
#: alongside the recovery waves that caused them.
_TRACKER_INSTANTS = {
    "lease_expired", "wave_purged", "failure_detected", "recover_stats",
    "recover_stats_final", "snapshot_rejected", "worker_recovered",
    "disk_resume", "metrics_snapshot",
    "spare_parked", "spare_dropped", "spare_promoted",
    "world_shrunk", "world_grown", "bootstrap_blob",
    "schedule_planned", "schedule_repaired", "link_degraded",
    "quorum_met", "contribution_late", "correction_folded",
    "correction_dropped",
    "relay_up", "relay_lost", "batch_folded", "messages_dropped",
    "journal_snapshot", "journal_gap", "standby_synced",
    "tracker_failover",
    "job_admitted", "admission_refused", "worker_leased",
    "job_completed",
    "obs_scrape", "metrics_delta_folded",
    "incident_opened", "incident_resolved", "critical_path_folded",
    "snapshot_published", "snapshot_fetched", "blob_cache_evicted",
}


def recovery_windows(job: JobTrace) -> list[tuple[float, float]]:
    """(start, end) tracker-clock windows of each recovery wave: end is the
    wave's assignment broadcast; start is the latest preceding failure
    evidence (failure_detected / lease_expired / wave_purged), or the wave
    instant itself when none was recorded."""
    if not job.telemetry:
        return []
    events = job.telemetry.get("events") or []
    failures = sorted(e["ts"] for e in events
                      if e.get("kind") in ("failure_detected",
                                           "lease_expired", "wave_purged"))
    windows = []
    for w in (job.telemetry.get("waves") or []):
        if w.get("epoch", 0) <= 0:
            continue
        end = float(w["ts"])
        start = end
        for ts in failures:
            if ts < end:
                start = min(start, ts) if start != end else ts
            else:
                break
        # keep only evidence reasonably tied to THIS wave
        preceding = [ts for ts in failures if ts < end]
        start = preceding[-1] if preceding else end
        windows.append((min(start, end), end))
    return windows


def build_chrome_trace(job: JobTrace) -> dict:
    """One Chrome ``trace_event`` document: a track per rank (collective +
    bootstrap spans, lifecycle instants, all clock-projected onto the
    tracker timeline) plus a tracker track (wave spans, lease expiries,
    converted engine stats events)."""
    all_ts: list[float] = []
    for rank, events in job.ranks.items():
        all_ts.extend(job.project(rank, e.ts) for e in events)
    if job.telemetry:
        all_ts.extend(float(e["ts"]) for e in
                      (job.telemetry.get("events") or []) if "ts" in e)
        if job.telemetry.get("started_at"):
            all_ts.append(float(job.telemetry["started_at"]))
    t_base = min(all_ts) if all_ts else 0.0

    out: list[dict] = []
    for rank in sorted(job.ranks):
        out.append({"name": "process_name", "ph": "M", "ts": 0.0,
                    "pid": rank, "tid": 0,
                    "args": {"name": f"rank {rank}"}})
        out.append({"name": "process_sort_index", "ph": "M", "ts": 0.0,
                    "pid": rank, "tid": 0, "args": {"sort_index": rank}})

    unpaired = 0
    for rank, events in sorted(job.ranks.items()):
        off = job.offset(rank)
        for span in pair_ops(events):
            if span.end is None:
                unpaired += 1
                continue
            args = {"nbytes": span.nbytes, "rank": rank}
            if span.keyed:
                args.update(version=span.version, seqno=span.seqno)
            if span.cache_key:
                args["cache_key"] = span.cache_key
            if span.fused:
                args["fused"] = 1
            out.append({
                "name": span.op, "cat": "collective", "ph": "X",
                "ts": _us(span.begin + off, t_base),
                "dur": round(max(span.end - span.begin, 0.0) * 1e6, 3),
                "pid": rank, "tid": 0, "args": args,
            })
        # bootstrap spans: engine_init -> bootstrap_done, sequential per life
        init_ts: float | None = None
        for ev in events:
            if ev.kind == "engine_init":
                init_ts = ev.ts
            elif ev.kind == "bootstrap_done" and init_ts is not None:
                out.append({
                    "name": "bootstrap", "cat": "lifecycle", "ph": "X",
                    "ts": _us(init_ts + off, t_base),
                    "dur": round(max(ev.ts - init_ts, 0.0) * 1e6, 3),
                    "pid": rank, "tid": 0,
                    "args": {k: v for k, v in ev.fields.items()
                             if k != "engine"},
                })
                init_ts = None
            elif ev.kind in _RANK_INSTANTS:
                out.append(_instant(ev.kind, _us(ev.ts + off, t_base), rank,
                                    args=dict(ev.fields)))

    if job.telemetry:
        out.append({"name": "process_name", "ph": "M", "ts": 0.0,
                    "pid": TRACKER_PID, "tid": 0,
                    "args": {"name": "tracker"}})
        out.append({"name": "process_sort_index", "ph": "M", "ts": 0.0,
                    "pid": TRACKER_PID, "tid": 0,
                    "args": {"sort_index": TRACKER_PID}})
        for start, end in recovery_windows(job):
            out.append({
                "name": "recovery wave", "cat": "recovery", "ph": "X",
                "ts": _us(start, t_base),
                "dur": round(max(end - start, 0.0) * 1e6, 3),
                "pid": TRACKER_PID, "tid": 0, "args": {},
            })
        for ev in (job.telemetry.get("events") or []):
            kind, ts = ev.get("kind"), ev.get("ts")
            if ts is None:
                continue
            if kind == "wave":
                out.append(_instant(
                    f"wave {ev.get('epoch')}", _us(float(ts), t_base),
                    TRACKER_PID, scope="p",
                    args={k: v for k, v in ev.items()
                          if k not in ("ts", "kind")}))
            elif kind in _TRACKER_INSTANTS:
                out.append(_instant(
                    kind, _us(float(ts), t_base), TRACKER_PID, scope="p",
                    args={k: v for k, v in ev.items()
                          if k not in ("ts", "kind")}))

    out.sort(key=lambda e: (e.get("ph") != "M", e.get("ts", 0.0)))
    return {
        "traceEvents": out,
        "displayTimeUnit": "ms",
        "otherData": {
            "t_base_epoch_s": round(t_base, 6),
            "ranks": sorted(job.ranks),
            "dumps_merged": len(job.dump_paths),
            "spans_inflight_at_dump": unpaired,
            "clock_max_err_s": round(job.max_clock_err(), 6),
            "generator": "rabit_tpu tools/trace_tool.py",
        },
    }


#: Phase types this exporter emits; the validator is deliberately strict —
#: a new phase type must be added here AND given rules below.
_ALLOWED_PH = {"X", "i", "M"}


def validate_chrome_trace(doc: object) -> list[str]:
    """Structural check against the Chrome ``trace_event`` format (the
    subset this exporter emits).  Returns a list of problems — empty means
    the document loads in ui.perfetto.dev / chrome://tracing."""
    errs: list[str] = []
    if not isinstance(doc, dict):
        return ["document is not a JSON object"]
    events = doc.get("traceEvents")
    if not isinstance(events, list):
        return ["traceEvents is missing or not a list"]
    for i, ev in enumerate(events):
        where = f"traceEvents[{i}]"
        if not isinstance(ev, dict):
            errs.append(f"{where}: not an object")
            continue
        ph = ev.get("ph")
        if ph not in _ALLOWED_PH:
            errs.append(f"{where}: bad ph {ph!r}")
            continue
        if not isinstance(ev.get("name"), str) or not ev["name"]:
            errs.append(f"{where}: missing name")
        for key in ("pid", "tid"):
            if not isinstance(ev.get(key), int):
                errs.append(f"{where}: {key} must be an int")
        ts = ev.get("ts")
        if not isinstance(ts, (int, float)) or isinstance(ts, bool):
            errs.append(f"{where}: ts must be a number")
        elif ph != "M" and ts < 0:
            errs.append(f"{where}: negative ts {ts}")
        if ph == "X":
            dur = ev.get("dur")
            if (not isinstance(dur, (int, float)) or isinstance(dur, bool)
                    or dur < 0):
                errs.append(f"{where}: X event needs dur >= 0")
        if ph == "i" and ev.get("s") not in ("t", "p", "g"):
            errs.append(f"{where}: instant scope must be t|p|g")
        if "args" in ev and not isinstance(ev["args"], dict):
            errs.append(f"{where}: args must be an object")
    try:
        json.dumps(doc)
    except (TypeError, ValueError) as exc:
        errs.append(f"document is not JSON-serializable: {exc!r}")
    return errs


# -- straggler analytics -----------------------------------------------------

def collective_arrivals(job: JobTrace) -> dict[tuple, dict[int, OpSpan]]:
    """(version, seqno, op) -> {rank: span} with clock-projected begin/end
    (spans are rewritten onto the tracker timeline in place of the worker
    clock).  Only seqno-stamped spans participate — legacy dumps have no
    cross-rank identity."""
    table: dict[tuple, dict[int, OpSpan]] = {}
    for rank, events in job.ranks.items():
        off = job.offset(rank)
        for span in pair_ops(events):
            if not span.keyed:
                continue
            span.begin += off
            if span.end is not None:
                span.end += off
            table.setdefault(span.key, {})[rank] = span
    return table


def straggler_report(job: JobTrace, top_k: int = 3) -> dict:
    """Per-seqno arrival-skew analytics.

    For every collective observed by >= 2 ranks: ``skew`` is last-enter
    minus first-enter; each rank's ``lateness`` is its own enter minus the
    first enter (the straggler's signature), and its ``wait`` is the last
    enter minus its own (the cost stragglers impose on it).  Collectives
    whose [first-begin, last-end] window intersects a recovery wave are
    tallied separately (``collectives_recovery_affected``) so restart
    latency doesn't crown a restarted rank as the straggler."""
    arrivals = collective_arrivals(job)
    windows = recovery_windows(job)
    margin = RECOVERY_MARGIN_SEC + job.max_clock_err()

    def recovery_affected(begins: list[float], ends: list[float]) -> bool:
        lo = min(begins) - margin
        hi = max(ends if ends else begins) + margin
        return any(s <= hi and e >= lo for s, e in windows)

    per_rank: dict[int, dict] = {
        r: {"arrivals": 0, "last_arriver_count": 0,
            "lateness_total_s": 0.0, "wait_total_s": 0.0}
        for r in job.ranks
    }
    analyzed = affected = 0
    worst: list[dict] = []
    for key in sorted(arrivals, key=lambda k: (k[0] or 0, k[1] or 0)):
        ranks = arrivals[key]
        if len(ranks) < 2:
            continue
        begins = [s.begin for s in ranks.values()]
        ends = [s.end for s in ranks.values() if s.end is not None]
        if recovery_affected(begins, ends):
            affected += 1
            continue
        analyzed += 1
        first, last = min(begins), max(begins)
        last_rank = max(ranks, key=lambda r: ranks[r].begin)
        version, seqno, op = key
        entry = {"op": op, "version": version, "seqno": seqno,
                 "skew_s": round(last - first, 6),
                 "first_enter_s": round(first, 6),
                 "last_enter_s": round(last, 6),
                 "last_rank": last_rank}
        if any(s.fused for s in ranks.values()):
            # fused-path skew is device-graph scheduling, not host encode
            # latency — keep the two data planes separable in the report
            entry["fused"] = 1
        worst.append(entry)
        for rank, span in ranks.items():
            stats = per_rank[rank]
            stats["arrivals"] += 1
            stats["lateness_total_s"] += span.begin - first
            stats["wait_total_s"] += last - span.begin
            if rank == last_rank:
                stats["last_arriver_count"] += 1

    total_lateness = sum(s["lateness_total_s"] for s in per_rank.values())
    for stats in per_rank.values():
        n = max(stats["arrivals"], 1)
        stats["lateness_mean_s"] = round(stats["lateness_total_s"] / n, 6)
        stats["lateness_share"] = round(
            stats["lateness_total_s"] / total_lateness, 4
        ) if total_lateness > 0 else 0.0
        stats["lateness_total_s"] = round(stats["lateness_total_s"], 6)
        stats["wait_total_s"] = round(stats["wait_total_s"], 6)
    order = sorted(per_rank, key=lambda r: per_rank[r]["lateness_total_s"],
                   reverse=True)
    worst.sort(key=lambda w: w["skew_s"], reverse=True)
    return {
        "collectives_total": len(arrivals),
        "collectives_analyzed": analyzed,
        "collectives_recovery_affected": affected,
        "recovery_windows": [[round(s, 6), round(e, 6)] for s, e in windows],
        "clock_max_err_s": round(job.max_clock_err(), 6),
        "per_rank": {str(r): per_rank[r] for r in sorted(per_rank)},
        "top_stragglers": [
            {"rank": r, **per_rank[r]} for r in order[:max(top_k, 0)]
        ],
        "worst_skews": worst[:max(top_k, 0)],
    }


# -- persistence -------------------------------------------------------------

def fold_into_telemetry(obs_dir: str, report: dict,
                        job_key: str = "") -> str | None:
    """Write the straggler aggregates back into the (job's) telemetry
    file under a ``stragglers`` key (atomic rewrite).  Returns the path,
    or None when there is no telemetry file to fold into."""
    path = os.path.join(obs_dir, telemetry_name(job_key))
    if not os.path.exists(path):
        return None
    try:
        with open(path) as f:
            doc = json.load(f)
    except (OSError, ValueError) as exc:
        raise TraceError(f"cannot fold into "
                         f"{os.path.basename(path)}: {exc!r}") from exc
    doc["stragglers"] = report
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w") as f:
        json.dump(doc, f, indent=1, sort_keys=True)
    os.replace(tmp, path)
    return path


def export_job(obs_dir: str, out_path: str | None = None,
               fold: bool = True, top_k: int = 3,
               job_key: str = "") -> tuple[dict, str, dict]:
    """The one-call export path (what ``trace_tool.py export`` and the CI
    gate run): load, merge, build, self-validate, write, and fold the
    straggler aggregates back into the (job's) telemetry file.  Returns
    ``(trace_doc, written_path, straggler_report)``."""
    job = load_job(obs_dir, job_key=job_key)
    doc = build_chrome_trace(job)
    errs = validate_chrome_trace(doc)
    if errs:
        raise TraceError("export produced an invalid trace: "
                         + "; ".join(errs[:5]))
    out_path = out_path or os.path.join(
        obs_dir, f"trace-{job_key}.json" if job_key else "trace.json")
    tmp = f"{out_path}.tmp.{os.getpid()}"
    with open(tmp, "w") as f:
        json.dump(doc, f, sort_keys=True)
    os.replace(tmp, out_path)
    report = straggler_report(job, top_k=top_k)
    if fold:
        fold_into_telemetry(obs_dir, report, job_key=job_key)
    return doc, out_path, report


def export_follow(obs_dir: str, out_path: str | None = None,
                  interval: float = 1.0, fold: bool = True, top_k: int = 3,
                  job_key: str = "", max_rounds: int | None = None,
                  should_stop=None,
                  on_round=None) -> tuple[dict, str, dict, int]:
    """Tail mode: re-export the trace every ``interval`` seconds while the
    job is still running (``trace_tool export --follow``).

    Each round merges whatever spill dumps exist so far
    (``rabit_obs_spill_sec`` makes the flight rings land on disk mid-run)
    and atomically rewrites ``out_path`` — so at EVERY instant the output
    is a complete, validated Perfetto document that simply grows between
    rounds; a reader never sees a torn file.  Dumps that are mid-write or
    just evicted are skipped (``tolerant`` load), not fatal.

    Stops when the job's telemetry file appears (the tracker writes it at
    shutdown) — then runs one final *strict* :func:`export_job` so the
    finished artifact gets the full validation + straggler fold — or after
    ``max_rounds`` rounds (final pass stays tolerant and unfolded, the job
    is still live).  ``should_stop()`` and ``on_round(round, doc)`` are
    test/driver hooks.  Returns ``(doc, out_path, report, rounds)``."""
    out_path = out_path or os.path.join(
        obs_dir, f"trace-{job_key}.json" if job_key else "trace.json")
    tele_path = os.path.join(obs_dir, telemetry_name(job_key))
    rounds = 0
    while True:
        final_key = job_key
        finished = os.path.exists(tele_path)
        if not finished and not job_key:
            # Multi-tenant dirs never produce the bare legacy name: a
            # service job lands as telemetry-<job>.json, so a bare-key
            # follow adopts the first finished job's key and finalizes
            # against it (consistent with ``trace_tool export --job``).
            jobs = discover_telemetry_jobs(obs_dir)
            if jobs:
                final_key, finished = jobs[0], True
        if finished:
            doc, out_path, report = export_job(
                obs_dir, out_path, fold=fold, top_k=top_k,
                job_key=final_key)
            return doc, out_path, report, rounds + 1
        job = load_job(obs_dir, job_key=job_key, tolerant=True)
        doc = build_chrome_trace(job)
        errs = validate_chrome_trace(doc)
        if errs:
            raise TraceError("follow export produced an invalid trace: "
                             + "; ".join(errs[:5]))
        tmp = f"{out_path}.tmp.{os.getpid()}"
        with open(tmp, "w") as f:
            json.dump(doc, f, sort_keys=True)
        os.replace(tmp, out_path)
        rounds += 1
        if on_round is not None:
            on_round(rounds, doc)
        if max_rounds is not None and rounds >= max_rounds:
            return doc, out_path, straggler_report(job, top_k=top_k), rounds
        if should_stop is not None and should_stop():
            return doc, out_path, straggler_report(job, top_k=top_k), rounds
        time.sleep(max(interval, 0.05))
