"""Worker-side tracker shipping: metric snapshots and heartbeat leases.

Workers ship their metrics snapshot to the tracker as a ``CMD_METRICS``
message (a JSON string on the same framed wire as ``CMD_PRINT``, see
rabit_tpu/tracker/protocol.py) — on shutdown always, and periodically when
``rabit_obs_heartbeat_sec`` > 0.  The tracker aggregates the latest
snapshot per rank into the job-level ``telemetry.json``.

With ``rabit_heartbeat_sec`` > 0 a second periodic sender renews a
``CMD_HEARTBEAT`` lease (doc/fault_tolerance.md): the tracker suspects a
worker whose lease lapses for ``LEASE_FACTOR`` intervals — the failure
detector for SILENT deaths (frozen process, preempted VM) that never
produce an exit code or a TCP error.

Everything here rides :func:`rabit_tpu.tracker.protocol.tracker_rpc`, the
one bounded/retrying client path, and is best-effort: observability must
never fail a job, so a dead tracker or refused connection is swallowed (a
missed lease renewal is healed by the next tick — the lease tolerates one).
"""

from __future__ import annotations

import json
import threading
from typing import Callable

from rabit_tpu.tracker import protocol as P

#: Current snapshot envelope version (bump on incompatible change).
SNAPSHOT_SCHEMA = 1


def _note_clock(reply: object) -> None:
    """Fold a timestamped ACK into the process clock estimate (lazy import:
    this module is imported by the obs package __init__, trace is not)."""
    if isinstance(reply, P.TimedAck):
        from rabit_tpu.obs.trace import GLOBAL_CLOCK

        GLOBAL_CLOCK.update(reply.offset, reply.err)


def build_snapshot(registry, rank: int, task_id: str, host: str = "",
                   extra: dict | None = None) -> dict:
    """The JSON envelope a worker ships: identity + full registry state."""
    snap = {
        "schema": SNAPSHOT_SCHEMA,
        "rank": rank,
        "task_id": task_id,
        "host": host,
        "metrics": registry.snapshot(),
    }
    if extra:
        snap.update(extra)
    return snap


def ship_snapshot(snapshot: dict, tracker_host: str, tracker_port: int,
                  task_id: str, timeout: float = 5.0, retries: int = 0,
                  addrs: list | None = None) -> bool:
    """Send one snapshot; True on ACK.  Raises nothing.  ``addrs`` is
    the HA failover list (rabit_tracker_addrs, doc/ha.md)."""
    try:
        reply = P.tracker_rpc(
            tracker_host, tracker_port, P.CMD_METRICS, task_id,
            message=json.dumps(snapshot), timeout=timeout, retries=retries,
            addrs=addrs,
        )
    except (P.TrackerUnreachable, ValueError):
        return False
    _note_clock(reply)
    return reply == P.ACK


def renew_lease(tracker_host: str, tracker_port: int, task_id: str,
                interval: float, rank: int = -1,
                timeout: float | None = None,
                addrs: list | None = None) -> bool:
    """Renew this worker's heartbeat lease; True on ACK.  Raises nothing.

    No retries: a renewal that misses its window is worthless — the next
    tick is the retry, and the tracker-side lease tolerates one miss
    (``LEASE_FACTOR``).  The send is bounded by ``timeout`` (default: one
    interval) so a wedged tracker cannot back the sender up.  With an
    ``addrs`` failover list ONE retry is allowed — the rotation lands
    the second attempt on the standby, so a taken-over lease is renewed
    within the same tick instead of a tick late (doc/ha.md)."""
    try:
        reply = P.tracker_rpc(
            tracker_host, tracker_port, P.CMD_HEARTBEAT, task_id,
            prev_rank=rank, message=repr(float(interval)),
            timeout=timeout if timeout is not None else max(interval, 0.2),
            retries=1 if addrs else 0, addrs=addrs,
        )
    except (P.TrackerUnreachable, ValueError):
        return False
    _note_clock(reply)
    return reply == P.ACK


def clock_ping(tracker_host: str, tracker_port: int, task_id: str,
               samples: int = 2, timeout: float = 2.0,
               addrs: list | None = None) -> int:
    """Collect clock-offset samples without any other effect: a heartbeat
    with interval 0 grants no lease (the tracker ignores non-positive
    intervals) but its reply still carries the tracker clock stamp.  Used
    at shutdown so even a job that never enabled periodic heartbeats ships
    a clock estimate in its final snapshot.  Returns how many samples
    landed; raises nothing."""
    got = 0
    for _ in range(max(samples, 0)):
        try:
            reply = P.tracker_rpc(
                tracker_host, tracker_port, P.CMD_HEARTBEAT, task_id,
                message="0", timeout=timeout, retries=0, addrs=addrs,
            )
        except (P.TrackerUnreachable, ValueError):
            return got
        _note_clock(reply)
        got += 1
    return got


class Heartbeat:
    """Daemon thread invoking ``ship()`` every ``interval`` seconds until
    stopped — the one periodic-sender mechanism, used for both metric
    snapshots and lease renewals.  ``ship`` runs on the heartbeat thread;
    whatever it reads must be thread-safe by contract.  ``immediate=True``
    fires once at start() so a lease exists before the first full interval
    elapses."""

    def __init__(self, interval: float, ship: Callable[[], object],
                 immediate: bool = False):
        self._interval = max(float(interval), 0.05)
        self._ship = ship
        self._immediate = immediate
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._run, name="rabit-obs-heartbeat", daemon=True
        )

    def start(self) -> "Heartbeat":
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        self._thread.join(timeout=2.0)

    def _run(self) -> None:
        if self._immediate:
            self._ship()
        while not self._stop.wait(self._interval):
            self._ship()
