"""Worker-side metric shipping over the tracker protocol.

Workers ship their metrics snapshot to the tracker as a ``CMD_METRICS``
message (a JSON string on the same framed wire as ``CMD_PRINT``, see
rabit_tpu/tracker/protocol.py) — on shutdown always, and periodically when
``rabit_obs_heartbeat_sec`` > 0.  The tracker aggregates the latest
snapshot per rank into the job-level ``telemetry.json``.

Everything here is best-effort: observability must never fail a job, so a
dead tracker or refused connection is swallowed (and counted on the
registry so it is still visible in the next successful ship).
"""

from __future__ import annotations

import json
import socket
import threading
from typing import Callable

from rabit_tpu.tracker import protocol as P

#: Current snapshot envelope version (bump on incompatible change).
SNAPSHOT_SCHEMA = 1


def build_snapshot(registry, rank: int, task_id: str, host: str = "",
                   extra: dict | None = None) -> dict:
    """The JSON envelope a worker ships: identity + full registry state."""
    snap = {
        "schema": SNAPSHOT_SCHEMA,
        "rank": rank,
        "task_id": task_id,
        "host": host,
        "metrics": registry.snapshot(),
    }
    if extra:
        snap.update(extra)
    return snap


def ship_snapshot(snapshot: dict, tracker_host: str, tracker_port: int,
                  task_id: str, timeout: float = 5.0) -> bool:
    """Send one snapshot; True on ACK.  Raises nothing."""
    try:
        with socket.create_connection(
            (tracker_host, int(tracker_port)), timeout=timeout
        ) as sock:
            P.send_hello(sock, P.CMD_METRICS, task_id,
                         message=json.dumps(snapshot))
            return P.get_u32(sock) == P.ACK
    except (OSError, ValueError):
        return False


class Heartbeat:
    """Daemon thread shipping a fresh snapshot every ``interval`` seconds
    until stopped.  ``make_snapshot`` is called on the heartbeat thread —
    the registry is thread-safe by contract."""

    def __init__(self, interval: float, make_snapshot: Callable[[], dict],
                 tracker_host: str, tracker_port: int, task_id: str):
        self._interval = max(float(interval), 0.05)
        self._make_snapshot = make_snapshot
        self._addr = (tracker_host, int(tracker_port))
        self._task_id = task_id
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._run, name="rabit-obs-heartbeat", daemon=True
        )

    def start(self) -> "Heartbeat":
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        self._thread.join(timeout=2.0)

    def _run(self) -> None:
        while not self._stop.wait(self._interval):
            ship_snapshot(self._make_snapshot(), self._addr[0], self._addr[1],
                          self._task_id)
