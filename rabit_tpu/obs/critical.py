"""Per-round critical-path engine — who bounded each collective round.

``trace.straggler_report`` ranks ranks by cumulative lateness, but a
lateness table cannot say *what kind* of fault bounded a given round: a
compute straggler and a degraded planned-ring link both stretch wall
time, with opposite signatures.  This module classifies every collective
round from the merged span timeline (``trace.collective_arrivals``):

* **entry skew** — the last-entering rank's begin minus the median
  begin.  A compute straggler enters late every round, so its rounds
  show entry skew ~= the straggle and near-baseline drain;
* **excess drain** — the round's drain (last END minus last BEGIN — the
  in-collective time after everyone has arrived) minus the job's median
  drain.  A degraded link costs nothing at entry (the carry-over is one
  round's delay) but stretches the in-collective phase by ~(W-1) hop
  delays, so its rounds show excess drain >> entry skew.

Whichever term dominates names the gate: ``compute`` rounds indict the
last-entering rank; ``link`` rounds indict the slowest in-collective
rank's *incoming* planned-ring link (the DST of a slow link drains
last — the same asymmetry ``sched/repair.py`` exploits).  Rounds where
both terms sit under the noise margin are ``balanced``, and rounds
overlapping a recovery wave are excluded from gating tables and costed
separately (recovery-wave accounting), mirroring ``straggler_report``.

The report joins the streamed ``link_wait_seconds{src,dst}`` rollup out
of ``telemetry.json`` so each gating link carries its streamed wait
total next to the span-derived drain — two independent witnesses of the
same fault.  ``fold_critical_path`` writes the report back into the
telemetry file under ``critical_path``; ``trace_tool diagnose`` is the
CLI (doc/observability.md).
"""

from __future__ import annotations

import json
import os
import time

from rabit_tpu.obs.trace import (JobTrace, TraceError, collective_arrivals,
                                 recovery_windows, telemetry_name)

#: Critical-path report schema (bump on incompatible change).
CRITICAL_SCHEMA = 1

#: Below this, neither entry skew nor excess drain indicts anyone — the
#: round is "balanced".  Generous vs scheduler jitter on a loopback CI
#: box; chaos-injected faults sit well above it.
DEFAULT_MARGIN_SEC = 0.02


def _median(values: list[float]) -> float:
    if not values:
        return 0.0
    s = sorted(values)
    mid = len(s) // 2
    return s[mid] if len(s) % 2 else (s[mid - 1] + s[mid]) / 2.0


def ring_prev(rank: int, ring: list[int]) -> int:
    """The planned-ring predecessor of ``rank`` among the round's
    participants (the schedule orders the ring by rank, so the cyclic
    predecessor in sorted order is the rank whose frames ``rank`` waits
    on — see sched/ring planning)."""
    order = sorted(ring)
    return order[order.index(rank) - 1]


def critical_path_report(job: JobTrace, margin_sec: float = DEFAULT_MARGIN_SEC,
                         top_k: int = 3) -> dict:
    """Classify every seqno-stamped collective round and aggregate the
    gating tables.  Pure function of an already-loaded :class:`JobTrace`;
    raises nothing on thin data (an empty job yields an empty report)."""
    arrivals = collective_arrivals(job)
    windows = recovery_windows(job)
    err = job.max_clock_err()

    rounds: list[dict] = []
    drains: list[float] = []
    affected = 0
    for key in sorted(arrivals, key=lambda k: (k[0] or 0, k[1] or 0)):
        ranks = arrivals[key]
        if len(ranks) < 2:
            continue
        begins = {r: s.begin for r, s in ranks.items()}
        ends = {r: s.end for r, s in ranks.items() if s.end is not None}
        if not ends:
            continue
        lo = min(begins.values()) - margin_sec - err
        hi = max(ends.values()) + margin_sec + err
        if any(s <= hi and e >= lo for s, e in windows):
            affected += 1
            continue
        last_rank = max(begins, key=begins.get)
        entry_skew = begins[last_rank] - _median(list(begins.values()))
        drain = max(ends.values()) - max(begins.values())
        drains.append(drain)
        rounds.append({
            "key": key, "ranks": ranks, "begins": begins, "ends": ends,
            "last_rank": last_rank, "entry_skew": max(entry_skew, 0.0),
            "drain": max(drain, 0.0),
            "latency": max(ends.values()) - min(begins.values()),
        })

    base_drain = _median(drains)
    by_class = {"compute": 0, "link": 0, "balanced": 0}
    rank_gates: dict[int, dict] = {}
    link_gates: dict[tuple[int, int], dict] = {}
    breakdown: list[dict] = []
    for rnd in rounds:
        excess = max(rnd["drain"] - base_drain, 0.0)
        skew = rnd["entry_skew"]
        entry = {"op": rnd["key"][2], "version": rnd["key"][0],
                 "seqno": rnd["key"][1],
                 "latency_s": round(rnd["latency"], 6),
                 "entry_skew_s": round(skew, 6),
                 "excess_drain_s": round(excess, 6)}
        if max(skew, excess) < margin_sec:
            by_class["balanced"] += 1
            entry["gate"] = "balanced"
        elif skew >= excess:
            by_class["compute"] += 1
            rank = rnd["last_rank"]
            entry.update(gate="compute", rank=rank)
            agg = rank_gates.setdefault(rank, {"rounds": 0, "cost_s": 0.0})
            agg["rounds"] += 1
            agg["cost_s"] += skew
        else:
            by_class["link"] += 1
            # the slowest in-collective rank is the dst of the gating link
            spans = rnd["ranks"]
            dst = max(rnd["ends"],
                      key=lambda r: rnd["ends"][r] - spans[r].begin)
            src = ring_prev(dst, list(spans))
            entry.update(gate="link", src=src, dst=dst)
            agg = link_gates.setdefault((src, dst),
                                        {"rounds": 0, "cost_s": 0.0})
            agg["rounds"] += 1
            agg["cost_s"] += excess
        breakdown.append(entry)

    # join the streamed link_wait_seconds rollup: an independent witness
    stream = ((job.telemetry or {}).get("stream") or {})
    stream_wait: dict[tuple, float] = {}
    for row in stream.get("links", ()):
        try:
            stream_wait[(int(row["src"]), int(row["dst"]))] = float(
                row.get("sum", 0.0))
        except (KeyError, TypeError, ValueError):
            continue

    def link_rows():
        out = []
        for (src, dst), agg in sorted(link_gates.items(),
                                      key=lambda kv: -kv[1]["cost_s"]):
            row = {"src": src, "dst": dst, "rounds": agg["rounds"],
                   "cost_s": round(agg["cost_s"], 6)}
            if (src, dst) in stream_wait:
                row["streamed_wait_s"] = round(stream_wait[(src, dst)], 6)
            out.append(row)
        return out

    rank_rows = [{"rank": r, "rounds": agg["rounds"],
                  "cost_s": round(agg["cost_s"], 6)}
                 for r, agg in sorted(rank_gates.items(),
                                      key=lambda kv: -kv[1]["cost_s"])]
    breakdown.sort(key=lambda e: -e["latency_s"])
    waves = [{"start_s": round(s, 6), "end_s": round(e, 6),
              "cost_s": round(e - s, 6)} for s, e in windows]
    return {
        "schema": CRITICAL_SCHEMA,
        "margin_s": margin_sec,
        "clock_max_err_s": round(err, 6),
        "rounds_total": len(arrivals),
        "rounds_analyzed": len(rounds),
        "rounds_recovery_affected": affected,
        "rounds_by_gate": by_class,
        "base_drain_s": round(base_drain, 6),
        "latency_total_s": round(sum(r["latency"] for r in rounds), 6),
        "entry_skew_total_s": round(sum(r["entry_skew"] for r in rounds), 6),
        "top_gating_ranks": rank_rows[:max(top_k, 0)],
        "top_gating_links": link_rows()[:max(top_k, 0)],
        "worst_rounds": breakdown[:max(top_k, 0)],
        "recovery_waves": waves,
        "recovery_cost_s": round(sum(w["cost_s"] for w in waves), 6),
    }


def fold_critical_path(obs_dir: str, report: dict,
                       job_key: str = "") -> str | None:
    """Write the report back into the (job's) telemetry file under
    ``critical_path`` and stamp a ``critical_path_folded`` event into its
    event log (atomic rewrite, mirroring ``trace.fold_into_telemetry``).
    Returns the path, or None when there is no telemetry file."""
    path = os.path.join(obs_dir, telemetry_name(job_key))
    if not os.path.exists(path):
        return None
    try:
        with open(path) as f:
            doc = json.load(f)
    except (OSError, ValueError) as exc:
        raise TraceError(f"cannot fold critical path into "
                         f"{os.path.basename(path)}: {exc!r}") from exc
    doc["critical_path"] = report
    events = doc.setdefault("events", [])
    if isinstance(events, list):
        events.append({"ts": time.time(), "kind": "critical_path_folded",
                       "rounds": report.get("rounds_analyzed", 0),
                       "links": len(report.get("top_gating_links", ())),
                       "ranks": len(report.get("top_gating_ranks", ()))})
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w") as f:
        json.dump(doc, f, indent=1, sort_keys=True)
    os.replace(tmp, path)
    return path
