"""Live telemetry plane — streamed metric deltas and the scrape schema.

Everything else in ``rabit_tpu.obs`` is post-mortem: telemetry.json is
written at tracker shutdown, traces are merged after the job dies.  This
module is the LIVE half (doc/observability.md "Live telemetry plane"):

* **Delta streaming** — workers extract bounded counter/histogram deltas
  from the process :class:`~rabit_tpu.obs.metrics.MetricsRegistry`
  (:class:`DeltaSource`) and piggyback them on the existing CMD_METRICS
  snapshot cadence; relays coalesce them per job per flush
  (:func:`merge_state` — counters sum, histogram buckets add); the
  tracker folds them into per-job/per-rank rollups
  (:class:`StreamRollup`) a CMD_OBS scrape renders without touching a
  worker.
* **Scrape exposition** — the versioned JSON document a ``CMD_OBS`` RPC
  returns (``Tracker.build_scrape``): live control-plane state plus the
  folded rollups, shaped tenant -> job -> rank -> link so the QoS /
  autoscaler / route-around policy loops can consume it directly.

Streamed metric names are DECLARED in :data:`STREAM_METRICS` — the same
closed-registry discipline as ``obs.events.KINDS``: the stream is
stringly typed end to end (producers here and in compress/elastic;
consumers in the tracker fold, obs_top, tests), so a typo'd producer
name silently starves every consumer.  ``tools/tpulint`` statically
checks every :func:`stream_count`/:func:`stream_observe` literal against
this dict; add the entry HERE in the same change that adds a producer.

Labeled series are flat strings — ``wire_bytes{codec=i8,fused=1}`` —
so they ride the existing registry/snapshot machinery unchanged;
:func:`parse_series` splits them back apart for rollup rendering.

All delta math is pure computation over dicts: the tracker-side fold
runs inside reactor callbacks and the relay batch fold, where blocking
is forbidden (tpulint reactor-blocking family).
"""

from __future__ import annotations

import math
import threading

from rabit_tpu.obs.metrics import GLOBAL_REGISTRY, MetricsRegistry

#: Version stamp of both the delta documents and the scrape exposition.
#: Consumers must check it: the schema (tenant -> job -> rank -> link) is
#: the contract the QoS/autoscaler/route-around loops build against.
STREAM_SCHEMA = 1

#: The declared streamed-metric registry — every metric name the delta
#: stream carries, with the producer/meaning in one line.  Checked by
#: tools/tpulint (stream-metric-unregistered) against every
#: stream_count/stream_observe call site.
STREAM_METRICS: dict[str, str] = {
    "wire_bytes": "post-codec bytes put on the wire, labeled "
                  "codec=<name>,fused=<0|1> (compress/transport.observe; "
                  "the per-tenant accounting the QoS loop meters)",
    "raw_bytes": "pre-codec payload bytes for the same events, same "
                 "labels — wire_bytes/raw_bytes is the live ratio",
    "link_wait_seconds": "per-planned-link receive wait, labeled "
                         "src=<rank>,dst=<rank> (ElasticWorker ring "
                         "timers; the route-around loop's health signal)",
    # model-delivery plane (rabit_tpu/delivery, doc/delivery.md)
    "delivery_bytes_served": "snapshot bytes the tracker served over "
                             "CMD_SNAP, labeled job=<job>,digest=<hex>",
    "delivery_subscribers": "distinct subscriber task ids seen on the "
                            "CMD_SUB poll path, labeled job=<job>",
    "delivery_cache_hits": "relay-local CMD_SNAP fetches answered from "
                           "the digest cache, labeled relay=<id>",
    "delivery_cache_misses": "CMD_SNAP fetches the relay had to proxy "
                             "upstream, labeled relay=<id>",
}


def series_name(name: str, **labels) -> str:
    """The flat registry name of one labeled series:
    ``name{k1=v1,k2=v2}`` with keys sorted (no labels: the bare name)."""
    if not labels:
        return name
    inner = ",".join(f"{k}={labels[k]}" for k in sorted(labels))
    return f"{name}{{{inner}}}"


def parse_series(series: str) -> tuple[str, dict[str, str]]:
    """Split one flat series name back into ``(base, labels)``."""
    if not series.endswith("}") or "{" not in series:
        return series, {}
    base, _, inner = series[:-1].partition("{")
    labels: dict[str, str] = {}
    for part in inner.split(","):
        k, sep, v = part.partition("=")
        if sep:
            labels[k] = v
    return base, labels


def stream_count(name: str, n: int, registry: MetricsRegistry | None = None,
                 **labels) -> None:
    """Count ``n`` into the streamed counter ``name`` (declared in
    :data:`STREAM_METRICS`) under the given labels.  Writes into the
    process registry, so the cumulative value also rides every ordinary
    snapshot/telemetry path — the delta stream is a VIEW, not a fork."""
    reg = registry if registry is not None else GLOBAL_REGISTRY
    reg.counter(series_name(name, **labels)).inc(int(n))


def stream_observe(name: str, value: float,
                   registry: MetricsRegistry | None = None,
                   **labels) -> None:
    """Observe ``value`` into the streamed histogram ``name`` (declared
    in :data:`STREAM_METRICS`) under the given labels."""
    reg = registry if registry is not None else GLOBAL_REGISTRY
    reg.histogram(series_name(name, **labels)).observe(float(value))


# -- delta math --------------------------------------------------------------
#
# A "state" is MetricsRegistry.raw_state() shape: {"counters": {name: int},
# "histograms": {name: {"bounds", "counts", "count", "sum", "min", "max"}}}.
# A delta is the same shape holding window differences (min/max stay
# cumulative — they are monotone, so idempotent re-folds are harmless).

def empty_state() -> dict:
    return {"counters": {}, "histograms": {}}


def _hist_delta(cur: dict, prev: dict | None) -> dict | None:
    if prev is None:
        d_counts = list(cur["counts"])
        d_count = int(cur["count"])
        d_sum = float(cur["sum"])
    else:
        pc = prev["counts"]
        d_counts = [int(c) - int(pc[i]) if i < len(pc) else int(c)
                    for i, c in enumerate(cur["counts"])]
        d_count = int(cur["count"]) - int(prev["count"])
        d_sum = float(cur["sum"]) - float(prev["sum"])
    if d_count <= 0:
        return None
    return {"bounds": list(cur["bounds"]), "counts": d_counts,
            "count": d_count, "sum": d_sum,
            "min": cur.get("min"), "max": cur.get("max")}


def diff_state(cur: dict, prev: dict | None) -> dict | None:
    """The bounded delta taking ``prev`` to ``cur`` (both raw states), or
    None when nothing changed.  Counters that did not move are omitted —
    the frame size is proportional to the window's activity, not the
    metric vocabulary."""
    prev = prev or empty_state()
    delta = empty_state()
    for name, value in cur.get("counters", {}).items():
        d = int(value) - int(prev.get("counters", {}).get(name, 0))
        if d:
            delta["counters"][name] = d
    for name, hist in cur.get("histograms", {}).items():
        d = _hist_delta(hist, prev.get("histograms", {}).get(name))
        if d is not None:
            delta["histograms"][name] = d
    if not delta["counters"] and not delta["histograms"]:
        return None
    return delta


def merge_state(acc: dict, delta: dict) -> dict:
    """Fold ``delta`` into ``acc`` IN PLACE (and return it): counters
    sum; histogram buckets add elementwise (count/sum likewise), min/max
    fold monotonically.  This is the relay's coalesce step AND the
    tracker's rollup step — one sum semantics end to end."""
    for name, d in delta.get("counters", {}).items():
        acc["counters"][name] = acc["counters"].get(name, 0) + int(d)
    for name, dh in delta.get("histograms", {}).items():
        ah = acc["histograms"].get(name)
        if ah is None:
            acc["histograms"][name] = {
                "bounds": list(dh.get("bounds", [])),
                "counts": list(dh.get("counts", [])),
                "count": int(dh.get("count", 0)),
                "sum": float(dh.get("sum", 0.0)),
                "min": dh.get("min"), "max": dh.get("max"),
            }
            continue
        dc = dh.get("counts", [])
        if len(ah["counts"]) == len(dc):
            ah["counts"] = [a + int(b) for a, b in zip(ah["counts"], dc)]
        ah["count"] += int(dh.get("count", 0))
        ah["sum"] += float(dh.get("sum", 0.0))
        for key, fold in (("min", min), ("max", max)):
            v = dh.get(key)
            if v is not None:
                ah[key] = v if ah.get(key) is None else fold(ah[key], v)
    return acc


def summarize_histogram(h: dict) -> dict:
    """Percentile summary of one merged raw histogram (the scrape's
    rendering — same fields as ``Histogram.snapshot``)."""
    count = int(h.get("count", 0))
    if count <= 0:
        return {"count": 0, "sum": 0.0}
    bounds, counts = h.get("bounds", []), h.get("counts", [])
    vmin = h.get("min")
    vmax = h.get("max")

    def pctl(p: float) -> float:
        target = max(1, math.ceil(p / 100.0 * count))
        cum = 0
        for i, c in enumerate(counts):
            cum += int(c)
            if cum >= target:
                bound = bounds[i] if i < len(bounds) else (vmax or 0.0)
                lo = vmin if vmin is not None else bound
                hi = vmax if vmax is not None else bound
                return min(max(bound, lo), hi)
        return vmax if vmax is not None else 0.0

    out = {"count": count, "sum": round(float(h.get("sum", 0.0)), 9)}
    if vmin is not None:
        out["min"] = round(float(vmin), 9)
    if vmax is not None:
        out["max"] = round(float(vmax), 9)
    if counts:
        out.update(p50=round(pctl(50), 9), p90=round(pctl(90), 9),
                   p99=round(pctl(99), 9))
    return out


# -- worker side: delta extraction -------------------------------------------

class DeltaSource:
    """Extracts successive bounded deltas from one registry.  ``take()``
    diffs the current raw state against the last taken baseline and
    advances it — each activity window is emitted exactly once, so the
    tracker-side fold of every delta equals the cumulative counters (the
    byte-for-byte reconciliation bar against telemetry.json)."""

    def __init__(self, registry: MetricsRegistry | None = None):
        self._registry = registry if registry is not None else GLOBAL_REGISTRY
        self._lock = threading.Lock()
        self._baseline: dict | None = None

    def take(self) -> dict | None:
        """The delta since the previous ``take`` (None when idle)."""
        cur = self._registry.raw_state()
        with self._lock:
            delta = diff_state(cur, self._baseline)
            if delta is not None:
                self._baseline = cur
        return delta


def delta_doc(job: str, rank: int, delta: dict) -> dict:
    """One rank's delta wrapped in the wire envelope a CMD_OBS batch
    payload carries (``put_delta_frame``): schema stamp, job key, and a
    per-rank section map — the relay merges several workers' docs into
    one per-job frame by merging the ``ranks`` maps."""
    return {"schema": STREAM_SCHEMA, "job": job, "ranks": {str(rank): delta}}


def merge_delta_doc(acc: dict | None, doc: dict) -> dict:
    """Coalesce one delta doc into a per-job accumulator doc (the relay's
    per-flush step): same-rank sections fold via :func:`merge_state`."""
    if acc is None:
        acc = {"schema": STREAM_SCHEMA, "job": doc.get("job", ""),
               "ranks": {}}
    for rank, delta in doc.get("ranks", {}).items():
        held = acc["ranks"].get(rank)
        if held is None:
            acc["ranks"][rank] = merge_state(empty_state(), delta)
        else:
            merge_state(held, delta)
    return acc


# -- tracker side: live rollups ----------------------------------------------

class StreamRollup:
    """Per-job fold target of every streamed delta: per-rank accumulated
    states plus the job total, all under one lock.  Pure dict math — safe
    inside reactor callbacks and the relay batch fold."""

    def __init__(self):
        self._lock = threading.Lock()
        self._per_rank: dict[str, dict] = {}
        self._total = empty_state()
        self.n_folds = 0
        self.last_fold_ts = 0.0

    def fold(self, rank: int | str, delta: dict, ts: float = 0.0) -> None:
        rank = str(rank)
        with self._lock:
            held = self._per_rank.get(rank)
            if held is None:
                self._per_rank[rank] = merge_state(empty_state(), delta)
            else:
                merge_state(held, delta)
            merge_state(self._total, delta)
            self.n_folds += 1
            if ts:
                self.last_fold_ts = ts

    def render(self) -> dict:
        """The JSON rollup a scrape embeds: cumulative counters verbatim
        (reconcilable against telemetry.json snapshots), histograms as
        percentile summaries, plus the per-link health table parsed out
        of the ``link_wait_seconds`` series labels."""
        with self._lock:
            per_rank = {r: _render_state(s)
                        for r, s in sorted(self._per_rank.items())}
            total = _render_state(self._total)
            links = _render_links(self._total)
            n_folds, last_ts = self.n_folds, self.last_fold_ts
        return {"schema": STREAM_SCHEMA, "n_folds": n_folds,
                "last_fold_ts": round(last_ts, 6), "total": total,
                "links": links, "per_rank": per_rank}


def _render_state(state: dict) -> dict:
    return {
        "counters": dict(sorted(state["counters"].items())),
        "histograms": {name: summarize_histogram(h)
                       for name, h in sorted(state["histograms"].items())},
    }


def _render_links(state: dict) -> list[dict]:
    """The per-planned-link wait table: one row per
    ``link_wait_seconds{src=...,dst=...}`` series in the rollup."""
    rows = []
    for name, h in sorted(state["histograms"].items()):
        base, labels = parse_series(name)
        if base != "link_wait_seconds" or "src" not in labels:
            continue
        row = {"src": labels.get("src", "?"), "dst": labels.get("dst", "?")}
        row.update(summarize_histogram(h))
        rows.append(row)
    return rows


def wire_bytes_by_codec(rendered: dict) -> dict[str, int]:
    """``{codec[:fused] -> wire bytes}`` from one RENDERED state's
    counters — the (job, codec, fused) accounting split the QoS loop
    reads (``fused=1`` series render as ``<codec>:fused``)."""
    out: dict[str, int] = {}
    for name, value in rendered.get("counters", {}).items():
        base, labels = parse_series(name)
        if base != "wire_bytes":
            continue
        key = labels.get("codec", "?")
        if labels.get("fused") in ("1", "True", "true"):
            key += ":fused"
        out[key] = out.get(key, 0) + int(value)
    return out
