"""Metrics registry — counters, gauges, latency histograms, per-op stats.

Subsumes the old ``rabit_tpu.profile.CollectiveStats`` (which remains as a
thin facade over a registry): the registry keeps the same per-op
calls/bytes/latency aggregates, adds log-bucketed latency histograms with
percentile estimation, and serializes to a JSON-able snapshot that workers
ship to the tracker (see rabit_tpu/obs/ship.py) for job-level aggregation.

Everything is thread-safe: the native engine invokes prepare/reduce
callbacks from non-main threads, and the heartbeat shipper snapshots
concurrently with collectives in flight.
"""

from __future__ import annotations

import contextlib
import math
import threading
import time
from bisect import bisect_left
from dataclasses import dataclass


@dataclass
class OpStats:
    """Per-operation accumulated timing — the Python-layer analogue of the
    mock engine's tsum_allreduce/tsum_allgather counters."""

    calls: int = 0
    nbytes: int = 0
    seconds: float = 0.0
    max_seconds: float = 0.0

    def add(self, nbytes: int, seconds: float) -> None:
        self.calls += 1
        self.nbytes += nbytes
        self.seconds += seconds
        self.max_seconds = max(self.max_seconds, seconds)


class Counter:
    """Monotonic counter."""

    __slots__ = ("_lock", "_value")

    def __init__(self):
        self._lock = threading.Lock()
        self._value = 0

    def inc(self, n: int = 1) -> None:
        with self._lock:
            self._value += n

    @property
    def value(self) -> int:
        with self._lock:
            return self._value


class Gauge:
    """Last-write-wins instantaneous value."""

    __slots__ = ("_lock", "_value")

    def __init__(self):
        self._lock = threading.Lock()
        self._value = 0.0

    def set(self, v: float) -> None:
        with self._lock:
            self._value = float(v)

    @property
    def value(self) -> float:
        with self._lock:
            return self._value


#: Default latency buckets: geometric, 1 µs .. ~67 s (factor 2, 27 bounds)
#: plus an implicit overflow bucket.  Fine enough that a bucket-upper-bound
#: percentile is within 2x of the true value across the whole range.
DEFAULT_BUCKETS: tuple[float, ...] = tuple(1e-6 * 2 ** i for i in range(27))


class Histogram:
    """Fixed-bucket histogram with percentile estimation.

    ``observe`` counts into the first bucket whose upper bound >= value
    (an implicit +inf overflow bucket catches the rest); ``percentile``
    returns the upper bound of the bucket holding the p-th observation,
    clamped into [min, max] of what was actually observed — deterministic
    and cheap, precise to one bucket width.
    """

    def __init__(self, buckets: tuple[float, ...] | list[float] | None = None):
        bounds = tuple(buckets) if buckets else DEFAULT_BUCKETS
        if list(bounds) != sorted(bounds):
            raise ValueError("histogram buckets must be sorted ascending")
        self._bounds = bounds
        self._counts = [0] * (len(bounds) + 1)  # +1: overflow
        self._lock = threading.Lock()
        self.count = 0
        self.total = 0.0
        self.vmin = math.inf
        self.vmax = -math.inf

    def observe(self, value: float) -> None:
        idx = bisect_left(self._bounds, value)
        with self._lock:
            self._counts[idx] += 1
            self.count += 1
            self.total += value
            self.vmin = min(self.vmin, value)
            self.vmax = max(self.vmax, value)

    def percentile(self, p: float) -> float:
        """p in [0, 100]."""
        with self._lock:
            if self.count == 0:
                return 0.0
            target = max(1, math.ceil(p / 100.0 * self.count))
            cum = 0
            for i, c in enumerate(self._counts):
                cum += c
                if cum >= target:
                    bound = (self._bounds[i] if i < len(self._bounds)
                             else self.vmax)
                    return min(max(bound, self.vmin), self.vmax)
            return self.vmax  # unreachable (cum == count >= target)

    def snapshot(self) -> dict:
        with self._lock:
            if self.count == 0:
                return {"count": 0, "sum": 0.0}
        return {
            "count": self.count,
            "sum": round(self.total, 9),
            "min": round(self.vmin, 9),
            "max": round(self.vmax, 9),
            "p50": round(self.percentile(50), 9),
            "p90": round(self.percentile(90), 9),
            "p99": round(self.percentile(99), 9),
        }

    def raw(self) -> dict:
        """Mergeable full state (raw bucket counts, not percentile
        summaries) — the substrate the live-telemetry delta stream
        subtracts and re-adds (rabit_tpu/obs/stream.py).  ``bounds`` ride
        along so a receiver can merge histograms it never constructed."""
        with self._lock:
            return {
                "bounds": list(self._bounds),
                "counts": list(self._counts),
                "count": self.count,
                "sum": self.total,
                "min": self.vmin if self.count else None,
                "max": self.vmax if self.count else None,
            }


class _Span:
    """Mutable handle yielded by ``MetricsRegistry.timed`` so callers whose
    byte count is only known after the operation (object broadcast: the
    non-root learns the payload length from the wire) can set it before the
    window closes."""

    __slots__ = ("op", "nbytes", "cache_key")

    def __init__(self, op: str, nbytes: int, cache_key: str | None = None):
        self.op = op
        self.nbytes = nbytes
        self.cache_key = cache_key


class MetricsRegistry:
    """Named counters/gauges/histograms plus per-op collective stats, all
    under one re-entrant lock.  Metric names are flat strings; per-op
    latency histograms are auto-named ``{op}_latency_seconds``."""

    def __init__(self):
        self._lock = threading.RLock()
        self._counters: dict[str, Counter] = {}
        self._gauges: dict[str, Gauge] = {}
        self._histograms: dict[str, Histogram] = {}
        self._ops: dict[str, OpStats] = {}

    # -- metric handles (create-or-get) -----------------------------------

    def counter(self, name: str) -> Counter:
        with self._lock:
            return self._counters.setdefault(name, Counter())

    def gauge(self, name: str) -> Gauge:
        with self._lock:
            return self._gauges.setdefault(name, Gauge())

    def histogram(self, name: str,
                  buckets: tuple[float, ...] | None = None) -> Histogram:
        with self._lock:
            hist = self._histograms.get(name)
            if hist is None:
                hist = self._histograms[name] = Histogram(buckets)
            return hist

    # -- collective timing -------------------------------------------------

    @property
    def ops(self) -> dict[str, OpStats]:
        """Live per-op aggregates.  Read-mostly; mutate via ``timed`` /
        ``observe_op`` so updates stay under the registry lock."""
        with self._lock:
            return self._ops

    def observe_op(self, op: str, nbytes: int, seconds: float) -> None:
        with self._lock:
            self._ops.setdefault(op, OpStats()).add(nbytes, seconds)
        self.histogram(f"{op}_latency_seconds").observe(seconds)

    @contextlib.contextmanager
    def timed(self, op: str, nbytes: int, cache_key: str | None = None):
        """Time one collective into the per-op stats + latency histogram.
        Yields a span whose ``nbytes`` may be updated inside the window."""
        span = _Span(op, nbytes, cache_key)
        t0 = time.perf_counter()
        try:
            yield span
        finally:
            self.observe_op(op, span.nbytes, time.perf_counter() - t0)

    # -- lifecycle / output ------------------------------------------------

    def reset(self) -> None:
        with self._lock:
            self._counters.clear()
            self._gauges.clear()
            self._histograms.clear()
            self._ops.clear()

    def report(self) -> str:
        """One line per op: count, volume, mean/max latency, bandwidth —
        the historical CollectiveStats.report format, plus p50/p99 from the
        latency histogram."""
        with self._lock:
            ops = {k: OpStats(v.calls, v.nbytes, v.seconds, v.max_seconds)
                   for k, v in self._ops.items()}
            hists = dict(self._histograms)
        lines = []
        for op in sorted(ops):
            s = ops[op]
            mean_ms = 1e3 * s.seconds / max(s.calls, 1)
            bw = s.nbytes / s.seconds / 2**20 if s.seconds > 0 else 0.0
            line = (
                f"{op}: {s.calls} calls, {s.nbytes / 2**20:.2f} MiB, "
                f"mean {mean_ms:.3f} ms, max {1e3 * s.max_seconds:.3f} ms, "
                f"{bw:.1f} MiB/s"
            )
            hist = hists.get(f"{op}_latency_seconds")
            if hist is not None and hist.count:
                line += (f", p50 {1e3 * hist.percentile(50):.3f} ms, "
                         f"p99 {1e3 * hist.percentile(99):.3f} ms")
            lines.append(line)
        return "\n".join(lines) if lines else "(no collectives recorded)"

    def raw_state(self) -> dict:
        """Mergeable counter/histogram state for the live-telemetry delta
        stream (rabit_tpu/obs/stream.py): raw bucket counts instead of the
        percentile summaries :meth:`snapshot` emits, so two states can be
        subtracted into a bounded delta and deltas re-summed losslessly."""
        with self._lock:
            counters = {k: c.value for k, c in self._counters.items()}
            hists = {k: h.raw() for k, h in self._histograms.items()}
        return {"counters": counters, "histograms": hists}

    def snapshot(self) -> dict:
        """JSON-able full state — what workers ship to the tracker."""
        with self._lock:
            counters = {k: c.value for k, c in self._counters.items()}
            gauges = {k: g.value for k, g in self._gauges.items()}
            hists = {k: h.snapshot() for k, h in self._histograms.items()}
            ops = {
                k: {"calls": v.calls, "nbytes": v.nbytes,
                    "seconds": round(v.seconds, 9),
                    "max_seconds": round(v.max_seconds, 9)}
                for k, v in self._ops.items()
            }
        return {"counters": counters, "gauges": gauges,
                "histograms": hists, "ops": ops}


#: Process-wide registry (rabit_tpu.api times every collective into it).
GLOBAL_REGISTRY = MetricsRegistry()
