"""Flight recorder — a bounded ring buffer of structured events.

The NCCL flight-recorder idea applied to the rabit protocol: every rank
keeps the last N structured events (collective begin/end with
cache_key/nbytes, engine lifecycle, checkpoint commits, recovery phases) in
memory at negligible cost, and dumps them as JSONL when something goes
wrong — a hang, a SIGTERM, an explicit request.  A `test_hang.py`-class
failure then leaves per-rank evidence in ``RABIT_OBS_DIR`` instead of
silence.

Events are flat JSON objects: ``{"ts": ..., "kind": ..., <fields>}`` — one
per line in a dump, so ``jq``/``grep`` work without a schema.  ``ts`` is
``time.time()`` (the same epoch clock as the launcher's death stamps and
the robust engine's ``failure_detected at=`` prints, so cross-process
timelines line up).
"""

from __future__ import annotations

import io
import json
import os
import re
import threading
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Iterable

#: Default ring capacity (events); override with rabit_obs_capacity.
DEFAULT_CAPACITY = 2048

#: Keys reserved by the envelope — event fields must not collide.
_RESERVED = ("ts", "kind")

#: The declared event-kind registry — every ``kind`` string this stack
#: emits, with the producer/meaning in one line.  The obs pipeline is
#: stringly typed end to end (producers here and in api/engine/tracker;
#: consumers in trace.py, telemetry aggregation, tools/, tests), so a
#: typo or a one-sided rename fails silently: the event is recorded but
#: no consumer ever matches it, and the Perfetto timeline or telemetry
#: tally quietly loses that signal.  ``tools/tpulint`` statically checks
#: all three directions against this dict (emitted => registered,
#: consumed => registered AND emitted, registered => emitted); add the
#: entry HERE in the same change that adds a producer or consumer.
KINDS: dict[str, str] = {
    # envelope / ring
    "flight_dump": "dump header line: pid, rank, reason, n_events, dropped",
    # collective spans (obs.collective; paired into trace spans)
    "op_begin": "collective entered: op, nbytes, cache_key, version, seqno",
    "op_end": "collective completed: adds seconds; pairs with op_begin",
    "op_inflight": "dump-time marker: op stuck in flight, stuck_seconds",
    # engine lifecycle (api.py / engine bridge)
    "engine_ready": "init() complete: engine class, rank, world",
    "engine_init": "native bridge entering RabitInit",
    "bootstrap_done": "(re)bootstrap complete: rank, world, attempt, seconds",
    "engine_shutdown": "native bridge entering RabitFinalize",
    "engine_finalize": "rabit_tpu.finalize() reached (pre-shutdown)",
    "engine_error": "native call failed: what, error (pre-exception)",
    "init_after_exception": "robust re-init after a caught exception",
    # compression (rabit_tpu/compress, doc/compression.md)
    "compress_policy": "codec policy resolved at init: allreduce codec, "
                       "min_bytes, checkpoint codec, deflate stage",
    "recovery_blob_compressed": "disk-resume blob served over the wire "
                                "zlib-compressed: raw, wire, version",
    # checkpoint line (api.py / native bridge)
    "checkpoint_commit": "version bump committed: version, nbytes",
    "checkpoint_loaded": "bridge served a peer-recovered blob: version",
    "load_checkpoint": "api load_checkpoint returned: version, recovered",
    "version_bump": "native checkpoint committed: version",
    # hang watchdog (obs.__init__)
    "hang_detected": "collective stuck past rabit_obs_hang_sec",
    "hang_recovered": "declared-hung op completed; lease renewals resume",
    "hang_abort": "dump-then-die escalation firing (exit 11)",
    # stats-line bridge (event_from_stats_line) + tracker-side events
    "recover_stats": "robust engine per-recovery counters (from prints)",
    "recover_stats_final": "robust engine shutdown-time counters",
    "failure_detected": "robust engine noticed a dead peer: at=",
    "worker_recovered": "workload's recovered_at= stamp (in-job recovery)",
    "disk_resume": "workload resumed from durable spill: version",
    # tracker telemetry (tracker.py)
    "wave": "bootstrap/recovery wave assigned: epoch, assignments",
    "wave_purged": "dead pending connections dropped at wave fill",
    "lease_expired": "heartbeat lease lapsed: task_id, rank, overdue",
    "snapshot_rejected": "CMD_METRICS snapshot with out-of-range rank",
    "metrics_snapshot": "CMD_METRICS snapshot accepted: rank, task_id",
    # live telemetry plane (rabit_tpu/obs/stream.py,
    # doc/observability.md "Live telemetry plane")
    "obs_scrape": "first CMD_OBS scrape served this tracker lifetime "
                  "(per-scrape counts live in serve_stats.obs_scrapes)",
    "metrics_delta_folded": "first streamed metric delta folded for a "
                            "rank: rank (per-delta counts live in the "
                            "rollup's n_folds)",
    "obs_evicted": "flight-dump retention removed oldest dumps: n, "
                   "max_files (rabit_obs_max_files)",
    # elastic worlds (rabit_tpu/elastic, doc/elasticity.md)
    "spare_parked": "hot spare checked in and parked: task_id, blob_version",
    "spare_dropped": "parked spare hung up; removed from the pool",
    "spare_promoted": "spare filled a dead rank's slot: task_id, rank, epoch",
    "world_shrunk": "wave closed below the previous world: from, to, lost",
    "world_grown": "wave closed above the previous world: from, to, joined",
    "bootstrap_blob": "tracker cached a spare bootstrap blob: version, nbytes",
    "epoch_changed": "worker adopted a new world epoch: epoch, world",
    "shard_rebalanced": "shard-rebalance callbacks ran for a resize",
    # partial (quorum) allreduce (rabit_tpu/quorum,
    # doc/partial_allreduce.md)
    "quorum_policy": "quorum policy resolved at init: spec, wait_sec, "
                     "flag_after",
    "quorum_met": "round decided with exclusions: epoch, version, k, "
                  "world, n_have, excluded",
    "contribution_late": "an excluded round's block was delivered: "
                         "src_version, rank",
    "correction_folded": "a late block folded into a later round: "
                         "version, src_version, rank",
    "correction_dropped": "epoch boundary dropped an undelivered "
                          "correction: src_version, rank, world",
    # serving at scale (reactor + relay tier; rabit_tpu/relay,
    # doc/scaling.md)
    "relay_up": "a relay's persistent CMD_BATCH channel registered: "
                "relay, host",
    "relay_lost": "a relay channel died (stateless fan-in: children "
                  "reconnect): relay",
    "batch_folded": "one coalesced relay envelope folded: relay, n "
                    "sub-messages",
    "messages_dropped": "the bounded worker-print log overflowed: cap "
                        "(total drops in telemetry.json)",
    # HA control plane (rabit_tpu/ha, doc/ha.md)
    "journal_snapshot": "journal compacted to one snapshot record: n, "
                        "nbytes",
    "journal_gap": "journal replay hit a torn/divergent stretch "
                   "(truncated or healed from a snapshot): error",
    "standby_synced": "standby replayed to a consistent state: epoch, "
                      "world",
    "tracker_failover": "standby promoted itself over the dead primary: "
                        "standby, epoch, world, synced",
    # multi-tenant collective service (rabit_tpu/service, doc/service.md)
    "job_admitted": "a job passed admission and got its partition: job, "
                    "world, tenant, pooled (restored=True after a "
                    "failover/journal replay)",
    "admission_refused": "a job hit a quota / bad key and was refused: "
                         "job, tenant, reason",
    "worker_leased": "a parked pool worker was leased into a job's "
                     "wave: task_id, job, pool",
    "job_completed": "a job finished and its partition retired: job, "
                     "world, seconds",
    # collective schedules (rabit_tpu/sched, doc/scheduling.md)
    "schedule_planned": "tracker planned a wave's schedule: epoch, algo, "
                        "ring_order, n_avoided",
    "schedule_repaired": "plan rewritten around degraded links: epoch, "
                         "avoided, residual",
    "link_degraded": "worker slow_link report (from prints): src, dst, "
                     "wait, share",
    # diagnosis plane (rabit_tpu/obs/diagnose.py, doc/observability.md)
    "incident_opened": "HealthMonitor opened an incident: incident, "
                       "class, + the subject fields (src/dst, rank, "
                       "relay...)",
    "incident_resolved": "an open incident went quiet past the "
                         "hysteresis bar: incident, class, + subject",
    "critical_path_folded": "trace_tool diagnose folded a critical-path "
                            "report into telemetry.json: rounds, links, "
                            "ranks",
    # model-delivery plane (rabit_tpu/delivery, doc/delivery.md)
    "snapshot_published": "a checkpoint commit registered as a "
                          "content-addressed snapshot: version, epoch, "
                          "digest, size (journaled so a standby restores "
                          "the version line)",
    "snapshot_fetched": "first CMD_SNAP fetch of a digest served: "
                        "digest, nbytes (per-fetch byte counts stream as "
                        "delivery_bytes_served)",
    "blob_cache_evicted": "a relay's digest-keyed snapshot cache dropped "
                          "an entry: digest, nbytes, reason "
                          "(lru|superseded|job_retired)",
}


@dataclass(frozen=True)
class Event:
    ts: float
    kind: str
    fields: dict = field(default_factory=dict)

    def to_json(self) -> str:
        return json.dumps({"ts": round(self.ts, 6), "kind": self.kind,
                           **self.fields}, sort_keys=True)

    @classmethod
    def from_json(cls, line: str) -> "Event":
        obj = json.loads(line)
        ts = float(obj.pop("ts"))
        kind = str(obj.pop("kind"))
        return cls(ts, kind, obj)


class FlightRecorder:
    """Thread-safe bounded event ring.  ``record`` is cheap enough to call
    on every collective (a dict build + deque append under a lock); old
    events are evicted silently but counted (``dropped``)."""

    def __init__(self, capacity: int = DEFAULT_CAPACITY):
        self._lock = threading.Lock()
        self._buf: deque[Event] = deque(maxlen=max(int(capacity), 1))
        self._dropped = 0

    @property
    def capacity(self) -> int:
        return self._buf.maxlen or 0

    @property
    def dropped(self) -> int:
        """Events evicted from the ring so far."""
        with self._lock:
            return self._dropped

    def set_capacity(self, capacity: int) -> None:
        """Resize the ring, keeping the newest events."""
        capacity = max(int(capacity), 1)
        with self._lock:
            if capacity == self._buf.maxlen:
                return
            old = list(self._buf)
            self._dropped += max(len(old) - capacity, 0)
            self._buf = deque(old[-capacity:], maxlen=capacity)

    def record(self, kind: str, /, **fields) -> Event:
        for key in _RESERVED:
            if key in fields:
                raise ValueError(f"event field {key!r} is reserved")
        ev = Event(time.time(), kind, fields)
        with self._lock:
            if len(self._buf) == self._buf.maxlen:
                self._dropped += 1
            self._buf.append(ev)
        return ev

    def snapshot(self) -> list[Event]:
        with self._lock:
            return list(self._buf)

    def clear(self) -> None:
        with self._lock:
            self._buf.clear()
            self._dropped = 0

    def dump(self, path: str | os.PathLike, header: dict | None = None) -> str:
        """Write the ring as JSONL (oldest first).  ``header`` fields land in
        a first ``kind="flight_dump"`` line (pid, rank, reason, ...)."""
        events = self.snapshot()
        meta = dict(header or {})
        meta.setdefault("pid", os.getpid())
        meta["n_events"] = len(events)
        meta["dropped"] = self.dropped
        buf = io.StringIO()
        buf.write(Event(time.time(), "flight_dump", meta).to_json() + "\n")
        for ev in events:
            buf.write(ev.to_json() + "\n")
        path = os.fspath(path)
        with open(path, "w") as f:
            f.write(buf.getvalue())
        return path


def load_dump(path: str | os.PathLike) -> list[Event]:
    """Read a JSONL dump back into events (header line included)."""
    with open(path) as f:
        return [Event.from_json(line) for line in f if line.strip()]


# -- stdout-line bridge ------------------------------------------------------
#
# The native robust engine's observability prints (``recover_stats``,
# ``recover_stats_final``, ``failure_detected``) reach the tracker as plain
# CMD_PRINT lines.  These converters are the bridge from that legacy line
# format into structured events — the tracker applies them on every print
# so consumers (tools/recovery_bench.py, tools/consensus_bench.py,
# telemetry.json) never scrape stdout themselves.

def parse_stats_line(line: str) -> dict[str, str]:
    """Parse a ``key=value``-style line into a dict (one point of truth for
    the robust engine's stats-line format)."""
    return dict(p.split("=", 1) for p in line.split() if "=" in p)


def is_recovery_stats_line(line: str) -> bool:
    """True for a recovered life's per-recovery ``recover_stats`` line from
    LoadCheckPoint.  Excludes the shutdown-time ``recover_stats_final``
    lines (shared prefix, no per-recovery fields) and first lives
    (version=0)."""
    return ("recover_stats " in line and "recover_stats_final" not in line
            and "version=0 " not in line)


def _line_rank(line: str) -> int:
    """Rank from the conventional ``[N] ...`` print prefix, -1 if absent."""
    line = line.lstrip()
    if line.startswith("["):
        head = line[1:line.find("]")] if "]" in line else ""
        try:
            return int(head)
        except ValueError:
            pass
    return -1


def event_from_stats_line(line: str, ts: float | None = None) -> Event | None:
    """Convert one worker observability print into a structured event, or
    None for ordinary prints.  Numeric fields are parsed to int/float; the
    emitting rank comes from the ``[N]`` prefix.

    Recognized: the robust engine's ``recover_stats`` /
    ``recover_stats_final`` / ``failure_detected`` lines, plus the recovery
    workloads' ``recovered_at=`` (in-job peer recovery complete) and
    ``resumed from disk`` (durable whole-job resume) stamps — so tools read
    ``LocalCluster.events`` / ``telemetry.json`` instead of scraping
    stdout."""
    if "recover_stats_final" in line:
        kind = "recover_stats_final"
    elif "recover_stats " in line:
        kind = "recover_stats"
    elif "failure_detected" in line:
        kind = "failure_detected"
    elif "recovered_at=" in line:
        kind = "worker_recovered"
    elif "resumed from disk" in line:
        kind = "disk_resume"
    elif "slow_link " in line:
        # an executor indicting its incoming ring link (rabit_tpu.sched
        # repair policy): src=/dst= ranks, wait=/share= evidence
        kind = "link_degraded"
    else:
        return None
    fields: dict = {"rank": _line_rank(line)}
    for key, raw in parse_stats_line(line).items():
        if key in _RESERVED:
            # a printed ts= stamp must not shadow the envelope's ts
            key = "at"
        try:
            fields[key] = int(raw)
        except ValueError:
            try:
                fields[key] = float(raw)
            except ValueError:
                fields[key] = raw
    if kind == "disk_resume" and "version" not in fields:
        m = re.search(r"at version (\d+)", line)
        if m:
            fields["version"] = int(m.group(1))
    return Event(time.time() if ts is None else ts, kind, fields)


def events_from_lines(lines: Iterable[str]) -> list[Event]:
    """Batch form of :func:`event_from_stats_line` (skips ordinary lines)."""
    out = []
    for line in lines:
        ev = event_from_stats_line(line)
        if ev is not None:
            out.append(ev)
    return out
