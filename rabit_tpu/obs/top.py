"""rabit-top — live job/rank/link view over the CMD_OBS scrape RPC.

A deliberately curses-free poller (``python -m rabit_tpu.obs.top``): every
``--interval`` seconds it issues one CMD_OBS scrape
(doc/observability.md "Live telemetry plane"), diffs it against the
previous poll, and repaints one plain-text frame:

* header — tracker address, uptime, serve counters, scrape round-trip;
* per tenant -> job — epoch/world/leases/pending/restarts plus the poll-
  to-poll cadence (delta folds/s and wire B/s, per codec);
* straggler watch — ranks ordered by their share of cumulative link wait
  (the same signal ``trace_tool report`` computes post-hoc, but live);
* link health — the per-planned-link wait table (src -> dst, p50/p99).

Nothing here talks to a worker: one cheap RPC against the tracker, which
answers from already-folded rollups.  ``--json`` emits the raw scrape
document once per poll instead of the rendered frame (for piping into
watch scripts); ``--once`` polls a single time and exits.
"""

from __future__ import annotations

import argparse
import json
import sys
import time

from rabit_tpu.obs import stream as obs_stream
from rabit_tpu.tracker import protocol as P


def scrape(host: str, port: int, task_id: str = "obs", job: str = "",
           registry: bool = False, timeout: float = 5.0) -> dict:
    """One CMD_OBS round trip.  A bare ``task_id`` gets the tracker- (or
    service-) level view; ``job`` prefixes it so a multi-job service
    routes the scrape to that job's partition (doc/service.md)."""
    tid = P.join_job(job, task_id) if job else task_id
    doc = P.tracker_rpc(host, port, P.CMD_OBS, tid,
                        message=json.dumps({"registry": bool(registry)}),
                        timeout=timeout, retries=1)
    if not isinstance(doc, dict):
        raise P.TrackerUnreachable(f"CMD_OBS returned {doc!r}, not a scrape")
    return doc


def _fmt_bytes(n: float) -> str:
    for unit in ("B", "KiB", "MiB", "GiB", "TiB"):
        if abs(n) < 1024.0 or unit == "TiB":
            return f"{n:.1f}{unit}" if unit != "B" else f"{int(n)}B"
        n /= 1024.0
    return f"{n:.1f}TiB"


def _fmt_age(sec: float) -> str:
    sec = max(sec, 0.0)
    if sec < 90:
        return f"{sec:.0f}s"
    if sec < 5400:
        return f"{sec / 60:.1f}m"
    return f"{sec / 3600:.1f}h"


def _job_rows(doc: dict) -> list[tuple[str, str, dict]]:
    """Flatten a scrape into (tenant, job_key, job_state) rows — the
    service exposition nests jobs under tenants; the base tracker has a
    single anonymous tenant."""
    tenants = doc.get("tenants")
    if isinstance(tenants, dict) and tenants:
        return [(tenant, key, jstate)
                for tenant, tdoc in sorted(tenants.items())
                for key, jstate in sorted(tdoc.get("jobs", {}).items())]
    return [("-", key, jstate)
            for key, jstate in sorted(doc.get("jobs", {}).items())]


def _wire_total(jstate: dict) -> int:
    split = obs_stream.wire_bytes_by_codec(
        jstate.get("stream", {}).get("total", {}))
    return sum(split.values())


def _straggler_rows(jstate: dict, top: int = 4) -> list[dict]:
    """Ranks ordered by cumulative link-wait share (from the per-rank
    ``link_wait_seconds{...}`` histogram sums in the rollup)."""
    per_rank = jstate.get("stream", {}).get("per_rank", {})
    waits: dict[str, float] = {}
    for rank, state in per_rank.items():
        total = 0.0
        for name, h in state.get("histograms", {}).items():
            base, _labels = obs_stream.parse_series(name)
            if base == "link_wait_seconds":
                total += float(h.get("sum", 0.0))
        if total > 0:
            waits[rank] = total
    whole = sum(waits.values())
    rows = [{"rank": r, "wait_s": w,
             "share": (w / whole) if whole > 0 else 0.0}
            for r, w in sorted(waits.items(), key=lambda kv: -kv[1])]
    return rows[:top]


def render(doc: dict, prev: dict | None = None, top_links: int = 6) -> str:
    """One plain-text frame from a scrape document (+ the previous poll
    for cadence).  Pure function of its inputs — the unit under test."""
    now = float(doc.get("ts", 0.0))
    dt = (now - float(prev.get("ts", now))) if prev else 0.0
    serving = doc.get("serving", {})
    lines = [
        f"rabit-top  schema={doc.get('schema')}  "
        f"up {_fmt_age(now - float(doc.get('started_at', now)))}  "
        f"reactor={'on' if serving.get('reactor') else 'off'}  "
        f"accepts={serving.get('accepts', 0)}  rpcs={serving.get('rpcs', 0)}  "
        f"scrapes={serving.get('obs_scrapes', 0)}"
    ]
    svc = doc.get("service")
    if isinstance(svc, dict):
        lines.append(
            f"service: live={svc.get('live')} admitted={svc.get('admitted')} "
            f"completed={svc.get('completed')} "
            f"pool_parked={svc.get('pool_parked')} "
            f"auto_world={svc.get('auto_world')}")
    # incidents pane (diagnosis plane, doc/observability.md): every open
    # incident across the jobs, newest-evidence fields inline
    incidents = doc.get("incidents")
    if isinstance(incidents, dict) and incidents.get("open"):
        lines.append(f"incidents: {incidents.get('n_open', 0)} open")
        for inc in incidents["open"]:
            subject = " ".join(f"{k}={v}" for k, v in
                               sorted((inc.get("subject") or {}).items()))
            lines.append(f"  [{inc.get('class')}] {inc.get('id')} "
                         f"job={inc.get('job') or '-'} {subject} "
                         f"({inc.get('windows', 0)}w)")

    prev_jobs = {key: j for _t, key, j in _job_rows(prev)} if prev else {}
    lines.append(f"{'tenant':<10} {'job':<12} {'ep':>3} {'world':>5} "
                 f"{'lease':>5} {'pend':>4} {'rst':>3} {'folds/s':>8} "
                 f"{'wire/s':>10} {'wire total':>11}")
    for tenant, key, jstate in _job_rows(doc):
        stream = jstate.get("stream", {})
        wire = _wire_total(jstate)
        folds = int(stream.get("n_folds", 0))
        rate = folds_s = 0.0
        if dt > 0 and key in prev_jobs:
            pstream = prev_jobs[key].get("stream", {})
            rate = max(wire - _wire_total(prev_jobs[key]), 0) / dt
            folds_s = max(folds - int(pstream.get("n_folds", 0)), 0) / dt
        lines.append(
            f"{tenant:<10.10} {(key or '-'): <12.12} "
            f"{jstate.get('epoch', 0):>3} {jstate.get('world', 0):>5} "
            f"{jstate.get('leases', 0):>5} {jstate.get('pending', 0):>4} "
            f"{jstate.get('restarts', 0):>3} {folds_s:>8.2f} "
            f"{_fmt_bytes(rate) + '/s':>10} {_fmt_bytes(wire):>11}")
        split = obs_stream.wire_bytes_by_codec(stream.get("total", {}))
        if split:
            per = "  ".join(f"{c}={_fmt_bytes(b)}"
                            for c, b in sorted(split.items()))
            lines.append(f"{'':<10} {'':<12} codecs: {per}")
        # delivery pane (doc/delivery.md): the published version line and
        # the content-addressed store behind it, when the job has one
        delivery = jstate.get("delivery")
        if isinstance(delivery, dict) and (delivery.get("line")
                                           or delivery.get("subscribers")):
            dline = delivery.get("line") or {}
            lines.append(
                f"{'':<10} {'':<12} delivery: "
                f"v{dline.get('version', 0)} "
                f"digest={str(dline.get('digest', ''))[:12] or '-'} "
                f"size={_fmt_bytes(float(dline.get('size', 0)))} "
                f"snaps={delivery.get('snaps', 0)}"
                f"({_fmt_bytes(float(delivery.get('snap_bytes', 0)))}) "
                f"subs={delivery.get('subscribers', 0)}")
        stragglers = _straggler_rows(jstate)
        if stragglers:
            per = "  ".join(
                f"r{s['rank']}={s['wait_s'] * 1e3:.0f}ms"
                f"({s['share'] * 100:.0f}%)" for s in stragglers)
            lines.append(f"{'':<10} {'':<12} straggler-watch: {per}")
        links = stream.get("links", [])
        for row in sorted(links, key=lambda r: -float(r.get("p99", 0.0))
                          )[:top_links]:
            lines.append(
                f"{'':<10} {'':<12} link {row.get('src')}->{row.get('dst')}: "
                f"n={row.get('count', 0)} "
                f"p50={float(row.get('p50', 0.0)) * 1e3:.2f}ms "
                f"p99={float(row.get('p99', 0.0)) * 1e3:.2f}ms")
    return "\n".join(lines)


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m rabit_tpu.obs.top",
        description="poll a live tracker's CMD_OBS scrape and render a "
                    "top-style job/rank/link view")
    ap.add_argument("addr", metavar="HOST:PORT",
                    help="tracker (or service) control address")
    ap.add_argument("--interval", type=float, default=2.0)
    ap.add_argument("--job", default="",
                    help="scrape one job's partition of a multi-job "
                         "service instead of the service-level view")
    ap.add_argument("--task-id", default="obs",
                    help="scrape identity shown in tracker logs "
                         "(config rabit_obs_scrape)")
    ap.add_argument("--once", action="store_true", help="one poll, no loop")
    ap.add_argument("--rounds", type=int, default=None,
                    help="stop after N polls (default: until ^C)")
    ap.add_argument("--json", action="store_true",
                    help="emit the raw scrape JSON per poll (no rendering)")
    ap.add_argument("--registry", action="store_true",
                    help="include the full metrics registry in the scrape")
    args = ap.parse_args(argv)
    host, _, port_s = args.addr.rpartition(":")
    if not host:
        ap.error(f"addr wants HOST:PORT, got {args.addr!r}")

    prev: dict | None = None
    polls = 0
    clear = sys.stdout.isatty() and not args.json
    try:
        while True:
            t0 = time.perf_counter()
            doc = scrape(host, int(port_s), task_id=args.task_id,
                         job=args.job, registry=args.registry)
            rtt_ms = (time.perf_counter() - t0) * 1e3
            polls += 1
            if doc.get("schema") != obs_stream.STREAM_SCHEMA:
                # the exposition schema is the contract downstream
                # pollers gate on — refuse to mis-render a foreign one
                # (--json consumers read the stamp from the doc itself)
                print(f"unsupported scrape schema {doc.get('schema')!r} "
                      f"(want {obs_stream.STREAM_SCHEMA})", file=sys.stderr)
                return 3
            if args.json:
                print(json.dumps(doc, sort_keys=True), flush=True)
            else:
                frame = render(doc, prev)
                if clear:
                    sys.stdout.write("\x1b[2J\x1b[H")
                print(f"{frame}\n[poll {polls}, rtt {rtt_ms:.1f}ms]",
                      flush=True)
            prev = doc
            if args.once or (args.rounds is not None
                             and polls >= args.rounds):
                return 0
            time.sleep(max(args.interval, 0.1))
    except KeyboardInterrupt:
        return 0
    except P.TrackerUnreachable as exc:
        print(f"scrape failed: {exc}", file=sys.stderr)
        return 2


if __name__ == "__main__":
    sys.exit(main())
