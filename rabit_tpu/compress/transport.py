"""Host-side compressed-allreduce transport and the codec metering hooks.

The host transport is the pure-numpy reference path every engine gets for
free (the XLA engine overrides it with an on-device fused path):

1. ``prepare``d local contribution -> ``codec.encode`` -> optional deflate
   stage -> an 8-byte frame header (codec id + flags + payload length);
2. ONE engine allgather of the framed wire bytes (plus, only when the
   deflate stage makes sizes rank-dependent, one tiny int64 MAX allreduce
   agreeing on the padded slice size first — a fixed two-op sequence,
   identical on every rank, so the robust engine's positional seqno/replay
   contract is untouched);
3. every rank decodes all ranks' planes and folds them in rank order with
   the exact same numpy ops — so the delivered result is **bitwise
   identical on every rank**, and :func:`reference_allreduce` reproduces
   it in closed form for self-verifying workloads.

Replay safety: the engine-level collectives carry the caller's cache_key
(suffixed per sub-op); after a failure the robust engine replays the
*gathered wire bytes* verbatim, and because ``decode`` and the fold are
deterministic pure functions of those bytes, the decoded delivery is
bitwise identical to the first one.  A cross-rank codec mismatch is caught
by the frame header (``CodecMismatchError`` naming the ranks) instead of
silently folding garbage.
"""

from __future__ import annotations

import struct
import time
import zlib

import numpy as np

from rabit_tpu.compress.codecs import DEFLATE_LEVEL, Codec, get_codec
from rabit_tpu.obs import stream as obs_stream
from rabit_tpu.obs.metrics import GLOBAL_REGISTRY

#: Wire frame prepended to every rank's allgather slice:
#: codec id, flags, reserved, encoded payload length.
FRAME = struct.Struct("<BBxxI")

FLAG_DEFLATE = 0x01


class CodecMismatchError(RuntimeError):
    """Peers disagree on the collective's codec — config skew, not data."""


def observe(codec_name: str, raw: int, wire: int,
            encode_s: float | None = None,
            decode_s: float | None = None,
            fused: bool = False) -> None:
    """Record one compression event into the process metrics registry:
    raw/wire byte counters plus per-codec ratio and latency histograms
    (doc/observability.md, "Compression metrics").  ``fused=True`` marks
    bytes moved by the fused in-graph device path (engine/fused.py) —
    the labeled ``wire_bytes``/``raw_bytes`` series feed the live
    telemetry plane's (job, codec, fused) accounting."""
    reg = GLOBAL_REGISTRY
    reg.counter("compress_raw_bytes_total").inc(int(raw))
    reg.counter("compress_wire_bytes_total").inc(int(wire))
    obs_stream.stream_count("wire_bytes", wire, codec=codec_name,
                            fused=int(bool(fused)))
    obs_stream.stream_count("raw_bytes", raw, codec=codec_name,
                            fused=int(bool(fused)))
    if wire > 0:
        reg.histogram(f"compress_ratio_{codec_name}").observe(raw / wire)
    if encode_s is not None:
        reg.histogram(f"compress_encode_seconds_{codec_name}").observe(encode_s)
    if decode_s is not None:
        reg.histogram(f"compress_decode_seconds_{codec_name}").observe(decode_s)


def encode_wire(codec: Codec, buf: np.ndarray, deflate: bool) -> bytes:
    """Frame one rank's contribution: header + encoded planes, with the
    lossless deflate stage applied when requested."""
    enc = codec.encode(buf)
    flags = 0
    if deflate:
        enc = zlib.compress(enc, DEFLATE_LEVEL)
        flags |= FLAG_DEFLATE
    return FRAME.pack(codec.codec_id, flags, len(enc)) + enc


def decode_wire(codec: Codec, slice_bytes: bytes, n: int,
                rank: int) -> np.ndarray:
    """Inverse of :func:`encode_wire` for one rank's (possibly padded)
    allgather slice; validates the frame's codec id."""
    codec_id, flags, enc_len = FRAME.unpack_from(slice_bytes)
    if codec_id != codec.codec_id:
        raise CodecMismatchError(
            f"compressed allreduce: rank {rank} sent codec id {codec_id}, "
            f"this rank expects {codec.codec_id} ({codec.name!r}) — ranks "
            f"disagree on rabit_compress_allreduce / the codec= argument"
        )
    enc = slice_bytes[FRAME.size:FRAME.size + enc_len]
    if flags & FLAG_DEFLATE:
        enc = zlib.decompress(enc)
    return codec.decode(enc, n)


def _fold(op: int, acc: np.ndarray | None, part: np.ndarray) -> np.ndarray:
    from rabit_tpu.engine.base import numpy_reduce

    if acc is None:
        return np.array(part, copy=True)
    return numpy_reduce(op, acc, part)


def host_allreduce(engine, buf: np.ndarray, op: int, codec: Codec,
                   cache_key: str | None = None,
                   deflate: bool = True) -> np.ndarray:
    """The default (numpy) compressed allreduce over any engine's
    primitives; see the module docstring for the wire shape."""
    from rabit_tpu.engine.base import MAX

    n = buf.size
    t0 = time.perf_counter()
    payload = encode_wire(codec, buf, deflate)
    enc_s = time.perf_counter() - t0
    world = engine.get_world_size()
    key = lambda suffix: None if cache_key is None else cache_key + suffix
    if deflate and world > 1:
        # Deflate makes wire sizes data-dependent; agree on the padded
        # slice size first (same fixed two-op sequence on every rank).
        nmax = int(engine.allreduce(
            np.array([len(payload)], np.int64), MAX,
            cache_key=key("#wiresz"))[0])
    else:
        nmax = len(payload)
    wire = np.zeros(nmax, np.uint8)
    wire[:len(payload)] = np.frombuffer(payload, np.uint8)
    gathered = np.asarray(engine.allgather(wire, cache_key=key("#wire")))
    parts = gathered.reshape(world, nmax)
    t1 = time.perf_counter()
    out: np.ndarray | None = None
    for r in range(world):
        out = _fold(op, out, decode_wire(codec, parts[r].tobytes(), n, r))
    observe(codec.name, raw=buf.nbytes, wire=len(payload), encode_s=enc_s,
            decode_s=time.perf_counter() - t1)
    return out.astype(buf.dtype, copy=False)


def reference_allreduce(contribs: list[np.ndarray], op: int,
                        codec: str | Codec) -> np.ndarray:
    """Closed-form mirror of :func:`host_allreduce`: fold every rank's
    lossy round trip in rank order with the same numpy ops.  Self-verifying
    workloads (tests/workers/recover_worker.py) compute their expected
    values through this, so a compressed collective — first delivery OR
    post-recovery replay — must match **bitwise**."""
    c = codec if isinstance(codec, Codec) else get_codec(codec)
    out: np.ndarray | None = None
    for contrib in contribs:
        flat = np.ascontiguousarray(contrib, np.float32).reshape(-1)
        out = _fold(op, out, c.decode(c.encode(flat), flat.size))
    return out.reshape(np.shape(contribs[0]))
