"""rabit_tpu.compress — the codec subsystem (ISSUE 5 tentpole).

One registry of codecs with a single contract — deterministic,
rank-symmetric encode; documented decode(encode(x)) error bounds; a
pure-numpy reference plus an in-graph JAX path per codec — wired through
every data-plane seam:

* ``api.allreduce(..., codec=...)`` — per-call override, with a policy
  default (``rabit_compress_allreduce``) and a size floor
  (``rabit_compress_min_bytes``); the XLA engine runs the quantize /
  dequantize on-device so a fused flush stays one device collective,
  every other engine gets the numpy transport (compress.transport);
* ``fusion.LazyAllreduce`` — groups by (dtype, op, codec) so a flush is
  one collective per group and two-plane codecs ride as planes of the
  same fused buffer;
* ``store.CheckpointStore`` — a codec byte in the durable frame
  (``rabit_checkpoint_compress``; old frames stay readable);
* ``api._disk_resume`` — peer-served recovery/bootstrap blobs cross the
  wire zlib-compressed.

Policy resolution (:func:`resolve`): an explicit ``codec=`` argument is
validated loudly (wrong dtype or a BITOR op raises); the config policy is
applied quietly only where it is sound — float32 payloads, non-BITOR ops,
at least ``rabit_compress_min_bytes`` bytes — and everything else falls
through uncompressed, so turning the knob on can never corrupt an exact
path.  See doc/compression.md for the codec table and the replay-safety
contract.
"""

from __future__ import annotations

from typing import NamedTuple

import numpy as np

from rabit_tpu.compress.codecs import (  # noqa: F401 (re-exports)
    BLOCK,
    CODECS,
    DEFLATE_LEVEL,
    Codec,
    get_codec,
    get_codec_by_id,
)
from rabit_tpu.compress.transport import (  # noqa: F401 (re-exports)
    CodecMismatchError,
    host_allreduce,
    observe,
    reference_allreduce,
)

#: codec names accepted as "no compression"
_OFF = ("", "identity", "off", "none", "0")


class Policy(NamedTuple):
    """Resolved ``rabit_compress_*`` configuration (one per init)."""

    allreduce: str = ""        # default codec for api.allreduce ("" = off)
    min_bytes: int = 1024      # policy floor: smaller payloads stay exact
    wire_deflate: bool = True  # lossless deflate stage on host wire bytes
    broadcast: str = ""        # byte codec for api.broadcast payloads
    checkpoint: str = "zlib"   # byte codec for durable store frames
    fused: str = "auto"        # rabit_fused_allreduce: auto|1|0 — the fused
                               # in-graph path (auto = on for XLA engines,
                               # off elsewhere; engine/fused.py)
    fused_chunk_kib: int = 256  # ppermute hop sub-chunk size (KiB)


_POLICY = Policy()


def policy() -> Policy:
    return _POLICY


def _numeric(name: str, what: str) -> str:
    if name in _OFF:
        return ""
    c = get_codec(name)  # raises on unknown names — a typo'd policy is loud
    if c.kind != "numeric":
        raise ValueError(f"{what}: codec {name!r} is a byte codec, not a "
                         f"numeric array codec")
    return name


#: accepted rabit_fused_allreduce spellings (doc/parameters.md)
_FUSED_MODES = ("auto", "1", "0", "on", "off", "true", "false", "yes", "no",
                "")


def _fused_mode(value: str) -> str:
    mode = value.strip().lower()
    if mode not in _FUSED_MODES:
        raise ValueError(
            f"rabit_fused_allreduce={value!r}: want auto, 1/on, or 0/off")
    return mode or "auto"


def _bytes_codec(name: str, what: str) -> str:
    if name in _OFF:
        return ""
    c = get_codec(name)
    if c.kind != "bytes" and not c.lossless:
        raise ValueError(f"{what}: codec {name!r} is lossy — byte blobs "
                         f"(checkpoints, broadcasts) need lossless codecs")
    return name


def configure(config) -> Policy:
    """Resolve the ``rabit_compress_*`` / ``rabit_checkpoint_compress``
    keys into the process policy (called by ``rabit_tpu.init``)."""
    global _POLICY
    _POLICY = Policy(
        allreduce=_numeric(
            config.get("rabit_compress_allreduce", "") or "",
            "rabit_compress_allreduce"),
        min_bytes=config.get_size("rabit_compress_min_bytes", 1024),
        wire_deflate=config.get_bool("rabit_compress_wire_deflate", True),
        broadcast=_bytes_codec(
            config.get("rabit_compress_broadcast", "") or "",
            "rabit_compress_broadcast"),
        checkpoint=_bytes_codec(
            config.get("rabit_checkpoint_compress", "zlib") or "",
            "rabit_checkpoint_compress"),
        fused=_fused_mode(
            config.get("rabit_fused_allreduce", "auto") or "auto"),
        fused_chunk_kib=config.get_int("rabit_fused_chunk_kib", 256),
    )
    return _POLICY


def reset() -> None:
    """Back to built-in defaults (used by tests and finalize)."""
    global _POLICY
    _POLICY = Policy()


def resolve(codec, dtype, op: int, nbytes: int) -> Codec | None:
    """The one gate deciding whether a collective is compressed.

    ``codec`` is the per-call argument (str | Codec | None).  Explicit
    requests are validated loudly; the policy default applies quietly only
    to float32, non-BITOR payloads of at least ``min_bytes`` bytes.
    Returns the codec to use, or None for the exact path."""
    from rabit_tpu.engine.base import BITOR

    if codec is not None:
        name = codec.name if isinstance(codec, Codec) else str(codec)
        if name in _OFF:
            return None
        c = get_codec(name)
        if c.kind != "numeric":
            raise ValueError(
                f"allreduce codec {name!r} is a byte codec; numeric "
                f"payloads take identity/bf16/bf16x2/i8/i8x2")
        if np.dtype(dtype) != np.float32:
            raise TypeError(
                f"codec {name!r} compresses float32 payloads only, got "
                f"{np.dtype(dtype)} — cast first or drop the codec")
        if op == BITOR and not c.lossless:
            raise ValueError(
                f"codec {name!r} is lossy; BITOR needs exact bits")
        return None if c.lossless else c
    p = _POLICY
    if not p.allreduce:
        return None
    if (np.dtype(dtype) != np.float32 or op == BITOR
            or nbytes < p.min_bytes):
        return None
    c = get_codec(p.allreduce)
    return None if c.lossless else c
