"""Codec implementations — numeric plane codecs and the byte-blob codec.

Every numeric codec obeys one contract (doc/compression.md):

* ``encode`` is **deterministic** (same bytes for the same input, every
  time, on every rank — no timestamps, no dict-order, a pinned deflate
  level) and **rank-symmetric** (the function does not depend on rank);
* ``decode(encode(x))`` error is **bounded and documented** per codec
  (the ``error_bound`` field, asserted by tests/test_compress.py);
* every codec has a **pure-numpy reference** (``encode``/``decode``) and
  an **in-graph JAX path** (``jax_encode``/``jax_decode``) producing the
  same plane layout, so the XLA engine quantizes/dequantizes on-device
  and a fused flush stays one device collective.

Plane layouts (the byte strings ``encode`` returns, before the transport's
optional deflate stage):

* ``identity`` — the raw f32 bytes.
* ``bf16``     — one uint16 plane: the top 16 bits of each f32, rounded
  to nearest-even (error ~2^-8 relative per element).
* ``bf16x2``   — two uint16 planes hi/lo with ``lo = x - f32(hi)`` (the
  same split as ops/boost.py ``_encode_bf16``; error ~2^-16 relative).
  Same byte count as f32 — its value is near-exactness plus whatever the
  deflate stage recovers, not raw width.
* ``i8``       — one int8 plane + one f32 scale per 256-element block:
  ``a = round(clip(x) * 127)`` against the block max (error ~2^-8 of the
  block max; ~3.9x before deflate).
* ``i8x2``     — two int8 planes + f32 block scales, the exact fixed-point
  split of ops/boost.py ``_encode_i8``: ``a = round(x*64)``,
  ``b = round((x - a/64) * 8192)`` (error ~2^-14 of the block max).

Two-plane codecs concatenate their planes into ONE byte string (plane 0,
plane 1, scales), so a fused buffer's planes ride together — one wire
payload, one device array, one collective.

Non-finite inputs are saturated deterministically before quantization
(``nan -> 0``, ``±inf -> ±block max``) in both the numpy and JAX paths, so
a stray inf cannot turn into undefined int8 casts that differ by backend.
"""

from __future__ import annotations

import zlib

import numpy as np

#: Elements per scale block of the block-scaled int8 codecs (matches the
#: MXU encoder's effective granularity and parallel/collectives.py).
BLOCK = 256

#: Smallest normal f32 — the all-zero-block guard of ops/boost.py
#: ``_encode_i8`` (1/tiny stays finite, tiny-but-nonzero blocks survive).
_TINY = np.float32(1.1754944e-38)

#: Pinned deflate level for every zlib use in this package.  Level 1:
#: within ~1% of level 6 on histogram planes (measured) at ~3x the
#: throughput, and the level is part of the determinism contract — all
#: ranks must produce identical bytes for identical input.
DEFLATE_LEVEL = 1


def _blocks(n: int) -> int:
    return -(-n // BLOCK)


def _pad_blocks_np(v: np.ndarray) -> np.ndarray:
    """[n] f32 -> [nblocks, BLOCK] f32, zero padded."""
    n = v.size
    npad = _blocks(n) * BLOCK
    if npad != n:
        out = np.zeros(npad, np.float32)
        out[:n] = v
        v = out
    return v.reshape(-1, BLOCK)


def _block_scale_np(vb: np.ndarray) -> np.ndarray:
    amax = np.max(np.abs(np.where(np.isfinite(vb), vb, 0.0)), axis=1,
                  keepdims=True).astype(np.float32)
    return np.maximum(amax, _TINY)


def _saturate_np(x: np.ndarray) -> np.ndarray:
    return np.nan_to_num(x, nan=0.0, posinf=1.0, neginf=-1.0).astype(np.float32)


def _f32_to_bf16_np(arr: np.ndarray) -> np.ndarray:
    """Round-to-nearest-even truncation to the top 16 bits (numpy has no
    bfloat16; the plane is carried as uint16)."""
    u = np.ascontiguousarray(arr, np.float32).view(np.uint32)
    bias = np.uint32(0x7FFF) + ((u >> np.uint32(16)) & np.uint32(1))
    return ((u + bias) >> np.uint32(16)).astype(np.uint16)


def _bf16_to_f32_np(bits: np.ndarray) -> np.ndarray:
    return (bits.astype(np.uint32) << np.uint32(16)).view(np.float32)


class Codec:
    """Base class; also the registry row (name, wire id, error bound)."""

    #: registry name
    name: str = ""
    #: stable 1-byte wire/frame id (store frames, transport headers)
    codec_id: int = -1
    #: "numeric" (f32 arrays) or "bytes" (opaque blobs)
    kind: str = "numeric"
    #: True when decode(encode(x)) == x exactly
    lossless: bool = False
    #: documented decode(encode(x)) error bound (doc/compression.md)
    error_bound: str = ""
    #: True when encode output length depends only on the input length —
    #: equal-shape inputs on every rank then yield equal wire slices and
    #: the transport needs no size-agreement preamble
    fixed_size: bool = True

    # -- numeric path (f32 arrays) -----------------------------------------

    def encode(self, arr: np.ndarray) -> bytes:
        raise NotImplementedError

    def decode(self, blob: bytes, n: int) -> np.ndarray:
        raise NotImplementedError

    def roundtrip(self, arr: np.ndarray) -> np.ndarray:
        """decode(encode(arr)), reshaped like ``arr`` — the reference lossy
        round trip tests and closed-form self-checks fold with."""
        flat = np.ascontiguousarray(arr, np.float32).reshape(-1)
        return self.decode(self.encode(flat), flat.size).reshape(arr.shape)

    def wire_len(self, n: int) -> int:
        """Encoded byte count for an n-element f32 input (fixed-size
        codecs only)."""
        raise NotImplementedError

    # -- in-graph JAX path (None => host-only codec) -----------------------

    #: set False on codecs without a device path
    has_jax: bool = True

    def jax_encode(self, x):
        """f32 [n] jnp array -> uint8 [wire_len(n)] jnp array, same plane
        layout as ``encode`` (in-graph ops only)."""
        raise NotImplementedError

    def jax_decode(self, packed, n: int):
        """uint8 [wire_len(n)] -> f32 [n] (in-graph ops only)."""
        raise NotImplementedError

    # -- byte path (blobs) -------------------------------------------------

    def encode_bytes(self, blob: bytes) -> bytes:
        raise NotImplementedError(f"codec {self.name!r} is not a byte codec")

    def decode_bytes(self, blob: bytes) -> bytes:
        raise NotImplementedError(f"codec {self.name!r} is not a byte codec")


class IdentityCodec(Codec):
    name = "identity"
    codec_id = 0
    lossless = True
    error_bound = "exact"

    def encode(self, arr):
        return np.ascontiguousarray(arr, np.float32).tobytes()

    def decode(self, blob, n):
        return np.frombuffer(blob, np.float32, count=n).copy()

    def wire_len(self, n):
        return 4 * n

    def jax_encode(self, x):
        from jax import lax

        return lax.bitcast_convert_type(x, np.uint8).reshape(-1)

    def jax_decode(self, packed, n):
        from jax import lax

        return lax.bitcast_convert_type(packed.reshape(n, 4), np.float32)

    def encode_bytes(self, blob):
        return bytes(blob)

    def decode_bytes(self, blob):
        return bytes(blob)


class ZlibCodec(Codec):
    """Lossless byte-blob codec (checkpoint frames, recovery/bootstrap
    blobs).  Deterministic at the pinned :data:`DEFLATE_LEVEL`."""

    name = "zlib"
    codec_id = 1
    kind = "bytes"
    lossless = True
    error_bound = "exact"
    fixed_size = False
    has_jax = False

    def encode_bytes(self, blob):
        return zlib.compress(bytes(blob), DEFLATE_LEVEL)

    def decode_bytes(self, blob):
        return zlib.decompress(bytes(blob))


class Bf16Codec(Codec):
    name = "bf16"
    codec_id = 2
    error_bound = "~2^-8 relative per element"

    def encode(self, arr):
        return _f32_to_bf16_np(np.ascontiguousarray(arr, np.float32)).tobytes()

    def decode(self, blob, n):
        return _bf16_to_f32_np(np.frombuffer(blob, np.uint16, count=n))

    def wire_len(self, n):
        return 2 * n

    def jax_encode(self, x):
        import jax.numpy as jnp
        from jax import lax

        return lax.bitcast_convert_type(
            lax.bitcast_convert_type(x.astype(jnp.bfloat16), np.uint16),
            np.uint8).reshape(-1)

    def jax_decode(self, packed, n):
        import jax.numpy as jnp
        from jax import lax

        bits = lax.bitcast_convert_type(packed.reshape(n, 2), np.uint16)
        return lax.bitcast_convert_type(bits, jnp.bfloat16).astype(jnp.float32)


class Bf16x2Codec(Codec):
    """Hi/lo two-plane bf16 (ops/boost.py ``_encode_bf16``): same byte
    count as f32, near-exact; the deflate stage recovers real bytes from
    the low-entropy hi plane."""

    name = "bf16x2"
    codec_id = 3
    error_bound = "~2^-16 relative per element"

    def encode(self, arr):
        x = np.ascontiguousarray(arr, np.float32)
        hi = _f32_to_bf16_np(x)
        with np.errstate(invalid="ignore"):  # inf - inf: nan rides the lo plane
            lo = _f32_to_bf16_np(x - _bf16_to_f32_np(hi))
        return hi.tobytes() + lo.tobytes()

    def decode(self, blob, n):
        hi = np.frombuffer(blob, np.uint16, count=n)
        lo = np.frombuffer(blob, np.uint16, count=n, offset=2 * n)
        return _bf16_to_f32_np(hi) + _bf16_to_f32_np(lo)

    def wire_len(self, n):
        return 4 * n

    def jax_encode(self, x):
        import jax.numpy as jnp
        from jax import lax

        hi = x.astype(jnp.bfloat16)
        lo = (x - hi.astype(jnp.float32)).astype(jnp.bfloat16)
        as_u8 = lambda p: lax.bitcast_convert_type(
            lax.bitcast_convert_type(p, np.uint16), np.uint8).reshape(-1)
        return jnp.concatenate([as_u8(hi), as_u8(lo)])

    def jax_decode(self, packed, n):
        import jax.numpy as jnp
        from jax import lax

        def plane(off):
            bits = lax.bitcast_convert_type(
                lax.dynamic_slice_in_dim(packed, off, 2 * n).reshape(n, 2),
                np.uint16)
            return lax.bitcast_convert_type(bits, jnp.bfloat16).astype(
                jnp.float32)

        return plane(0) + plane(2 * n)


class _BlockI8(Codec):
    """Shared machinery of the block-scaled int8 codecs: planes are laid
    out plane-major (plane 0 bytes, [plane 1 bytes,] f32 scales)."""

    planes: int = 1

    def wire_len(self, n):
        nb = _blocks(n)
        return self.planes * nb * BLOCK + 4 * nb

    def _encode_planes_np(self, x: np.ndarray) -> list[np.ndarray]:
        raise NotImplementedError

    def _decode_planes_np(self, planes: list[np.ndarray],
                          scale: np.ndarray) -> np.ndarray:
        raise NotImplementedError

    def encode(self, arr):
        vb = _pad_blocks_np(np.ascontiguousarray(arr, np.float32).reshape(-1))
        scale = _block_scale_np(vb)
        x = _saturate_np(vb * (np.float32(1.0) / scale))
        planes = self._encode_planes_np(x)
        return (b"".join(p.astype(np.int8).tobytes() for p in planes)
                + scale.astype(np.float32).tobytes())

    def decode(self, blob, n):
        nb = _blocks(n)
        npad = nb * BLOCK
        planes = [
            np.frombuffer(blob, np.int8, count=npad, offset=i * npad)
            .reshape(nb, BLOCK).astype(np.float32)
            for i in range(self.planes)
        ]
        scale = np.frombuffer(blob, np.float32, count=nb,
                              offset=self.planes * npad).reshape(nb, 1)
        return self._decode_planes_np(planes, scale).reshape(-1)[:n]

    # JAX mirrors of the numpy ops (bit-parity is asserted by tests)

    def _jax_pad_blocks(self, x):
        import jax.numpy as jnp

        n = x.shape[0]
        npad = _blocks(n) * BLOCK
        if npad != n:
            x = jnp.pad(x, (0, npad - n))
        return x.reshape(-1, BLOCK)

    def jax_encode(self, x):
        import jax.numpy as jnp
        from jax import lax

        vb = self._jax_pad_blocks(x.astype(jnp.float32))
        amax = jnp.max(jnp.abs(jnp.where(jnp.isfinite(vb), vb, 0.0)), axis=1,
                       keepdims=True)
        scale = jnp.maximum(amax, _TINY)
        xb = jnp.nan_to_num(vb * (np.float32(1.0) / scale), nan=0.0,
                            posinf=1.0, neginf=-1.0)
        planes = self._encode_planes_jax(xb)
        parts = [lax.bitcast_convert_type(p.astype(jnp.int8), np.uint8)
                 .reshape(-1) for p in planes]
        parts.append(lax.bitcast_convert_type(
            scale.reshape(-1).astype(jnp.float32), np.uint8).reshape(-1))
        return jnp.concatenate(parts)

    def jax_decode(self, packed, n):
        import jax.numpy as jnp
        from jax import lax

        nb = _blocks(n)
        npad = nb * BLOCK
        planes = [
            lax.bitcast_convert_type(
                lax.dynamic_slice_in_dim(packed, i * npad, npad),
                np.int8).reshape(nb, BLOCK).astype(jnp.float32)
            for i in range(self.planes)
        ]
        scale = lax.bitcast_convert_type(
            lax.dynamic_slice_in_dim(packed, self.planes * npad, 4 * nb)
            .reshape(nb, 4), np.float32).reshape(nb, 1)
        return self._decode_planes_np(planes, scale).reshape(-1)[:n]

    def _encode_planes_jax(self, x):
        raise NotImplementedError


class I8Codec(_BlockI8):
    name = "i8"
    codec_id = 4
    planes = 1
    error_bound = "~2^-8 of the block max (256-element blocks)"

    def _encode_planes_np(self, x):
        return [np.clip(np.round(x * np.float32(127.0)), -127, 127)]

    def _encode_planes_jax(self, x):
        import jax.numpy as jnp

        return [jnp.clip(jnp.round(x * np.float32(127.0)), -127, 127)]

    def _decode_planes_np(self, planes, scale):
        return planes[0] * (scale * np.float32(1.0 / 127.0))


class I8x2Codec(_BlockI8):
    """The exact two-plane fixed-point split of ops/boost.py
    ``_encode_i8``: ``a = round(x*64)`` (|a| <= 64), residual plane
    ``b = round((x - a/64) * 8192)`` (|b| <= 65) — 14-bit fixed point,
    error ~2^-14 of the block max."""

    name = "i8x2"
    codec_id = 5
    planes = 2
    error_bound = "~2^-14 of the block max (256-element blocks)"

    def _encode_planes_np(self, x):
        a = np.round(x * np.float32(64.0))
        b = np.round((x - a * np.float32(1.0 / 64.0)) * np.float32(8192.0))
        return [a, b]

    def _encode_planes_jax(self, x):
        import jax.numpy as jnp

        a = jnp.round(x * np.float32(64.0))
        b = jnp.round((x - a * np.float32(1.0 / 64.0)) * np.float32(8192.0))
        return [a, b]

    def _decode_planes_np(self, planes, scale):
        hi, lo = planes
        return (hi * np.float32(1.0 / 64.0)
                + lo * np.float32(1.0 / 8192.0)) * scale


#: The registry — name -> singleton codec instance.
CODECS: dict[str, Codec] = {
    c.name: c
    for c in (IdentityCodec(), ZlibCodec(), Bf16Codec(), Bf16x2Codec(),
              I8Codec(), I8x2Codec())
}

_BY_ID: dict[int, Codec] = {c.codec_id: c for c in CODECS.values()}


def get_codec(name: str) -> Codec:
    try:
        return CODECS[name]
    except KeyError:
        raise ValueError(
            f"unknown codec {name!r}; registered: {sorted(CODECS)}"
        ) from None


def get_codec_by_id(codec_id: int) -> Codec:
    try:
        return _BY_ID[codec_id]
    except KeyError:
        raise ValueError(
            f"unknown codec id {codec_id}; registered: "
            f"{sorted((c.codec_id, c.name) for c in CODECS.values())}"
        ) from None
