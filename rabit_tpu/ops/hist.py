"""Gradient-histogram kernels — the hot op of the flagship GBDT workload.

``hist[node, f, b] = sum_i [node_i == node][xb_i[f] == b] * (g_i, h_i)``

Implementations of the same contract:

* ``node_histograms_scatter`` — ``segment_sum`` (XLA scatter-add).  Exact
  f32, the portable reference; scatter serializes on TPU so it is the slow
  path there (and what the original bench measured at ~350-560 ms/level for
  1M x 28 x 256).
* ``node_histograms_onehot`` — one-hot matmul, pure XLA: a chunked
  ``lax.scan`` whose body contracts a (rows x 2*nodes) gradient matrix
  against a (rows x F*B) bin-indicator matrix.  Runs the FLOPs on the MXU
  on TPU and vectorizes fine on CPU.
* ``node_histograms_pallas`` — the same contraction as a Pallas TPU kernel:
  the indicator matrices are built in VMEM and never touch HBM, and the f32
  gradients are split hi/lo into two bfloat16 matmuls so the MXU runs at
  bf16 rate with ~f32 accuracy (error 2^-16-relative, vs 2^-8 for naive
  bf16).  ``mxu_i8=True`` switches the contraction to a two-plane int8
  fixed-point split (s8 x s8 -> s32, 2x the bf16 issue rate on
  v5e-class MXUs, error ~2^-14 of the block max: 14-bit fixed point,
  2^-13 quantization step, 2^-14 round-off — see ops/boost.py
  ``_encode_i8``).

``node_histograms`` dispatches: Pallas on TPU, scatter elsewhere (tests run
on the virtual CPU mesh and want exact-f32 determinism).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl

_DN = (((0,), (0,)), ((), ()))  # contract dim 0 against dim 0, no batch


def _round_up(x: int, m: int) -> int:
    return -(-x // m) * m


# -- scatter (reference) ----------------------------------------------------


def node_histograms_scatter(xb, g, h, node, n_nodes: int, n_bins: int):
    """Exact-f32 segment_sum implementation; [n_nodes, F, B, 2]."""
    n, F = xb.shape
    seg = (node[:, None] * F + jnp.arange(F)[None, :]) * n_bins + xb  # [n, F]
    gh = jnp.stack(
        [
            jnp.broadcast_to(g[:, None], (n, F)),
            jnp.broadcast_to(h[:, None], (n, F)),
        ],
        axis=-1,
    )  # [n, F, 2]
    hist = jax.ops.segment_sum(
        gh.reshape(-1, 2), seg.reshape(-1), num_segments=n_nodes * F * n_bins
    )
    return hist.reshape(n_nodes, F, n_bins, 2)


# -- one-hot matmul (pure XLA) ---------------------------------------------


def node_histograms_onehot(xb, g, h, node, n_nodes: int, n_bins: int,
                           block_rows: int = 8192):
    """One-hot-matmul implementation; [n_nodes, F, B, 2].

    Per row chunk: L[r, m] puts g (m < n_nodes) / h (m >= n_nodes) in the
    column of the row's node; Bo[r, f*B+b] indicates bin membership; the
    chunk's histogram is L^T @ Bo, accumulated in f32 across chunks.
    """
    n, F = xb.shape
    R = min(block_rows, _round_up(n, 128))
    n_pad = _round_up(n, R)
    if n_pad != n:
        pad = n_pad - n
        xb = jnp.pad(xb, ((0, pad), (0, 0)))
        g = jnp.pad(g, (0, pad))
        h = jnp.pad(h, (0, pad))  # zero g/h => padded rows contribute nothing
        node = jnp.pad(node, (0, pad))
    nb = n_pad // R

    def body(acc, sl):
        xbc, gc, hc, nodec = sl
        N = jax.nn.one_hot(nodec, n_nodes, dtype=jnp.float32)      # [R, nodes]
        L = jnp.concatenate([N * gc[:, None], N * hc[:, None]], 1)  # [R, 2*nodes]
        Bo = jax.nn.one_hot(xbc, n_bins, dtype=jnp.float32)         # [R, F, B]
        acc += lax.dot_general(
            L, Bo.reshape(R, F * n_bins), _DN,
            precision=lax.Precision.HIGHEST,
            preferred_element_type=jnp.float32,
        )
        return acc, None

    sl = (
        xb.reshape(nb, R, F),
        g.reshape(nb, R),
        h.reshape(nb, R),
        node.reshape(nb, R),
    )
    acc0 = jnp.zeros((2 * n_nodes, F * n_bins), jnp.float32)
    acc, _ = lax.scan(body, acc0, sl)
    acc = acc.reshape(2, n_nodes, F, n_bins)
    return jnp.stack([acc[0], acc[1]], axis=-1)


# -- Pallas TPU kernel ------------------------------------------------------


def _hist_kernel(xb_ref, node_ref, g_ref, h_ref, out_ref, *,
                 n_nodes: int, n_bins: int, m_pad: int, n_feat: int, fc: int,
                 i8: bool):
    from rabit_tpu.ops import boost

    @pl.when(pl.program_id(0) == 0)
    def _init():
        out_ref[:] = jnp.zeros_like(out_ref)

    L = boost._gradient_matrix(node_ref[0], g_ref[0], h_ref[0],
                               n_nodes=n_nodes, m_pad=m_pad)
    boost._accum(xb_ref[0], L, out_ref,
                 n_bins=n_bins, n_feat=n_feat, fc=fc, i8=i8)


@functools.partial(
    jax.jit,
    static_argnames=("n_nodes", "n_bins", "block_rows", "interpret", "mxu_i8"),
)
def node_histograms_pallas(xb, g, h, node, n_nodes: int, n_bins: int,
                           block_rows: int = 1024, interpret: bool = False,
                           mxu_i8: bool = False):
    """Pallas implementation; [n_nodes, F, B, 2].  Grid = row blocks: the
    whole (2*nodes, F*B) histogram stays resident in VMEM (1.8 MB at
    depth 6 / 28 features / 256 bins) while row blocks stream through; the
    gradient matrix L is built once per block and contracted against the
    bin-indicator matrices on the MXU (shared kernel helpers in ops.boost)."""
    from rabit_tpu.ops import boost

    n, F = xb.shape
    R = block_rows
    n_pad = _round_up(n, R)
    if n_pad != n:
        pad = n_pad - n
        xb = jnp.pad(xb, ((0, pad), (0, 0)))
        g = jnp.pad(g, (0, pad))
        h = jnp.pad(h, (0, pad))
        node = jnp.pad(node, (0, pad))
    m_pad = _round_up(2 * n_nodes, 8)
    be = boost._bins_eff(n_bins)
    fc = boost._pick_fc(F, n_bins)
    nb = n_pad // R

    out = pl.pallas_call(
        functools.partial(
            _hist_kernel, n_nodes=n_nodes, n_bins=n_bins, m_pad=m_pad,
            n_feat=F, fc=fc, i8=mxu_i8,
        ),
        grid=(nb,),
        in_specs=[
            pl.BlockSpec((1, R, F), lambda i: (i, 0, 0)),
            pl.BlockSpec((1, R, 1), lambda i: (i, 0, 0)),
            pl.BlockSpec((1, R, 1), lambda i: (i, 0, 0)),
            pl.BlockSpec((1, R, 1), lambda i: (i, 0, 0)),
        ],
        out_specs=pl.BlockSpec((m_pad, F * be), lambda i: (0, 0)),
        out_shape=jax.ShapeDtypeStruct((m_pad, F * be), jnp.float32),
        interpret=interpret,
    )(
        xb.reshape(nb, R, F),
        node.reshape(nb, R, 1),
        g.reshape(nb, R, 1),
        h.reshape(nb, R, 1),
    )

    out = out.reshape(m_pad, F, be)[..., :n_bins]
    return jnp.stack([out[:n_nodes], out[n_nodes : 2 * n_nodes]], axis=-1)


# -- segment-sum-as-matmul (for small segment counts, e.g. leaf fit) -------


def segment_sum_matmul(values, seg, num_segments: int, block_rows: int = 8192):
    """``segment_sum(values, seg)`` as chunked one-hot matmuls; values
    [n, C] f32, seg [n] int32 -> [num_segments, C].  Beats scatter on TPU
    when num_segments is small (leaf-weight fit: 2**depth segments)."""
    n, C = values.shape
    R = min(block_rows, _round_up(n, 128))
    n_pad = _round_up(n, R)
    if n_pad != n:
        pad = n_pad - n
        values = jnp.pad(values, ((0, pad), (0, 0)))
        seg = jnp.pad(seg, (0, pad), constant_values=0)
        # padded rows land in segment 0 with zero value
    nb = n_pad // R

    def body(acc, sl):
        vc, sc = sl
        N = jax.nn.one_hot(sc, num_segments, dtype=jnp.float32)  # [R, S]
        acc += lax.dot_general(
            N, vc, _DN,
            precision=lax.Precision.HIGHEST,
            preferred_element_type=jnp.float32,
        )
        return acc, None

    acc0 = jnp.zeros((num_segments, C), jnp.float32)
    acc, _ = lax.scan(body, acc0, (values.reshape(nb, R, C), seg.reshape(nb, R)))
    return acc


# -- dispatchers ------------------------------------------------------------


def node_histograms(xb, g, h, node, n_nodes: int, n_bins: int,
                    impl: str | None = None, mxu_i8: bool = False):
    """Backend-appropriate histogram build; [n_nodes, F, B, 2].  With
    ``mxu_i8`` the TPU default becomes the int8-rate Pallas kernel (an
    explicit ``impl`` always wins)."""
    if impl is None:
        if jax.default_backend() == "tpu":
            impl = "pallas_i8" if mxu_i8 else "pallas"
        else:
            impl = "scatter"
    if impl == "pallas":
        return node_histograms_pallas(xb, g, h, node, n_nodes, n_bins)
    if impl == "pallas_i8":
        return node_histograms_pallas(xb, g, h, node, n_nodes, n_bins,
                                      mxu_i8=True)
    if impl == "onehot":
        return node_histograms_onehot(xb, g, h, node, n_nodes, n_bins)
    if impl == "scatter":
        return node_histograms_scatter(xb, g, h, node, n_nodes, n_bins)
    raise ValueError(f"unknown hist impl {impl!r}")


def segment_sum(values, seg, num_segments: int, impl: str | None = None):
    """Backend-appropriate segment_sum for small segment counts (leaf fit):
    one-hot matmul on TPU (scatter-add serializes there), XLA scatter
    elsewhere (exact f32)."""
    if impl is None:
        impl = "matmul" if jax.default_backend() == "tpu" else "scatter"
    if impl == "matmul":
        return segment_sum_matmul(values, seg, num_segments)
    if impl == "scatter":
        return jax.ops.segment_sum(values, seg, num_segments=num_segments)
    raise ValueError(f"unknown segment_sum impl {impl!r}")
