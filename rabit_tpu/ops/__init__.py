"""TPU-native hot ops (the compute the reference never had to do itself —
rabit's only numeric kernel is the CPU reducer callback at
/root/reference/src/allreduce_base.cc:566-605; on TPU the framework owns
the workload kernels too, so they live here as first-class ops).
"""

from rabit_tpu.ops.hist import (  # noqa: F401
    node_histograms,
    node_histograms_onehot,
    node_histograms_pallas,
    node_histograms_scatter,
    segment_sum,
    segment_sum_matmul,
)
