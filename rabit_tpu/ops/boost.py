"""Fused Pallas kernels for one GBDT boosting round on TPU.

The hook-based ``models.gbdt.train_round`` makes one pass over the rows per
level for histograms plus separate passes for routing, leaf fit, and margin
update — each a round-trip through HBM.  These kernels fuse a level's work
into a single streaming pass per row block:

* ``hist_level0``   — histogram at the root (no routing needed).
* ``hist_level``    — route rows one level down through the parent split
  table (split lookup + feature select + compare, all in VMEM) and
  histogram at the new nodes, emitting the updated node ids as a second
  output.
* ``leaf_fit``      — route to the leaves and reduce per-leaf (g, h) mass
  with the same MXU contraction, emitting final leaf assignments.

The histogram itself is the one-hot MXU contraction of ``ops.hist``: the
row block's gradient matrix L (one g column + one h column per node) is
contracted against per-feature bin indicators built in VMEM; f32 gradients
are split hi/lo into two bfloat16 matmuls (error ~2^-16-relative).

All wrappers take pre-blocked arrays (nb, R, ...) so padding/reshaping
happens once per fit, not once per level.  ``interpret=True`` runs the
kernels in the Pallas interpreter, which is how the CPU test suite checks
them against the reference ``train_round`` (tests/test_gbdt.py).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl

_DN = (((0,), (0,)), ((), ()))  # contract dim 0 vs dim 0


def _round_up(x: int, m: int) -> int:
    return -(-x // m) * m


def _check_r_split(R: int, r_split: int) -> None:
    if r_split < 1 or R % r_split:
        raise ValueError(
            f"r_split={r_split} must be >= 1 and divide the row block {R}")


def _bins_eff(n_bins: int) -> int:
    """Mask width per feature: bins padded to full 128-lane registers (the
    pad columns never match a bin id, so they stay zero)."""
    return _round_up(n_bins, 128)


def _pick_fc(n_feat: int, n_bins: int) -> int:
    """Features per matmul group (N = fc * bins_eff ~ 1792 lanes)."""
    return min(n_feat, max(1, 1792 // _bins_eff(n_bins)))


def _encode_bf16(L):
    """Hi/lo-bf16 split of the f32 gradient matrix (~2^-16-relative error).

    The two halves share ONE matmul, stacked along M: the MXU pads M to a
    full 128-row tile anyway, and m_pad <= 64 for depth <= 6, so two
    separate matmuls each waste >= half the tile — packing them halves the
    level's MXU passes (measured ~1.4x whole-round).  The result splits
    back and sums in f32, bitwise identical to the two-matmul form."""
    lhi = L.astype(jnp.bfloat16)
    llo = (L - lhi.astype(jnp.float32)).astype(jnp.bfloat16)
    l2 = jnp.concatenate([lhi, llo], axis=1)
    m = L.shape[1]
    decode = lambda acc2: acc2[:m] + acc2[m:]
    return l2, jnp.bfloat16, jnp.float32, decode


def _encode_i8(L):
    """Two-plane int8 fixed-point split, running the MXU at int8 rate (2x
    the bf16 issue rate on v5e-class chips): L is split against the block
    max into two int8 planes (14-bit fixed point, error ~2^-14 of the
    block max — a little tail precision traded for double MXU
    throughput), stacked along M into ONE s8 x s8 -> s32 matmul.

    The scale needs no power-of-two rounding: any scale >= max|L| keeps
    |x| <= 1 (+1 ulp from the reciprocal multiply, far inside the int8
    headroom: |a| <= 64, |b| <= 65 vs the 127 limit), and the ~ulp
    rounding of x and of the f32 decode is negligible against the 2^-14
    quantization step.  (An exact exponent-field split via scalar bitcast
    does NOT lower through Mosaic — tpu.bitcast wants vectors.)"""
    m = L.shape[1]
    amax = jnp.max(jnp.abs(L))
    # Floor at the smallest NORMAL f32: keeps the all-zero-block guard
    # (1/tiny is finite) without zeroing tiny-but-nonzero blocks, and
    # 1/scale can never flush to a subnormal zero on hardware.
    scale = jnp.maximum(amax, jnp.float32(1.1754944e-38))
    x = L * (1.0 / scale)
    a = jnp.round(x * 64.0)                      # |a| <= 64
    b = jnp.round((x - a * (1.0 / 64.0)) * 8192.0)  # residual <~ 2^-7 => |b| <= 65
    l2 = jnp.concatenate([a, b], axis=1).astype(jnp.int8)

    def decode(acc2):
        # |acc| <= R * 64 = 2^16 — exact in int32 and in the f32 convert.
        hi = acc2[:m].astype(jnp.float32)
        lo = acc2[m:].astype(jnp.float32)
        return (hi * (1.0 / 64.0) + lo * (1.0 / 8192.0)) * scale

    return l2, jnp.int8, jnp.int32, decode


def _accum(xb_blk, L, out_ref, *, n_bins: int, n_feat: int, fc: int, i8: bool,
           r_split: int = 1):
    """out_ref[m, f*Beff+b] += sum_r L[r, m] * [xb_blk[r, f] == b], via the
    MXU: the encoded gradient planes are contracted against per-feature-
    group bin-indicator matrices built in VMEM.

    ``r_split > 1`` splits the row block into that many independent
    sub-contractions per feature group (raw accumulators summed, one
    decode; bitwise identical to the unsplit path for i8, f32-sum
    reassociation only for bf16) — an overlap experiment: sub-block i's
    matmul (MXU) has no data dependency on sub-block i+1's indicator
    build (VPU), giving Mosaic's scheduler explicit room to run them
    concurrently.  Round-5 on-chip roofline: the ~3.7 ms/level indicator
    rebuild is co-dominant with the int8-rate matmul, so full overlap is
    worth up to ~25% of the round (RESULTS.md §1); measured by the
    ablation's rsplit rows."""
    be = _bins_eff(n_bins)
    l2, onehot_dtype, acc_dtype, decode = (_encode_i8 if i8 else _encode_bf16)(L)
    r = xb_blk.shape[0]
    rs = r // r_split
    # The indicator compare runs at i32 lane width BY TARGET CONSTRAINT,
    # not choice: narrow codes (int8 4/lane, bf16 2/lane) would cut the
    # co-dominant ~3.7 ms/level VPU rebuild 2-4x, but the chip's Mosaic
    # rejects sub-32-bit vector compares — "Target does not support this
    # comparison" on vector<...xi8> cmpi AND vector<...xbf16> cmpf
    # (RESULTS/narrow_compare_rejection.txt; the local jax.export gate
    # accepts both, so only on-chip compiles catch this).
    b_iota = lax.broadcasted_iota(jnp.int32, (rs, be), 1)
    for gi in range(0, n_feat, fc):
        k = min(fc, n_feat - gi)
        # Sum the RAW accumulators across sub-blocks and decode once:
        # decode is linear, so this is bitwise identical to the unsplit
        # path for i8 (int32 adds commute exactly) and costs one decode
        # per group instead of r_split.
        acc2 = None
        for s in range(r_split):
            lo = s * rs
            onehot = jnp.concatenate(
                [(xb_blk[lo : lo + rs, f : f + 1] == b_iota)
                 for f in range(gi, gi + k)],
                axis=1,
            ).astype(onehot_dtype)
            part = lax.dot_general(l2[lo : lo + rs], onehot, _DN,
                                   preferred_element_type=acc_dtype)
            acc2 = part if acc2 is None else acc2 + part
        out_ref[:, gi * be : (gi + k) * be] += decode(acc2)


def _gradient_matrix(node, g, h, *, n_nodes: int, m_pad: int):
    """L[r, m]: g_r at column node_r, h_r at column n_nodes+node_r."""
    r = node.shape[0]
    m_iota = lax.broadcasted_iota(jnp.int32, (r, m_pad), 1)
    is_g = m_iota < n_nodes
    idx = jnp.where(is_g, m_iota, m_iota - n_nodes)
    sel = (node == idx) & (m_iota < 2 * n_nodes)
    val = jnp.where(is_g, g, h)  # (R,1) -> (R, m_pad)
    return jnp.where(sel, val, 0.0)


def _route(xb_blk, node, feat_row, thr_row, *, p_pad: int, n_feat: int):
    """node' = 2*node + [x[feat[node]] > thr[node]] — split-table lookup and
    feature select via lane-masked reductions (no gathers)."""
    r = node.shape[0]
    p_iota = lax.broadcasted_iota(jnp.int32, (r, p_pad), 1)
    pm = node == p_iota  # (R, P) one-hot over parent nodes
    fsel = jnp.sum(jnp.where(pm, feat_row, 0), axis=1, keepdims=True)
    tsel = jnp.sum(jnp.where(pm, thr_row, 0), axis=1, keepdims=True)
    f_iota = lax.broadcasted_iota(jnp.int32, (r, n_feat), 1)
    xv = jnp.sum(jnp.where(f_iota == fsel, xb_blk, 0), axis=1, keepdims=True)
    return node * 2 + (xv > tsel).astype(jnp.int32)


# -- level 0: histogram at the root ----------------------------------------


def _level0_kernel(xb_ref, g_ref, h_ref, out_ref, *, n_bins, n_feat, fc, i8,
                   r_split=1):
    @pl.when(pl.program_id(0) == 0)
    def _init():
        out_ref[:] = jnp.zeros_like(out_ref)

    r = g_ref.shape[1]
    node = jnp.zeros((r, 1), jnp.int32)
    L = _gradient_matrix(node, g_ref[0], h_ref[0], n_nodes=1, m_pad=8)
    _accum(xb_ref[0], L, out_ref, n_bins=n_bins, n_feat=n_feat, fc=fc, i8=i8,
           r_split=r_split)


# -- level d >= 1: route + histogram ---------------------------------------


def _level_kernel(xb_ref, node_ref, g_ref, h_ref, feat_ref, thr_ref,
                  out_ref, node_out_ref, *,
                  n_nodes, n_bins, n_feat, m_pad, p_pad, fc, i8, r_split=1):
    @pl.when(pl.program_id(0) == 0)
    def _init():
        out_ref[:] = jnp.zeros_like(out_ref)

    node = _route(xb_ref[0], node_ref[0], feat_ref[0:1], thr_ref[0:1],
                  p_pad=p_pad, n_feat=n_feat)
    node_out_ref[0] = node
    L = _gradient_matrix(node, g_ref[0], h_ref[0], n_nodes=n_nodes, m_pad=m_pad)
    _accum(xb_ref[0], L, out_ref, n_bins=n_bins, n_feat=n_feat, fc=fc, i8=i8,
           r_split=r_split)


# -- routing-only pass (leaf assignment without histogramming) -------------


def _route_kernel(xb_ref, node_ref, feat_ref, thr_ref, node_out_ref, *,
                  p_pad, n_feat):
    node_out_ref[0] = _route(xb_ref[0], node_ref[0], feat_ref[0:1],
                             thr_ref[0:1], p_pad=p_pad, n_feat=n_feat)


# -- final pass: route to leaves + margin update in one kernel -------------


def _route_margin_kernel(xb_ref, node_ref, margin_ref, feat_ref, thr_ref,
                         leaf_ref, margin_out_ref, node_out_ref, *,
                         p_pad, l_pad, n_feat):
    node = _route(xb_ref[0], node_ref[0], feat_ref[0:1], thr_ref[0:1],
                  p_pad=p_pad, n_feat=n_feat)
    node_out_ref[0] = node
    # margin += leaf[node] without a gather: the leaf table is tiny (64
    # entries at depth 6), so the same lane-masked reduction as _route's
    # split lookup replaces XLA's slow 1M-row gather from a small table.
    r = node.shape[0]
    l_iota = lax.broadcasted_iota(jnp.int32, (r, l_pad), 1)
    lv = jnp.sum(jnp.where(node == l_iota, leaf_ref[0:1], 0.0), axis=1,
                 keepdims=True)
    margin_out_ref[0] = margin_ref[0] + lv


@functools.partial(jax.jit, static_argnames=("depth", "interpret"))
def route_margin_level(xb3, node3, margin3, feat, thr, leaf, *, depth: int,
                       interpret: bool = False):
    """Final fused pass: route rows through the level-(depth-1) split tables
    to their leaves AND apply the margin update ``margin += leaf[node]`` in
    the same streaming pass.  Returns (margin3', leaf_node3).  Replaces
    route_level + a host-level gather: XLA lowers a 1M-row gather from a
    64-entry table poorly on TPU, while the in-kernel lane-masked sum is a
    few VPU ops per row."""
    nb, R, F = xb3.shape
    n_prev = 2 ** (depth - 1)
    n_leaves = 2 ** depth
    p_pad = _round_up(n_prev, 128)
    l_pad = _round_up(n_leaves, 128)
    featp = jnp.zeros((8, p_pad), jnp.int32).at[0, :n_prev].set(feat)
    thrp = jnp.zeros((8, p_pad), jnp.int32).at[0, :n_prev].set(thr)
    leafp = jnp.zeros((8, l_pad), jnp.float32).at[0, :n_leaves].set(leaf)
    return pl.pallas_call(
        functools.partial(_route_margin_kernel, p_pad=p_pad, l_pad=l_pad,
                          n_feat=F),
        grid=(nb,),
        in_specs=[
            _blk(R, F), _blk(R, 1), _blk(R, 1),
            pl.BlockSpec((8, p_pad), lambda i: (0, 0)),
            pl.BlockSpec((8, p_pad), lambda i: (0, 0)),
            pl.BlockSpec((8, l_pad), lambda i: (0, 0)),
        ],
        out_specs=[_blk(R, 1), _blk(R, 1)],
        out_shape=[
            jax.ShapeDtypeStruct((nb, R, 1), jnp.float32),
            jax.ShapeDtypeStruct((nb, R, 1), jnp.int32),
        ],
        interpret=interpret,
    )(xb3, node3, margin3, featp, thrp, leafp)


@functools.partial(jax.jit, static_argnames=("depth", "interpret"))
def route_level(xb3, node3, feat, thr, *, depth: int, interpret: bool = False):
    """Route rows one level down through the level-(depth-1) split tables —
    no histogram: the leaf (g, h) masses are read off the final level's
    histogram instead (models.gbdt.split_child_masses), so the last row
    pass only needs the leaf assignment for the margin update."""
    nb, R, F = xb3.shape
    n_prev = 2 ** (depth - 1)
    p_pad = _round_up(n_prev, 128)
    featp = jnp.zeros((8, p_pad), jnp.int32).at[0, :n_prev].set(feat)
    thrp = jnp.zeros((8, p_pad), jnp.int32).at[0, :n_prev].set(thr)
    return pl.pallas_call(
        functools.partial(_route_kernel, p_pad=p_pad, n_feat=F),
        grid=(nb,),
        in_specs=[
            _blk(R, F), _blk(R, 1),
            pl.BlockSpec((8, p_pad), lambda i: (0, 0)),
            pl.BlockSpec((8, p_pad), lambda i: (0, 0)),
        ],
        out_specs=_blk(R, 1),
        out_shape=jax.ShapeDtypeStruct((nb, R, 1), jnp.int32),
        interpret=interpret,
    )(xb3, node3, featp, thrp)


# -- leaf fit: route + per-leaf (g, h) mass --------------------------------


def _leaf_kernel(xb_ref, node_ref, g_ref, h_ref, feat_ref, thr_ref,
                 out_ref, node_out_ref, *, n_leaves, n_feat, m_pad, p_pad):
    @pl.when(pl.program_id(0) == 0)
    def _init():
        out_ref[:] = jnp.zeros_like(out_ref)

    node = _route(xb_ref[0], node_ref[0], feat_ref[0:1], thr_ref[0:1],
                  p_pad=p_pad, n_feat=n_feat)
    node_out_ref[0] = node
    L = _gradient_matrix(node, g_ref[0], h_ref[0], n_nodes=n_leaves, m_pad=m_pad)
    lhi = L.astype(jnp.bfloat16)
    llo = (L - lhi.astype(jnp.float32)).astype(jnp.bfloat16)
    ones = jnp.ones((L.shape[0], 128), jnp.bfloat16)
    acc = lax.dot_general(lhi, ones, _DN, preferred_element_type=jnp.float32)
    acc += lax.dot_general(llo, ones, _DN, preferred_element_type=jnp.float32)
    out_ref[:] += acc


# -- host wrappers (pre-blocked (nb, R, .) arrays) -------------------------


_blk = lambda R, k: pl.BlockSpec((1, R, k), lambda i: (i, 0, 0))


@functools.partial(
    jax.jit, static_argnames=("n_bins", "interpret", "mxu_i8", "r_split")
)
def hist_level0(xb3, g3, h3, *, n_bins: int, interpret: bool = False,
                mxu_i8: bool = False, r_split: int = 1):
    """Root histogram; [1, F, B, 2].  ``r_split``: see _accum."""
    nb, R, F = xb3.shape
    _check_r_split(R, r_split)
    be = _bins_eff(n_bins)
    fc = _pick_fc(F, n_bins)
    out = pl.pallas_call(
        functools.partial(_level0_kernel, n_bins=n_bins, n_feat=F, fc=fc,
                          i8=mxu_i8, r_split=r_split),
        grid=(nb,),
        in_specs=[_blk(R, F), _blk(R, 1), _blk(R, 1)],
        out_specs=pl.BlockSpec((8, F * be), lambda i: (0, 0)),
        out_shape=jax.ShapeDtypeStruct((8, F * be), jnp.float32),
        interpret=interpret,
    )(xb3, g3, h3)
    out = out.reshape(8, F, be)[..., :n_bins]
    return jnp.stack([out[0:1], out[1:2]], axis=-1)


@functools.partial(
    jax.jit,
    static_argnames=("depth", "n_bins", "interpret", "mxu_i8", "r_split"),
)
def hist_level(xb3, node3, g3, h3, feat, thr, *, depth: int, n_bins: int,
               interpret: bool = False, mxu_i8: bool = False,
               r_split: int = 1):
    """Route one level down and histogram; returns
    ([2**depth, F, B, 2], node3').  ``feat``/``thr`` are the level-(depth-1)
    split tables, shape [2**(depth-1)].  ``r_split``: see _accum."""
    nb, R, F = xb3.shape
    _check_r_split(R, r_split)
    be = _bins_eff(n_bins)
    n_nodes = 2 ** depth
    n_prev = 2 ** (depth - 1)
    m_pad = _round_up(2 * n_nodes, 8)
    p_pad = _round_up(n_prev, 128)
    fc = _pick_fc(F, n_bins)
    featp = jnp.zeros((8, p_pad), jnp.int32).at[0, :n_prev].set(feat)
    thrp = jnp.zeros((8, p_pad), jnp.int32).at[0, :n_prev].set(thr)
    out, node_out = pl.pallas_call(
        functools.partial(
            _level_kernel, n_nodes=n_nodes, n_bins=n_bins, n_feat=F,
            m_pad=m_pad, p_pad=p_pad, fc=fc, i8=mxu_i8, r_split=r_split,
        ),
        grid=(nb,),
        in_specs=[
            _blk(R, F), _blk(R, 1), _blk(R, 1), _blk(R, 1),
            pl.BlockSpec((8, p_pad), lambda i: (0, 0)),
            pl.BlockSpec((8, p_pad), lambda i: (0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((m_pad, F * be), lambda i: (0, 0)),
            _blk(R, 1),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((m_pad, F * be), jnp.float32),
            jax.ShapeDtypeStruct((nb, R, 1), jnp.int32),
        ],
        interpret=interpret,
    )(xb3, node3, g3, h3, featp, thrp)
    out = out.reshape(m_pad, F, be)[..., :n_bins]
    hist = jnp.stack([out[:n_nodes], out[n_nodes : 2 * n_nodes]], axis=-1)
    return hist, node_out


@functools.partial(jax.jit, static_argnames=("depth", "interpret"))
def leaf_fit(xb3, node3, g3, h3, feat, thr, *, depth: int,
             interpret: bool = False):
    """Route to leaves and sum (g, h) per leaf; returns
    ([2**depth, 2], leaf_node3).  ``feat``/``thr`` are the level-(depth-1)
    split tables."""
    nb, R, F = xb3.shape
    n_leaves = 2 ** depth
    n_prev = 2 ** (depth - 1)
    m_pad = _round_up(2 * n_leaves, 128)  # also the dummy N dim of the matmul
    p_pad = _round_up(n_prev, 128)
    featp = jnp.zeros((8, p_pad), jnp.int32).at[0, :n_prev].set(feat)
    thrp = jnp.zeros((8, p_pad), jnp.int32).at[0, :n_prev].set(thr)
    out, node_out = pl.pallas_call(
        functools.partial(
            _leaf_kernel, n_leaves=n_leaves, n_feat=F, m_pad=m_pad, p_pad=p_pad,
        ),
        grid=(nb,),
        in_specs=[
            _blk(R, F), _blk(R, 1), _blk(R, 1), _blk(R, 1),
            pl.BlockSpec((8, p_pad), lambda i: (0, 0)),
            pl.BlockSpec((8, p_pad), lambda i: (0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((m_pad, 128), lambda i: (0, 0)),
            _blk(R, 1),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((m_pad, 128), jnp.float32),
            jax.ShapeDtypeStruct((nb, R, 1), jnp.int32),
        ],
        interpret=interpret,
    )(xb3, node3, g3, h3, featp, thrp)
    gh = out[:, 0]
    return jnp.stack([gh[:n_leaves], gh[n_leaves : 2 * n_leaves]], axis=-1), node_out


# -- blocking helpers -------------------------------------------------------


def block_rows(x, block: int = 1024):
    """Pad a [n, ...] array with zeros to a block multiple and reshape to
    (nb, block, k) for the fused kernels.  Returns (blocked, n)."""
    n = x.shape[0]
    n_pad = _round_up(n, block)
    if x.ndim == 1:
        x = x[:, None]
    if n_pad != n:
        x = jnp.pad(x, ((0, n_pad - n), (0, 0)))
    return x.reshape(n_pad // block, block, x.shape[1]), n


def unblock_rows(x3, n: int):
    """Inverse of block_rows for [nb, R, 1] -> [n]."""
    return x3.reshape(-1)[:n] if x3.shape[-1] == 1 else x3.reshape(-1, x3.shape[-1])[:n]
