"""Chaos proxy — scriptable TCP fault injection for liveness testing.

A :class:`ChaosProxy` sits between workers and the tracker (or between
peers) and forwards byte streams while injecting the network-fault shapes
that dominate real TPU-pod incidents (PAPERS.md: "Highly Available Data
Parallel ML training on Mesh Networks", "Don't Let a Few Network Failures
Slow the Entire AllReduce"):

* **refuse** — a new connection is accepted and immediately closed
  (flaky dial path; exercises connect retry/backoff);
* **delay** — every forwarded chunk waits a sampled latency first
  (congested DCN; exercises timeout margins);
* **truncate** — the client→upstream stream is severed after a sampled
  prefix, mid-message (torn hello; exercises the tracker's per-connection
  read deadline and the client's retry);
* **blackhole** — the connection stays open but nothing is ever forwarded
  (silent partition; the worst shape — only deadlines catch it);
* **partition** — a switch: while on, new connections are refused and
  every established one is severed;
* **slow_link** — ASYMMETRIC per-link degradation (the arxiv 2606.01680
  failure shape): only the client→upstream direction is delayed, and —
  when the proxy fronts a worker's listen socket — only for the dialer
  whose MAGIC_LINK hello carries a chosen source rank, so exactly ONE
  direction of ONE ``(src, dst)`` peer link is slow.  This is the fault
  the schedule planner's degraded-link repair routes around
  (doc/scheduling.md); ``run_elastic_schedule(slow_link=...)`` wires the
  proxy in front of the dst worker end-to-end.

All randomness comes from one seeded ``random.Random`` so a failing fuzz
schedule replays exactly.  The proxy is pure stdlib and threads; a
connection costs two pump threads, which is plenty for protocol-level
fuzzing (the tracker wire is one short exchange per message).

:func:`run_schedule` is the shared fuzz harness (tests/test_chaos.py and
tools/chaos_bench.py): it drives full bootstrap + recovery waves of
thread-workers through the proxy against a real in-process tracker, heals
the network, and requires the job to converge — completion or fail-fast,
never a hang.

:func:`run_elastic_schedule` is its elastic sibling (tests/test_elastic.py,
tools/recovery_bench.py --elastic): seeded shrink/grow wave scenarios —
kills WITHOUT restart, delayed spare arrivals, spares dying parked or
mid-promotion — driven through real :class:`~rabit_tpu.elastic.client.
ElasticWorker` threads against an elastic tracker, with heal-then-must-
converge and bitwise-correctness asserts at every intermediate world size.
"""

from __future__ import annotations

import random
import socket
import struct
import threading
import time
from dataclasses import dataclass, field

import numpy as np

from rabit_tpu.tracker import protocol as P
from rabit_tpu.tracker.tracker import Tracker

#: recv chunk size of the pump loops; also the granularity of delay faults.
_CHUNK = 4096

#: link-hello field codecs (same layout as protocol.py's MAGIC_LINK frame)
_U32 = struct.Struct("<I")
_I32 = struct.Struct("<i")


@dataclass
class FaultSpec:
    """Probabilities/ranges of the injected faults.  Mutable at runtime:
    assigning a fresh spec to ``proxy.spec`` re-scripts the proxy live
    (e.g. heavy faults during bootstrap, then heal)."""

    p_refuse: float = 0.0
    p_truncate: float = 0.0
    truncate_bytes: tuple[int, int] = (0, 64)
    p_blackhole: float = 0.0
    delay: tuple[float, float] = (0.0, 0.0)
    #: asymmetric per-link slowness: ``(src_rank, delay_s)`` delays every
    #: client->upstream chunk of connections whose MAGIC_LINK hello names
    #: ``src_rank`` as the dialer (``src_rank=None`` delays the c2u
    #: direction of EVERY connection — the one-way-congested tracker
    #: path).  A proxy with slow_link set is a dedicated link proxy: the
    #: sampled faults above do not apply to it.
    slow_link: tuple[int | None, float] | None = None
    #: relay-tier faults (doc/scaling.md; consumed by
    #: :func:`run_elastic_schedule` ``relays=`` mode, not by the proxy):
    #: ``relay_death=(at_s, down_s)`` stops relay 0 ``at_s`` seconds into
    #: the run and restarts it on the SAME port ``down_s`` later — the
    #: relay-bounce shape (children reconnect; their padded upstream
    #: leases must survive without a spurious lease_expired).
    #: ``relay_partition=(at_s, dur_s)`` severs relay 0's upstream
    #: channel for ``dur_s`` while it keeps serving children locally —
    #: the split-coordination-tier shape (batches resume at heal).
    relay_death: tuple[float, float] | None = None
    relay_partition: tuple[float, float] | None = None
    #: HA control-plane faults (doc/ha.md; consumed by
    #: :func:`run_elastic_schedule` ``failover=`` mode, not by the
    #: proxy): ``tracker_death=at_s`` SIGKILLs the PRIMARY tracker
    #: ``at_s`` seconds into the run (``Tracker.kill()`` — every socket
    #: drops with no goodbye), wherever the job happens to be:
    #: mid-bootstrap-wave, mid-quorum-round, mid-shrink-wave.  The warm
    #: standby must take over within its lease and the job must
    #: converge bitwise-identically.  ``standby_death=at_s`` kills the
    #: STANDBY instead — the job must ride on the primary, unbothered.
    tracker_death: float | None = None
    standby_death: float | None = None

    def clear(self) -> "FaultSpec":
        return FaultSpec()


@dataclass
class ChaosStats:
    connections: int = 0
    refused: int = 0
    truncated: int = 0
    blackholed: int = 0
    severed_by_partition: int = 0
    bytes_forwarded: int = 0
    slowed: int = 0  # connections whose c2u direction got the slow_link


@dataclass
class _Conn:
    client: socket.socket
    upstream: socket.socket
    closed: bool = False
    lock: threading.Lock = field(default_factory=threading.Lock)

    def sever(self) -> None:
        with self.lock:
            if self.closed:
                return
            self.closed = True
        for s in (self.client, self.upstream):
            try:
                s.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                s.close()
            except OSError:
                pass


class ChaosProxy:
    """TCP proxy with scriptable fault injection (see module docstring).

    Usage::

        proxy = ChaosProxy((tracker.host, tracker.port),
                           FaultSpec(p_refuse=0.3), seed=7).start()
        ...point workers at (proxy.host, proxy.port)...
        proxy.spec = FaultSpec()        # heal mid-run
        proxy.set_partition(True)       # or cut everything
        proxy.stop()
    """

    def __init__(self, upstream: tuple[str, int],
                 spec: FaultSpec | None = None, seed: int = 0,
                 listen_host: str = "127.0.0.1", listen_port: int = 0):
        self.upstream = (upstream[0], int(upstream[1]))
        self.spec = spec if spec is not None else FaultSpec()
        self.stats = ChaosStats()
        self._rng = random.Random(seed)
        self._rng_lock = threading.Lock()
        self._partitioned = False
        self._stopped = threading.Event()
        self._conns: list[_Conn] = []
        self._conns_lock = threading.Lock()
        self._srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._srv.bind((listen_host, listen_port))
        self._srv.listen(128)
        self.host, self.port = self._srv.getsockname()

    # -- lifecycle ---------------------------------------------------------

    def start(self) -> "ChaosProxy":
        threading.Thread(target=self._accept_loop, daemon=True,
                         name="chaos-accept").start()
        return self

    def stop(self) -> None:
        self._stopped.set()
        try:
            self._srv.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        try:
            self._srv.close()
        except OSError:
            pass
        with self._conns_lock:
            conns, self._conns = self._conns, []
        for c in conns:
            c.sever()

    def set_partition(self, on: bool) -> None:
        """While partitioned, refuse new connections and sever live ones."""
        self._partitioned = bool(on)
        if on:
            with self._conns_lock:
                conns, self._conns = self._conns, []
            for c in conns:
                self.stats.severed_by_partition += 1
                c.sever()

    # -- internals ---------------------------------------------------------

    def _roll(self) -> random.Random:
        # One shared seeded stream; per-decision access is serialized so a
        # given seed yields a reproducible fault sequence for a (mostly)
        # deterministic connection order.
        return self._rng

    def _accept_loop(self) -> None:
        while not self._stopped.is_set():
            try:
                client, _ = self._srv.accept()
            except OSError:
                return
            self.stats.connections += 1
            with self._rng_lock:
                refuse = (self._partitioned or
                          self._roll().random() < self.spec.p_refuse)
            if refuse:
                self.stats.refused += 1
                try:
                    client.close()
                except OSError:
                    pass
                continue
            threading.Thread(target=self._serve_conn, args=(client,),
                             daemon=True, name="chaos-conn").start()

    @staticmethod
    def _peek_link_hello(client: socket.socket) -> tuple[bytes, int | None]:
        """Read the 12-byte MAGIC_LINK hello (magic, rank, epoch) off a
        fresh peer-link connection.  Returns (bytes read, dialer rank or
        None); the bytes are forwarded upstream by the caller, so the
        handshake is observed, never consumed."""
        head = b""
        try:
            client.settimeout(5.0)
            while len(head) < 12:
                chunk = client.recv(12 - len(head))
                if not chunk:
                    break
                head += chunk
        except OSError:
            return head, None
        if len(head) < 8:
            return head, None
        magic = _U32.unpack_from(head, 0)[0]
        if magic != P.MAGIC_LINK:
            return head, None
        return head, _I32.unpack_from(head, 4)[0]

    def _serve_conn(self, client: socket.socket) -> None:
        spec = self.spec
        head = b""
        c2u_delay: tuple[float, float] | None = None
        if spec.slow_link is not None:
            # Dedicated link proxy: identify the dialer from the link
            # hello, delay only the matching client->upstream direction.
            src_rank, slow_s = spec.slow_link
            dialer = None
            if src_rank is not None:
                head, dialer = self._peek_link_hello(client)
            if src_rank is None or dialer == src_rank:
                c2u_delay = (float(slow_s), float(slow_s))
                self.stats.slowed += 1
        try:
            up = socket.create_connection(self.upstream, timeout=5.0)
        except OSError:
            try:
                client.close()
            except OSError:
                pass
            return
        conn = _Conn(client, up)
        with self._conns_lock:
            self._conns.append(conn)
        if spec.slow_link is not None:
            if head:
                try:
                    up.sendall(head)
                    self.stats.bytes_forwarded += len(head)
                except OSError:
                    conn.sever()
                    return
            threading.Thread(
                target=self._pump,
                args=(conn, client, up, None, c2u_delay or (0.0, 0.0)),
                daemon=True, name="chaos-pump-c2u").start()
            threading.Thread(
                target=self._pump, args=(conn, up, client, None, (0.0, 0.0)),
                daemon=True, name="chaos-pump-u2c").start()
            return
        with self._rng_lock:
            rng = self._roll()
            blackhole = rng.random() < spec.p_blackhole
            truncate_at = None
            if rng.random() < spec.p_truncate:
                truncate_at = rng.randint(*spec.truncate_bytes)
            delays = spec.delay
        if blackhole:
            # Forward nothing, close nothing: the silent-partition shape.
            # The conn stays registered so stop()/partition() reap it, and
            # both endpoints see only their own deadlines.
            self.stats.blackholed += 1
            return
        if truncate_at is not None:
            self.stats.truncated += 1
        threading.Thread(
            target=self._pump, args=(conn, client, up, truncate_at, delays),
            daemon=True, name="chaos-pump-c2u").start()
        threading.Thread(
            target=self._pump, args=(conn, up, client, None, delays),
            daemon=True, name="chaos-pump-u2c").start()

    def _pump(self, conn: _Conn, src: socket.socket, dst: socket.socket,
              truncate_at: int | None, delays: tuple[float, float]) -> None:
        budget = truncate_at
        try:
            try:
                src.settimeout(0.2)  # poll the stop/partition flags
            except OSError:
                return  # the sibling pump already severed this conn
            while not self._stopped.is_set() and not conn.closed:
                try:
                    data = src.recv(_CHUNK)
                except socket.timeout:
                    continue
                except OSError:
                    break
                if not data:
                    break
                if delays[1] > 0:
                    with self._rng_lock:
                        pause = self._roll().uniform(*delays)
                    if pause > 0:
                        time.sleep(pause)
                if budget is not None:
                    data = data[:budget]
                    budget -= len(data)
                try:
                    if data:
                        dst.sendall(data)
                        self.stats.bytes_forwarded += len(data)
                except OSError:
                    break
                if budget == 0:
                    break  # prefix forwarded; sever mid-message
        finally:
            conn.sever()


# -- fuzz schedule runner ----------------------------------------------------

@dataclass
class ScheduleResult:
    seed: int
    world: int
    rounds: int
    completed: bool
    epoch: int
    rank_of: dict[str, int]
    elapsed: float
    stats: ChaosStats
    outcome: str  # "completed" | "failed_fast"


def _random_spec(rng: random.Random) -> FaultSpec:
    """A sampled fault mix: always at least one fault family active."""
    spec = FaultSpec(
        p_refuse=rng.choice([0.0, 0.2, 0.5]),
        p_truncate=rng.choice([0.0, 0.2, 0.5]),
        p_blackhole=rng.choice([0.0, 0.15]),
        delay=rng.choice([(0.0, 0.0), (0.0, 0.02), (0.01, 0.05)]),
    )
    if (spec.p_refuse == spec.p_truncate == spec.p_blackhole == 0.0
            and spec.delay[1] == 0.0):
        spec.p_refuse = 0.3
    return spec


def run_schedule(seed: int, world: int | None = None,
                 faulty_rounds: int = 2, deadline_sec: float = 20.0,
                 quiet: bool = True,
                 slow_one_way: float | None = None) -> ScheduleResult:
    """One fuzzed bootstrap/recovery scenario (deterministic per seed).

    Thread-workers bootstrap through a freshly scripted :class:`ChaosProxy`
    against a real :class:`Tracker`, in rounds that mirror the native
    engine's re-wave loop (comm.cc Init): every worker check-ins and waits
    for its assignment; a round where anyone failed or the epochs disagree
    is retried with survivors sending CMD_RECOVER — exactly the protocol's
    failed-wave contract.  One sampled worker "dies" after its first
    successful check-in and re-enters as a restart (fresh CMD_START, same
    task id), fuzzing the stale-entry replacement path.  After
    ``faulty_rounds`` rounds the proxy is healed, so every schedule must
    then CONVERGE: all workers agree on one epoch with stable, distinct
    ranks.  Any outcome is acceptable except a hang — every socket
    operation is bounded, and the schedule deadline converts "stuck" into a
    hard failure.
    """
    rng = random.Random(seed)
    world = world if world is not None else rng.choice([2, 3, 4])
    tracker = Tracker(world, quiet=True, conn_timeout_sec=1.0).start()
    # slow_one_way swaps the sampled fault mix for the asymmetric shape:
    # only the worker->tracker direction is delayed (hellos crawl,
    # replies fly) until the heal round, when convergence is mandatory.
    spec = (FaultSpec(slow_link=(None, float(slow_one_way)))
            if slow_one_way is not None else _random_spec(rng))
    proxy = ChaosProxy((tracker.host, tracker.port), spec,
                       seed=seed).start()
    t0 = time.monotonic()
    deadline = t0 + deadline_sec
    tasks = [str(i) for i in range(world)]
    cmd = {t: P.CMD_START for t in tasks}
    rank_of: dict[str, int] = {}
    die_once = rng.choice(tasks) if rng.random() < 0.5 else None
    rounds = 0
    completed = False
    epoch = -1
    try:
        while time.monotonic() < deadline:
            rounds += 1
            if rounds > faulty_rounds:
                proxy.spec = FaultSpec()  # heal: convergence now mandatory
            results: dict[str, object] = {}

            # Every RPC is bounded: retries+1 attempts x (connect timeout +
            # reply timeout) + backoff.  A thread alive past that sum is a
            # genuine hang (the watchdog-bound analog of this harness), not
            # a slow retry.
            retries, timeout, reply_timeout = 4, 0.25, 0.5
            worst_thread = (retries + 1) * (timeout + reply_timeout) + 2.0

            def boot(task_id: str) -> None:
                try:
                    results[task_id] = P.tracker_rpc(
                        proxy.host, proxy.port, cmd[task_id], task_id,
                        prev_rank=rank_of.get(task_id, -1),
                        listen_port=40000 + int(task_id),
                        timeout=timeout, reply_timeout=reply_timeout,
                        retries=retries, backoff=0.02, backoff_cap=0.2,
                        rng=random.Random(f"{seed}:{task_id}:{rounds}"),
                    )
                except P.TrackerUnreachable as exc:
                    results[task_id] = exc

            threads = [threading.Thread(target=boot, args=(t,), daemon=True)
                       for t in tasks]
            for th in threads:
                th.start()
            for th in threads:
                th.join(timeout=worst_thread)
                if th.is_alive():
                    raise TimeoutError(
                        f"schedule seed={seed}: worker thread hung past its "
                        f"RPC bound ({worst_thread:.0f}s, round {rounds})")
            asgs = {t: r for t, r in results.items()
                    if isinstance(r, P.Assignment)}
            for t, asg in asgs.items():
                prev = rank_of.get(t)
                if prev is not None and prev != asg.rank:
                    raise AssertionError(
                        f"seed={seed}: task {t} rank changed {prev} -> "
                        f"{asg.rank} (stable re-admission violated)")
                rank_of[t] = asg.rank
            if len(asgs) == world:
                epochs = {a.epoch for a in asgs.values()}
                ranks = sorted(a.rank for a in asgs.values())
                if len(epochs) == 1 and ranks == list(range(world)):
                    epoch = epochs.pop()
                    completed = True
                    break
            # Failed wave: survivors re-enter as recover (the BuildLinks
            # failure path), failures keep re-sending CMD_START.
            for t in tasks:
                cmd[t] = P.CMD_RECOVER if t in asgs else P.CMD_START
            if die_once is not None and die_once in asgs:
                cmd[die_once] = P.CMD_START  # its "restart" re-enters fresh
                die_once = None
        if not completed and time.monotonic() >= deadline:
            raise TimeoutError(
                f"schedule seed={seed}: no convergence within "
                f"{deadline_sec}s ({rounds} rounds)")
    finally:
        proxy.stop()
        tracker.stop()
    return ScheduleResult(
        seed=seed, world=world, rounds=rounds, completed=completed,
        epoch=epoch, rank_of=dict(rank_of),
        elapsed=time.monotonic() - t0, stats=proxy.stats,
        outcome="completed" if completed else "failed_fast",
    )


# -- elastic fuzz schedule runner ---------------------------------------------

@dataclass
class ElasticScheduleResult:
    seed: int
    world: int
    n_spares: int
    niter: int
    n_completed: int
    n_died: int
    worlds_seen: list[int]
    epochs: list[dict]
    elapsed: float
    outcome: str  # "completed" | "failed"
    schedule: str = "auto"    # the rabit_schedule value this run planned
    n_repaired: int = 0       # schedule_repaired waves committed
    dst_wait_s: float = 0.0   # slow_link runs: dst's cumulative link wait
    dst_slow_reports: int = 0
    # quorum runs (rabit_tpu.quorum, doc/partial_allreduce.md)
    quorum: str = ""                  # the rabit_quorum spec this run used
    straggler: tuple | None = None    # (rank, delay_s, heal_version)
    n_quorum_met: int = 0             # rounds decided with exclusions
    n_corrections_folded: int = 0
    n_corrections_dropped: int = 0    # epoch boundaries settling by drop
    #: task "0"'s mean inter-commit gap over the steady rounds — the
    #: live-rank round cadence the quorum ablation compares (a straggler
    #: shows up here under quorum off, and must NOT under quorum on)
    cadence_s: float = 0.0
    # relay-tier runs (rabit_tpu.relay, doc/scaling.md)
    relays: int = 0                   # relay nodes interposed (0 = direct)
    n_relay_lost: int = 0             # relay channel drops the tracker saw
    n_batches_folded: int = 0         # non-empty CMD_BATCH envelopes folded
    n_spurious_expired: int = 0       # lease_expired for tasks that never
    #                                   died (must stay 0 across a bounce)
    # HA failover runs (rabit_tpu.ha, doc/ha.md)
    standby: bool = False             # a warm standby rode along
    n_failover: int = 0               # tracker_failover promotions
    n_journal_gap: int = 0            # replay divergences (must stay 0)
    primary_killed: bool = False      # the tracker_death fault landed
    # diagnosis plane (rabit_tpu.obs.diagnose, doc/observability.md):
    # the active tracker's HealthMonitor exposition at schedule end —
    # open + recent incidents and the lifetime open/resolve counters,
    # so chaos runs can assert WHAT the monitor indicted (class and
    # named subject), not just that repair machinery moved.
    incidents: dict = field(default_factory=dict)


def run_elastic_schedule(seed: int, world: int | None = None,
                         deadline_sec: float = 30.0,
                         quiet: bool = True,
                         schedule: str | None = None,
                         slow_link: tuple[int, int, float] | None = None,
                         repair: bool = True,
                         niter: int | None = None,
                         straggler: tuple | None = None,
                         quorum: str = "",
                         quorum_wait: float = 0.15,
                         quorum_flag_after: int = 0,
                         codec: str = "",
                         mix_faults: bool = False,
                         iter_sleep: float | None = None,
                         relays: int = 0,
                         relay_fault: FaultSpec | None = None,
                         relay_flush: float = 0.1,
                         heartbeat_sec: float = 0.15,
                         failover: FaultSpec | None = None,
                         takeover_sec: float = 0.5,
                         job: str = "") -> ElasticScheduleResult:
    """One fuzzed shrink/grow scenario (deterministic per seed).

    A seeded mix of elastic failure shapes against a real elastic tracker:

    * **kill-without-restart** — workers die silently at a sampled version
      and nothing relaunches them (the preempted-fleet shape; the
      launcher's restart loop is deliberately absent);
    * **delayed spare arrival** — hot spares park a sampled delay after
      launch, so promotions race shrinks and grow-backs race completion;
    * **spare dying parked / mid-promotion** — a spare's warm socket goes
      dead in the pool, or the instant its promotion Assignment lands.

    Every worker runs the deterministic histogram workload over one shared
    dataset, re-cut per epoch by the dense elastic partition — so at EVERY
    intermediate world size the rank-order int64 fold must reproduce the
    exact closed-form totals.  Task "0" is never killed, so at least one
    worker must complete; any outcome is acceptable except a hang (every
    socket operation is bounded and the schedule deadline converts "stuck"
    into a hard failure) or a wrong bit.

    Asserts (raising on violation, like :func:`run_schedule`):
    completion of all never-killed workers, bitwise-correct final states,
    dense distinct ranks in every committed wave, strictly increasing
    epochs.

    ``schedule`` pins the tracker's ``rabit_schedule`` (None samples one
    per seed, so the fuzz campaigns sweep all four values).  ``slow_link
    = (src, dst, delay_s)`` interposes a :class:`ChaosProxy` in front of
    worker ``dst``'s listen socket that delays only ``src``'s frames —
    the asymmetric degraded-link shape; the dst worker self-reports
    (``slow_report_share``) and, with ``repair`` on, the tracker's next
    wave routes the ring around the link (``repair=False`` is the
    unrepaired control arm the bench compares against).  slow_link runs
    disable the sampled kills/spares so the two arms differ only in the
    repair.

    ``straggler = (rank, delay_s)`` or ``(rank, delay_s, heal_version)``
    is the COMPUTE-side fault (distinct from ``slow_link``'s network
    delay): that rank's contribution takes ``delay_s`` extra seconds for
    every version up to ``heal_version`` (default: never heals).  Pair
    it with ``quorum`` (a ``rabit_quorum`` spec for tracker AND workers;
    ``quorum_wait``/``quorum_flag_after``/``codec`` ride along) to
    exercise the K-of-N partial allreduce: excluded rounds, correction
    landing, and epoch-boundary drops.  A straggler run disables the
    sampled kills/spares for a clean arm unless ``mix_faults=True`` (the
    straggler+quorum+kill campaigns); the sampled victim set never
    contains the straggler or task "0".

    ``relays=R`` interposes R :class:`rabit_tpu.relay.Relay` nodes
    between the workers and the tracker (workers shard round-robin, the
    wire they speak is unchanged — doc/scaling.md).  ``relay_fault``
    applies the :class:`FaultSpec` relay faults to relay 0:
    ``relay_death`` bounces it (stop, wait, restart on the SAME port —
    children retry and reconnect), ``relay_partition`` severs only its
    upstream channel while children keep getting local ACKs.  Relay runs
    additionally assert that NO task that stayed alive suffered a
    ``lease_expired`` (the padded upstream lease must ride out a bounce)
    and that a relay death never shows up as a membership event of its
    children.

    ``failover=FaultSpec(tracker_death=at_s)`` arms the HA arm
    (doc/ha.md): the tracker journals every mutation (in-memory
    journal), a warm :class:`rabit_tpu.ha.Standby` streams it over
    CMD_JOURNAL, workers (and relays, when ``relays>0``) carry the
    two-entry address list — and at ``at_s`` the primary is killed
    ABRUPTLY (``Tracker.kill()``), wherever the job is: mid-bootstrap,
    mid-quorum-round, mid-shrink.  The standby must promote within its
    takeover lease, the interrupted wave must re-complete on it, live
    ranks must not suffer a spurious ``lease_expired``, and every
    bitwise assert below applies unchanged across the merged
    primary+standby event timeline.  ``standby_death=at_s`` kills the
    standby instead — the job must ride the primary, unbothered.

    ``job`` namespaces every worker's wire task id ("<job>/<task>",
    doc/service.md) so the whole fuzzed scenario can run as ONE tenant
    of a multi-job CollectiveService; the default empty key keeps the
    legacy ids (and the result dict's task-id keys) byte-identical.

    Quorum correctness asserts: every completed worker's final state is
    BITWISE IDENTICAL; with a single epoch the state equals the closed
    form minus exactly the contributions the exclusion records name as
    never-folded (exact accounting from ``quorum_met`` /
    ``correction_folded`` events); across recovery waves (records of an
    aborted epoch may describe rounds that were then redone exactly) the
    state is sandwiched elementwise between the closed form and the
    closed form minus every potentially-missing contribution.  Codec
    runs (lossy wire) assert the bitwise cross-rank identity and a loose
    closeness to the closed form instead.
    """
    from rabit_tpu.elastic.client import ElasticWorker
    from rabit_tpu.elastic.rebalance import shard_slice

    rng = random.Random(seed)
    world = world if world is not None else rng.choice([2, 3, 4])
    n_spares = rng.choice([0, 1, 2])
    drawn_niter = rng.choice([3, 4, 5])
    niter = int(niter) if niter is not None else drawn_niter
    drawn_sleep = rng.choice([0.05, 0.1])
    iter_sleep = float(iter_sleep) if iter_sleep is not None else drawn_sleep
    if schedule is None:
        schedule = rng.choice(["auto", "tree", "ring", "swing"])
    if slow_link is not None:
        n_spares = 0  # a clean A/B: no confounding resize traffic
    s_rank, s_delay, s_heal = -1, 0.0, 0
    if straggler is not None:
        s_rank, s_delay = int(straggler[0]), float(straggler[1])
        s_heal = int(straggler[2]) if len(straggler) > 2 else niter + 1
        if not (0 <= s_rank < world) or s_delay < 0:
            raise ValueError(f"bad straggler {straggler!r} for world {world}")
        if not mix_faults:
            n_spares = 0  # a clean quorum arm: only the compute fault
    n_rows, n_bins = 8 * world, 8
    data = np.array([rng.randrange(n_bins) for _ in range(n_rows)])
    # codec runs fold float32 (the compress contract); exact runs keep
    # the int64 bitwise closed form.
    fold_dtype = np.float32 if codec else np.int64

    def contribution(version: int, w: int, r: int) -> np.ndarray:
        time.sleep(iter_sleep)
        if r == s_rank and version <= s_heal:
            time.sleep(s_delay)  # the compute-side straggler fault
        rows = data[shard_slice(n_rows, w, r)]
        return np.bincount(rows, minlength=n_bins).astype(fold_dtype) * version

    def per_contribution(version: int, w: int, r: int) -> np.ndarray:
        """One rank's contribution WITHOUT the fault sleeps — the exact
        term the quorum accounting subtracts for a never-folded block."""
        rows = data[shard_slice(n_rows, w, r)]
        return np.bincount(rows, minlength=n_bins).astype(fold_dtype) * version

    expected = sum(np.bincount(data, minlength=n_bins).astype(fold_dtype) * v
                   for v in range(1, niter + 1))

    n_kills = rng.randint(0, min(world - 1, 2))
    pool = [str(i) for i in range(1, world) if i != s_rank]
    victims = rng.sample(pool, min(n_kills, len(pool)))
    kill_at = {t: rng.randint(2, max(niter, 2)) for t in victims}
    if slow_link is not None or (straggler is not None and not mix_faults):
        kill_at = {}
    spare_specs = []
    for i in range(n_spares):
        roll = rng.random()
        fail = (("die_parked",) if roll < 0.15
                else ("die_promoted",) if roll < 0.3 else None)
        spare_specs.append((f"s{i}", rng.uniform(0.0, 0.8), fail))

    # shrink_after must outlast the workers' link timeout: a survivor that
    # detects a death slowly (accept-side wait for a dead dialer) re-enters
    # only after link_timeout, and a shorter shrink deadline would close
    # the wave without it — splitting the job (doc/elasticity.md, "Choosing
    # the knobs").
    tracker_kwargs = dict(quiet=quiet, conn_timeout_sec=1.0,
                          shrink_after_sec=1.5, promote_after_sec=0.1,
                          schedule=schedule, sched_repair=repair,
                          quorum=quorum,
                          quorum_flag_after=quorum_flag_after)
    journal = None
    standby = None
    if failover is not None:
        from rabit_tpu.ha import Journal

        journal = Journal(None)  # in-memory: the CMD_JOURNAL stream syncs
    tracker = Tracker(world, journal=journal, **tracker_kwargs).start()
    addr = (tracker.host, tracker.port)
    worker_addrs: list = [addr]
    if failover is not None:
        from rabit_tpu.ha import Standby

        standby = Standby(primary=addr, takeover_sec=takeover_sec,
                          poll_sec=0.05, quiet=quiet,
                          tracker_kwargs=tracker_kwargs).start()
        worker_addrs.append((standby.host, standby.port))
    # Relay tier (doc/scaling.md): workers shard round-robin across R
    # in-process relays; relay 0 is the fault target.
    relay_objs: list = []
    relay_lock = threading.Lock()
    if relays > 0:
        from rabit_tpu.relay import Relay

        # relays carry the full failover list: children never re-dial
        # across a root failover, the relay channel rotates for them
        relay_objs = [Relay(worker_addrs, relay_id=f"relay{i}",
                            flush_sec=relay_flush, quiet=True).start()
                      for i in range(int(relays))]

    def task_addr(tid: str):
        if not relay_objs:
            return worker_addrs if len(worker_addrs) > 1 else addr
        try:
            idx = int(tid.lstrip("s"))
        except ValueError:
            idx = sum(tid.encode())
        with relay_lock:
            r = relay_objs[idx % len(relay_objs)]
        return (r.host, r.port)

    stop_fault = threading.Event()
    fault_threads: list[threading.Thread] = []
    if relay_objs and relay_fault is not None:
        from rabit_tpu.relay import Relay

        def bounce_relay() -> None:
            at_s, down_s = relay_fault.relay_death
            if stop_fault.wait(at_s):
                return
            with relay_lock:
                old = relay_objs[0]
            port = old.port
            old.stop()
            if stop_fault.wait(down_s):
                return
            for _ in range(30):  # the freed port can lag a beat
                try:
                    fresh = Relay(addr, relay_id="relay0", port=port,
                                  flush_sec=relay_flush, quiet=True).start()
                    break
                except OSError:
                    if stop_fault.wait(0.1):
                        return
            else:
                return
            with relay_lock:
                relay_objs[0] = fresh

        def partition_relay() -> None:
            at_s, dur_s = relay_fault.relay_partition
            if stop_fault.wait(at_s):
                return
            with relay_lock:
                r0 = relay_objs[0]
            r0.set_partition(True)
            stop_fault.wait(dur_s)
            r0.set_partition(False)

        if relay_fault.relay_death is not None:
            fault_threads.append(threading.Thread(target=bounce_relay,
                                                  daemon=True))
        if relay_fault.relay_partition is not None:
            fault_threads.append(threading.Thread(target=partition_relay,
                                                  daemon=True))
    if failover is not None:
        # HA faults (doc/ha.md): SIGKILL the primary (or the standby)
        # wherever the job happens to be.  Tracker.kill() drops every
        # socket with no goodbye — parked waves, spare pool, relay and
        # journal channels — exactly the preempted-VM shape.
        def kill_primary() -> None:
            if stop_fault.wait(failover.tracker_death):
                return
            tracker.kill()

        def kill_standby() -> None:
            if stop_fault.wait(failover.standby_death):
                return
            standby.kill()

        if failover.tracker_death is not None:
            fault_threads.append(threading.Thread(target=kill_primary,
                                                  daemon=True))
        if failover.standby_death is not None:
            fault_threads.append(threading.Thread(target=kill_standby,
                                                  daemon=True))
    t0 = time.monotonic()
    results: dict[str, object] = {}
    lock = threading.Lock()

    def run_worker(w: "ElasticWorker") -> None:
        res = w.run()
        with lock:
            # keyed by the job-LOCAL id: the asserts below reason about
            # "worker i", whatever tenant namespace the run used
            results[P.split_job(w.task_id)[1]] = res

    threads = []
    workers: list["ElasticWorker"] = []
    for i in range(world):
        tid = str(i)
        fail = ("die", kill_at[tid]) if tid in kill_at else None
        # slow_link/straggler runs need a longer link patience: a
        # degraded hop (or a legacy-mode recv blocked on a computing
        # straggler) legitimately stalls frames without the peer dying.
        link_to = 1.0 if slow_link is None else max(1.0, 4 * slow_link[2])
        if straggler is not None:
            link_to = max(link_to, 4 * s_delay)
        w = ElasticWorker(task_addr(tid), tid, contribution, niter,
                          heartbeat_sec=heartbeat_sec, rpc_timeout=2.0,
                          wave_timeout=10.0, link_timeout=link_to,
                          deadline_sec=deadline_sec, fail=fail,
                          quorum=quorum, quorum_wait=quorum_wait,
                          codec=codec, job=job)
        workers.append(w)
        threads.append(threading.Thread(target=run_worker, args=(w,),
                                        daemon=True))
    link_proxy: ChaosProxy | None = None
    if slow_link is not None:
        src, dst, slow_s = slow_link
        if not (0 <= src < world and 0 <= dst < world and src != dst):
            raise ValueError(f"bad slow_link {slow_link!r} for world {world}")
        # Interpose the link proxy in front of dst's listen socket: every
        # inbound peer dial crosses it, but only src's frames are slowed
        # (the proxy reads the MAGIC_LINK hello to tell dialers apart).
        if src > dst:
            # peer links are dialed by the LOWER rank; only in-dials
            # cross a listen-side proxy, so the slowable direction is
            # src < dst (the dialer's send path)
            raise ValueError(f"slow_link wants src < dst, got {slow_link!r}")
        link_proxy = ChaosProxy(
            ("127.0.0.1", workers[dst].listen_port),
            FaultSpec(slow_link=(src, float(slow_s))), seed=seed).start()
        workers[dst].advertise_port = link_proxy.port
        workers[dst].slow_report_share = 0.2

    spare_workers: list["ElasticWorker"] = []

    def run_spare(tid: str, delay: float, fail: tuple | None) -> None:
        time.sleep(delay)
        if time.monotonic() - t0 > deadline_sec:
            return
        w = ElasticWorker(task_addr(tid), tid, contribution, niter,
                          spare=True,
                          heartbeat_sec=heartbeat_sec, rpc_timeout=2.0,
                          wave_timeout=10.0, link_timeout=1.0,
                          deadline_sec=max(deadline_sec
                                           - (time.monotonic() - t0), 1.0),
                          fail=fail, quorum=quorum,
                          quorum_wait=quorum_wait, codec=codec, job=job)
        with lock:
            spare_workers.append(w)
        run_worker(w)

    spare_threads = [threading.Thread(target=run_spare,
                                      args=(tid, delay, fail), daemon=True)
                     for tid, delay, fail in spare_specs]
    try:
        for th in threads + spare_threads + fault_threads:
            th.start()
        for th in threads:
            th.join(timeout=deadline_sec + 10.0 - (time.monotonic() - t0))
            if th.is_alive():
                raise TimeoutError(
                    f"elastic schedule seed={seed}: worker thread hung past "
                    f"the schedule deadline ({deadline_sec}s)")
    finally:
        stop_fault.set()
        # Primaries are done (or the schedule failed): release the pool —
        # stop() closes the warm sockets, so spares that were never
        # promoted exit their park loop instead of waiting out their
        # deadline.  A promoted spare finished with the group (collectives
        # are lockstep), so the short join below is enough.
        tracker.stop()
        if link_proxy is not None:
            link_proxy.stop()
        # Join the fault threads BEFORE stopping relays: a bounce thread
        # mid-restart could otherwise install a fresh relay after the
        # stop loop ran and leak it.
        for th in fault_threads:
            th.join(timeout=8.0)
        # The standby after the faults settle: if it promoted, it IS the
        # job's tracker and its stop() tears that tracker down too; if
        # not, stop() just ends the tail loop (before its takeover lease
        # could fire against the deliberately-stopped primary).
        if standby is not None:
            standby.stop()
        with relay_lock:
            for r in relay_objs:
                r.stop()
        # A promoted spare mid-recovery would otherwise spin its bounded
        # re-check-in loop against the stopped tracker until its own
        # deadline — stop() flips it to a fast, clean exit.
        with lock:
            for w in spare_workers:
                w.stop()
        for th in spare_threads:
            th.join(timeout=10.0)
    for th in spare_threads:
        if th.is_alive():
            raise TimeoutError(
                f"elastic schedule seed={seed}: spare thread hung after "
                f"tracker stop")

    # HA runs: the job's timeline spans BOTH trackers — the primary's
    # events up to its death, the promoted standby's from takeover (the
    # standby seeds its own sync/failover events into the tracker it
    # promotes).  Every assert below reads the merged line.
    promoted_tracker = (standby.tracker
                        if standby is not None and standby.promoted.is_set()
                        else None)
    all_events = list(tracker.events)
    if promoted_tracker is not None:
        all_events += list(promoted_tracker.events)
    elif standby is not None:
        all_events += list(standby.events)
    active_tracker = (promoted_tracker if promoted_tracker is not None
                      else tracker)
    completed = [r for r in results.values() if r.completed]
    died = [r for r in results.values() if r.died]
    # -- convergence: every never-killed primary completes with the exact
    # closed-form totals, no matter which world sizes it passed through.
    for i in range(world):
        tid = str(i)
        if tid in kill_at:
            continue
        res = results.get(tid)
        if res is None or not res.completed:
            raise AssertionError(
                f"seed={seed}: surviving worker {tid} did not complete: "
                f"{getattr(res, 'error', 'no result')!r}")
    for res in completed:
        if res.final_version != niter:
            raise AssertionError(
                f"seed={seed}: task {res.task_id} completed at version "
                f"{res.final_version}, wanted {niter}")
    # -- cross-rank determinism: every completed worker reproduced the
    # SAME bits, no matter which quorum records, corrections, codecs, or
    # world sizes it passed through.
    ref = completed[0].state if completed else None
    for res in completed[1:]:
        if not np.array_equal(res.state, ref):
            raise AssertionError(
                f"seed={seed}: task {res.task_id} state diverges bitwise "
                f"from task {completed[0].task_id}")
    # -- value correctness against the closed form.
    qm = [e for e in all_events if e["kind"] == "quorum_met"]
    folded = {(e["src_version"], e["rank"])
              for e in all_events if e["kind"] == "correction_folded"}
    missing = {(e["version"], r, e["world"])
               for e in qm for r in e["excluded"]}
    missing = {(sv, r, w) for (sv, r, w) in missing if (sv, r) not in folded}
    n_epochs = len(active_tracker.elastic.history)
    if ref is not None:
        if not quorum:
            if not np.array_equal(ref, expected):
                raise AssertionError(
                    f"seed={seed}: state {ref!r} != expected {expected!r}")
        elif codec:
            # lossy wire: the bitwise contract is cross-rank identity
            # (asserted above); the value has to be close to the
            # quorum-adjusted closed form (missing mass subtracted).
            adjusted = expected.copy()
            for sv, r, w in missing:
                adjusted = adjusted - per_contribution(sv, w, r)
            tol = 0.05 * float(np.max(np.abs(expected))) + 1.0
            if n_epochs <= 1:
                close = np.allclose(ref, adjusted, atol=tol)
            else:
                close = bool(np.all(ref <= expected + tol)
                             and np.all(ref >= adjusted - tol))
            if not close:
                raise AssertionError(
                    f"seed={seed}: codec state {ref!r} too far from "
                    f"quorum-adjusted {adjusted!r} (tol {tol})")
        elif n_epochs <= 1:
            # single epoch: the exclusion records account EXACTLY for
            # every never-folded contribution.
            adjusted = expected.copy()
            for sv, r, w in missing:
                adjusted = adjusted - per_contribution(sv, w, r)
            if not np.array_equal(ref, adjusted):
                raise AssertionError(
                    f"seed={seed}: state {ref!r} != quorum-adjusted "
                    f"{adjusted!r} (missing {sorted(missing)})")
        else:
            # recovery waves redo rounds: a record from an aborted epoch
            # may describe a round that then folded fully, so the exact
            # set is unknowable from events alone — sandwich instead
            # (contributions are non-negative, nothing folds twice).
            floor = expected.copy()
            for sv, r, w in missing:
                floor = floor - per_contribution(sv, w, r)
            if not (np.all(ref <= expected) and np.all(ref >= floor)):
                raise AssertionError(
                    f"seed={seed}: state {ref!r} outside "
                    f"[{floor!r}, {expected!r}]")
    # -- membership sanity on the tracker's committed timeline.
    waves = [e for e in all_events if e["kind"] == "wave"]
    epochs = [e["epoch"] for e in waves]
    if epochs != sorted(set(epochs)):
        raise AssertionError(f"seed={seed}: epochs not strictly "
                             f"increasing: {epochs}")
    for e in waves:
        ranks = sorted(e["assignments"].values())
        if ranks != list(range(e["world"])):
            raise AssertionError(
                f"seed={seed}: wave epoch {e['epoch']} ranks {ranks} not "
                f"dense for world {e['world']}")
    worlds_seen = sorted({e["world"] for e in waves})
    # -- relay-tier sanity: a relay bounce/partition is NOT a membership
    # event of its children — no task that stayed alive may have had its
    # lease expired (the padded upstream lease must cover the gap).
    died_tasks = {tid for tid, r in results.items()
                  if getattr(r, "died", False)}
    expired_tasks = {e.get("task_id") for e in all_events
                     if e["kind"] == "lease_expired"}
    spurious = expired_tasks - died_tasks - set(kill_at)
    if (relays or failover is not None) and spurious:
        raise AssertionError(
            f"seed={seed}: spurious lease_expired for live tasks "
            f"{sorted(spurious)} (a relay bounce or tracker failover "
            f"must not kill children)")
    dst_res = results.get(str(slow_link[1])) if slow_link is not None else None
    cadence = 0.0
    ct = getattr(results.get("0"), "commit_times", None) or {}
    if niter >= 3 and 1 in ct and (niter - 1) in ct:
        cadence = (ct[niter - 1] - ct[1]) / (niter - 2)
    return ElasticScheduleResult(
        seed=seed, world=world, n_spares=n_spares, niter=niter,
        n_completed=len(completed), n_died=len(died),
        worlds_seen=worlds_seen,
        epochs=[{"epoch": we.epoch, "world": we.world_size}
                for we in active_tracker.elastic.history],
        elapsed=time.monotonic() - t0,
        outcome="completed",
        schedule=schedule,
        n_repaired=sum(1 for e in all_events
                       if e["kind"] == "schedule_repaired"),
        dst_wait_s=getattr(dst_res, "wait_prev_s", 0.0),
        dst_slow_reports=getattr(dst_res, "slow_reports", 0),
        quorum=quorum,
        straggler=(s_rank, s_delay, s_heal) if straggler is not None
        else None,
        n_quorum_met=len(qm),
        n_corrections_folded=sum(1 for e in all_events
                                 if e["kind"] == "correction_folded"),
        n_corrections_dropped=sum(1 for e in all_events
                                  if e["kind"] == "correction_dropped"),
        cadence_s=round(cadence, 6),
        relays=int(relays),
        n_relay_lost=sum(1 for e in all_events
                         if e["kind"] == "relay_lost"),
        n_batches_folded=sum(1 for e in all_events
                             if e["kind"] == "batch_folded"),
        n_spurious_expired=len(spurious),
        standby=standby is not None,
        n_failover=sum(1 for e in all_events
                       if e["kind"] == "tracker_failover"),
        n_journal_gap=sum(1 for e in all_events
                          if e["kind"] == "journal_gap"),
        primary_killed=bool(getattr(tracker, "_killed", False)),
        incidents=active_tracker._health.render(),
    )
