"""Degraded-link flagging — telemetry in, avoid-set out.

The planner (:mod:`rabit_tpu.sched.planner`) routes around whatever
links it is told to avoid; this module decides WHAT to avoid, from the
two telemetry surfaces the stack already produces:

* **live worker reports** — an executor that keeps waiting on its
  incoming ring link past ``rabit_sched_wait_share`` prints a
  ``slow_link src=A dst=B ...`` line; the tracker's stats-line bridge
  converts it to a ``link_degraded`` event
  (:func:`rabit_tpu.obs.events.event_from_stats_line`) and feeds it
  here.  The delayed frame cascades downstream, but the DST of the slow
  link accumulates the most wait (it waits on every one of the W-1
  delayed hops), so the per-worker report of its own incoming link is
  the right attribution;
* **offline straggler analytics** — :func:`rabit_tpu.obs.trace.
  straggler_report`'s per-rank lateness/wait shares: the top straggler's
  incoming ring link is the prime suspect when its wait share dominates.

Both emit ``(src_rank, dst_rank)`` pairs.  Ranks are only meaningful
within one epoch — the tracker stores flags keyed by TASK id
(``link_flags_by_task``) and re-derives rank pairs against each new
epoch's rank map, so a shrink/grow between flag and repair cannot point
the avoid set at the wrong worker.
"""

from __future__ import annotations

from typing import Iterable, Mapping


def links_from_events(events: Iterable[Mapping],
                      min_reports: int = 1) -> set[tuple[int, int]]:
    """Degraded ``(src, dst)`` rank pairs from ``link_degraded`` events
    (tracker event dicts or anything mapping-shaped with ``kind``/
    ``src``/``dst``).  ``min_reports`` requires repeated evidence before
    a link is flagged (1 = first report wins — the chaos default)."""
    counts: dict[tuple[int, int], int] = {}
    for ev in events:
        if ev.get("kind") != "link_degraded":
            continue
        try:
            src, dst = int(ev["src"]), int(ev["dst"])
        except (KeyError, TypeError, ValueError):
            continue
        if src == dst or src < 0 or dst < 0:
            continue
        counts[(src, dst)] = counts.get((src, dst), 0) + 1
    return {link for link, n in counts.items() if n >= max(min_reports, 1)}


def links_from_stragglers(report: Mapping,
                          ring_order: Iterable[int],
                          wait_share: float = 0.5) -> set[tuple[int, int]]:
    """Degraded links implied by a straggler report
    (:func:`rabit_tpu.obs.trace.straggler_report`): for each rank whose
    lateness share exceeds ``wait_share``, flag its INCOMING ring link
    under ``ring_order`` — the link whose slowness makes that rank enter
    every collective last."""
    order = [int(r) for r in ring_order]
    if len(order) < 2:
        return set()
    pos = {r: i for i, r in enumerate(order)}
    flagged: set[tuple[int, int]] = set()
    per_rank = report.get("per_rank") or {}
    for rank_s, stats in per_rank.items():
        try:
            rank = int(rank_s)
            share = float(stats.get("lateness_share", 0.0))
        except (TypeError, ValueError):
            continue
        if rank in pos and share >= wait_share:
            prev = order[(pos[rank] - 1) % len(order)]
            flagged.add((prev, rank))
    return flagged


def flags_to_tasks(links: Iterable[tuple[int, int]],
                   rank_map: Mapping[str, int]) -> set[tuple[str, str]]:
    """Rank pairs -> task-id pairs under one epoch's rank map (flags
    survive resizes as task pairs; pairs whose rank left the map drop)."""
    by_rank = {r: t for t, r in rank_map.items()}
    out: set[tuple[str, str]] = set()
    for src, dst in links:
        if src in by_rank and dst in by_rank:
            out.add((by_rank[src], by_rank[dst]))
    return out


def tasks_to_flags(task_links: Iterable[tuple[str, str]],
                   rank_map: Mapping[str, int]) -> set[tuple[int, int]]:
    """Task-id pairs -> rank pairs under a (possibly different) epoch's
    rank map; pairs with a departed task silently drop — a dead worker's
    links no longer exist to avoid."""
    out: set[tuple[int, int]] = set()
    for src_t, dst_t in task_links:
        if src_t in rank_map and dst_t in rank_map:
            out.add((rank_map[src_t], rank_map[dst_t]))
    return out
