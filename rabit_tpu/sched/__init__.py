"""rabit_tpu.sched — topology-aware collective schedule planning
(ISSUE 7 tentpole; doc/scheduling.md).

Three pieces, all pure:

* **mesh** — the interconnect model: ranks row-major on a grid/torus,
  link cost = hop distance (``rabit_sched_mesh`` or near-square auto
  dims);
* **planner** — ``plan(world, algo, mesh, avoid)``: tree/ring (the
  reference's fixed layout), ``swing`` short-cutting rings laid as
  boustrophedon Hamiltonian cycles over the mesh, and a deterministic
  repair pass that rewrites any ring around flagged degraded links.
  Plans are ring ORDERS — the fold stays rank-order, so every schedule
  is bitwise identical;
* **repair** — telemetry consumers turning ``link_degraded`` events and
  straggler analytics into the planner's avoid set, with task-id keyed
  persistence across elastic epochs.

The tracker plans once per wave and ships the plan in the Assignment
TRAILING the rank_map (the native C++ client reads up to the epoch and
never sees it); elastic workers execute whatever ring order they are
handed.  Replanning rides the elastic rewave path, so schedule repair
and shrink/grow share one epoch boundary.
"""

from rabit_tpu.sched.mesh import (  # noqa: F401 (re-exports)
    MeshModel,
    auto_dims,
    mesh_for_world,
    parse_mesh_spec,
)
from rabit_tpu.sched.planner import (  # noqa: F401 (re-exports)
    ALGOS,
    Plan,
    plan,
    repair_ring,
    ring_cost,
    serpentine_order,
    tree_cost,
)
from rabit_tpu.sched.repair import (  # noqa: F401 (re-exports)
    flags_to_tasks,
    links_from_events,
    links_from_stragglers,
    tasks_to_flags,
)


def resolve(cfg) -> dict:
    """Resolve the schedule config keys (doc/parameters.md, "Collective
    schedules") into the tracker/launcher-facing knobs: the algorithm
    name, the mesh spec, whether degraded-link repair replans, and the
    executor's slow-link report threshold."""
    algo = (cfg.get("rabit_schedule", "auto") or "auto").strip().lower()
    if algo not in ALGOS:
        raise ValueError(
            f"rabit_schedule={algo!r} is not one of {'|'.join(ALGOS)}")
    return {
        "schedule": algo,
        "mesh": (cfg.get("rabit_sched_mesh", "") or "").strip(),
        "repair": cfg.get_bool("rabit_sched_repair", True),
        "wait_share": float(
            cfg.get("rabit_sched_wait_share", "0.25") or "0.25"),
    }
