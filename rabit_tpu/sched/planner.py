"""Schedule planner — pure, seed-free, deterministic collective plans.

The tracker already knows the topology (host grouping, mesh dims) and the
obs layer already measures per-link skew; this module is the closing of
that loop (ROADMAP "Topology-aware collective schedules"): given a world
size, an algorithm name, a :class:`~rabit_tpu.sched.mesh.MeshModel`, and
a set of degraded links to avoid, emit a :class:`Plan` every rank can
execute.  Three algorithms behind ``rabit_schedule``:

* ``tree``/``ring`` — the reference's fixed layout: binary-heap tree plus
  the identity ring ``0-1-...-W-1-0``.  The planned ring equals the wire
  prefix the native client already executes, so these modes are
  byte-for-byte the status quo;
* ``swing`` — a short-cutting ring in the spirit of *Swing* (arxiv
  2401.09356): the ring is laid as a **boustrophedon Hamiltonian cycle**
  over the mesh model, so every hop is (near-)nearest-neighbor instead
  of the identity ring's row-return jumps — higher per-step bandwidth on
  a mesh/torus, identical arithmetic;
* ``auto`` — ``swing`` when the mesh model has real extent (>= 2 rows),
  else ``ring``.

A plan is a RING ORDER (a permutation of ranks), never a different
reduction: executors allgather along the planned ring and fold **in rank
order** (rank 0 first — :func:`rabit_tpu.elastic.rebalance.refold`), so
the result is bitwise identical for every ``rabit_schedule`` value, under
recovery replay, and across elastic resizes.  Determinism guarantee: the
planner is a pure function of ``(world, algo, mesh, avoid)`` — same
inputs, same plan, no RNG, no wall clock (doc/scheduling.md).

The **repair pass** (:func:`repair_ring`) rewrites a ring so flagged
directed links ``(src, dst)`` are no longer adjacencies — one slow path
then stops gating every lockstep step (arxiv 2606.01680).  Flags come
from live telemetry: worker ``slow_link`` reports (``link_degraded``
events), or offline straggler analytics (:mod:`rabit_tpu.sched.repair`).
"""

from __future__ import annotations

from dataclasses import dataclass, field

from rabit_tpu.sched.mesh import MeshModel, mesh_for_world

#: The rabit_schedule vocabulary (doc/parameters.md).
ALGOS = ("auto", "tree", "ring", "swing")


@dataclass(frozen=True)
class Plan:
    """One epoch's executable schedule.

    ``ring_order[i]`` is the rank at ring position ``i``; position
    ``i`` sends to position ``i+1 (mod W)``.  ``tree``/``ring`` plans
    carry the identity order.  ``avoided`` lists the degraded links the
    ring was rewritten around; ``residual`` the requested avoids that
    could not be removed (e.g. a 2-world has exactly one ring)."""

    algo: str
    world: int
    ring_order: tuple[int, ...]
    avoided: tuple[tuple[int, int], ...] = field(default_factory=tuple)
    residual: tuple[tuple[int, int], ...] = field(default_factory=tuple)

    @property
    def repaired(self) -> bool:
        """True when the repair pass actually rewrote the ring."""
        return bool(self.avoided)

    def position(self, rank: int) -> int:
        return self.ring_order.index(rank)

    def ring_neighbors(self, rank: int) -> tuple[int, int]:
        """(ring_prev, ring_next) of ``rank`` under the planned order."""
        pos = self.position(rank)
        w = self.world
        return self.ring_order[(pos - 1) % w], self.ring_order[(pos + 1) % w]

    def links(self) -> list[tuple[int, int]]:
        """The W directed ring adjacencies (src, dst) in position order."""
        w = self.world
        return [(self.ring_order[i], self.ring_order[(i + 1) % w])
                for i in range(w)]


def serpentine_order(mesh: MeshModel) -> list[int]:
    """Boustrophedon Hamiltonian cycle over the mesh placement: even rows
    left-to-right, odd rows right-to-left — every intra-row hop is one
    link, every row transition stays in one column, and the closing edge
    is one wrap hop on a torus.  Partial last rows just truncate."""
    order: list[int] = []
    for row in range((mesh.world + mesh.cols - 1) // mesh.cols):
        cols = range(mesh.cols) if row % 2 == 0 else reversed(range(mesh.cols))
        for col in cols:
            rank = row * mesh.cols + col
            if rank < mesh.world:
                order.append(rank)
    return order


def repair_ring(order: list[int] | tuple[int, ...],
                avoid: set[tuple[int, int]]) -> tuple[list[int],
                                                      list[tuple[int, int]]]:
    """Rewrite ``order`` so no directed adjacency is in ``avoid``.

    Deterministic greedy: take the first violating adjacency ``(a, b)``
    and swap ``b`` with the first other position that strictly reduces
    the violation count; repeat up to ``W`` passes.  Returns the repaired
    order and the residual violations (empty when fully repaired —
    always achievable for ``W >= 3`` with a single flagged link; a
    2-world has exactly one ring and cannot reroute)."""
    order = list(order)
    w = len(order)
    avoid = {(int(a), int(b)) for a, b in avoid}

    def violations(o: list[int]) -> list[int]:
        return [i for i in range(w) if (o[i], o[(i + 1) % w]) in avoid]

    for _ in range(w):
        viol = violations(order)
        if not viol:
            break
        i = viol[0]
        j_bad = (i + 1) % w
        fixed = False
        for j in range(w):
            if j in (i, j_bad):
                continue
            cand = list(order)
            cand[j_bad], cand[j] = cand[j], cand[j_bad]
            if len(violations(cand)) < len(viol):
                order = cand
                fixed = True
                break
        if not fixed:
            break  # no single swap helps; report the residual honestly
    residual = [(order[i], order[(i + 1) % w]) for i in violations(order)]
    return order, residual


def plan(world: int, algo: str = "auto", mesh: MeshModel | None = None,
         avoid: set[tuple[int, int]] | frozenset | None = None) -> Plan:
    """The one planning entry point (tracker, benches, tests).

    ``avoid`` is a set of degraded directed links ``(src_rank,
    dst_rank)``; the repair pass runs for every algorithm (the identity
    ring reroutes too — a degraded link is a fault, not a layout
    preference)."""
    if world < 1:
        raise ValueError(f"world must be >= 1, got {world}")
    if algo not in ALGOS:
        raise ValueError(f"unknown schedule {algo!r} (want one of {ALGOS})")
    if mesh is None:
        mesh = mesh_for_world(world)
    if mesh.world != world:
        raise ValueError(f"mesh models world {mesh.world}, planning {world}")
    resolved = algo
    if algo == "auto":
        resolved = "swing" if mesh.rows >= 2 else "ring"
    if resolved == "swing":
        base = serpentine_order(mesh)
    else:  # tree | ring: the reference's identity ring
        base = list(range(world))
    avoid = {(int(a), int(b)) for a, b in (avoid or ())
             if 0 <= int(a) < world and 0 <= int(b) < world
             and int(a) != int(b)}
    if avoid:
        order, residual = repair_ring(base, avoid)
    else:
        order, residual = base, []
    base_links = {(base[i], base[(i + 1) % world]) for i in range(world)}
    final_links = {(order[i], order[(i + 1) % world]) for i in range(world)}
    avoided = sorted((avoid & base_links) - final_links)
    return Plan(
        algo=resolved,
        world=world,
        ring_order=tuple(order),
        avoided=tuple(avoided),
        residual=tuple(sorted(residual)),
    )


# -- cost model (the bench's alpha model) -------------------------------------

def ring_cost(order: list[int] | tuple[int, ...], mesh: MeshModel,
              slow: dict[tuple[int, int], float] | None = None) -> dict:
    """Per-step cost of a lockstep ring schedule under the mesh model.

    Every ring step uses ALL W links simultaneously (each position sends
    to the next), so the step time is gated by the slowest link:
    ``max_hops`` (times any ``slow`` multiplier on degraded links).  One
    allreduce round runs ``W - 1`` steps -> ``round_cost = (W - 1) *
    max_link_cost``; ``total_hops`` tracks aggregate wire occupancy."""
    w = len(order)
    slow = slow or {}
    link_costs = []
    for i in range(w):
        src, dst = order[i], order[(i + 1) % w]
        link_costs.append(mesh.hops(src, dst) * float(slow.get((src, dst),
                                                               1.0)))
    max_cost = max(link_costs) if link_costs else 0.0
    return {
        "total_hops": sum(mesh.hops(order[i], order[(i + 1) % w])
                          for i in range(w)),
        "max_link_cost": max_cost,
        "round_cost": (w - 1) * max_cost if w > 1 else 0.0,
    }


def tree_cost(world: int, mesh: MeshModel) -> dict:
    """Cost of the fixed binary-heap tree on the mesh: per-edge hop
    distances (parent ``(r-1)//2``), the tree depth, and the critical
    path a depth-pipelined reduce pays (``depth * max_edge_hops``).  The
    heap tree is placement-blind — edge ``(r, 2r+1)`` spans ~r cells of
    the row-major layout, which is exactly why its mesh cost explodes
    with world size while the planned rings stay flat."""
    edges = [(r, (r - 1) // 2) for r in range(1, world)]
    hops = [mesh.hops(a, b) for a, b in edges]
    depth = 0
    n = world
    while n > 1:
        depth += 1
        n //= 2
    return {
        "depth": depth,
        "max_edge_hops": max(hops) if hops else 0,
        "total_hops": sum(hops),
        "critical_path": depth * (max(hops) if hops else 0),
    }
