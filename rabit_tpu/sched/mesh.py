"""Mesh model — the planner's picture of the physical interconnect.

The tracker lays ranks onto hosts (assign_ranks host grouping,
tpu_slice_host_order), but the data plane then runs the reference's one
fixed tree+ring REGARDLESS of where those ranks sit.  Swing-style ring
planning (arxiv 2401.09356) starts from a topology model: ranks placed on
a 2-D grid/torus, link cost = hop distance between placements.  This
module is that model, deliberately tiny and pure:

* ranks are placed **row-major** on a ``rows x cols`` grid — matching the
  tracker's host-grouped rank order (consecutive ranks share a host /
  mesh row, exactly the layout ``TPU_WORKER_HOSTNAMES`` walks);
* ``hops(a, b)`` is the Manhattan distance between placements, with
  per-axis wraparound when the mesh is a torus (``wrap=True``, the TPU
  slice shape) — the store-and-forward cost of one message on the
  bench's alpha model;
* dims come from an explicit ``"RxC"`` spec (``rabit_sched_mesh``) or a
  near-square factorization of the world size, so the planner always has
  SOME model to optimize against (a 1 x W "mesh" degrades every planned
  ring to the identity ring — nothing gets worse than the status quo).

Everything downstream (planner schedules, repair rewrites, the
consensus_bench ablation) consumes only ``coords``/``hops``; swapping in
a measured topology later only has to reproduce this interface.
"""

from __future__ import annotations

import math
from dataclasses import dataclass


@dataclass(frozen=True)
class MeshModel:
    """A ``rows x cols`` grid (torus when ``wrap``) holding ``world``
    ranks row-major.  ``rows * cols >= world``; trailing cells of the
    last row may be empty (non-rectangular worlds)."""

    world: int
    rows: int
    cols: int
    wrap: bool = True

    def __post_init__(self):
        if self.world < 1:
            raise ValueError(f"world must be >= 1, got {self.world}")
        if self.rows < 1 or self.cols < 1:
            raise ValueError(f"bad mesh dims {self.rows}x{self.cols}")
        if self.rows * self.cols < self.world:
            raise ValueError(
                f"mesh {self.rows}x{self.cols} too small for world "
                f"{self.world}")

    def coords(self, rank: int) -> tuple[int, int]:
        """(row, col) of ``rank`` under the row-major placement."""
        if not 0 <= rank < self.world:
            raise ValueError(f"rank {rank} outside 0..{self.world - 1}")
        return divmod(rank, self.cols)

    def _axis_dist(self, a: int, b: int, extent: int) -> int:
        d = abs(a - b)
        return min(d, extent - d) if self.wrap and extent > 1 else d

    def hops(self, a: int, b: int) -> int:
        """ICI hop distance between two ranks' placements (0 for a==b)."""
        (ra, ca), (rb, cb) = self.coords(a), self.coords(b)
        return (self._axis_dist(ra, rb, self.rows)
                + self._axis_dist(ca, cb, self.cols))


def auto_dims(world: int) -> tuple[int, int]:
    """Near-square ``rows x cols`` with ``rows * cols == world`` — rows is
    the largest divisor of ``world`` not exceeding sqrt(world) (primes
    degrade to 1 x W, where every planned ring equals the identity ring)."""
    rows = 1
    for r in range(int(math.isqrt(world)), 0, -1):
        if world % r == 0:
            rows = r
            break
    return rows, world // rows


def parse_mesh_spec(spec: str) -> tuple[int, int, bool] | None:
    """Parse a ``rabit_sched_mesh`` value: ``"RxC"`` (torus) or
    ``"RxC:nowrap"`` (open grid).  Empty/whitespace -> None (auto dims).
    Malformed specs raise — a typo'd topology must not silently plan
    against the wrong machine."""
    spec = (spec or "").strip().lower()
    if not spec:
        return None
    wrap = True
    if spec.endswith(":nowrap"):
        wrap = False
        spec = spec[: -len(":nowrap")]
    try:
        rows_s, cols_s = spec.split("x", 1)
        rows, cols = int(rows_s), int(cols_s)
    except ValueError:
        raise ValueError(f"bad mesh spec {spec!r} (want 'RxC[:nowrap]')")
    if rows < 1 or cols < 1:
        raise ValueError(f"bad mesh spec {spec!r} (dims must be >= 1)")
    return rows, cols, wrap


def mesh_for_world(world: int, spec: str = "") -> MeshModel:
    """The planner's mesh for ``world`` ranks: explicit dims from
    ``spec`` when given (and large enough), else the near-square auto
    factorization."""
    parsed = parse_mesh_spec(spec)
    if parsed is not None:
        rows, cols, wrap = parsed
        if rows * cols >= world:
            return MeshModel(world, rows, cols, wrap)
        # an explicit spec the CURRENT world outgrew (elastic grow past
        # the configured slice): fall back to auto dims rather than fail
        # a recovery wave over a stale operator hint
    rows, cols = auto_dims(world)
    return MeshModel(world, rows, cols, True)
