"""Public module-level API.

Parity surface with the reference Python binding
(``/root/reference/python/rabit.py``) plus ``allgather`` and
``lazy_checkpoint`` which the reference exposes only at the C++ layer
(rabit.h:224-232, :311-332).  Objects are pickled for broadcast/checkpoint
exactly as the reference does (python/rabit.py:171-206, :320-351); allreduce
takes numpy arrays with the same dtype/op enums.

Caller-site capture: the reference records ``__builtin_FILE()/LINE()`` of the
caller as the bootstrap-cache key for every collective (rabit.h:29-37).  The
Python equivalent reads the caller frame via ``sys._getframe`` and passes
``file:line:function`` down to the engine as ``cache_key``.
"""

from __future__ import annotations

import pickle
import sys
from typing import Any, Callable

import numpy as np

from rabit_tpu.config import Config
from rabit_tpu.engine import create_engine
from rabit_tpu.engine.base import MAX, MIN, SUM, BITOR, DTYPE_ENUM, Engine
import time

from rabit_tpu.profile import GLOBAL_STATS, CollectiveStats, OpStats

_engine: Engine | None = None


def collective_stats() -> CollectiveStats:
    """Accumulated per-collective timing for this process (see
    rabit_tpu.profile; the Python-layer analogue of the reference's
    rabit_debug/report_stats observability)."""
    return GLOBAL_STATS


def reset_collective_stats() -> None:
    GLOBAL_STATS.reset()


def _caller_key(depth: int = 2) -> str:
    frame = sys._getframe(depth)
    return f"{frame.f_code.co_filename}::{frame.f_lineno}::{frame.f_code.co_name}"


def _get_engine() -> Engine:
    """Return the active engine; like the reference (engine.cc:71-82), an
    uninitialized process gets a solo engine so single-process programs work
    with zero config."""
    global _engine
    if _engine is None:
        from rabit_tpu.engine.empty import SoloEngine

        _engine = SoloEngine(Config([]))
        # A zero-config engine is provisional: an explicit init() later may
        # still replace it (mirrors the reference's uninitialized static
        # engine, engine.cc:71-82).
        _engine._provisional = True
    return _engine


def init(args: list[str] | None = None, **overrides: Any) -> None:
    """Initialize the engine.  ``args`` are ``"key=value"`` strings (defaults
    to ``sys.argv[1:]``); keyword overrides win over args, args win over env
    vars (see rabit_tpu.config)."""
    global _engine
    if _engine is not None:
        if getattr(_engine, "_provisional", False):
            _engine = None
        else:
            import warnings

            warnings.warn("rabit_tpu.init ignored: already initialized", stacklevel=2)
            return
    if args is None:
        args = [a for a in sys.argv[1:] if "=" in a]
    args = [a.decode() if isinstance(a, bytes) else a for a in args]
    cfg = Config(args, {k: str(v) for k, v in overrides.items()})
    _engine = create_engine(cfg)
    _engine.init()


def finalize() -> None:
    """Shut down the engine (reference: RabitFinalize)."""
    global _engine
    if _engine is not None:
        _engine.shutdown()
        _engine = None


def get_rank() -> int:
    return _get_engine().get_rank()


def get_world_size() -> int:
    return _get_engine().get_world_size()


def is_distributed() -> bool:
    return _get_engine().is_distributed()


def tracker_print(msg: str) -> None:
    """Send a message to the tracker console (reference: TrackerPrint)."""
    if not isinstance(msg, str):
        msg = str(msg)
    _get_engine().tracker_print(msg)


def get_processor_name() -> str:
    return _get_engine().get_host()


def broadcast(data: Any, root: int) -> Any:
    """Broadcast any picklable object from ``root``.  Two-phase
    length-then-payload, like the reference (python/rabit.py:171-206)."""
    engine = _get_engine()
    key = _caller_key()
    rank = engine.get_rank()
    payload = None
    if rank == root:
        if data is None:
            raise ValueError("need to pass in data when broadcasting")
        payload = pickle.dumps(data, protocol=pickle.HIGHEST_PROTOCOL)
    t0 = time.perf_counter()
    out = engine.broadcast(payload, root, cache_key=key)
    nbytes = len(payload) if payload is not None else len(out) if out else 0
    GLOBAL_STATS.ops.setdefault("broadcast", OpStats()).add(
        nbytes, time.perf_counter() - t0
    )
    return data if rank == root else pickle.loads(out)


def allreduce(
    data: np.ndarray,
    op: int,
    prepare_fun: Callable[[np.ndarray], None] | None = None,
) -> np.ndarray:
    """Allreduce a numpy array.  ``op`` is one of MAX/MIN/SUM/BITOR.
    ``prepare_fun(data)`` is called lazily right before the reduction and is
    skipped when the result is recovered from a peer's replay buffer
    (reference semantics, python/rabit.py:220-263)."""
    if not isinstance(data, np.ndarray):
        raise TypeError("allreduce only takes numpy ndarrays")
    if data.dtype not in DTYPE_ENUM:
        raise TypeError(f"dtype {data.dtype} not supported")
    if op not in (MAX, MIN, SUM, BITOR):
        raise ValueError(f"unknown reduction op {op}")
    buf = data.flatten()  # always a fresh 1-D C-order copy
    shape = data.shape
    if prepare_fun is not None:
        orig_prepare = prepare_fun

        def prepare_fun(buf_view: np.ndarray) -> None:  # type: ignore[misc]
            orig_prepare(data)
            buf_view[...] = np.ascontiguousarray(data).reshape(-1)

    # NOTE: the timed window includes a lazy prepare_fun's execution (it
    # runs inside the engine, interleaved with recovery decisions), so
    # expensive preparation shows up as allreduce latency in the stats.
    with GLOBAL_STATS.timed("allreduce", buf.nbytes):
        out = _get_engine().allreduce(
            buf, op, prepare_fun=prepare_fun, cache_key=_caller_key()
        )
    return np.asarray(out).reshape(shape)


def allgather(data: np.ndarray) -> np.ndarray:
    """Gather this rank's array from every rank; returns shape
    ``(world_size,) + data.shape``."""
    if not isinstance(data, np.ndarray):
        raise TypeError("allgather only takes numpy ndarrays")
    engine = _get_engine()
    flat = np.ascontiguousarray(data).reshape(-1)
    with GLOBAL_STATS.timed("allgather", flat.nbytes):
        out = engine.allgather(flat, cache_key=_caller_key())
    return np.asarray(out).reshape((engine.get_world_size(),) + data.shape)


def load_checkpoint(with_local: bool = False):
    """Load the latest checkpoint.  Returns ``(version, global_model)`` or
    ``(version, global_model, local_model)``; version 0 means nothing has
    been checkpointed yet."""
    version, gblob, lblob = _get_engine().load_checkpoint()
    gmodel = pickle.loads(gblob) if version > 0 and gblob is not None else None
    if with_local:
        lmodel = pickle.loads(lblob) if version > 0 and lblob is not None else None
        return version, gmodel, lmodel
    return version, gmodel


def checkpoint(global_model: Any, local_model: Any = None) -> None:
    """Commit an iteration: pickle and store the models, bump the version.
    ``local_model`` (rank-specific state) costs ring replication; prefer
    ``global_model`` (reference notes, python/rabit.py:320-351)."""
    gblob = pickle.dumps(global_model, protocol=pickle.HIGHEST_PROTOCOL)
    lblob = None if local_model is None else pickle.dumps(local_model, protocol=pickle.HIGHEST_PROTOCOL)
    _get_engine().checkpoint(gblob, lblob)


def lazy_checkpoint(global_model: Any) -> None:
    """Checkpoint without eager serialization: the model is only pickled if a
    failure actually needs the blob.  Contract (reference rabit.h:311-332):
    ``global_model`` must stay unchanged until the NEXT checkpoint call
    RETURNS — recovery during that next call's pre-commit consensus can
    still serve this version through this call's callback.  Rebind a fresh
    object per iteration rather than mutating in place."""
    _get_engine().lazy_checkpoint(
        lambda: pickle.dumps(global_model, protocol=pickle.HIGHEST_PROTOCOL)
    )


def version_number() -> int:
    return _get_engine().version_number()
