"""Public module-level API.

Parity surface with the reference Python binding
(``/root/reference/python/rabit.py``) plus ``allgather`` and
``lazy_checkpoint`` which the reference exposes only at the C++ layer
(rabit.h:224-232, :311-332).  Objects are pickled for broadcast/checkpoint
exactly as the reference does (python/rabit.py:171-206, :320-351); allreduce
takes numpy arrays with the same dtype/op enums.

Caller-site capture: the reference records ``__builtin_FILE()/LINE()`` of the
caller as the bootstrap-cache key for every collective (rabit.h:29-37).  The
Python equivalent reads the caller frame via ``sys._getframe`` and passes
``file:line:function`` down to the engine as ``cache_key``.
"""

from __future__ import annotations

import pickle
import sys
from typing import Any, Callable

import numpy as np

from rabit_tpu import compress, obs, quorum
from rabit_tpu.config import Config
from rabit_tpu.engine import create_engine
from rabit_tpu.engine.base import MAX, MIN, SUM, BITOR, DTYPE_ENUM, Engine
from rabit_tpu.profile import GLOBAL_STATS, CollectiveStats

_engine: Engine | None = None
# Durable-spill state (rabit_checkpoint_dir): the store, and the user-visible
# version base when this job resumed a previous job's disk checkpoints.  The
# base also travels inside every wrapped global blob (_wrap/_unwrap), so a
# worker restarted mid-job recovers it from the peer-served blob rather than
# from process memory.
_ckpt_store = None
_ckpt_base = 0

# Delivery-plane publisher (rabit_tpu/delivery, doc/delivery.md): built at
# init() on rank 0 when rabit_delivery_publish=1, it registers every
# checkpoint commit as a content-addressed snapshot with the tracker.
_publisher = None

# Elastic-world state (rabit_tpu/elastic, doc/elasticity.md): the world
# epoch this process last adopted, and the shard-rebalance callbacks run
# when it changes.  The epoch is stamped into durable checkpoint frames
# (RTC3) so replay stays deterministic across a resize.
_world_epoch: dict = {"epoch": 0, "world_size": 1}
_rebalance_cbs: list[Callable[[dict, dict], None]] = []

_WRAP_TAG = "__rabit_tpu_ckpt1__"


def _wrap(base: int, gblob: bytes) -> bytes:
    return pickle.dumps((_WRAP_TAG, base, gblob), protocol=pickle.HIGHEST_PROTOCOL)


def _unwrap(blob: bytes) -> tuple[int, bytes]:
    """Returns (base, inner_blob); plain blobs (store off) pass through."""
    try:
        obj = pickle.loads(blob)
    except Exception:  # noqa: BLE001 — not a pickle we wrote
        return 0, blob
    if isinstance(obj, tuple) and len(obj) == 3 and obj[0] == _WRAP_TAG:
        return int(obj[1]), obj[2]
    return 0, blob


def collective_stats() -> CollectiveStats:
    """Accumulated per-collective timing for this process (see
    rabit_tpu.profile; the Python-layer analogue of the reference's
    rabit_debug/report_stats observability).  The full registry — named
    counters/gauges/histograms — is ``collective_stats().registry`` or
    ``rabit_tpu.obs.get_registry()``."""
    return GLOBAL_STATS


def reset_collective_stats() -> None:
    GLOBAL_STATS.reset()


def _caller_key(depth: int = 2) -> str:
    frame = sys._getframe(depth)
    return f"{frame.f_code.co_filename}::{frame.f_lineno}::{frame.f_code.co_name}"


def _get_engine() -> Engine:
    """Return the active engine; like the reference (engine.cc:71-82), an
    uninitialized process gets a solo engine so single-process programs work
    with zero config."""
    global _engine
    if _engine is None:
        from rabit_tpu.engine.empty import SoloEngine

        _engine = SoloEngine(Config([]))
        # A zero-config engine is provisional: an explicit init() later may
        # still replace it (mirrors the reference's uninitialized static
        # engine, engine.cc:71-82).
        _engine._provisional = True
    return _engine


def init(args: list[str] | None = None, **overrides: Any) -> None:
    """Initialize the engine.  ``args`` are ``"key=value"`` strings (defaults
    to ``sys.argv[1:]``); keyword overrides win over args, args win over env
    vars (see rabit_tpu.config)."""
    global _engine
    if _engine is not None:
        if getattr(_engine, "_provisional", False):
            _engine = None
        else:
            import warnings

            warnings.warn("rabit_tpu.init ignored: already initialized", stacklevel=2)
            return
    if args is None:
        args = [a for a in sys.argv[1:] if "=" in a]
    args = [a.decode() if isinstance(a, bytes) else a for a in args]
    cfg = Config(args, {k: str(v) for k, v in overrides.items()})
    # Quorum policy (rabit_tpu/quorum, doc/partial_allreduce.md): resolve
    # BEFORE any engine spins up, so a typo'd rabit_quorum fails loudly
    # with nothing to tear down.
    qpol = quorum.resolve(cfg)
    _engine = create_engine(cfg)
    _engine.init()
    # Observability wiring: flight recorder capacity, hang/SIGTERM dump
    # paths (RABIT_OBS_DIR), metric shipping identity (see rabit_tpu.obs).
    obs.configure(cfg, rank=_engine.get_rank())
    # Compression policy (rabit_tpu/compress, doc/compression.md): the
    # rabit_compress_* keys resolve once per init; the resolved policy is
    # recorded so a cross-rank config skew is visible in the dumps.
    pol = compress.configure(cfg)
    obs.record_event(
        "compress_policy",
        allreduce=pol.allreduce or "identity",
        min_bytes=pol.min_bytes,
        wire_deflate=pol.wire_deflate,
        broadcast=pol.broadcast or "identity",
        checkpoint=pol.checkpoint or "identity",
        fused=pol.fused,
        fused_chunk_kib=pol.fused_chunk_kib,
    )
    # Record the resolved quorum policy so a cross-rank config skew is
    # visible in the dumps.  The engines' own collectives stay exact —
    # the quorum data plane is the tracker + schedule-aware executor
    # contract (ElasticWorker), the same seam the planned rings ride.
    if qpol["quorum"]:
        obs.record_event(
            "quorum_policy",
            quorum=qpol["quorum"],
            wait_sec=qpol["wait_sec"],
            flag_after=qpol["flag_after"],
        )
    obs.record_event(
        "engine_ready",
        engine=type(_engine).__name__,
        rank=_engine.get_rank(),
        world=_engine.get_world_size(),
    )
    global _ckpt_store, _ckpt_base, _world_epoch, _publisher
    _ckpt_base = 0
    _world_epoch = {"epoch": 0, "world_size": _engine.get_world_size()}
    ckpt_dir = cfg.get("rabit_checkpoint_dir", "") or ""
    if ckpt_dir and ckpt_dir != "NULL":
        from rabit_tpu.store import CheckpointStore

        _ckpt_store = CheckpointStore(ckpt_dir, _engine.get_rank(),
                                      codec=pol.checkpoint)
    else:
        _ckpt_store = None
    # Delivery plane (doc/delivery.md): rank 0 publishes each commit's
    # bytes content-addressed through the tracker.  Only the committing
    # rank publishes — every rank holds the same global blob, and N
    # identical publishes would be N redundant digest registrations.
    _publisher = None
    uri = cfg.get("rabit_tracker_uri", "NULL") or "NULL"
    if (cfg.get_bool("rabit_delivery_publish") and uri != "NULL"
            and _engine.get_rank() == 0):
        from rabit_tpu.delivery import Publisher
        from rabit_tpu.tracker.protocol import parse_addrs

        _publisher = Publisher(
            uri, cfg.get_int("rabit_tracker_port", 9091),
            job=cfg.get("rabit_job_key", "") or "",
            task_id=f"pub-{cfg.get('rabit_task_id', '0')}",
            addrs=parse_addrs(cfg.get("rabit_tracker_addrs", "") or ""),
        )


def finalize() -> None:
    """Shut down the engine (reference: RabitFinalize).  Ships the final
    metrics snapshot to the tracker first — the tracker keeps serving until
    every rank's shutdown handshake, so the snapshot always lands."""
    global _engine, _ckpt_store, _ckpt_base, _world_epoch, _publisher
    if _engine is not None:
        obs.ship_final_snapshot()
        obs.record_event("engine_finalize", engine=type(_engine).__name__)
        _engine.shutdown()
        _engine = None
        # rabit_trace_exit=1: leave this life's ring as a -exit flight dump
        # so the cross-rank trace merger has per-rank evidence of CLEAN runs
        obs.dump_final()
    compress.reset()
    _ckpt_store = None
    _ckpt_base = 0
    _world_epoch = {"epoch": 0, "world_size": 1}
    _publisher = None


def world_epoch() -> dict:
    """The world epoch this process last adopted: ``{"epoch", "world_size"}``
    (doc/elasticity.md).  Epoch 0 / the engine's world until an elastic
    resize is observed."""
    return dict(_world_epoch)


def register_rebalance(callback: Callable[[dict, dict], None]) -> None:
    """Register a shard-rebalance callback ``callback(old, new)`` invoked
    whenever this process adopts a new world epoch (``old``/``new`` are
    ``world_epoch()``-shaped dicts).  The GBDT histogram deployment re-cuts
    its data shard here (``models.gbdt.elastic_shard`` /
    ``elastic.rebalance.shard_slice``) so the fold keeps covering the whole
    dataset around the hole.  Callbacks must be idempotent; exceptions
    propagate to the notifier."""
    if callback not in _rebalance_cbs:
        _rebalance_cbs.append(callback)


def unregister_rebalance(callback: Callable[[dict, dict], None]) -> None:
    try:
        _rebalance_cbs.remove(callback)
    except ValueError:
        pass


def notify_world_change(epoch: int, world_size: int) -> None:
    """Adopt a new world epoch: record it (checkpoint frames stamp it from
    here), emit the ``epoch_changed``/``shard_rebalanced`` evidence, and
    run the registered rebalance callbacks."""
    global _world_epoch
    old = dict(_world_epoch)
    if epoch == old["epoch"] and world_size == old["world_size"]:
        return
    _world_epoch = {"epoch": int(epoch), "world_size": int(world_size)}
    obs.record_event("epoch_changed", epoch=int(epoch),
                     world=int(world_size), prev_world=old["world_size"])
    for cb in list(_rebalance_cbs):
        cb(old, dict(_world_epoch))
    if _rebalance_cbs:
        obs.record_event("shard_rebalanced", epoch=int(epoch),
                         callbacks=len(_rebalance_cbs))


def rebootstrap() -> dict:
    """Re-enter the tracker after a world-epoch change: the native engine
    finalizes and re-bootstraps (fresh assignment, possibly a different
    world), the XLA engine rebuilds its process mesh, and the adopted
    epoch is bumped so rebalance callbacks and checkpoint stamps follow.
    Returns the new ``world_epoch()``."""
    engine = _get_engine()
    if hasattr(engine, "rebootstrap"):
        engine.rebootstrap()
    elif hasattr(engine, "rebuild_mesh"):
        engine.rebuild_mesh()
    notify_world_change(_world_epoch["epoch"] + 1, engine.get_world_size())
    return world_epoch()


def get_rank() -> int:
    return _get_engine().get_rank()


def get_world_size() -> int:
    return _get_engine().get_world_size()


def is_distributed() -> bool:
    return _get_engine().is_distributed()


def tracker_print(msg: str) -> None:
    """Send a message to the tracker console (reference: TrackerPrint)."""
    if not isinstance(msg, str):
        msg = str(msg)
    _get_engine().tracker_print(msg)


def get_processor_name() -> str:
    return _get_engine().get_host()


def broadcast(data: Any, root: int) -> Any:
    """Broadcast any picklable object from ``root``.  Two-phase
    length-then-payload, like the reference (python/rabit.py:171-206).

    With ``rabit_compress_broadcast`` configured (e.g. ``zlib``), the
    pickled payload crosses the wire compressed behind a one-byte codec
    frame; payloads under ``rabit_compress_min_bytes`` ride as identity.
    The policy comes from the shared job config, so every rank frames and
    deframes symmetrically."""
    engine = _get_engine()
    key = _caller_key()
    rank = engine.get_rank()
    pol = compress.policy()
    bcodec = compress.get_codec(pol.broadcast) if pol.broadcast else None
    payload = None
    if rank == root:
        if data is None:
            raise ValueError("need to pass in data when broadcasting")
        payload = pickle.dumps(data, protocol=pickle.HIGHEST_PROTOCOL)
        if bcodec is not None:
            if len(payload) >= pol.min_bytes:
                wire = bcodec.encode_bytes(payload)
                compress.observe(bcodec.name, raw=len(payload),
                                 wire=len(wire))
                payload = bytes([bcodec.codec_id]) + wire
            else:
                payload = bytes([0]) + payload  # identity frame
    # Same timed/evented path as allreduce/allgather; a non-root only
    # learns the payload length from the wire, so the span's byte count is
    # set inside the window.
    with obs.collective(
        "broadcast", len(payload) if payload is not None else 0,
        cache_key=key, codec=bcodec.name if bcodec is not None else None,
    ) as span:
        out = engine.broadcast(payload, root, cache_key=key)
        span.nbytes = (len(payload) if payload is not None
                       else len(out) if out else 0)
    if rank == root:
        return data
    if bcodec is not None:
        out = bytes(out)
        out = compress.get_codec_by_id(out[0]).decode_bytes(out[1:])
    return pickle.loads(out)


def allreduce(
    data: np.ndarray,
    op: int,
    prepare_fun: Callable[[np.ndarray], None] | None = None,
    codec: str | None = None,
) -> np.ndarray:
    """Allreduce a numpy array.  ``op`` is one of MAX/MIN/SUM/BITOR.
    ``prepare_fun(data)`` is called lazily right before the reduction and is
    skipped when the result is recovered from a peer's replay buffer
    (reference semantics, python/rabit.py:220-263).

    ``codec`` selects a wire codec (rabit_tpu.compress; doc/compression.md)
    for this call: the payload crosses the engine encoded and every rank
    decodes/folds identically, trading the codec's documented error bound
    for wire bytes.  ``None`` applies the ``rabit_compress_allreduce``
    policy (float32, non-BITOR payloads of at least
    ``rabit_compress_min_bytes``); ``"identity"`` forces the exact path.
    On the compressed path ``prepare_fun`` runs eagerly — its output feeds
    the encoder."""
    if not isinstance(data, np.ndarray):
        raise TypeError("allreduce only takes numpy ndarrays")
    if data.dtype not in DTYPE_ENUM:
        raise TypeError(f"dtype {data.dtype} not supported")
    if op not in (MAX, MIN, SUM, BITOR):
        raise ValueError(f"unknown reduction op {op}")
    buf = data.flatten()  # always a fresh 1-D C-order copy
    shape = data.shape
    if prepare_fun is not None:
        orig_prepare = prepare_fun

        def prepare_fun(buf_view: np.ndarray) -> None:  # type: ignore[misc]
            orig_prepare(data)
            buf_view[...] = np.ascontiguousarray(data).reshape(-1)

    c = compress.resolve(codec, buf.dtype, op, buf.nbytes)
    # NOTE: the timed window includes a lazy prepare_fun's execution (it
    # runs inside the engine, interleaved with recovery decisions), so
    # expensive preparation shows up as allreduce latency in the stats.
    key = _caller_key()
    if c is None:
        with obs.collective("allreduce", buf.nbytes, cache_key=key):
            out = _get_engine().allreduce(
                buf, op, prepare_fun=prepare_fun, cache_key=key
            )
    else:
        engine = _get_engine()
        with obs.collective("allreduce", buf.nbytes, cache_key=key,
                            codec=c.name,
                            fused=engine.fused_active(c, op)):
            out = engine.allreduce_compressed(
                buf, op, c, prepare_fun=prepare_fun, cache_key=key
            )
    return np.asarray(out).reshape(shape)


def allgather(data: np.ndarray) -> np.ndarray:
    """Gather this rank's array from every rank; returns shape
    ``(world_size,) + data.shape``."""
    if not isinstance(data, np.ndarray):
        raise TypeError("allgather only takes numpy ndarrays")
    engine = _get_engine()
    flat = np.ascontiguousarray(data).reshape(-1)
    key = _caller_key()
    with obs.collective("allgather", flat.nbytes, cache_key=key):
        out = engine.allgather(flat, cache_key=key)
    return np.asarray(out).reshape((engine.get_world_size(),) + data.shape)


def _disk_resume():
    """Fresh-cluster disk resume (store configured, engine version 0).

    Every first-life worker runs this IDENTICAL deterministic collective
    sequence (decisions depend only on collective results, which agree on
    all ranks), so the robust engine's replay contract holds; a worker
    restarted before the first checkpoint re-enters this same path, and
    one restarted after sees engine version > 0 and never comes here.

    Returns (base_version, gblob, lblob) — (0, None, None) when there is
    nothing on disk anywhere."""
    engine = _get_engine()
    mine = np.array([_ckpt_store.latest_valid()], np.int64)
    vmax = int(engine.allreduce(mine, MAX, cache_key="rabit_tpu.store::vmax")[0])
    if vmax <= 0:
        return 0, None, None
    have = int(_ckpt_store.has(vmax))
    all_have = int(
        engine.allreduce(np.array([have], np.int64), MIN,
                         cache_key="rabit_tpu.store::have")[0]
    )
    if all_have:
        return vmax, _ckpt_store.load_global(vmax), _ckpt_store.load_local(vmax)
    # Someone's disk copy is missing/stale: the lowest-ranked holder serves
    # the (rank-identical) global blob over a broadcast.  Rank-specific
    # local models cannot be served this way; a rank without its own file
    # resumes with local_model=None (warned below — the caller must be able
    # to rebuild rank-local state, see doc/guide.md "Durable spill").
    if not have:
        import warnings

        warnings.warn(
            f"rabit_tpu durable resume: rank {engine.get_rank()} has no "
            f"valid disk checkpoint for v{vmax} (killed between the commit "
            "barrier and its disk save?); the global model is served by a "
            "peer but any rank-local model is LOST — load_checkpoint will "
            "return local_model=None and the caller must rebuild it",
            stacklevel=3,
        )
    world = engine.get_world_size()
    root = int(
        engine.allreduce(
            np.array([engine.get_rank() if have else world], np.int64), MIN,
            cache_key="rabit_tpu.store::root")[0]
    )
    # The recovery/bootstrap blob crosses the wire zlib-compressed (both
    # ends run this same code, so no frame negotiation is needed; the
    # holder's own broadcast-return decompresses identically).
    zcodec = compress.get_codec("zlib")
    wireblob = engine.broadcast(
        zcodec.encode_bytes(_ckpt_store.load_global(vmax))
        if engine.get_rank() == root else None,
        root, cache_key="rabit_tpu.store::blob",
    )
    gblob = zcodec.decode_bytes(bytes(wireblob))
    compress.observe(zcodec.name, raw=len(gblob), wire=len(wireblob))
    obs.record_event("recovery_blob_compressed", raw=len(gblob),
                     wire=len(wireblob), version=vmax)
    lblob = _ckpt_store.load_local(vmax) if have else None
    return vmax, bytes(gblob), lblob


def load_checkpoint(with_local: bool = False):
    """Load the latest checkpoint.  Returns ``(version, global_model)`` or
    ``(version, global_model, local_model)``; version 0 means nothing has
    been checkpointed yet.  With ``rabit_checkpoint_dir`` configured, a
    fresh cluster first agrees on and resumes from the newest disk
    checkpoint (whole-job preemption durability)."""
    global _ckpt_base
    version, gblob, lblob = _get_engine().load_checkpoint()
    if _ckpt_store is not None:
        if version == 0:
            vmax, dgblob, dlblob = _disk_resume()
            if vmax > 0:
                # Resuming a PREVIOUS job: the file's version is the new
                # base; the wrapper inside carries the old job's base and
                # is discarded.
                _ckpt_base = vmax
                _, gblob = _unwrap(dgblob)
                lblob = dlblob
                version = vmax
        else:
            # Peer-served blob from the CURRENT job: its wrapper carries
            # this job's base (authoritative for a restarted worker, whose
            # process state starts empty).
            _ckpt_base, gblob = _unwrap(gblob)
            version = _ckpt_base + version
    # Cross-rank collective numbering (obs/trace.py): landing on version V
    # resets the per-version seqno exactly like the survivors' commit of V
    # did, so a restarted worker resumes the shared (version, seqno) line.
    obs.collective_epoch(version)
    obs.record_event("load_checkpoint", version=version,
                     recovered=version > 0)
    if version > 0:
        obs.get_registry().counter("load_checkpoint_recovered_total").inc()
    gmodel = pickle.loads(gblob) if version > 0 and gblob is not None else None
    if with_local:
        lmodel = pickle.loads(lblob) if version > 0 and lblob is not None else None
        return version, gmodel, lmodel
    return version, gmodel


def _note_commit(engine: Engine, nbytes: int) -> None:
    """Record one checkpoint commit (engine version bump) in the flight
    recorder and registry."""
    version = _ckpt_base + engine.version_number()
    obs.collective_epoch(version)
    obs.record_event("checkpoint_commit", version=version, nbytes=nbytes)
    reg = obs.get_registry()
    reg.counter("checkpoint_commits_total").inc()
    reg.gauge("checkpoint_version").set(version)


def checkpoint(global_model: Any, local_model: Any = None) -> None:
    """Commit an iteration: pickle and store the models, bump the version.
    ``local_model`` (rank-specific state) costs ring replication; prefer
    ``global_model`` (reference notes, python/rabit.py:320-351).  With
    ``rabit_checkpoint_dir`` configured, the committed blobs are also
    spilled to disk (whole-job preemption durability)."""
    gblob = pickle.dumps(global_model, protocol=pickle.HIGHEST_PROTOCOL)
    lblob = None if local_model is None else pickle.dumps(local_model, protocol=pickle.HIGHEST_PROTOCOL)
    engine = _get_engine()
    if _ckpt_store is None:
        engine.checkpoint(gblob, lblob)
        _note_commit(engine, len(gblob))
        _publish_commit(engine, gblob)
        return
    wrapped = _wrap(_ckpt_base, gblob)
    engine.checkpoint(wrapped, lblob)
    _note_commit(engine, len(wrapped))
    # Persist AFTER the commit barrier: live ranks' disk versions can then
    # skew by at most one, which the store's keep-2 retention covers.  The
    # adopted world epoch rides in the frame (RTC3) so a resume can tell
    # which membership generation produced each version — replay across a
    # resize stays deterministic (doc/elasticity.md).
    _ckpt_store.save(_ckpt_base + engine.version_number(), wrapped, lblob,
                     epoch=_world_epoch["epoch"])
    _publish_commit(engine, wrapped)


def _publish_commit(engine: Engine, blob: bytes) -> None:
    """Delivery-plane publish seam (doc/delivery.md): register the
    committed blob with the tracker AFTER commit (and after the durable
    spill, when on) so the plane only ever advertises bytes a resume
    could also serve.  Publishing is best-effort — a delivery outage
    must never fail the training job's commit."""
    if _publisher is None:
        return
    version = _ckpt_base + engine.version_number()
    try:
        _publisher.publish(version, blob, epoch=_world_epoch["epoch"])
        if _ckpt_store is not None:
            # Pin what subscribers were just told about: the retention
            # prune must not race a fetch-in-flight of this version.
            _ckpt_store.pin(version)
        obs.record_event("snapshot_published", version=version,
                         nbytes=len(blob))
    except (ConnectionError, OSError, ValueError):
        pass


def lazy_checkpoint(global_model: Any) -> None:
    """Checkpoint without eager serialization: the model is only pickled if a
    failure actually needs the blob.  Contract (reference rabit.h:311-332):
    ``global_model`` must stay unchanged until the NEXT checkpoint call
    RETURNS — recovery during that next call's pre-commit consensus can
    still serve this version through this call's callback.  Rebind a fresh
    object per iteration rather than mutating in place.

    With ``rabit_checkpoint_dir`` configured this degrades to the eager
    path: disk durability requires the bytes at commit time."""
    if _ckpt_store is not None:
        checkpoint(global_model)
        return
    engine = _get_engine()
    engine.lazy_checkpoint(
        lambda: pickle.dumps(global_model, protocol=pickle.HIGHEST_PROTOCOL)
    )
    _note_commit(engine, 0)  # lazy: bytes unknown unless a failure asks


def version_number() -> int:
    """Checkpoint count.  When this job resumed disk checkpoints from a
    previous job, the resumed base is included — user code always sees one
    monotonically growing version line."""
    return _ckpt_base + _get_engine().version_number()
