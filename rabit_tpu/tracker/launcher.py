"""Local cluster launcher — the cluster-in-a-box test harness.

Capability parity with ``dmlc-submit --cluster local --num-workers N
--local-num-attempt R`` (reference test harness, test/test.mk:14-38): runs
the tracker in-process, spawns N copies of a worker command as local
processes with the tracker's address in their environment, and restarts any
worker that dies (nonzero exit) up to ``max_restarts`` times — which is how
multi-node fault tolerance is tested on one machine.

Self-healing (doc/fault_tolerance.md): the tracker's heartbeat-lease
failure detector calls back into the launcher when a worker goes silent
(``on_suspect``), and the launcher SIGKILLs the suspect — converting a
SILENT hang (frozen process, preempted VM) into the ordinary death shape
the restart path and the engines' wave-based recovery already handle.

Usage:
    python -m rabit_tpu.tracker.launcher --num-workers 4 \
        [--max-restarts 20] -- python worker_prog.py [args...]
"""

from __future__ import annotations

import argparse
import os
import signal
import subprocess
import sys
import threading
import time

from rabit_tpu.tracker.tracker import Tracker


def cpu_worker_env() -> dict[str, str]:
    """PYTHONPATH for spawned CPU-only workers: the repo root, with any
    accelerator sitecustomize entries (e.g. the axon TPU shim) stripped.
    A wedged TPU tunnel makes that sitecustomize burn ~2s of CPU at every
    child interpreter boot, which poisons wall-clock benchmarks and slows
    worker-spawning tests by minutes; workers that genuinely need the TPU
    backend must keep their inherited PYTHONPATH instead."""
    repo = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    parts = [p for p in os.environ.get("PYTHONPATH", "").split(os.pathsep)
             if p and os.path.basename(p.rstrip("/")) != ".axon_site"]
    if repo not in parts:
        parts.insert(0, repo)
    return {"PYTHONPATH": os.pathsep.join(parts)}


class LocalCluster:
    def __init__(
        self,
        num_workers: int,
        max_restarts: int = 0,
        quiet: bool = False,
        extra_env: dict[str, str] | None = None,
    ):
        self.num_workers = num_workers
        self.max_restarts = max_restarts
        self.quiet = quiet
        self.extra_env = extra_env or {}
        self.restarts = [0] * num_workers
        self.returncodes: list[int | None] = [None] * num_workers
        self.messages: list[str] = []  # tracker print log of the last run
        # Structured observability of the last run (doc/observability.md):
        # tracker events (bootstrap/recovery waves, recover_stats converted
        # from prints) and the job-level telemetry document — what tools/
        # consume instead of scraping self.messages.
        self.events: list[dict] = []
        self.telemetry: dict | None = None
        # time.time() at each observed worker death (recovery-latency
        # benchmarks diff these against worker-reported recovery stamps).
        # Preemptions are stamped when the SIGKILL is confirmed delivered —
        # including via the deferred-reap path — not when the restart branch
        # later reaps them, so benchmark latencies start at the actual kill.
        self.death_times: list[float] = []
        # how many scheduled preemptions were actually delivered (a target
        # that already exited cleanly is left alone and not counted)
        self.preempts_delivered = 0
        # how many scheduled SIGSTOP wedges landed (silent-hang injection),
        # and time.time() at each — liveness tests diff these against the
        # tracker's lease_expired timestamps for detection latency
        self.wedges_delivered = 0
        self.wedge_times: list[float] = []
        # task ids the tracker's lease monitor suspected; drained by the
        # poll loop, which SIGKILLs them (the monitor thread never touches
        # procs[] directly — all process state stays on the run() thread)
        self._suspects: list[str] = []
        self._suspect_lock = threading.Lock()
        # indices whose death was already stamped into death_times by the
        # preemption path (the restart branch must not stamp them twice)
        self._death_stamped: set[int] = set()

    def _on_suspect(self, task_id: str) -> None:
        """Tracker lease-monitor callback (runs on the monitor thread)."""
        with self._suspect_lock:
            self._suspects.append(task_id)

    def _spawn(self, cmd: list[str], tracker: Tracker, i: int) -> subprocess.Popen:
        env = dict(os.environ)
        env.update(self.extra_env)
        env.update(
            DMLC_TRACKER_URI=tracker.host,
            DMLC_TRACKER_PORT=str(tracker.port),
            DMLC_TASK_ID=str(i),
            DMLC_NUM_ATTEMPT=str(self.restarts[i]),
        )
        return subprocess.Popen(cmd, env=env)

    def run(
        self,
        cmd: list[str],
        timeout: float = 300.0,
        preempt: list[tuple[float, int]] | None = None,
        wedge: list[tuple[float, int]] | None = None,
    ) -> int:
        """Run ``cmd`` x num_workers under a fresh tracker.  Returns 0 when
        every worker exited cleanly; raises on restart-budget exhaustion or
        timeout.

        ``preempt`` schedules abrupt external deaths: ``[(delay_s, rank),
        ...]`` SIGKILLs that worker ``delay_s`` seconds after launch,
        wherever it happens to be — mid-collective, mid-bootstrap, inside a
        checkpoint.  This is the TPU-VM-preemption failure shape (BASELINE
        north star: "checkpoint-recover under induced preemption"), the
        complement of the mock engine's deterministic kill points.  The
        killed worker is restarted from the normal budget like any other
        death.

        ``wedge`` schedules SILENT hangs: ``[(delay_s, rank), ...]``
        SIGSTOPs that worker instead — no exit, no TCP error, its sockets
        stay open and its peers just block.  With heartbeat leases enabled
        (``rabit_heartbeat_sec`` on the workers) the tracker suspects the
        frozen worker, this launcher SIGKILLs it, and the hang becomes an
        ordinary recoverable death."""
        tracker = Tracker(self.num_workers, quiet=self.quiet,
                          on_suspect=self._on_suspect).start()
        self.messages = tracker.messages
        self.events = tracker.events
        procs = [self._spawn(cmd, tracker, i) for i in range(self.num_workers)]
        start = time.monotonic()
        deadline = start + timeout
        pending = sorted(preempt or [], key=lambda p: p[0], reverse=True)
        wedges = sorted(wedge or [], key=lambda p: p[0], reverse=True)
        reap_pending: set[int] = set()  # killed, reap deferred to poll loop
        try:
            while True:
                if time.monotonic() > deadline:
                    raise TimeoutError(f"cluster did not finish within {timeout}s")
                while pending and time.monotonic() - start >= pending[-1][0]:
                    _, idx = pending[-1]
                    proc = procs[idx]
                    if proc is not None and proc.poll() is not None:
                        # Target died but hasn't been reaped/restarted yet:
                        # keep the entry queued so the kill lands on the
                        # restarted life instead of being silently dropped.
                        break
                    pending.pop()
                    if proc is None:
                        continue  # finished cleanly — nothing to preempt
                    proc.kill()
                    killed_at = time.time()
                    # kill() on a child that exited between the poll()
                    # above and here is a silent no-op; only count the
                    # preemption as delivered when the reaped status shows
                    # the SIGKILL actually landed (returncode -9).  The
                    # wait here is deliberately short so a slow-to-reap
                    # child can't stall other scheduled preemptions or the
                    # deadline check; a pending reap is counted later from
                    # the poll loop's observed returncode.
                    try:
                        rc = proc.wait(timeout=0.5)
                        if rc == -signal.SIGKILL:
                            self.preempts_delivered += 1
                            # Stamp the death at the kill, not at the later
                            # restart reap — recovery-latency benchmarks
                            # measure from the real preemption instant.
                            self.death_times.append(killed_at)
                            self._death_stamped.add(idx)
                    except subprocess.TimeoutExpired:
                        reap_pending.add(idx)
                    if not self.quiet:
                        print(f"[launcher] preempted worker {idx} "
                              f"(SIGKILL)", flush=True)
                while wedges and time.monotonic() - start >= wedges[-1][0]:
                    _, idx = wedges[-1]
                    wedges.pop()
                    proc = procs[idx]
                    if proc is None or proc.poll() is not None:
                        continue  # already gone — nothing to freeze
                    proc.send_signal(signal.SIGSTOP)
                    self.wedges_delivered += 1
                    self.wedge_times.append(time.time())
                    if not self.quiet:
                        print(f"[launcher] wedged worker {idx} (SIGSTOP)",
                              flush=True)
                with self._suspect_lock:
                    suspects, self._suspects = self._suspects, []
                for task_id in suspects:
                    try:
                        idx = int(task_id)
                    except ValueError:
                        continue  # not one of ours
                    proc = procs[idx] if 0 <= idx < len(procs) else None
                    if proc is None or proc.poll() is not None:
                        continue  # already dead/finished; nothing to heal
                    # Convert the silent hang into a death: SIGKILL works on
                    # stopped processes too, peers get TCP resets, and the
                    # normal restart/recovery path below takes over.
                    proc.kill()
                    if not self.quiet:
                        print(f"[launcher] worker {idx} suspected by lease "
                              f"monitor: SIGKILL to force recovery",
                              flush=True)
                alive = 0
                for i, proc in enumerate(procs):
                    if proc is None:
                        continue
                    ret = proc.poll()
                    if ret is not None and i in reap_pending:
                        reap_pending.discard(i)
                        if ret == -signal.SIGKILL:
                            self.preempts_delivered += 1
                            # Deferred-reap preemptions must land in
                            # death_times too; reap time is the closest
                            # observable stamp left.
                            self.death_times.append(time.time())
                            self._death_stamped.add(i)
                    if ret is None:
                        alive += 1
                    elif ret == 0:
                        self.returncodes[i] = 0
                        procs[i] = None
                    else:
                        # Worker died: the reference tracker restarts it and
                        # peers recover (doc/guide.md:338-374).
                        if self.restarts[i] >= self.max_restarts:
                            raise RuntimeError(
                                f"worker {i} died with code {ret}; restart "
                                f"budget ({self.max_restarts}) exhausted"
                            )
                        self.restarts[i] += 1
                        if i in self._death_stamped:
                            self._death_stamped.discard(i)
                        else:
                            self.death_times.append(time.time())
                        if not self.quiet:
                            print(
                                f"[launcher] worker {i} died (code {ret}); "
                                f"restart {self.restarts[i]}/{self.max_restarts}",
                                flush=True,
                            )
                        procs[i] = self._spawn(cmd, tracker, i)
                        alive += 1
                if alive == 0:
                    return 0
                time.sleep(0.02)
        finally:
            for proc in procs:
                if proc is not None and proc.poll() is None:
                    proc.kill()
                    proc.wait()
            tracker.stop()  # also flushes telemetry.json (idempotent)
            self.telemetry = tracker.telemetry


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--num-workers", "-n", type=int, required=True)
    ap.add_argument("--max-restarts", type=int, default=0)
    ap.add_argument("--timeout", type=float, default=300.0)
    ap.add_argument("--quiet", action="store_true")
    ap.add_argument(
        "--preempt", action="append", default=[], metavar="DELAY:RANK",
        help="SIGKILL worker RANK DELAY seconds after launch, wherever it "
             "happens to be (repeatable; induced-preemption testing)",
    )
    ap.add_argument(
        "--wedge", action="append", default=[], metavar="DELAY:RANK",
        help="SIGSTOP worker RANK DELAY seconds after launch — a silent "
             "hang with no exit and no TCP error (repeatable; pair with "
             "rabit_heartbeat_sec on the workers so the lease detector "
             "converts the hang into a restart)",
    )
    ap.add_argument("cmd", nargs=argparse.REMAINDER)
    args = ap.parse_args(argv)
    cmd = args.cmd
    if cmd and cmd[0] == "--":
        cmd = cmd[1:]
    if not cmd:
        ap.error("worker command required after --")

    def parse_schedule(entries: list[str], flag: str) -> list[tuple[float, int]]:
        out = []
        for s in entries:
            try:
                delay, rank = s.split(":")
                out.append((float(delay), int(rank)))
            except ValueError:
                ap.error(f"{flag} wants DELAY:RANK pairs, got {s!r}")
            if not 0 <= out[-1][1] < args.num_workers:
                ap.error(f"{flag} rank {out[-1][1]} outside "
                         f"0..{args.num_workers - 1}")
        return out

    preempt = parse_schedule(args.preempt, "--preempt")
    wedge = parse_schedule(args.wedge, "--wedge")
    cluster = LocalCluster(args.num_workers, args.max_restarts, quiet=args.quiet)
    return cluster.run(cmd, timeout=args.timeout, preempt=preempt, wedge=wedge)


if __name__ == "__main__":
    sys.exit(main())
