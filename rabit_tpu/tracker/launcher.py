"""Local cluster launcher — the cluster-in-a-box test harness.

Capability parity with ``dmlc-submit --cluster local --num-workers N
--local-num-attempt R`` (reference test harness, test/test.mk:14-38): runs
the tracker in-process, spawns N copies of a worker command as local
processes with the tracker's address in their environment, and restarts any
worker that dies (nonzero exit) up to ``max_restarts`` times — which is how
multi-node fault tolerance is tested on one machine.

Self-healing (doc/fault_tolerance.md): the tracker's heartbeat-lease
failure detector calls back into the launcher when a worker goes silent
(``on_suspect``), and the launcher SIGKILLs the suspect — converting a
SILENT hang (frozen process, preempted VM) into the ordinary death shape
the restart path and the engines' wave-based recovery already handle.

Elastic worlds (doc/elasticity.md): ``--spares K`` additionally launches K
hot-spare processes (task ids ``s0..s{K-1}``, ``rabit_spare=1`` in their
config environment) that park in the tracker's pool; ``--shrink-after S``
lets recovery waves close shrunk when the pool is empty past S seconds.
Spares are not restarted when they die and do not gate job completion.

Bookkeeping is keyed by TASK ID (``restarts``/``returncodes`` dicts), not
by spawn order: late-joining spares and shrunk worlds have no stable dense
index, and the old fixed-size lists would IndexError the moment task
``s0`` died or a world closed below its launch size.

Usage:
    python -m rabit_tpu.tracker.launcher --num-workers 4 \
        [--max-restarts 20] [--spares K] -- python worker_prog.py [args...]
"""

from __future__ import annotations

import argparse
import os
import signal
import subprocess
import sys
import threading
import time

from rabit_tpu.tracker.tracker import Tracker


def cpu_worker_env() -> dict[str, str]:
    """PYTHONPATH for spawned CPU-only workers: the repo root, with any
    accelerator sitecustomize entries (e.g. the axon TPU shim) stripped.
    A wedged TPU tunnel makes that sitecustomize burn ~2s of CPU at every
    child interpreter boot, which poisons wall-clock benchmarks and slows
    worker-spawning tests by minutes; workers that genuinely need the TPU
    backend must keep their inherited PYTHONPATH instead."""
    repo = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    parts = [p for p in os.environ.get("PYTHONPATH", "").split(os.pathsep)
             if p and os.path.basename(p.rstrip("/")) != ".axon_site"]
    if repo not in parts:
        parts.insert(0, repo)
    return {"PYTHONPATH": os.pathsep.join(parts)}


def spare_task_id(i: int) -> str:
    """Task id of the i-th hot spare (workers use the dense ``str(i)``
    launcher numbering; spares must NOT — a spare is outside the dense
    rank space until the tracker promotes it)."""
    return f"s{i}"


class LocalCluster:
    def __init__(
        self,
        num_workers: int,
        max_restarts: int = 0,
        quiet: bool = False,
        extra_env: dict[str, str] | None = None,
        spares: int = 0,
        shrink_after_sec: float = 0.0,
        schedule: str = "auto",
        sched_mesh: str = "",
        relays: int = 0,
        relay_flush_sec: float = 0.25,
        standby: bool = False,
        ha_journal: str = "",
        takeover_sec: float = 1.0,
        job: str = "",
    ):
        self.num_workers = num_workers
        self.max_restarts = max_restarts
        self.quiet = quiet
        self.extra_env = extra_env or {}
        self.num_spares = int(spares)
        self.shrink_after_sec = float(shrink_after_sec)
        self.schedule = schedule
        self.sched_mesh = sched_mesh
        #: hierarchical relay tier (doc/scaling.md): R in-process relay
        #: nodes between the workers and the tracker; workers are
        #: sharded round-robin across them (worker i -> relay i % R), so
        #: the root tracker serves O(R) connections instead of O(N).
        #: 0 = direct (the wire bytes workers see are identical).
        self.num_relays = int(relays)
        self.relay_flush_sec = float(relay_flush_sec)
        self.relays: list = []
        #: HA control plane (doc/ha.md): standby=True runs a warm
        #: standby tracker in-process — the primary journals every
        #: control-plane mutation (to ha_journal when set, else an
        #: in-memory journal streamed over CMD_JOURNAL), workers get
        #: both addresses in rabit_tracker_addrs, and a primary death
        #: (run(kill_tracker_after=...) or a real crash) fails the job
        #: over within takeover_sec instead of killing it.
        self.use_standby = bool(standby)
        self.ha_journal = str(ha_journal or "")
        self.takeover_sec = float(takeover_sec)
        #: multi-tenant job key (doc/service.md): exported to the
        #: workers as rabit_job_key so they prefix their wire task ids
        #: — point the cluster at a CollectiveService and the whole run
        #: becomes one tenant of it.  Empty = legacy ids, byte-identical.
        self.job = str(job)
        self.standby = None
        self._worker_addrs: list[tuple[str, int]] = []
        #: per-task restart / last-returncode bookkeeping, keyed by TASK ID
        #: (workers "0".."N-1", spares "s0".."sK-1") — dicts, not spawn-
        #: order lists, so elastic membership cannot index out of range.
        self.restarts: dict[str, int] = {
            str(i): 0 for i in range(num_workers)}
        self.returncodes: dict[str, int | None] = {
            str(i): None for i in range(num_workers)}
        for i in range(self.num_spares):
            self.restarts[spare_task_id(i)] = 0
            self.returncodes[spare_task_id(i)] = None
        self.messages: list[str] = []  # tracker print log of the last run
        # Structured observability of the last run (doc/observability.md):
        # tracker events (bootstrap/recovery waves, recover_stats converted
        # from prints) and the job-level telemetry document — what tools/
        # consume instead of scraping self.messages.
        self.events: list[dict] = []
        self.telemetry: dict | None = None
        # time.time() at each observed worker death (recovery-latency
        # benchmarks diff these against worker-reported recovery stamps).
        # Preemptions are stamped when the SIGKILL is confirmed delivered —
        # including via the deferred-reap path — not when the restart branch
        # later reaps them, so benchmark latencies start at the actual kill.
        self.death_times: list[float] = []
        # how many scheduled preemptions were actually delivered (a target
        # that already exited cleanly is left alone and not counted)
        self.preempts_delivered = 0
        # how many scheduled SIGSTOP wedges landed (silent-hang injection),
        # and time.time() at each — liveness tests diff these against the
        # tracker's lease_expired timestamps for detection latency
        self.wedges_delivered = 0
        self.wedge_times: list[float] = []
        # task ids the tracker's lease monitor suspected; drained by the
        # poll loop, which SIGKILLs them (the monitor thread never touches
        # procs{} directly — all process state stays on the run() thread)
        self._suspects: list[str] = []
        self._suspect_lock = threading.Lock()
        # task ids whose death was already stamped into death_times by the
        # preemption/suspect path (the reap branch must not stamp them
        # twice — including a promoted spare later reaped dead)
        self._death_stamped: set[str] = set()

    def _on_suspect(self, task_id: str) -> None:
        """Tracker lease-monitor callback (runs on the monitor thread)."""
        with self._suspect_lock:
            self._suspects.append(task_id)

    def _target_addr(self, tracker: Tracker, task_id: str) -> tuple[str, int]:
        """The coordination address this task dials: the tracker, or its
        round-robin relay (stable per task id, so a restarted life lands
        on the same relay)."""
        if not self.relays:
            return tracker.host, tracker.port
        try:
            idx = int(task_id.lstrip("s"))
        except ValueError:
            idx = sum(task_id.encode())
        relay = self.relays[idx % len(self.relays)]
        return relay.host, relay.port

    def _spawn(self, cmd: list[str], tracker: Tracker,
               task_id: str, spare: bool = False) -> subprocess.Popen:
        host, port = self._target_addr(tracker, task_id)
        env = dict(os.environ)
        env.update(self.extra_env)
        env.update(
            DMLC_TRACKER_URI=host,
            DMLC_TRACKER_PORT=str(port),
            DMLC_TASK_ID=task_id,
            DMLC_NUM_ATTEMPT=str(self.restarts[task_id]),
        )
        if spare:
            # config layer 2 (rabit_tpu/config.py): RABIT_TPU_* env wins
            # over defaults, so the worker sees rabit_spare=1 without
            # touching its argv.
            env["RABIT_TPU_RABIT_SPARE"] = "1"
        if self.job:
            env["RABIT_TPU_RABIT_JOB_KEY"] = self.job
        if self._worker_addrs and not self.relays:
            # The HA failover list (doc/ha.md): direct workers rotate
            # through primary-then-standby; relayed workers keep their
            # relay address — the relay's channel rotates for them.
            env["RABIT_TPU_RABIT_TRACKER_ADDRS"] = ",".join(
                f"{h}:{p}" for h, p in self._worker_addrs)
        return subprocess.Popen(cmd, env=env)

    def run(
        self,
        cmd: list[str],
        timeout: float = 300.0,
        preempt: list[tuple[float, int]] | None = None,
        wedge: list[tuple[float, int]] | None = None,
        kill_tracker_after: float | None = None,
    ) -> int:
        """Run ``cmd`` x num_workers (+ spares) under a fresh tracker.
        Returns 0 when every primary worker exited cleanly; raises on
        restart-budget exhaustion or timeout.

        ``preempt`` schedules abrupt external deaths: ``[(delay_s, rank),
        ...]`` SIGKILLs that worker ``delay_s`` seconds after launch,
        wherever it happens to be — mid-collective, mid-bootstrap, inside a
        checkpoint.  This is the TPU-VM-preemption failure shape (BASELINE
        north star: "checkpoint-recover under induced preemption"), the
        complement of the mock engine's deterministic kill points.  The
        killed worker is restarted from the normal budget like any other
        death.

        ``wedge`` schedules SILENT hangs: ``[(delay_s, rank), ...]``
        SIGSTOPs that worker instead — no exit, no TCP error, its sockets
        stay open and its peers just block.  With heartbeat leases enabled
        (``rabit_heartbeat_sec`` on the workers) the tracker suspects the
        frozen worker, this launcher SIGKILLs it, and the hang becomes an
        ordinary recoverable death.

        ``kill_tracker_after`` (needs ``standby=True`` to be survivable)
        kills the PRIMARY TRACKER abruptly that many seconds in —
        ``Tracker.kill()``, the in-process SIGKILL: every socket drops
        with no goodbye.  The warm standby replays the journal, takes
        over within ``takeover_sec``, and the workers fail over via
        their ``rabit_tracker_addrs`` rotation (doc/ha.md)."""
        tracker_kwargs = dict(quiet=self.quiet,
                              on_suspect=self._on_suspect,
                              shrink_after_sec=self.shrink_after_sec,
                              schedule=self.schedule,
                              sched_mesh=self.sched_mesh)
        journal = None
        if self.use_standby:
            if self.ha_journal:
                journal = self.ha_journal
            else:
                from rabit_tpu.ha import Journal

                journal = Journal(None)
        tracker = Tracker(self.num_workers, journal=journal,
                          **tracker_kwargs).start()
        self.messages = tracker.messages
        self.events = tracker.events
        self._worker_addrs = []
        if self.use_standby:
            from rabit_tpu.ha import Standby

            self.standby = Standby(
                primary=(tracker.host, tracker.port),
                takeover_sec=self.takeover_sec,
                journal=self.ha_journal or None,
                tracker_kwargs=tracker_kwargs,
                quiet=self.quiet).start()
            self._worker_addrs = [(tracker.host, tracker.port),
                                  (self.standby.host, self.standby.port)]
        if self.num_relays > 0:
            from rabit_tpu.relay import Relay

            relay_target = (self._worker_addrs
                            or (tracker.host, tracker.port))
            self.relays = [
                Relay(relay_target, relay_id=f"relay{i}",
                      flush_sec=self.relay_flush_sec,
                      quiet=self.quiet).start()
                for i in range(self.num_relays)
            ]
        primaries = [str(i) for i in range(self.num_workers)]
        procs: dict[str, subprocess.Popen | None] = {
            t: self._spawn(cmd, tracker, t) for t in primaries}
        for i in range(self.num_spares):
            sid = spare_task_id(i)
            procs[sid] = self._spawn(cmd, tracker, sid, spare=True)
        start = time.monotonic()
        deadline = start + timeout
        pending = sorted(preempt or [], key=lambda p: p[0], reverse=True)
        wedges = sorted(wedge or [], key=lambda p: p[0], reverse=True)
        reap_pending: set[str] = set()  # killed, reap deferred to poll loop
        tracker_killed = False
        try:
            while True:
                if time.monotonic() > deadline:
                    raise TimeoutError(f"cluster did not finish within {timeout}s")
                if (kill_tracker_after is not None and not tracker_killed
                        and time.monotonic() - start >= kill_tracker_after):
                    tracker_killed = True
                    tracker.kill()
                    if not self.quiet:
                        print("[launcher] primary tracker KILLED "
                              "(abrupt; standby takeover pending)",
                              flush=True)
                while pending and time.monotonic() - start >= pending[-1][0]:
                    _, idx = pending[-1]
                    tid = str(idx)
                    proc = procs.get(tid)
                    if proc is not None and proc.poll() is not None:
                        # Target died but hasn't been reaped/restarted yet:
                        # keep the entry queued so the kill lands on the
                        # restarted life instead of being silently dropped.
                        break
                    pending.pop()
                    if proc is None:
                        continue  # finished cleanly — nothing to preempt
                    proc.kill()
                    killed_at = time.time()
                    # kill() on a child that exited between the poll()
                    # above and here is a silent no-op; only count the
                    # preemption as delivered when the reaped status shows
                    # the SIGKILL actually landed (returncode -9).  The
                    # wait here is deliberately short so a slow-to-reap
                    # child can't stall other scheduled preemptions or the
                    # deadline check; a pending reap is counted later from
                    # the poll loop's observed returncode.
                    try:
                        rc = proc.wait(timeout=0.5)
                        if rc == -signal.SIGKILL:
                            self.preempts_delivered += 1
                            # Stamp the death at the kill, not at the later
                            # restart reap — recovery-latency benchmarks
                            # measure from the real preemption instant.
                            self.death_times.append(killed_at)
                            self._death_stamped.add(tid)
                    except subprocess.TimeoutExpired:
                        reap_pending.add(tid)
                    if not self.quiet:
                        print(f"[launcher] preempted worker {tid} "
                              f"(SIGKILL)", flush=True)
                while wedges and time.monotonic() - start >= wedges[-1][0]:
                    _, idx = wedges[-1]
                    wedges.pop()
                    proc = procs.get(str(idx))
                    if proc is None or proc.poll() is not None:
                        continue  # already gone — nothing to freeze
                    proc.send_signal(signal.SIGSTOP)
                    self.wedges_delivered += 1
                    self.wedge_times.append(time.time())
                    if not self.quiet:
                        print(f"[launcher] wedged worker {idx} (SIGSTOP)",
                              flush=True)
                with self._suspect_lock:
                    suspects, self._suspects = self._suspects, []
                for task_id in suspects:
                    proc = procs.get(task_id)
                    if proc is None or proc.poll() is not None:
                        continue  # already dead/finished; nothing to heal
                    # Convert the silent hang into a death: SIGKILL works on
                    # stopped processes too, peers get TCP resets, and the
                    # normal restart/recovery path below takes over.  Stamp
                    # the death here (once — the reap branch checks the
                    # stamp), so spare-promotion latency benchmarks measure
                    # from the confirmed kill even for tasks that are never
                    # restarted (spares, shrunk-away ranks).
                    proc.kill()
                    self.death_times.append(time.time())
                    self._death_stamped.add(task_id)
                    if not self.quiet:
                        print(f"[launcher] worker {task_id} suspected by "
                              f"lease monitor: SIGKILL to force recovery",
                              flush=True)
                alive = 0
                for tid, proc in list(procs.items()):
                    if proc is None:
                        continue
                    is_spare = not tid.isdigit()
                    ret = proc.poll()
                    if ret is not None and tid in reap_pending:
                        reap_pending.discard(tid)
                        if ret == -signal.SIGKILL:
                            self.preempts_delivered += 1
                            # Deferred-reap preemptions must land in
                            # death_times too; reap time is the closest
                            # observable stamp left.
                            self.death_times.append(time.time())
                            self._death_stamped.add(tid)
                    if ret is None:
                        if not is_spare:
                            alive += 1
                    elif ret == 0:
                        self.returncodes[tid] = 0
                        procs[tid] = None
                    elif is_spare:
                        # A dead spare is not restarted and does not gate
                        # completion: the pool shrank, nothing more.
                        self.returncodes[tid] = ret
                        procs[tid] = None
                        if tid not in self._death_stamped:
                            self.death_times.append(time.time())
                            self._death_stamped.add(tid)
                        if not self.quiet:
                            print(f"[launcher] spare {tid} died "
                                  f"(code {ret}); pool shrank", flush=True)
                    else:
                        # Worker died: the reference tracker restarts it and
                        # peers recover (doc/guide.md:338-374).
                        self.returncodes[tid] = ret
                        if self.restarts[tid] >= self.max_restarts:
                            raise RuntimeError(
                                f"worker {tid} died with code {ret}; restart "
                                f"budget ({self.max_restarts}) exhausted"
                            )
                        self.restarts[tid] += 1
                        if tid in self._death_stamped:
                            self._death_stamped.discard(tid)
                        else:
                            self.death_times.append(time.time())
                        if not self.quiet:
                            print(
                                f"[launcher] worker {tid} died (code {ret}); "
                                f"restart {self.restarts[tid]}/{self.max_restarts}",
                                flush=True,
                            )
                        procs[tid] = self._spawn(cmd, tracker, tid)
                        alive += 1
                if alive == 0:
                    return 0
                time.sleep(0.02)
        finally:
            for proc in procs.values():
                if proc is not None and proc.poll() is None:
                    proc.kill()
                    proc.wait()
            for relay in self.relays:
                relay.stop()
            self.relays = []
            promoted = (self.standby.tracker
                        if self.standby is not None
                        and self.standby.promoted.is_set() else None)
            if promoted is not None:
                # The promoted standby is the job's tracker of record:
                # its stop() (inside standby.stop) flushes telemetry,
                # and the job timeline is the primary's events up to
                # the cut plus the standby's from takeover.
                self.standby.stop()
                tracker.stop()
                self.telemetry = promoted.telemetry
                self.events = list(tracker.events) + list(promoted.events)
            else:
                if self.standby is not None:
                    self.standby.stop()
                tracker.stop()  # also flushes telemetry.json (idempotent)
                self.telemetry = tracker.telemetry


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--num-workers", "-n", type=int, required=True)
    ap.add_argument("--max-restarts", type=int, default=0)
    ap.add_argument("--timeout", type=float, default=300.0)
    ap.add_argument("--quiet", action="store_true")
    ap.add_argument(
        "--spares", type=int, default=0, metavar="K",
        help="launch K hot-spare processes (rabit_spare=1; task ids "
             "s0..s{K-1}) that park in the tracker's pool and are promoted "
             "into dead ranks' slots (doc/elasticity.md)",
    )
    ap.add_argument(
        "--relays", type=int, default=0, metavar="R",
        help="interpose R relay nodes between the workers and the "
             "tracker (hierarchical fan-out; workers shard round-robin "
             "across them — doc/scaling.md).  0 = direct",
    )
    ap.add_argument(
        "--shrink-after", type=float, default=0.0, metavar="SEC",
        help="let a recovery wave close SHRUNK when no spare fills the "
             "hole within SEC seconds (0 = legacy block-until-full)",
    )
    ap.add_argument(
        "--schedule", default="auto", choices=("auto", "tree", "ring",
                                               "swing"),
        help="collective schedule the tracker plans per epoch "
             "(rabit_schedule; doc/scheduling.md)",
    )
    ap.add_argument(
        "--sched-mesh", default="", metavar="RxC[:nowrap]",
        help="mesh-model dims for schedule planning (rabit_sched_mesh; "
             "empty = near-square auto dims)",
    )
    ap.add_argument(
        "--standby", action="store_true",
        help="run a warm-standby tracker in-process: the primary "
             "journals every control-plane mutation, workers get both "
             "addresses in rabit_tracker_addrs, and a primary tracker "
             "death fails over within --takeover-sec (doc/ha.md)",
    )
    ap.add_argument(
        "--ha-journal", default="", metavar="PATH",
        help="durable journal file for the HA control plane (default: "
             "the rabit_ha_journal config key; empty = in-memory, "
             "streamed to the standby over CMD_JOURNAL)",
    )
    ap.add_argument(
        "--takeover-sec", type=float, default=None, metavar="SEC",
        help="the standby's takeover lease (default: the "
             "rabit_ha_takeover_sec config key)",
    )
    ap.add_argument(
        "--job", default="", metavar="KEY",
        help="multi-tenant job key (rabit_job_key; doc/service.md): "
             "workers prefix their task ids with KEY/ so a "
             "CollectiveService routes them to this job's partition "
             "(default: the rabit_job_key config key)",
    )
    ap.add_argument(
        "--kill-tracker-after", type=float, default=None, metavar="SEC",
        help="ABRUPTLY kill the primary tracker SEC seconds in (the "
             "in-process SIGKILL; pair with --standby to prove the "
             "failover, omit --standby to prove the job loss)",
    )
    ap.add_argument(
        "--preempt", action="append", default=[], metavar="DELAY:RANK",
        help="SIGKILL worker RANK DELAY seconds after launch, wherever it "
             "happens to be (repeatable; induced-preemption testing)",
    )
    ap.add_argument(
        "--wedge", action="append", default=[], metavar="DELAY:RANK",
        help="SIGSTOP worker RANK DELAY seconds after launch — a silent "
             "hang with no exit and no TCP error (repeatable; pair with "
             "rabit_heartbeat_sec on the workers so the lease detector "
             "converts the hang into a restart)",
    )
    ap.add_argument("cmd", nargs=argparse.REMAINDER)
    args = ap.parse_args(argv)
    cmd = args.cmd
    if cmd and cmd[0] == "--":
        cmd = cmd[1:]
    if not cmd:
        ap.error("worker command required after --")

    def parse_schedule(entries: list[str], flag: str) -> list[tuple[float, int]]:
        out = []
        for s in entries:
            try:
                delay, rank = s.split(":")
                out.append((float(delay), int(rank)))
            except ValueError:
                ap.error(f"{flag} wants DELAY:RANK pairs, got {s!r}")
            if not 0 <= out[-1][1] < args.num_workers:
                ap.error(f"{flag} rank {out[-1][1]} outside "
                         f"0..{args.num_workers - 1}")
        return out

    preempt = parse_schedule(args.preempt, "--preempt")
    wedge = parse_schedule(args.wedge, "--wedge")
    from rabit_tpu.config import Config

    cfg = Config()
    ha_journal = args.ha_journal or cfg.get("rabit_ha_journal", "") or ""
    takeover = (args.takeover_sec if args.takeover_sec is not None
                else float(cfg.get("rabit_ha_takeover_sec", "1.0")
                           or "1.0"))
    cluster = LocalCluster(args.num_workers, args.max_restarts,
                           quiet=args.quiet, spares=args.spares,
                           shrink_after_sec=args.shrink_after,
                           schedule=args.schedule,
                           sched_mesh=args.sched_mesh,
                           relays=args.relays,
                           standby=args.standby,
                           ha_journal=ha_journal,
                           takeover_sec=takeover,
                           job=args.job or cfg.get("rabit_job_key", "")
                           or "")
    return cluster.run(cmd, timeout=args.timeout, preempt=preempt,
                       wedge=wedge,
                       kill_tracker_after=args.kill_tracker_after)


if __name__ == "__main__":
    sys.exit(main())
