"""Tracker wire protocol — framed binary, little-endian.

The reference outsources its tracker to dmlc-core and speaks an ad-hoc
magic/struct protocol (worker side: ConnectTracker/ReConnectLinks,
/root/reference/src/allreduce_base.cc:221-438).  This framework owns both
ends, so the protocol is redesigned: one request/assignment round-trip per
(re)bootstrap wave instead of the reference's incremental link-repair loop
— every worker learns the full peer table and connects deterministically
(lower rank dials, higher rank accepts).

Message layout (all u32/i32 little-endian; strings are u32 length + utf-8):

worker -> tracker (fresh connection per message):
    u32 MAGIC_HELLO
    u32 cmd          (CMD_START | CMD_RECOVER | CMD_PRINT | CMD_SHUTDOWN
                      | CMD_METRICS | CMD_HEARTBEAT | CMD_SPARE
                      | CMD_EPOCH | CMD_BLOB | CMD_QUORUM | CMD_BATCH)
    i32 prev_rank    (-1 if never assigned; stable re-admission key is task_id)
    str task_id
    if start/recover/spare: u32 listen_port (worker binds BEFORE contacting
                      tracker; a spare parks on this connection and is
                      answered with an Assignment only when promoted)
    if print:         str message
    if metrics:       str json_snapshot (rabit_tpu.obs.ship envelope; the
                      tracker folds it into the job-level telemetry.json)
    if heartbeat:     str interval_sec  (decimal; the worker's renewal cadence.
                      The tracker grants a lease of 2x this interval — one
                      missed renewal is tolerated, two expire the lease and
                      suspect the worker; see doc/fault_tolerance.md)
    if epoch:         str version       (the worker's committed checkpoint
                      version, informational — the poll elastic workers run
                      at every version boundary, see doc/elasticity.md)
    if blob:          u32 version, u32 nbytes, bytes — the current global
                      model, already codec-compressed by the sender; the
                      tracker caches the newest as the spare bootstrap blob
    if quorum:        str json — one quorum-round report, ``{"epoch": E,
                      "v": V, "have": [ranks...], "held": [[src_v, rank]
                      ...]}`` (doc/partial_allreduce.md): the ranks whose
                      version-V blocks this worker holds plus the late
                      blocks from earlier excluded rounds it can fold as
                      corrections.  The tracker decides each round's
                      exclusion record exactly ONCE (first report meeting
                      the K-of-N quorum wins) so every rank folds the
                      same K contributions

tracker -> worker (start/recover reply, sent when the wave of world_size
workers is complete):
    u32 MAGIC_ASSIGN
    i32 rank
    u32 world_size
    i32 parent       (-1 for root)
    u32 nchildren, i32 children...
    i32 ring_prev, i32 ring_next
    u32 npeers, each: i32 rank, str host, u32 port
    u32 epoch        (world-epoch number; stamps peer-link handshakes)
    u32 nmap, each: str task_id, i32 rank — the epoch's full rank_map
                     (rabit_tpu.elastic.membership; the delta against the
                     previous epoch derives by comparison, and a freshly
                     promoted spare needs the whole map anyway).  The
                     native C++ client (comm.cc RecvAssignment) reads up
                     to the epoch and closes; the trailing map bytes are
                     discarded with the connection, so both clients stay
                     compatible with one tracker encoding.
    str algo, u32 nring, i32 ring_order... — the epoch's planned collective
                     schedule (rabit_tpu.sched; put_sched_frame /
                     read_sched_frame).  Trails the rank_map for the same
                     reason the map trails the epoch: the native client's
                     prefix read never sees it.  The PREFIX keeps the
                     legacy tree+ring (heap tree, identity ring) so the
                     native data plane is byte-for-byte untouched;
                     schedule-aware executors (rabit_tpu.elastic.client)
                     adopt the trailing ring order instead.

tracker -> worker (spare reply, immediate): u32 MAGIC_BLOB, u32 version,
    u32 nbytes, bytes — the cached compressed bootstrap blob (version 0 /
    empty when nothing is cached yet).  The connection then stays open
    ("warm socket"); promotion answers it with a normal Assignment.

tracker -> worker (print/shutdown/blob reply): u32 ACK

tracker -> worker (epoch reply): u32 ACK, str json — ``{"epoch": E,
    "world": W, "rewave": bool}``; rewave asks the worker to re-enter a
    wave at this version boundary (grow-back pending)

tracker -> worker (quorum reply): u32 ACK, str json — the round's
    exclusion record ``{"decided": true, "epoch": E, "version": V,
    "k": K, "excluded": [ranks...], "corrections": [[src_v, rank]...]}``
    once decided, else ``{"decided": false, ...}`` (the worker keeps
    pumping blocks and re-reports until the record lands)

tracker -> worker (metrics/heartbeat reply): u32 ACK, str server_ts — the
    tracker's ``time.time()`` stamped while answering.  The worker brackets
    the RPC and takes the NTP-style midpoint: ``offset = server_ts -
    (t_send + t_recv)/2`` with error bound rtt/2 — the clock-alignment
    signal rabit_tpu.obs.trace projects per-rank timelines with.  Only the
    two Python-side commands carry the stamp; the native C++ client speaks
    only start/recover/print/shutdown, whose replies are unchanged.

relay <-> tracker channel (doc/scaling.md): a relay (rabit_tpu.relay)
    establishes ONE persistent duplex channel with the hello above using
    ``cmd=CMD_BATCH`` (task_id = the relay's id; no listen_port).  The
    tracker answers ``u32 ACK`` and the connection then switches to
    framed mode:

    relay -> tracker, one CMD_BATCH envelope per flush interval
    (``put_batch_frame``): u32 nmsgs, then per sub-message: str task_id,
    u32 cmd, i32 prev_rank, str host (the child's peer address — the
    tracker must record the CHILD's host for the peer table, not the
    relay's), u32 listen_port, u32 nbytes + payload, str recv_ts (the
    relay's clock when the child's RPC landed).  The relay terminates
    its children's heartbeat/metrics/epoch/print RPCs locally and
    coalesces them here — N workers cost the root tracker ONE
    connection and one frame per interval instead of N accepts per
    interval.  START/RECOVER/SPARE check-ins ride the same envelope
    (flushed immediately), so a bootstrap wave costs the root O(relays)
    connections instead of O(world); a CMD_HANGUP sub-message reports a
    parked child's EOF so wave purges stay live-survivor-exact.
    CMD_QUORUM and CMD_BLOB never ride a batch — the relay proxies them
    straight through (decide-once replies and rank-0 blob uploads need
    the synchronous path).

    tracker -> relay (``put_route_frame``): str task_id, u32 flags
    (bit 0 = close the child connection after delivering), u32 nbytes +
    payload.  A frame with task_id "" is the BATCH ACK: its payload is
    JSON ``{"server_ts": ..., "acks": [...], "epoch": E, "world": W,
    "rewave": bool}`` — server_ts is the tracker clock stamped while
    folding the batch (the relay brackets the batch round-trip and
    projects its children's heartbeat/metrics ACK stamps onto the
    tracker clock, so PR 3 clock sync still works per rank through a
    relay), acks are the per-sub-message tracker-clock ingest stamps,
    and epoch/world/rewave refresh the relay's local CMD_EPOCH cache.
    Frames with a task_id route a reply (an Assignment, a MAGIC_BLOB
    park frame) to that parked child connection.

standby <-> tracker journal channel (doc/ha.md): a warm-standby tracker
    (rabit_tpu.ha) establishes ONE persistent channel with the hello
    above using ``cmd=CMD_JOURNAL`` (task_id = the standby's id; no
    listen_port).  The tracker answers ``u32 ACK`` and then streams
    journal frames (``put_journal_frame``): first a ``snapshot`` record
    of the full control-plane state, then every subsequent mutation
    record as it commits, with periodic ``tick`` keepalives so the
    standby's takeover lease (rabit_ha_takeover_sec) can distinguish an
    idle primary from a dead one.  Each frame reuses the durable
    store's RTC2 layout (magic, codec byte, crc over the ENCODED
    payload, length): magic "RJL1", then the codec-compressed JSON
    record ``{"kind": ..., <fields>}`` — the same frames a
    ``rabit_ha_journal`` file holds, so file tailing and channel
    streaming replay identically (rabit_tpu/ha/journal.py).

multi-tenant job keys (rabit_tpu.service, doc/service.md): a worker of a
    named job prefixes its wire task id with the job key —
    ``"<job>/<task>"`` (:func:`join_job` / :func:`split_job`).  The key
    rides INSIDE the existing task-id field, so the hello's byte layout
    is untouched: an empty job key produces byte-for-byte the legacy
    single-job hello (asserted by tests/test_service.py), the native C++
    client needs no change (its task id is an opaque string), and every
    reply — assignments, park frames, routed relay frames — already
    routes by the full task id.  A multi-job tracker
    (``rabit_tpu.service.CollectiveService``) splits the prefix off and
    dispatches to the job's control-plane partition; the plain Tracker
    treats the whole string as the task id, exactly as before.  The
    reserved prefix ``pool/`` marks service-level pooled workers
    (leased across jobs); job keys are validated against
    ``[A-Za-z0-9_.-]`` at admission so a key can never alias a path or
    another job's records.

worker <-> worker link handshake (both directions on connect/accept):
    u32 MAGIC_LINK, i32 my_rank, u32 epoch

worker -> worker skip handshake (quorum mode, doc/partial_allreduce.md):
    u32 MAGIC_SKIP, i32 my_rank, u32 epoch, u32 version — a ring
    successor past the quorum deadline dials AROUND its silent
    predecessor to the next live upstream rank; the acceptor registers
    the socket as a tee (every tagged block it holds or later sees is
    duplicated onto it) so the flow of contributions routes around the
    straggler.  Tagged blocks ride inside the ordinary length-framed
    link protocol as ``put_block_frame`` payloads: u32 version,
    i32 origin_rank, raw encoded bytes.
"""

from __future__ import annotations

import random
import socket
import struct
import time
from dataclasses import dataclass, field

MAGIC_HELLO = 0x7AB17001
MAGIC_ASSIGN = 0x7AB17002
MAGIC_LINK = 0x7AB17003
MAGIC_BLOB = 0x7AB17004
MAGIC_SKIP = 0x7AB17005
MAGIC_DELTA = 0x7AB17006
MAGIC_SNAP = 0x7AB17007
ACK = 0

CMD_START = 1
CMD_RECOVER = 2
CMD_PRINT = 3
CMD_SHUTDOWN = 4
CMD_METRICS = 5
CMD_HEARTBEAT = 6
CMD_SPARE = 7
CMD_EPOCH = 8
CMD_BLOB = 9
CMD_QUORUM = 10
CMD_BATCH = 11
#: Relay-internal sub-message (never a worker hello): the relay observed
#: a parked child hang up (EOF on its held connection) — the tracker
#: marks the matching virtual connection dead so the wave purge counts
#: live survivors only, exactly as _conn_dead does for direct sockets.
CMD_HANGUP = 12
#: Warm-standby journal channel (rabit_tpu.ha, doc/ha.md): the hello of
#: a standby tracker asking to tail the primary's control-plane journal.
#: The reply is ACK followed by a stream of journal frames (a snapshot
#: record first, then every mutation as it commits).
CMD_JOURNAL = 13
#: Live-telemetry introspection (rabit_tpu/obs/stream.py,
#: doc/observability.md "Live telemetry plane").  As a worker hello the
#: message field selects the scrape view (a JSON options doc, usually
#: ``{}``); the reply is ACK + one JSON exposition of the tracker's live
#: state (jobs, epochs, leases, spare pool, quorum depth, admission
#: counters, folded metric rollups).  As a relay batch sub-message the
#: payload is one coalesced per-job metric-delta frame
#: (:func:`put_delta_frame`) the tracker folds into its rollups.
CMD_OBS = 14
#: Model-delivery plane (rabit_tpu/delivery, doc/delivery.md).  The
#: message field is a JSON doc: a reader's poll (usually ``{}``) is
#: answered with ACK + the job's current published version line
#: ``{"version": V, "epoch": E, "digest": D, "size": N}`` (version 0 =
#: nothing published yet); a writer's ``{"publish": {...}}`` registers a
#: freshly committed snapshot's line, journals ``snapshot_published``,
#: and the reply's ``"have"`` flag tells the publisher whether the
#: content-addressed bytes for that digest are already held (cross-job
#: dedup: identical bytes upload once).
CMD_SUB = 15
#: Content-addressed snapshot fetch (rabit_tpu/delivery).  The message
#: is ``{"digest": D, "off": O, "len": L}`` (off/len optional: whole
#: blob); the reply is one :func:`put_snap_frame` — NOT an ACK — so the
#: relay tree can cache and serve the bytes digest-keyed without
#: consulting the root.  An unknown digest answers with an empty frame
#: (digest "", total 0): absence is a retryable state, not an error.
CMD_SNAP = 16

#: put_route_frame flags bit 0: close the child connection after
#: delivering this frame's payload (the tracker's "conn.close()" crossing
#: the relay channel).
ROUTE_CLOSE = 1

#: Serving-path asymmetries that are DESIGN, not drift — the machine-
#: checked ledger behind tools/tpulint's serving-path-parity family
#: (doc/static_analysis.md).  Every command served at one of the three
#: serving paths (threaded handler, shared reactor, relay batch fold)
#: must be served at the others OR declared here with the reason; the
#: lint also flags entries whose asymmetry no longer exists, so this
#: table cannot rot silently.
PARITY_EXEMPT = {
    "relay-fold": {
        "CMD_EPOCH": "never rides a batch: the relay answers epoch polls "
                     "from its ack-refreshed cache (doc/scaling.md)",
        "CMD_BLOB": "proxied straight through by the relay: rank-0 blob "
                    "uploads are large and rare, they keep the "
                    "synchronous path",
        "CMD_BATCH": "a batch cannot nest inside a batch: the envelope "
                     "is the relay channel itself",
        "CMD_JOURNAL": "standby trackers tail the journal over a direct "
                       "socket, never through a worker relay "
                       "(doc/ha.md)",
        "CMD_SNAP": "proxied straight through by the relay with "
                    "digest-keyed caching: snapshot fetches are large "
                    "and the relay serves repeat digests locally "
                    "(doc/delivery.md)",
    },
}

#: How many renewal intervals a lease survives without a renewal.  2 means
#: one lost/late heartbeat is tolerated; the second expires the lease, so a
#: frozen worker is suspected within 2 x rabit_heartbeat_sec.
LEASE_FACTOR = 2.0

_U32 = struct.Struct("<I")
_I32 = struct.Struct("<i")

#: Separator of the optional multi-tenant job key inside the wire task id
#: (doc/service.md).  The key is a PREFIX of the existing string field —
#: not a new wire field — so an empty key is byte-identical to the
#: legacy single-job hello.
JOB_SEP = "/"

#: Reserved task-id prefix of service-level pooled workers (parked once,
#: leased to successive jobs; rabit_tpu.service.PooledWorker).  Never a
#: valid job key.
POOL_PREFIX = "pool"


def join_job(job: str, task_id: str) -> str:
    """The wire task id of ``task_id`` under job ``job`` ("" = the
    legacy single-job namespace: returns ``task_id`` unchanged)."""
    return f"{job}{JOB_SEP}{task_id}" if job else task_id


def split_job(task_id: str) -> tuple[str, str]:
    """Split one wire task id into ``(job_key, local_task_id)`` —
    ``("", task_id)`` when it carries no job prefix."""
    job, sep, rest = task_id.partition(JOB_SEP)
    return (job, rest) if sep else ("", task_id)


def send_all(sock: socket.socket, data: bytes) -> None:
    sock.sendall(data)


def recv_exact(sock: socket.socket, n: int) -> bytes:
    buf = bytearray()
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            raise ConnectionError("peer closed connection")
        buf.extend(chunk)
    return bytes(buf)


def put_u32(v: int) -> bytes:
    return _U32.pack(v)


def put_i32(v: int) -> bytes:
    return _I32.pack(v)


def put_str(s: str) -> bytes:
    raw = s.encode()
    return _U32.pack(len(raw)) + raw


def get_u32(sock) -> int:
    return _U32.unpack(recv_exact(sock, 4))[0]


def get_i32(sock) -> int:
    return _I32.unpack(recv_exact(sock, 4))[0]


def get_str(sock) -> str:
    n = get_u32(sock)
    return recv_exact(sock, n).decode() if n else ""


@dataclass
class Assignment:
    rank: int
    world_size: int
    parent: int
    children: list[int]
    ring_prev: int
    ring_next: int
    peers: dict[int, tuple[str, int]] = field(default_factory=dict)
    epoch: int = 0
    # The epoch's full task-id -> rank map (rabit_tpu.elastic).  Trails
    # the epoch on the wire so the native client, which reads up to the
    # epoch and closes, never sees it.
    rank_map: dict[str, int] = field(default_factory=dict)
    # The epoch's planned schedule (rabit_tpu.sched): the resolved
    # algorithm name and the planned ring order (ring_order[i] = rank at
    # ring position i; empty = legacy identity ring).  Trails the
    # rank_map — native-invisible, executor-adopted.
    algo: str = ""
    ring_order: list[int] = field(default_factory=list)

    def encode(self) -> bytes:
        return (assignment_head_bytes(
                    self.rank, self.world_size, self.parent, self.children,
                    self.ring_prev, self.ring_next)
                + assignment_tail_bytes(self.peers, self.epoch,
                                        self.rank_map, self.algo,
                                        self.ring_order))

    @classmethod
    def recv(cls, sock) -> "Assignment":
        magic = get_u32(sock)
        if magic != MAGIC_ASSIGN:
            raise ValueError(f"bad assignment magic {magic:#x}")
        return cls.recv_body(sock)

    @classmethod
    def recv_body(cls, sock) -> "Assignment":
        """Parse the fields after MAGIC_ASSIGN — for callers that dispatch
        on the magic themselves (the elastic client's wave reply is either
        an Assignment or a MAGIC_BLOB park frame)."""
        rank = get_i32(sock)
        world = get_u32(sock)
        parent = get_i32(sock)
        children = [get_i32(sock) for _ in range(get_u32(sock))]
        ring_prev = get_i32(sock)
        ring_next = get_i32(sock)
        peers = {}
        for _ in range(get_u32(sock)):
            r = get_i32(sock)
            host = get_str(sock)
            port = get_u32(sock)
            peers[r] = (host, port)
        epoch = get_u32(sock)
        rank_map = {}
        for _ in range(get_u32(sock)):
            task_id = get_str(sock)
            rank_map[task_id] = get_i32(sock)
        algo, ring_order = read_sched_frame(sock)
        return cls(rank, world, parent, children, ring_prev, ring_next,
                   peers, epoch, rank_map, algo, ring_order)


def assignment_head_bytes(rank: int, world_size: int, parent: int,
                          children: list[int], ring_prev: int,
                          ring_next: int) -> bytes:
    """The per-member PREFIX of an encoded Assignment (magic through the
    legacy ring neighbors).  Split out so the tracker can encode one
    wave's shared suffix ONCE (:func:`assignment_tail_bytes`) instead of
    re-walking the full O(world) peer table and rank_map per member —
    at world 4096 the per-member encode is what dominated wave latency."""
    out = [
        put_u32(MAGIC_ASSIGN),
        put_i32(rank),
        put_u32(world_size),
        put_i32(parent),
        put_u32(len(children)),
    ]
    out += [put_i32(c) for c in children]
    out += [put_i32(ring_prev), put_i32(ring_next)]
    return b"".join(out)


def assignment_tail_bytes(peers: dict[int, tuple[str, int]], epoch: int,
                          rank_map: dict[str, int], algo: str,
                          ring_order: list[int]) -> bytes:
    """The member-independent SUFFIX of an encoded Assignment (peer
    table, epoch, rank_map, trailing schedule frame) — identical bytes
    for every member of one wave."""
    out = [put_u32(len(peers))]
    for r, (host, port) in sorted(peers.items()):
        out += [put_i32(r), put_str(host), put_u32(port)]
    out.append(put_u32(epoch))
    out.append(put_u32(len(rank_map)))
    for task_id, r in sorted(rank_map.items()):
        out += [put_str(task_id), put_i32(r)]
    out.append(put_sched_frame(algo, ring_order))
    return b"".join(out)


def tree_topology(rank: int, world: int) -> tuple[int, list[int]]:
    """Balanced binary heap tree: parent (r-1)//2, children 2r+1 / 2r+2."""
    parent = (rank - 1) // 2 if rank > 0 else -1
    children = [c for c in (2 * rank + 1, 2 * rank + 2) if c < world]
    return parent, children


def send_hello(
    sock,
    cmd: int,
    task_id: str,
    prev_rank: int = -1,
    listen_port: int = 0,
    message: str = "",
    blob: bytes = b"",
    blob_version: int = 0,
    job: str = "",
) -> None:
    # The optional job key is a task-id prefix, never a new field: an
    # empty key writes byte-for-byte the legacy hello (doc/service.md).
    task_id = join_job(job, task_id)
    out = [put_u32(MAGIC_HELLO), put_u32(cmd), put_i32(prev_rank), put_str(task_id)]
    if cmd in (CMD_START, CMD_RECOVER, CMD_SPARE):
        out.append(put_u32(listen_port))
    elif cmd in (CMD_PRINT, CMD_METRICS, CMD_HEARTBEAT, CMD_EPOCH,
                 CMD_QUORUM, CMD_OBS, CMD_SUB, CMD_SNAP):
        out.append(put_str(message))
    elif cmd == CMD_BLOB:
        out += [put_u32(blob_version), put_u32(len(blob)), blob]
    send_all(sock, b"".join(out))


def put_blob_frame(version: int, blob: bytes) -> bytes:
    """The spare park reply: the cached compressed bootstrap blob behind
    a MAGIC_BLOB header (version 0 / empty payload = nothing cached)."""
    return b"".join([put_u32(MAGIC_BLOB), put_u32(version),
                     put_u32(len(blob)), blob])


def put_sched_frame(algo: str, ring_order: list[int]) -> bytes:
    """The Assignment's trailing schedule section (rabit_tpu.sched): the
    resolved algorithm name and the planned ring order.  An empty order
    means "execute the legacy identity ring" — the pre-schedule wire
    shape."""
    out = [put_str(algo), put_u32(len(ring_order))]
    out += [put_i32(r) for r in ring_order]
    return b"".join(out)


def read_sched_frame(sock) -> tuple[str, list[int]]:
    """Read one trailing schedule section; returns (algo, ring_order)."""
    algo = get_str(sock)
    ring_order = [get_i32(sock) for _ in range(get_u32(sock))]
    return algo, ring_order


def put_block_frame(version: int, origin: int, payload: bytes) -> bytes:
    """Tag one quorum-mode block: ``(version, origin_rank, payload)``.
    The tagged bytes ride INSIDE the ordinary length-framed link protocol
    (doc/partial_allreduce.md) — tagging is what lets a late contribution
    from an excluded round be recognized as a correction term, and what
    makes duplicate delivery over a skip tee idempotent."""
    return _U32.pack(version) + _I32.pack(origin) + payload


def read_block_frame(data: bytes) -> tuple[int, int, bytes]:
    """Parse one tagged block payload; returns (version, origin, bytes).
    Raises ValueError on anything too short to carry the tag (a torn or
    foreign frame from a stale-epoch writer)."""
    if len(data) < 8:
        raise ValueError(f"short block frame ({len(data)} bytes)")
    version = _U32.unpack_from(data, 0)[0]
    origin = _I32.unpack_from(data, 4)[0]
    return version, origin, data[8:]


def put_skip_frame(rank: int, epoch: int, version: int) -> bytes:
    """The quorum skip handshake a ring successor dials AROUND a silent
    predecessor with (MAGIC_SKIP + dialer rank + epoch + the round it is
    stuck on).  The acceptor validates the epoch, replays every tagged
    block it retains, and tees all later blocks onto the socket."""
    return b"".join([put_u32(MAGIC_SKIP), put_i32(rank), put_u32(epoch),
                     put_u32(version)])


def read_skip_frame(sock) -> tuple[int, int, int]:
    """Read the skip-handshake fields AFTER the dispatching caller
    consumed MAGIC_SKIP; returns (dialer_rank, epoch, version)."""
    rank = get_i32(sock)
    epoch = get_u32(sock)
    version = get_u32(sock)
    return rank, epoch, version


#: Journal frame header (rabit_tpu/ha, doc/ha.md): the durable store's
#: RTC2 layout applied to control-plane mutation records — magic, codec
#: byte (rabit_tpu.compress ids; 0 = identity), pad, crc32 over the
#: ENCODED payload, encoded length.  Integrity is checked before any
#: decode touches the bytes, so a torn tail record reads as ABSENT and
#: replay truncates to the last good record instead of crashing.
JOURNAL_MAGIC = b"RJL1"
_JHDR = struct.Struct("<4sBxxxII")


def put_journal_frame(kind: str, fields: dict | None = None,
                      codec: str = "zlib") -> bytes:
    """Encode one control-plane journal record (``{"kind": ..,
    <fields>}`` as canonical sorted-key JSON) behind the crc'd,
    codec-tagged RJL1 header.  The same bytes land in the
    ``rabit_ha_journal`` file and on the CMD_JOURNAL channel."""
    import json as _json

    payload = _json.dumps({"kind": kind, **(fields or {})},
                          sort_keys=True,
                          separators=(",", ":")).encode()
    codec_id = 0
    if codec and codec != "identity":
        from rabit_tpu.compress import get_codec

        c = get_codec(codec)
        payload = c.encode_bytes(payload)
        codec_id = c.codec_id
    import zlib as _zlib

    return _JHDR.pack(JOURNAL_MAGIC, codec_id, _zlib.crc32(payload),
                      len(payload)) + payload


def read_journal_frame(sock) -> tuple[str, dict]:
    """Read one journal frame off a blocking stream; returns ``(kind,
    fields)``.  Raises ValueError on a bad magic / crc mismatch /
    undecodable payload (the caller treats it as a torn tail) and
    ConnectionError on EOF."""
    head = recv_exact(sock, _JHDR.size)
    magic, codec_id, crc, n = _JHDR.unpack(head)
    if magic != JOURNAL_MAGIC:
        raise ValueError(f"bad journal magic {magic!r}")
    payload = recv_exact(sock, n) if n else b""
    return decode_journal_payload(codec_id, crc, payload)


def decode_journal_payload(codec_id: int, crc: int,
                           payload: bytes) -> tuple[str, dict]:
    """Shared integrity-check-then-decode of one journal payload (the
    socket reader above and the file/buffer reader in
    rabit_tpu/ha/journal.py both end here)."""
    import json as _json
    import zlib as _zlib

    if _zlib.crc32(payload) != crc:
        raise ValueError("journal frame crc mismatch")
    if codec_id != 0:
        from rabit_tpu.compress import get_codec_by_id

        try:
            payload = get_codec_by_id(codec_id).decode_bytes(payload)
        except Exception as exc:  # noqa: BLE001 — unknown codec/torn stream
            raise ValueError(f"journal frame undecodable: {exc!r}")
    try:
        obj = _json.loads(payload.decode())
        kind = str(obj.pop("kind"))
    except (ValueError, KeyError, UnicodeDecodeError) as exc:
        raise ValueError(f"journal record malformed: {exc!r}")
    return kind, obj


def journal_frames_from_buffer(
        buf: bytes) -> tuple[list[tuple[str, dict]], int, str | None]:
    """Parse every COMPLETE journal frame at the head of ``buf``.
    Returns ``(records, consumed_bytes, error)``: a trailing partial
    frame is simply not consumed (stream more bytes and retry); a frame
    that fails the magic/crc/decode checks stops parsing with ``error``
    set and nothing past the last good record consumed — the torn-tail
    truncation shape (doc/ha.md)."""
    records: list[tuple[str, dict]] = []
    off = 0
    while len(buf) - off >= _JHDR.size:
        magic, codec_id, crc, n = _JHDR.unpack_from(buf, off)
        if magic != JOURNAL_MAGIC:
            return records, off, f"bad journal magic {magic!r}"
        if len(buf) - off - _JHDR.size < n:
            break  # partial tail frame: wait for more bytes
        payload = bytes(buf[off + _JHDR.size:off + _JHDR.size + n])
        try:
            records.append(decode_journal_payload(codec_id, crc, payload))
        except ValueError as exc:
            return records, off, str(exc)
        off += _JHDR.size + n
    return records, off, None


def parse_addrs(spec: str) -> list[tuple[str, int]]:
    """Parse a ``rabit_tracker_addrs`` value ("host:port,host:port",
    primary first) into an address list for :func:`tracker_rpc`'s
    failover rotation.  Malformed entries are skipped — a bad config
    must degrade to the primary address, not crash a worker."""
    out: list[tuple[str, int]] = []
    for part in (spec or "").split(","):
        part = part.strip()
        if not part or ":" not in part:
            continue
        host, _, port_s = part.rpartition(":")
        try:
            out.append((host, int(port_s)))
        except ValueError:
            continue
    return out


def recv_blob_frame(sock) -> tuple[int, bytes]:
    """Read one MAGIC_BLOB frame; returns (version, payload)."""
    magic = get_u32(sock)
    if magic != MAGIC_BLOB:
        raise ValueError(f"bad blob magic {magic:#x}")
    version = get_u32(sock)
    n = get_u32(sock)
    return version, recv_exact(sock, n) if n else b""


def put_snap_frame(digest: str, total: int, off: int,
                   payload: bytes) -> bytes:
    """Encode one CMD_SNAP reply (doc/delivery.md): MAGIC_SNAP, the
    content digest the bytes hash to, the blob's TOTAL size, the chunk
    offset, then the chunk itself.  A miss is ``("", 0, 0, b"")`` —
    the digest is not (yet) held, the subscriber retries.  The same
    bytes ride a direct socket, a relay route frame, and the relay's
    digest-keyed cache."""
    return b"".join([put_u32(MAGIC_SNAP), put_str(digest), put_u32(total),
                     put_u32(off), put_u32(len(payload)), payload])


def read_snap_frame(sock) -> tuple[str, int, int, bytes]:
    """Read one snap frame off a blocking stream; returns ``(digest,
    total, off, chunk)``.  Raises ValueError on a bad magic or an
    oversized field and ConnectionError on EOF."""
    magic = get_u32(sock)
    if magic != MAGIC_SNAP:
        raise ValueError(f"bad snap magic {magic:#x}")
    digest = get_str(sock)
    total = get_u32(sock)
    off = get_u32(sock)
    n = get_u32(sock)
    if n > 1 << 30:
        raise ValueError(f"oversized snap chunk ({n} bytes)")
    return digest, total, off, recv_exact(sock, n) if n else b""


def snap_frame_from_bytes(data: bytes) -> tuple[str, int, int, bytes]:
    """Parse one COMPLETE snap frame held in memory (a relay route-frame
    payload).  Raises ValueError on bad magic or a torn frame."""
    if len(data) < 8:
        raise ValueError(f"short snap frame ({len(data)} bytes)")
    if _U32.unpack_from(data, 0)[0] != MAGIC_SNAP:
        raise ValueError(f"bad snap magic {_U32.unpack_from(data, 0)[0]:#x}")
    dn = _U32.unpack_from(data, 4)[0]
    if len(data) < 8 + dn + 12:
        raise ValueError(f"torn snap frame ({len(data)} bytes)")
    digest = data[8:8 + dn].decode()
    total = _U32.unpack_from(data, 8 + dn)[0]
    off = _U32.unpack_from(data, 12 + dn)[0]
    n = _U32.unpack_from(data, 16 + dn)[0]
    if len(data) != 20 + dn + n:
        raise ValueError(f"torn snap frame ({len(data)} of {20 + dn + n})")
    return digest, total, off, data[20 + dn:]


@dataclass
class BatchMsg:
    """One relayed sub-message inside a CMD_BATCH envelope (see module
    docstring): the child's hello fields plus the child's peer host (the
    relay observed it; the tracker must not record the relay's address)
    and the relay-clock receive stamp."""

    task_id: str
    cmd: int
    prev_rank: int = -1
    host: str = ""
    listen_port: int = 0
    payload: bytes = b""
    recv_ts: float = 0.0


def put_batch_frame(msgs: list[BatchMsg]) -> bytes:
    """Encode one CMD_BATCH envelope (relay -> tracker): N coalesced
    sub-messages, one framed write per flush interval."""
    out = [put_u32(len(msgs))]
    for m in msgs:
        out += [put_str(m.task_id), put_u32(m.cmd), put_i32(m.prev_rank),
                put_str(m.host), put_u32(m.listen_port),
                put_u32(len(m.payload)), m.payload,
                put_str(f"{m.recv_ts:.6f}")]
    return b"".join(out)


def read_batch_frame(sock) -> list[BatchMsg]:
    """Read one CMD_BATCH envelope off the relay channel."""
    msgs = []
    for _ in range(get_u32(sock)):
        task_id = get_str(sock)
        cmd = get_u32(sock)
        prev_rank = get_i32(sock)
        host = get_str(sock)
        listen_port = get_u32(sock)
        n = get_u32(sock)
        payload = recv_exact(sock, n) if n else b""
        recv_ts = float(get_str(sock) or "0")
        msgs.append(BatchMsg(task_id, cmd, prev_rank, host, listen_port,
                             payload, recv_ts))
    return msgs


def put_route_frame(task_id: str, flags: int, payload: bytes) -> bytes:
    """Encode one tracker -> relay routed reply: deliver ``payload`` to
    the parked child ``task_id`` (close it when ``flags & ROUTE_CLOSE``);
    task_id "" is the batch ACK (JSON payload, see module docstring)."""
    return b"".join([put_str(task_id), put_u32(flags),
                     put_u32(len(payload)), payload])


def read_route_frame(sock) -> tuple[str, int, bytes]:
    """Read one routed reply off the relay channel; returns
    ``(task_id, flags, payload)``."""
    task_id = get_str(sock)
    flags = get_u32(sock)
    n = get_u32(sock)
    return task_id, flags, recv_exact(sock, n) if n else b""


#: Hard cap on one encoded metric-delta frame.  Deltas are BOUNDED by
#: design (a few counters + fixed-bucket histograms per rank); anything
#: larger is a torn frame or a foreign writer, not a bigger delta.
DELTA_MAX_BYTES = 4 << 20


def put_delta_frame(doc: dict) -> bytes:
    """Encode one coalesced metric-delta document (rabit_tpu/obs/stream.py
    schema) as a self-delimiting frame: MAGIC_DELTA + encoded length +
    zlib-compressed canonical JSON.  The same bytes ride as a CMD_OBS
    BatchMsg payload (relay -> tracker) and over a direct socket."""
    import json as _json
    import zlib as _zlib

    payload = _zlib.compress(_json.dumps(
        doc, sort_keys=True, separators=(",", ":")).encode())
    if len(payload) > DELTA_MAX_BYTES:
        raise ValueError(f"oversized delta frame ({len(payload)} bytes)")
    return put_u32(MAGIC_DELTA) + put_u32(len(payload)) + payload


def read_delta_frame(sock) -> dict:
    """Read one delta frame off a blocking stream; raises ValueError on a
    bad magic / oversized length / undecodable payload (a torn frame) and
    ConnectionError on EOF."""
    magic = get_u32(sock)
    if magic != MAGIC_DELTA:
        raise ValueError(f"bad delta magic {magic:#x}")
    n = get_u32(sock)
    if n > DELTA_MAX_BYTES:
        raise ValueError(f"oversized delta frame ({n} bytes)")
    return _decode_delta_payload(recv_exact(sock, n) if n else b"")


def delta_frame_from_bytes(data: bytes) -> dict:
    """Parse one COMPLETE delta frame held in memory (a CMD_OBS BatchMsg
    payload).  Raises ValueError on bad magic, a length that disagrees
    with the buffer (torn frame), or an undecodable payload."""
    if len(data) < 8:
        raise ValueError(f"short delta frame ({len(data)} bytes)")
    magic = _U32.unpack_from(data, 0)[0]
    if magic != MAGIC_DELTA:
        raise ValueError(f"bad delta magic {magic:#x}")
    n = _U32.unpack_from(data, 4)[0]
    if n > DELTA_MAX_BYTES:
        raise ValueError(f"oversized delta frame ({n} bytes)")
    if len(data) != 8 + n:
        raise ValueError(f"torn delta frame ({len(data)} of {8 + n} bytes)")
    return _decode_delta_payload(data[8:])


def _decode_delta_payload(payload: bytes) -> dict:
    import json as _json
    import zlib as _zlib

    try:
        doc = _json.loads(_zlib.decompress(payload).decode())
    except (ValueError, _zlib.error, UnicodeDecodeError) as exc:
        raise ValueError(f"delta frame undecodable: {exc!r}")
    if not isinstance(doc, dict):
        raise ValueError("delta frame payload is not an object")
    return doc


@dataclass
class Hello:
    """One parsed worker hello (the event-loop serving path's unit of
    work — see :func:`hello_parser`)."""

    cmd: int
    prev_rank: int
    task_id: str
    listen_port: int = 0
    message: str = ""
    blob_version: int = 0
    blob: bytes = b""


def hello_parser():
    """Generator-based INCREMENTAL parser of one worker hello — the
    event-loop tracker (and the relay's child loop) cannot block a
    thread per connection on ``recv_exact``, so this parser yields the
    number of bytes it needs next and is fed exact chunks by
    :class:`StreamParser`; it returns a :class:`Hello` (or raises
    ValueError on a bad magic/overlong field).  One generator instance
    parses exactly one hello."""
    magic = _U32.unpack((yield 4))[0]
    if magic != MAGIC_HELLO:
        raise ValueError(f"bad hello magic {magic:#x}")
    cmd = _U32.unpack((yield 4))[0]
    prev_rank = _I32.unpack((yield 4))[0]
    n = _U32.unpack((yield 4))[0]
    if n > 1 << 16:
        raise ValueError(f"oversized task_id ({n} bytes)")
    task_id = (yield n).decode() if n else ""
    if cmd in (CMD_START, CMD_RECOVER, CMD_SPARE):
        listen_port = _U32.unpack((yield 4))[0]
        return Hello(cmd, prev_rank, task_id, listen_port=listen_port)
    if cmd in (CMD_PRINT, CMD_METRICS, CMD_HEARTBEAT, CMD_EPOCH,
               CMD_QUORUM, CMD_OBS, CMD_SUB, CMD_SNAP):
        n = _U32.unpack((yield 4))[0]
        if n > 64 << 20:
            raise ValueError(f"oversized message ({n} bytes)")
        message = (yield n).decode() if n else ""
        return Hello(cmd, prev_rank, task_id, message=message)
    if cmd == CMD_BLOB:
        version = _U32.unpack((yield 4))[0]
        n = _U32.unpack((yield 4))[0]
        if n > 1 << 30:
            raise ValueError(f"oversized blob ({n} bytes)")
        blob = (yield n) if n else b""
        return Hello(cmd, prev_rank, task_id, blob_version=version,
                     blob=blob)
    # CMD_SHUTDOWN / CMD_BATCH / CMD_JOURNAL (and anything future): the
    # base hello is the whole message.
    return Hello(cmd, prev_rank, task_id)


class StreamParser:
    """Drives a byte-count generator parser over a nonblocking stream:
    ``feed()`` buffered chunks as they arrive; ``done`` flips when the
    parser returned (``result`` holds its return value).  Raises
    whatever the parser raises (bad magic, oversized field)."""

    def __init__(self, gen):
        self._gen = gen
        self._need = next(gen)
        self._buf = bytearray()
        self.done = False
        self.result = None

    def feed(self, data: bytes) -> bool:
        """Feed newly received bytes; returns True when parsing
        completed (extra bytes beyond the message stay in ``rest()``)."""
        if self.done:
            self._buf += data
            return True
        self._buf += data
        while len(self._buf) >= self._need:
            chunk = bytes(self._buf[:self._need])
            del self._buf[:self._need]
            try:
                self._need = self._gen.send(chunk)
            except StopIteration as stop:
                self.result = stop.value
                self.done = True
                return True
        return False

    def rest(self) -> bytes:
        """Bytes received beyond the parsed message (a pipelined client
        — e.g. a relay that wrote its first batch behind the hello)."""
        return bytes(self._buf)


class TimedAck(int):
    """An ACK that carries the tracker's clock stamp (metrics/heartbeat
    replies).  Compares equal to the plain u32 ACK value, so existing
    ``reply == ACK`` callers are unaffected; ``offset``/``err`` expose the
    NTP-style midpoint estimate of tracker_clock - worker_clock."""

    server_ts: float
    t_send: float
    t_recv: float

    def __new__(cls, ack: int, server_ts: float, t_send: float,
                t_recv: float) -> "TimedAck":
        self = super().__new__(cls, ack)
        self.server_ts = server_ts
        self.t_send = t_send
        self.t_recv = t_recv
        return self

    @property
    def rtt(self) -> float:
        return max(self.t_recv - self.t_send, 0.0)

    @property
    def offset(self) -> float:
        """tracker_ts - worker_ts; project with worker_ts + offset."""
        return self.server_ts - (self.t_send + self.t_recv) / 2.0

    @property
    def err(self) -> float:
        """Half the round trip — the offset estimate's error bound."""
        return self.rtt / 2.0


class TrackerUnreachable(ConnectionError):
    """The tracker could not be reached (or never replied) within the retry
    budget.  Raised by :func:`tracker_rpc` so callers can fail fast with a
    clear diagnosis instead of blocking forever on a dead tracker."""


def tracker_rpc(
    host: str,
    port: int,
    cmd: int,
    task_id: str,
    *,
    prev_rank: int = -1,
    listen_port: int = 0,
    message: str = "",
    timeout: float = 10.0,
    reply_timeout: float | None = None,
    retries: int = 5,
    backoff: float = 0.1,
    backoff_cap: float = 2.0,
    rng: random.Random | None = None,
    addrs: "list[tuple[str, int]] | None" = None,
    job: str = "",
) -> "Assignment | int":
    """The one resilient client path for every Python-side tracker message
    (bootstrap check-ins, print, metrics, heartbeat, shutdown).

    One RPC = fresh connection, hello, reply.  Every socket operation is
    bounded: ``timeout`` covers connect and the control replies,
    ``reply_timeout`` (default: ``timeout``) separately covers waiting for a
    START/RECOVER assignment — the tracker legitimately holds those until
    the wave of world_size check-ins is complete, so callers usually want a
    larger bound there.  Transport failures (refused, reset, torn reply,
    timed-out read) are retried up to ``retries`` more times with
    exponential backoff plus jitter (``backoff * 2^attempt``, capped at
    ``backoff_cap``, scaled by a uniform 0.5-1.0 factor so a restart wave
    doesn't stampede the tracker); when the budget is exhausted the last
    error surfaces as :class:`TrackerUnreachable`.

    Returns the :class:`Assignment` for START/RECOVER, the parsed reply
    dict for EPOCH (``{"epoch", "world", "rewave"}``) and QUORUM (the
    round's exclusion record, or ``{"decided": false}``), the u32 ACK value
    otherwise — as a :class:`TimedAck` (ACK plus the tracker's clock stamp
    and the local send/recv bracket) for METRICS/HEARTBEAT.  Retrying
    START/RECOVER is safe: the tracker replaces a task id's stale pending
    entry on re-check-in (Tracker._register).  SPARE does not ride this
    path: its connection is long-lived by design (park-then-promote; see
    rabit_tpu.elastic.client).

    ``addrs`` is the HA failover list (``rabit_tracker_addrs``,
    doc/ha.md): additional tracker addresses — a warm standby — the
    retry loop rotates through when an attempt fails, so a primary
    tracker death surfaces as one failed attempt followed by the same
    RPC landing on whichever address answers, not as
    :class:`TrackerUnreachable`.  ``(host, port)`` stays the first
    candidate; duplicates are dropped.
    """
    rng = rng if rng is not None else random
    task_id = join_job(job, task_id)
    retries = max(int(retries), 0)
    cands = [(host, int(port))]
    for a in addrs or []:
        t = (a[0], int(a[1]))
        if t not in cands:
            cands.append(t)
    last_err: Exception | None = None
    for attempt in range(retries + 1):
        host, port = cands[attempt % len(cands)]
        try:
            with socket.create_connection((host, int(port)), timeout=timeout) as sock:
                sock.settimeout(timeout)
                t_send = time.time()
                send_hello(sock, cmd, task_id, prev_rank=prev_rank,
                           listen_port=listen_port, message=message)
                if cmd in (CMD_START, CMD_RECOVER):
                    sock.settimeout(reply_timeout if reply_timeout is not None
                                    else timeout)
                    return Assignment.recv(sock)
                if cmd == CMD_SNAP:
                    # binary reply: the snap frame IS the message, no ACK
                    return read_snap_frame(sock)
                ack = get_u32(sock)
                if cmd in (CMD_METRICS, CMD_HEARTBEAT):
                    # timestamped reply (see module docstring): the stamp
                    # plus the local send/recv bracket is one clock sample
                    server_ts = float(get_str(sock))
                    return TimedAck(ack, server_ts, t_send, time.time())
                if cmd in (CMD_EPOCH, CMD_QUORUM, CMD_OBS, CMD_SUB):
                    import json as _json

                    return _json.loads(get_str(sock))
                return ack
        except (ConnectionError, OSError) as exc:  # socket.timeout is OSError
            last_err = exc
            if attempt < retries:
                delay = min(backoff * (2 ** attempt), backoff_cap)
                time.sleep(delay * (0.5 + 0.5 * rng.random()))
    raise TrackerUnreachable(
        f"tracker {host}:{port} unreachable: {retries + 1} attempt(s) failed "
        f"(cmd={cmd}, task_id={task_id!r}); last error: {last_err!r}"
    )
