"""The tracker: rank assignment, topology, bootstrap waves, worker restart
coordination.

Capability parity with dmlc-core's tracker (the piece the reference
outsources — SURVEY.md C18): it launches nothing itself (see launcher.py);
it accepts worker check-ins, assigns stable ranks keyed by task id, builds
the reduction tree + ring, hands every worker the full peer table, and
funnels worker ``print``/``shutdown`` messages.  Recovery is wave-based: a
worker death cascades into every survivor reconnecting with ``recover``
while the launcher restarts the dead one with ``start``; once world_size
check-ins are pending, the tracker broadcasts a fresh assignment with a
bumped epoch.

The tracker is also the job-level telemetry aggregator
(doc/observability.md): it keeps a structured event timeline (bootstrap/
recovery waves; the robust engine's ``recover_stats``/``failure_detected``
prints converted to events at ingest), accepts ``CMD_METRICS`` snapshots
from workers, and writes ``telemetry.json`` into ``RABIT_OBS_DIR`` when
the job ends.

Liveness (doc/fault_tolerance.md): workers renewing a ``CMD_HEARTBEAT``
lease get per-rank failure detection for SILENT failures — a preempted VM
or frozen process stops renewing, its lease expires after
``LEASE_FACTOR x interval``, the tracker emits a ``lease_expired`` event
and invokes the pluggable ``on_suspect(task_id)`` callback.  The launcher
wires that callback to SIGKILL-and-restart the suspect, converting a hang
into the recoverable-death shape the wave-based recovery already handles.

Elastic worlds (doc/elasticity.md): membership is a monotonically
increasing world epoch ``(epoch, world_size, rank_map)`` owned by
``rabit_tpu.elastic.MembershipManager``.  Workers launched with
``rabit_spare=1`` check in with ``CMD_SPARE``, receive the cached
compressed bootstrap blob (``CMD_BLOB`` uploads from rank 0), and park on
a warm socket; a dead rank's slot is filled by promoting a parked spare
into the recovery wave (``spare_promoted``), and when the pool is empty
past ``shrink_after_sec`` the wave closes SHRUNK (``world_shrunk``) —
ranks reassign densely and the job keeps making progress — then grows
back (``world_grown``) when spares return, at the next version boundary
(workers poll ``CMD_EPOCH`` between checkpoints and re-enter a wave when
the reply carries ``rewave``).

Quorum collectives (doc/partial_allreduce.md): with ``quorum=`` set the
tracker owns the per-round **exclusion record** — ``CMD_QUORUM`` reports
name the blocks a rank holds, the first report meeting the K-of-N quorum
freezes ``(epoch, version) -> (excluded_ranks, corrections)``, and every
rank (including the excluded straggler, arriving rounds late) folds the
same frozen record, so quorum folds and post-recovery replay stay
bitwise deterministic.  Late blocks fold as corrections at the next
record after delivery (``contribution_late``/``correction_folded``); an
epoch boundary settles undelivered corrections by dropping them with
``correction_dropped`` evidence (a shrunk rank is excluded permanently,
not buffered); a rank excluded ``quorum_flag_after`` rounds in a row
feeds the SAME degraded-link avoid-set machinery as a slow link, so the
next plan moves the persistent straggler off the ring hot path.

Serving at scale (doc/scaling.md): every short-lived RPC (heartbeat,
metrics, epoch poll, quorum report, print, blob, shutdown) is served by
ONE ``selectors``-based reactor thread — no thread-per-connection spawn,
no per-heartbeat thread churn at O(10^4) workers.  Only wave-held
connections (START/RECOVER check-ins parked until the wave completer
answers, CMD_SPARE warm sockets) and relay channels leave the reactor
for dedicated handling.  ``reactor=False`` keeps the legacy
thread-per-connection path (the scale sweep's comparison arm; the wire
bytes are identical either way).  The listen backlog is the
``rabit_tracker_backlog`` config key.  A relay (``rabit_tpu.relay``)
checks in with ``CMD_BATCH`` and holds one persistent channel: its
children's coalesced RPCs arrive as framed batches, replies (wave
assignments, park frames) are routed back by task id — so a world of N
workers behind R relays costs the root tracker O(R) connections, not
O(N), for bootstrap and liveness alike.

Multi-tenant service (rabit_tpu.service, doc/service.md): every worker
hello is mapped through ONE routing seam (``_route_hello``) to the
tracker that owns it — the base class maps every id to itself, so plain
single-job serving is byte-for-byte unrouted.  ``headless=True`` builds
a job PARTITION (no listen socket, no threads): a CollectiveService
multiplexes many such partitions on its one reactor, drives their
``_lease_tick``/``_wave_tick`` from one monitor pair, and namespaces
their journal records and telemetry files by job key.

Collective schedules (doc/scheduling.md): every wave is planned by
``rabit_tpu.sched`` — ``rabit_schedule=auto|tree|ring|swing`` picks the
ring layout over the mesh model, and worker ``slow_link`` reports
(``link_degraded`` events) flag degraded links the next plan routes
around.  The Assignment's PREFIX keeps the legacy tree+ring (the native
client's fixed data plane); the planned ring order trails the rank_map
for schedule-aware executors.  Repair replanning rides the same
``rewave`` epoch boundary as grow-back, so a degraded link is healed by
one ordinary recovery wave (``schedule_planned``/``schedule_repaired``
events).
"""

from __future__ import annotations

import hashlib
import json
import os
import queue
import selectors
import socket
import threading
import time
from collections import deque
from dataclasses import dataclass
from typing import Callable

from rabit_tpu import sched
from rabit_tpu.config import Config
from rabit_tpu.elastic.membership import CLOSE, MembershipManager
from rabit_tpu.obs import diagnose as obs_diagnose
from rabit_tpu.obs import stream as obs_stream
from rabit_tpu.obs.events import event_from_stats_line
from rabit_tpu.obs.metrics import GLOBAL_REGISTRY
from rabit_tpu.quorum import QuorumTable
from rabit_tpu.tracker import protocol as P

#: telemetry.json envelope version (bump on incompatible change).
TELEMETRY_SCHEMA = 1


def _aggregate_incidents(jobs: dict) -> dict:
    """The scrape's top-level incidents digest: every job's open
    incidents flattened (job-stamped) so a poller reads one section
    regardless of tenancy.  A CollectiveService rebuilds this after
    merging its tenants' job docs."""
    open_inc: list[dict] = []
    for job_key, jdoc in sorted(jobs.items()):
        for inc in ((jdoc.get("incidents") or {}).get("open") or ()):
            open_inc.append({**inc, "job": job_key})
    return {"schema": obs_diagnose.DIAG_SCHEMA,
            "n_open": len(open_inc), "open": open_inc}


@dataclass
class _Pending:
    conn: socket.socket
    task_id: str
    listen_port: int
    host: str
    prev_rank: int
    cmd: int = P.CMD_START
    origin: str = "worker"  # "worker" | "spare" (promoted from the pool)


def _conn_dead(conn: socket.socket) -> bool:
    """True when the peer of a held-open connection has hung up (EOF/RST
    visible without consuming data).  Workers never send past their hello,
    so a readable-with-EOF socket means the worker abandoned this wave."""
    try:
        return conn.recv(1, socket.MSG_PEEK | socket.MSG_DONTWAIT) == b""
    except (BlockingIOError, InterruptedError):
        return False  # open and idle — the normal pending state
    except OSError:
        return True


@dataclass
class _Lease:
    expires: float   # time.monotonic() deadline
    interval: float  # the worker's renewal cadence (seconds)
    rank: int        # rank the worker reported at renewal (-1 pre-assignment)


class _RelayChannel:
    """One relay's persistent duplex channel.  Reads (batch frames) stay
    on the channel's dedicated server thread; writes (routed replies,
    batch ACKs) are serialized through a queue drained by one writer
    thread, so any tracker thread — the wave completer, the reactor, the
    channel server itself — can enqueue without blocking or locking
    around a socket send."""

    def __init__(self, sock: socket.socket, relay_id: str):
        self.sock = sock
        self.relay_id = relay_id
        self.dead = False
        #: live virtual connections by task id (CMD_HANGUP folds flip
        #: the matching one dead so wave purges see the EOF)
        self.vconns: dict[str, "_RelayedConn"] = {}
        self._q: queue.Queue = queue.Queue()
        self._writer = threading.Thread(target=self._drain, daemon=True,
                                        name=f"rabit-relay-tx-{relay_id}")
        self._writer.start()

    def _drain(self) -> None:
        while True:
            frame = self._q.get()
            if frame is None or self.dead:
                break
            try:
                self.sock.sendall(frame)
            except OSError:
                self.dead = True
                break

    def send_route(self, task_id: str, flags: int, payload: bytes) -> bool:
        """Enqueue one routed frame; False when the channel is dead (the
        caller treats the child as a hung-up connection)."""
        if self.dead:
            return False
        self._q.put(P.put_route_frame(task_id, flags, payload))
        return True

    def close(self) -> None:
        self.dead = True
        self._q.put(None)
        for how in (socket.SHUT_RDWR,):
            try:
                self.sock.shutdown(how)
            except OSError:
                pass
        try:
            self.sock.close()
        except OSError:
            pass


class _RelayedConn:
    """A virtual worker connection riding a relay channel: duck-types
    the few socket methods the wave machinery touches (``sendall``,
    ``close``, ``recv`` for the ``_conn_dead`` peek, ``settimeout``),
    routing bytes to the child parked at the relay.  A dead channel
    makes every relayed conn read as hung up, so the ordinary
    dead-pending purge and spare reaping clean up after a relay death —
    a dead relay is a reconnect, not a membership event."""

    def __init__(self, channel: _RelayChannel, task_id: str):
        self._channel = channel
        self.task_id = task_id
        self._closed = False
        self.child_dead = False  # relay reported the child hung up
        channel.vconns[task_id] = self

    def sendall(self, data: bytes) -> None:
        if self.child_dead or not self._channel.send_route(
                self.task_id, 0, bytes(data)):
            raise OSError("relay channel down")

    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        if self._channel.vconns.get(self.task_id) is self:
            self._channel.vconns.pop(self.task_id, None)
        self._channel.send_route(self.task_id, P.ROUTE_CLOSE, b"")

    def recv(self, n: int, flags: int = 0) -> bytes:
        if self._channel.dead or self._closed or self.child_dead:
            return b""  # reads as EOF: the purge paths drop us
        raise BlockingIOError  # open and idle — the normal pending state

    def settimeout(self, timeout) -> None:  # noqa: ARG002 — socket parity
        pass


class _BufferedSock:
    """A recv shim serving buffered bytes first — covers a client that
    pipelined bytes behind the message the reactor already parsed."""

    def __init__(self, sock: socket.socket, rest: bytes):
        self._sock = sock
        self._rest = bytearray(rest)

    def recv(self, n: int) -> bytes:
        if self._rest:
            out = bytes(self._rest[:n])
            del self._rest[:n]
            return out
        return self._sock.recv(n)


class _RConn:
    """Per-connection reactor state: the incremental hello parser, the
    pending reply bytes, and the read deadline for torn hellos."""

    __slots__ = ("sock", "addr", "parser", "out", "deadline")

    def __init__(self, sock: socket.socket, addr, deadline: float):
        self.sock = sock
        self.addr = addr
        self.parser = P.StreamParser(P.hello_parser())
        self.out = bytearray()
        self.deadline = deadline


def assign_ranks(
    wave: list[tuple[str, str]],
    world_size: int,
    prev_ranks: dict[str, int],
    host_order: list[str] | None = None,
) -> dict[str, int]:
    """Topology-aware rank assignment (pure, unit-testable).

    ``wave`` is ``[(task_id, host), ...]`` in check-in order.  Precedence:

    1. stable re-admission — a task id seen before keeps its rank (the
       reference tracker's recover contract, ReConnectLinks
       allreduce_base.cc:263-438);
    2. launcher-numbered ids — ``int(task_id)`` when valid and free, so
       mock-kill specs and restart counters line up;
    3. the rest are grouped BY HOST and handed contiguous free ranks, so
       ring neighbors (rank±1) and tree subtrees stay on one host and
       cross-host traffic rides as few DCN hops as possible (the reference
       tracker is host-blind here; BASELINE north star: topology-aware).

    ``host_order`` ranks the host groups (e.g. a TPU slice's physical
    worker order, see tpu_slice_host_order); unlisted hosts follow in
    first-seen order.
    """
    ranks: dict[str, int] = {}
    taken: set[int] = set()
    for task_id, _host in wave:
        prev = prev_ranks.get(task_id)
        # Two task ids can hold the SAME stale rank (one freed it in an
        # earlier wave, another inherited it, then the first rejoins):
        # first-in-wave wins, the other falls through to a fresh slot.
        if prev is not None and 0 <= prev < world_size and prev not in taken:
            ranks[task_id] = prev
            taken.add(prev)
    for task_id, _host in wave:
        if task_id in ranks:
            continue
        try:
            cand = int(task_id)
        except ValueError:
            continue
        if 0 <= cand < world_size and cand not in taken:
            ranks[task_id] = cand
            taken.add(cand)
    # Host-grouped fill of the remaining slots.
    order_index = {h: i for i, h in enumerate(host_order or [])}
    groups: dict[str, list[str]] = {}
    first_seen: dict[str, int] = {}
    for i, (task_id, host) in enumerate(wave):
        if task_id in ranks:
            continue
        groups.setdefault(host, []).append(task_id)
        first_seen.setdefault(host, i)
    free = iter(r for r in range(world_size) if r not in taken)
    for host in sorted(
        groups, key=lambda h: (order_index.get(h, len(order_index)), first_seen[h])
    ):
        for task_id in groups[host]:
            ranks[task_id] = next(free)
    return ranks


def tpu_slice_host_order() -> list[str] | None:
    """Physical host order of the current TPU slice from TPU-VM metadata.

    Cloud TPU VMs export ``TPU_WORKER_HOSTNAMES`` (comma-separated, in
    worker-id order — which walks the slice's ICI topology) and
    ``TPU_WORKER_ID``.  Ordering tracker ranks along it lays the rabit ring
    over ICI neighbors instead of arbitrary DCN paths (BASELINE north star:
    "tracker discovers v5e pod topology").  Returns None off-TPU.
    """
    names = os.environ.get("TPU_WORKER_HOSTNAMES", "")
    hosts = [h.strip() for h in names.split(",") if h.strip()]
    return hosts or None


class Tracker:
    def __init__(self, world_size: int, host: str = "127.0.0.1", port: int = 0,
                 quiet: bool = False, topology: str = "auto",
                 host_order: list[str] | None = None,
                 obs_dir: str | None = None,
                 conn_timeout_sec: float = 60.0,
                 on_suspect: Callable[[str], None] | None = None,
                 shrink_after_sec: float = 0.0,
                 min_world: int = 1,
                 promote_after_sec: float = 0.25,
                 schedule: str = "auto",
                 sched_mesh: str = "",
                 sched_repair: bool = True,
                 sched_wait_share: float = 0.25,
                 quorum: str = "",
                 quorum_flag_after: int = 3,
                 reactor: bool = True,
                 backlog: int | None = None,
                 max_messages: int = 4096,
                 journal=None,
                 resume_from=None,
                 listen_sock: socket.socket | None = None,
                 ha_tick_sec: float | None = None,
                 job: str = "",
                 headless: bool = False):
        #: CURRENT world size — mutable under elastic membership (shrink/
        #: grow); ``base_world`` is the launch size and grow-back target.
        self.world_size = world_size
        self.base_world = world_size
        # The membership manager owns the world-epoch line (epoch,
        # world_size, rank_map) and every resize decision; the tracker
        # feeds it check-in counts/wave ages under self._lock and mirrors
        # the committed world into self.world_size.  shrink_after_sec=0
        # keeps the legacy block-until-full contract.
        self.elastic = MembershipManager(
            world_size, min_world=min_world,
            shrink_after_sec=shrink_after_sec,
            promote_after_sec=promote_after_sec)
        self.quiet = quiet
        # Per-connection read deadline: a client that connects and sends a
        # torn/partial hello must not pin a _handle thread (and its socket)
        # forever — the read times out and the connection is dropped without
        # wedging the pending wave.  0 disables (tests of the blocking path).
        self.conn_timeout_sec = conn_timeout_sec
        # Liveness hook: called (from the lease monitor thread) with the
        # task_id whose heartbeat lease expired.  The launcher wires this to
        # SIGKILL-and-restart; standalone deployments can plug in their own
        # remediation.  Exceptions are swallowed — detection must not kill
        # the tracker.
        self.on_suspect = on_suspect
        self._leases: dict[str, _Lease] = {}
        # Job-level telemetry (doc/observability.md): structured events
        # (bootstrap/recovery waves, recover_stats converted from prints),
        # the latest metric snapshot per rank (CMD_METRICS), restart
        # counts — written to <obs_dir>/telemetry.json when the job ends.
        if obs_dir is None:
            obs_dir = os.environ.get("RABIT_OBS_DIR", "") or None
        self.obs_dir = obs_dir
        self.events: list[dict] = []
        self.snapshots: dict[int, dict] = {}  # rank -> latest shipped snapshot
        # Live telemetry plane (doc/observability.md): streamed metric
        # deltas (piggybacked on CMD_METRICS snapshots, or relay-coalesced
        # CMD_OBS batch frames) fold into per-rank/per-job rollups that a
        # CMD_OBS scrape renders live, without touching a worker.
        self._stream = obs_stream.StreamRollup()
        # Diagnosis plane (doc/observability.md): the HealthMonitor
        # evaluates detection windows over the rollup + control-plane
        # deltas from the lease-monitor thread (never the reactor) and
        # opens/resolves structured incidents; confirmed degraded-link
        # incidents feed the avoid-set repair machinery (_flag_link).
        self._health = obs_diagnose.HealthMonitor()
        self._diag_next = 0.0   # monotonic deadline of the next window
        self._diag_ev_idx = 0   # events already consumed by past windows
        self._delta_ranks: set[str] = set()  # first-fold evidence, per rank
        self._obs_scraped = False  # first-scrape evidence (one event)
        self.telemetry: dict | None = None
        self._started_at = time.time()
        self._n_starts: dict[str, int] = {}  # task_id -> CMD_START check-ins
        self._telemetry_written = False
        self._telemetry_flushed = threading.Event()
        # topology: "auto" uses TPU slice metadata when present, "tpu"
        # requires it, anything else is plain host grouping.
        if host_order is None and topology in ("auto", "tpu"):
            host_order = tpu_slice_host_order()
            if topology == "tpu" and host_order is None:
                raise RuntimeError(
                    "topology='tpu' but TPU_WORKER_HOSTNAMES is not set"
                )
        self.host_order = host_order
        # Collective schedule planning (rabit_tpu.sched): the algorithm
        # name, the mesh-model spec, and whether degraded-link reports
        # trigger a repair replan.  Link flags persist as TASK pairs so
        # a resize between flag and repair cannot mis-aim the avoid set.
        if schedule not in sched.ALGOS:
            raise ValueError(f"schedule={schedule!r} not in {sched.ALGOS}")
        self.schedule = schedule
        self.sched_mesh = sched_mesh
        self.sched_repair = bool(sched_repair)
        self.sched_wait_share = float(sched_wait_share)
        self._link_flags: set[tuple[str, str]] = set()  # (src_task, dst_task)
        self._repair_wanted = False
        # Quorum collectives (rabit_tpu/quorum, doc/partial_allreduce.md):
        # the per-round exclusion-record ledger, or None when quorum mode
        # is off.  _last_ring remembers the most recent planned ring order
        # so a persistent straggler's INCOMING link can be flagged into
        # the repair avoid set.
        self._quorum = (QuorumTable(quorum, flag_after=quorum_flag_after)
                        if quorum else None)
        self._last_ring: list[int] = []
        # Serving model (doc/scaling.md): reactor=True (default) serves
        # every short-lived RPC on one selectors loop; False keeps the
        # legacy thread-per-connection path (wire-identical — the scale
        # sweep's comparison arm).  The listen backlog comes from the
        # rabit_tracker_backlog config key unless pinned by the caller:
        # a 4096-worker wave is an accept storm, and a short backlog
        # turns it into SYN-retransmit latency.
        self._reactor = bool(reactor)
        if backlog is None:
            backlog = Config().get_int("rabit_tracker_backlog", 1024)
        self.backlog = max(int(backlog), 1)
        # Multi-tenant service seams (rabit_tpu.service, doc/service.md):
        # ``job`` names this tracker's control-plane partition (it tags
        # the telemetry filename — telemetry-<job>.json — and every
        # journal record the service wraps); ``headless=True`` builds a
        # PARTITION: no listen socket, no serving threads — a
        # CollectiveService owns the one reactor and feeds this
        # partition parsed hellos, and its monitor loop drives
        # _lease_tick/_wave_tick.
        self.job = str(job)
        self.headless = bool(headless)
        if headless:
            self._srv = None
            self.host, self.port = host, int(port)
        elif listen_sock is not None:
            # HA takeover (rabit_tpu.ha.Standby): the standby pre-bound
            # its advertised address; listen() here is the moment it
            # starts answering the client-side failover rotation.
            self._srv = listen_sock
            self._srv.listen(self.backlog)
        else:
            self._srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
            self._srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
            self._srv.bind((host, port))
            self._srv.listen(self.backlog)
        if self._srv is not None:
            self.host, self.port = self._srv.getsockname()
        self._lock = threading.Lock()
        self._pending: list[_Pending] = []
        self._pending_ids: set[str] = set()  # O(1) re-check-in detection
        self._wave_started: float | None = None  # monotonic, first check-in
        self._spares: list[_Pending] = []  # parked hot spares (warm sockets)
        self._blob: tuple[int, bytes] | None = None  # (version, compressed)
        # Model-delivery plane (rabit_tpu.delivery, doc/delivery.md):
        # the published version line of this partition's model stream
        # (version/epoch/digest/size of the newest snapshot) and the
        # digest-keyed content-addressed snapshot store.  A
        # CollectiveService aliases ONE store dict into every partition
        # (cross-job dedup: N tenants publishing identical bytes hold —
        # and ship — one copy).
        self._delivery: dict | None = None
        self._snaps: dict[str, bytes] = {}
        self._sub_ids: set[str] = set()  # distinct subscriber task ids
        self._fetched_digests: set[str] = set()  # first-fetch evidence
        self._ranks: dict[str, int] = {}  # task_id -> stable rank
        self._n_shutdown = 0
        self._shutdown_tasks: set[str] = set()
        self._done = threading.Event()
        self._thread: threading.Thread | None = None
        # Worker print log (also echoed): BOUNDED — at O(10^4) workers an
        # unbounded list is a memory leak; drops are counted and surfaced
        # in telemetry.json as messages_dropped.
        self.messages: deque[str] = deque(maxlen=max(int(max_messages), 1))
        self.messages_dropped = 0
        # Serving-path evidence (the scale sweep's FD/thread story):
        # accepts = connections the root tracker ever accepted,
        # handler_threads_hwm = peak live thread-per-connection handlers
        # (legacy path), reactor_conns_hwm = peak connections registered
        # on the reactor loop, rpcs = short RPCs answered, batches /
        # batch_msgs = relay envelopes folded and sub-messages therein.
        self.serve_stats: dict[str, int] = {
            "accepts": 0, "rpcs": 0, "handler_threads_hwm": 0,
            "reactor_conns_hwm": 0, "batches": 0, "batch_msgs": 0,
            "obs_scrapes": 0,
        }
        self._stats_lock = threading.Lock()
        self._handler_threads = 0
        self._relay_channels: list[_RelayChannel] = []
        # HA control plane (rabit_tpu/ha, doc/ha.md): the durable state
        # journal (every control-plane mutation appended as a framed,
        # crc'd record; a path string opens a file journal), the state a
        # promoted tracker resumes from, and the abrupt-death flag the
        # chaos harness flips.  journal=None disables journaling; a
        # CMD_JOURNAL standby then gets refused instead of silently
        # syncing nothing.
        self._killed = False
        self._journal_conns: list[socket.socket] = []
        if isinstance(journal, str):
            from rabit_tpu.ha.journal import Journal

            cfg = Config()
            journal = Journal(
                journal,
                snapshot_every=cfg.get_int("rabit_ha_snapshot_every", 256))
        self.journal = journal
        if self.journal is not None:
            self.journal.on_event = self._journal_event
        self._ha_tick_sec = (float(ha_tick_sec) if ha_tick_sec is not None
                             else float(Config().get("rabit_ha_tick_sec",
                                                     "0.25") or "0.25"))
        if resume_from is not None:
            self._adopt_state(resume_from)
        self._journal("init", base_world=self.base_world)

    # -- HA journal seams (rabit_tpu/ha, doc/ha.md) ------------------------

    def _journal(self, kind: str, **fields) -> None:
        """Append one control-plane mutation record.  Non-blocking (the
        journal's writer thread does the IO), so safe at every mutation
        point — including under self._lock."""
        if self.journal is not None:
            self.journal.append(kind, **fields)

    def _journal_event(self, ev: dict) -> None:
        """Journal-writer telemetry (journal_snapshot / journal_gap)
        folded into the tracker's event timeline."""
        with self._lock:
            self.events.append({"ts": round(time.time(), 6), **ev})

    def _adopt_state(self, st) -> None:
        """Seed this tracker from a replayed ControlState (a standby's
        takeover): stable ranks, the membership epoch line, frozen
        quorum records, link flags, the spare-pool roster and admission
        counters all survive the failover, so every wave the new
        primary closes is the wave the old one would have closed.
        Journaled leases re-arm with FRESH deadlines — a worker that
        died during the cut still gets suspected, one takeover lease
        late, while live workers renew well before that."""
        self.base_world = int(st.base_world) or self.base_world
        self.world_size = int(st.world) or self.world_size
        self.elastic.base_world = self.base_world
        if st.epoch >= 0:
            self.elastic.restore(st.epoch, st.world, st.rank_map,
                                 history=[tuple(e) for e in st.epochs])
        self._ranks.update(st.ranks)
        self._n_starts.update(st.n_starts)
        self._shutdown_tasks |= set(st.shutdown)
        self._n_shutdown = len(self._shutdown_tasks)
        self._link_flags |= {tuple(p) for p in st.link_flags}
        self._last_ring = list(st.last_ring)
        if self._quorum is not None:
            self._quorum.seed(st.quorum_seed())
        now = time.monotonic()
        for task_id, (interval, rank) in sorted(st.leases.items()):
            if task_id not in self._shutdown_tasks:
                self._leases[task_id] = _Lease(
                    now + P.LEASE_FACTOR * float(interval),
                    float(interval), int(rank))
        # The bootstrap-blob BYTES are deliberately not journaled (only
        # the version, via spare_park records): rank 0 re-ships the blob
        # after its next commit, and a pre-failover spare already holds
        # its copy.
        # The delivery VERSION LINE survives the failover
        # (snapshot_published records replay into st.delivery); the
        # snapshot bytes are likewise not journaled — relays keep their
        # digest-keyed copies and the publisher re-pushes on its next
        # commit, so a direct fetch of a not-yet-restored digest reads
        # as an empty frame the subscriber retries past
        # (doc/delivery.md).
        if getattr(st, "delivery", None):
            self._delivery = dict(st.delivery)

    def _drop_lease_locked(self, task_id: str) -> None:
        """Drop a lease (re-check-in, shutdown, park) and journal the
        drop exactly when one existed.  Caller holds self._lock."""
        if self._leases.pop(task_id, None) is not None:
            self._journal("lease_drop", task_id=task_id)

    # -- lifecycle ---------------------------------------------------------

    def start(self) -> "Tracker":
        if self.headless:
            raise RuntimeError(
                "a headless partition has no serving loop — its owning "
                "CollectiveService serves and ticks it (doc/service.md)")
        serve = self._serve_reactor if self._reactor else self._serve
        self._thread = threading.Thread(target=serve, daemon=True,
                                        name="rabit-tracker-serve")
        self._thread.start()
        threading.Thread(target=self._lease_monitor, daemon=True,
                         name="rabit-tracker-leases").start()
        threading.Thread(target=self._wave_monitor, daemon=True,
                         name="rabit-tracker-waves").start()
        return self

    def wait(self, timeout: float | None = None) -> bool:
        return self._done.wait(timeout)

    def stop(self) -> None:
        self._done.set()
        # shutdown() BEFORE close(): close() alone defers the real fd close
        # while the serve thread is blocked in accept() (CPython keeps the
        # fd alive for the in-flight call), leaving a "stopped" tracker
        # listening — and serving — indefinitely.  shutdown() wakes the
        # accept with an error immediately.
        if self._srv is not None:
            try:
                self._srv.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                self._srv.close()
            except OSError:
                pass
        with self._lock:
            channels, self._relay_channels = self._relay_channels, []
            jconns, self._journal_conns = self._journal_conns, []
        for ch in channels:
            ch.close()
        for conn in jconns:
            try:
                conn.close()
            except OSError:
                pass
        self._release_spares()
        # Safety net for jobs torn down without a full shutdown wave (kill,
        # timeout): idempotent, so the normal all-ranks-shut-down path has
        # already written by the time stop() runs.
        self.write_telemetry()
        if self.journal is not None:
            self.journal.close()

    def kill(self) -> None:
        """ABRUPT death — the in-process analog of SIGKILL, for the HA
        chaos campaigns (doc/ha.md): every socket drops with no goodbye
        (parked waves, spare pool, relay and journal channels, the
        listener), no telemetry is written, and the journal's writer
        stops wherever it was.  Workers see resets and fail over via
        their rabit_tracker_addrs rotation; the standby's journal
        channel EOFs and its takeover lease starts running."""
        self._killed = True
        with self._lock:
            self._telemetry_written = True  # a SIGKILL leaves no gasp
        self._telemetry_flushed.set()  # nothing to wait for either
        self._done.set()
        if self._srv is not None:
            try:
                self._srv.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                self._srv.close()
            except OSError:
                pass
        with self._lock:
            channels, self._relay_channels = self._relay_channels, []
            jconns, self._journal_conns = self._journal_conns, []
            held = [p.conn for p in self._pending] + \
                   [s.conn for s in self._spares]
            self._pending, self._spares = [], []
            self._pending_ids = set()
        for ch in channels:
            ch.close()
        for conn in jconns + held:
            try:
                conn.close()
            except OSError:
                pass
        if self.journal is not None:
            self.journal.close()

    def _release_spares(self) -> None:
        """Release parked spares: their warm sockets EOF and the spare
        workers exit their park loop instead of waiting out a deadline.
        Runs at stop() AND the moment the job completes — a launcher-run
        spare process (or a surplus-parked restarted worker) must exit as
        soon as the last primary shuts down, not when the launcher tears
        the tracker down.  The release is journaled as an ordinary
        spare_drop so a standby replaying past a completed job sees the
        same empty pool the primary holds (journal-coverage)."""
        with self._lock:
            spares, self._spares = self._spares, []
            if spares:
                self._journal("spare_drop",
                              task_ids=sorted(sp.task_id for sp in spares))
        for sp in spares:
            try:
                sp.conn.close()
            except OSError:
                pass

    # -- serving -----------------------------------------------------------

    def _serve(self) -> None:
        """LEGACY serving path: one thread per connection.  Kept (behind
        ``reactor=False``) as the scale sweep's comparison arm — it is
        the accept storm the reactor replaces."""
        while not self._done.is_set():
            try:
                conn, addr = self._srv.accept()
            except OSError:
                break
            with self._stats_lock:
                self.serve_stats["accepts"] += 1
            threading.Thread(
                target=self._handle_counted, args=(conn, addr), daemon=True
            ).start()

    def _handle_counted(self, conn: socket.socket, addr) -> None:
        with self._stats_lock:
            self._handler_threads += 1
            self.serve_stats["handler_threads_hwm"] = max(
                self.serve_stats["handler_threads_hwm"],
                self._handler_threads)
        try:
            self._handle(conn, addr)
        finally:
            with self._stats_lock:
                self._handler_threads -= 1

    def _handle(self, conn: socket.socket, addr) -> None:
        try:
            # Bound every hello read: a slow/torn client (partial hello,
            # chaos-severed proxy stream) is dropped at the deadline instead
            # of leaking this thread and its socket.
            if self.conn_timeout_sec > 0:
                conn.settimeout(self.conn_timeout_sec)
            magic = P.get_u32(conn)
            if magic != P.MAGIC_HELLO:
                conn.close()
                return
            cmd = P.get_u32(conn)
            prev_rank = P.get_i32(conn)
            task_id = P.get_str(conn)
            if cmd in (P.CMD_START, P.CMD_RECOVER):
                listen_port = P.get_u32(conn)
                # The hello is complete; from here the connection only ever
                # WAITS (held open until the wave completer answers it), so
                # the read deadline comes off again.
                conn.settimeout(None)
                tr, tid = self._route_hello(task_id, cmd)
                if tr is None:
                    conn.close()  # admission refused (doc/service.md)
                    return
                with tr._lock:
                    # A (re-)check-in supersedes any lease of the previous
                    # life: the fresh worker renews once it is back up, and
                    # a stale lease must not re-suspect it mid-bootstrap.
                    tr._drop_lease_locked(tid)
                plan = tr._register(conn, addr[0], tid, listen_port,
                                    prev_rank, cmd)
                if plan is not None:
                    tr._send_wave(plan)  # handler thread: inline is fine
                # conn is answered (and closed) by the wave completer.
                return
            if cmd == P.CMD_SPARE:
                listen_port = P.get_u32(conn)
                conn.settimeout(None)
                tr, tid = self._route_hello(task_id, cmd)
                if tr is None:
                    conn.close()
                    return
                tr._park_spare(conn, addr[0], tid, listen_port,
                               prev_rank)
                # conn stays open (the warm socket); promotion answers it.
                return
            if cmd == P.CMD_BATCH:
                # A relay's persistent channel (doc/scaling.md): this
                # thread BECOMES the channel server.
                conn.settimeout(None)
                self._serve_relay(conn, task_id, addr)
                return
            if cmd == P.CMD_JOURNAL:
                # A warm standby tailing the control-plane journal
                # (rabit_tpu.ha, doc/ha.md): this thread streams frames.
                conn.settimeout(None)
                self._serve_journal(conn, task_id)
                return
            hello = P.Hello(cmd, prev_rank, task_id)
            if cmd == P.CMD_BLOB:
                hello.blob_version = P.get_u32(conn)
                nbytes = P.get_u32(conn)
                hello.blob = P.recv_exact(conn, nbytes) if nbytes else b""
            elif cmd != P.CMD_SHUTDOWN:
                hello.message = P.get_str(conn)
            tr, tid = self._route_hello(task_id, cmd)
            if tr is None:
                conn.close()
                return
            hello.task_id = tid
            reply, post = tr._short_rpc_reply(hello)
            conn.sendall(reply)
            if post is not None:
                post()
            conn.close()
        except (ConnectionError, OSError, ValueError):
            try:
                conn.close()
            except OSError:
                pass

    def _short_rpc_reply(
            self, h: P.Hello) -> tuple[bytes, Callable[[], None] | None]:
        """Serve one short-lived RPC: side effects now, reply bytes
        returned (plus a post-send callable for work that must follow
        the ACK — shutdown's completion bookkeeping).  Shared verbatim by
        the threaded path, the reactor, and the relay batch fold, so all
        three produce identical wire bytes."""
        with self._stats_lock:
            self.serve_stats["rpcs"] += 1
        if h.cmd == P.CMD_EPOCH:
            # The version-boundary poll: the worker's committed version
            # rides as the payload (informational); the reply carries
            # the current epoch and the rewave flag that triggers the
            # grow-back wave (doc/elasticity.md).
            return (P.put_u32(P.ACK)
                    + P.put_str(json.dumps(self._epoch_info()))), None
        if h.cmd == P.CMD_BLOB:
            # Content-addressing (doc/delivery.md): the digest is
            # computed HERE from the received payload, so the snapshot
            # store is self-certifying — an uploader cannot register
            # bytes under a digest that does not match them, and two
            # jobs uploading identical bytes land on one entry.
            digest = hashlib.sha256(h.blob).hexdigest()
            with self._lock:
                if self._blob is None or h.blob_version >= self._blob[0]:
                    self._blob = (h.blob_version, h.blob)
                    self._journal("blob", version=h.blob_version)
                self._snaps[digest] = h.blob
                self.events.append({
                    "ts": round(time.time(), 6),
                    "kind": "bootstrap_blob", "task_id": h.task_id,
                    "version": h.blob_version, "nbytes": len(h.blob),
                })
            return P.put_u32(P.ACK), None
        if h.cmd == P.CMD_QUORUM:
            # One quorum-round report (doc/partial_allreduce.md): the
            # reply is the round's frozen exclusion record, or an
            # undecided placeholder the worker re-polls past.
            reply = self._quorum_report(h.message)
            return P.put_u32(P.ACK) + P.put_str(json.dumps(reply)), None
        if h.cmd == P.CMD_PRINT:
            self._log_print(h.message)
            return P.put_u32(P.ACK), None
        if h.cmd == P.CMD_METRICS:
            self._accept_snapshot(h.message)
            return P.put_u32(P.ACK) + self._clock_stamp(), None
        if h.cmd == P.CMD_HEARTBEAT:
            self._renew_lease(h.task_id, h.prev_rank, h.message)
            return P.put_u32(P.ACK) + self._clock_stamp(), None
        if h.cmd == P.CMD_SHUTDOWN:
            with self._lock:
                # A clean exit must not be suspected afterwards; drop
                # the lease BEFORE acking so the worker observing the
                # ACK observes the drop too.
                self._drop_lease_locked(h.task_id)
            return P.put_u32(P.ACK), lambda: self._note_shutdown(h.task_id)
        if h.cmd == P.CMD_OBS:
            # Live-telemetry scrape (doc/observability.md "Live telemetry
            # plane"): the exposition is assembled from already-locked
            # copies of live state — no file IO, no wave waits — so it
            # serves inline on the reactor (the reactor-blocking
            # invariant, doc/static_analysis.md).
            try:
                opts = json.loads(h.message) if h.message else {}
            except ValueError:
                opts = {}
            doc = self.build_scrape(opts if isinstance(opts, dict) else {})
            with self._stats_lock:
                self.serve_stats["obs_scrapes"] += 1
            with self._lock:
                if not self._obs_scraped:
                    # One event per tracker lifetime — evidence the live
                    # plane was used, without a 1 Hz scraper flooding the
                    # event timeline for hours.
                    self._obs_scraped = True
                    self.events.append({
                        "ts": round(time.time(), 6), "kind": "obs_scrape",
                        "task_id": h.task_id,
                    })
            return P.put_u32(P.ACK) + P.put_str(json.dumps(doc)), None
        if h.cmd == P.CMD_SUB:
            # Model-delivery version-line RPC (doc/delivery.md): dict
            # math over live state only — reactor-safe.
            return self._sub_reply(h.task_id, h.message), None
        if h.cmd == P.CMD_SNAP:
            # Snapshot chunk fetch: the reply IS a snap frame (no ACK
            # prefix) — a byte slice of an in-memory blob, reactor-safe.
            return self._snap_reply(h.task_id, h.message), None
        raise ValueError(f"unknown tracker cmd {h.cmd}")

    def _epoch_info(self) -> dict:
        """The CMD_EPOCH reply document — current epoch/world plus the
        rewave flag (grow-back AND pending schedule repair resolve at
        the same version-boundary wave; doc/scheduling.md)."""
        with self._lock:
            self._reap_spares_locked()
            return {"epoch": self.elastic.epoch,
                    "world": self.world_size,
                    "rewave": (self.elastic.grow_wanted(len(self._spares))
                               or self._repair_wanted)}

    def _sub_reply(self, task_id: str, message: str) -> bytes:
        """Serve one CMD_SUB delivery RPC (doc/delivery.md).  A reader
        poll (``{}``) answers the current published version line; a
        writer ``publish`` registers a new line, journals it
        (``snapshot_published`` — a standby restores the line from the
        replay) and reports whether the digest's bytes are already held,
        so the publisher skips the upload when another tenant shipped
        identical bytes first.  Shared verbatim by the threaded path,
        the reactor, and the relay batch fold — identical wire bytes and
        journal side effects on all three (serving-parity)."""
        try:
            req = json.loads(message) if message else {}
        except ValueError:
            req = {}
        if not isinstance(req, dict):
            req = {}
        pub = req.get("publish")
        if isinstance(pub, dict):
            line = {"version": int(pub.get("version", 0)),
                    "epoch": int(pub.get("epoch", 0)),
                    "digest": str(pub.get("digest", "")),
                    "size": int(pub.get("size", 0))}
            with self._lock:
                prev = self._delivery
                if prev is None or line["version"] >= prev["version"]:
                    self._delivery = line
                    self._journal("snapshot_published", **line)
                    self.events.append({
                        "ts": round(time.time(), 6),
                        "kind": "snapshot_published",
                        "task_id": task_id, **line,
                    })
                reply = dict(self._delivery)
                reply["have"] = line["digest"] in self._snaps
            return P.put_u32(P.ACK) + P.put_str(json.dumps(reply))
        with self._lock:
            line = (dict(self._delivery) if self._delivery is not None
                    else {"version": 0, "epoch": 0, "digest": "", "size": 0})
            new_sub = task_id not in self._sub_ids
            if new_sub:
                self._sub_ids.add(task_id)
        if new_sub:
            obs_stream.stream_count("delivery_subscribers", 1, job=self.job)
        return P.put_u32(P.ACK) + P.put_str(json.dumps(line))

    def _snap_reply(self, task_id: str, message: str) -> bytes:
        """Serve one CMD_SNAP chunk fetch: the reply is one snap frame —
        the frame IS the message, no ACK prefix.  An UNKNOWN digest
        answers an empty frame, not an error: the publisher registers
        the version line before its bytes finish landing, and a freshly
        promoted standby restores the line before anyone re-pushes the
        bytes — absence is a retryable race, never a subscriber fault
        (doc/delivery.md)."""
        try:
            req = json.loads(message) if message else {}
        except ValueError:
            req = {}
        if not isinstance(req, dict):
            req = {}
        digest = str(req.get("digest", ""))
        with self._lock:
            blob = self._snaps.get(digest)
        if blob is None:
            return P.put_snap_frame("", 0, 0, b"")
        off = max(int(req.get("off", 0)), 0)
        ln = int(req.get("len", 0) or 0)
        chunk = blob[off:off + ln] if ln > 0 else blob[off:]
        with self._lock:
            if digest not in self._fetched_digests:
                # First-fetch evidence per digest — a 10^5-subscriber
                # swarm must not flood the event timeline.
                self._fetched_digests.add(digest)
                self.events.append({
                    "ts": round(time.time(), 6), "kind": "snapshot_fetched",
                    "task_id": task_id, "digest": digest,
                    "nbytes": len(blob),
                })
        obs_stream.stream_count("delivery_bytes_served", len(chunk),
                                job=self.job, digest=digest)
        return P.put_snap_frame(digest, len(blob), off, chunk)

    def _route_hello(self, task_id: str,
                     cmd: int) -> "tuple[Tracker | None, str]":
        """The multiplexing seam (rabit_tpu.service, doc/service.md):
        map one worker hello to ``(owner tracker, owner-local task id)``.
        The base tracker owns every id verbatim — single-job serving is
        byte-for-byte unrouted.  A CollectiveService overrides this to
        split the job-key prefix off and dispatch to the job's headless
        partition; ``(None, reason)`` refuses the hello (the connection
        closes with no reply — admission control's shape on the wire)."""
        return self, task_id

    def _note_shutdown(self, task_id: str) -> None:
        """Post-ACK shutdown bookkeeping (the completion guard)."""
        done = False
        with self._lock:
            # Idempotent by task id: a relay replaying its un-ACKed
            # batch across a failover cut (doc/ha.md) may deliver the
            # same shutdown twice, and a double count could close the
            # completion guard early.
            if task_id not in self._shutdown_tasks:
                self._n_shutdown += 1
                self._shutdown_tasks.add(task_id)
                self._journal("shutdown", task_id=task_id)
            # Elastic guard on the completion condition: a shrunk
            # world can reach n_shutdown >= world_size while OTHER
            # workers still hold live leases (they detected the
            # failure later and are re-waving toward their own
            # epoch).  The job is done only when no leased task
            # remains un-shut-down — a dead task's lease expires
            # and releases the guard on its own.
            done = (self._n_shutdown >= self.world_size
                    and not (set(self._leases)
                             - self._shutdown_tasks))
        if done:
            # The finalize step does file IO (telemetry.json), so it
            # must leave the serving thread — a shutdown RPC is answered
            # by the reactor / relay fold, and a slow disk there would
            # freeze every tenant (the reactor-blocking invariant).  The
            # ordering contract survives the hand-off: _finalize_done
            # persists BEFORE releasing wait()ers.
            threading.Thread(target=self._finalize_done, daemon=True,
                             name="rabit-tracker-finalize").start()

    def _finalize_done(self) -> None:
        """Job-completion finalizer: persist telemetry BEFORE releasing
        wait()ers (by the time the launcher sees the job done,
        telemetry.json exists), then free the spare pool."""
        self.write_telemetry()
        self._done.set()
        self._release_spares()

    def _log_print(self, msg: str) -> None:
        """Fold one worker print into the BOUNDED message log and the
        stats-line event bridge: the robust engine's recover_stats /
        failure_detected prints become structured events here, so
        consumers read self.events / telemetry.json instead of scraping
        stdout."""
        # The message log is fed from the reactor (CMD_PRINT) AND every
        # relay channel's fold thread concurrently; the deque append
        # alone is GIL-atomic, but the drop counter and its one-shot
        # event are a check-then-act — take the lock for the whole
        # bookkeeping step (thread-shared-mutation invariant).
        with self._lock:
            if (self.messages.maxlen is not None
                    and len(self.messages) >= self.messages.maxlen):
                first = self.messages_dropped == 0
                self.messages_dropped += 1
                if first:
                    self.events.append({
                        "ts": round(time.time(), 6),
                        "kind": "messages_dropped",
                        "cap": self.messages.maxlen,
                    })
            self.messages.append(msg)
        ev = event_from_stats_line(msg)
        if ev is not None:
            with self._lock:
                self.events.append(
                    {"ts": round(ev.ts, 6), "kind": ev.kind,
                     **ev.fields})
            # Live worker self-reports no longer flag the link
            # directly: the event feeds the HealthMonitor (_diag_tick),
            # which attributes + hysteresis-gates the signal and calls
            # flag_link when the degraded-link incident opens
            # (doc/observability.md, "Diagnosis plane").  Reports with
            # an explicit origin= stamp (trace_tool --flag-links, the
            # offline analytics half of doc/scheduling.md's repair
            # policy) are operator decisions, not symptoms — they keep
            # the direct path.
            if ev.kind == "link_degraded" and ev.fields.get("origin"):
                self._flag_link(ev.fields)
        if not self.quiet:
            print(msg, end="" if msg.endswith("\n") else "\n", flush=True)

    # -- event-loop serving (doc/scaling.md) -------------------------------

    def _serve_reactor(self) -> None:
        """The default serving path: ONE selectors loop owns every
        short-lived RPC — accept, incremental hello parse, inline reply.
        Wave-held connections (START/RECOVER/SPARE) detach to the wave
        machinery once their hello completes; relay channels detach to a
        dedicated channel thread; wave SENDS run on a completer thread
        so an O(world) assignment broadcast never stalls the accept
        path."""
        sel = selectors.DefaultSelector()
        self._srv.setblocking(False)
        try:
            sel.register(self._srv, selectors.EVENT_READ, None)
        except (OSError, ValueError):
            return
        conns: set[_RConn] = set()
        next_sweep = time.monotonic() + 0.5
        try:
            while not self._done.is_set():
                try:
                    events = sel.select(0.05)
                except OSError:
                    break
                for key, mask in events:
                    if key.data is None:
                        self._reactor_accept(sel, conns)
                    elif mask & selectors.EVENT_READ:
                        self._reactor_read(sel, conns, key.data)
                    elif mask & selectors.EVENT_WRITE:
                        self._reactor_flush(sel, conns, key.data)
                now = time.monotonic()
                if now >= next_sweep:
                    next_sweep = now + 0.5
                    for rc in [r for r in conns
                               if r.deadline and now > r.deadline]:
                        # A torn hello past the read deadline must not
                        # pin its socket (the threaded path's settimeout
                        # analog).
                        self._reactor_drop(sel, conns, rc)
        finally:
            for rc in list(conns):
                self._reactor_drop(sel, conns, rc)
            sel.close()

    def _reactor_accept(self, sel, conns: set[_RConn]) -> None:
        while True:
            try:
                conn, addr = self._srv.accept()
            except (BlockingIOError, InterruptedError):
                return
            except OSError:
                return
            conn.setblocking(False)
            deadline = (time.monotonic() + self.conn_timeout_sec
                        if self.conn_timeout_sec > 0 else 0.0)
            rc = _RConn(conn, addr, deadline)
            try:
                sel.register(conn, selectors.EVENT_READ, rc)
            except (OSError, ValueError):
                try:
                    conn.close()
                except OSError:
                    pass
                continue
            conns.add(rc)
            with self._stats_lock:
                self.serve_stats["accepts"] += 1
                self.serve_stats["reactor_conns_hwm"] = max(
                    self.serve_stats["reactor_conns_hwm"], len(conns))

    def _reactor_drop(self, sel, conns: set[_RConn], rc: _RConn) -> None:
        conns.discard(rc)
        try:
            sel.unregister(rc.sock)
        except (KeyError, OSError, ValueError):
            pass
        try:
            rc.sock.close()
        except OSError:
            pass

    def _reactor_detach(self, sel, conns: set[_RConn], rc: _RConn) -> None:
        """Hand a completed hello's socket OFF the reactor (wave-held
        connections, relay channels): back to blocking mode, ownership
        moves to the wave machinery / channel thread."""
        conns.discard(rc)
        try:
            sel.unregister(rc.sock)
        except (KeyError, OSError, ValueError):
            pass
        rc.sock.setblocking(True)

    def _reactor_read(self, sel, conns: set[_RConn], rc: _RConn) -> None:
        try:
            data = rc.sock.recv(65536)
        except (BlockingIOError, InterruptedError):
            return
        except OSError:
            self._reactor_drop(sel, conns, rc)
            return
        if not data:
            self._reactor_drop(sel, conns, rc)
            return
        try:
            if not rc.parser.feed(data):
                return
            h = rc.parser.result
        except ValueError:
            self._reactor_drop(sel, conns, rc)  # bad magic / oversized
            return
        try:
            if h.cmd in (P.CMD_START, P.CMD_RECOVER):
                tr, tid = self._route_hello(h.task_id, h.cmd)
                if tr is None:
                    self._reactor_drop(sel, conns, rc)
                    return
                self._reactor_detach(sel, conns, rc)
                with tr._lock:
                    tr._drop_lease_locked(tid)
                plan = tr._register(rc.sock, rc.addr[0], tid,
                                    h.listen_port, h.prev_rank, h.cmd)
                if plan is not None:
                    tr._send_wave_async(plan)
                return
            if h.cmd == P.CMD_SPARE:
                # Park replies ship the cached blob (possibly large):
                # spares are rare, wave-held sockets — a thread each is
                # the design, not a regression.
                tr, tid = self._route_hello(h.task_id, h.cmd)
                if tr is None:
                    self._reactor_drop(sel, conns, rc)
                    return
                self._reactor_detach(sel, conns, rc)
                threading.Thread(
                    target=tr._park_spare,
                    args=(rc.sock, rc.addr[0], tid, h.listen_port,
                          h.prev_rank),
                    daemon=True, name="rabit-tracker-park").start()
                return
            if h.cmd == P.CMD_BATCH:
                self._reactor_detach(sel, conns, rc)
                rest = rc.parser.rest()
                threading.Thread(
                    target=self._serve_relay,
                    args=(rc.sock, h.task_id, rc.addr, rest),
                    daemon=True,
                    name=f"rabit-relay-rx-{h.task_id}").start()
                return
            if h.cmd == P.CMD_JOURNAL:
                self._reactor_detach(sel, conns, rc)
                threading.Thread(
                    target=self._serve_journal,
                    args=(rc.sock, h.task_id),
                    daemon=True,
                    name=f"rabit-ha-tx-{h.task_id}").start()
                return
            tr, tid = self._route_hello(h.task_id, h.cmd)
            if tr is None:
                self._reactor_drop(sel, conns, rc)
                return
            h.task_id = tid
            reply, post = tr._short_rpc_reply(h)
        except (ValueError, OSError):
            self._reactor_drop(sel, conns, rc)
            return
        rc.out += reply
        self._reactor_flush(sel, conns, rc)
        if post is not None:
            post()

    def _reactor_flush(self, sel, conns: set[_RConn], rc: _RConn) -> None:
        """Drain the reply buffer without blocking the loop; a reply that
        outruns the socket buffer parks on EVENT_WRITE.  A fully drained
        short-RPC connection closes (one RPC per connection, exactly the
        threaded path's contract)."""
        while rc.out:
            try:
                n = rc.sock.send(rc.out)
            except (BlockingIOError, InterruptedError):
                try:
                    sel.modify(rc.sock, selectors.EVENT_WRITE, rc)
                except (KeyError, OSError, ValueError):
                    self._reactor_drop(sel, conns, rc)
                return
            except OSError:
                self._reactor_drop(sel, conns, rc)
                return
            del rc.out[:n]
        self._reactor_drop(sel, conns, rc)

    # -- journal channels (rabit_tpu.ha; doc/ha.md) ------------------------

    def _serve_journal(self, conn: socket.socket, standby_id: str) -> None:
        """Stream the control-plane journal to a warm standby: ACK the
        hello, then forward every frame the journal's writer fans out —
        a snapshot of the current state first (Journal.subscribe seeds
        it), then each mutation record in commit order, with the
        periodic ``tick`` records doubling as the keepalive the
        standby's takeover lease watches.  A tracker with no journal
        configured REFUSES the channel (closes without ACK): silently
        streaming nothing would let a misconfigured standby 'sync' an
        empty state and take over with it."""
        if self.journal is None:
            if not self.quiet:
                print(f"[tracker] standby {standby_id} asked for the "
                      f"journal but journaling is off (pass journal= / "
                      f"rabit_ha_journal); refusing", flush=True)
            try:
                conn.close()
            except OSError:
                pass
            return
        try:
            conn.sendall(P.put_u32(P.ACK))
        except OSError:
            try:
                conn.close()
            except OSError:
                pass
            return
        sub = self.journal.subscribe()
        with self._lock:
            self._journal_conns.append(conn)
        if not self.quiet:
            print(f"[tracker] standby {standby_id} journal channel up",
                  flush=True)
        try:
            while not self._done.is_set():
                try:
                    frame = sub.get(timeout=0.25)
                except queue.Empty:
                    continue
                conn.sendall(frame)
        except OSError:
            pass
        finally:
            self.journal.unsubscribe(sub)
            with self._lock:
                if conn in self._journal_conns:
                    self._journal_conns.remove(conn)
            try:
                conn.close()
            except OSError:
                pass

    # -- relay channels (rabit_tpu.relay; doc/scaling.md) ------------------

    def _serve_relay(self, conn: socket.socket, relay_id: str, addr,
                     rest: bytes = b"") -> None:
        """Serve one relay's persistent channel: ACK the hello, then fold
        framed CMD_BATCH envelopes until EOF.  Replies to relayed
        children (assignments, park frames) are routed back over the
        same channel by task id; each batch is answered with a clock-
        stamped ACK frame the relay syncs its tracker-clock projection
        and CMD_EPOCH cache from.  A dying channel is NOT a membership
        event: its virtual connections read as hung up and the ordinary
        purge/reap paths clean them, while the relay reconnects and its
        children re-enter."""
        channel = _RelayChannel(conn, relay_id)
        try:
            conn.sendall(P.put_u32(P.ACK))
        except OSError:
            channel.close()
            return
        with self._lock:
            self._relay_channels.append(channel)
            self.events.append({
                "ts": round(time.time(), 6), "kind": "relay_up",
                "relay": relay_id, "host": addr[0],
            })
        if not self.quiet:
            print(f"[tracker] relay {relay_id} channel up ({addr[0]})",
                  flush=True)
        src = _BufferedSock(conn, rest) if rest else conn
        try:
            while not self._done.is_set():
                msgs = P.read_batch_frame(src)
                acks = [self._fold_batch_msg(channel, m) for m in msgs]
                with self._stats_lock:
                    self.serve_stats["batches"] += 1
                    self.serve_stats["batch_msgs"] += len(msgs)
                info = self._batch_ack_info()
                info["acks"] = acks
                if msgs:  # empty keepalives refresh caches silently
                    with self._lock:
                        self.events.append({
                            "ts": info["server_ts"], "kind": "batch_folded",
                            "relay": relay_id, "n": len(msgs),
                        })
                channel.send_route("", 0, json.dumps(info).encode())
        except (ConnectionError, OSError, ValueError):
            pass
        finally:
            channel.close()
            with self._lock:
                if channel in self._relay_channels:
                    self._relay_channels.remove(channel)
                self.events.append({
                    "ts": round(time.time(), 6), "kind": "relay_lost",
                    "relay": relay_id,
                })
            if not self.quiet and not self._done.is_set():
                print(f"[tracker] relay {relay_id} channel lost "
                      f"(stateless fan-in: children reconnect)", flush=True)

    def _batch_ack_info(self) -> dict:
        """The batch-ACK document a relay refreshes its caches from:
        clock stamp plus the current epoch/world/rewave.  A
        CollectiveService adds a per-job ``jobs`` map so one shared
        relay tier can answer every job's CMD_EPOCH polls locally
        (doc/service.md)."""
        info = {"server_ts": round(time.time(), 6)}
        info.update(self._epoch_info())
        with self._lock:
            if self._delivery is not None:
                # The published version line rides every batch ACK so a
                # relay answers its children's CMD_SUB polls locally —
                # root accepts stay O(relays) under a 10^5-subscriber
                # swarm (doc/delivery.md).
                info["delivery"] = dict(self._delivery)
        return info

    def _fold_batch_msg(self, channel: _RelayChannel,
                        m: P.BatchMsg) -> float:
        """Fold one relayed sub-message; returns the tracker-clock ingest
        stamp for the batch ACK's per-child acks list."""
        ts = round(time.time(), 6)
        try:
            # The route key stays the FULL wire task id (job prefix
            # included) — that is what the relay parked the child under;
            # the owning partition sees its local id (doc/service.md).
            tr, tid = self._route_hello(m.task_id, m.cmd)
            if tr is None:
                if m.cmd != P.CMD_HANGUP:
                    return ts  # admission refused; the child's RPC times out
                tr, tid = self, m.task_id
            if m.cmd in (P.CMD_START, P.CMD_RECOVER):
                vconn = _RelayedConn(channel, m.task_id)
                with tr._lock:
                    tr._drop_lease_locked(tid)
                plan = tr._register(vconn, m.host, tid, m.listen_port,
                                    m.prev_rank, m.cmd)
                if plan is not None:
                    tr._send_wave_async(plan)
            elif m.cmd == P.CMD_SPARE:
                tr._park_spare(_RelayedConn(channel, m.task_id), m.host,
                               tid, m.listen_port, m.prev_rank)
            elif m.cmd == P.CMD_HEARTBEAT:
                tr._renew_lease(tid, m.prev_rank,
                                m.payload.decode())
            elif m.cmd == P.CMD_METRICS:
                tr._accept_snapshot(m.payload.decode())
            elif m.cmd == P.CMD_PRINT:
                tr._log_print(m.payload.decode())
            elif m.cmd == P.CMD_SHUTDOWN:
                with tr._lock:
                    tr._drop_lease_locked(tid)
                tr._note_shutdown(tid)
            elif m.cmd == P.CMD_QUORUM:
                # A quorum-round report folded through the batch
                # envelope (the PR 9 follow-on: a quorum-heavy world no
                # longer costs the root one connection per rank per
                # round).  The frozen record routes back to the child
                # parked at the relay under its ``q#``-prefixed key —
                # the reply bytes are exactly the direct path's
                # (ACK + record JSON), and re-delivery after a channel
                # cut is safe because the table decides once.
                reply = tr._quorum_report(m.payload.decode())
                channel.send_route(
                    m.task_id, P.ROUTE_CLOSE,
                    P.put_u32(P.ACK) + P.put_str(json.dumps(reply)))
            elif m.cmd == P.CMD_OBS:
                # A relay-coalesced streamed-metrics delta frame
                # (doc/observability.md "Live telemetry plane"): fold
                # into the live rollup, no reply (fire-and-forget, like
                # the heartbeat/metrics it piggybacks on).
                tr._fold_delta_frame(m.payload, ts)
            elif m.cmd == P.CMD_SUB:
                # A relayed delivery poll/publish the relay could not
                # answer from its ack-refreshed cache (doc/delivery.md):
                # the reply bytes are exactly the direct path's
                # (_sub_reply is shared by all three serving paths), and
                # they route back to the child parked at the relay.
                channel.send_route(m.task_id, P.ROUTE_CLOSE,
                                   tr._sub_reply(tid, m.payload.decode()))
            elif m.cmd == P.CMD_HANGUP:
                # The relay saw a parked child's connection EOF: make its
                # virtual connection read as hung up so the wave purge
                # drops it (live-survivor counting stays correct through
                # a relay).
                vconn = channel.vconns.get(m.task_id)
                if vconn is not None:
                    vconn.child_dead = True
            # CMD_EPOCH never rides a batch (the relay answers polls from
            # its ack-refreshed cache); CMD_BLOB and CMD_SNAP are proxied
            # straight through by the relay (blob uploads and snapshot
            # fetches are large — they keep the synchronous path, and the
            # relay serves repeat CMD_SNAP digests from its own
            # digest-keyed cache without touching the root).
        except (ValueError, UnicodeDecodeError):
            pass  # one malformed sub-message must not hurt the batch
        return ts

    @staticmethod
    def _clock_stamp() -> bytes:
        """The tracker's clock, appended to metrics/heartbeat ACKs — one
        half of the NTP-style offset estimate (protocol.TimedAck).  The
        tracker clock is the job's reference timeline: every worker's
        events are projected onto it by rabit_tpu.obs.trace."""
        return P.put_str(f"{time.time():.6f}")

    def _register(self, conn, host, task_id, listen_port, prev_rank,
                  cmd=P.CMD_START) -> dict | None:
        """Admit one wave check-in; returns the closed wave's send plan
        (or None while the wave is still filling).  The CALLER delivers
        the plan — the threaded path sends inline, the reactor and the
        relay batch fold spawn :meth:`_send_wave_async` so an O(world)
        assignment broadcast can never stall the accept path or the
        fold (the reactor-blocking invariant, doc/static_analysis.md)."""
        with self._lock:
            # A re-check-in from the same task id replaces its stale entry
            # (e.g. worker retried while the wave was still filling).  The
            # membership test is O(1) — a per-check-in list scan is an
            # O(world^2) bootstrap at 10^4 workers.
            if task_id in self._pending_ids:
                for stale in (p for p in self._pending
                              if p.task_id == task_id):
                    try:
                        stale.conn.close()
                    except OSError:
                        pass
                self._pending = [p for p in self._pending
                                 if p.task_id != task_id]
            self._pending_ids.add(task_id)
            self._pending.append(
                _Pending(conn, task_id, listen_port, host, prev_rank, cmd))
            if self._wave_started is None:
                self._wave_started = time.monotonic()
            return self._close_wave_locked(timer=False)

    def _send_wave_async(self, plan: dict) -> None:
        """Deliver a wave plan on a completer thread (reactor /
        relay-channel callers)."""
        threading.Thread(target=self._send_wave, args=(plan,),
                         daemon=True,
                         name="rabit-tracker-wave-send").start()

    def _park_spare(self, conn, host, task_id, listen_port,
                    prev_rank) -> None:
        """CMD_SPARE: hand the spare the cached compressed bootstrap blob
        and park its connection in the pool.  The warm socket is answered
        with an Assignment when the spare is promoted into a wave."""
        with self._lock:
            self._drop_lease_locked(task_id)
            version, blob = self._blob if self._blob is not None else (0, b"")
        try:
            conn.sendall(P.put_blob_frame(version, blob))
        except OSError:
            try:
                conn.close()
            except OSError:
                pass
            return
        with self._lock:
            for stale in (s for s in self._spares if s.task_id == task_id):
                try:
                    stale.conn.close()
                except OSError:
                    pass
            self._spares = [s for s in self._spares if s.task_id != task_id]
            self._spares.append(_Pending(conn, task_id, listen_port, host,
                                         prev_rank, P.CMD_START,
                                         origin="spare"))
            self._journal("spare_park", task_id=task_id,
                          blob_version=version)
            self.events.append({
                "ts": round(time.time(), 6), "kind": "spare_parked",
                "task_id": task_id, "blob_version": version,
                "pool": len(self._spares),
            })
        if not self.quiet:
            print(f"[tracker] spare {task_id} parked "
                  f"(blob v{version}, pool {len(self._spares)})", flush=True)

    # -- quorum agreement --------------------------------------------------

    def _quorum_report(self, payload: str) -> dict:
        """Fold one CMD_QUORUM report into the quorum table (decide-once
        exclusion records; doc/partial_allreduce.md).  Emits the table's
        telemetry events and feeds persistent-straggler flags into the
        schedule-repair avoid set OUTSIDE the lock (flag_link locks)."""
        try:
            req = json.loads(payload)
            epoch = int(req["epoch"])
            version = int(req["v"])
            have = [int(r) for r in req.get("have", ())]
            held = [(int(sv), int(r)) for sv, r in req.get("held", ())]
        except (ValueError, TypeError, KeyError):
            return {"decided": False, "error": "malformed report"}
        late_links: list[tuple[int, int]] = []
        with self._lock:
            if self._quorum is None:
                return {"decided": False, "disabled": True}
            if epoch != self.elastic.epoch:
                # A worker a wave behind: its round will be redone under
                # the new epoch — never decide against a stale world.
                return {"decided": False, "stale_epoch": True}
            known = self._quorum.has_record(epoch, version)
            rec, events, flag_ranks = self._quorum.report(
                epoch, version, self.world_size, have, held)
            ts = round(time.time(), 6)
            for ev in events:
                self.events.append({"ts": ts, **ev})
                if ev["kind"] == "contribution_late":
                    self._journal("quorum_late",
                                  src_version=ev["src_version"],
                                  rank=ev["rank"])
            if not known and rec.get("decided"):
                # This report FROZE the round's record: the frozen dict
                # is law on every rank, so it must survive a failover
                # byte-for-byte (doc/ha.md, doc/partial_allreduce.md).
                self._journal("quorum_freeze", epoch=epoch,
                              version=version, world=self.world_size,
                              record=dict(rec))
            order = self._last_ring or list(range(self.world_size))
            pos = {r: i for i, r in enumerate(order)}
            for r in flag_ranks:
                if r in pos and len(order) >= 2:
                    late_links.append((order[(pos[r] - 1) % len(order)], r))
        for src, dst in late_links:
            # A rank late quorum_flag_after rounds in a row feeds the SAME
            # avoid-set machinery as a slow link: the next wave's plan
            # routes the ring around the persistent straggler.
            with self._lock:
                self.events.append({
                    "ts": round(time.time(), 6), "kind": "link_degraded",
                    "rank": dst, "src": src, "dst": dst, "via": "quorum",
                })
            if not self.quiet:
                print(f"[tracker] rank {dst} persistently late under "
                      f"quorum; flagging incoming link {src}->{dst} for "
                      f"repair", flush=True)
            self.flag_link(src, dst)
        return rec

    # -- schedule planning -------------------------------------------------

    def _flag_link(self, fields: dict) -> None:
        """Ingest one ``link_degraded`` report: record the (src, dst)
        link as a task-id pair and arm the repair rewave.  With
        ``sched_repair`` off the event is telemetry only — the plan
        never changes (the bench's unrepaired control arm)."""
        if not self.sched_repair:
            return
        try:
            src, dst = int(fields["src"]), int(fields["dst"])
        except (KeyError, TypeError, ValueError):
            return
        with self._lock:
            tasks = sched.flags_to_tasks([(src, dst)],
                                         self.elastic.current.rank_map)
            fresh = tasks - self._link_flags
            if not fresh:
                return
            self._link_flags |= fresh
            self._repair_wanted = True
            for src_t, dst_t in sorted(fresh):
                self._journal("link_flag", src=src_t, dst=dst_t)
        if not self.quiet:
            print(f"[tracker] link {src}->{dst} flagged degraded; repair "
                  f"replan armed", flush=True)

    def flag_link(self, src: int, dst: int) -> None:
        """Operator/analytics hook: flag a degraded link directly (the
        offline path — ``sched.links_from_stragglers`` output, an
        external NCCL-style failure localizer...).  Same effect as a
        worker ``slow_link`` report."""
        self._flag_link({"src": src, "dst": dst})

    def _plan_schedule(self, world: int,
                       rank_map: dict[str, int]) -> "sched.Plan":
        """Plan the closing wave's schedule: resolve flags into this
        epoch's rank pairs and route around them.  Pure planning — the
        caller emits the events and stamps the assignments."""
        with self._lock:
            avoid = sched.tasks_to_flags(self._link_flags, rank_map)
            self._repair_wanted = False  # this plan consumes the flags
        return sched.plan(world, self.schedule,
                          mesh=sched.mesh_for_world(world, self.sched_mesh),
                          avoid=avoid)

    # -- elastic wave machinery --------------------------------------------

    def _purge_dead_locked(self) -> None:
        """Drop pending check-ins whose worker hung up: a dead socket would
        receive its assignment into the void, wasting the whole wave — and
        a shrink decision must count live survivors only."""
        dead = [p for p in self._pending if _conn_dead(p.conn)]
        if not dead:
            return
        for p in dead:
            try:
                p.conn.close()
            except OSError:
                pass
        self._pending = [p for p in self._pending if p not in dead]
        self._pending_ids = {p.task_id for p in self._pending}
        self.events.append({
            "ts": round(time.time(), 6), "kind": "wave_purged",
            "dropped": sorted(p.task_id for p in dead),
        })

    def _reap_spares_locked(self) -> None:
        """Drop parked spares whose warm socket hung up (a spare can die
        while parked — it must not be counted by promote/grow decisions or
        promoted into a dead socket)."""
        dead = [s for s in self._spares if _conn_dead(s.conn)]
        if not dead:
            return
        for s in dead:
            try:
                s.conn.close()
            except OSError:
                pass
        self._spares = [s for s in self._spares if s not in dead]
        self._journal("spare_drop",
                      task_ids=sorted(s.task_id for s in dead))
        self.events.append({
            "ts": round(time.time(), 6), "kind": "spare_dropped",
            "dropped": sorted(s.task_id for s in dead),
        })

    def _close_wave_locked(self, timer: bool) -> dict | None:
        """Try to close the pending wave under the tracker lock.

        ``timer=False`` is the check-in path: only the legacy full-wave
        close (or an elastic grow absorbing spares/surplus) fires, so the
        non-elastic contract is byte-for-byte the old behavior.
        ``timer=True`` is the wave monitor / lease path and additionally
        applies the age-gated decisions (spare promotion, shrink).

        Returns a send plan (assignments + surplus parks) executed by
        :meth:`_send_wave` OUTSIDE the lock, or None to keep waiting."""
        if not self._pending:
            self._wave_started = None
            return None
        elastic_active = (bool(self._spares)
                          or self.elastic.shrink_after_sec > 0
                          or self.world_size < self.base_world)
        if timer and not elastic_active:
            return None
        age = time.monotonic() - (self._wave_started or time.monotonic())
        if len(self._pending) >= self.world_size or (timer and elastic_active):
            self._purge_dead_locked()
        if timer:
            self._reap_spares_locked()
        if not self._pending:
            self._wave_started = None
            return None
        decision = self.elastic.decide(
            len(self._pending), len(self._spares) if elastic_active else 0,
            age)
        if decision.action != CLOSE:
            return None
        # Promote parked spares into the wave's empty slots.
        for _ in range(decision.take_spares):
            if not self._spares:
                break
            sp = self._spares.pop(0)
            sp.cmd = P.CMD_START
            self._pending.append(sp)
        world = decision.world
        # Membership selection: survivors already holding a rank keep
        # priority; surplus check-ins (a restarted worker racing a
        # promoted spare for one slot) are parked as spares.
        ordered = sorted(
            range(len(self._pending)),
            key=lambda i: (not (0 <= self._ranks.get(
                self._pending[i].task_id, -1) < world), i))
        members = [self._pending[i] for i in sorted(ordered[:world])]
        surplus = [self._pending[i] for i in sorted(ordered[world:])]
        self._pending = []
        self._pending_ids = set()
        self._wave_started = None
        # Pool provenance, not take_spares, decides what counts as a
        # promotion: note_dead pre-stages a spare into _pending directly
        # (take_spares 0), and a taken spare can still land in surplus.
        promoted = [p.task_id for p in members if p.origin == "spare"]
        # Rank assignment + membership commit: stable re-admission >
        # launcher numbering > host-grouped fill (assign_ranks), then the
        # MembershipManager stamps the next world epoch.
        self._ranks.update(assign_ranks(
            [(p.task_id, p.host) for p in members], world, self._ranks,
            host_order=self.host_order))
        rank_map = {p.task_id: self._ranks[p.task_id] for p in members}
        prev_world = self.world_size
        prev_map = dict(self.elastic.current.rank_map)
        wepoch, delta = self.elastic.commit(rank_map, world)
        self.world_size = world
        ts = round(time.time(), 6)
        if self._quorum is not None:
            # An epoch boundary settles the correction ledger by dropping
            # (doc/partial_allreduce.md): ranks renumber and shards re-cut,
            # so an undelivered late block from the old world can never
            # fold — record exactly what went missing.
            for sv, r, w in self._quorum.epoch_changed(wepoch.epoch):
                self.events.append({
                    "ts": ts, "kind": "correction_dropped",
                    "epoch": wepoch.epoch, "src_version": sv, "rank": r,
                    "world": w,
                })
        restarted = []
        for p in members:
            if p.cmd == P.CMD_START:
                n_seen = self._n_starts.get(p.task_id, 0)
                self._n_starts[p.task_id] = n_seen + 1
                if n_seen > 0:
                    restarted.append(p.task_id)
        self.events.append({
            "ts": ts,
            "kind": "wave",
            "epoch": wepoch.epoch,
            "world": world,
            "assignments": dict(rank_map),
            "recovering": sorted(p.task_id for p in members
                                 if p.cmd == P.CMD_RECOVER),
            "restarted": sorted(restarted),
            "delta": delta,
        })
        for task_id in promoted:
            self.events.append({
                "ts": ts, "kind": "spare_promoted", "task_id": task_id,
                "rank": rank_map[task_id], "epoch": wepoch.epoch,
            })
        if world < prev_world:
            self.events.append({
                "ts": ts, "kind": "world_shrunk", "epoch": wepoch.epoch,
                "from": prev_world, "to": world,
                "lost": sorted(t for t in prev_map if t not in rank_map),
            })
        elif world > prev_world:
            self.events.append({
                "ts": ts, "kind": "world_grown", "epoch": wepoch.epoch,
                "from": prev_world, "to": world,
                "joined": sorted(delta["joined"]),
            })
        # The wave is THE control-plane commit: one journal record
        # carries everything a standby needs to close the same waves
        # (rank line, admission counters, promoted spares) — and its
        # epoch boundary settles the replayed quorum ledger exactly as
        # epoch_changed settled the live one (doc/ha.md).
        self._journal(
            "wave", epoch=wepoch.epoch, world=world,
            rank_map=dict(rank_map),
            started=sorted(p.task_id for p in members
                           if p.cmd == P.CMD_START),
            promoted=sorted(promoted))
        return {"members": members, "world": world, "epoch": wepoch.epoch,
                "rank_map": rank_map, "surplus": surplus,
                "promoted": promoted, "resized": decision.resized}

    def _wave_monitor(self) -> None:
        """Age-gated wave decisions (spare promotion, shrink, grow): scan
        the pending wave and close it when the membership manager says so.
        A no-op for non-elastic jobs (no spares, shrinking disabled)."""
        while not self._done.wait(0.05):
            self._wave_tick()

    def _wave_tick(self) -> None:
        """One wave-monitor scan — factored out so a CollectiveService's
        single monitor thread can tick every headless partition
        (doc/service.md) instead of running a thread pair per job."""
        with self._lock:
            plan = self._close_wave_locked(timer=True)
        if plan is not None:
            self._send_wave(plan)

    def note_dead(self, task_id: str) -> None:
        """Fast-path promotion hook: a task known dead (lease expired,
        launcher-confirmed kill) pre-stages a parked spare into the forming
        wave, so the recovery wave closes the moment the survivors arrive
        — promotion within ONE wave instead of one wave plus a timeout."""
        with self._lock:
            if any(p.task_id == task_id for p in self._pending):
                return  # it is checking in right now — not actually dead
            self._reap_spares_locked()
            if not self._spares:
                return
            sp = self._spares.pop(0)
            sp.cmd = P.CMD_START
            self._pending.append(sp)
            self._pending_ids.add(sp.task_id)
            if self._wave_started is None:
                self._wave_started = time.monotonic()
            plan = self._close_wave_locked(timer=True)
        if plan is not None:
            self._send_wave(plan)

    def _send_wave(self, plan: dict) -> None:
        """Deliver a closed wave: one Assignment per member (the epoch and
        the full rank_map ride along), park replies to surplus check-ins.
        Runs OUTSIDE the tracker lock — sends must not serialize against
        check-in handling."""
        world = plan["world"]
        peers = {plan["rank_map"][p.task_id]: (p.host, p.listen_port)
                 for p in plan["members"]}
        # Plan the epoch's collective schedule (rabit_tpu.sched).  The
        # Assignment PREFIX stays the legacy tree+ring — the native
        # client's fixed data plane; the planned ring order trails the
        # rank_map for schedule-aware executors.
        splan = self._plan_schedule(world, plan["rank_map"])
        ts = round(time.time(), 6)
        with self._lock:
            self._last_ring = (list(splan.ring_order)
                               or list(range(world)))
            self._journal("sched", epoch=plan["epoch"], algo=splan.algo,
                          ring=list(self._last_ring))
            self.events.append({
                "ts": ts, "kind": "schedule_planned",
                "epoch": plan["epoch"], "algo": splan.algo, "world": world,
                "ring_order": list(splan.ring_order),
                "n_avoided": len(splan.avoided),
            })
            if splan.repaired or splan.residual:
                self.events.append({
                    "ts": ts, "kind": "schedule_repaired",
                    "epoch": plan["epoch"], "algo": splan.algo,
                    "avoided": [list(l) for l in splan.avoided],
                    "residual": [list(l) for l in splan.residual],
                })
        if (splan.repaired or splan.residual) and not self.quiet:
            print(f"[tracker] schedule repaired for epoch {plan['epoch']}: "
                  f"routed around {list(splan.avoided)}"
                  + (f", residual {list(splan.residual)}"
                     if splan.residual else ""), flush=True)
        # The peer table, rank_map, and schedule frame are identical for
        # every member: encode that suffix ONCE per wave.  The legacy
        # serving path keeps the per-member Assignment.encode (the PR 8
        # behavior the scale sweep measures against) — the bytes are
        # identical either way (protocol.assignment_tail_bytes).
        tail = (P.assignment_tail_bytes(peers, plan["epoch"],
                                        plan["rank_map"], splan.algo,
                                        list(splan.ring_order))
                if self._reactor else None)
        for p in plan["members"]:
            rank = plan["rank_map"][p.task_id]
            parent, children = P.tree_topology(rank, world)
            if tail is not None:
                payload = P.assignment_head_bytes(
                    rank, world, parent, children,
                    (rank - 1) % world, (rank + 1) % world) + tail
            else:
                payload = P.Assignment(
                    rank=rank,
                    world_size=world,
                    parent=parent,
                    children=children,
                    ring_prev=(rank - 1) % world,
                    ring_next=(rank + 1) % world,
                    peers=peers,
                    epoch=plan["epoch"],
                    rank_map=plan["rank_map"],
                    algo=splan.algo,
                    ring_order=list(splan.ring_order),
                ).encode()
            try:
                p.conn.sendall(payload)
            except OSError:
                pass  # worker died mid-bootstrap; next wave will handle it
            finally:
                try:
                    p.conn.close()
                except OSError:
                    pass
        for p in plan["surplus"]:
            # A live check-in the wave had no slot for: park it as a spare
            # (the park reply — a MAGIC_BLOB frame — tells an elastic
            # client "you are pooled"; promotion answers the same socket).
            with self._lock:
                version, blob = (self._blob if self._blob is not None
                                 else (0, b""))
            try:
                p.conn.sendall(P.put_blob_frame(version, blob))
            except OSError:
                try:
                    p.conn.close()
                except OSError:
                    pass
                continue
            p.origin = "spare"
            p.cmd = P.CMD_START
            with self._lock:
                self._spares.append(p)
                self._journal("spare_park", task_id=p.task_id,
                              blob_version=version)
                self.events.append({
                    "ts": round(time.time(), 6), "kind": "spare_parked",
                    "task_id": p.task_id, "blob_version": version,
                    "pool": len(self._spares),
                })

    # -- liveness ----------------------------------------------------------

    def _renew_lease(self, task_id: str, rank: int, payload: str) -> None:
        """Grant/renew a heartbeat lease: the worker promises to renew every
        ``interval`` seconds and is suspected after LEASE_FACTOR intervals
        of silence.  The payload is the decimal interval (see protocol.py)."""
        try:
            interval = float(payload)
        except ValueError:
            return  # malformed heartbeat must not hurt the tracker
        if not (0 < interval < 86400):
            return
        with self._lock:
            prev = self._leases.get(task_id)
            self._leases[task_id] = _Lease(
                time.monotonic() + P.LEASE_FACTOR * interval, interval, rank)
            # Journal GRANTS (and identity changes), not every renewal:
            # the replayable fact is "this task holds a lease of this
            # interval at this rank" — deadlines are wall-clock and
            # re-arm fresh at takeover (doc/ha.md).
            if prev is None or prev.interval != interval \
                    or prev.rank != rank:
                self._journal("lease", task_id=task_id,
                              interval=interval, rank=rank)

    def _lease_monitor(self) -> None:
        """Scan leases and suspect the silent.  An expired lease is removed
        before ``on_suspect`` fires, so one hang produces exactly one
        suspicion (the restarted life re-establishes its own lease)."""
        next_tick = time.monotonic() + self._ha_tick_sec
        while not self._done.wait(0.05):
            now = time.monotonic()
            if self.journal is not None and now >= next_tick:
                # The HA keepalive: a tick record proves the primary is
                # alive to file-tailing AND streaming standbys, so an
                # idle job never looks dead (doc/ha.md).  Ticks stay in
                # the serving tracker's loop — headless partitions share
                # their service's journal, which ticks once for all.
                next_tick = now + self._ha_tick_sec
                self._journal("tick")
            self._lease_tick(now)

    def _lease_tick(self, now: float) -> None:
        """One lease-monitor scan (see :meth:`_wave_tick` for why this
        is factored out of the thread loop)."""
        expired: list[tuple[str, _Lease]] = []
        with self._lock:
            for task_id, lease in list(self._leases.items()):
                if now >= lease.expires:
                    del self._leases[task_id]
                    self._journal("lease_drop", task_id=task_id)
                    expired.append((task_id, lease))
            for task_id, lease in expired:
                self.events.append({
                    "ts": round(time.time(), 6), "kind": "lease_expired",
                    "task_id": task_id, "rank": lease.rank,
                    "interval": lease.interval,
                    "overdue": round(now - lease.expires, 6),
                })
        for task_id, lease in expired:
            if not self.quiet:
                print(f"[tracker] lease expired for task {task_id} "
                      f"(rank {lease.rank}, interval {lease.interval}s): "
                      f"suspecting worker", flush=True)
            if self.on_suspect is not None:
                try:
                    self.on_suspect(task_id)
                except Exception:  # noqa: BLE001 — detection must survive
                    pass
            # Elastic fast path: a confirmed-dead task's slot is filled
            # by pre-staging a parked spare into the forming recovery
            # wave — promotion within one wave (doc/elasticity.md).
            self.note_dead(task_id)
        if expired:
            # An expired lease may have been the last thing holding the
            # completion guard (every shut-down rank already counted):
            # re-evaluate, or wait() would hang on a dead straggler.
            with self._lock:
                done = (0 < self.world_size <= self._n_shutdown
                        and not (set(self._leases)
                                 - self._shutdown_tasks))
            if done:
                self._finalize_done()
        self._diag_tick(now)

    def live_tasks(self) -> list[str]:
        """Task ids currently holding an unexpired lease."""
        with self._lock:
            return sorted(self._leases)

    # -- live telemetry plane (doc/observability.md) -----------------------

    def _fold_delta_frame(self, payload: bytes,
                          ts: float | None = None) -> None:
        """Fold one relay-coalesced CMD_OBS metric-delta frame.  Pure
        dict math over an already-received payload — safe inside the
        relay batch fold (reactor-blocking family)."""
        self._fold_delta_doc(P.delta_frame_from_bytes(payload), ts)

    def _fold_delta_doc(self, doc: dict, ts: float | None = None) -> None:
        """Fold one delta document ({schema, job, ranks: {rank: delta}})
        into the live rollup.  Unknown schema versions are dropped whole —
        a newer worker must not half-corrupt an older tracker's rollup."""
        if doc.get("schema") != obs_stream.STREAM_SCHEMA:
            return
        stamp = ts if ts is not None else round(time.time(), 6)
        for rank, delta in doc.get("ranks", {}).items():
            if not isinstance(delta, dict):
                continue
            self._stream.fold(rank, delta, ts=stamp)
            with self._lock:
                if str(rank) not in self._delta_ranks:
                    # First-fold evidence per rank (not per delta — a
                    # heartbeat-cadence stream would flood the timeline).
                    self._delta_ranks.add(str(rank))
                    self.events.append({
                        "ts": stamp, "kind": "metrics_delta_folded",
                        "rank": str(rank),
                    })

    def _diag_tick(self, now: float) -> None:
        """One diagnosis window (``rabit_diag_window_sec`` cadence), run
        from the lease-monitor thread — a service ticks every partition's
        monitor through its ``_lease_tick`` override.  State is copied
        under the lock, the rules evaluate OUTSIDE it (HealthMonitor has
        its own leaf lock; the rollup render takes its own), and the
        repair feed fires with no lock held (flag_link locks)."""
        hm = self._health
        if not hm.enabled or now < self._diag_next:
            return
        self._diag_next = now + hm.window_sec
        with self._lock:
            events_delta = self.events[self._diag_ev_idx:]
            self._diag_ev_idx = len(self.events)
            dropped = self.messages_dropped
        stream_doc = self._stream.render()
        opened, resolved = hm.observe(now, stream_doc,
                                      {"events_delta": events_delta,
                                       "messages_dropped": dropped})
        if not opened and not resolved:
            return
        ts = round(time.time(), 6)
        with self._lock:
            for inc in opened:
                self.events.append({"ts": ts, "kind": "incident_opened",
                                    "incident": inc.incident_id,
                                    "class": inc.cls, **inc.subject})
            for inc in resolved:
                self.events.append({"ts": ts, "kind": "incident_resolved",
                                    "incident": inc.incident_id,
                                    "class": inc.cls, **inc.subject})
        for inc in opened:
            if not self.quiet:
                print(f"[tracker] incident opened: {inc.incident_id} "
                      f"{inc.subject}", flush=True)
            if inc.cls == "degraded-link":
                # The attributed, hysteresis-confirmed repair signal —
                # same avoid-set machinery as a worker slow_link report,
                # minus the one-report-per-epoch guesswork.
                try:
                    src = int(inc.subject.get("src"))
                    dst = int(inc.subject.get("dst"))
                except (TypeError, ValueError):
                    continue
                self.flag_link(src, dst)
        for inc in resolved:
            if not self.quiet:
                print(f"[tracker] incident resolved: {inc.incident_id} "
                      f"after {inc.windows} window(s)", flush=True)

    def _scrape_job_state(self) -> dict:
        """One job's live scrape section, assembled from already-locked
        copies of control state (never file IO): membership, leases, the
        spare pool, admission/wave pressure, quorum ledger depth, and the
        streamed-metrics rollup.  The schema (job -> rank -> link) is the
        contract the QoS/autoscaler/route-around loops consume."""
        with self._lock:
            live = {
                "epoch": self.elastic.epoch,
                "world": self.world_size,
                "base_world": self.base_world,
                "leases": len(self._leases),
                "spares": len(self._spares),
                "pending": len(self._pending),
                "n_shutdown": self._n_shutdown,
                "restarts": sum(n - 1 for n in self._n_starts.values()
                                if n > 1),
                "quorum_outstanding": (len(self._quorum.outstanding())
                                       if self._quorum is not None else 0),
                "link_flags": len(self._link_flags),
                "n_events": len(self.events),
                "n_snapshots": len(self.snapshots),
                "messages_dropped": self.messages_dropped,
                # Model-delivery plane (doc/delivery.md): the published
                # version line, the digest store's footprint, and the
                # distinct-subscriber count the autoscaler watches.
                "delivery": {
                    "line": (dict(self._delivery)
                             if self._delivery is not None else None),
                    "snaps": len(self._snaps),
                    "snap_bytes": sum(len(b)
                                      for b in self._snaps.values()),
                    "subscribers": len(self._sub_ids),
                },
            }
        # The rollup carries its own leaf lock; render it OUTSIDE
        # self._lock (lock-order discipline, doc/static_analysis.md).
        live["stream"] = self._stream.render()
        live["incidents"] = self._health.render()
        return live

    def build_scrape(self, opts: dict | None = None) -> dict:
        """The CMD_OBS exposition: a versioned JSON document of live
        tracker state + per-job rollups + this process's own metrics
        registry.  ``opts`` (the RPC payload) may set ``registry: false``
        to skip the registry section (cheaper high-frequency polls).
        A CollectiveService overrides this with the multi-tenant view
        (tenant -> job -> rank -> link, doc/service.md)."""
        opts = opts or {}
        with self._stats_lock:
            serve = dict(self.serve_stats)
        doc = {
            "schema": obs_stream.STREAM_SCHEMA,
            "ts": round(time.time(), 6),
            "started_at": round(self._started_at, 6),
            "serving": {"reactor": self._reactor, "backlog": self.backlog,
                        **serve},
            "jobs": {self.job or "": self._scrape_job_state()},
        }
        doc["incidents"] = _aggregate_incidents(doc["jobs"])
        if opts.get("registry", True):
            doc["registry"] = GLOBAL_REGISTRY.snapshot()
        return doc

    # -- telemetry ---------------------------------------------------------

    def _accept_snapshot(self, payload: str) -> None:
        """Fold one CMD_METRICS JSON envelope into the per-rank table
        (latest per rank wins — a restarted life's final snapshot replaces
        its dead predecessor's heartbeat).  Snapshots with an out-of-range
        rank are rejected at ingest: a malformed ``rank=-1`` (worker shipped
        before its assignment) must not pollute the per-rank table that
        telemetry.json presents as ground truth."""
        try:
            snap = json.loads(payload)
            rank = int(snap.get("rank", -1))
        except (ValueError, TypeError, AttributeError):
            return  # malformed snapshot must not hurt the tracker
        # The piggybacked streamed-metrics delta (doc/observability.md
        # "Live telemetry plane") is stripped BEFORE the snapshot is
        # stored: the stored snapshot stays cumulative-only, and a
        # latest-per-rank replacement can never lose a window.
        delta = snap.pop("delta", None)
        # Validate against the LARGEST world this job has seen: a shrunken
        # world must not reject the final snapshot of a rank that was valid
        # in the epoch the snapshot describes.
        if not 0 <= rank < max(self.world_size, self.base_world):
            with self._lock:
                self.events.append({
                    "ts": round(time.time(), 6), "kind": "snapshot_rejected",
                    "rank": rank, "task_id": str(snap.get("task_id", "")),
                })
            return
        with self._lock:
            self.snapshots[rank] = snap
            self.events.append({
                "ts": round(time.time(), 6), "kind": "metrics_snapshot",
                "rank": rank, "task_id": snap.get("task_id", ""),
            })
        if isinstance(delta, dict) and delta:
            self._fold_delta_doc({"schema": obs_stream.STREAM_SCHEMA,
                                  "job": self.job,
                                  "ranks": {str(rank): delta}})

    def build_telemetry(self) -> dict:
        """Assemble the job-level telemetry document: per-rank op latency
        stats/percentiles (from shipped registry snapshots), the
        bootstrap/recovery wave timeline, and restart counts."""
        with self._lock:
            events = list(self.events)
            snapshots = {str(r): s for r, s in sorted(self.snapshots.items())}
            restarts = {t: n - 1 for t, n in self._n_starts.items() if n > 1}
            q_outstanding = ([list(t) for t in self._quorum.outstanding()]
                             if self._quorum is not None else [])
        with self._stats_lock:
            serve = dict(self.serve_stats)
        # The live-plane rollup rides into the post-mortem document too:
        # a scrape taken mid-run and the shutdown telemetry.json agree
        # byte-for-byte on every fully-folded cumulative counter.
        stream_rollup = self._stream.render()
        incidents = self._health.render()
        waves = [e for e in events if e["kind"] == "wave"]
        # Per-rank clock-offset estimates (tracker_ts = worker_ts +
        # offset_s), shipped inside snapshots; the trace merger uses these
        # to project every rank's dump onto the tracker timeline.
        clocks = {r: s["clock"] for r, s in snapshots.items()
                  if isinstance(s, dict) and s.get("clock")}
        return {
            "schema": TELEMETRY_SCHEMA,
            "job": self.job,
            "world_size": self.world_size,
            "base_world": self.base_world,
            "started_at": round(self._started_at, 6),
            "finished_at": round(time.time(), 6),
            "n_waves": len(waves),
            "n_recovery_waves": sum(1 for w in waves if w["epoch"] > 0),
            "n_lease_expired": sum(1 for e in events
                                   if e["kind"] == "lease_expired"),
            "n_shrunk": sum(1 for e in events
                            if e["kind"] == "world_shrunk"),
            "n_grown": sum(1 for e in events
                           if e["kind"] == "world_grown"),
            "n_spares_promoted": sum(1 for e in events
                                     if e["kind"] == "spare_promoted"),
            "schedule": self.schedule,
            "n_schedule_repaired": sum(1 for e in events
                                       if e["kind"] == "schedule_repaired"),
            "quorum": self._quorum.spec if self._quorum is not None else "",
            "n_quorum_met": sum(1 for e in events
                                if e["kind"] == "quorum_met"),
            "n_corrections_folded": sum(1 for e in events
                                        if e["kind"] == "correction_folded"),
            "n_corrections_dropped": sum(
                1 for e in events if e["kind"] == "correction_dropped"),
            # still-undelivered exclusions at telemetry time, as
            # [src_version, rank, world] — the exact missing mass
            "quorum_outstanding": q_outstanding,
            # serving-path evidence (doc/scaling.md): reactor/threaded
            # model, connection and thread high-water marks, relay
            # batching counts, and worker-print log drops
            "serving": {"reactor": self._reactor, "backlog": self.backlog,
                        **serve},
            "messages_dropped": self.messages_dropped,
            "n_relays_up": sum(1 for e in events if e["kind"] == "relay_up"),
            "n_relays_lost": sum(1 for e in events
                                 if e["kind"] == "relay_lost"),
            "epochs": [{"epoch": we.epoch, "world": we.world_size}
                       for we in self.elastic.history],
            "restarts": restarts,
            "clocks": clocks,
            "stream": stream_rollup,
            "incidents": incidents,
            "waves": waves,
            "events": events,
            "ranks": snapshots,
        }

    def write_telemetry(self) -> str | None:
        """Write telemetry.json into the obs dir (atomic rename so a
        concurrent reader never sees a torn file).  Idempotent: the first
        caller wins; returns the path, or None when no obs dir is set.
        A LOSING caller blocks until the winner's file is on disk —
        the completion finalizer runs on its own thread (reactor
        discipline), and "stop() returned" must still imply
        telemetry.json exists."""
        with self._lock:
            claimed = self._telemetry_written
            self._telemetry_written = True
        if claimed:
            self._telemetry_flushed.wait(5.0)
            return None
        try:
            self.telemetry = self.build_telemetry()
            if not self.obs_dir:
                return None
            os.makedirs(self.obs_dir, exist_ok=True)
            # Per-job namespacing (doc/service.md): two jobs sharing one
            # RABIT_OBS_DIR must not clobber each other's telemetry; the
            # bare legacy name is kept for the single-job path.
            name = (f"telemetry-{self.job}.json" if self.job
                    else "telemetry.json")
            path = os.path.join(self.obs_dir, name)
            tmp = f"{path}.tmp.{os.getpid()}"
            with open(tmp, "w") as f:
                json.dump(self.telemetry, f, indent=1, sort_keys=True)
            os.replace(tmp, path)
            return path
        except OSError:
            return None  # observability must not fail the job
        finally:
            self._telemetry_flushed.set()

