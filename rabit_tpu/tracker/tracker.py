"""The tracker: rank assignment, topology, bootstrap waves, worker restart
coordination.

Capability parity with dmlc-core's tracker (the piece the reference
outsources — SURVEY.md C18): it launches nothing itself (see launcher.py);
it accepts worker check-ins, assigns stable ranks keyed by task id, builds
the reduction tree + ring, hands every worker the full peer table, and
funnels worker ``print``/``shutdown`` messages.  Recovery is wave-based: a
worker death cascades into every survivor reconnecting with ``recover``
while the launcher restarts the dead one with ``start``; once world_size
check-ins are pending, the tracker broadcasts a fresh assignment with a
bumped epoch.

The tracker is also the job-level telemetry aggregator
(doc/observability.md): it keeps a structured event timeline (bootstrap/
recovery waves; the robust engine's ``recover_stats``/``failure_detected``
prints converted to events at ingest), accepts ``CMD_METRICS`` snapshots
from workers, and writes ``telemetry.json`` into ``RABIT_OBS_DIR`` when
the job ends.
"""

from __future__ import annotations

import json
import os
import socket
import threading
import time
from dataclasses import dataclass

from rabit_tpu.obs.events import event_from_stats_line
from rabit_tpu.tracker import protocol as P

#: telemetry.json envelope version (bump on incompatible change).
TELEMETRY_SCHEMA = 1


@dataclass
class _Pending:
    conn: socket.socket
    task_id: str
    listen_port: int
    host: str
    prev_rank: int
    cmd: int = P.CMD_START


def assign_ranks(
    wave: list[tuple[str, str]],
    world_size: int,
    prev_ranks: dict[str, int],
    host_order: list[str] | None = None,
) -> dict[str, int]:
    """Topology-aware rank assignment (pure, unit-testable).

    ``wave`` is ``[(task_id, host), ...]`` in check-in order.  Precedence:

    1. stable re-admission — a task id seen before keeps its rank (the
       reference tracker's recover contract, ReConnectLinks
       allreduce_base.cc:263-438);
    2. launcher-numbered ids — ``int(task_id)`` when valid and free, so
       mock-kill specs and restart counters line up;
    3. the rest are grouped BY HOST and handed contiguous free ranks, so
       ring neighbors (rank±1) and tree subtrees stay on one host and
       cross-host traffic rides as few DCN hops as possible (the reference
       tracker is host-blind here; BASELINE north star: topology-aware).

    ``host_order`` ranks the host groups (e.g. a TPU slice's physical
    worker order, see tpu_slice_host_order); unlisted hosts follow in
    first-seen order.
    """
    ranks: dict[str, int] = {}
    taken: set[int] = set()
    for task_id, _host in wave:
        prev = prev_ranks.get(task_id)
        # Two task ids can hold the SAME stale rank (one freed it in an
        # earlier wave, another inherited it, then the first rejoins):
        # first-in-wave wins, the other falls through to a fresh slot.
        if prev is not None and 0 <= prev < world_size and prev not in taken:
            ranks[task_id] = prev
            taken.add(prev)
    for task_id, _host in wave:
        if task_id in ranks:
            continue
        try:
            cand = int(task_id)
        except ValueError:
            continue
        if 0 <= cand < world_size and cand not in taken:
            ranks[task_id] = cand
            taken.add(cand)
    # Host-grouped fill of the remaining slots.
    order_index = {h: i for i, h in enumerate(host_order or [])}
    groups: dict[str, list[str]] = {}
    first_seen: dict[str, int] = {}
    for i, (task_id, host) in enumerate(wave):
        if task_id in ranks:
            continue
        groups.setdefault(host, []).append(task_id)
        first_seen.setdefault(host, i)
    free = iter(r for r in range(world_size) if r not in taken)
    for host in sorted(
        groups, key=lambda h: (order_index.get(h, len(order_index)), first_seen[h])
    ):
        for task_id in groups[host]:
            ranks[task_id] = next(free)
    return ranks


def tpu_slice_host_order() -> list[str] | None:
    """Physical host order of the current TPU slice from TPU-VM metadata.

    Cloud TPU VMs export ``TPU_WORKER_HOSTNAMES`` (comma-separated, in
    worker-id order — which walks the slice's ICI topology) and
    ``TPU_WORKER_ID``.  Ordering tracker ranks along it lays the rabit ring
    over ICI neighbors instead of arbitrary DCN paths (BASELINE north star:
    "tracker discovers v5e pod topology").  Returns None off-TPU.
    """
    names = os.environ.get("TPU_WORKER_HOSTNAMES", "")
    hosts = [h.strip() for h in names.split(",") if h.strip()]
    return hosts or None


class Tracker:
    def __init__(self, world_size: int, host: str = "127.0.0.1", port: int = 0,
                 quiet: bool = False, topology: str = "auto",
                 host_order: list[str] | None = None,
                 obs_dir: str | None = None):
        self.world_size = world_size
        self.quiet = quiet
        # Job-level telemetry (doc/observability.md): structured events
        # (bootstrap/recovery waves, recover_stats converted from prints),
        # the latest metric snapshot per rank (CMD_METRICS), restart
        # counts — written to <obs_dir>/telemetry.json when the job ends.
        if obs_dir is None:
            obs_dir = os.environ.get("RABIT_OBS_DIR", "") or None
        self.obs_dir = obs_dir
        self.events: list[dict] = []
        self.snapshots: dict[int, dict] = {}  # rank -> latest shipped snapshot
        self.telemetry: dict | None = None
        self._started_at = time.time()
        self._n_starts: dict[str, int] = {}  # task_id -> CMD_START check-ins
        self._telemetry_written = False
        # topology: "auto" uses TPU slice metadata when present, "tpu"
        # requires it, anything else is plain host grouping.
        if host_order is None and topology in ("auto", "tpu"):
            host_order = tpu_slice_host_order()
            if topology == "tpu" and host_order is None:
                raise RuntimeError(
                    "topology='tpu' but TPU_WORKER_HOSTNAMES is not set"
                )
        self.host_order = host_order
        self._srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._srv.bind((host, port))
        self._srv.listen(256)
        self.host, self.port = self._srv.getsockname()
        self._lock = threading.Lock()
        self._pending: list[_Pending] = []
        self._ranks: dict[str, int] = {}  # task_id -> stable rank
        self._epoch = 0
        self._n_shutdown = 0
        self._done = threading.Event()
        self._thread: threading.Thread | None = None
        self.messages: list[str] = []  # worker print log (also echoed)

    # -- lifecycle ---------------------------------------------------------

    def start(self) -> "Tracker":
        self._thread = threading.Thread(target=self._serve, daemon=True)
        self._thread.start()
        return self

    def wait(self, timeout: float | None = None) -> bool:
        return self._done.wait(timeout)

    def stop(self) -> None:
        self._done.set()
        try:
            self._srv.close()
        except OSError:
            pass
        # Safety net for jobs torn down without a full shutdown wave (kill,
        # timeout): idempotent, so the normal all-ranks-shut-down path has
        # already written by the time stop() runs.
        self.write_telemetry()

    # -- serving -----------------------------------------------------------

    def _serve(self) -> None:
        while not self._done.is_set():
            try:
                conn, addr = self._srv.accept()
            except OSError:
                break
            threading.Thread(
                target=self._handle, args=(conn, addr), daemon=True
            ).start()

    def _handle(self, conn: socket.socket, addr) -> None:
        try:
            magic = P.get_u32(conn)
            if magic != P.MAGIC_HELLO:
                conn.close()
                return
            cmd = P.get_u32(conn)
            prev_rank = P.get_i32(conn)
            task_id = P.get_str(conn)
            if cmd in (P.CMD_START, P.CMD_RECOVER):
                listen_port = P.get_u32(conn)
                self._register(conn, addr[0], task_id, listen_port, prev_rank,
                               cmd)
                # conn is answered (and closed) by the wave completer.
                return
            if cmd == P.CMD_PRINT:
                msg = P.get_str(conn)
                self.messages.append(msg)
                # Legacy-line bridge: the robust engine's recover_stats /
                # failure_detected prints become structured events here, so
                # consumers read self.events / telemetry.json instead of
                # scraping stdout.
                ev = event_from_stats_line(msg)
                if ev is not None:
                    with self._lock:
                        self.events.append(
                            {"ts": round(ev.ts, 6), "kind": ev.kind,
                             **ev.fields})
                if not self.quiet:
                    print(msg, end="" if msg.endswith("\n") else "\n", flush=True)
                conn.sendall(P.put_u32(P.ACK))
            elif cmd == P.CMD_METRICS:
                msg = P.get_str(conn)
                self._accept_snapshot(msg)
                conn.sendall(P.put_u32(P.ACK))
            elif cmd == P.CMD_SHUTDOWN:
                conn.sendall(P.put_u32(P.ACK))
                done = False
                with self._lock:
                    self._n_shutdown += 1
                    done = self._n_shutdown >= self.world_size
                if done:
                    # Persist BEFORE releasing wait()ers: by the time the
                    # launcher sees the job done, telemetry.json exists.
                    self.write_telemetry()
                    self._done.set()
            conn.close()
        except (ConnectionError, OSError, ValueError):
            try:
                conn.close()
            except OSError:
                pass

    def _register(self, conn, host, task_id, listen_port, prev_rank,
                  cmd=P.CMD_START) -> None:
        with self._lock:
            # A re-check-in from the same task id replaces its stale entry
            # (e.g. worker retried while the wave was still filling).
            for stale in (p for p in self._pending if p.task_id == task_id):
                try:
                    stale.conn.close()
                except OSError:
                    pass
            self._pending = [p for p in self._pending if p.task_id != task_id]
            self._pending.append(
                _Pending(conn, task_id, listen_port, host, prev_rank, cmd))
            if len(self._pending) < self.world_size:
                return
            wave, self._pending = self._pending, []
            epoch = self._epoch
            self._epoch += 1
        self._assign_and_send(wave, epoch)

    # -- telemetry ---------------------------------------------------------

    def _accept_snapshot(self, payload: str) -> None:
        """Fold one CMD_METRICS JSON envelope into the per-rank table
        (latest per rank wins — a restarted life's final snapshot replaces
        its dead predecessor's heartbeat)."""
        try:
            snap = json.loads(payload)
            rank = int(snap.get("rank", -1))
        except (ValueError, TypeError):
            return  # malformed snapshot must not hurt the tracker
        with self._lock:
            self.snapshots[rank] = snap
            self.events.append({
                "ts": round(time.time(), 6), "kind": "metrics_snapshot",
                "rank": rank, "task_id": snap.get("task_id", ""),
            })

    def build_telemetry(self) -> dict:
        """Assemble the job-level telemetry document: per-rank op latency
        stats/percentiles (from shipped registry snapshots), the
        bootstrap/recovery wave timeline, and restart counts."""
        with self._lock:
            events = list(self.events)
            snapshots = {str(r): s for r, s in sorted(self.snapshots.items())}
            restarts = {t: n - 1 for t, n in self._n_starts.items() if n > 1}
        waves = [e for e in events if e["kind"] == "wave"]
        return {
            "schema": TELEMETRY_SCHEMA,
            "world_size": self.world_size,
            "started_at": round(self._started_at, 6),
            "finished_at": round(time.time(), 6),
            "n_waves": len(waves),
            "n_recovery_waves": sum(1 for w in waves if w["epoch"] > 0),
            "restarts": restarts,
            "waves": waves,
            "events": events,
            "ranks": snapshots,
        }

    def write_telemetry(self) -> str | None:
        """Write telemetry.json into the obs dir (atomic rename so a
        concurrent reader never sees a torn file).  Idempotent: the first
        caller wins; returns the path, or None when no obs dir is set."""
        with self._lock:
            if self._telemetry_written:
                return None
            self._telemetry_written = True
        self.telemetry = self.build_telemetry()
        if not self.obs_dir:
            return None
        try:
            os.makedirs(self.obs_dir, exist_ok=True)
            path = os.path.join(self.obs_dir, "telemetry.json")
            tmp = f"{path}.tmp.{os.getpid()}"
            with open(tmp, "w") as f:
                json.dump(self.telemetry, f, indent=1, sort_keys=True)
            os.replace(tmp, path)
            return path
        except OSError:
            return None  # observability must not fail the job

    def _assign_and_send(self, wave: list[_Pending], epoch: int) -> None:
        # Stable re-admission > launcher numbering > host-grouped fill; see
        # assign_ranks for the full policy and rationale.
        self._ranks.update(
            assign_ranks(
                [(p.task_id, p.host) for p in wave],
                self.world_size,
                self._ranks,
                host_order=self.host_order,
            )
        )
        peers = {
            self._ranks[p.task_id]: (p.host, p.listen_port) for p in wave
        }
        # Timeline entry per bootstrap wave.  epoch 0 is the initial wave;
        # any later wave is a recovery wave: survivors re-check-in with
        # CMD_RECOVER while the launcher's restarted workers arrive with a
        # fresh CMD_START — those restarts are the per-task restart count.
        with self._lock:
            restarted = []
            for p in wave:
                if p.cmd == P.CMD_START:
                    n_seen = self._n_starts.get(p.task_id, 0)
                    self._n_starts[p.task_id] = n_seen + 1
                    if n_seen > 0:
                        restarted.append(p.task_id)
            self.events.append({
                "ts": round(time.time(), 6),
                "kind": "wave",
                "epoch": epoch,
                "assignments": {p.task_id: self._ranks[p.task_id]
                                for p in wave},
                "recovering": sorted(p.task_id for p in wave
                                     if p.cmd == P.CMD_RECOVER),
                "restarted": sorted(restarted),
            })
        n = self.world_size
        for p in wave:
            rank = self._ranks[p.task_id]
            parent, children = P.tree_topology(rank, n)
            asg = P.Assignment(
                rank=rank,
                world_size=n,
                parent=parent,
                children=children,
                ring_prev=(rank - 1) % n,
                ring_next=(rank + 1) % n,
                peers=peers,
                epoch=epoch,
            )
            try:
                p.conn.sendall(asg.encode())
            except OSError:
                pass  # worker died mid-bootstrap; next wave will handle it
            finally:
                try:
                    p.conn.close()
                except OSError:
                    pass
