"""The tracker: rank assignment, topology, bootstrap waves, worker restart
coordination.

Capability parity with dmlc-core's tracker (the piece the reference
outsources — SURVEY.md C18): it launches nothing itself (see launcher.py);
it accepts worker check-ins, assigns stable ranks keyed by task id, builds
the reduction tree + ring, hands every worker the full peer table, and
funnels worker ``print``/``shutdown`` messages.  Recovery is wave-based: a
worker death cascades into every survivor reconnecting with ``recover``
while the launcher restarts the dead one with ``start``; once world_size
check-ins are pending, the tracker broadcasts a fresh assignment with a
bumped epoch.
"""

from __future__ import annotations

import socket
import threading
from dataclasses import dataclass

from rabit_tpu.tracker import protocol as P


@dataclass
class _Pending:
    conn: socket.socket
    task_id: str
    listen_port: int
    host: str
    prev_rank: int


class Tracker:
    def __init__(self, world_size: int, host: str = "127.0.0.1", port: int = 0,
                 quiet: bool = False):
        self.world_size = world_size
        self.quiet = quiet
        self._srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._srv.bind((host, port))
        self._srv.listen(256)
        self.host, self.port = self._srv.getsockname()
        self._lock = threading.Lock()
        self._pending: list[_Pending] = []
        self._ranks: dict[str, int] = {}  # task_id -> stable rank
        self._epoch = 0
        self._n_shutdown = 0
        self._done = threading.Event()
        self._thread: threading.Thread | None = None
        self.messages: list[str] = []  # worker print log (also echoed)

    # -- lifecycle ---------------------------------------------------------

    def start(self) -> "Tracker":
        self._thread = threading.Thread(target=self._serve, daemon=True)
        self._thread.start()
        return self

    def wait(self, timeout: float | None = None) -> bool:
        return self._done.wait(timeout)

    def stop(self) -> None:
        self._done.set()
        try:
            self._srv.close()
        except OSError:
            pass

    # -- serving -----------------------------------------------------------

    def _serve(self) -> None:
        while not self._done.is_set():
            try:
                conn, addr = self._srv.accept()
            except OSError:
                break
            threading.Thread(
                target=self._handle, args=(conn, addr), daemon=True
            ).start()

    def _handle(self, conn: socket.socket, addr) -> None:
        try:
            magic = P.get_u32(conn)
            if magic != P.MAGIC_HELLO:
                conn.close()
                return
            cmd = P.get_u32(conn)
            prev_rank = P.get_i32(conn)
            task_id = P.get_str(conn)
            if cmd in (P.CMD_START, P.CMD_RECOVER):
                listen_port = P.get_u32(conn)
                self._register(conn, addr[0], task_id, listen_port, prev_rank)
                # conn is answered (and closed) by the wave completer.
                return
            if cmd == P.CMD_PRINT:
                msg = P.get_str(conn)
                self.messages.append(msg)
                if not self.quiet:
                    print(msg, end="" if msg.endswith("\n") else "\n", flush=True)
                conn.sendall(P.put_u32(P.ACK))
            elif cmd == P.CMD_SHUTDOWN:
                conn.sendall(P.put_u32(P.ACK))
                with self._lock:
                    self._n_shutdown += 1
                    if self._n_shutdown >= self.world_size:
                        self._done.set()
            conn.close()
        except (ConnectionError, OSError, ValueError):
            try:
                conn.close()
            except OSError:
                pass

    def _register(self, conn, host, task_id, listen_port, prev_rank) -> None:
        with self._lock:
            # A re-check-in from the same task id replaces its stale entry
            # (e.g. worker retried while the wave was still filling).
            for stale in (p for p in self._pending if p.task_id == task_id):
                try:
                    stale.conn.close()
                except OSError:
                    pass
            self._pending = [p for p in self._pending if p.task_id != task_id]
            self._pending.append(_Pending(conn, task_id, listen_port, host, prev_rank))
            if len(self._pending) < self.world_size:
                return
            wave, self._pending = self._pending, []
            epoch = self._epoch
            self._epoch += 1
        self._assign_and_send(wave, epoch)

    def _assign_and_send(self, wave: list[_Pending], epoch: int) -> None:
        # Stable ranks: task ids seen before keep their rank (re-admission of
        # a restarted worker, reference ReConnectLinks "recover").  New ids
        # get rank == int(task_id) when the launcher numbered them (so
        # mock-kill specs and launcher restart counters line up), otherwise
        # fill free slots in check-in order.
        taken = {self._ranks[p.task_id] for p in wave if p.task_id in self._ranks}
        for p in wave:
            if p.task_id in self._ranks:
                continue
            try:
                cand = int(p.task_id)
            except ValueError:
                continue
            if 0 <= cand < self.world_size and cand not in taken:
                self._ranks[p.task_id] = cand
                taken.add(cand)
        free = iter(r for r in range(self.world_size) if r not in taken)
        for p in wave:
            if p.task_id not in self._ranks:
                self._ranks[p.task_id] = next(free)
        peers = {
            self._ranks[p.task_id]: (p.host, p.listen_port) for p in wave
        }
        n = self.world_size
        for p in wave:
            rank = self._ranks[p.task_id]
            parent, children = P.tree_topology(rank, n)
            asg = P.Assignment(
                rank=rank,
                world_size=n,
                parent=parent,
                children=children,
                ring_prev=(rank - 1) % n,
                ring_next=(rank + 1) % n,
                peers=peers,
                epoch=epoch,
            )
            try:
                p.conn.sendall(asg.encode())
            except OSError:
                pass  # worker died mid-bootstrap; next wave will handle it
            finally:
                try:
                    p.conn.close()
                except OSError:
                    pass
