"""The tracker: rank assignment, topology, bootstrap waves, worker restart
coordination.

Capability parity with dmlc-core's tracker (the piece the reference
outsources — SURVEY.md C18): it launches nothing itself (see launcher.py);
it accepts worker check-ins, assigns stable ranks keyed by task id, builds
the reduction tree + ring, hands every worker the full peer table, and
funnels worker ``print``/``shutdown`` messages.  Recovery is wave-based: a
worker death cascades into every survivor reconnecting with ``recover``
while the launcher restarts the dead one with ``start``; once world_size
check-ins are pending, the tracker broadcasts a fresh assignment with a
bumped epoch.
"""

from __future__ import annotations

import os
import socket
import threading
from dataclasses import dataclass

from rabit_tpu.tracker import protocol as P


@dataclass
class _Pending:
    conn: socket.socket
    task_id: str
    listen_port: int
    host: str
    prev_rank: int


def assign_ranks(
    wave: list[tuple[str, str]],
    world_size: int,
    prev_ranks: dict[str, int],
    host_order: list[str] | None = None,
) -> dict[str, int]:
    """Topology-aware rank assignment (pure, unit-testable).

    ``wave`` is ``[(task_id, host), ...]`` in check-in order.  Precedence:

    1. stable re-admission — a task id seen before keeps its rank (the
       reference tracker's recover contract, ReConnectLinks
       allreduce_base.cc:263-438);
    2. launcher-numbered ids — ``int(task_id)`` when valid and free, so
       mock-kill specs and restart counters line up;
    3. the rest are grouped BY HOST and handed contiguous free ranks, so
       ring neighbors (rank±1) and tree subtrees stay on one host and
       cross-host traffic rides as few DCN hops as possible (the reference
       tracker is host-blind here; BASELINE north star: topology-aware).

    ``host_order`` ranks the host groups (e.g. a TPU slice's physical
    worker order, see tpu_slice_host_order); unlisted hosts follow in
    first-seen order.
    """
    ranks: dict[str, int] = {}
    taken: set[int] = set()
    for task_id, _host in wave:
        prev = prev_ranks.get(task_id)
        # Two task ids can hold the SAME stale rank (one freed it in an
        # earlier wave, another inherited it, then the first rejoins):
        # first-in-wave wins, the other falls through to a fresh slot.
        if prev is not None and 0 <= prev < world_size and prev not in taken:
            ranks[task_id] = prev
            taken.add(prev)
    for task_id, _host in wave:
        if task_id in ranks:
            continue
        try:
            cand = int(task_id)
        except ValueError:
            continue
        if 0 <= cand < world_size and cand not in taken:
            ranks[task_id] = cand
            taken.add(cand)
    # Host-grouped fill of the remaining slots.
    order_index = {h: i for i, h in enumerate(host_order or [])}
    groups: dict[str, list[str]] = {}
    first_seen: dict[str, int] = {}
    for i, (task_id, host) in enumerate(wave):
        if task_id in ranks:
            continue
        groups.setdefault(host, []).append(task_id)
        first_seen.setdefault(host, i)
    free = iter(r for r in range(world_size) if r not in taken)
    for host in sorted(
        groups, key=lambda h: (order_index.get(h, len(order_index)), first_seen[h])
    ):
        for task_id in groups[host]:
            ranks[task_id] = next(free)
    return ranks


def tpu_slice_host_order() -> list[str] | None:
    """Physical host order of the current TPU slice from TPU-VM metadata.

    Cloud TPU VMs export ``TPU_WORKER_HOSTNAMES`` (comma-separated, in
    worker-id order — which walks the slice's ICI topology) and
    ``TPU_WORKER_ID``.  Ordering tracker ranks along it lays the rabit ring
    over ICI neighbors instead of arbitrary DCN paths (BASELINE north star:
    "tracker discovers v5e pod topology").  Returns None off-TPU.
    """
    names = os.environ.get("TPU_WORKER_HOSTNAMES", "")
    hosts = [h.strip() for h in names.split(",") if h.strip()]
    return hosts or None


class Tracker:
    def __init__(self, world_size: int, host: str = "127.0.0.1", port: int = 0,
                 quiet: bool = False, topology: str = "auto",
                 host_order: list[str] | None = None):
        self.world_size = world_size
        self.quiet = quiet
        # topology: "auto" uses TPU slice metadata when present, "tpu"
        # requires it, anything else is plain host grouping.
        if host_order is None and topology in ("auto", "tpu"):
            host_order = tpu_slice_host_order()
            if topology == "tpu" and host_order is None:
                raise RuntimeError(
                    "topology='tpu' but TPU_WORKER_HOSTNAMES is not set"
                )
        self.host_order = host_order
        self._srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._srv.bind((host, port))
        self._srv.listen(256)
        self.host, self.port = self._srv.getsockname()
        self._lock = threading.Lock()
        self._pending: list[_Pending] = []
        self._ranks: dict[str, int] = {}  # task_id -> stable rank
        self._epoch = 0
        self._n_shutdown = 0
        self._done = threading.Event()
        self._thread: threading.Thread | None = None
        self.messages: list[str] = []  # worker print log (also echoed)

    # -- lifecycle ---------------------------------------------------------

    def start(self) -> "Tracker":
        self._thread = threading.Thread(target=self._serve, daemon=True)
        self._thread.start()
        return self

    def wait(self, timeout: float | None = None) -> bool:
        return self._done.wait(timeout)

    def stop(self) -> None:
        self._done.set()
        try:
            self._srv.close()
        except OSError:
            pass

    # -- serving -----------------------------------------------------------

    def _serve(self) -> None:
        while not self._done.is_set():
            try:
                conn, addr = self._srv.accept()
            except OSError:
                break
            threading.Thread(
                target=self._handle, args=(conn, addr), daemon=True
            ).start()

    def _handle(self, conn: socket.socket, addr) -> None:
        try:
            magic = P.get_u32(conn)
            if magic != P.MAGIC_HELLO:
                conn.close()
                return
            cmd = P.get_u32(conn)
            prev_rank = P.get_i32(conn)
            task_id = P.get_str(conn)
            if cmd in (P.CMD_START, P.CMD_RECOVER):
                listen_port = P.get_u32(conn)
                self._register(conn, addr[0], task_id, listen_port, prev_rank)
                # conn is answered (and closed) by the wave completer.
                return
            if cmd == P.CMD_PRINT:
                msg = P.get_str(conn)
                self.messages.append(msg)
                if not self.quiet:
                    print(msg, end="" if msg.endswith("\n") else "\n", flush=True)
                conn.sendall(P.put_u32(P.ACK))
            elif cmd == P.CMD_SHUTDOWN:
                conn.sendall(P.put_u32(P.ACK))
                with self._lock:
                    self._n_shutdown += 1
                    if self._n_shutdown >= self.world_size:
                        self._done.set()
            conn.close()
        except (ConnectionError, OSError, ValueError):
            try:
                conn.close()
            except OSError:
                pass

    def _register(self, conn, host, task_id, listen_port, prev_rank) -> None:
        with self._lock:
            # A re-check-in from the same task id replaces its stale entry
            # (e.g. worker retried while the wave was still filling).
            for stale in (p for p in self._pending if p.task_id == task_id):
                try:
                    stale.conn.close()
                except OSError:
                    pass
            self._pending = [p for p in self._pending if p.task_id != task_id]
            self._pending.append(_Pending(conn, task_id, listen_port, host, prev_rank))
            if len(self._pending) < self.world_size:
                return
            wave, self._pending = self._pending, []
            epoch = self._epoch
            self._epoch += 1
        self._assign_and_send(wave, epoch)

    def _assign_and_send(self, wave: list[_Pending], epoch: int) -> None:
        # Stable re-admission > launcher numbering > host-grouped fill; see
        # assign_ranks for the full policy and rationale.
        self._ranks.update(
            assign_ranks(
                [(p.task_id, p.host) for p in wave],
                self.world_size,
                self._ranks,
                host_order=self.host_order,
            )
        )
        peers = {
            self._ranks[p.task_id]: (p.host, p.listen_port) for p in wave
        }
        n = self.world_size
        for p in wave:
            rank = self._ranks[p.task_id]
            parent, children = P.tree_topology(rank, n)
            asg = P.Assignment(
                rank=rank,
                world_size=n,
                parent=parent,
                children=children,
                ring_prev=(rank - 1) % n,
                ring_next=(rank + 1) % n,
                peers=peers,
                epoch=epoch,
            )
            try:
                p.conn.sendall(asg.encode())
            except OSError:
                pass  # worker died mid-bootstrap; next wave will handle it
            finally:
                try:
                    p.conn.close()
                except OSError:
                    pass
