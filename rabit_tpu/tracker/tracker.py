"""The tracker: rank assignment, topology, bootstrap waves, worker restart
coordination.

Capability parity with dmlc-core's tracker (the piece the reference
outsources — SURVEY.md C18): it launches nothing itself (see launcher.py);
it accepts worker check-ins, assigns stable ranks keyed by task id, builds
the reduction tree + ring, hands every worker the full peer table, and
funnels worker ``print``/``shutdown`` messages.  Recovery is wave-based: a
worker death cascades into every survivor reconnecting with ``recover``
while the launcher restarts the dead one with ``start``; once world_size
check-ins are pending, the tracker broadcasts a fresh assignment with a
bumped epoch.

The tracker is also the job-level telemetry aggregator
(doc/observability.md): it keeps a structured event timeline (bootstrap/
recovery waves; the robust engine's ``recover_stats``/``failure_detected``
prints converted to events at ingest), accepts ``CMD_METRICS`` snapshots
from workers, and writes ``telemetry.json`` into ``RABIT_OBS_DIR`` when
the job ends.

Liveness (doc/fault_tolerance.md): workers renewing a ``CMD_HEARTBEAT``
lease get per-rank failure detection for SILENT failures — a preempted VM
or frozen process stops renewing, its lease expires after
``LEASE_FACTOR x interval``, the tracker emits a ``lease_expired`` event
and invokes the pluggable ``on_suspect(task_id)`` callback.  The launcher
wires that callback to SIGKILL-and-restart the suspect, converting a hang
into the recoverable-death shape the wave-based recovery already handles.
"""

from __future__ import annotations

import json
import os
import socket
import threading
import time
from dataclasses import dataclass
from typing import Callable

from rabit_tpu.obs.events import event_from_stats_line
from rabit_tpu.tracker import protocol as P

#: telemetry.json envelope version (bump on incompatible change).
TELEMETRY_SCHEMA = 1


@dataclass
class _Pending:
    conn: socket.socket
    task_id: str
    listen_port: int
    host: str
    prev_rank: int
    cmd: int = P.CMD_START


def _conn_dead(conn: socket.socket) -> bool:
    """True when the peer of a held-open connection has hung up (EOF/RST
    visible without consuming data).  Workers never send past their hello,
    so a readable-with-EOF socket means the worker abandoned this wave."""
    try:
        return conn.recv(1, socket.MSG_PEEK | socket.MSG_DONTWAIT) == b""
    except (BlockingIOError, InterruptedError):
        return False  # open and idle — the normal pending state
    except OSError:
        return True


@dataclass
class _Lease:
    expires: float   # time.monotonic() deadline
    interval: float  # the worker's renewal cadence (seconds)
    rank: int        # rank the worker reported at renewal (-1 pre-assignment)


def assign_ranks(
    wave: list[tuple[str, str]],
    world_size: int,
    prev_ranks: dict[str, int],
    host_order: list[str] | None = None,
) -> dict[str, int]:
    """Topology-aware rank assignment (pure, unit-testable).

    ``wave`` is ``[(task_id, host), ...]`` in check-in order.  Precedence:

    1. stable re-admission — a task id seen before keeps its rank (the
       reference tracker's recover contract, ReConnectLinks
       allreduce_base.cc:263-438);
    2. launcher-numbered ids — ``int(task_id)`` when valid and free, so
       mock-kill specs and restart counters line up;
    3. the rest are grouped BY HOST and handed contiguous free ranks, so
       ring neighbors (rank±1) and tree subtrees stay on one host and
       cross-host traffic rides as few DCN hops as possible (the reference
       tracker is host-blind here; BASELINE north star: topology-aware).

    ``host_order`` ranks the host groups (e.g. a TPU slice's physical
    worker order, see tpu_slice_host_order); unlisted hosts follow in
    first-seen order.
    """
    ranks: dict[str, int] = {}
    taken: set[int] = set()
    for task_id, _host in wave:
        prev = prev_ranks.get(task_id)
        # Two task ids can hold the SAME stale rank (one freed it in an
        # earlier wave, another inherited it, then the first rejoins):
        # first-in-wave wins, the other falls through to a fresh slot.
        if prev is not None and 0 <= prev < world_size and prev not in taken:
            ranks[task_id] = prev
            taken.add(prev)
    for task_id, _host in wave:
        if task_id in ranks:
            continue
        try:
            cand = int(task_id)
        except ValueError:
            continue
        if 0 <= cand < world_size and cand not in taken:
            ranks[task_id] = cand
            taken.add(cand)
    # Host-grouped fill of the remaining slots.
    order_index = {h: i for i, h in enumerate(host_order or [])}
    groups: dict[str, list[str]] = {}
    first_seen: dict[str, int] = {}
    for i, (task_id, host) in enumerate(wave):
        if task_id in ranks:
            continue
        groups.setdefault(host, []).append(task_id)
        first_seen.setdefault(host, i)
    free = iter(r for r in range(world_size) if r not in taken)
    for host in sorted(
        groups, key=lambda h: (order_index.get(h, len(order_index)), first_seen[h])
    ):
        for task_id in groups[host]:
            ranks[task_id] = next(free)
    return ranks


def tpu_slice_host_order() -> list[str] | None:
    """Physical host order of the current TPU slice from TPU-VM metadata.

    Cloud TPU VMs export ``TPU_WORKER_HOSTNAMES`` (comma-separated, in
    worker-id order — which walks the slice's ICI topology) and
    ``TPU_WORKER_ID``.  Ordering tracker ranks along it lays the rabit ring
    over ICI neighbors instead of arbitrary DCN paths (BASELINE north star:
    "tracker discovers v5e pod topology").  Returns None off-TPU.
    """
    names = os.environ.get("TPU_WORKER_HOSTNAMES", "")
    hosts = [h.strip() for h in names.split(",") if h.strip()]
    return hosts or None


class Tracker:
    def __init__(self, world_size: int, host: str = "127.0.0.1", port: int = 0,
                 quiet: bool = False, topology: str = "auto",
                 host_order: list[str] | None = None,
                 obs_dir: str | None = None,
                 conn_timeout_sec: float = 60.0,
                 on_suspect: Callable[[str], None] | None = None):
        self.world_size = world_size
        self.quiet = quiet
        # Per-connection read deadline: a client that connects and sends a
        # torn/partial hello must not pin a _handle thread (and its socket)
        # forever — the read times out and the connection is dropped without
        # wedging the pending wave.  0 disables (tests of the blocking path).
        self.conn_timeout_sec = conn_timeout_sec
        # Liveness hook: called (from the lease monitor thread) with the
        # task_id whose heartbeat lease expired.  The launcher wires this to
        # SIGKILL-and-restart; standalone deployments can plug in their own
        # remediation.  Exceptions are swallowed — detection must not kill
        # the tracker.
        self.on_suspect = on_suspect
        self._leases: dict[str, _Lease] = {}
        # Job-level telemetry (doc/observability.md): structured events
        # (bootstrap/recovery waves, recover_stats converted from prints),
        # the latest metric snapshot per rank (CMD_METRICS), restart
        # counts — written to <obs_dir>/telemetry.json when the job ends.
        if obs_dir is None:
            obs_dir = os.environ.get("RABIT_OBS_DIR", "") or None
        self.obs_dir = obs_dir
        self.events: list[dict] = []
        self.snapshots: dict[int, dict] = {}  # rank -> latest shipped snapshot
        self.telemetry: dict | None = None
        self._started_at = time.time()
        self._n_starts: dict[str, int] = {}  # task_id -> CMD_START check-ins
        self._telemetry_written = False
        # topology: "auto" uses TPU slice metadata when present, "tpu"
        # requires it, anything else is plain host grouping.
        if host_order is None and topology in ("auto", "tpu"):
            host_order = tpu_slice_host_order()
            if topology == "tpu" and host_order is None:
                raise RuntimeError(
                    "topology='tpu' but TPU_WORKER_HOSTNAMES is not set"
                )
        self.host_order = host_order
        self._srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._srv.bind((host, port))
        self._srv.listen(256)
        self.host, self.port = self._srv.getsockname()
        self._lock = threading.Lock()
        self._pending: list[_Pending] = []
        self._ranks: dict[str, int] = {}  # task_id -> stable rank
        self._epoch = 0
        self._n_shutdown = 0
        self._done = threading.Event()
        self._thread: threading.Thread | None = None
        self.messages: list[str] = []  # worker print log (also echoed)

    # -- lifecycle ---------------------------------------------------------

    def start(self) -> "Tracker":
        self._thread = threading.Thread(target=self._serve, daemon=True)
        self._thread.start()
        threading.Thread(target=self._lease_monitor, daemon=True,
                         name="rabit-tracker-leases").start()
        return self

    def wait(self, timeout: float | None = None) -> bool:
        return self._done.wait(timeout)

    def stop(self) -> None:
        self._done.set()
        # shutdown() BEFORE close(): close() alone defers the real fd close
        # while the serve thread is blocked in accept() (CPython keeps the
        # fd alive for the in-flight call), leaving a "stopped" tracker
        # listening — and serving — indefinitely.  shutdown() wakes the
        # accept with an error immediately.
        try:
            self._srv.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        try:
            self._srv.close()
        except OSError:
            pass
        # Safety net for jobs torn down without a full shutdown wave (kill,
        # timeout): idempotent, so the normal all-ranks-shut-down path has
        # already written by the time stop() runs.
        self.write_telemetry()

    # -- serving -----------------------------------------------------------

    def _serve(self) -> None:
        while not self._done.is_set():
            try:
                conn, addr = self._srv.accept()
            except OSError:
                break
            threading.Thread(
                target=self._handle, args=(conn, addr), daemon=True
            ).start()

    def _handle(self, conn: socket.socket, addr) -> None:
        try:
            # Bound every hello read: a slow/torn client (partial hello,
            # chaos-severed proxy stream) is dropped at the deadline instead
            # of leaking this thread and its socket.
            if self.conn_timeout_sec > 0:
                conn.settimeout(self.conn_timeout_sec)
            magic = P.get_u32(conn)
            if magic != P.MAGIC_HELLO:
                conn.close()
                return
            cmd = P.get_u32(conn)
            prev_rank = P.get_i32(conn)
            task_id = P.get_str(conn)
            if cmd in (P.CMD_START, P.CMD_RECOVER):
                listen_port = P.get_u32(conn)
                # The hello is complete; from here the connection only ever
                # WAITS (held open until the wave completer answers it), so
                # the read deadline comes off again.
                conn.settimeout(None)
                with self._lock:
                    # A (re-)check-in supersedes any lease of the previous
                    # life: the fresh worker renews once it is back up, and
                    # a stale lease must not re-suspect it mid-bootstrap.
                    self._leases.pop(task_id, None)
                self._register(conn, addr[0], task_id, listen_port, prev_rank,
                               cmd)
                # conn is answered (and closed) by the wave completer.
                return
            if cmd == P.CMD_PRINT:
                msg = P.get_str(conn)
                self.messages.append(msg)
                # Legacy-line bridge: the robust engine's recover_stats /
                # failure_detected prints become structured events here, so
                # consumers read self.events / telemetry.json instead of
                # scraping stdout.
                ev = event_from_stats_line(msg)
                if ev is not None:
                    with self._lock:
                        self.events.append(
                            {"ts": round(ev.ts, 6), "kind": ev.kind,
                             **ev.fields})
                if not self.quiet:
                    print(msg, end="" if msg.endswith("\n") else "\n", flush=True)
                conn.sendall(P.put_u32(P.ACK))
            elif cmd == P.CMD_METRICS:
                msg = P.get_str(conn)
                self._accept_snapshot(msg)
                conn.sendall(P.put_u32(P.ACK) + self._clock_stamp())
            elif cmd == P.CMD_HEARTBEAT:
                msg = P.get_str(conn)
                self._renew_lease(task_id, prev_rank, msg)
                conn.sendall(P.put_u32(P.ACK) + self._clock_stamp())
            elif cmd == P.CMD_SHUTDOWN:
                with self._lock:
                    # A clean exit must not be suspected afterwards; drop
                    # the lease BEFORE acking so the worker observing the
                    # ACK observes the drop too.
                    self._leases.pop(task_id, None)
                conn.sendall(P.put_u32(P.ACK))
                done = False
                with self._lock:
                    self._n_shutdown += 1
                    done = self._n_shutdown >= self.world_size
                if done:
                    # Persist BEFORE releasing wait()ers: by the time the
                    # launcher sees the job done, telemetry.json exists.
                    self.write_telemetry()
                    self._done.set()
            conn.close()
        except (ConnectionError, OSError, ValueError):
            try:
                conn.close()
            except OSError:
                pass

    @staticmethod
    def _clock_stamp() -> bytes:
        """The tracker's clock, appended to metrics/heartbeat ACKs — one
        half of the NTP-style offset estimate (protocol.TimedAck).  The
        tracker clock is the job's reference timeline: every worker's
        events are projected onto it by rabit_tpu.obs.trace."""
        return P.put_str(f"{time.time():.6f}")

    def _register(self, conn, host, task_id, listen_port, prev_rank,
                  cmd=P.CMD_START) -> None:
        with self._lock:
            # A re-check-in from the same task id replaces its stale entry
            # (e.g. worker retried while the wave was still filling).
            for stale in (p for p in self._pending if p.task_id == task_id):
                try:
                    stale.conn.close()
                except OSError:
                    pass
            self._pending = [p for p in self._pending if p.task_id != task_id]
            self._pending.append(
                _Pending(conn, task_id, listen_port, host, prev_rank, cmd))
            if len(self._pending) < self.world_size:
                return
            # The wave is nominally full — but a worker that died or gave
            # up after checking in would receive its assignment into a dead
            # socket, wasting the whole wave and starving its own retry out
            # of the next one.  Purge hung-up entries first; their tasks'
            # re-check-ins complete a later, fully live wave.
            dead = [p for p in self._pending if _conn_dead(p.conn)]
            if dead:
                for p in dead:
                    try:
                        p.conn.close()
                    except OSError:
                        pass
                self._pending = [p for p in self._pending if p not in dead]
                self.events.append({
                    "ts": round(time.time(), 6), "kind": "wave_purged",
                    "dropped": sorted(p.task_id for p in dead),
                })
                if len(self._pending) < self.world_size:
                    return
            wave, self._pending = self._pending, []
            epoch = self._epoch
            self._epoch += 1
        self._assign_and_send(wave, epoch)

    # -- liveness ----------------------------------------------------------

    def _renew_lease(self, task_id: str, rank: int, payload: str) -> None:
        """Grant/renew a heartbeat lease: the worker promises to renew every
        ``interval`` seconds and is suspected after LEASE_FACTOR intervals
        of silence.  The payload is the decimal interval (see protocol.py)."""
        try:
            interval = float(payload)
        except ValueError:
            return  # malformed heartbeat must not hurt the tracker
        if not (0 < interval < 86400):
            return
        with self._lock:
            self._leases[task_id] = _Lease(
                time.monotonic() + P.LEASE_FACTOR * interval, interval, rank)

    def _lease_monitor(self) -> None:
        """Scan leases and suspect the silent.  An expired lease is removed
        before ``on_suspect`` fires, so one hang produces exactly one
        suspicion (the restarted life re-establishes its own lease)."""
        while not self._done.wait(0.05):
            now = time.monotonic()
            expired: list[tuple[str, _Lease]] = []
            with self._lock:
                for task_id, lease in list(self._leases.items()):
                    if now >= lease.expires:
                        del self._leases[task_id]
                        expired.append((task_id, lease))
                for task_id, lease in expired:
                    self.events.append({
                        "ts": round(time.time(), 6), "kind": "lease_expired",
                        "task_id": task_id, "rank": lease.rank,
                        "interval": lease.interval,
                        "overdue": round(now - lease.expires, 6),
                    })
            for task_id, lease in expired:
                if not self.quiet:
                    print(f"[tracker] lease expired for task {task_id} "
                          f"(rank {lease.rank}, interval {lease.interval}s): "
                          f"suspecting worker", flush=True)
                if self.on_suspect is not None:
                    try:
                        self.on_suspect(task_id)
                    except Exception:  # noqa: BLE001 — detection must survive
                        pass

    def live_tasks(self) -> list[str]:
        """Task ids currently holding an unexpired lease."""
        with self._lock:
            return sorted(self._leases)

    # -- telemetry ---------------------------------------------------------

    def _accept_snapshot(self, payload: str) -> None:
        """Fold one CMD_METRICS JSON envelope into the per-rank table
        (latest per rank wins — a restarted life's final snapshot replaces
        its dead predecessor's heartbeat).  Snapshots with an out-of-range
        rank are rejected at ingest: a malformed ``rank=-1`` (worker shipped
        before its assignment) must not pollute the per-rank table that
        telemetry.json presents as ground truth."""
        try:
            snap = json.loads(payload)
            rank = int(snap.get("rank", -1))
        except (ValueError, TypeError):
            return  # malformed snapshot must not hurt the tracker
        if not 0 <= rank < self.world_size:
            with self._lock:
                self.events.append({
                    "ts": round(time.time(), 6), "kind": "snapshot_rejected",
                    "rank": rank, "task_id": str(snap.get("task_id", "")),
                })
            return
        with self._lock:
            self.snapshots[rank] = snap
            self.events.append({
                "ts": round(time.time(), 6), "kind": "metrics_snapshot",
                "rank": rank, "task_id": snap.get("task_id", ""),
            })

    def build_telemetry(self) -> dict:
        """Assemble the job-level telemetry document: per-rank op latency
        stats/percentiles (from shipped registry snapshots), the
        bootstrap/recovery wave timeline, and restart counts."""
        with self._lock:
            events = list(self.events)
            snapshots = {str(r): s for r, s in sorted(self.snapshots.items())}
            restarts = {t: n - 1 for t, n in self._n_starts.items() if n > 1}
        waves = [e for e in events if e["kind"] == "wave"]
        # Per-rank clock-offset estimates (tracker_ts = worker_ts +
        # offset_s), shipped inside snapshots; the trace merger uses these
        # to project every rank's dump onto the tracker timeline.
        clocks = {r: s["clock"] for r, s in snapshots.items()
                  if isinstance(s, dict) and s.get("clock")}
        return {
            "schema": TELEMETRY_SCHEMA,
            "world_size": self.world_size,
            "started_at": round(self._started_at, 6),
            "finished_at": round(time.time(), 6),
            "n_waves": len(waves),
            "n_recovery_waves": sum(1 for w in waves if w["epoch"] > 0),
            "n_lease_expired": sum(1 for e in events
                                   if e["kind"] == "lease_expired"),
            "restarts": restarts,
            "clocks": clocks,
            "waves": waves,
            "events": events,
            "ranks": snapshots,
        }

    def write_telemetry(self) -> str | None:
        """Write telemetry.json into the obs dir (atomic rename so a
        concurrent reader never sees a torn file).  Idempotent: the first
        caller wins; returns the path, or None when no obs dir is set."""
        with self._lock:
            if self._telemetry_written:
                return None
            self._telemetry_written = True
        self.telemetry = self.build_telemetry()
        if not self.obs_dir:
            return None
        try:
            os.makedirs(self.obs_dir, exist_ok=True)
            path = os.path.join(self.obs_dir, "telemetry.json")
            tmp = f"{path}.tmp.{os.getpid()}"
            with open(tmp, "w") as f:
                json.dump(self.telemetry, f, indent=1, sort_keys=True)
            os.replace(tmp, path)
            return path
        except OSError:
            return None  # observability must not fail the job

    def _assign_and_send(self, wave: list[_Pending], epoch: int) -> None:
        # Stable re-admission > launcher numbering > host-grouped fill; see
        # assign_ranks for the full policy and rationale.
        self._ranks.update(
            assign_ranks(
                [(p.task_id, p.host) for p in wave],
                self.world_size,
                self._ranks,
                host_order=self.host_order,
            )
        )
        peers = {
            self._ranks[p.task_id]: (p.host, p.listen_port) for p in wave
        }
        # Timeline entry per bootstrap wave.  epoch 0 is the initial wave;
        # any later wave is a recovery wave: survivors re-check-in with
        # CMD_RECOVER while the launcher's restarted workers arrive with a
        # fresh CMD_START — those restarts are the per-task restart count.
        with self._lock:
            restarted = []
            for p in wave:
                if p.cmd == P.CMD_START:
                    n_seen = self._n_starts.get(p.task_id, 0)
                    self._n_starts[p.task_id] = n_seen + 1
                    if n_seen > 0:
                        restarted.append(p.task_id)
            self.events.append({
                "ts": round(time.time(), 6),
                "kind": "wave",
                "epoch": epoch,
                "assignments": {p.task_id: self._ranks[p.task_id]
                                for p in wave},
                "recovering": sorted(p.task_id for p in wave
                                     if p.cmd == P.CMD_RECOVER),
                "restarted": sorted(restarted),
            })
        n = self.world_size
        for p in wave:
            rank = self._ranks[p.task_id]
            parent, children = P.tree_topology(rank, n)
            asg = P.Assignment(
                rank=rank,
                world_size=n,
                parent=parent,
                children=children,
                ring_prev=(rank - 1) % n,
                ring_next=(rank + 1) % n,
                peers=peers,
                epoch=epoch,
            )
            try:
                p.conn.sendall(asg.encode())
            except OSError:
                pass  # worker died mid-bootstrap; next wave will handle it
            finally:
                try:
                    p.conn.close()
                except OSError:
                    pass
