"""Tracker: bootstrap/topology service + local cluster launcher."""

from rabit_tpu.tracker.tracker import Tracker

__all__ = ["Tracker", "LocalCluster"]


def __getattr__(name):
    # Lazy so `python -m rabit_tpu.tracker.launcher` doesn't double-import
    # the launcher module (runpy warning).
    if name == "LocalCluster":
        from rabit_tpu.tracker.launcher import LocalCluster

        return LocalCluster
    raise AttributeError(name)
