"""LazyAllreduce — host-side fusion of small reductions.

The north-star capability (reference: guide/lazy_allreduce.cc and the lazy
``prepare_fun`` contract, rabit.h:182-206): instead of paying one collective
per small buffer, pending reductions are queued and flushed as ONE
allreduce per (dtype, op, codec) group.  Works against any engine — the
XLA engine turns the flush into a single fused device collective (a
compressed group's planes are encoded on-device, so the fused buffer still
crosses as one collective); the native engine into one TCP tree/ring pass.

A compressed group's flush routes through ``api.allreduce(codec=...)``,
so under ``rabit_fused_allreduce`` (auto on the XLA engine) the whole
group — planes, scales and all — runs as ONE jitted
encode→ppermute→decode-fold graph over the process mesh
(engine/fused.py): host-side fusion picks the buffers, in-XLA fusion
moves them, and the op_begin/op_end identity carries ``fused=1``.
"""

from __future__ import annotations

import inspect
from typing import Any, Callable

import numpy as np

from rabit_tpu.engine.base import SUM


class _Handle:
    """Future-like handle for one queued buffer."""

    __slots__ = ("_result",)

    def __init__(self) -> None:
        self._result: np.ndarray | None = None

    def get(self) -> np.ndarray:
        if self._result is None:
            raise RuntimeError("LazyAllreduce handle read before flush()")
        return self._result


class LazyAllreduce:
    """Queue buffers with ``add``; ``flush`` runs one fused allreduce per
    (dtype, op, codec) group and resolves every handle.

    Determinism contract (SURVEY hard part #3 — fusion must not break the
    robust engine's seqno/replay alignment): groups flush in first-queued
    order (dict insertion order), so as long as every rank queues the same
    logical sequence of (dtype, op, codec) buffers — the same requirement
    plain collectives already have — every rank issues identical fused
    collectives in identical order, and each fused op gets a deterministic
    seqno + replayable result like any other.

    ``add(..., codec=...)`` tags a buffer with a rabit_tpu.compress codec:
    same-codec buffers fuse into one compressed collective (a two-plane
    codec's planes ride as planes of the single fused buffer), and
    ``codec=None`` buffers still pick up the ``rabit_compress_allreduce``
    policy at flush time exactly like a direct ``api.allreduce`` call.
    """

    def __init__(self, allreduce_fn: Callable[..., np.ndarray] | None = None):
        if allreduce_fn is None:
            from rabit_tpu import api

            allreduce_fn = lambda buf, op, codec=None: api.allreduce(
                buf, op, codec=codec)
        self._allreduce = allreduce_fn
        try:
            self._takes_codec = "codec" in inspect.signature(
                allreduce_fn).parameters
        except (TypeError, ValueError):
            self._takes_codec = False
        self._pending: list[tuple[np.ndarray, int, str | None, _Handle]] = []

    def add(self, data: np.ndarray, op: int = SUM,
            codec: str | None = None) -> _Handle:
        arr = np.ascontiguousarray(data)
        handle = _Handle()
        self._pending.append((arr, op, codec, handle))
        return handle

    def __len__(self) -> int:
        return len(self._pending)

    def flush(self) -> None:
        groups: dict[tuple[Any, int, str | None],
                     list[tuple[np.ndarray, _Handle]]] = {}
        for arr, op, codec, handle in self._pending:
            groups.setdefault((arr.dtype, op, codec), []).append((arr, handle))
        self._pending.clear()
        for (dtype, op, codec), items in groups.items():
            flats = [a.reshape(-1) for a, _ in items]
            fused = np.concatenate(flats) if len(flats) > 1 else flats[0].copy()
            if self._takes_codec:
                reduced = np.asarray(self._allreduce(fused, op, codec=codec))
            else:
                # custom reducer without a codec seam: the codec still
                # partitions the groups, but the fused buffer goes exact
                reduced = np.asarray(self._allreduce(fused, op))
            offset = 0
            for arr, handle in items:
                handle._result = (
                    reduced[offset : offset + arr.size].reshape(arr.shape).astype(dtype)
                )
                offset += arr.size
